import os
os.environ.setdefault('JAX_PLATFORMS','cpu')
from dragonboat_tpu._jaxenv import maybe_pin_cpu
maybe_pin_cpu()
import time, tempfile, shutil
from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry

class SM(IStateMachine):
    def __init__(s, c, n): s.n = 0
    def update(s, data): s.n += 1; return Result(value=s.n)
    def lookup(s, q): return s.n
    def save_snapshot(s, w, fc, done): w.write(s.n.to_bytes(8,'little'))
    def recover_from_snapshot(s, r, fc, done): s.n = int.from_bytes(r.read(8),'little')
    def close(s): pass

reg = _Registry()
members = {1:'s:1',2:'s:2',3:'s:3'}
wd = tempfile.mkdtemp(prefix='dbtpu-shared-')
hosts = {}
G = 8
for nid, addr in members.items():
    cfg = NodeHostConfig(
        raft_address=addr, rtt_millisecond=10,
        nodehost_dir=os.path.join(wd, f'nh{nid}'),
        raft_rpc_factory=lambda a: loopback_factory(a, reg),
        engine=EngineConfig(kind='vector', max_groups=3*G, max_peers=4,
                            log_window=128, inbox_depth=4,
                            max_entries_per_msg=32,
                            share_scope='smoke'),
    )
    hosts[nid] = NodeHost(cfg)
core = hosts[1].engine.core
assert hosts[2].engine.core is core, 'not shared'
for c in range(1, G+1):
    for nid in members:
        hosts[nid].start_cluster(dict(members), False, lambda c_, n_: SM(c_, n_),
            Config(node_id=nid, cluster_id=c, election_rtt=20, heartbeat_rtt=2))
t0 = time.monotonic()
leaders = {}
while len(leaders) < G and time.monotonic()-t0 < 60:
    snap = hosts[1].engine.leader_snapshot()
    leaders = {c:(l,t) for c,(l,t) in snap.items() if l}
    time.sleep(0.02)
print('bring-up', round(time.monotonic()-t0, 2), 's; leaders:', len(leaders))
assert len(leaders) == G
# propose on each group
total = 0
for c in range(1, G+1):
    lid = leaders[c][0]
    sess = hosts[lid].get_noop_session(c)
    rss = hosts[lid].propose_batch(sess, [b'x'*16]*64, 10)
    rss[-1].wait(10)
    total += sum(1 for rs in rss if rs.result and rs.result.completed)
print('committed', total, 'of', G*64)
assert total == G*64, total
# linearizable read
v = hosts[leaders[1][0]].sync_read(1, None)
print('read ok:', v)
for nh in hosts.values(): nh.stop()
shutil.rmtree(wd, ignore_errors=True)
print('SHARED ENGINE SMOKE OK')
