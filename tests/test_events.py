"""Events, metrics, profiler, and logger subsystem tests
(cf. reference event.go, trace.go, logger/logger.go surfaces)."""
import io
import threading
import time

from dragonboat_tpu.events import MetricsRegistry, RaftEventAggregator
from dragonboat_tpu.logger import ILogger, get_logger, set_logger_factory
from dragonboat_tpu.raftio import IRaftEventListener, LeaderInfo
from dragonboat_tpu.trace import Profiler, Sample


def test_metrics_registry_counters_and_gauges():
    m = MetricsRegistry()
    m.inc("raftnode_campaign_launched_total", (1, 2))
    m.inc("raftnode_campaign_launched_total", (1, 2))
    m.set_gauge("raftnode_term", (1, 2), 7)
    assert m.counter_value("raftnode_campaign_launched_total", (1, 2)) == 2
    assert m.gauge_value("raftnode_term", (1, 2)) == 7
    out = io.StringIO()
    m.write(out)
    text = out.getvalue()
    assert (
        'dragonboat_tpu_raftnode_campaign_launched_total{clusterid="1",nodeid="2"} 2'
        in text
    )
    assert "# TYPE dragonboat_tpu_raftnode_term gauge" in text


def test_aggregator_updates_metrics_and_forwards_leader():
    got = []
    done = threading.Event()

    class L(IRaftEventListener):
        def leader_updated(self, info: LeaderInfo) -> None:
            got.append(info)
            done.set()

    m = MetricsRegistry()
    agg = RaftEventAggregator(m, user_listener=L(), enable_metrics=True)
    agg.leader_updated(9, 3, 2, 5)
    agg.campaign_launched(9, 3, 5)
    agg.proposal_dropped(9, 3, [1, 2, 3])
    assert done.wait(2)
    agg.stop()
    assert got[0].cluster_id == 9 and got[0].leader_id == 2 and got[0].term == 5
    assert m.gauge_value("raftnode_has_leader", (9, 3)) == 1.0
    assert m.counter_value("raftnode_campaign_launched_total", (9, 3)) == 1
    assert m.counter_value("raftnode_proposal_dropped_total", (9, 3)) == 3


def test_aggregator_survives_listener_exception():
    class Bad(IRaftEventListener):
        def leader_updated(self, info):
            raise RuntimeError("boom")

    m = MetricsRegistry()
    agg = RaftEventAggregator(m, user_listener=Bad())
    agg.leader_updated(1, 1, 1, 1)
    time.sleep(0.05)
    agg.leader_updated(1, 1, 2, 2)  # dispatcher still alive
    time.sleep(0.05)
    agg.stop()
    assert m.gauge_value("raftnode_leader_id", (1, 1)) == 2.0


def test_metrics_disabled():
    m = MetricsRegistry()
    agg = RaftEventAggregator(m, enable_metrics=False)
    agg.campaign_launched(1, 1, 1)
    assert m.counter_value("raftnode_campaign_launched_total", (1, 1)) == 0
    agg.stop()


def test_sample_percentiles():
    s = Sample("x")
    for v in range(1, 101):
        s.record(float(v))
    assert s.percentile(0.5) == 51.0
    assert s.percentile(0.99) == 100.0
    assert 50.0 <= s.mean() <= 51.0
    assert "p99" in s.report()


def test_profiler_samples_at_ratio():
    p = Profiler(sample_ratio=4)
    for _ in range(16):
        p.new_iteration(8)
        p.start()
        p.end("step")
    assert len(p.samples["step"]) == 4
    assert len(p.batched_groups) == 4
    assert "step:" in p.report()


def test_logger_factory_swap_retroactive():
    lines = []

    class Rec(ILogger):
        def __init__(self, pkg):
            self.pkg = pkg

        def set_level(self, level):
            pass

        def debugf(self, fmt, *a):
            lines.append(("D", self.pkg, fmt % a if a else fmt))

        def infof(self, fmt, *a):
            lines.append(("I", self.pkg, fmt % a if a else fmt))

        def warningf(self, fmt, *a):
            lines.append(("W", self.pkg, fmt % a if a else fmt))

        def errorf(self, fmt, *a):
            lines.append(("E", self.pkg, fmt % a if a else fmt))

        def panicf(self, fmt, *a):
            raise RuntimeError(fmt)

    log = get_logger("testpkg")  # handed out BEFORE the swap
    try:
        set_logger_factory(Rec)
        log.infof("hello %d", 42)
        assert lines == [("I", "testpkg", "hello 42")]
    finally:
        from dragonboat_tpu.logger import StdLogger

        set_logger_factory(StdLogger)


def test_nodehost_health_metrics_end_to_end():
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
    from tests.test_nodehost import KVSM as KVStateMachine

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1, rtt_millisecond=5, raft_address="m1:1",
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            enable_metrics=True,
        )
    )
    try:
        nh.start_cluster(
            {1: "m1:1"}, False, lambda c, n: KVStateMachine(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.time() + 40
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("no leader")
        out = io.StringIO()
        nh.write_health_metrics(out)
        text = out.getvalue()
        assert 'raftnode_has_leader{clusterid="1",nodeid="1"} 1' in text
        assert "transport_" in text
    finally:
        nh.stop()


def test_engine_profiler_disabled_by_default_enabled_on_request():
    from dragonboat_tpu.engine.execengine import ExecEngine
    from dragonboat_tpu.storage.logdb import ShardedLogDB

    db = ShardedLogDB()
    eng = ExecEngine(db)  # soft.latency_sample_ratio defaults to 0
    assert eng.profilers == []
    eng.stop()

    eng2 = ExecEngine(db, sample_ratio=4)
    assert len(eng2.profilers) == len(eng2._threads) - eng2._n_task - eng2._n_snap
    eng2.exec_nodes([], worker=0)
    eng2.stop()
    db.close()
