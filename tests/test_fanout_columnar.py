"""Differential test: the columnar host fan-out must be byte-identical to
the per-lane scalar fan-out it replaced.

Three boundaries are compared against straightforward per-element reference
implementations (transcribed from the pre-columnar engine code):

  1. StepOutput -> wire Messages (replicates, votes, heartbeats,
     timeout-now, response plane): every emitted message must encode to
     the same bytes in the same order.
  2. StepOutput -> saved hard state (per-lane Update construction): the
     same updates, and the multi-group deferred write wave must leave the
     logdb byte-identical to per-update individual writes.
  3. wire Messages -> inbox planes (columnar row staging vs direct
     per-row scalar stores), seeded with realistic protocol traffic
     generated through tests/raft_harness.

Traces are randomized (seeded) across many multi-group trials so slot
mapping, window rebasing, reject flags and skip rules are all exercised.
"""
from __future__ import annotations

import random

import numpy as np
import pytest

from dragonboat_tpu import codec
from dragonboat_tpu.config import Config
from dragonboat_tpu.engine.vector import (
    _RESP_WIRE,
    VectorEngine,
    _Lane,
    build_save_updates,
    gather_post_sends,
    gather_replicate_sends,
    gather_resp_sends,
)
from dragonboat_tpu.ops.state import (
    MSG,
    SEND_HEARTBEAT,
    SEND_REPLICATE,
    SEND_TIMEOUT_NOW,
    SEND_VOTE_REQ,
    KernelConfig,
)
from dragonboat_tpu.types import Entry, Message, MessageType, State, Update

from tests.raft_harness import make_cluster

MT = MessageType


# ---------------------------------------------------------------------------
# fixtures: lanes without a live engine
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self, kcfg: KernelConfig) -> None:
        self.kcfg = kcfg


class _StubNode:
    """The exact node surface the fan-out builders touch."""

    def __init__(self, cluster_id: int, node_id: int, engine) -> None:
        self.cluster_id = cluster_id
        self._node_id = node_id
        self.engine = engine
        self.config = Config(
            node_id=node_id, cluster_id=cluster_id,
            election_rtt=10, heartbeat_rtt=1,
        )

    def node_id(self) -> int:
        return self._node_id

    def describe(self) -> str:
        return f"c{self.cluster_id}n{self._node_id}"


KCFG = KernelConfig(
    groups=8, peers=4, log_window=32, inbox_depth=4,
    max_entries_per_msg=4, readindex_depth=4,
)


def _make_lanes(rng: random.Random):
    """G lanes with randomized membership, window bases and arenas."""
    engine = _StubEngine(KCFG)
    G, P, W = KCFG.groups, KCFG.peers, KCFG.log_window
    lane_by_g = [None] * G
    base = np.zeros(G, np.int64)
    for g in range(G):
        if rng.random() < 0.2:
            continue  # unoccupied lane: fan-out must skip it
        n_members = rng.randint(1, P)
        member_ids = rng.sample(range(1, 100), n_members)
        node = _StubNode(g + 1, rng.choice(member_ids), engine)
        lane = _Lane(g, node)
        lane.set_slots(member_ids)
        lane.active = True
        base[g] = rng.choice([0, 0, W, 5 * W])
        # fill the arena with a contiguous run so replicate/save gathers
        # can fetch entry payloads at device-assigned indexes
        for i in range(1, W):
            idx = int(base[g]) + i
            lane.arena[idx] = Entry(
                index=idx, term=rng.randint(1, 5),
                cmd=bytes([g, i % 251]),
            )
        lane_by_g[g] = lane
    return lane_by_g, base


def _random_output(rng: random.Random, lane_by_g, base):
    """A randomized plausible StepOutput dict (numpy planes)."""
    G, P, K, W = KCFG.groups, KCFG.peers, KCFG.inbox_depth, KCFG.log_window
    E = KCFG.max_entries_per_msg

    def i32(shape, lo, hi):
        return rng_ints(rng, shape, lo, hi)

    o = {
        "send_flags": np.zeros((G, P), np.int32),
        "send_prev_index": i32((G, P), 0, W - E - 2),
        "send_prev_term": i32((G, P), 0, 5),
        "send_n_entries": i32((G, P), 0, E),
        "send_commit": i32((G, P), 0, W - 2),
        "send_hb_commit": i32((G, P), 0, W - 2),
        "send_hint": i32((G, P), 0, 1 << 20),
        "send_hint2": i32((G, P), 0, 1 << 20),
        "vote_last_index": i32((G,), 0, W - 2),
        "vote_last_term": i32((G,), 0, 5),
        "term": i32((G,), 1, 6),
        "vote": i32((G,), 0, P),
        # end-of-step role plane: the vote kind selects its wire type and
        # term from it (PRE_CANDIDATE lanes poll at the prospective term)
        "role": np.asarray(
            [rng.choice((0, 1, 2, 5)) for _ in range(G)], np.int32
        ),
        "resp_type": np.zeros((G, K), np.int32),
        "resp_to": i32((G, K), 0, P - 1),
        "resp_term": i32((G, K), 1, 6),
        "resp_log_index": i32((G, K), 0, W - 2),
        "resp_reject": np.asarray(
            rng_ints(rng, (G, K), 0, 1), bool
        ),
        "resp_hint": i32((G, K), 0, W - 2),
        "resp_hint2": i32((G, K), 0, 1 << 20),
        "save_from": np.zeros((G,), np.int32),
        "save_to": np.zeros((G,), np.int32),
        "commit_index": i32((G,), 0, W - 2),
        "hard_changed": np.asarray(rng_ints(rng, (G,), 0, 1), bool),
        # opaque lease round tag: rides heartbeat log_index verbatim
        # (no base translation; 0 = leases off)
        "lease_round": i32((G,), 0, 1 << 16),
    }
    flag_choices = (
        0, 0, SEND_REPLICATE, SEND_HEARTBEAT, SEND_VOTE_REQ,
        SEND_TIMEOUT_NOW, SEND_REPLICATE | SEND_HEARTBEAT,
    )
    resp_choices = (
        0, 0, int(MSG.REPLICATE_RESP), int(MSG.REQUEST_VOTE_RESP),
        int(MSG.HEARTBEAT_RESP), int(MSG.NOOP), 7,  # 7 = unknown type
    )
    for g in range(G):
        for p in range(P):
            o["send_flags"][g, p] = rng.choice(flag_choices)
        for k in range(K):
            o["resp_type"][g, k] = rng.choice(resp_choices)
        sf = rng.choice([0, 0, rng.randint(1, W // 2)])
        o["save_from"][g] = sf
        if sf:
            o["save_to"][g] = sf + rng.randint(0, E - 1)
    return o


def rng_ints(rng: random.Random, shape, lo, hi):
    n = int(np.prod(shape))
    return np.asarray(
        [rng.randint(lo, hi) for _ in range(n)], np.int32
    ).reshape(shape)


# ---------------------------------------------------------------------------
# reference (pre-columnar) implementations: per-element device reads
# ---------------------------------------------------------------------------


def _ref_replicates(o, base, lane_by_g):
    out = []
    gs, ps = np.nonzero(o["send_flags"] & SEND_REPLICATE)
    for g, p in zip(gs.tolist(), ps.tolist()):
        lane = lane_by_g[g]
        if lane is None:
            continue
        to_nid = lane.rev.get(p)
        if to_nid is None:
            continue
        b = int(base[g])
        prev = int(o["send_prev_index"][g, p])
        n = int(o["send_n_entries"][g, p])
        try:
            ents = [lane.arena[b + prev + 1 + i] for i in range(n)]
        except KeyError:
            continue
        out.append(
            Message(
                type=MT.REPLICATE, cluster_id=lane.node.cluster_id,
                to=to_nid, from_=lane.node.node_id(),
                term=int(o["term"][g]), log_index=b + prev,
                log_term=int(o["send_prev_term"][g, p]),
                commit=b + int(o["send_commit"][g, p]), entries=ents,
            )
        )
    return out


def _ref_post(o, base, lane_by_g):
    out = []
    for flag, mk in (
        (SEND_VOTE_REQ, "vote"),
        (SEND_HEARTBEAT, "hb"),
        (SEND_TIMEOUT_NOW, "tn"),
    ):
        gs, ps = np.nonzero(o["send_flags"] & flag)
        for g, p in zip(gs.tolist(), ps.tolist()):
            lane = lane_by_g[g]
            if lane is None:
                continue
            to_nid = lane.rev.get(p)
            if to_nid is None:
                continue
            if mk == "vote":
                # pre-candidate lanes poll: REQUEST_PREVOTE at term+1
                pre = int(o["role"][g]) == 5
                m = Message(
                    type=MT.REQUEST_PREVOTE if pre else MT.REQUEST_VOTE,
                    cluster_id=lane.node.cluster_id,
                    to=to_nid, from_=lane.node.node_id(),
                    term=int(o["term"][g]) + 1 if pre else int(o["term"][g]),
                    log_index=int(base[g]) + int(o["vote_last_index"][g]),
                    log_term=int(o["vote_last_term"][g]),
                    hint=int(o["send_hint"][g, p]),
                )
            elif mk == "hb":
                m = Message(
                    type=MT.HEARTBEAT, cluster_id=lane.node.cluster_id,
                    to=to_nid, from_=lane.node.node_id(),
                    term=int(o["term"][g]),
                    # lease round tag: untranslated (not an index)
                    log_index=int(o["lease_round"][g]),
                    commit=int(base[g]) + int(o["send_hb_commit"][g, p]),
                    hint=int(o["send_hint"][g, p]),
                    hint_high=int(o["send_hint2"][g, p]),
                )
            else:
                m = Message(
                    type=MT.TIMEOUT_NOW, cluster_id=lane.node.cluster_id,
                    to=to_nid, from_=lane.node.node_id(),
                    term=int(o["term"][g]),
                )
            out.append(m)
    return out


def _ref_resps(o, base, lane_by_g):
    out = []
    gs, ks = np.nonzero(o["resp_type"] != MSG.NONE)
    for g, k in zip(gs.tolist(), ks.tolist()):
        lane = lane_by_g[g]
        if lane is None:
            continue
        t = int(o["resp_type"][g, k])
        to_nid = lane.rev.get(int(o["resp_to"][g, k]))
        if to_nid is None or to_nid == lane.node.node_id():
            continue
        wire = _RESP_WIRE.get(t)
        if wire is None:
            continue
        b = int(base[g])
        log_index = int(o["resp_log_index"][g, k])
        hint = int(o["resp_hint"][g, k])
        if wire == MT.REPLICATE_RESP:
            log_index += b
            hint += b
        out.append(
            Message(
                type=wire, cluster_id=lane.node.cluster_id, to=to_nid,
                from_=lane.node.node_id(), term=int(o["resp_term"][g, k]),
                log_index=log_index, reject=bool(o["resp_reject"][g, k]),
                hint=hint, hint_high=int(o["resp_hint2"][g, k]),
            )
        )
    return out


def _ref_saves(o, base, lane_by_g):
    updates = []
    save_gs = np.nonzero((o["save_from"] > 0) | o["hard_changed"])[0]
    for g in save_gs.tolist():
        lane = lane_by_g[g]
        if lane is None or not lane.active:
            continue
        b = int(base[g])
        sf, st_ = int(o["save_from"][g]), int(o["save_to"][g])
        ents = []
        if sf > 0:
            ents, _missing = lane.arena.get_run(b + sf, b + st_)
            if ents is None:
                ents = []
        vote_slot = int(o["vote"][g])
        state = State(
            term=int(o["term"][g]),
            vote=lane.rev.get(vote_slot - 1, 0) if vote_slot > 0 else 0,
            commit=b + int(o["commit_index"][g]),
        )
        if ents or bool(o["hard_changed"][g]):
            updates.append(
                Update(
                    cluster_id=lane.node.cluster_id,
                    node_id=lane.node.node_id(),
                    state=state,
                    entries_to_save=ents,
                )
            )
    return updates


def _encode_stream(msgs):
    return [codec.encode_message(m) for m in msgs]


# ---------------------------------------------------------------------------
# 1 + 2: StepOutput -> messages / saved hard state
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(12))
def test_fanout_messages_byte_identical(seed):
    rng = random.Random(1000 + seed)
    lane_by_g, base = _make_lanes(rng)
    o = _random_output(rng, lane_by_g, base)
    col = [m for _lane, m in gather_replicate_sends(o, base, lane_by_g)]
    col += [m for _lane, m in gather_post_sends(o, base, lane_by_g)]
    col += [m for _lane, m in gather_resp_sends(o, base, lane_by_g)]
    ref = _ref_replicates(o, base, lane_by_g)
    ref += _ref_post(o, base, lane_by_g)
    ref += _ref_resps(o, base, lane_by_g)
    assert _encode_stream(col) == _encode_stream(ref)
    assert len(col) > 0  # the trial must actually exercise the fan-out


@pytest.mark.parametrize("seed", range(12))
def test_save_updates_identical(seed):
    rng = random.Random(2000 + seed)
    lane_by_g, base = _make_lanes(rng)
    o = _random_output(rng, lane_by_g, base)
    col, lane_saves = build_save_updates(o, base, lane_by_g)
    ref = _ref_saves(o, base, lane_by_g)
    assert len(col) == len(ref)
    for a, b in zip(col, ref):
        assert (a.cluster_id, a.node_id) == (b.cluster_id, b.node_id)
        assert codec.encode_state(a.state) == codec.encode_state(b.state)
        assert [codec.encode_entry(e) for e in a.entries_to_save] == [
            codec.encode_entry(e) for e in b.entries_to_save
        ]
    assert len(lane_saves) == len(col)


def test_deferred_write_wave_matches_individual_saves(tmp_path):
    """The multi-group deferred write wave (one batch per shard + one
    parallel sync) must leave the logdb byte-identical to saving every
    update individually through the fsync-per-call path."""
    from dragonboat_tpu.storage.kv import sync_all
    from dragonboat_tpu.storage.logdb import ShardedLogDB

    rng = random.Random(7)
    updates = []
    for cid in range(1, 40):
        idx0 = rng.randint(1, 50)
        ents = [
            Entry(index=idx0 + i, term=rng.randint(1, 4), cmd=bytes([cid, i]))
            for i in range(rng.randint(0, 6))
        ]
        updates.append(
            Update(
                cluster_id=cid, node_id=1,
                state=State(
                    term=rng.randint(1, 4), vote=rng.randint(0, 3),
                    commit=idx0,
                ),
                entries_to_save=ents,
            )
        )

    def dump(db):
        out = {}
        for sh in db._shards:
            sh.kv.iterate_value(
                b"", b"\xff" * 64, True,
                lambda k, v: (out.__setitem__(bytes(k), bytes(v)), True)[1],
            )
        return out

    grouped = ShardedLogDB(str(tmp_path / "grouped"), num_shards=4)
    one_by_one = ShardedLogDB(str(tmp_path / "single"), num_shards=4)
    sync_all(grouped.save_raft_state_deferred(updates))
    for ud in updates:
        one_by_one.save_raft_state([ud])
    assert dump(grouped) == dump(one_by_one)
    grouped.close()
    # deferred writes must also be durable: reopen and compare again
    reopened = ShardedLogDB(str(tmp_path / "grouped"), num_shards=4)
    assert dump(reopened) == dump(one_by_one)
    reopened.close()
    one_by_one.close()


# ---------------------------------------------------------------------------
# 3: wire messages -> inbox planes (columnar staging vs direct stores)
# ---------------------------------------------------------------------------


class _PackHarness:
    """Just enough engine surface to drive _stage_row/_flush_staged_rows."""

    _stage_row = VectorEngine._stage_row
    _flush_staged_rows = VectorEngine._flush_staged_rows

    def __init__(self, G, K, E):
        self._buf = _empty_planes(G, K, E)
        self._rows = {
            "g": [], "k": [], "mtype": [], "from_slot": [], "term": [],
            "log_index": [], "log_term": [], "commit": [], "reject": [],
            "hint": [], "hint_high": [], "n_entries": [], "ents": [],
        }


def _empty_planes(G, K, E):
    return {
        "mtype": np.full((G, K), MSG.NONE, np.int32),
        "from_slot": np.zeros((G, K), np.int32),
        "term": np.zeros((G, K), np.int32),
        "log_index": np.zeros((G, K), np.int32),
        "log_term": np.zeros((G, K), np.int32),
        "commit": np.zeros((G, K), np.int32),
        "reject": np.zeros((G, K), bool),
        "hint": np.zeros((G, K), np.int32),
        "hint_high": np.zeros((G, K), np.int32),
        "n_entries": np.zeros((G, K), np.int32),
        "entry_terms": np.zeros((G, K, E), np.int32),
        "entry_cc": np.zeros((G, K, E), bool),
    }


def _harness_traffic():
    """Realistic protocol traffic: drive a scalar 3-node cluster through
    elections and proposals (tests/raft_harness) and collect every
    non-local wire message it produces."""
    net = make_cluster(3)
    collected = []
    orig_collect = net.collect

    def collect():
        msgs = orig_collect()
        collected.extend(msgs)
        return msgs

    net.collect = collect
    net.elect(1)
    for i in range(8):
        net.propose(1, b"payload-%d" % i)
    net.elect(2)
    for i in range(4):
        net.propose(2, b"more-%d" % i)
    return [m for m in collected if m.term or m.entries]


def test_pack_staging_matches_direct_stores():
    G, K, E = 8, 4, 8
    rng = random.Random(99)
    msgs = _harness_traffic()
    assert len(msgs) > 20
    h = _PackHarness(G, K, E)
    ref = _empty_planes(G, K, E)
    wire_to_msg = {
        MT.REPLICATE: MSG.REPLICATE,
        MT.HEARTBEAT: MSG.HEARTBEAT,
        MT.REQUEST_VOTE: MSG.REQUEST_VOTE,
        MT.REQUEST_VOTE_RESP: MSG.REQUEST_VOTE_RESP,
        MT.REPLICATE_RESP: MSG.REPLICATE_RESP,
        MT.HEARTBEAT_RESP: MSG.HEARTBEAT_RESP,
    }
    used = set()
    for m in msgs:
        mtype = wire_to_msg.get(m.type)
        if mtype is None:
            continue
        g, k = rng.randrange(G), rng.randrange(K)
        if (g, k) in used:
            continue
        used.add((g, k))
        n = min(len(m.entries), E)
        # columnar staging
        h._stage_row(
            g, k, mtype, from_slot=m.from_, term=m.term,
            log_index=m.log_index, log_term=m.log_term, commit=m.commit,
            reject=m.reject, hint=m.hint, hint_high=m.hint_high,
            n_entries=n,
        )
        if n:
            h._rows["ents"].append(
                (
                    g, k,
                    [e.term for e in m.entries[:n]],
                    [e.is_config_change() for e in m.entries[:n]],
                )
            )
        # reference: direct per-row scalar stores (the pre-columnar path)
        ref["mtype"][g, k] = mtype
        ref["from_slot"][g, k] = max(m.from_, 0)
        ref["term"][g, k] = m.term
        ref["log_index"][g, k] = m.log_index
        ref["log_term"][g, k] = m.log_term
        ref["commit"][g, k] = m.commit
        ref["reject"][g, k] = m.reject
        ref["hint"][g, k] = m.hint
        ref["hint_high"][g, k] = m.hint_high
        ref["n_entries"][g, k] = n
        for i, e in enumerate(m.entries[:n]):
            ref["entry_terms"][g, k, i] = e.term
            ref["entry_cc"][g, k, i] = e.is_config_change()
    h._flush_staged_rows()
    for plane in ref:
        assert np.array_equal(h._buf[plane], ref[plane]), plane
    # staging columns must be reset for the next step
    assert all(not col for col in h._rows.values())
