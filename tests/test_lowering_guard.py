"""Kernel lowering guard (VERDICT r3 item 8): step_batch must compile to
ONE fused device program with no host callbacks or host transfers inside.

An accidental io_callback / debug.print / device_get introduced into the
step would silently serialize every protocol step through the host and
destroy the framework's core performance property; this guard turns that
mistake into a CI failure. It also budgets the lowered program size so the
step cannot quietly balloon past what fits a sane compile."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import pytest

from dragonboat_tpu.ops.kernel import step_batch
from dragonboat_tpu.ops.state import (
    KernelConfig,
    init_state,
    make_empty_inbox,
)

CFG = KernelConfig(
    groups=64,
    peers=4,
    log_window=64,
    inbox_depth=4,
    max_entries_per_msg=16,
    readindex_depth=4,
)

# markers any host round-trip inside a lowered jax program would leave in
# the StableHLO text (python callbacks lower to custom_call targets with
# 'callback' in the name; infeed/outfeed are the raw host-transfer ops)
_HOST_MARKERS = ("callback", "infeed", "outfeed", "send_to_host",
                 "recv_from_host", "py_func")


@pytest.fixture(scope="module")
def lowered():
    fn = jax.jit(functools.partial(step_batch, cfg=CFG))
    state = init_state(CFG)
    inbox = make_empty_inbox(CFG)
    ticks = jnp.zeros((CFG.groups,), jnp.int32)
    return fn.lower(state, inbox, ticks)


def test_step_lowers_without_host_callbacks(lowered):
    txt = lowered.as_text().lower()
    for marker in _HOST_MARKERS:
        assert marker not in txt, (
            f"step_batch lowering contains host round-trip marker "
            f"{marker!r}: a device step must never call back into Python"
        )


def test_step_lowering_size_budget(lowered):
    # StableHLO text size is a stable proxy for program complexity; the
    # current step lowers to well under this budget. A 4x regression means
    # someone unrolled a loop over entries/slots again (the exact failure
    # the loop-free ring scatter removed) — look there first.
    txt = lowered.as_text()
    assert len(txt) < 8_000_000, (
        f"step_batch lowering ballooned to {len(txt)} bytes"
    )


def test_step_compiles_and_runs(lowered):
    compiled = lowered.compile()
    state = init_state(CFG)
    inbox = make_empty_inbox(CFG)
    ticks = jnp.zeros((CFG.groups,), jnp.int32)
    new_state, out = compiled(state, inbox, ticks)
    jax.block_until_ready(out.term)
