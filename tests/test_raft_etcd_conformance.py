"""Ported etcd/raft conformance scenarios against the scalar core.

The reference vendors etcd's raft tests to guarantee corner-case parity
(internal/raft/raft_etcd_test.go, raft_etcd_paper_test.go — docs/test.md:4).
These are the highest-value scenarios re-expressed against our scalar core
through the same message-level interface; each test cites the etcd test or
Raft paper/thesis section it validates.
"""
import random

import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft, RaftNodeState
from dragonboat_tpu.types import (
    Entry,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
)

from tests.raft_harness import Network, make_cluster, new_test_raft


def tick_until_election(r: Raft) -> None:
    for _ in range(2 * r.election_timeout):
        r.tick()

MT = MessageType
F, C, L = RaftNodeState.FOLLOWER, RaftNodeState.CANDIDATE, RaftNodeState.LEADER


def logdb_with_terms(*terms: int) -> InMemLogDB:
    """A stub log whose entry i (1-based) has term terms[i-1]
    (the etcd-test idiom of seeding divergent logs)."""
    db = InMemLogDB()
    db.append([Entry(index=i + 1, term=t) for i, t in enumerate(terms)])
    return db


def terms_of(r: Raft):
    first, last = r.log.first_index(), r.log.last_index()
    return [r.log.term(i) for i in range(first, last + 1)]


# ---------------------------------------------------------------------------
# Paper figure 7 / etcd TestLeaderSyncFollowerLog: a newly elected leader
# reconciles every divergent follower log shape.
# ---------------------------------------------------------------------------
LEADER_TERMS = (1, 1, 1, 4, 4, 5, 5, 6, 6, 6)
FOLLOWER_SHAPES = [
    (1, 1, 1, 4, 4, 5, 5, 6, 6),               # (a) missing entries
    (1, 1, 1, 4),                              # (b) far behind
    (1, 1, 1, 4, 4, 5, 5, 6, 6, 6, 6),         # (c) extra uncommitted
    (1, 1, 1, 4, 4, 5, 5, 6, 6, 6, 7, 7),      # (d) extra higher-term
    (1, 1, 1, 4, 4, 4, 4),                     # (e) conflicting tail
    (1, 1, 1, 2, 2, 2, 3, 3, 3, 3, 3),         # (f) long conflicting tail
]


@pytest.mark.parametrize("shape", FOLLOWER_SHAPES, ids="abcdef")
def test_leader_sync_follower_log(shape):
    db1 = logdb_with_terms(*LEADER_TERMS)
    db1.set_state(State(term=6, vote=1))
    db2 = logdb_with_terms(*shape)
    db2.set_state(State(term=max(shape)))
    r1 = new_test_raft(1, [1, 2, 3], logdb=db1)
    r2 = new_test_raft(2, [1, 2, 3], logdb=db2)
    r3 = new_test_raft(3, [1, 2, 3], logdb=logdb_with_terms(*LEADER_TERMS))
    nt = Network({1: r1, 2: r2, 3: r3})
    nt.elect(1)
    assert r1.state == L
    # election appended a noop at the new term; replication must rewrite the
    # follower to exactly the leader's log
    nt.propose(1, b"sync")
    assert terms_of(r2) == terms_of(r1)
    assert r2.log.committed == r1.log.committed


# ---------------------------------------------------------------------------
# etcd TestCommit: quorum-match + current-term-only commit matrix (§5.4.2)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "matches,log_terms,term,want",
    [
        # single voter
        ([1], (1,), 1, 1),
        ([1], (1,), 2, 0),       # entry not from current term (§5.4.2)
        ([2], (1, 2), 2, 2),
        ([1], (2,), 2, 1),
        # two voters: quorum = BOTH, so the min match is decisive
        ([2, 1], (1, 2), 2, 0),  # quorum index 1 has old term -> no commit
        ([2, 2], (1, 2), 2, 2),
        ([2, 1], (1, 1), 2, 0),
        # three voters (self is index 0): quorum = 2nd-highest match
        ([3, 2, 1], (1, 2, 3), 3, 0),  # quorum idx 2, term(2)=2 != 3
        ([3, 3, 1], (1, 2, 3), 3, 3),  # quorum idx 3, current term
    ],
)
def test_commit_matrix(matches, log_terms, term, want):
    db = logdb_with_terms(*log_terms)
    db.set_state(State(term=term))
    peers = list(range(1, len(matches) + 1))
    r = new_test_raft(1, peers, logdb=db)
    r.term = term
    r.state = L
    r.leader_id = 1
    for nid, m in zip(peers, matches):
        r.remotes[nid].match = m
        r.remotes[nid].next = m + 1
    r.try_commit()
    assert r.log.committed == want


def test_commit_only_current_term_explicit():
    """etcd TestCommit core case: quorum match on an old-term entry does not
    commit it; a current-term entry at the same quorum does."""
    db = logdb_with_terms(1, 2)
    db.set_state(State(term=2))
    r = new_test_raft(1, [1, 2], logdb=db)
    r.term = 2
    r.state = L
    r.remotes[1].match = 2  # leader's own progress
    r.remotes[1].next = 3
    r.remotes[2].match = 1
    r.try_commit()
    assert r.log.committed == 0  # index 1 has term 1 != current term 2
    r.remotes[2].match = 2
    r.try_commit()
    assert r.log.committed == 2  # commits both (log matching)


# ---------------------------------------------------------------------------
# etcd TestRecvMsgVote / TestVoter: the grant/reject matrix on log
# up-to-dateness (§5.4.1)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "my_terms,cand_log_term,cand_log_index,grant",
    [
        # empty local log: grant anything
        ((), 0, 0, True),
        ((), 1, 1, True),
        # local log [(1,1)]
        ((1,), 0, 0, False),   # candidate log older term
        ((1,), 1, 0, False),   # same term, shorter
        ((1,), 1, 1, True),    # identical
        ((1,), 1, 2, True),    # same term, longer
        ((1,), 2, 1, True),    # higher last term wins even if shorter
        # local log [(1,1),(2,2)]
        ((1, 2), 1, 1, False),
        ((1, 2), 1, 3, False),  # longer but lower last term loses
        ((1, 2), 2, 1, False),  # same last term, shorter
        ((1, 2), 2, 2, True),
        ((1, 2), 3, 1, True),
    ],
)
def test_vote_grant_matrix(my_terms, cand_log_term, cand_log_index, grant):
    db = logdb_with_terms(*my_terms)
    r = new_test_raft(1, [1, 2], logdb=db)
    r.handle(
        Message(
            type=MT.REQUEST_VOTE, from_=2, to=1, term=3,
            log_term=cand_log_term, log_index=cand_log_index,
        )
    )
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP][-1]
    assert resp.reject != grant


# ---------------------------------------------------------------------------
# etcd TestDuelingCandidates
# ---------------------------------------------------------------------------
def test_dueling_candidates():
    nt = make_cluster(3)
    nt.drop(1, 3)
    nt.drop(3, 1)
    nt.elect(1)   # 1 wins with {1,2}
    nt.elect(3)   # 3 campaigns at term 2; 2's log has 1's noop so vote denied
    assert nt.rafts[1].state == L
    assert nt.rafts[3].state == C
    nt.heal()
    # 3 campaigns again at a higher term; its log is stale so it still can't
    # win, but the higher term forces 1 to step down and re-elect
    nt.elect(3)
    assert nt.rafts[3].state != L
    assert nt.rafts[1].log.last_index() >= 1


# ---------------------------------------------------------------------------
# etcd TestOldMessages: stale-term replicate after re-election is ignored
# ---------------------------------------------------------------------------
def test_old_messages_ignored():
    nt = make_cluster(3)
    nt.elect(1)
    nt.elect(2)
    nt.elect(1)  # term 3, leader 1 again
    r1 = nt.rafts[1]
    assert r1.state == L and r1.term == 3
    last = r1.log.last_index()
    # replay an old term-2 replicate carrying a conflicting entry
    nt.send(
        Message(
            type=MT.REPLICATE, from_=2, to=1, term=2,
            log_index=0, log_term=0, entries=[Entry(index=last + 1, term=2)],
        )
    )
    assert r1.term == 3 and r1.state == L
    assert r1.log.last_index() == last  # nothing appended


# ---------------------------------------------------------------------------
# etcd TestProposalByProxy
# ---------------------------------------------------------------------------
def test_proposal_by_proxy_commits_everywhere():
    nt = make_cluster(3)
    nt.elect(1)
    before = nt.rafts[1].log.committed
    nt.propose(2, b"proxied")  # follower forwards to leader
    for r in nt.rafts.values():
        assert r.log.committed == before + 1
    ents = nt.rafts[3].log.entries(nt.rafts[3].log.committed, 1 << 20)
    assert ents[0].cmd == b"proxied"


# ---------------------------------------------------------------------------
# etcd TestAllServerStepdown: every state steps down on higher-term messages
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("start_state", ["follower", "candidate", "leader"])
@pytest.mark.parametrize("mtype", [MT.REQUEST_VOTE, MT.REPLICATE])
def test_all_server_stepdown(start_state, mtype):
    r = new_test_raft(1, [1, 2, 3])
    if start_state == "candidate":
        tick_until_election(r)
        assert r.state == C
    elif start_state == "leader":
        tick_until_election(r)
        r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=2, to=1,
                         term=r.term, reject=False))
        assert r.state == L
    r.msgs = []
    high = r.term + 10
    r.handle(Message(type=mtype, from_=2, to=1, term=high,
                     log_index=10, log_term=high))
    assert r.state == F
    assert r.term == high


# ---------------------------------------------------------------------------
# etcd TestBcastBeat / paper §5.2: leader heartbeats on its timeout
# ---------------------------------------------------------------------------
def test_leader_broadcasts_heartbeat_on_timeout():
    nt = make_cluster(3, election=10, heartbeat=2)
    nt.elect(1)
    r1 = nt.rafts[1]
    nt.collect()  # drain
    for _ in range(2):
        r1.tick()
    beats = [m for m in r1.msgs if m.type == MT.HEARTBEAT]
    assert {m.to for m in beats} == {2, 3}


# ---------------------------------------------------------------------------
# paper §5.2: candidate starts a NEW election (higher term) after timeout
# ---------------------------------------------------------------------------
def test_candidate_restarts_election_with_higher_term():
    r = new_test_raft(1, [1, 2, 3], seed=7)
    tick_until_election(r)
    assert r.state == C and r.term == 1
    tick_until_election(r)
    assert r.state == C and r.term == 2
    reqs = [m for m in r.msgs if m.type == MT.REQUEST_VOTE and m.term == 2]
    assert {m.to for m in reqs} == {2, 3}


# ---------------------------------------------------------------------------
# paper §5.2 / etcd TestFollowerElectionTimeoutNonconflict: randomized
# timeouts de-synchronize elections
# ---------------------------------------------------------------------------
def test_randomized_election_timeouts_differ():
    timeouts = set()
    for seed in range(8):
        r = new_test_raft(1, [1, 2, 3], seed=seed)
        n = 0
        while r.state == F:
            r.tick()
            n += 1
        timeouts.add(n)
    assert len(timeouts) > 1, "all seeds timed out identically"


# ---------------------------------------------------------------------------
# etcd TestLeaderIncreaseNext: optimistic next after sending entries
# ---------------------------------------------------------------------------
def test_leader_optimistic_next_index():
    nt = make_cluster(3)
    nt.elect(1)
    r1 = nt.rafts[1]
    for i in range(3):
        nt.propose(1, b"p%d" % i)
    assert r1.remotes[2].next == r1.log.last_index() + 1
    assert r1.remotes[2].match == r1.log.last_index()


# ---------------------------------------------------------------------------
# etcd TestVoteRequest: campaign carries the candidate's last log position
# ---------------------------------------------------------------------------
def test_vote_request_carries_last_log_position():
    db = logdb_with_terms(1, 1, 2)
    db.set_state(State(term=2))
    r = new_test_raft(1, [1, 2], logdb=db)
    tick_until_election(r)
    req = [m for m in r.msgs if m.type == MT.REQUEST_VOTE][-1]
    assert req.log_index == 3
    assert req.log_term == 2
    assert req.term == 3


# ---------------------------------------------------------------------------
# etcd TestRestore: InstallSnapshot rebuilds log + membership
# ---------------------------------------------------------------------------
def test_install_snapshot_restores_follower():
    r = new_test_raft(1, [1, 2], seed=1)
    ss = Snapshot(
        index=11, term=11,
        membership=Membership(addresses={1: "a1", 2: "a2", 3: "a3"}),
    )
    r.handle(
        Message(type=MT.INSTALL_SNAPSHOT, from_=2, to=1, term=11, snapshot=ss)
    )
    assert r.log.committed == 11
    assert r.log.term(11) == 11
    # remotes rebuild via the host-driven SnapshotReceived message AFTER the
    # SM recovered (reference raft.go:1566-1568 handleRestoreRemote; the
    # node runtime sends it from the snapshot worker)
    r.handle(
        Message(type=MT.SNAPSHOT_RECEIVED, from_=1, to=1, term=11, snapshot=ss)
    )
    assert set(r.remotes) == {1, 2, 3}
    # re-delivering the same snapshot is a no-op ack (etcd TestRestoreIgnores)
    r.msgs = []
    r.handle(
        Message(type=MT.INSTALL_SNAPSHOT, from_=2, to=1, term=11, snapshot=ss)
    )
    resp = [m for m in r.msgs if m.type == MT.REPLICATE_RESP][-1]
    assert resp.log_index == 11


# ---------------------------------------------------------------------------
# etcd TestProvideSnap / reference raft.go:774-785: a follower whose needed
# entries were compacted away gets an InstallSnapshot instead
# ---------------------------------------------------------------------------
def test_slow_follower_triggers_snapshot_send():
    db = logdb_with_terms(1, 1, 1, 1, 1)
    db.set_state(State(term=1, commit=3))
    db.create_snapshot(
        Snapshot(index=3, term=1,
                 membership=Membership(addresses={1: "a", 2: "b"}))
    )
    db.compact(3)  # entries <= 3 unavailable
    r = new_test_raft(1, [1, 2], logdb=db)
    r.state = C  # campaign would bump the term; force the transition
    r.term = 1
    r.become_leader()
    assert r.state == L
    r.msgs = []
    # follower far behind: next=1 is compacted away. The fallback only fires
    # for ACTIVE remotes (reference raft.go:776-780 skips inactive ones —
    # also conformance-checked below)
    r.remotes[2].match = 0
    r.remotes[2].next = 1
    r.broadcast_replicate_message()
    assert [m for m in r.msgs if m.type == MT.INSTALL_SNAPSHOT] == []
    r.remotes[2].set_active()
    r.broadcast_replicate_message()
    snaps = [m for m in r.msgs if m.type == MT.INSTALL_SNAPSHOT]
    assert len(snaps) == 1
    assert snaps[0].snapshot.index == 3
