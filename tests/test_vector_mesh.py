"""Multi-device VectorEngine: the engine's (G, ...) state sharded over a
jax.sharding.Mesh along the group axis (conftest pins an 8-device CPU
platform). Proves propose->quorum->commit with the protocol state spread
across devices — the multi-chip scaling story of SURVEY §2.9.1."""
import time

import jax
import numpy as np
import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry


class KV(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=len(self.d))

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, fc, done):
        import json

        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, fc, done):
        import json

        self.d = json.loads(r.read().decode())

    def close(self):
        pass


def wait(pred, timeout=30):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.mark.skipif(jax.device_count() < 2, reason="needs a multi-device mesh")
def test_sharded_engine_three_replicas_commit():
    n_dev = jax.device_count()
    groups = 2 * n_dev  # at least two lanes per device
    reg = _Registry()
    members = {1: "m:1", 2: "m:2", 3: "m:3"}
    hosts = {}
    for nid, addr in members.items():
        hosts[nid] = NodeHost(NodeHostConfig(
            deployment_id=11, rtt_millisecond=20, raft_address=addr,
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=groups, max_peers=4,
                log_window=64, shard_over_mesh=True,
            ),
        ))
    try:
        # the engine state must actually live on the mesh
        for nh in hosts.values():
            sh = nh.engine._state.term.sharding
            assert len(sh.device_set) == n_dev, sh
        for c in range(1, groups + 1):
            for nid in members:
                hosts[nid].start_cluster(
                    dict(members), False, KV,
                    Config(cluster_id=c, node_id=nid, election_rtt=20,
                           heartbeat_rtt=4),
                )
        pending = set(range(1, groups + 1))
        deadline = time.monotonic() + 150
        while pending and time.monotonic() < deadline:
            pending -= {
                c for c in pending if hosts[1].get_leader_id(c)[1]
            }
            if pending:
                time.sleep(0.1)
        assert not pending, f"{len(pending)} groups leaderless"
        # one write per group through its leader, quorum-committed across
        # lanes living on different devices; leadership can churn under
        # full-suite CPU load between the probe and the propose — retry
        # against the refreshed leader like a real client
        from dragonboat_tpu.requests import RequestError

        for c in range(1, groups + 1):
            for attempt in range(6):
                lid, ok = hosts[1].get_leader_id(c)
                try:
                    if not ok or lid not in hosts:
                        raise RequestError("leaderless between waves")
                    s = hosts[lid].get_noop_session(c)
                    hosts[lid].sync_propose(s, f"g{c}=v{c}".encode(), 30.0)
                    break
                except RequestError:
                    if attempt == 5:
                        raise
                    time.sleep(1.0)
        # linearizable read-back on a follower host for a few groups
        for c in (1, groups // 2, groups):
            lid = hosts[1].get_leader_id(c)[0]
            fid = next(n for n in members if n != lid)
            assert wait(
                lambda c=c, fid=fid: hosts[fid].sync_read(
                    c, f"g{c}", timeout_s=10.0
                ) == f"v{c}",
                timeout=60,
            )
    finally:
        for nh in hosts.values():
            nh.stop()
