"""Multi-device VectorEngine: the engine's (G, ...) state sharded over a
jax.sharding.Mesh along the group axis (conftest pins an 8-device CPU
platform). Proves propose->quorum->commit with the protocol state spread
across devices — the multi-chip scaling story of SURVEY §2.9.1."""
import time

import jax
import numpy as np
import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry


class KV(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=len(self.d))

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, fc, done):
        import json

        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, fc, done):
        import json

        self.d = json.loads(r.read().decode())

    def close(self):
        pass


def wait(pred, timeout=30):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


@pytest.mark.skipif(jax.device_count() < 2, reason="needs a multi-device mesh")
def test_sharded_engine_three_replicas_commit():
    n_dev = jax.device_count()
    groups = 2 * n_dev  # at least two lanes per device
    reg = _Registry()
    members = {1: "m:1", 2: "m:2", 3: "m:3"}
    hosts = {}
    for nid, addr in members.items():
        hosts[nid] = NodeHost(NodeHostConfig(
            deployment_id=11, rtt_millisecond=20, raft_address=addr,
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=groups, max_peers=4,
                log_window=64, shard_over_mesh=True,
            ),
        ))
    try:
        # the engine state must actually live on the mesh
        for nh in hosts.values():
            sh = nh.engine._state.term.sharding
            assert len(sh.device_set) == n_dev, sh
        for c in range(1, groups + 1):
            for nid in members:
                hosts[nid].start_cluster(
                    dict(members), False, KV,
                    Config(cluster_id=c, node_id=nid, election_rtt=20,
                           heartbeat_rtt=4),
                )
        pending = set(range(1, groups + 1))
        deadline = time.monotonic() + 150
        while pending and time.monotonic() < deadline:
            pending -= {
                c for c in pending if hosts[1].get_leader_id(c)[1]
            }
            if pending:
                time.sleep(0.1)
        assert not pending, f"{len(pending)} groups leaderless"
        # one write per group through its leader, quorum-committed across
        # lanes living on different devices; leadership can churn under
        # full-suite CPU load between the probe and the propose — retry
        # against the refreshed leader like a real client
        from dragonboat_tpu.requests import RequestError

        for c in range(1, groups + 1):
            for attempt in range(6):
                lid, ok = hosts[1].get_leader_id(c)
                try:
                    if not ok or lid not in hosts:
                        raise RequestError("leaderless between waves")
                    s = hosts[lid].get_noop_session(c)
                    hosts[lid].sync_propose(s, f"g{c}=v{c}".encode(), 30.0)
                    break
                except RequestError:
                    if attempt == 5:
                        raise
                    time.sleep(1.0)
        # linearizable read-back on a follower host for a few groups
        for c in (1, groups // 2, groups):
            lid = hosts[1].get_leader_id(c)[0]
            fid = next(n for n in members if n != lid)
            assert wait(
                lambda c=c, fid=fid: hosts[fid].sync_read(
                    c, f"g{c}", timeout_s=10.0
                ) == f"v{c}",
                timeout=60,
            )
    finally:
        for nh in hosts.values():
            nh.stop()


@pytest.mark.perf
@pytest.mark.skipif(jax.device_count() < 2, reason="needs a multi-device mesh")
def test_sharded_multistep_engine_padding_and_device_routing(tmp_path):
    """shard_over_mesh composes with steps_per_sync>1 on a shared core:
    the lane round-up is stamped (not silent), ghost lanes are never
    allocated or reported, co-hosted cross-shard traffic rides the
    on-device router (zero host Message objects), and a live lane
    add/remove mid-run stays inside the blessed sync seam with zero
    steady-state retraces."""
    from dragonboat_tpu.profile import (
        compile_watch, diff_compiles, diff_sync, sync_audit,
    )
    from dragonboat_tpu.requests import RequestError

    n_dev = jax.device_count()
    reg = _Registry()
    members = {1: "mk4:1", 2: "mk4:2", 3: "mk4:3"}
    groups = 3       # clusters live at bring-up
    max_groups = 12  # 3 hosts x (3 clusters + 1 live-add slot)
    hosts = {}
    for nid, addr in members.items():
        hosts[nid] = NodeHost(NodeHostConfig(
            deployment_id=11, rtt_millisecond=10, raft_address=addr,
            nodehost_dir=str(tmp_path / f"nh{nid}"),
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=max_groups, max_peers=4,
                log_window=64, shard_over_mesh=True, steps_per_sync=4,
                share_scope="mesh-k4",
            ),
        ))
    try:
        core = hosts[1].engine.core
        assert core._multi == 4  # K>1 really composed with the mesh
        # the requested lane count rounds UP to a mesh multiple: the
        # round-up is stamped in stats and the ghost lanes are never
        # handed to the allocator
        padded = -(-max_groups // n_dev) * n_dev
        assert core.kcfg.groups == padded
        assert core._groups_requested == max_groups
        assert len(core._free) == max_groups
        ss = core.step_stats()
        assert ss["mesh_devices"] == n_dev
        assert ss["padded_groups"] == padded - max_groups
        assert len(core._state.term.sharding.device_set) == n_dev
        for c in range(1, groups + 1):
            for nid in members:
                hosts[nid].start_cluster(
                    dict(members), False, KV,
                    Config(cluster_id=c, node_id=nid, election_rtt=20,
                           heartbeat_rtt=4),
                )
        pending = set(range(1, groups + 1))
        deadline = time.monotonic() + 150
        while pending and time.monotonic() < deadline:
            pending -= {c for c in pending if hosts[1].get_leader_id(c)[1]}
            if pending:
                time.sleep(0.1)
        assert not pending, f"{len(pending)} groups leaderless"

        def _propose(c, payload):
            for attempt in range(6):
                lid, ok = hosts[1].get_leader_id(c)
                try:
                    if not ok or lid not in hosts:
                        raise RequestError("leaderless between waves")
                    s = hosts[lid].get_noop_session(c)
                    hosts[lid].sync_propose(s, payload, 30.0)
                    return
                except RequestError:
                    if attempt == 5:
                        raise
                    time.sleep(1.0)

        # warm the steady state — including one full lane add/remove
        # cycle so the batch-size-parameterized activation helpers are
        # compiled — then mark the audit window
        for c in range(1, groups + 1):
            _propose(c, f"warm{c}=w".encode())
        for nid in members:
            hosts[nid].start_cluster(
                dict(members), False, KV,
                Config(cluster_id=groups + 1, node_id=nid,
                       election_rtt=20, heartbeat_rtt=4),
            )
        assert wait(lambda: hosts[1].get_leader_id(groups + 1)[1],
                    timeout=120)
        for nid in members:
            hosts[nid].stop_cluster(groups + 1)
        sync_mark = sync_audit().snapshot()
        compile_mark = compile_watch().snapshot()
        stats_mark = core.step_stats()

        for i in range(10):
            _propose(1, f"x{i}=v".encode())
        # forwarded linearizable read from a follower host: the routed
        # READ_INDEX / READ_INDEX_RESP round trip crosses shards too
        lid = hosts[1].get_leader_id(1)[0]
        fol = next(n for n in members if n != lid)
        assert wait(
            lambda: hosts[fol].sync_read(1, "x0", timeout_s=10.0) == "v",
            timeout=60,
        )

        # steady state: ZERO host Message objects for co-hosted traffic
        # — everything rode the on-device cross-shard router
        stats_mid = core.step_stats()
        for key in ("msgs_replicate", "msgs_broadcast", "msgs_resp"):
            assert stats_mid[key] == stats_mark[key], (key, stats_mid)
        assert (
            stats_mid["msgs_routed_device"]
            > stats_mark["msgs_routed_device"]
        )

        # live lane add: a new cluster joins all three hosts mid-run...
        c_new = groups + 2
        for nid in members:
            hosts[nid].start_cluster(
                dict(members), False, KV,
                Config(cluster_id=c_new, node_id=nid, election_rtt=20,
                       heartbeat_rtt=4),
            )
        assert wait(lambda: hosts[1].get_leader_id(c_new)[1], timeout=120)
        _propose(c_new, b"live=add")
        # ...and leaves again; the mesh keeps serving the old lanes
        for nid in members:
            hosts[nid].stop_cluster(c_new)
        _propose(1, b"after=remove")

        # across the add/remove the device router kept carrying traffic;
        # a handful of host messages are EXPECTED mid-add (a lane whose
        # peers' lanes don't exist yet rides the host fallback by
        # construction), so only the device counter is asserted here
        stats = core.step_stats()
        assert stats["msgs_routed_device"] > stats_mid["msgs_routed_device"]
        d = diff_sync(sync_mark, sync_audit().snapshot())
        assert d["in_seam"] > 0
        bad = sync_audit().out_of_seam_in_package()
        assert not bad, bad
        # steady state compiles nothing: the sharded scanned kernel is
        # warm and lane add/remove reuses it
        dc = diff_compiles(compile_mark, compile_watch().snapshot())
        assert not dc["per_function"], dc
        # lane_stats reports only REAL lanes: padding never leaks ghosts
        # and cluster c_new's lanes were freed on stop
        assert len(core.lane_stats()) <= 3 * groups
    finally:
        for nh in hosts.values():
            nh.stop()
