"""Pre-vote (Raft thesis 9.6): the non-disruptive election poll.

Three layers:

  * scalar-core conformance — the poll changes NOTHING on either side
    (no term adoption, no vote, no timer reset), grants echo the
    prospective term, stale polls teach the poller the higher term, the
    check-quorum lease refuses polls like votes, and a transfer target
    skips the poll;
  * kernel differential — the vectorized kernel with prevote ON agrees
    with the scalar oracle replica-for-replica across seeded randomized
    fault schedules (prevote OFF equivalence is carried by the whole
    pre-existing differential suite, which runs the same kernel with the
    gate cleared);
  * the rejoin-storm verdict — an isolated/rejoining replica must cause
    ZERO leader changes and ZERO term bumps in the stable quorum with
    pre-vote on, and the SAME schedule reproduces the disturbance with
    it off.
"""
import numpy as np
import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft, RaftNodeState
from dragonboat_tpu.core.remote import Remote
from dragonboat_tpu.ops.loopback import LoopbackCluster
from dragonboat_tpu.ops.state import ROLE, _mix
from dragonboat_tpu.types import Entry, Message, MessageType as MT, is_local_message

N = 3
ELECTION = 10
HEARTBEAT = 2


def mk_raft(nid, pre_vote=True, check_quorum=False, full=(1, 2, 3)):
    r = Raft(
        Config(
            node_id=nid, cluster_id=1, election_rtt=ELECTION,
            heartbeat_rtt=HEARTBEAT, pre_vote=pre_vote,
            check_quorum=check_quorum,
        ),
        InMemLogDB(),
    )
    for p in full:
        r.remotes[p] = Remote(next=1)
    return r


class TestScalarPreVote:
    def test_poll_does_not_touch_term_or_vote(self):
        r = mk_raft(1)
        r.handle(Message(type=MT.ELECTION, from_=1))
        assert r.is_pre_candidate()
        assert r.term == 0 and r.vote == 0
        polls = [m for m in r.msgs if m.type == MT.REQUEST_PREVOTE]
        assert len(polls) == 2  # both peers
        assert all(m.term == r.term + 1 for m in polls)

    def test_voter_grants_without_state_change(self):
        v = mk_raft(2)
        v.term = 4
        v.election_tick = 3
        v.handle(
            Message(type=MT.REQUEST_PREVOTE, from_=1, to=2, term=5,
                    log_index=100, log_term=100)
        )
        # grant echoed at the PROSPECTIVE term; nothing else moved
        resp = [m for m in v.msgs if m.type == MT.REQUEST_PREVOTE_RESP]
        assert len(resp) == 1 and not resp[0].reject and resp[0].term == 5
        assert v.term == 4 and v.vote == 0 and v.election_tick == 3

    def test_voter_rejects_stale_log(self):
        v = mk_raft(2)
        v.term = 4
        v.log.append([Entry(term=4, index=1)])
        v.handle(
            Message(type=MT.REQUEST_PREVOTE, from_=1, to=2, term=5,
                    log_index=0, log_term=0)
        )
        resp = [m for m in v.msgs if m.type == MT.REQUEST_PREVOTE_RESP]
        assert len(resp) == 1 and resp[0].reject

    def test_stale_poll_teaches_higher_term(self):
        """A poll below the receiver's term is rejected AT the receiver's
        term; the poller adopts it and abandons the poll."""
        v = mk_raft(2)
        v.term = 9
        v.handle(
            Message(type=MT.REQUEST_PREVOTE, from_=1, to=2, term=5,
                    log_index=0, log_term=0)
        )
        resp = [m for m in v.msgs if m.type == MT.REQUEST_PREVOTE_RESP]
        assert len(resp) == 1 and resp[0].reject and resp[0].term == 9
        p = mk_raft(1)
        p.term = 4
        p.handle(Message(type=MT.ELECTION, from_=1))
        assert p.is_pre_candidate()
        resp[0].to = 1
        p.handle(resp[0])
        assert p.is_follower() and p.term == 9

    def test_checkquorum_lease_refuses_poll(self):
        v = mk_raft(2, check_quorum=True)
        v.set_leader_id(3)
        v.election_tick = 0  # lease fresh
        v.msgs.clear()
        v.handle(
            Message(type=MT.REQUEST_PREVOTE, from_=1, to=2, term=1,
                    log_index=100, log_term=100)
        )
        assert v.msgs == []  # silently dropped, like the vote

    def test_precandidate_becomes_follower_on_replicate(self):
        r = mk_raft(1)
        r.handle(Message(type=MT.ELECTION, from_=1))
        assert r.is_pre_candidate()
        r.handle(
            Message(type=MT.REPLICATE, from_=2, to=1, term=0,
                    log_index=0, log_term=0, commit=0)
        )
        assert r.is_follower() and r.leader_id == 2

    def test_quorum_of_grants_runs_real_campaign(self):
        r = mk_raft(1)
        r.handle(Message(type=MT.ELECTION, from_=1))
        r.msgs.clear()
        r.handle(
            Message(type=MT.REQUEST_PREVOTE_RESP, from_=2, to=1, term=1)
        )
        assert r.is_candidate() and r.term == 1 and r.vote == 1
        votes = [m for m in r.msgs if m.type == MT.REQUEST_VOTE]
        assert len(votes) == 2

    def test_quorum_of_rejects_falls_back_to_follower(self):
        r = mk_raft(1)
        r.handle(Message(type=MT.ELECTION, from_=1))
        for peer in (2, 3):
            r.handle(
                Message(
                    type=MT.REQUEST_PREVOTE_RESP, from_=peer, to=1,
                    term=r.term, reject=True,
                )
            )
        assert r.is_follower() and r.term == 0

    def test_transfer_target_skips_poll(self):
        r = mk_raft(1)
        r.handle(Message(type=MT.TIMEOUT_NOW, from_=2, to=1))
        # straight to a real (term-bumping) campaign: the transfer IS the
        # quorum's sanction
        assert r.is_candidate() and r.term == 1

    def test_witness_grants_polls_observer_ignores(self):
        w = Raft(
            Config(node_id=3, cluster_id=1, election_rtt=ELECTION,
                   heartbeat_rtt=HEARTBEAT, is_witness=True),
            InMemLogDB(),
        )
        w.remotes[1] = Remote(next=1)
        w.witnesses[3] = Remote(next=1)
        w.handle(
            Message(type=MT.REQUEST_PREVOTE, from_=1, to=3, term=1,
                    log_index=10, log_term=10)
        )
        assert any(
            m.type == MT.REQUEST_PREVOTE_RESP and not m.reject
            for m in w.msgs
        )
        o = Raft(
            Config(node_id=4, cluster_id=1, election_rtt=ELECTION,
                   heartbeat_rtt=HEARTBEAT, is_observer=True),
            InMemLogDB(),
        )
        o.observers[4] = Remote(next=1)
        o.handle(
            Message(type=MT.REQUEST_PREVOTE, from_=1, to=4, term=1,
                    log_index=10, log_term=10)
        )
        assert o.msgs == []


# --------------------------------------------------------------------------
# kernel differential with prevote ON (mirrors test_differential's round
# structure; the scalar oracle runs the same config)
# --------------------------------------------------------------------------


class ScalarPrevoteCluster:
    def __init__(self, seed_of_group, g: int = 0):
        self.rafts = {}
        for nid in range(1, N + 1):
            r = Raft(
                Config(
                    node_id=nid, cluster_id=1, election_rtt=ELECTION,
                    heartbeat_rtt=HEARTBEAT, pre_vote=True,
                ),
                InMemLogDB(),
            )
            for p in range(1, N + 1):
                r.remotes[p] = Remote(next=1)
            slot = nid - 1

            def patched(r=r, slot=slot):
                r.randomized_election_timeout = r.election_timeout + _mix(
                    seed_of_group, r.term, slot
                ) % r.election_timeout

            r.set_randomized_election_timeout = patched
            patched()
            self.rafts[nid] = r
        self.dropped_links = set()
        self.isolated = set()

    def tick_all(self):
        for r in self.rafts.values():
            r.tick()

    def _deliverable(self, m) -> bool:
        f, t = m.from_ - 1, m.to - 1
        if (f, t) in self.dropped_links:
            return False
        return f not in self.isolated and t not in self.isolated

    def settle(self, rounds=20):
        for _ in range(rounds):
            msgs = []
            for r in self.rafts.values():
                msgs.extend(m for m in r.msgs if not is_local_message(m.type))
                r.msgs = []
            if not msgs:
                return
            for m in msgs:
                if m.to in self.rafts and self._deliverable(m):
                    self.rafts[m.to].handle(m)

    def propose(self, nid, n=1):
        self.rafts[nid].handle(
            Message(
                type=MT.PROPOSE, from_=nid,
                entries=[Entry(cmd=b"p%d" % i) for i in range(n)],
            )
        )

    def observables(self):
        res = []
        for nid in range(1, N + 1):
            r = self.rafts[nid]
            res.append(
                {
                    "role": int(r.state),
                    "term": r.term,
                    "leader": r.leader_id - 1 if r.leader_id else -1,
                    "committed": r.log.committed,
                    "last": r.log.last_index(),
                }
            )
        return res


def _kernel_observables(kc, g=0):
    res = []
    for h in range(kc.n_replicas):
        st = kc.states[h]
        res.append(
            {
                "role": int(np.asarray(st.role)[g]),
                "term": int(np.asarray(st.term)[g]),
                "leader": int(np.asarray(st.leader)[g]) - 1,
                "committed": int(np.asarray(st.committed)[g]),
                "last": int(np.asarray(st.last_index)[g]),
            }
        )
    return res


@pytest.mark.parametrize("seed", [3, 17])
def test_differential_prevote_randomized_faults(seed):
    """Kernel (prevote on) vs scalar oracle (pre_vote=True) under a
    seeded schedule of link faults, isolation windows and proposals:
    role/term/leader/commit/last must agree replica-for-replica after
    every settled round."""
    import random

    rng = random.Random(seed)
    kc = LoopbackCluster(
        n_replicas=N, n_groups=1, election=ELECTION, heartbeat=HEARTBEAT,
        prevote=True, seed=0,
    )
    seed_of_group = int(np.asarray(kc.states[0].seed)[0])
    sc = ScalarPrevoteCluster(seed_of_group)

    def run_round(proposals=0):
        kc.step(tick=True)
        kc.settle()
        sc.tick_all()
        sc.settle()
        if proposals:
            lead = kc.leader_of(0)
            if lead is not None:
                kc.propose(lead, 0, proposals)
                sc.propose(lead + 1, proposals)
                kc.settle()
                sc.settle()

    for step in range(120):
        # seeded fault churn, mirrored onto both implementations
        if rng.random() < 0.08:
            a, b = rng.sample(range(N), 2)
            kc.dropped_links.add((a, b))
            sc.dropped_links.add((a, b))
        if rng.random() < 0.08:
            kc.dropped_links.clear()
            sc.dropped_links.clear()
        if rng.random() < 0.04 and not kc.isolated:
            v = rng.randrange(N)
            kc.isolated.add(v)
            sc.isolated.add(v)
        if rng.random() < 0.10:
            kc.isolated.clear()
            sc.isolated.clear()
        run_round(proposals=1 if rng.random() < 0.3 else 0)
        ko = _kernel_observables(kc)
        so = sc.observables()
        assert ko == so, f"seed {seed} diverged at step {step}:\n{ko}\n{so}"


def test_rejoin_storm_prevote_on_vs_off():
    """The acceptance verdict at kernel level: the same isolation/heal
    schedule disturbs the stable quorum with pre-vote OFF (term
    inflation forces a term bump on heal) and leaves it untouched with
    pre-vote ON."""

    def run(prevote):
        kc = LoopbackCluster(
            n_replicas=N, n_groups=1, election=ELECTION,
            heartbeat=HEARTBEAT, prevote=prevote,
        )
        for _ in range(200):
            kc.step()
            kc.settle()
            if kc.leader_of(0) is not None:
                break
        lead = kc.leader_of(0)
        assert lead is not None
        base_terms = kc.field("term", 0)
        victim = (lead + 1) % N
        kc.isolated.add(victim)
        for _ in range(8 * ELECTION):
            kc.step()
            kc.settle()
        kc.isolated.clear()
        for _ in range(4 * ELECTION):
            kc.step()
            kc.settle()
        return lead, base_terms, kc.field("term", 0), kc.leader_of(0)

    lead_on, t0_on, t1_on, lead_after_on = run(True)
    # pre-vote ON: zero disturbance — same leader, stable quorum's term
    # never moved, the rejoiner's term never inflated
    assert lead_after_on == lead_on
    assert t1_on == t0_on, f"terms moved with prevote on: {t0_on} -> {t1_on}"

    lead_off, t0_off, t1_off, _ = run(False)
    # pre-vote OFF, same schedule: the isolated replica's term inflates
    # and the heal disturbs the quorum (term bump at minimum)
    assert t1_off != t0_off, "expected a disturbance with prevote off"
