"""The overload robustness plane (dragonboat_tpu.serving) — tier-1 gate.

Covers the ISSUE 8 contract end to end:

  * admission control: per-tenant token buckets, urgent-ahead-of-bulk,
    saturation-tightened rates, typed ErrOverloaded sheds with
    machine-readable retry-after hints;
  * backpressure: the WAL barrier / engine inbox / request-pool signals
    folded into one cached saturation score;
  * the deadline-honoring client retry helper (jittered exponential,
    server hint as floor, retries never outlive the caller's timeout);
  * quiesce wake-on-admit (engine/quiesce.py contract) on the scalar
    engine, plus the vector-lane mirror probe;
  * the pool-exhaustion ErrSystemBusy raise sites in requests.py (both
    single-slot sites, incl. slot reuse after a timeout sweep);
  * the seeded overload_storm graceful-degradation verdict: under 2x
    sustained overload, zero urgent-class sheds, bounded urgent p99,
    fail-fast hinted bulk sheds, admitted throughput within 20% of the
    unloaded baseline, and bit-identical same-seed replay.

Run alone with `-m serving`.
"""
import io
import random
import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.client import Session
from dragonboat_tpu.events import MetricsRegistry
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import (
    ErrRejected,
    ErrSystemBusy,
    ErrTimeout,
    LogicalClock,
    PendingConfigChange,
    PendingLeaderTransfer,
    REQUEST_COMPLETED,
    RequestResult,
    RequestState,
)
from dragonboat_tpu.serving import (
    AdmissionConfig,
    AdmissionController,
    ErrBackpressure,
    ErrOverloaded,
    ErrTenantThrottled,
    KLASS_BULK,
    KLASS_URGENT,
    SaturationMonitor,
    SaturationThresholds,
    ServingFront,
    TenantSpec,
    TokenBucket,
    call_with_retries,
    run_overload_storm,
)
from dragonboat_tpu.serving.front import FrontConfig
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.storage.kv import (
    _barrier_stats,
    barrier_stats,
    reset_barrier_stats,
)
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

pytestmark = pytest.mark.serving


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def now(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# token bucket + admission decisions
# ---------------------------------------------------------------------------


def test_token_bucket_refill_and_hint():
    clk = FakeClock()
    b = TokenBucket(rate=10.0, burst=5.0, clock=clk.now)
    for _ in range(5):
        assert b.take(1.0) == 0.0
    # empty: the hint is the refill time for the refused cost, and the
    # failed take consumes nothing
    wait = b.take(2.0)
    assert wait == pytest.approx(0.2)
    assert b.balance() == pytest.approx(0.0)
    clk.sleep(wait)
    assert b.take(2.0) == 0.0
    # refill caps at burst
    clk.sleep(100.0)
    b.take(0.0)
    assert b.balance() == pytest.approx(5.0)


def test_token_bucket_zero_rate_blocks_without_crashing():
    """rate=0 is the natural way to fully block a tenant: takes beyond
    the initial burst throttle with an infinite hint (never refills)
    instead of dividing by zero, and the retry helper converts that hint
    into an immediate ErrTimeout rather than an unbounded sleep."""
    clk = FakeClock()
    b = TokenBucket(rate=0.0, burst=1.0, clock=clk.now)
    assert b.take(1.0) == 0.0  # the initial burst is still spendable
    assert b.take(1.0) == float("inf")
    clk.sleep(1e6)
    assert b.take(1.0) == float("inf")  # really never refills
    ac = AdmissionController(
        AdmissionConfig(tenants={7: TenantSpec(rate=0.0, burst=0.0)})
    )
    with pytest.raises(ErrTenantThrottled) as ei:
        ac.admit(7, KLASS_BULK)
    assert ei.value.retry_after_s == float("inf")
    with pytest.raises(ErrTimeout):
        call_with_retries(
            lambda _rem: ac.admit(7, KLASS_BULK),
            deadline_s=5.0,
            clock=clk.now,
            sleep=clk.sleep,
        )


def test_token_bucket_saturation_scale_slows_refill():
    clk = FakeClock()
    b = TokenBucket(rate=10.0, burst=1.0, clock=clk.now)
    assert b.take(1.0) == 0.0
    # at scale 0.1 the effective rate is 1/s: one token needs 1s not .1s
    assert b.take(1.0, scale=0.1) == pytest.approx(1.0)


def test_admission_urgent_never_shed_even_saturated():
    ac = AdmissionController(
        AdmissionConfig(default=TenantSpec(rate=1.0, burst=1.0)),
        saturation=lambda: 1.0,
    )
    for _ in range(100):
        ac.admit(7, KLASS_URGENT)
    c = ac.counters()[7]
    assert c["admitted"][KLASS_URGENT] == 100
    assert c["shed"][KLASS_URGENT] == 0


def test_admission_bulk_sheds_at_saturation_with_hint():
    ac = AdmissionController(
        AdmissionConfig(default=TenantSpec(rate=1e9, burst=1e9)),
        saturation=lambda: 0.95,
    )
    with pytest.raises(ErrBackpressure) as ei:
        ac.admit(3, KLASS_BULK)
    assert ei.value.retry_after_s > 0.0
    assert isinstance(ei.value, ErrSystemBusy)  # uniform client contract
    assert ac.counters()[3]["shed"][KLASS_BULK] == 1


def test_admission_bucket_empty_sheds_with_refill_hint():
    clk = FakeClock()
    ac = AdmissionController(
        AdmissionConfig(default=TenantSpec(rate=10.0, burst=1.0)),
        saturation=lambda: 0.0,
        clock=clk.now,
    )
    ac.admit(4, KLASS_BULK)
    with pytest.raises(ErrTenantThrottled) as ei:
        ac.admit(4, KLASS_BULK)
    assert ei.value.retry_after_s == pytest.approx(0.1)
    c = ac.counters()[4]
    assert c["admitted"][KLASS_BULK] == 1 and c["shed"][KLASS_BULK] == 1


def test_admission_rate_scale_curve():
    ac = AdmissionController(
        AdmissionConfig(tighten_from=0.5, shed_bulk_at=0.9, min_rate_scale=0.1)
    )
    assert ac.rate_scale(0.0) == 1.0
    assert ac.rate_scale(0.5) == 1.0
    assert ac.rate_scale(0.7) == pytest.approx(0.55)
    assert ac.rate_scale(0.9) == pytest.approx(0.1)
    assert ac.rate_scale(1.0) == pytest.approx(0.1)


def test_admission_downstream_shed_keeps_ledger_honest():
    ac = AdmissionController(
        AdmissionConfig(default=TenantSpec(rate=1e9, burst=1e9))
    )
    ac.admit(5, KLASS_BULK)
    ac.note_downstream_shed(5, KLASS_BULK)
    c = ac.counters()[5]
    assert c["admitted"][KLASS_BULK] == 0 and c["shed"][KLASS_BULK] == 1


# ---------------------------------------------------------------------------
# backpressure folding
# ---------------------------------------------------------------------------


class _FakeEngine:
    def __init__(self):
        self.stats = {"inbox_occupancy": 0.0, "staged_backlog": 0}

    def pressure_stats(self):
        return dict(self.stats)


class _FakePressureHost:
    def __init__(self):
        self.engine = _FakeEngine()
        self.fill = 0.0

    def ingress_fill(self):
        return self.fill


def test_scalar_pressure_staged_backlog_counts_queued():
    """ISSUE 18 satellite: ExecEngine.pressure_stats() must report the
    REAL accepted-but-not-yet-stepped backlog (EntryQueue + ReadIndex
    queue pending counts), not a hardcoded 0 — vector-engine parity for
    the serving front's saturation fold."""
    from types import SimpleNamespace

    from dragonboat_tpu.engine.execengine import ExecEngine
    from dragonboat_tpu.engine.queue import EntryQueue, ReadIndexQueue
    from dragonboat_tpu.storage.logdb import ShardedLogDB
    from dragonboat_tpu.types import Entry

    eng = ExecEngine(ShardedLogDB())
    try:
        p = eng.pressure_stats()
        assert p == {"inbox_occupancy": 0.0, "staged_backlog": 0}
        node = SimpleNamespace(
            incoming_proposals=EntryQueue(size=8),
            incoming_reads=ReadIndexQueue(size=8),
        )
        for i in range(3):
            assert node.incoming_proposals.add(Entry(cmd=b"x"))
        assert node.incoming_reads.add(object())
        with eng._nodes_mu:
            eng._nodes[1] = node
        p = eng.pressure_stats()
        assert p["staged_backlog"] == 4
        assert p["inbox_occupancy"] == pytest.approx(3 / 8)
        # the step worker draining the queues drains the backlog
        node.incoming_proposals.get()
        node.incoming_reads.get()
        assert eng.pressure_stats()["staged_backlog"] == 0
    finally:
        with eng._nodes_mu:
            eng._nodes.clear()
        eng.stop()


@pytest.fixture
def clean_barrier_stats():
    reset_barrier_stats()
    yield
    reset_barrier_stats()


def test_saturation_monitor_folds_max_of_signals(clean_barrier_stats):
    clk = FakeClock()
    nh = _FakePressureHost()
    mon = SaturationMonitor(
        nh,
        SaturationThresholds(
            fsync_ewma_full_s=0.1, fsync_inflight_full=4,
            staged_backlog_full=100,
        ),
        interval_s=0.0,
        clock=clk.now,
    )
    assert mon.score() == 0.0
    nh.engine.stats["staged_backlog"] = 50
    clk.sleep(1.0)
    assert mon.score() == pytest.approx(0.5)
    # the WAL barrier is the bottleneck: the score is the MAX, not a mean
    _barrier_stats.enter()
    _barrier_stats.exit(10.0)  # ewma saturates past 0.1s full-scale
    clk.sleep(1.0)
    assert mon.score() == 1.0
    sig = mon.last_signals()
    assert sig["fsync_latency"] == 1.0
    assert sig["engine_staged"] == pytest.approx(0.5)
    # request-pool fill drives the score too
    reset_barrier_stats()
    nh.engine.stats["staged_backlog"] = 0
    nh.fill = 0.8
    clk.sleep(1.0)
    assert mon.score() == pytest.approx(0.8)


def test_saturation_monitor_caches_by_interval(clean_barrier_stats):
    clk = FakeClock()
    nh = _FakePressureHost()
    mon = SaturationMonitor(nh, interval_s=1.0, clock=clk.now)
    assert mon.score() == 0.0
    nh.fill = 1.0
    assert mon.score() == 0.0  # cached sample
    clk.sleep(1.5)
    assert mon.score() == 1.0


def test_saturation_override_pins_score():
    mon = SaturationMonitor(None)
    mon.set_override(0.77)
    assert mon.score() == 0.77
    mon.set_override(None)
    assert mon.score() <= 1.0


def test_wal_barrier_stats_track_real_fsyncs(tmp_path, clean_barrier_stats):
    from dragonboat_tpu.storage.kv import WalKV, WriteBatch, sync_all

    kv = WalKV(str(tmp_path / "wal"))
    try:
        wb = WriteBatch()
        wb.put(b"k", b"v")
        kv.commit_write_batch(wb)
        sync_all([kv])
        bs = barrier_stats()
        assert bs["barriers"] >= 1
        assert bs["ewma_s"] > 0.0
        assert bs["inflight"] == 0
        assert bs["last_wave_s"] > 0.0
    finally:
        kv.close()


# ---------------------------------------------------------------------------
# deadline-aware retry helper
# ---------------------------------------------------------------------------


def test_retry_retries_busy_until_success_honoring_hint():
    clk = FakeClock()
    sleeps = []

    def sleep(dt):
        sleeps.append(dt)
        clk.sleep(dt)

    calls = []

    def fn(remaining):
        calls.append(remaining)
        if len(calls) < 3:
            raise ErrTenantThrottled(retry_after_s=0.05)
        return "ok"

    assert (
        call_with_retries(
            fn, 10.0, base_s=0.01, rng=random.Random(7),
            clock=clk.now, sleep=sleep,
        )
        == "ok"
    )
    assert len(sleeps) == 2
    assert all(s >= 0.05 for s in sleeps)  # server hint is the floor
    # fn receives the SHRINKING remaining budget
    assert calls[0] == pytest.approx(10.0)
    assert calls[1] < calls[0] and calls[2] < calls[1]


def test_retry_propagates_session_same_series():
    """ISSUE 14 satellite: call_with_retries(session=...) hands the SAME
    session object to every attempt and the series id never advances
    between retries — a retried proposal dedups against the original
    apply instead of double-applying under an accidental new series."""
    clk = FakeClock()
    sess = Session.new_session(5)
    sess.prepare_for_propose()
    series0 = sess.series_id
    attempts = []

    def fn(remaining, session):
        attempts.append((session, session.series_id))
        if len(attempts) < 3:
            raise ErrTenantThrottled(retry_after_s=0.01)
        return "applied"

    assert (
        call_with_retries(
            fn, 10.0, rng=random.Random(3),
            clock=clk.now, sleep=clk.sleep, session=sess,
        )
        == "applied"
    )
    assert len(attempts) == 3
    assert all(s is sess for s, _ in attempts)
    assert {sid for _, sid in attempts} == {series0}, (
        "a retry minted a new series"
    )


def test_retry_refuses_advanced_series_on_retryable_failure():
    """If an attempt ADVANCED the session (it completed) and still
    raised a retryable error, retrying would re-propose under a fresh
    series — the one double-apply shape the session parameter exists to
    prevent — so the helper refuses loudly instead of sleeping."""
    clk = FakeClock()
    sess = Session.new_session(5)
    sess.prepare_for_propose()

    def fn(remaining, session):
        session.proposal_completed()  # buggy caller: acked mid-attempt
        raise ErrTenantThrottled(retry_after_s=0.01)

    with pytest.raises(RuntimeError, match="series advanced"):
        call_with_retries(
            fn, 10.0, rng=random.Random(3),
            clock=clk.now, sleep=clk.sleep, session=sess,
        )


def test_retry_never_outlives_deadline():
    clk = FakeClock()
    sleeps = []

    def fn(remaining):
        raise ErrBackpressure(retry_after_s=5.0)

    with pytest.raises(ErrTimeout):
        call_with_retries(
            fn, 1.0, rng=random.Random(1), clock=clk.now,
            sleep=lambda dt: sleeps.append(dt),
        )
    # the hint says the server won't take it before the caller stops
    # caring: give up NOW, without burning the backoff sleep
    assert sleeps == []
    assert clk.t == pytest.approx(100.0)


def test_retry_zero_budget_and_non_busy_errors():
    with pytest.raises(ErrTimeout):
        call_with_retries(lambda r: "x", 0.0)

    def rejected(remaining):
        raise ErrRejected()

    with pytest.raises(ErrRejected):  # only the busy family retries
        call_with_retries(rejected, 10.0)


def test_retry_backoff_is_jittered_exponential():
    clk = FakeClock()
    sleeps = []

    def sleep(dt):
        sleeps.append(dt)
        clk.sleep(dt)

    attempts = [0]

    def fn(remaining):
        attempts[0] += 1
        if attempts[0] <= 6:
            raise ErrOverloaded()  # no hint: pure jittered backoff
        return None

    call_with_retries(
        fn, 100.0, base_s=0.01, factor=2.0, max_backoff_s=0.1,
        rng=random.Random(3), clock=clk.now, sleep=sleep,
    )
    # each delay is uniform(0, min(base*2^k, cap)): bounded by the cap
    caps = [min(0.01 * (2.0 ** k), 0.1) for k in range(6)]
    assert all(0.0 <= s <= c for s, c in zip(sleeps, caps))


# ---------------------------------------------------------------------------
# requests.py pool-exhaustion raise sites (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def test_single_slot_pool_busy_and_timeout_reuse():
    clock = LogicalClock()
    pool = PendingConfigChange(clock)
    rs, _cc, key = pool.request(None, timeout_ticks=2)
    # the raise site: a second request while one is pending
    with pytest.raises(ErrSystemBusy):
        pool.request(None, timeout_ticks=2)
    # a slot freed by TIMEOUT is reusable
    clock.tick += 3
    pool.gc()
    assert rs.wait(1.0).timeout
    rs2, _cc2, key2 = pool.request(None, timeout_ticks=2)
    assert key2 != key
    pool.apply(key2, rejected=False)
    assert rs2.wait(1.0).completed


def test_leader_transfer_slot_busy_and_reuse():
    p = PendingLeaderTransfer()
    p.request(2)
    with pytest.raises(ErrSystemBusy):  # the second raise site
        p.request(3)
    assert p.get() == 2  # consumed by the step loop
    p.request(3)  # freed slot is reusable
    assert p.get() == 3


# ---------------------------------------------------------------------------
# serving front over a fake host (deterministic shed paths)
# ---------------------------------------------------------------------------


class _FakeHost:
    """The minimum NodeHost surface ServingFront touches, with manual
    completion control."""

    def __init__(self):
        self.metrics = MetricsRegistry()
        self.batches = []  # (cluster_id, cmds, rss)
        self.busy = False
        self.woken = []

    def get_noop_session(self, cluster_id):
        return Session.noop_session(cluster_id)

    def propose_batch(self, session, cmds, timeout_s):
        if self.busy:
            raise ErrSystemBusy()
        rss = [RequestState() for _ in cmds]
        self.batches.append((session.cluster_id, list(cmds), rss))
        return rss

    def read_index(self, cluster_id, timeout_s):
        rs = RequestState()
        rs.notify(RequestResult(code=REQUEST_COMPLETED))
        return rs

    def notify_group_admission(self, cluster_id):
        self.woken.append(cluster_id)
        return True


def _mk_front(host=None, **admission_kw):
    host = host or _FakeHost()
    mon = SaturationMonitor(None)
    front = ServingFront(
        host,
        admission=AdmissionConfig(**admission_kw) if admission_kw else None,
        monitor=mon,
    )
    return host, front


def test_front_completes_admitted_bulk_and_counts_wakes():
    host, front = _mk_front()
    try:
        t = front.propose(1, 100, b"k=v", 5.0)
        deadline = time.monotonic() + 5
        while not host.batches and time.monotonic() < deadline:
            time.sleep(0.005)
        assert host.batches, "pump never submitted"
        cid, cmds, rss = host.batches[0]
        assert (cid, cmds) == (100, [b"k=v"])
        rss[0].notify(RequestResult(code=REQUEST_COMPLETED))
        assert t.wait(5.0).completed
        c = front.admission.counters()[1]
        assert c["admitted"][KLASS_BULK] == 1
        # the fake host reports the group as quiesced: wake counted
        assert host.woken == [100] and c["wakes"] == 1
    finally:
        front.stop()


def test_front_downstream_busy_fails_fast_with_hint():
    host, front = _mk_front()
    host.busy = True
    try:
        t = front.propose(1, 100, b"k=v", 30.0)
        t0 = time.monotonic()
        with pytest.raises(ErrBackpressure) as ei:
            t.wait(10.0)
        # the CONTRACT: a shed op fails fast, it does not wait out the
        # client's 30s timeout behind a saturated engine
        assert time.monotonic() - t0 < 5.0
        assert ei.value.retry_after_s > 0.0
        c = front.admission.counters()[1]
        assert c["shed"][KLASS_BULK] == 1 and c["admitted"][KLASS_BULK] == 0
    finally:
        front.stop()


def test_front_saturation_sheds_bulk_admits_urgent():
    host, front = _mk_front()
    front.monitor.set_override(0.95)
    try:
        with pytest.raises(ErrBackpressure) as ei:
            front.propose(2, 100, b"k=v", 5.0)
        assert ei.value.retry_after_s > 0.0
        rs = front.read(2, 100, 5.0)  # urgent still flows
        assert rs.wait(1.0).completed
        c = front.admission.counters()[2]
        assert c["shed"][KLASS_BULK] == 1
        assert c["admitted"][KLASS_URGENT] == 1
        assert c["shed"][KLASS_URGENT] == 0
    finally:
        front.stop()


def test_front_queue_bound_sheds_instead_of_growing():
    host = _FakeHost()
    front = ServingFront(
        host, front=FrontConfig(max_queued_per_tenant=0)
    )
    try:
        with pytest.raises(ErrBackpressure):
            front.propose(3, 100, b"k=v", 5.0)
        assert front.admission.counters()[3]["shed"][KLASS_BULK] == 1
    finally:
        front.stop()


def test_front_stop_drains_queued_tickets():
    from dragonboat_tpu.requests import ErrClusterClosed
    from dragonboat_tpu.serving.front import _QueuedOp
    from dragonboat_tpu.serving import Ticket

    host = _FakeHost()
    # a long pump interval parks injected ops until stop() runs
    front = ServingFront(host, front=FrontConfig(pump_interval_s=5.0))
    now = time.monotonic()
    tk = Ticket(now + 30.0, now)
    with front._mu:
        front._queues.setdefault(1, []).append(_QueuedOp(100, b"k=v", tk))
    front.stop()
    with pytest.raises(ErrClusterClosed):  # drained, never hangs
        tk.wait(5.0)


def test_front_gauge_export_labels():
    host, front = _mk_front()
    try:
        with pytest.raises(ErrTimeout):
            front.sync_propose(9, 100, b"k=v", 0.05)
        front.export_gauges(host.metrics)
        w = io.StringIO()
        host.metrics.write(w)
        text = w.getvalue()
        assert 'serving_admitted_total{klass="bulk",tenant="9"} 1' in text
        assert 'serving_shed_total{klass="urgent",tenant="9"} 0' in text
        assert "serving_saturation" in text
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# live-host integration (scalar + vector engines)
# ---------------------------------------------------------------------------


class KVSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.data = {}
        self.n = 0

    def update(self, cmd: bytes) -> Result:
        k, v = cmd.decode().split("=", 1)
        self.data[k] = v
        self.n += 1
        return Result(value=self.n)

    def lookup(self, q):
        return self.data.get(q)

    def save_snapshot(self, w, files, done):
        import json

        w.write(json.dumps([self.data, self.n]).encode())

    def recover_from_snapshot(self, r, files, done):
        import json

        self.data, self.n = json.loads(r.read().decode())


def mk_host(addr, registry, engine_kind="scalar", rtt_ms=5):
    return NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=rtt_ms,
            raft_address=addr,
            raft_rpc_factory=lambda listen: loopback_factory(listen, registry),
            engine=EngineConfig(
                kind=engine_kind, max_groups=32, max_peers=4, log_window=64
            ),
        )
    )


def group_config(cluster_id, node_id, **kw):
    return Config(
        cluster_id=cluster_id,
        node_id=node_id,
        election_rtt=10,
        heartbeat_rtt=2,
        **kw,
    )


def wait_for(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(params=["scalar", "vector"])
def engine_kind(request):
    return request.param


def test_front_end_to_end_on_live_host(engine_kind):
    reg = _Registry()
    nh = mk_host("a:1", reg, engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, KVSM, group_config(100, 1))
        assert wait_for(lambda: nh.get_leader_id(100)[1], timeout=60)
        front = nh.serving_front()
        assert nh.serving_front() is front  # one per host
        assert front.sync_propose(7, 100, b"k1=v1", 20.0).value == 1
        assert front.sync_read(7, 100, "k1", 20.0) == "v1"
        # the engine-side pressure probe exists and is sane
        p = nh.engine.pressure_stats()
        assert 0.0 <= p["inbox_occupancy"] <= 1.0
        assert p["staged_backlog"] >= 0
        assert 0.0 <= nh.ingress_fill() <= 1.0
        # per-tenant ledger reaches the health exposition
        nh._export_health_gauges()
        w = io.StringIO()
        nh.write_health_metrics(w)
        assert 'serving_admitted_total{klass="bulk",tenant="7"} 1' in (
            w.getvalue()
        )
    finally:
        nh.stop()


def test_quiesce_wake_on_admit_scalar():
    """ISSUE 8 satellite: an idle quiesced group resumes ticking on the
    FIRST admitted proposal and re-quiesces after the burst."""
    reg = _Registry()
    nh = mk_host("a:1", reg, "scalar", rtt_ms=2)
    try:
        nh.start_cluster(
            {1: "a:1"}, False, KVSM, group_config(100, 1, quiesce=True)
        )
        assert wait_for(lambda: nh.get_leader_id(100)[1])
        node = nh._get_node(100)
        assert wait_for(lambda: node.quiesce_mgr.quiesced(), timeout=30), (
            "group never quiesced while idle"
        )
        front = nh.serving_front()
        t = front.propose(3, 100, b"a=1", 20.0)
        # the admit itself woke the group (before the op reached the
        # step loop) and the wake was counted to the tenant
        assert not node.quiesce_mgr.quiesced()
        assert front.admission.counters()[3]["wakes"] == 1
        assert t.wait().completed
        # after the burst the group re-enters quiesce on its own
        assert wait_for(lambda: node.quiesce_mgr.quiesced(), timeout=30), (
            "group never re-quiesced after the burst"
        )
        # a second admit wakes again: the counter keeps meaning wakes
        assert front.sync_propose(3, 100, b"b=2", 20.0).value == 2
        assert front.admission.counters()[3]["wakes"] == 2
    finally:
        nh.stop()


def test_vector_wake_counted_once_per_transition():
    """The vector mirror probe must match the scalar semantics: a burst
    of admits against one quiesced lane is ONE quiesced->active
    transition, so only the first admit reports a wake — the mirror
    stays stale until the next decode, and the latch re-arms once the
    lane is actually awake."""
    reg = _Registry()
    nh = mk_host("a:1", reg, "vector", rtt_ms=2)
    try:
        nh.start_cluster(
            {1: "a:1"}, False, KVSM, group_config(100, 1, quiesce=True)
        )
        assert wait_for(lambda: nh.get_leader_id(100)[1], timeout=60)
        node = nh._get_node(100)
        lane = node._vec_lane
        quiesced = lambda: bool(nh.engine._m_quiesced[lane.g])
        assert wait_for(quiesced, timeout=60), "lane never quiesced"
        assert node.notify_admission() is True
        assert node.notify_admission() is False  # mirror still stale
        # real traffic wakes the lane; an active lane reports no wake
        # and re-arms the latch for the next transition
        front = nh.serving_front()
        assert front.sync_propose(3, 100, b"a=1", 20.0).value == 1
        assert wait_for(lambda: not quiesced()), "lane never woke"
        assert node.notify_admission() is False
        assert wait_for(quiesced, timeout=60), "lane never re-quiesced"
        assert node.notify_admission() is True
    finally:
        nh.stop()


def test_storm_count_survives_downstream_sheds():
    """An admitted ticket shed deeper in the stack re-raises its typed
    error from wait(); the storm verdict must fold that into the shed
    ledger (hint checked) instead of crashing — regression for the
    tier-1 gate dying under exactly the overload it measures."""
    from dragonboat_tpu.serving.front import Ticket
    from dragonboat_tpu.serving.storm import StormReport, _count_completed

    now = time.monotonic()
    ok = Ticket(now + 5.0, now)
    ok._complete(RequestResult(code=REQUEST_COMPLETED))
    hinted = Ticket(now + 5.0, now)
    hinted._fail(ErrBackpressure(retry_after_s=0.1))
    unhinted = Ticket(now + 5.0, now)
    unhinted._fail(ErrBackpressure(retry_after_s=0.0))
    rep = StormReport(seed=1)
    assert _count_completed([ok, hinted], rep) == 1
    assert rep.shed == 1 and rep.retry_hints_ok
    assert _count_completed([unhinted], rep) == 0
    assert rep.shed == 2 and not rep.retry_hints_ok


def test_quiesce_manager_wake_on_admit_unit():
    from dragonboat_tpu.engine.quiesce import QuiesceManager

    qm = QuiesceManager(enabled=True, election_tick=2)
    assert qm.wake_on_admit() is False  # active group: no wake counted
    for _ in range(qm.threshold + 1):
        qm.tick()
    assert qm.quiesced()
    assert qm.wake_on_admit() is True
    assert not qm.quiesced()
    # disabled managers never report wakes
    qd = QuiesceManager(enabled=False, election_tick=2)
    for _ in range(100):
        qd.tick()
    assert qd.wake_on_admit() is False


# ---------------------------------------------------------------------------
# the graceful-degradation verdict (acceptance criteria)
# ---------------------------------------------------------------------------


def test_overload_storm_graceful_degradation_verdict():
    """Under seeded 2x overload: zero urgent sheds, bounded urgent p99,
    fail-fast hinted bulk sheds, admitted throughput >= 0.8x baseline —
    and the same seed replays the window schedule bit-identically."""
    reg = _Registry()
    nh = mk_host("a:1", reg, "scalar")
    try:
        nh.start_cluster({1: "a:1"}, False, KVSM, group_config(100, 1))
        assert wait_for(lambda: nh.get_leader_id(100)[1])
        # capacity well under the engine's unloaded rate, so the verdict
        # threshold rides the policy cap with margin on a slow box
        rep = run_overload_storm(
            nh, 100, seed=0xD1A60, storm_s=0.8, baseline_ops=300,
            capacity_rate=800.0,
        )
        assert rep.verdicts["zero_urgent_shed"], rep.verdicts
        assert rep.verdicts["urgent_p99_bounded"], rep.urgent_p99_s
        assert rep.verdicts["bulk_shed_under_overload"], rep.shed
        assert rep.verdicts["shed_fails_fast"], rep.shed_max_latency_s
        assert rep.verdicts["throughput_within_20pct"], (
            rep.baseline_tput, rep.storm_tput,
        )
        assert rep.ok
        assert rep.shed > 0 and rep.offered > rep.admitted
        # same-seed replay: identical window schedule AND signature
        rep2 = run_overload_storm(
            nh, 100, seed=0xD1A60, storm_s=0.8, baseline_ops=300,
            capacity_rate=800.0,
        )
        assert rep2.windows == rep.windows
        assert rep2.signature == rep.signature
        # a different seed draws a different storm
        rep3 = run_overload_storm(
            nh, 100, seed=0xBEEF, storm_s=0.8, baseline_ops=300,
            capacity_rate=800.0,
        )
        assert rep3.signature != rep.signature
    finally:
        nh.stop()


def test_storm_schedule_is_seed_deterministic_without_a_host():
    from dragonboat_tpu.faults import FaultPlane

    def draw(seed):
        fp = FaultPlane(seed)
        return [
            (p, round(m, 6), round(w, 6), wts)
            for p, m, w, wts in fp.overload_storm_schedule(
                "storm", (1, 2, 3), 2.0
            )
        ]

    a, b, c = draw(11), draw(11), draw(12)
    assert a == b
    assert a != c
    for profile, mult, window, weights in a:
        assert profile in ("burst", "sustained")
        if profile == "burst":
            assert 2.0 <= mult <= 4.0
        else:
            assert 1.5 <= mult <= 2.5
        assert set(weights) == {1, 2, 3}
    assert sum(w for _, _, w, _ in a) >= 2.0


# ---------------------------------------------------------------------------
# bench JSON fold schema
# ---------------------------------------------------------------------------


def test_bench_serving_report_schema_stable():
    import bench

    keys = {
        "serving_admitted_total",
        "serving_shed_total",
        "serving_wakes_total",
        "serving_urgent_p99_s",
        "serving_bulk_p50_s",
        "serving_bulk_p99_s",
        # ISSUE 14: per-tenant latency + the session/migration ledger
        # joined the ALWAYS-present fold (zero/empty when no front,
        # placement plane or migration stream existed)
        "serving_tenant_latency",
        "migrations_started",
        "migrations_completed",
        "migrations_aborted",
        "migration_streams",
    }
    assert keys == set(bench._serving_report({}))  # zero hosts
    host, front = _mk_front()
    try:
        with pytest.raises(ErrTimeout):
            front.sync_propose(1, 100, b"k=v", 0.05)
        host._serving = front
        r = bench._serving_report({1: host})
        assert r["serving_admitted_total"] == 1
        assert r["migrations_started"] == 0
        assert r["serving_tenant_latency"] == {}
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# queue fill probes (the request-pool backpressure source)
# ---------------------------------------------------------------------------


def test_queue_fill_probes():
    from dragonboat_tpu.engine.queue import EntryQueue, ReadIndexQueue
    from dragonboat_tpu.types import Entry

    q = EntryQueue(4)
    assert q.fill() == 0.0
    q.add(Entry(cmd=b"x"))
    assert q.fill() == pytest.approx(0.25)
    for _ in range(5):
        q.add(Entry(cmd=b"x"))
    assert q.fill() == 1.0  # clamped even past capacity refusals

    rq = ReadIndexQueue(2)
    assert rq.fill() == 0.0
    rq.add(RequestState())
    assert rq.fill() == pytest.approx(0.5)


def test_vector_inbox_occupancy_signal_is_live():
    """Regression: the pack-time inbox-row count must be captured BEFORE
    _flush_staged_rows clears the staging columns (a post-flush read is
    always zero and silently kills the engine_inbox saturation signal).
    Under sustained load the vector engine must report occupancy > 0."""
    reg = _Registry()
    nh = mk_host("a:1", reg, "vector")
    try:
        nh.start_cluster({1: "a:1"}, False, KVSM, group_config(100, 1))
        assert wait_for(lambda: nh.get_leader_id(100)[1], timeout=60)
        s = nh.get_noop_session(100)
        stop = threading.Event()

        def load():
            i = 0
            while not stop.is_set():
                try:
                    nh.propose_batch(
                        s, [b"k%d=v" % (i + j) for j in range(16)], 5.0
                    )
                except Exception:
                    pass
                i += 16

        th = threading.Thread(target=load, daemon=True)
        th.start()
        try:
            seen = 0.0
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and seen == 0.0:
                seen = max(
                    seen, nh.engine.pressure_stats()["inbox_occupancy"]
                )
                time.sleep(0.0005)
        finally:
            stop.set()
            th.join(timeout=5)
        assert seen > 0.0, "inbox occupancy never observed under load"
    finally:
        nh.stop()
