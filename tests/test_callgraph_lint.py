"""Meta-tests for the interprocedural analysis layer (ISSUE 20).

Covers, in order:
  * call-graph resolution unit tests (self/cls methods, module functions,
    imports, nested closures -> deferred edges, dynamic calls -> no edge,
    never a crash);
  * one known-bad snippet per new rule family, with the matching
    "the PR 5 lexical rules provably miss this" assertion;
  * allowed-idiom negatives (a `_locked` callee under the right lock,
    taint killed by `.shape`/`len()`, the CV-wait exemption, blessed
    seams);
  * the nested-closure lock regression (deferred edges: created under a
    `with` is neither "held" for ordering nor an excuse for a naked
    `_locked` call);
  * pragma/unused semantics incl. the config-gate allowlist escape;
  * CLI: `--changed` against a real temp git repo, `--baseline`
    round-trip, and the `--json` schema pin (rule_version included);
  * the longhaul preflight fragment (via the memoized check hook).

Everything runs the real `build_analyzer()` rule set through
`Analyzer.run_sources`, so these tests break when resolution or rule
semantics drift — that is their job.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from dragonboat_tpu.analysis import (
    ALL_RULES,
    DEFAULT_TARGETS,
    RULES_VERSION,
    build_analyzer,
    unsuppressed,
)
from dragonboat_tpu.analysis.callgraph import CallGraph, Program
from dragonboat_tpu.analysis.engine import Analyzer, CrossRule, SourceModule

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _xrun(sources):
    """Full rule set (lexical + interprocedural) over in-memory sources."""
    return build_analyzer().run_sources(dict(sources))


def _ids(findings, family=None):
    ids = sorted({f.rule for f in unsuppressed(findings)})
    if family is not None:
        ids = [i for i in ids if i.startswith(family)]
    return ids


def _lexical_only(sources):
    """What the PR 5 per-function rules see — the miss-proof baseline."""
    rules = [r for r in ALL_RULES if not isinstance(r, CrossRule)]
    analyzer = Analyzer(rules, DEFAULT_TARGETS)
    out = []
    for rel, src in sources.items():
        out.extend(analyzer.run_snippet(src, rel))
    return out


def _graph(sources) -> CallGraph:
    mods = [
        SourceModule.from_snippet(src, rel) for rel, src in sources.items()
    ]
    return Program(mods, DEFAULT_TARGETS).graph


# ---------------------------------------------------------------- call graph


def test_callgraph_resolves_self_method_and_module_function():
    g = _graph({
        "m.py": """
            def helper():
                pass

            class C:
                def a(self):
                    self.b()
                    helper()
                def b(self):
                    pass
            """,
    })
    callees = {s.callee[1] for s in g.callees(("m.py", "C.a"))}
    assert callees == {"C.b", "helper"}
    assert [s.caller[1] for s in g.callers(("m.py", "C.b"))] == ["C.a"]


def test_callgraph_resolves_method_through_base_class():
    g = _graph({
        "m.py": """
            class Base:
                def tick(self):
                    pass

            class Sub(Base):
                def run(self):
                    self.tick()
            """,
    })
    assert {s.callee for s in g.callees(("m.py", "Sub.run"))} == {
        ("m.py", "Base.tick")
    }


def test_callgraph_resolves_package_relative_import():
    g = _graph({
        "ops/kernel.py": """
            from .state import fold

            def step(s):
                return fold(s)
            """,
        "ops/state.py": """
            def fold(s):
                return s
            """,
    })
    assert {s.callee for s in g.callees(("ops/kernel.py", "step"))} == {
        ("ops/state.py", "fold")
    }


def test_callgraph_nested_def_gets_deferred_edge_and_call_edge():
    g = _graph({
        "m.py": """
            class C:
                def outer(self):
                    def inner():
                        pass
                    inner()
            """,
    })
    edges = g.out_edges[("m.py", "C.outer")]
    kinds = {(s.callee[1], s.deferred) for s in edges}
    # one DEFERRED edge (the def itself: runs later, lock-free) and one
    # normal edge (the direct invocation)
    assert kinds == {("C.outer.inner", True), ("C.outer.inner", False)}


def test_callgraph_dynamic_calls_degrade_to_no_edge():
    g = _graph({
        "m.py": """
            class C:
                def run(self, cb, items):
                    cb()                      # unknown callable
                    getattr(self, "x")()      # dynamic dispatch
                    items[0].go()             # unknown receiver type
                    (lambda: self.boom())()   # lambda body not entered
                def boom(self):
                    pass
            """,
    })
    assert g.callees(("m.py", "C.run")) == []


def test_callgraph_records_held_locks_at_call_sites():
    g = _graph({
        "nodehost.py": """
            class NodeHost:
                def a(self):
                    with self._nodes_mu:
                        self.b()
                def b(self):
                    pass
            """,
    })
    (site,) = g.callees(("nodehost.py", "NodeHost.a"))
    assert [(h.root, h.attr) for h in site.held] == [("self", "_nodes_mu")]
    assert site.held[0].spec is not None
    assert site.held[0].spec.cls == "NodeHost"


def test_caller_modules_of_reports_cross_module_callers():
    g = _graph({
        "a.py": "def f():\n    pass\n",
        "b.py": "from .a import f\n\ndef g():\n    f()\n",
    })
    assert g.caller_modules_of({"a.py"}) == {"b.py"}


# ----------------------------------------------------- locks/cross-function


_INVERSION = {
    "engine/node.py": """
        class Node:
            def api(self):
                with self._mu:
                    self._lookup()
            def _lookup(self):
                with self._nodes_mu:
                    pass
        """,
}


def test_cross_function_lock_inversion_is_caught():
    findings = [
        f
        for f in unsuppressed(_xrun(_INVERSION))
        if f.rule == "locks/cross-function-order"
    ]
    assert len(findings) == 1, findings
    msg = findings[0].message
    assert "Node._mu (rank 41)" in msg
    assert "NodeHost._nodes_mu (rank 38)" in msg
    assert "Node._lookup" in msg  # the witness chain


def test_cross_function_lock_inversion_missed_by_lexical_rules():
    assert _ids(_lexical_only(_INVERSION), "locks") == []


def test_cross_function_order_two_frames_down():
    findings = _xrun({
        "engine/node.py": """
            class Node:
                def api(self):
                    with self._mu:
                        self._mid()
                def _mid(self):
                    self._deep()
                def _deep(self):
                    with self._nodes_mu:
                        pass
            """,
    })
    msgs = [
        f.message
        for f in unsuppressed(findings)
        if f.rule == "locks/cross-function-order"
    ]
    assert any("Node._mid -> Node._deep" in m for m in msgs), msgs


def test_cross_function_order_inner_rank_is_clean():
    findings = _xrun({
        "engine/node.py": """
            class Node:
                def api(self):
                    with self._nodes_mu:
                        self._lookup()
                def _lookup(self):
                    with self._mu:
                        pass
            """,
    })
    # 38 held, 41 acquired: acquisition goes DOWN the table — legal
    assert _ids(findings, "locks/cross-function-order") == []


def test_same_lock_reacquired_through_chain_is_flagged():
    findings = _xrun({
        "engine/node.py": """
            class Node:
                def api(self):
                    with self._mu:
                        self._again()
                def _again(self):
                    with self._mu:
                        pass
            """,
    })
    msgs = [
        f.message
        for f in unsuppressed(findings)
        if f.rule == "locks/cross-function-order"
    ]
    assert any("same lock reacquired" in m for m in msgs), msgs


# ------------------------------------------------- locks/locked-callee-unheld


def test_locked_callee_without_lock_is_flagged():
    findings = _xrun({
        "transport/chunks.py": """
            class Chunks:
                def sweep(self):
                    self._expire_locked()
                def _expire_locked(self):
                    pass
            """,
    })
    assert _ids(findings, "locks/locked-callee-unheld") == [
        "locks/locked-callee-unheld"
    ]


def test_locked_callee_under_declared_lock_is_clean():
    findings = _xrun({
        "transport/chunks.py": """
            class Chunks:
                def sweep(self):
                    with self._mu:
                        self._expire_locked()
                def _expire_locked(self):
                    pass
            """,
    })
    assert _ids(findings, "locks/locked-callee-unheld") == []


def test_locked_callee_from_locked_sibling_is_clean():
    findings = _xrun({
        "transport/chunks.py": """
            class Chunks:
                def _sweep_locked(self):
                    self._expire_locked()
                def _expire_locked(self):
                    pass
            """,
    })
    assert _ids(findings, "locks/locked-callee-unheld") == []


def test_locked_callee_under_auxiliary_receiver_lock_is_clean():
    # Node declares only _mu, but an undeclared one-shot mutex on the
    # SAME receiver (the Node._init_mu recovery pattern) satisfies the
    # caller-holds convention
    findings = _xrun({
        "engine/node.py": """
            class Node:
                def recover(self):
                    with self._init_mu:
                        self._recover_locked()
                def _recover_locked(self):
                    pass
            """,
    })
    assert _ids(findings, "locks/locked-callee-unheld") == []


def test_locked_callee_on_other_receiver_lock_is_flagged():
    # holding YOUR OWN lock does not license a naked call into another
    # object's _locked method
    findings = _xrun({
        "nodehost.py": """
            class NodeHost:
                def sweep(self, node):
                    with self._nodes_mu:
                        node._expire_locked()
            """,
        "engine/node.py": """
            class Node:
                def _expire_locked(self):
                    pass
            """,
    })
    assert _ids(findings, "locks/locked-callee-unheld") == [
        "locks/locked-callee-unheld"
    ]


# ------------------------------------------- locks/blocking-under-hot-lock


_BLOCKING = {
    "engine/vector.py": """
        import os
        import time

        class VectorEngine:
            def tick(self):
                with self._lanes_mu:
                    self._spill()
            def _spill(self):
                self._sync()
            def _sync(self):
                os.fsync(3)
        """,
}


def test_blocking_reachable_under_hot_lock_is_caught():
    findings = [
        f
        for f in unsuppressed(_xrun(_BLOCKING))
        if f.rule == "locks/blocking-under-hot-lock"
    ]
    assert len(findings) == 1, findings
    assert "fsync()" in findings[0].message
    assert "VectorEngine._spill -> VectorEngine._sync" in findings[0].message


def test_blocking_under_hot_lock_missed_by_lexical_rules():
    assert _ids(_lexical_only(_BLOCKING), "locks") == []


def test_direct_sleep_under_hot_lock_is_caught():
    findings = _xrun({
        "engine/vector.py": """
            import time

            class VectorEngine:
                def tick(self):
                    with self._dirty_mu:
                        time.sleep(0.1)
            """,
    })
    assert _ids(findings, "locks/blocking-under-hot-lock") == [
        "locks/blocking-under-hot-lock"
    ]


def test_cv_wait_on_held_lock_is_exempt():
    # waiting ON the condition you hold is the CV idiom, not a stall bug
    # (and _SendQueue._cv is deliberately not an engine-hot lock)
    findings = _xrun({
        "transport/transport.py": """
            class _SendQueue:
                def get(self):
                    with self._cv:
                        self._cv.wait(1.0)
            """,
    })
    assert _ids(findings, "locks") == []


def test_blocking_under_cold_lock_is_clean():
    findings = _xrun({
        "storage/logdb.py": """
            import os

            class _Shard:
                def flush(self):
                    with self._wmu:
                        os.fsync(3)
            """,
    })
    # _Shard._wmu is the WAL writer lock: fsync under it is its JOB
    assert _ids(findings, "locks/blocking-under-hot-lock") == []


# --------------------------------------------------- nested-def regression


def test_deferred_closure_acquisition_not_treated_as_nested():
    # the closure is CREATED under Node._mu but runs later: its
    # NodeHost._nodes_mu acquisition is not nested inside _mu and must
    # not produce a cross-function-order finding
    findings = _xrun({
        "engine/node.py": """
            class Node:
                def api(self):
                    with self._mu:
                        def later():
                            with self._nodes_mu:
                                pass
                        self.defer = later
            """,
    })
    assert _ids(findings, "locks/cross-function-order") == []


def test_deferred_closure_calling_locked_method_is_flagged():
    # "closure called later, lock not held" made explicit: the closure
    # body's naked _locked call is a finding even though the enclosing
    # function holds the lock at CREATION time
    findings = _xrun({
        "transport/chunks.py": """
            class Chunks:
                def arm(self):
                    with self._mu:
                        def cb():
                            self._expire_locked()
                        self.cb = cb
                def _expire_locked(self):
                    pass
            """,
    })
    assert _ids(findings, "locks/locked-callee-unheld") == [
        "locks/locked-callee-unheld"
    ]


def test_closure_invoked_directly_under_with_keeps_held_set():
    # direct invocation INSIDE the with: the call edge carries the held
    # lock, so the closure's inner acquisition is checked as nested
    findings = _xrun({
        "engine/node.py": """
            class Node:
                def api(self):
                    with self._mu:
                        def inner():
                            with self._nodes_mu:
                                pass
                        inner()
            """,
    })
    assert _ids(findings, "locks/cross-function-order") == [
        "locks/cross-function-order"
    ]


# ------------------------------------------------ retrace/cross-function-taint


_HELPER_BRANCH = {
    "ops/kernel.py": """
        from .state import pick

        def step(state, cfg):
            return pick(state)
        """,
    "ops/state.py": """
        def pick(x):
            if x:
                return 1
            return 0
        """,
}


def test_traced_value_branched_in_helper_is_caught():
    findings = [
        f
        for f in unsuppressed(_xrun(_HELPER_BRANCH))
        if f.rule == "retrace/cross-function-taint"
    ]
    assert len(findings) == 1, findings
    assert findings[0].path == "ops/state.py"
    assert "`x` of pick tainted by step" in findings[0].message


def test_helper_branch_missed_by_lexical_rules():
    assert _ids(_lexical_only(_HELPER_BRANCH), "retrace") == []


def test_taint_killed_by_static_escapes():
    findings = _xrun({
        "ops/kernel.py": """
            from .state import pick

            def step(state, cfg):
                return pick(state)
            """,
        "ops/state.py": """
            def pick(x):
                n = x.shape[0]
                if n > 2:          # shape: a Python int at trace time
                    return 1
                if len(x) > 4:     # len(): same
                    return 2
                return 0
            """,
    })
    assert _ids(findings, "retrace/cross-function-taint") == []


def test_return_taint_flows_back_to_callers():
    # context-insensitive by design: once SOME traced caller taints
    # pick's param, pick's return is tainted for EVERY caller — `other`
    # never passes a traced value itself, and the lexical rules (which
    # conservatively taint any assignment mentioning a traced name)
    # cannot see this at all
    sources = {
        "ops/kernel.py": """
            from .state import pick

            def step(state, cfg):
                return pick(state)
            """,
        "ops/state.py": """
            def pick(x):
                y = x
                return y

            def other(n):
                flag = pick(n)
                while flag:
                    flag = 0
            """,
    }
    msgs = [
        f.message
        for f in unsuppressed(_xrun(sources))
        if f.rule == "retrace/cross-function-taint"
    ]
    assert any("while" in m and "other" in m for m in msgs), msgs
    assert _ids(_lexical_only(sources), "retrace") == []


def test_untraced_caller_does_not_taint_helper():
    # a host-side (untraced) caller passing host values taints nothing —
    # the chain must originate in declared-traced code
    findings = _xrun({
        "nodehost.py": """
            from .util import pick

            class NodeHost:
                def admin(self, req):
                    return pick(req)
            """,
        "util.py": """
            def pick(x):
                if x:
                    return 1
                return 0
            """,
    })
    assert _ids(findings, "retrace/cross-function-taint") == []


def test_shape_derived_args_do_not_leak_taint_through_returns():
    # the _route_segments shape: a traced-module helper CALLED with
    # shape-derived Python ints must not taint its caller's plumbing
    # through its return value (its coarse all-params seeding is a
    # lexical-analysis convention, not real arg taint)
    findings = _xrun({
        "ops/kernel.py": """
            def segments(p, k):
                return [p, k, p + k]

            def route(s, cfg):
                gl, p = s.member.shape
                segs = segments(p, 4)
                parts = []
                for seg in segs:
                    parts.append(seg)
                return parts
            """,
    })
    assert _ids(findings, "retrace/cross-function-taint") == []


# ------------------------------------------------- device-sync/cross-function


_HIDDEN_SYNC = {
    "engine/vector.py": """
        import jax

        class VectorEngine:
            def _decode(self):
                return self._probe()
            def _probe(self):
                return jax.device_get(self._state.term)
        """,
}


def test_device_get_in_helper_reachable_from_hot_is_caught():
    findings = [
        f
        for f in unsuppressed(_xrun(_HIDDEN_SYNC))
        if f.rule == "device-sync/cross-function"
    ]
    assert len(findings) == 1, findings
    assert "VectorEngine._decode -> VectorEngine._probe" in findings[0].message


def test_hidden_sync_missed_by_lexical_rules():
    assert _ids(_lexical_only(_HIDDEN_SYNC), "device-sync") == []


def test_chain_through_blessed_seam_is_clean():
    findings = _xrun({
        "engine/vector.py": """
            import jax

            class VectorEngine:
                def _decode(self):
                    return self._fetch_output()
                def _fetch_output(self):
                    return jax.device_get(self._state)
            """,
    })
    assert _ids(findings, "device-sync/cross-function") == []


def test_item_on_device_root_in_reachable_helper_is_caught():
    findings = _xrun({
        "engine/vector.py": """
            class VectorEngine:
                def _decode(self):
                    return self._one()
                def _one(self):
                    return self._state.term[0].item()
            """,
    })
    assert _ids(findings, "device-sync/cross-function") == [
        "device-sync/cross-function"
    ]


def test_item_outside_hot_modules_is_not_a_device_sync():
    # `self._state` only names the device plane in modules that host hot
    # functions; a Node._state.item() is ordinary host state even when
    # the function is REACHABLE from a hot root
    findings = _xrun({
        "engine/vector.py": """
            from .node import probe

            class VectorEngine:
                def _decode(self):
                    return probe(None)
            """,
        "engine/node.py": """
            def probe(node):
                return node._stat()

            class Node:
                def _stat(self):
                    return self._state.item()
            """,
    })
    assert _ids(findings, "device-sync/cross-function") == []


# ----------------------------------------------------------- pragma/unused


def _overlay_run(tmp_path, files, targets=None, families=None):
    root = tmp_path / "overlay"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    analyzer = build_analyzer(
        families=families,
        targets=targets or DEFAULT_TARGETS,
        root=str(root),
    )
    return analyzer.run(None)


_BAD_WITH_PRAGMA = (
    "import jax\n"
    "\n"
    "class VectorEngine:\n"
    "    def _decode(self):\n"
    "        jax.device_get(self._x)  "
    "# lint: allow(device-sync) one-off probe\n"
)


def test_used_pragma_is_not_reported(tmp_path):
    findings = _overlay_run(
        tmp_path, {"engine/vector.py": _BAD_WITH_PRAGMA}
    )
    assert _ids(findings, "pragma") == []


def test_unused_pragma_is_reported(tmp_path):
    findings = _overlay_run(
        tmp_path,
        {
            "engine/vector.py": (
                "class VectorEngine:\n"
                "    def _decode(self):\n"
                "        return 1  # lint: allow(device-sync) stale\n"
            )
        },
    )
    pragma = [f for f in unsuppressed(findings) if f.rule == "pragma/unused"]
    assert len(pragma) == 1, findings
    assert pragma[0].line == 3


def test_unused_pragma_allowlist_escape(tmp_path):
    import dataclasses

    targets = dataclasses.replace(
        DEFAULT_TARGETS, unused_pragma_allowlist={"device-sync"}
    )
    findings = _overlay_run(
        tmp_path,
        {
            "engine/vector.py": (
                "class VectorEngine:\n"
                "    def _decode(self):\n"
                "        return 1  # lint: allow(device-sync) config-gated\n"
            )
        },
        targets=targets,
    )
    assert _ids(findings, "pragma") == []


def test_unused_pragma_silent_on_family_restricted_runs(tmp_path):
    findings = _overlay_run(
        tmp_path,
        {
            "engine/vector.py": (
                "class VectorEngine:\n"
                "    def _decode(self):\n"
                "        return 1  # lint: allow(device-sync) stale\n"
            )
        },
        families=("locks",),
    )
    assert _ids(findings, "pragma") == []


def test_docstring_mention_of_pragma_syntax_is_not_a_pragma(tmp_path):
    # documentation QUOTING the pragma syntax must neither suppress nor
    # show up as pragma/unused — only real comment tokens count
    findings = _overlay_run(
        tmp_path,
        {
            "engine/vector.py": (
                'HOWTO = """suppress with `# lint: allow(locks) why`"""\n'
                "\n"
                "class VectorEngine:\n"
                "    def _decode(self):\n"
                "        return 1\n"
            )
        },
    )
    assert _ids(findings, "pragma") == []


# ------------------------------------------------------------------- CLI


def _check_cli(*argv, cwd=_REPO):
    return subprocess.run(
        [sys.executable, "-m", "dragonboat_tpu.tools.check", *argv],
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=120,
    )


def _write_overlay(root, files):
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)


_CLEAN = "class VectorEngine:\n    def _decode(self):\n        return 1\n"
_BAD = (
    "import jax\n\n"
    "class VectorEngine:\n"
    "    def _decode(self):\n"
    "        return jax.device_get(self._x)\n"
)


def test_cli_json_schema_is_pinned(tmp_path):
    root = tmp_path / "overlay"
    _write_overlay(root, {"engine/vector.py": _BAD})
    p = _check_cli("--json", "--root", str(root), str(root))
    assert p.returncode == 1, p.stdout + p.stderr
    out = json.loads(p.stdout)
    assert set(out) == {
        "findings",
        "unsuppressed",
        "suppressed",
        "ok",
        "rule_version",
    }
    assert out["rule_version"] == RULES_VERSION
    assert out["ok"] is False and out["unsuppressed"] >= 1
    assert set(out["findings"][0]) == {
        "rule",
        "path",
        "line",
        "message",
        "snippet",
        "suppressed",
        "suppress_reason",
    }


def test_cli_baseline_roundtrip(tmp_path):
    root = tmp_path / "overlay"
    _write_overlay(root, {"engine/vector.py": _BAD})
    snap = _check_cli("--json", "--root", str(root), str(root))
    base = tmp_path / "baseline.json"
    base.write_text(snap.stdout)

    # same tree vs its own snapshot: nothing new -> exit 0
    p = _check_cli(
        "--baseline", str(base), "--root", str(root), str(root)
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "0 new, 0 fixed" in p.stdout

    # add a fresh violation: exactly the NEW one fails
    _write_overlay(
        root,
        {
            "engine/vector.py": _BAD
            + "    def _pack(self):\n"
            + "        return jax.device_get(self._y)\n"
        },
    )
    p = _check_cli(
        "--baseline", str(base), "--root", str(root), str(root)
    )
    assert p.returncode == 1, p.stdout + p.stderr
    assert "_pack" in p.stdout
    assert "_decode" not in p.stdout  # old debt is baseline-excused

    # fix everything: exit 0 and the fixed count is reported
    _write_overlay(root, {"engine/vector.py": _CLEAN})
    p = _check_cli(
        "--baseline", str(base), "--root", str(root), str(root)
    )
    assert p.returncode == 0, p.stdout + p.stderr
    assert "1 fixed" in p.stdout


def _git(root, *args):
    return subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=root,
        capture_output=True,
        text=True,
        check=True,
    )


def test_cli_changed_mode_filters_to_diff_plus_callers(tmp_path):
    root = tmp_path / "overlay"
    _write_overlay(
        root,
        {
            # pre-existing debt in an UNCHANGED file: filtered out
            "engine/vector.py": _BAD,
            # clean helper module, about to change
            "ops/state.py": "def fold(s):\n    return s\n",
            # kernel calls the helper -> caller-module expansion target
            "ops/kernel.py": (
                "from .state import fold\n\n"
                "def step(state, cfg):\n"
                "    return fold(state)\n"
            ),
        },
    )
    _git(root, "init", "-q")
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "seed")

    # change ONLY the helper: give it a branch on its (tainted) param
    (root / "ops/state.py").write_text(
        "def fold(s):\n    if s:\n        return 1\n    return s\n"
    )
    p = _check_cli("--changed", "HEAD", "--root", str(root))
    assert p.returncode == 1, p.stdout + p.stderr
    assert "cross-function-taint" in p.stdout
    # the unchanged file's debt is out of scope for --changed
    assert "device-sync" not in p.stdout
    assert "1 file(s)" in p.stdout and "caller module(s)" in p.stdout

    # against a clean worktree nothing is in scope -> exit 0
    _git(root, "add", "-A")
    _git(root, "commit", "-qm", "helper branch")
    p = _check_cli("--changed", "HEAD", "--root", str(root))
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_changed_outside_git_fails_loudly(tmp_path):
    root = tmp_path / "overlay"
    _write_overlay(root, {"engine/vector.py": _CLEAN})
    env = dict(os.environ, GIT_CEILING_DIRECTORIES=str(tmp_path))
    p = subprocess.run(
        [
            sys.executable,
            "-m",
            "dragonboat_tpu.tools.check",
            "--changed",
            "--root",
            str(root),
        ],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    # tmp overlay is not a git repo (ceiling blocks the crawl upward):
    # exit 2, NOT a clean-looking 0
    assert p.returncode == 2, p.stdout + p.stderr


# -------------------------------------------------------- contract guards


def test_interprocedural_rules_are_registered():
    ids = {r.id for r in ALL_RULES}
    assert {
        "locks/cross-function-order",
        "locks/locked-callee-unheld",
        "locks/blocking-under-hot-lock",
        "retrace/cross-function-taint",
        "device-sync/cross-function",
    } <= ids


def test_cross_rules_never_fire_lexically():
    # the Analyzer routes CrossRules through check_program; their
    # check_function must be inert so family-restricted per-module runs
    # stay sound
    for r in ALL_RULES:
        if isinstance(r, CrossRule):
            assert list(r.check_function(None, DEFAULT_TARGETS)) == []
