"""Bounded smoke profile of the drummer-style long-haul runner.

Tier-1 proves tools.longhaul end to end under a tight budget (the
`-m longhaul` marker; the hours-long profile stays opt-in via
`python -m dragonboat_tpu.tools.longhaul --budget <secs>`):

  * a multi-round mixed-scenario run completes with green verdicts and
    prints per-round seed/verdict lines (the replay contract);
  * an injected failure produces the forensic bundle: flight dump +
    every ring/dump artifact swept from the run directory (incl. a
    planted crash ring — the ISSUE 7 "no manual collection" satellite),
    merged into one timeline, plus a working one-line replay command;
  * the CLI entry point round-trips (exit code, summary lines).
"""
import json
import os
import subprocess
import sys

import pytest

from dragonboat_tpu.tools.longhaul import Options, run_longhaul
from dragonboat_tpu.tools.timeline import merge_dumps, sweep_artifacts

pytestmark = pytest.mark.longhaul

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_longhaul_smoke_multi_round(tmp_path, capsys):
    report = run_longhaul(
        Options(
            budget_s=25.0,
            rounds_max=2,
            round_s=4.0,
            engine="scalar",
            out_dir=str(tmp_path / "run"),
            seed=0xD0C5,
            rotate=True,
            ring=False,  # the pytest session owns the process ring
        )
    )
    assert report["ok"], [r.verdicts for r in report["rounds"]]
    assert len(report["rounds"]) >= 1
    for r in report["rounds"]:
        assert r.ok and r.verdicts["lincheck"]
        assert r.verdicts["fairness_no_stall"]
        assert r.signature  # schedule signature printed per round
    out = capsys.readouterr().out
    assert "round 1 seed=0x" in out and "verdict=OK" in out
    # seed rotation: the two rounds must not share a seed
    if len(report["rounds"]) == 2:
        assert report["rounds"][0].seed != report["rounds"][1].seed


def test_longhaul_failure_bundle_sweeps_rings_and_prints_replay(
    tmp_path, capsys
):
    """Injected failure -> artifact bundle with the swept crash ring
    merged in + a replay command that names the exact seed."""
    from dragonboat_tpu.trace import MmapRing

    out_dir = str(tmp_path / "run")
    seed = 0xF00D
    # plant a crash ring where a SIGKILL'd co-process would have left
    # one: the sweep must pick it up without manual collection
    round_dir = os.path.join(out_dir, f"round-001-seed-0x{seed:X}")
    os.makedirs(round_dir, exist_ok=True)
    ring = MmapRing(os.path.join(round_dir, "crashed.ring"))
    ring.write(
        json.dumps(
            {"t": 1.0, "event": "planted_marker", "cluster": 0}
        ).encode()
    )
    ring.close()
    report = run_longhaul(
        Options(
            budget_s=20.0,
            rounds_max=1,
            round_s=3.0,
            engine="scalar",
            out_dir=out_dir,
            seed=seed,
            ring=False,
            inject_failure=True,
            reuse_out=True,  # the planted ring must survive the guard
            triage=False,  # the triage replay has its own test
        )
    )
    assert not report["ok"]
    r = report["rounds"][0]
    assert r.bundle and os.path.isdir(r.bundle)
    manifest = json.load(open(os.path.join(r.bundle, "manifest.json")))
    assert manifest["verdicts"]["injected_failure"] is False
    assert any(p.endswith("crashed.ring") for p in manifest["swept_artifacts"])
    # the bundle carries the telemetry history ring + doctor diagnosis
    from dragonboat_tpu.profile import read_history

    _meta, samples = read_history(os.path.join(r.bundle, "history.ring"))
    assert samples and all(s["event"] == "history_sample" for s in samples)
    diag = json.load(open(os.path.join(r.bundle, "diagnosis.json")))
    assert diag["schema"] == 1 and diag["samples"] == len(samples)
    kinds = [v["kind"] for v in diag["verdicts"]]
    assert kinds, diag
    assert r.diagnosis == kinds[0]
    assert manifest["doctor_verdict"] == kinds[0]
    merged = os.path.join(r.bundle, "merged_timeline.jsonl")
    events = [json.loads(ln) for ln in open(merged)]
    assert any(e.get("event") == "planted_marker" for e in events)
    assert any(e.get("event") != "planted_marker" for e in events)
    # the one-line replay command names the failing seed verbatim
    assert f"CHAOS_SEED=0x{seed:X}" in r.replay
    assert f"--seed 0x{seed:X} --rounds 1" in r.replay
    out = capsys.readouterr().out
    assert "replay: CHAOS_SEED=0x" in out and "FAILED" in out


def test_longhaul_out_dir_guard_rotates_stale_runs(tmp_path, capsys):
    """A populated --out dir is rotated to <out>.prev before any round
    starts: reusing stale h<N> dirs makes restarted hosts replay old WAL
    state and fail lincheck spuriously (the flake this guard kills)."""
    from dragonboat_tpu.tools.longhaul import _prepare_out_dir

    out = str(tmp_path / "run")
    stale = os.path.join(out, "round-001-seed-0xDEAD", "h1")
    os.makedirs(stale)
    with open(os.path.join(stale, "wal.bin"), "w") as f:
        f.write("stale")
    # unit: non-empty dir rotates (replacing an older .prev), empty and
    # reuse=True do not
    assert _prepare_out_dir(out) is True
    assert os.listdir(out) == []
    assert os.path.isdir(os.path.join(out + ".prev", "round-001-seed-0xDEAD"))
    assert _prepare_out_dir(out) is False  # now empty: no rotation
    assert _prepare_out_dir(out + ".prev", reuse=True) is False
    assert os.path.exists(os.path.join(stale.replace(out, out + ".prev"),
                                       "wal.bin"))
    # runner level: a zero-budget run over a dirty dir stamps the
    # rotation in the header and the report
    os.makedirs(os.path.join(out, "leftover"))
    report = run_longhaul(
        Options(budget_s=0.0, out_dir=out, seed=1, ring=False)
    )
    assert report["out_dir_rotated"] is True
    assert not os.path.exists(os.path.join(out, "leftover"))
    assert os.path.isdir(os.path.join(out + ".prev", "leftover"))
    assert "(rotated stale run to .prev)" in capsys.readouterr().out


def test_longhaul_triage_tags_injected_failure_deterministic(tmp_path):
    """Failure triage: an injected failure is a new signature, gets ONE
    same-seed replay in a fresh `-triage` dir, and — since the replay
    fails the same verdict — lands in triage.json as DETERMINISTIC."""
    out = str(tmp_path / "run")
    report = run_longhaul(
        Options(
            budget_s=30.0,
            rounds_max=1,
            round_s=3.0,
            engine="scalar",
            out_dir=out,
            seed=0xABC,
            ring=False,
            inject_failure=True,
        )
    )
    assert not report["ok"]
    assert len(report["triage"]) == 1
    entry = report["triage"][0]
    assert entry["tag"] == "DETERMINISTIC"
    assert "injected_failure" in entry["verdicts"]
    assert entry["rounds"] == [1] and entry["seed"] == "0xABC"
    assert report["rounds"][0].triage == "DETERMINISTIC"
    # the replay ran in its own dir (stale h<N> reuse is poison)
    assert os.path.isdir(os.path.join(out, "round-001-seed-0xABC-triage"))
    ledger = json.load(open(report["triage_path"]))
    assert ledger["schema"] == 1
    assert [e["signature"] for e in ledger["entries"]] == [entry["signature"]]


def test_longhaul_triage_signature_dedupes_repeat_failures(tmp_path):
    """Ledger mechanics without running rounds: equal (failed-verdicts,
    diagnosis) pairs share a signature and later rounds join the entry
    with NO extra replay; different pairs get distinct signatures."""
    from dragonboat_tpu.tools.longhaul import (
        RoundResult, _triage_round, _triage_signature,
    )

    a1 = RoundResult(1, 7, verdicts={"lincheck": False, "x": True},
                     diagnosis="wal_fsync_stall")
    a2 = RoundResult(5, 9, verdicts={"lincheck": False, "x": True},
                     diagnosis="wal_fsync_stall")
    b = RoundResult(2, 7, verdicts={"lincheck": False, "x": False},
                    diagnosis="wal_fsync_stall")
    c = RoundResult(3, 7, verdicts={"lincheck": False, "x": True},
                    diagnosis="election_churn")
    assert _triage_signature(a1) == _triage_signature(a2)
    assert len({_triage_signature(r) for r in (a1, b, c)}) == 3
    ledger = {
        _triage_signature(a1): {
            "signature": _triage_signature(a1),
            "verdicts": ["lincheck"], "diagnosis": "wal_fsync_stall",
            "rounds": [1], "seed": "0x7", "tag": "LOAD_SENSITIVE",
        }
    }
    # a known signature joins the entry; no _Round replay fires (it
    # would blow up on this bogus Options out dir if it did)
    _triage_round(a2, 9, Options(out_dir=str(tmp_path / "nope")), ledger)
    entry = ledger[_triage_signature(a2)]
    assert entry["rounds"] == [1, 5]
    assert a2.triage == "LOAD_SENSITIVE"


def test_timeline_sweep_flag_merges_run_dir(tmp_path):
    """`tools.timeline --sweep DIR` replaces manual artifact listing."""
    d = str(tmp_path)
    with open(os.path.join(d, "a.jsonl"), "w") as f:
        f.write(json.dumps({"t": 1.0, "event": "x", "cluster": 0}) + "\n")
    sub = os.path.join(d, "nested")
    os.makedirs(sub)
    with open(os.path.join(sub, "b.jsonl"), "w") as f:
        f.write(json.dumps({"t": 2.0, "event": "y", "cluster": 0}) + "\n")
    swept = sweep_artifacts(d)
    assert [os.path.basename(p) for p in swept] == ["a.jsonl", "b.jsonl"]
    assert [e["event"] for e in merge_dumps(swept)] == ["x", "y"]
    p = subprocess.run(
        [
            sys.executable, "-m", "dragonboat_tpu.tools.timeline",
            "--sweep", d, "--json",
        ],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert p.returncode == 0, p.stderr
    events = [json.loads(ln) for ln in p.stdout.splitlines()]
    assert [e["event"] for e in events] == ["x", "y"]

def test_longhaul_sharded_multistep_smoke(tmp_path, capsys):
    """The smoke rotation covers the sharded K-step engine: one round on
    shard_over_mesh + steps_per_sync=4 (the composition ISSUE 16 lifts
    the ValueError on) runs the usual chaos schedule to green verdicts —
    partition/drop force lanes onto the host-fallback path while the
    healthy co-hosted lanes keep riding the on-device router."""
    report = run_longhaul(
        Options(
            budget_s=90.0,
            rounds_max=1,
            round_s=3.0,
            engine="vector",
            out_dir=str(tmp_path / "run"),
            seed=0xD0C5,
            ring=False,
            scenarios=("partition", "drop", "none"),
            steps_per_sync=4,
            shard_over_mesh=True,
        )
    )
    assert report["ok"], [r.verdicts for r in report["rounds"]]
    r = report["rounds"][0]
    assert r.ok and r.verdicts["lincheck"]
    out = capsys.readouterr().out
    assert "verdict=OK" in out


def test_longhaul_replay_cmd_reproduces_engine_composition(tmp_path):
    """A sharded K-step failure must replay on the sharded K-step
    engine: the one-line replay command carries the composition flags."""
    from dragonboat_tpu.tools.longhaul import _Round

    opts = Options(
        out_dir=str(tmp_path / "run"), steps_per_sync=4,
        shard_over_mesh=True,
    )
    cmd = _Round(1, 0xBEEF, opts)._replay_cmd()
    assert "--steps-per-sync 4" in cmd
    assert "--shard-over-mesh" in cmd
    # the default composition stays flag-free (legacy replay lines keep
    # working)
    cmd = _Round(2, 0xBEEF, Options(out_dir=str(tmp_path / "run")))._replay_cmd()
    assert "--steps-per-sync" not in cmd
    assert "--shard-over-mesh" not in cmd


def test_longhaul_same_seed_round_signature_is_bit_identical(tmp_path):
    """The replay contract at the RUNNER level: two same-seeded rounds
    print the same orchestration-schedule signature even though wire/
    fsync draw counts follow traffic timing (they are excluded from the
    digest, see _ORCH_SITES), and execute the same scenario sequence."""
    runs = []
    for i in (1, 2):
        report = run_longhaul(
            Options(
                budget_s=20.0,
                rounds_max=1,
                round_s=3.0,
                engine="scalar",
                out_dir=str(tmp_path / f"run{i}"),
                seed=0x516,
                ring=False,
            )
        )
        assert report["ok"], [r.verdicts for r in report["rounds"]]
        runs.append(report["rounds"][0])
    assert runs[0].signature == runs[1].signature
    assert runs[0].scenarios == runs[1].scenarios


@pytest.mark.chaos
def test_longhaul_lease_clock_chaos_round_replays_bit_identical(tmp_path):
    """ISSUE 17: the `lease_clock_chaos` scenario (seeded skew/drift/
    jump windows on live hosts' tick clocks while lease-read traffic
    runs) passes its verdicts in a single seeded round, and the SAME
    seed replays to the SAME orchestration-schedule signature — clock
    faults ride the FaultPlane decision streams like crashes do."""
    runs = []
    for i in (1, 2):
        report = run_longhaul(
            Options(
                budget_s=30.0,
                rounds_max=1,
                round_s=4.0,
                engine="scalar",
                out_dir=str(tmp_path / f"run{i}"),
                seed=0x2B1,
                ring=False,
                scenarios=("lease_clock_chaos",),
            )
        )
        assert report["ok"], [r.verdicts for r in report["rounds"]]
        r = report["rounds"][0]
        assert r.verdicts["lincheck"]
        assert r.verdicts["fairness_no_stall"]
        # the lease verdicts are present whenever fault windows ran, and
        # a round that injected skew past the margin must show FALLBACK
        # (reads served via ReadIndex), never a lincheck violation
        if "lease_reads_linearizable" in r.verdicts:
            assert r.verdicts["lease_reads_linearizable"]
        if "lease_fallback_served" in r.verdicts:
            assert r.verdicts["lease_fallback_served"]
        runs.append(r)
    assert runs[0].signature == runs[1].signature
    assert runs[0].scenarios == runs[1].scenarios


def test_longhaul_preflight_verdict_recorded_in_report(tmp_path, capsys):
    """Every run report pins WHICH static-analysis gate the tree passed
    (findings count + rule version), and the header says so (ISSUE 20:
    tools.check is the pre-merge bar, longhaul refuses dirty trees)."""
    report = run_longhaul(
        Options(budget_s=0.0, out_dir=str(tmp_path / "run"), seed=1, ring=False)
    )
    check = report["check"]
    assert check["ok"] is True
    assert check["findings"] == 0
    assert check["rule_version"].startswith("2.")
    assert "skipped" not in check
    out = capsys.readouterr().out
    assert "preflight tools.check:" in out
    assert "-> OK" in out


def test_longhaul_preflight_failure_refuses_to_start(
    tmp_path, capsys, monkeypatch
):
    from dragonboat_tpu.tools import longhaul as lh

    monkeypatch.setattr(
        lh,
        "_preflight_check",
        lambda: {
            "ok": False,
            "findings": 2,
            "suppressed": 0,
            "rule_version": "2.0",
            "first": ["engine/vector.py:1: [device-sync/device-get] boom"],
        },
    )
    report = run_longhaul(
        Options(
            budget_s=30.0,
            rounds_max=1,
            round_s=2.0,
            out_dir=str(tmp_path / "run"),
            seed=1,
            ring=False,
        )
    )
    assert report["ok"] is False
    assert report["rounds"] == []  # zero rounds ran on a dirty tree
    assert report["check"]["findings"] == 2
    out = capsys.readouterr().out
    assert "-> FAIL" in out
    assert "refusing to start" in out
    assert "[device-sync/device-get]" in out


def test_longhaul_no_preflight_skips_the_gate(tmp_path):
    report = run_longhaul(
        Options(
            budget_s=0.0,
            out_dir=str(tmp_path / "run"),
            seed=1,
            ring=False,
            preflight=False,
        )
    )
    assert report["check"] == {"ok": True, "skipped": True}
