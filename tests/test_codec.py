"""Round-trip tests for the binary wire codec (the raftpb-equivalent layer).

Mirrors the reference's marshal/unmarshal round-trip fuzzing
(raftpb/fuzz.go:15-49) with deterministic randomized cases.
"""
import random

from dragonboat_tpu import codec
from dragonboat_tpu.types import (
    Bootstrap,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotChunk,
    SnapshotFile,
    State,
)

rng = random.Random(42)


def rand_entry():
    return Entry(
        type=rng.choice(list(EntryType)),
        term=rng.randrange(2**40),
        index=rng.randrange(2**40),
        key=rng.randrange(2**60),
        client_id=rng.randrange(2**60),
        series_id=rng.randrange(2**64),
        responded_to=rng.randrange(2**30),
        cmd=bytes(rng.randrange(256) for _ in range(rng.randrange(64))),
    )


def rand_membership():
    return Membership(
        config_change_id=rng.randrange(2**40),
        addresses={i: f"host{i}:90{i:02d}" for i in range(rng.randrange(5))},
        observers={9: "obs:9001"} if rng.random() < 0.5 else {},
        witnesses={8: "wit:9002"} if rng.random() < 0.5 else {},
        removed={7: True} if rng.random() < 0.5 else {},
    )


def rand_snapshot():
    return Snapshot(
        filepath="/tmp/snap/0001.gbsnap",
        file_size=rng.randrange(2**30),
        index=rng.randrange(2**30),
        term=rng.randrange(2**20),
        membership=rand_membership() if rng.random() < 0.7 else None,
        files=[
            SnapshotFile(
                filepath="/x/f1", file_size=10, file_id=1, metadata=b"m1"
            )
        ]
        if rng.random() < 0.5
        else [],
        checksum=b"\x01\x02",
        dummy=rng.random() < 0.2,
        cluster_id=rng.randrange(2**20),
        on_disk_index=rng.randrange(2**20),
        witness=rng.random() < 0.1,
    )


def test_entry_roundtrip():
    for _ in range(50):
        e = rand_entry()
        buf = codec.encode_entry(e)
        got, off = codec.decode_entry(buf)
        assert off == len(buf)
        assert got == e


def test_entries_roundtrip():
    ents = [rand_entry() for _ in range(17)]
    buf = codec.encode_entries(ents)
    got, off = codec.decode_entries(buf)
    assert off == len(buf)
    assert got == ents


def test_state_roundtrip():
    st = State(term=5, vote=2, commit=99)
    got, _ = codec.decode_state(codec.encode_state(st))
    assert got == st


def test_membership_roundtrip():
    for _ in range(20):
        m = rand_membership()
        got, off = codec.decode_membership(codec.encode_membership(m))
        assert got == m


def test_snapshot_roundtrip():
    for _ in range(20):
        ss = rand_snapshot()
        buf = codec.encode_snapshot(ss)
        got, off = codec.decode_snapshot(buf)
        assert off == len(buf)
        assert got == ss


def test_message_roundtrip():
    for _ in range(50):
        m = Message(
            type=rng.choice(list(MessageType)),
            to=rng.randrange(2**30),
            from_=rng.randrange(2**30),
            cluster_id=rng.randrange(2**40),
            term=rng.randrange(2**30),
            log_term=rng.randrange(2**30),
            log_index=rng.randrange(2**30),
            commit=rng.randrange(2**30),
            reject=rng.random() < 0.5,
            hint=rng.randrange(2**60),
            hint_high=rng.randrange(2**60),
            entries=[rand_entry() for _ in range(rng.randrange(4))],
            snapshot=rand_snapshot() if rng.random() < 0.3 else None,
        )
        buf = codec.encode_message(m)
        got, off = codec.decode_message(buf)
        assert off == len(buf)
        assert got == m


def test_message_batch_roundtrip():
    b = MessageBatch(
        requests=[
            Message(type=MessageType.REPLICATE, cluster_id=7, entries=[rand_entry()])
        ],
        deployment_id=123,
        source_address="a.b.c:1234",
        bin_ver=1,
    )
    got, off = codec.decode_message_batch(codec.encode_message_batch(b))
    assert got == b


def test_chunk_roundtrip():
    c = SnapshotChunk(
        cluster_id=1,
        node_id=2,
        from_=3,
        chunk_id=4,
        chunk_size=5,
        chunk_count=6,
        data=b"hello world",
        index=7,
        term=8,
        filepath="/a/b",
        file_size=9,
        deployment_id=10,
        file_chunk_id=11,
        file_chunk_count=12,
        has_file_info=True,
        file_info=SnapshotFile(filepath="/f", file_size=1, file_id=2, metadata=b"z"),
        membership=rand_membership(),
        on_disk_index=13,
        witness=False,
    )
    got, off = codec.decode_chunk(codec.encode_chunk(c))
    assert got == c


def test_bootstrap_roundtrip():
    b = Bootstrap(addresses={1: "a:1", 2: "b:2"}, join=True, type=1)
    got, _ = codec.decode_bootstrap(codec.encode_bootstrap(b))
    assert got == b
