"""Observability plane tests: latency histograms + Prometheus exposition
conformance, the flight recorder, reservoir sampling, the narrowed event
aggregator, and the end-to-end proposal-lifecycle instrumentation.

The exposition conformance test (minimal text-format parser) is the
regression net for the `_bucket`/`_sum`/`_count` contract: no duplicate
`# TYPE` lines, sorted label keys, monotone cumulative buckets, and a
`+Inf` bucket equal to `_count`.
"""
import io
import json
import os
import re
import time

import pytest

from dragonboat_tpu.events import (
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
    MetricsRegistry,
    RaftEventAggregator,
)
from dragonboat_tpu.trace import (
    FlightRecorder,
    LatencySampler,
    Sample,
    flight_recorder,
)


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------


def test_histogram_observe_and_quantile():
    h = Histogram()
    for v in (0.001, 0.002, 0.004, 0.008, 0.016):
        h.observe(v)
    assert h.count == 5
    assert abs(h.sum - 0.031) < 1e-9
    q50 = h.quantile(0.5)
    q99 = h.quantile(0.99)
    assert 0.001 <= q50 <= 0.008
    assert q50 <= q99 <= 0.032
    # values beyond the last bound land in the +Inf overflow bucket and
    # quantiles saturate at the last finite bound
    h2 = Histogram()
    h2.observe(10_000.0)
    assert h2.quantile(0.99) == DEFAULT_LATENCY_BUCKETS[-1]
    assert Histogram().quantile(0.5) == 0.0


def test_histogram_merge():
    a, b = Histogram(), Histogram()
    for v in (0.001, 0.002):
        a.observe(v)
    for v in (0.004, 0.008):
        b.observe(v)
    a.merge(b)
    assert a.count == 4
    assert abs(a.sum - 0.015) < 1e-9
    with pytest.raises(ValueError):
        a.merge(Histogram(bounds=(1.0, 2.0)))


# ---------------------------------------------------------------------------
# Prometheus exposition conformance (satellite: minimal text-format parser)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? (\S+)$")


def _parse_exposition(text):
    """Minimal Prometheus text-format parser: returns (types, samples)
    where samples are (name, labels_dict, value, raw_label_keys)."""
    types = {}
    samples = []
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, rest = ln.partition("# TYPE ")
            name, kind = rest.rsplit(" ", 1)
            assert name not in types, f"duplicate # TYPE line for {name}"
            types[name] = kind
            continue
        assert not ln.startswith("#"), f"unexpected comment line: {ln}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparseable sample line: {ln}"
        name, _, labelblock, value = m.groups()
        labels = {}
        keys = []
        if labelblock:
            for part in labelblock.split(","):
                k, _, v = part.partition("=")
                assert v.startswith('"') and v.endswith('"'), ln
                labels[k] = v.strip('"')
                keys.append(k)
        samples.append((name, labels, value, keys))
    return types, samples


def _populated_registry():
    m = MetricsRegistry()
    m.inc("raftnode_campaign_launched_total", (1, 2), 3)
    m.set_gauge("raftnode_term", (1, 2), 7)
    m.set_gauge("raftnode_term", (2, 1), 9)
    for v in (0.0001, 0.001, 0.01, 0.1, 1.5, 500.0):
        m.observe("proposal_commit_latency_seconds", (1, 2), v)
    for v in (0.002, 0.004):
        m.observe("fsync_latency_seconds", (0, 0), v)
    return m


def test_exposition_conformance():
    m = _populated_registry()
    out = io.StringIO()
    m.write(out)
    types, samples = _parse_exposition(out.getvalue())
    # every sample's family has exactly one TYPE line
    fams = set(types)
    for name, labels, value, keys in samples:
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in fams or base in fams, f"sample {name} missing # TYPE"
        # sorted label keys
        assert keys == sorted(keys), f"unsorted label keys in {name}{keys}"
    # histogram contract per label set
    hist = "dragonboat_tpu_proposal_commit_latency_seconds"
    assert types[hist] == "histogram"
    buckets = [
        (float("inf") if lb["le"] == "+Inf" else float(lb["le"]), float(v))
        for n, lb, v, _ in samples
        if n == hist + "_bucket"
    ]
    assert buckets == sorted(buckets), "buckets not in increasing le order"
    counts = [c for _, c in buckets]
    assert counts == sorted(counts), "cumulative bucket counts not monotone"
    count_v = [float(v) for n, _, v, _ in samples if n == hist + "_count"]
    sum_v = [float(v) for n, _, v, _ in samples if n == hist + "_sum"]
    assert len(count_v) == 1 and len(sum_v) == 1
    assert buckets[-1][0] == float("inf")
    assert buckets[-1][1] == count_v[0], "+Inf bucket != _count"
    assert count_v[0] == 6
    assert abs(sum_v[0] - 501.6111) < 1e-3


# ---------------------------------------------------------------------------
# reservoir Sample (satellite: long-run percentile bias fix)
# ---------------------------------------------------------------------------


def test_sample_reservoir_covers_whole_run():
    s = Sample("bias", cap=1000)
    n = 50_000
    for v in range(n):
        s.record(float(v))
    assert len(s) == n
    # the old fill-then-freeze cap kept only the first 1000 values, so the
    # p50 estimate would be ~500; reservoir sampling keeps it near n/2
    p50 = s.percentile(0.5)
    assert 0.4 * n < p50 < 0.6 * n, p50
    assert abs(s.mean() - (n - 1) / 2) < 1.0  # exact running mean


def test_sample_reservoir_deterministic():
    def run():
        s = Sample("det", cap=100)
        for v in range(10_000):
            s.record(float(v))
        return s.percentile(0.5), s.percentile(0.99)

    assert run() == run()


def test_latency_sampler_ratio():
    s = LatencySampler(4)
    got = sum(1 for _ in range(64) if s.sample())
    assert got == 16
    assert all(LatencySampler(1).sample() for _ in range(5))


# ---------------------------------------------------------------------------
# event aggregator __getattr__ narrowing (satellite)
# ---------------------------------------------------------------------------


def test_aggregator_optional_callbacks_are_noops():
    agg = RaftEventAggregator(MetricsRegistry())
    assert agg.membership_changed(1, 2) is None
    assert agg.connection_established("a", False) is None
    agg.stop()


def test_aggregator_rejects_typod_callbacks():
    agg = RaftEventAggregator(MetricsRegistry())
    try:
        with pytest.raises(AttributeError):
            agg.leader_updatd  # typo'd name must not resolve to a noop
        assert not hasattr(agg, "campaign_lunched")
        assert hasattr(agg, "campaign_launched")
        assert hasattr(agg, "membership_changed")
    finally:
        agg.stop()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_jsonl():
    rec = FlightRecorder(capacity=4)
    for i in range(6):
        rec.record("evt", i=i)
    assert len(rec) == 4  # bounded: oldest overwritten
    dump = rec.dump()
    assert [d["i"] for d in dump] == [2, 3, 4, 5]
    assert all("t" in d and d["event"] == "evt" for d in dump)
    ts = [d["t"] for d in dump]
    assert ts == sorted(ts)
    lines = rec.to_jsonl().splitlines()
    assert len(lines) == 4
    for ln in lines:
        json.loads(ln)  # every line parses as JSON
    rec.reset()
    assert len(rec) == 0 and rec.to_jsonl() == ""


def test_global_recorder_collects_fault_and_leader_events():
    from dragonboat_tpu.faults import FaultPlane, FaultSpec

    rec = flight_recorder()
    rec.reset()
    fp = FaultPlane(1234, FaultSpec(drop=1.0))
    assert fp.decide("wire:test", "drop", fp.spec.drop)
    agg = RaftEventAggregator(MetricsRegistry())
    agg.leader_updated(7, 1, 2, 3)
    agg.stop()
    events = {d["event"] for d in rec.dump()}
    assert "fault_injected" in events
    assert "leader_changed" in events
    by_kind = {d["event"]: d for d in rec.dump()}
    assert by_kind["fault_injected"]["site"] == "wire:test"
    assert by_kind["fault_injected"]["seed"] == 1234
    assert by_kind["leader_changed"]["cluster"] == 7
    assert by_kind["leader_changed"]["term"] == 3
    rec.reset()


def test_request_state_on_complete_chains():
    """The latency sampler registers on_complete on sampled reads BEFORE
    the caller sees the RequestState; a second (user/ABI) registration
    must chain, not replace — both callbacks fire exactly once, in
    registration order."""
    from dragonboat_tpu.requests import (
        REQUEST_COMPLETED,
        RequestResult,
        RequestState,
    )

    rs = RequestState()
    got = []
    rs.on_complete(lambda r: got.append(1))
    rs.on_complete(lambda r: got.append(2))
    rs.notify(RequestResult(code=REQUEST_COMPLETED))
    assert got == [1, 2]
    rs.on_complete(lambda r: got.append(3))  # late: fires immediately
    assert got == [1, 2, 3]


def test_faultykv_observer_measures_injected_stall():
    """fsync_latency must reflect the EFFECTIVE barrier including chaos
    stalls — the wrapper times (fault + inner sync), so a stall window
    shows up as the histogram spike the README's worked example promises."""
    from dragonboat_tpu.faults import FaultPlane, FaultSpec
    from dragonboat_tpu.storage.kv import MemKV, WriteBatch

    fp = FaultPlane(5, FaultSpec(fsync_stall=1.0, fsync_stall_s=(0.05, 0.05)))
    kv = fp.wrap_kv(MemKV(), "fs")
    seen = []
    kv.set_fsync_observer(seen.append)
    wb = WriteBatch()
    wb.put(b"k", b"v")
    kv.commit_write_batch(wb)
    kv.sync()
    assert len(seen) == 2
    assert all(dt >= 0.045 for dt in seen), seen


def test_breaker_and_sendq_record_transitions():
    from dragonboat_tpu.transport.transport import _Breaker, _SendQueue
    from dragonboat_tpu.types import Message, MessageType

    rec = flight_recorder()
    rec.reset()
    b = _Breaker(name="peer:1")
    b.fail()
    b.success()
    sq = _SendQueue(maxlen=1, name="peer:1")
    assert sq.try_put(Message(type=MessageType.REPLICATE, to=1, from_=2))
    # queue full of bulk: an urgent arrival evicts the oldest bulk
    assert sq.try_put(Message(type=MessageType.HEARTBEAT, to=1, from_=2))
    events = [d["event"] for d in rec.dump()]
    assert "breaker_open" in events
    assert "breaker_closed" in events
    assert "sendq_evicted_bulk" in events
    rec.reset()


# ---------------------------------------------------------------------------
# end-to-end: proposal lifecycle histograms + step stats + exposition
# ---------------------------------------------------------------------------


@pytest.fixture
def single_host(tmp_path):
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
    from tests.test_nodehost import KVSM

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=5,
            raft_address="obs1:1",
            nodehost_dir=str(tmp_path),  # WAL-backed: real fsync barriers
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            enable_metrics=True,
            engine=EngineConfig(
                kind="vector",
                max_groups=8,
                max_peers=4,
                log_window=64,
                profile_sample_ratio=1,  # sample EVERY request
            ),
        )
    )
    try:
        nh.start_cluster(
            {1: "obs1:1"},
            False,
            lambda c, n: KVSM(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("no leader")
        yield nh
    finally:
        nh.stop()


def test_e2e_latency_histograms_and_step_stats(single_host):
    nh = single_host
    sess = nh.get_noop_session(1)
    for i in range(8):
        nh.sync_propose(sess, f"k{i}=v".encode(), timeout_s=10.0)
    rs = nh.read_index(1, 5.0)
    assert rs.wait(10.0).completed
    m = nh.metrics
    commit = m.histogram("proposal_commit_latency_seconds", (1, 1))
    apply_ = m.histogram("proposal_apply_latency_seconds", (1, 1))
    reads = m.histogram("readindex_latency_seconds", (1, 1))
    assert commit is not None and commit.count >= 8
    assert apply_ is not None and apply_.count >= 8
    assert reads is not None and reads.count >= 1
    # commit happens no later than the apply-side notify
    assert commit.quantile(0.5) <= apply_.quantile(0.99) + 1e-6
    assert 0 < commit.quantile(0.99) < 60.0
    # WAL fsync barriers were observed into the host-level histogram
    fsync = m.histogram("fsync_latency_seconds", (0, 0))
    assert fsync is not None and fsync.count > 0
    # vector step stats flowed through the engine facade
    st = nh.engine.step_stats()
    assert st["steps"] > 0
    assert st["lanes_commit_advanced"] > 0
    assert st["entries_applied"] >= 8
    nh._export_health_gauges()
    assert m.gauge_value("engine_step_steps", (0, 0)) > 0
    # and the whole plane renders as conformant Prometheus text
    out = io.StringIO()
    nh.write_health_metrics(out)
    text = out.getvalue()
    assert "proposal_commit_latency_seconds_bucket" in text
    assert "fsync_latency_seconds_count" in text
    types, samples = _parse_exposition(
        "\n".join(
            ln for ln in text.splitlines()
            if not ln.startswith("# TYPE dragonboat_tpu_transport_")
            and not ln.startswith("dragonboat_tpu_transport_")
        )
    )
    for name, labels, value, keys in samples:
        assert keys == sorted(keys)


def test_scalar_engine_lane_stats_parity(tmp_path):
    """ROADMAP PR-4 headroom item: ExecEngine.lane_stats() returns the
    same per-lane shape as VectorEngine.lane_stats(), so engine_lane_*
    gauges and the bench JSON lane fold cover the scalar engine too."""
    import bench
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
    from tests.test_nodehost import KVSM

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=5,
            raft_address="scl1:1",
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            enable_metrics=True,
            engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4),
        )
    )
    try:
        nh.start_cluster(
            {1: "scl1:1"},
            False,
            lambda c, n: KVSM(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("no leader")
        sess = nh.get_noop_session(1)
        for i in range(4):
            nh.sync_propose(sess, f"k{i}=v".encode(), timeout_s=10.0)
        stats = nh.engine.lane_stats()
        assert 1 in stats, stats
        s = stats[1]
        # exact key parity with VectorEngine.lane_stats lanes
        assert set(s) == {
            "node_id",
            "leader_id",
            "term",
            "commit_gap",
            "last_index",
            "ticks_since_leader_change",
            "role",
            "payload_bytes",
        }
        assert s["last_index"] >= s["commit_gap"]
        assert s["role"] == 2  # this single node leads
        assert s["payload_bytes"] >= 0
        assert s["node_id"] == 1
        assert s["leader_id"] == 1
        assert s["term"] >= 1
        assert s["commit_gap"] >= 0
        # the election happened after tick 0, and ticks advanced since
        assert s["ticks_since_leader_change"] >= 0
        # gauges flow through the same _export_health_gauges seam
        nh._export_health_gauges()
        assert nh.metrics.gauge_value("engine_lane_leader_id", (1, 1)) == 1.0
        assert nh.metrics.gauge_value("engine_lane_term", (1, 1)) >= 1.0
        # and the bench JSON lane fold works under the scalar engine
        fold = bench._lane_report({1: nh})
        assert fold["lanes_total"] == 1
        assert fold["lanes_with_leader"] == 1
        assert fold["lane_commit_gap_max"] >= 0
    finally:
        nh.stop()


def test_census_and_counter_gauges_in_exposition(single_host):
    """ISSUE 18: the engine_hbm_* census gauges and engine_counter_*
    event gauges flow through _export_health_gauges into a conformant
    Prometheus exposition on a live vector host."""
    nh = single_host
    sess = nh.get_noop_session(1)
    for i in range(4):
        nh.sync_propose(sess, f"k{i}=v".encode(), timeout_s=10.0)
    nh._export_health_gauges()
    m = nh.metrics
    assert m.gauge_value("engine_hbm_bytes_total", (0, 0)) > 0
    assert m.gauge_value("engine_hbm_log_bytes", (0, 0)) > 0
    assert m.gauge_value("engine_hbm_log_fill_p50", (0, 0)) > 0.0
    assert m.gauge_value("engine_hbm_log_fill_p99", (0, 0)) > 0.0
    waste = m.gauge_value("engine_hbm_waste_ratio", (0, 0))
    assert 0.0 <= waste < 1.0
    assert m.gauge_value("engine_counter_elections_won", (0, 0)) >= 1.0
    assert m.gauge_value("engine_counter_commit_advances", (0, 0)) >= 4.0
    out = io.StringIO()
    nh.write_health_metrics(out)
    text = out.getvalue()
    assert "dragonboat_tpu_engine_hbm_bytes_total" in text
    assert "dragonboat_tpu_engine_counter_heartbeats_sent" in text
    types, samples = _parse_exposition(
        "\n".join(
            ln for ln in text.splitlines()
            if "_hbm_" in ln or "_counter_" in ln
        )
    )
    for name in (
        "dragonboat_tpu_engine_hbm_waste_ratio",
        "dragonboat_tpu_engine_counter_elections_started",
    ):
        assert types[name] == "gauge"


def test_history_gauges_in_exposition(single_host):
    """ISSUE 19: the engine_history_* sampler gauges are ALWAYS present
    (zero-filled with no sampler) and carry live counts once the host's
    HistorySampler runs, flowing through _export_health_gauges into a
    conformant Prometheus exposition."""
    nh = single_host
    # no sampler yet: gauges exist and read zero (stable dashboards)
    nh._export_health_gauges()
    m = nh.metrics
    assert m.gauge_value("engine_history_samples_total", (0, 0)) == 0.0
    assert m.gauge_value("engine_history_interval_seconds", (0, 0)) == 0.0
    nh.start_history(interval_s=0.02)
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if nh._history.stats()["samples_total"] >= 2:
                break
            time.sleep(0.02)
        nh._export_health_gauges()
        assert m.gauge_value("engine_history_samples_total", (0, 0)) >= 2.0
        assert m.gauge_value("engine_history_errors_total", (0, 0)) == 0.0
        assert m.gauge_value("engine_history_interval_seconds", (0, 0)) > 0.0
    finally:
        nh.stop_history()
    out = io.StringIO()
    nh.write_health_metrics(out)
    text = out.getvalue()
    assert "dragonboat_tpu_engine_history_samples_total" in text
    types, _samples = _parse_exposition(
        "\n".join(ln for ln in text.splitlines() if "_history_" in ln)
    )
    assert types["dragonboat_tpu_engine_history_samples_total"] == "gauge"
    # the ring landed next to the host's WAL dir and reads back
    from dragonboat_tpu.profile import read_history

    ring = os.path.join(nh._dir, "history.ring")
    _meta, hist_samples = read_history(ring)
    assert hist_samples and hist_samples[-1]["host"] == "obs1:1"


def test_scalar_engine_counter_and_census_parity(tmp_path):
    """ISSUE 18: ExecEngine exposes the same counter_stats /
    lane_counters / device_census shapes as the vector engine (names =
    ops.state.CTR_NAMES; census always-present and all-zero — the
    scalar engine holds no device memory), so gauges, bench JSON and
    tools.top need not branch per engine."""
    import bench
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.engine.execengine import _COUNTER_ATTRS
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.ops.state import CTR_NAMES
    from dragonboat_tpu.profile import CENSUS_KEYS
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
    from tests.test_nodehost import KVSM

    # the scalar twin's attribute list is pinned to the kernel's order
    assert _COUNTER_ATTRS == CTR_NAMES
    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=5,
            raft_address="sctr1:1",
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            enable_metrics=True,
            engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4),
        )
    )
    try:
        nh.start_cluster(
            {1: "sctr1:1"},
            False,
            lambda c, n: KVSM(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("no leader")
        sess = nh.get_noop_session(1)
        for i in range(4):
            nh.sync_propose(sess, f"k{i}=v".encode(), timeout_s=10.0)
        counters = nh.engine.counter_stats()
        assert set(counters) == set(CTR_NAMES)
        assert counters["elections_won"] >= 1
        assert counters["commit_advances"] >= 4
        lanes = nh.engine.lane_counters()
        assert set(lanes) == {1}
        assert set(lanes[1]) == set(CTR_NAMES)
        census = nh.engine.device_census()
        assert set(CENSUS_KEYS) <= set(census)
        assert census["hbm_bytes_total"] == 0
        assert census["hbm_waste_ratio"] == 0.0
        # gauges flow through the same export seam as the vector engine
        nh._export_health_gauges()
        assert nh.metrics.gauge_value(
            "engine_counter_elections_won", (0, 0)
        ) >= 1.0
        assert nh.metrics.gauge_value(
            "engine_hbm_bytes_total", (0, 0)
        ) == 0.0
        # and the bench census fold covers the scalar engine too
        fold = bench._census_report({1: nh})
        assert fold["hbm_bytes_total"] == 0
        assert fold["counters"]["commit_advances"] >= 4
    finally:
        nh.stop()


def test_e2e_unsampled_requests_stay_traceless(tmp_path):
    """profile_sample_ratio=0 -> sparse default (1/32): a couple of
    proposals should mostly carry NO trace object (allocation-free hot
    path), while the sampler still exists."""
    from dragonboat_tpu.engine.execengine import ExecEngine
    from dragonboat_tpu.storage.logdb import ShardedLogDB

    db = ShardedLogDB()
    eng = ExecEngine(db)
    try:
        assert eng.request_sampler.ratio == 32
        assert [eng.request_sampler.sample() for _ in range(31)].count(True) == 0
        assert eng.request_sampler.sample() is True
    finally:
        eng.stop()
        db.close()
