"""Request-plumbing tests: sharded pending proposals (cf. pendingProposal
requests.go:903-981) and the GC cadence fix — one should_gc() window must
sweep EVERY Pending* sharing the clock, not just the first caller."""
import threading

from dragonboat_tpu.client import Session
from dragonboat_tpu.requests import (
    REQUEST_TIMEOUT,
    LogicalClock,
    PendingConfigChange,
    PendingProposal,
    PendingReadIndex,
    PendingSnapshot,
)
from dragonboat_tpu.statemachine import Result
from dragonboat_tpu.types import ConfigChange


def test_sharded_proposals_route_completions_by_key():
    clock = LogicalClock()
    pp = PendingProposal(clock)
    sess = Session.noop_session(1)
    rss = []
    for _ in range(64):
        rs, e = pp.propose(sess, b"x", 10)
        assert e.key == rs.key
        rss.append(rs)
    assert len({rs.key for rs in rss}) == 64
    assert pp.has_pending()
    for rs in rss:
        pp.applied(rs.key, sess.client_id, sess.series_id,
                   Result(value=1), rejected=False)
    assert all(rs.done() for rs in rss)
    assert not pp.has_pending()


def test_sharded_proposals_spread_across_threads():
    """Different submitting threads use different shards (keys differ mod
    SHARDS) — the contention-spreading mechanism."""
    clock = LogicalClock()
    pp = PendingProposal(clock)
    sess = Session.noop_session(1)
    residues = set()
    mu = threading.Lock()

    def worker():
        rs, _ = pp.propose(sess, b"x", 10)
        with mu:
            residues.add(rs.key % PendingProposal.SHARDS)

    threads = [threading.Thread(target=worker) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # thread idents vary; at least two distinct shards is the honest bound
    assert len(residues) >= 2


def test_one_gc_window_sweeps_every_pending_kind():
    """Regression: each Pending.gc() used to consume should_gc() itself,
    so whichever ran first starved the others — read/cc/snapshot requests
    never timed out engine-side."""
    clock = LogicalClock()
    pp = PendingProposal(clock)
    pri = PendingReadIndex(clock)
    pcc = PendingConfigChange(clock)
    psn = PendingSnapshot(clock)
    sess = Session.noop_session(1)

    rs_p, _ = pp.propose(sess, b"x", 1)
    rs_r = pri.read(1)
    rs_c, _, _ = pcc.request(ConfigChange(), 1)
    rs_s, _ = psn.request(object(), 1)

    for _ in range(LogicalClock.GC_TICK + 2):
        clock.increase_tick()
    # caller-side gate: ONE window check, then all four sweep
    assert clock.should_gc()
    pp.gc()
    pri.gc()
    pcc.gc()
    psn.gc()
    for rs in (rs_p, rs_r, rs_c, rs_s):
        assert rs.done(), "a pending kind was not swept"
        assert rs.result.code == REQUEST_TIMEOUT
