"""RSM layer tests: sessions, membership legality, managed SM apply path,
snapshot IO format (cf. internal/rsm/statemachine_test.go,
session_test.go, membership_test.go patterns)."""
import io

import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.peer import encode_config_change
from dragonboat_tpu.rsm import (
    MembershipManager,
    SessionManager,
    SnapshotHeader,
    SnapshotReader,
    SnapshotWriter,
    StateMachineManager,
    StreamValidator,
    Task,
    wrap_state_machine,
)
from dragonboat_tpu.rsm.session import Session
from dragonboat_tpu.statemachine import (
    AbortSignal,
    IStateMachine,
    Result,
)
from dragonboat_tpu.types import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    NOOP_CLIENT_ID,
    SERIES_ID_FOR_REGISTER,
    SERIES_ID_FOR_UNREGISTER,
)


# ---------------------------------------------------------------- sessions
def test_session_response_cache():
    s = Session(100)
    s.add_response(1, Result(value=10))
    got, has = s.get_response(1)
    assert has and got.value == 10
    with pytest.raises(RuntimeError):
        s.add_response(1, Result(value=11))
    s.clear_to(1)
    assert s.has_responded(1)
    _, has = s.get_response(1)
    assert not has


def test_session_manager_lru_eviction():
    m = SessionManager(max_sessions=2)
    m.register_client_id(1)
    m.register_client_id(2)
    m.register_client_id(3)  # evicts 1
    assert m.get_registered_client(1) is None
    assert m.get_registered_client(2) is not None
    # 2 is now most recent; adding 4 evicts 3
    m.register_client_id(4)
    assert m.get_registered_client(3) is None
    assert m.get_registered_client(2) is not None


def test_session_manager_snapshot_roundtrip():
    m = SessionManager(max_sessions=8)
    for cid in (5, 6, 7):
        m.register_client_id(cid)
    s = m.get_registered_client(6)
    s.add_response(3, Result(value=33, data=b"abc"))
    s.responded_up_to = 2
    blob = m.save()
    m2 = SessionManager(max_sessions=8)
    m2.load(blob)
    s2 = m2.get_registered_client(6)
    got, has = s2.get_response(3)
    assert has and got.value == 33 and got.data == b"abc"
    assert m.hash() == m2.hash()


# -------------------------------------------------------------- membership
def mk_members():
    m = MembershipManager(1, 1, ordered=False)
    m.members.addresses = {1: "a:1", 2: "a:2", 3: "a:3"}
    return m


def test_membership_add_remove():
    m = mk_members()
    ok = m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=4, address="a:4"), 10
    )
    assert ok and m.members.addresses[4] == "a:4"
    assert m.members.config_change_id == 10
    ok = m.handle_config_change(
        ConfigChange(type=ConfigChangeType.REMOVE_NODE, node_id=4), 11
    )
    assert ok and 4 not in m.members.addresses and 4 in m.members.removed
    # re-adding a removed node is rejected
    ok = m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=4, address="a:9"), 12
    )
    assert not ok


def test_membership_rejects_dup_address():
    m = mk_members()
    ok = m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=9, address="a:2"), 10
    )
    assert not ok


def test_membership_observer_promotion():
    m = mk_members()
    assert m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_OBSERVER, node_id=5, address="a:5"), 10
    )
    # promote with same address ok
    assert m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=5, address="a:5"), 11
    )
    assert 5 in m.members.addresses and 5 not in m.members.observers


def test_membership_observer_promotion_wrong_address():
    m = mk_members()
    assert m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_OBSERVER, node_id=5, address="a:5"), 10
    )
    assert not m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=5, address="a:6"), 11
    )


def test_membership_cannot_delete_only_node():
    m = MembershipManager(1, 1)
    m.members.addresses = {1: "a:1"}
    assert not m.handle_config_change(
        ConfigChange(type=ConfigChangeType.REMOVE_NODE, node_id=1), 5
    )


def test_membership_ordered_ccid():
    m = MembershipManager(1, 1, ordered=True)
    m.members.addresses = {1: "a:1", 2: "a:2"}
    m.members.config_change_id = 7
    bad = ConfigChange(
        type=ConfigChangeType.ADD_NODE, node_id=3, address="a:3", config_change_id=6
    )
    assert not m.handle_config_change(bad, 10)
    good = ConfigChange(
        type=ConfigChangeType.ADD_NODE, node_id=3, address="a:3", config_change_id=7
    )
    assert m.handle_config_change(good, 10)


def test_membership_witness_rules():
    m = mk_members()
    assert m.handle_config_change(
        ConfigChange(type=ConfigChangeType.ADD_WITNESS, node_id=6, address="a:6"), 10
    )
    # adding an existing witness as full node must raise (illegal promotion)
    with pytest.raises(RuntimeError):
        m._apply(
            ConfigChange(type=ConfigChangeType.ADD_NODE, node_id=6, address="a:6"), 11
        )


# ----------------------------------------------------------- snapshot io
def test_snapshot_io_roundtrip():
    buf = io.BytesIO()
    hdr = SnapshotHeader(
        index=100,
        term=7,
        smtype=1,
        membership=Membership(addresses={1: "a:1"}, config_change_id=3),
    )
    payload = bytes(range(256)) * 5000  # > 1MB, multiple blocks
    with SnapshotWriter(buf, hdr, session=b"sess-image") as w:
        w.write(payload)
    buf.seek(0)
    r = SnapshotReader(buf)
    assert r.header.index == 100 and r.header.term == 7
    assert r.header.membership.addresses == {1: "a:1"}
    assert r.session == b"sess-image"
    got = r.read()
    assert got == payload


def test_snapshot_io_detects_corruption():
    buf = io.BytesIO()
    hdr = SnapshotHeader(index=1, term=1)
    with SnapshotWriter(buf, hdr, session=b"") as w:
        w.write(b"x" * 100000)
    raw = bytearray(buf.getvalue())
    raw[len(raw) // 2] ^= 0xFF  # flip a payload bit
    v = StreamValidator()
    v.feed(bytes(raw))
    assert not v.valid()
    v2 = StreamValidator()
    v2.feed(buf.getvalue())
    assert v2.valid()


# ------------------------------------------------------- manager apply path
class KVSM(IStateMachine):
    def __init__(self):
        self.data = {}
        self.update_count = 0

    def update(self, cmd: bytes) -> Result:
        self.update_count += 1
        k, v = cmd.decode().split("=", 1)
        self.data[k] = v
        return Result(value=len(self.data))

    def lookup(self, q):
        return self.data.get(q)

    def save_snapshot(self, w, files, done):
        import json

        w.write(json.dumps(self.data, sort_keys=True).encode())

    def recover_from_snapshot(self, r, files, done):
        import json

        self.data = json.loads(r.read().decode())


class FakeNodeProxy:
    def __init__(self):
        self.updates = []
        self.ccs = []
        self.cc_results = []

    def node_ready(self):
        pass

    def apply_update(self, entry, result, rejected, ignored, notify_read):
        self.updates.append((entry.index, result, rejected, ignored))

    def apply_config_change(self, cc):
        self.ccs.append(cc)

    def config_change_processed(self, key, accepted):
        self.cc_results.append((key, accepted))

    def node_id(self):
        return 1

    def cluster_id(self):
        return 5

    def should_stop(self):
        return False


def mk_manager(sm=None):
    sm = sm or KVSM()
    managed = wrap_state_machine(sm, 5, 1)
    proxy = FakeNodeProxy()
    cfg = Config(node_id=1, cluster_id=5, election_rtt=10, heartbeat_rtt=2)
    mgr = StateMachineManager(None, managed, proxy, cfg)
    return mgr, sm, proxy


def entry(index, cmd=b"", client=NOOP_CLIENT_ID, series=0, responded=0, term=1):
    return Entry(
        index=index,
        term=term,
        cmd=cmd,
        client_id=client,
        series_id=series,
        responded_to=responded,
    )


def run_tasks(mgr, *tasks):
    for t in tasks:
        mgr.task_queue.add(t)
    batch, apply = [], []
    return mgr.handle(batch, apply)


def test_manager_applies_noop_session_entries():
    mgr, sm, proxy = mk_manager()
    run_tasks(mgr, Task(entries=[entry(1, b"a=1"), entry(2, b"b=2")]))
    assert sm.data == {"a": "1", "b": "2"}
    assert mgr.last_applied_index() == 2
    assert [u[0] for u in proxy.updates] == [1, 2]


def test_manager_session_dedup():
    mgr, sm, proxy = mk_manager()
    # register client 77
    reg = entry(1, client=77, series=SERIES_ID_FOR_REGISTER)
    run_tasks(mgr, Task(entries=[reg]))
    assert proxy.updates[-1][1].value == 77
    # first proposal
    e1 = entry(2, b"k=v", client=77, series=1)
    run_tasks(mgr, Task(entries=[e1]))
    assert sm.update_count == 1
    # duplicate of series 1 must NOT re-apply; cached result returned
    dup = entry(3, b"k=v2", client=77, series=1)
    run_tasks(mgr, Task(entries=[dup]))
    assert sm.update_count == 1
    assert sm.data == {"k": "v"}
    assert proxy.updates[-1][1] == proxy.updates[-2][1]
    # acknowledged responses are evicted; a replay below responded_to is
    # flagged ignored
    e2 = entry(4, b"k2=v", client=77, series=2, responded=1)
    run_tasks(mgr, Task(entries=[e2]))
    assert sm.update_count == 2
    old = entry(5, b"k=zzz", client=77, series=1, responded=1)
    run_tasks(mgr, Task(entries=[old]))
    assert sm.update_count == 2
    assert proxy.updates[-1][3]  # ignored
    # unregister
    unreg = entry(6, client=77, series=SERIES_ID_FOR_UNREGISTER)
    run_tasks(mgr, Task(entries=[unreg]))
    # proposals from unregistered client rejected
    e3 = entry(7, b"x=y", client=77, series=3)
    run_tasks(mgr, Task(entries=[e3]))
    assert proxy.updates[-1][2]  # rejected
    assert sm.update_count == 2


def test_rsm_retried_proposal_returns_cached_result_every_time():
    """ISSUE 14 satellite: a deadline-retried proposal (same client,
    same series) that already applied returns the CACHED result on
    EVERY retry until the client acknowledges — one apply, identical
    results, never the 'ignored' flag (the caller needs the payload)."""
    mgr, sm, proxy = mk_manager()
    run_tasks(
        mgr, Task(entries=[entry(1, client=77, series=SERIES_ID_FOR_REGISTER)])
    )
    run_tasks(mgr, Task(entries=[entry(2, b"a=1", client=77, series=1)]))
    first = proxy.updates[-1][1]
    for idx in (3, 4, 5):  # three deadline retries of the SAME series
        run_tasks(
            mgr, Task(entries=[entry(idx, b"a=1", client=77, series=1)])
        )
        assert proxy.updates[-1][1] == first
        assert not proxy.updates[-1][2]  # not rejected
        assert not proxy.updates[-1][3]  # cached result, not 'ignored'
    assert sm.update_count == 1
    # the response cache really holds the unacknowledged series
    s = mgr._sessions.get_registered_client(77)
    assert s.get_response(1)[1]


def test_rsm_eviction_honors_responded_to_advance():
    """ISSUE 14 satellite: advancing responded_to EVICTS the cached
    result (session.go:109-120 clearTo — the client promised never to
    re-ask), and a late replay below the watermark reports
    already-responded (ignored) rather than re-applying or answering
    from a cache that no longer exists."""
    mgr, sm, proxy = mk_manager()
    run_tasks(
        mgr, Task(entries=[entry(1, client=77, series=SERIES_ID_FOR_REGISTER)])
    )
    run_tasks(mgr, Task(entries=[entry(2, b"a=1", client=77, series=1)]))
    s = mgr._sessions.get_registered_client(77)
    assert s.get_response(1)[1]
    # the next proposal carries responded_to=1: series 1's cache frees
    run_tasks(
        mgr,
        Task(entries=[entry(3, b"b=2", client=77, series=2, responded=1)]),
    )
    assert sm.update_count == 2
    assert s.responded_up_to == 1
    assert not s.get_response(1)[1], "acknowledged result not evicted"
    assert s.get_response(2)[1]  # the new series is cached
    # a late replay of the acknowledged series: ignored, no third apply
    run_tasks(
        mgr,
        Task(entries=[entry(4, b"a=zzz", client=77, series=1, responded=1)]),
    )
    assert proxy.updates[-1][3]  # ignored
    assert sm.update_count == 2


def test_rsm_expired_session_rejects_retry():
    """ISSUE 14 satellite: a session evicted by the replicated LRU
    (capacity pressure = session EXPIRY) REJECTS a retried proposal —
    at-most-once cover is gone and the client must re-register, never
    silently double-apply."""
    mgr, sm, proxy = mk_manager()
    mgr._sessions = SessionManager(max_sessions=1)
    run_tasks(
        mgr, Task(entries=[entry(1, client=77, series=SERIES_ID_FOR_REGISTER)])
    )
    run_tasks(mgr, Task(entries=[entry(2, b"a=1", client=77, series=1)]))
    assert sm.update_count == 1
    # registering a second client evicts 77 from the 1-slot LRU
    run_tasks(
        mgr, Task(entries=[entry(3, client=88, series=SERIES_ID_FOR_REGISTER)])
    )
    run_tasks(mgr, Task(entries=[entry(4, b"a=1", client=77, series=1)]))
    assert proxy.updates[-1][2], "expired session's retry not rejected"
    assert sm.update_count == 1, "expired session's retry re-applied"


def test_manager_config_change():
    mgr, sm, proxy = mk_manager()
    cc = ConfigChange(
        type=ConfigChangeType.ADD_NODE, node_id=2, address="a:2", initialize=True
    )
    e = Entry(
        index=1, term=1, type=EntryType.CONFIG_CHANGE, cmd=encode_config_change(cc),
        key=42,
    )
    run_tasks(mgr, Task(entries=[e]))
    assert proxy.cc_results == [(42, True)]
    assert mgr.get_membership().addresses == {2: "a:2"}
    # duplicate add rejected
    e2 = Entry(
        index=2, term=1, type=EntryType.CONFIG_CHANGE, cmd=encode_config_change(cc),
        key=43,
    )
    run_tasks(mgr, Task(entries=[e2]))
    assert proxy.cc_results[-1] == (43, False)


def test_manager_snapshot_task_interrupts_batch():
    mgr, sm, proxy = mk_manager()
    t1 = Task(entries=[entry(1, b"a=1")])
    t2 = Task(snapshot_requested=True)
    t3 = Task(entries=[entry(2, b"b=2")])
    mgr.task_queue.add(t1)
    mgr.task_queue.add(t2)
    mgr.task_queue.add(t3)
    batch, apply = [], []
    got = mgr.handle(batch, apply)
    assert got is t2
    assert sm.data == {"a": "1"}  # t1 applied before returning snapshot task
    got2 = mgr.handle(batch, apply)
    assert got2 is None
    assert sm.data == {"a": "1", "b": "2"}
