"""Entry payload compression (cf. reference internal/rsm/encoded.go:47-176):
round-trip at the codec level and end-to-end through propose -> wire ->
logdb -> restart replay -> apply."""
import os

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.rsm.encoded import (
    decode_payload,
    encode_payload,
    maybe_encode_entry,
)
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry
from dragonboat_tpu.types import CompressionType, Entry, EntryType

CT = CompressionType


def test_roundtrip():
    data = b"the quick brown fox " * 100
    enc = encode_payload(CT.SNAPPY, data)
    assert len(enc) < len(data)
    e = Entry(type=EntryType.ENCODED, cmd=enc)
    assert decode_payload(e) == data


def test_plain_entries_untouched():
    e = Entry(type=EntryType.APPLICATION, cmd=b"raw")
    assert decode_payload(e) == b"raw"


def test_tiny_and_incompressible_payloads_stay_plain():
    small = Entry(type=EntryType.APPLICATION, cmd=b"x" * 32)
    maybe_encode_entry(CT.SNAPPY, small)
    assert small.type == EntryType.APPLICATION
    incompressible = Entry(type=EntryType.APPLICATION, cmd=os.urandom(256))
    maybe_encode_entry(CT.SNAPPY, incompressible)
    assert incompressible.type == EntryType.APPLICATION


def test_config_change_entries_never_encoded():
    cc = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"c" * 256)
    maybe_encode_entry(CT.SNAPPY, cc)
    assert cc.type == EntryType.CONFIG_CHANGE


def test_compressible_payload_encodes():
    e = Entry(type=EntryType.APPLICATION, cmd=b"a" * 1024)
    maybe_encode_entry(CT.SNAPPY, e)
    assert e.type == EntryType.ENCODED
    assert len(e.cmd) < 1024
    assert decode_payload(e) == b"a" * 1024


class EchoSM(IStateMachine):
    payloads = []

    def __init__(self, cluster_id, node_id):
        pass

    def update(self, data):
        EchoSM.payloads.append(bytes(data))
        return Result(value=len(data))

    def lookup(self, q):
        return len(EchoSM.payloads)

    def save_snapshot(self, w, fc, done):
        import json

        w.write(json.dumps([p.hex() for p in EchoSM.payloads]).encode())

    def recover_from_snapshot(self, r, fc, done):
        import json

        EchoSM.payloads = [bytes.fromhex(h) for h in json.loads(r.read())]

    def close(self):
        pass


@pytest.fixture(autouse=True)
def _clear():
    EchoSM.payloads = []
    yield
    EchoSM.payloads = []


def test_e2e_compressed_propose_apply_and_restart(tmp_path):
    reg = _Registry()

    def mk():
        return NodeHost(NodeHostConfig(
            deployment_id=77, rtt_millisecond=5, raft_address="z:1",
            nodehost_dir=str(tmp_path / "h1"),
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            engine=EngineConfig(max_groups=8, max_peers=4, log_window=64),
        ))

    cfg = Config(
        cluster_id=5, node_id=1, election_rtt=10, heartbeat_rtt=2,
        entry_compression_type=CT.SNAPPY,
    )
    nh = mk()
    nh.start_cluster({1: "z:1"}, False, EchoSM, cfg)
    payload = b"compress me please " * 64  # ~1.2KB, highly compressible
    s = nh.get_noop_session(5)
    r = nh.sync_propose(s, payload, 15.0)
    assert r is not None
    # the SM must see the ORIGINAL bytes
    assert EchoSM.payloads == [payload]
    # the durable log must hold the COMPRESSED form
    ents, _ = nh.logdb.iterate_entries(5, 1, 1, 1 << 20, 1 << 30)
    stored = [e for e in ents if e.type == EntryType.ENCODED]
    assert stored and all(len(e.cmd) < len(payload) for e in stored)
    nh.stop()
    # restart: replay decodes transparently
    EchoSM.payloads = []
    nh2 = mk()
    nh2.start_cluster({1: "z:1"}, False, EchoSM, cfg)
    import time

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if EchoSM.payloads == [payload]:
            break
        time.sleep(0.05)
    assert EchoSM.payloads == [payload]
    nh2.stop()
