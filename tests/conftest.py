"""Test configuration: force JAX onto a virtual 8-device CPU mesh so that
multi-chip sharding paths are exercised without TPU hardware.

The backend guard itself (cpu pin + axon-factory drop + host device count)
lives in dragonboat_tpu._jaxenv; see its docstring for why JAX_PLATFORMS
alone is not enough."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu._jaxenv import pin_cpu

pin_cpu(n_devices=8)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration/chaos tests"
    )
