"""Test configuration: force JAX onto a virtual 8-device CPU mesh so that
multi-chip sharding paths are exercised without TPU hardware.

The environment auto-imports jax via a sitecustomize hook and registers an
'axon' TPU-tunnel backend whose client creation can hang when the tunnel is
busy. Tests must be hermetic and CPU-only, so before any backend is
initialized we (a) request the cpu platform, (b) drop the axon backend
factory, and (c) size the host platform to 8 virtual devices."""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:  # pragma: no cover - plugin absent outside this image
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration/chaos tests"
    )
