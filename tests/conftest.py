"""Test configuration: force JAX onto a virtual 8-device CPU mesh so that
multi-chip sharding paths are exercised without TPU hardware.

The backend guard itself (cpu pin + axon-factory drop + host device count)
lives in dragonboat_tpu._jaxenv; see its docstring for why JAX_PLATFORMS
alone is not enough."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu._jaxenv import enable_compile_cache, pin_cpu

pin_cpu(n_devices=8)
# warm XLA compiles across pytest processes: the step kernel costs seconds
# per distinct KernelConfig, and election-deadline tests race exactly that
# first compile on slow boxes
enable_compile_cache()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration/chaos tests"
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded FaultPlane chaos tests — bounded enough for tier-1; "
        "select the matrix alone with `-m chaos` (seeds print on failure "
        "so any run replays from the CI log)",
    )


# ---- hang diagnosis (the Python half of the race-detection story; see
# SURVEY §5: no -race exists for Python, so concurrency bugs here surface
# as deadlocks/stalls under the chaos + differential suites) ----
# If any single test wedges for 10 minutes, dump every thread's stack so
# the lock cycle is visible in CI output instead of an opaque timeout.
import faulthandler  # noqa: E402

_HANG_DUMP_S = 600


def pytest_runtest_setup(item):
    faulthandler.dump_traceback_later(_HANG_DUMP_S, exit=False)


def pytest_runtest_teardown(item, nextitem):
    faulthandler.cancel_dump_traceback_later()
