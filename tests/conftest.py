"""Test configuration: force JAX onto a virtual 8-device CPU mesh so that
multi-chip sharding paths are exercised without TPU hardware.

The backend guard itself (cpu pin + axon-factory drop + host device count)
lives in dragonboat_tpu._jaxenv; see its docstring for why JAX_PLATFORMS
alone is not enough."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu._jaxenv import enable_compile_cache, pin_cpu

pin_cpu(n_devices=8)
# warm XLA compiles across pytest processes: the step kernel costs seconds
# per distinct KernelConfig, and election-deadline tests race exactly that
# first compile on slow boxes
enable_compile_cache()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running integration/chaos tests"
    )
    config.addinivalue_line(
        "markers",
        "chaos: seeded FaultPlane chaos tests — bounded enough for tier-1; "
        "select the matrix alone with `-m chaos` (seeds print on failure "
        "so any run replays from the CI log)",
    )
    config.addinivalue_line(
        "markers",
        "lint: the static-analysis gate (dragonboat_tpu.analysis over the "
        "whole package + per-rule meta-tests) — the pure-AST, jax-free "
        "slice of tier-1; run it alone with `-m lint` for a sub-second "
        "pre-commit check (same gate as `python -m "
        "dragonboat_tpu.tools.check`)",
    )
    config.addinivalue_line(
        "markers",
        "perf: the perf-attribution gate — the tools.perfdiff regression "
        "gate over the checked-in fixtures (sub-second, jax-free) plus "
        "the runtime device-sync/retrace audit assertions over a live "
        "vector-engine scenario; run it alone with `-m perf` alongside "
        "the `-m lint` gate",
    )
    config.addinivalue_line(
        "markers",
        "serving: the overload robustness gate (dragonboat_tpu.serving) — "
        "admission control, backpressure folding, deadline-aware retry, "
        "quiesce wake-on-admit, and the seeded overload_storm graceful-"
        "degradation verdict; run it alone with `-m serving`",
    )
    config.addinivalue_line(
        "markers",
        "longhaul: the drummer-style long-haul runner's bounded smoke "
        "profile (tools.longhaul with a tight --budget, <60s) — tier-1 "
        "proves the runner end to end (rounds, verdicts, failure "
        "bundles); the hours-long profile stays opt-in via "
        "`python -m dragonboat_tpu.tools.longhaul --budget <secs>`",
    )


# ---- hang diagnosis (the Python half of the race-detection story; see
# SURVEY §5: no -race exists for Python, so concurrency bugs here surface
# as deadlocks/stalls under the chaos + differential suites) ----
# If any single test wedges for 10 minutes, dump every thread's stack so
# the lock cycle is visible in CI output instead of an opaque timeout.
import faulthandler  # noqa: E402

import pytest  # noqa: E402

_HANG_DUMP_S = 600

# ---- crash-persistent ring (the timeout-kill half of the forensics
# story): JSONL failure dumps only happen when pytest survives to report —
# a pytest-timeout / `timeout -k` SIGKILL leaves nothing. The session-wide
# mmap ring persists every recorded event the moment it happens (mmap
# pages live in the kernel page cache, so they survive ANY process death);
# after a killed run, `python -m dragonboat_tpu.tools.timeline
# .pytest_flight/live.ring` replays the tail, and the per-test
# `_test_start` markers show which test was running when the axe fell. ----
import atexit  # noqa: E402
import signal  # noqa: E402


def _flight_dump_dir() -> str:
    d = os.environ.get("FLIGHT_DUMP_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", ".pytest_flight"
    )
    return os.path.abspath(d)


def _attach_session_ring():
    try:
        from dragonboat_tpu.trace import flight_recorder

        path = os.environ.get("FLIGHT_RING_PATH") or os.path.join(
            _flight_dump_dir(), "live.ring"
        )
        rec = flight_recorder()
        rec.attach_mmap(path)
        atexit.register(rec.flush)
        # `timeout -k` sends SIGTERM first: flush the ring and fall back
        # to the default action so the artifact is complete even when the
        # follow-up SIGKILL never becomes necessary
        if signal.getsignal(signal.SIGTERM) in (
            signal.SIG_DFL, signal.default_int_handler,
        ):
            def _on_term(signum, frame):
                try:
                    rec.flush()
                finally:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_term)
    except Exception:
        pass  # forensics must never block the test run


_attach_session_ring()


def pytest_runtest_setup(item):
    faulthandler.dump_traceback_later(_HANG_DUMP_S, exit=False)
    # fresh flight-recorder timeline per test: a failure dump must show
    # THIS test's events, not the tail of whatever ran before it. The
    # mmap ring is NOT reset — it spans the session so a timeout kill
    # keeps the recent cross-test tail; the marker delimits tests.
    try:
        from dragonboat_tpu.trace import flight_recorder

        rec = flight_recorder()
        rec.reset()
        # nodeid clipped so the marker always fits one mmap ring slot
        rec.record("_test_start", nodeid=item.nodeid[-160:])
    except Exception:
        pass


def pytest_runtest_teardown(item, nextitem):
    faulthandler.cancel_dump_traceback_later()


# ---- flight recorder failure dump (the forensic half of the CHAOS_SEED
# story): any test failure writes the process-global FlightRecorder ring
# as JSONL next to the printed seed, so a chaos replay comes with the
# timeline of what the cluster actually did — leader changes, breaker
# trips, queue evictions, fault injections, fairness clamps. ----
import json as _json  # noqa: E402
import re as _re  # noqa: E402


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    if not rep.failed:
        return  # dump on ANY failing phase: setup failures (cluster never
        # elected) and teardown assertions need the timeline most
    try:
        from dragonboat_tpu.trace import flight_recorder

        rec = flight_recorder()
        events = rec.dump()
        if not events:
            return
        dump_dir = os.environ.get("FLIGHT_DUMP_DIR") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "..", ".pytest_flight"
        )
        dump_dir = os.path.abspath(dump_dir)
        os.makedirs(dump_dir, exist_ok=True)
        safe = _re.sub(r"[^A-Za-z0-9_.-]+", "_", item.nodeid)[-120:]
        suffix = "" if rep.when == "call" else f"-{rep.when}"
        path = os.path.join(dump_dir, safe + suffix + ".jsonl")
        with open(path, "w") as f:
            # the _meta header carries this process's mono->wall offset so
            # tools.timeline can merge this dump with other hosts'/rings'
            f.write(rec.to_jsonl(meta={"source": safe}) + "\n")
        tail = "\n".join(
            _json.dumps(e, default=str, sort_keys=True) for e in events[-25:]
        )
        rep.sections.append(
            (
                "flight recorder",
                f"{len(events)} events -> {path}\nlast events:\n{tail}",
            )
        )
    except Exception:
        pass  # the dump must never turn a failure into an error
