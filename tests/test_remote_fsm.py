"""Remote flow-control FSM conformance matrix.

Behavioral parity with the reference's remote states and transitions
(internal/raft/remote.go:44-198, matrix shapes from remote_test.go:22-360):
Retry/Wait/Replicate/Snapshot transitions, optimistic pipelining,
rejection backtracking, snapshot completion gating, pause semantics. The
same FSM runs as an int8 tensor lane per (group, peer) in the device
kernel (ops/state.py RSTATE), so this scalar matrix is also the oracle
for the differential suite.
"""
import pytest

from dragonboat_tpu.core.remote import Remote, RemoteState


def mk(match=0, next=1, state=RemoteState.RETRY, snapshot_index=0):
    r = Remote(match=match, next=next, snapshot_index=snapshot_index)
    r.state = state
    return r


class TestTransitions:
    def test_become_retry_from_replicate_resets_next_to_match(self):
        r = mk(match=10, next=25, state=RemoteState.REPLICATE)
        r.become_retry()
        assert r.state == RemoteState.RETRY
        assert r.next == 11
        assert r.snapshot_index == 0

    def test_become_retry_from_snapshot_keeps_snapshot_floor(self):
        """After an aborted/complete snapshot the probe restarts above the
        snapshot index, not at the stale match (remote_test.go:76-110)."""
        r = mk(match=3, state=RemoteState.SNAPSHOT, snapshot_index=40)
        r.become_retry()
        assert r.next == 41
        assert r.snapshot_index == 0
        r2 = mk(match=50, state=RemoteState.SNAPSHOT, snapshot_index=40)
        r2.become_retry()
        assert r2.next == 51  # match overtook the snapshot

    def test_become_replicate_starts_after_match(self):
        r = mk(match=7, next=3, state=RemoteState.RETRY)
        r.become_replicate()
        assert r.state == RemoteState.REPLICATE
        assert r.next == 8

    def test_become_snapshot_records_index(self):
        r = mk(match=7, state=RemoteState.REPLICATE)
        r.become_snapshot(99)
        assert r.state == RemoteState.SNAPSHOT
        assert r.snapshot_index == 99

    def test_become_wait_is_retry_then_pause(self):
        r = mk(match=5, next=9, state=RemoteState.REPLICATE)
        r.become_wait()
        assert r.state == RemoteState.WAIT
        assert r.next == 6

    def test_wait_retry_round_trip_only_from_matching_state(self):
        r = mk(state=RemoteState.REPLICATE)
        r.retry_to_wait()  # no-op outside RETRY
        assert r.state == RemoteState.REPLICATE
        r.wait_to_retry()  # no-op outside WAIT
        assert r.state == RemoteState.REPLICATE


class TestProgress:
    def test_replicate_progress_is_optimistic(self):
        """Pipelining: next jumps past the just-sent batch without waiting
        for the ack (remote_test.go:129-149)."""
        r = mk(match=10, next=11, state=RemoteState.REPLICATE)
        r.progress(last_index=18)
        assert r.next == 19

    def test_retry_progress_pauses_probe(self):
        """One probe message in flight at a time: sending from RETRY moves
        the remote to WAIT until a response arrives."""
        r = mk(state=RemoteState.RETRY)
        r.progress(last_index=5)
        assert r.state == RemoteState.WAIT
        assert r.is_paused()

    def test_snapshot_progress_is_invalid(self):
        r = mk(state=RemoteState.SNAPSHOT, snapshot_index=5)
        with pytest.raises(RuntimeError):
            r.progress(3)


class TestTryUpdate:
    def test_advances_match_and_next(self):
        r = mk(match=3, next=4, state=RemoteState.RETRY)
        assert r.try_update(9)
        assert r.match == 9 and r.next == 10

    def test_stale_ack_returns_false_but_keeps_next(self):
        r = mk(match=9, next=15)
        assert not r.try_update(7)
        assert r.match == 9
        assert r.next == 15  # never decreased by an old ack

    def test_ack_unpauses_wait(self):
        """A successful ack resumes a paused probe
        (remote_test.go:323-360 TryUpdateCauseResume)."""
        r = mk(match=3, next=4, state=RemoteState.WAIT)
        assert r.try_update(8)
        assert r.state == RemoteState.RETRY
        assert not r.is_paused()


class TestDecreaseTo:
    def test_replicate_rejection_backtracks_to_match(self):
        """In REPLICATE, a rejection above match resets next to match+1 —
        the optimistic window collapses (remote_test.go:266-288)."""
        r = mk(match=10, next=30, state=RemoteState.REPLICATE)
        assert r.decrease_to(rejected=20, last=25)
        assert r.next == 11

    def test_replicate_rejection_at_or_below_match_is_stale(self):
        r = mk(match=10, next=30, state=RemoteState.REPLICATE)
        assert not r.decrease_to(rejected=10, last=25)
        assert r.next == 30

    def test_probe_rejection_must_match_outstanding_probe(self):
        """Outside REPLICATE only the response to the CURRENT probe
        (rejected == next-1) backtracks (remote_test.go:290-321)."""
        r = mk(match=0, next=10, state=RemoteState.RETRY)
        assert not r.decrease_to(rejected=4, last=25)
        assert r.next == 10
        assert r.decrease_to(rejected=9, last=25)
        assert r.next == 9  # min(rejected, last+1): back one step
        r2 = mk(match=0, next=10, state=RemoteState.RETRY)
        assert r2.decrease_to(rejected=9, last=2)
        assert r2.next == 3  # follower's log is short: probe its tail

    def test_probe_rejection_unpauses_wait(self):
        r = mk(match=0, next=10, state=RemoteState.WAIT)
        assert r.decrease_to(rejected=9, last=20)
        assert r.state == RemoteState.RETRY

    def test_next_never_below_one(self):
        r = mk(match=0, next=1, state=RemoteState.RETRY)
        assert r.decrease_to(rejected=0, last=0)
        assert r.next == 1


class TestSnapshotCompletion:
    def test_responded_to_leaves_snapshot_only_after_catchup(self):
        """The remote stays in SNAPSHOT until its match reaches the
        snapshot index (the install is still in flight before that)."""
        r = mk(match=3, state=RemoteState.SNAPSHOT, snapshot_index=40)
        r.responded_to()
        assert r.state == RemoteState.SNAPSHOT
        r.try_update(40)
        r.responded_to()
        assert r.state == RemoteState.RETRY
        assert r.next == 41

    def test_responded_to_promotes_retry_to_replicate(self):
        r = mk(match=5, next=6, state=RemoteState.RETRY)
        r.responded_to()
        assert r.state == RemoteState.REPLICATE

    def test_clear_pending_snapshot(self):
        r = mk(state=RemoteState.SNAPSHOT, snapshot_index=40)
        r.clear_pending_snapshot()
        assert r.snapshot_index == 0


class TestPauseAndActivity:
    def test_paused_states(self):
        assert not mk(state=RemoteState.RETRY).is_paused()
        assert not mk(state=RemoteState.REPLICATE).is_paused()
        assert mk(state=RemoteState.WAIT).is_paused()
        assert mk(state=RemoteState.SNAPSHOT).is_paused()

    def test_activity_flag(self):
        r = mk()
        assert not r.is_active()
        r.set_active()
        assert r.is_active()
        r.set_not_active()
        assert not r.is_active()
