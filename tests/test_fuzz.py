"""Bounded fuzz campaigns in CI (cf. reference raftpb/fuzz.go:15-49 and
internal/transport/fuzz.go:68-77; the timed campaign lives in
dragonboat_tpu/fuzz.py and runs standalone via `python -m
dragonboat_tpu.fuzz`)."""
import random

import pytest

from dragonboat_tpu import codec
from dragonboat_tpu.fuzz import (
    fuzz_codec_mutations,
    fuzz_codec_roundtrip,
    fuzz_tcp_frames,
)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_codec_roundtrip_fuzz(seed):
    assert fuzz_codec_roundtrip(random.Random(seed), 200) == 200


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_codec_mutation_fuzz(seed):
    # every decode either succeeds or raises CodecError — anything else
    # propagates and fails the test
    assert fuzz_codec_mutations(random.Random(seed), 400) > 0


def test_tcp_frame_fuzz():
    assert fuzz_tcp_frames(random.Random(21), 60) == 60


def test_known_hostile_inputs():
    """Regression corpus: shapes that used to crash or hang the decoders
    before the bounds hardening."""
    # count field of 0xFFFFFFFF: would loop ~4e9 times building entries
    hostile = b"\xff\xff\xff\xff" + b"\x00" * 16
    with pytest.raises(codec.CodecError):
        codec.decode_entries(hostile)
    # entry cmd length beyond the buffer: used to silently return a SHORT
    # cmd instead of failing
    import struct

    ent = codec.encode_entry
    from dragonboat_tpu.types import Entry

    data = bytearray(ent(Entry(cmd=b"abcd")))
    struct.pack_into("<I", data, codec._ENTRY.size - 4, 1 << 30)
    with pytest.raises(codec.CodecError):
        codec.decode_entry(bytes(data))
    # truncated struct header
    with pytest.raises(codec.CodecError):
        codec.decode_message(b"\x01\x02")
    # bad enum value for message type
    from dragonboat_tpu.types import Message, MessageType

    bad = bytearray(codec.encode_message(Message(type=MessageType.HEARTBEAT)))
    bad[0] = 250
    with pytest.raises(codec.CodecError):
        codec.decode_message(bytes(bad))
