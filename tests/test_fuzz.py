"""Bounded fuzz campaigns in CI (cf. reference raftpb/fuzz.go:15-49 and
internal/transport/fuzz.go:68-77; the timed campaign lives in
dragonboat_tpu/fuzz.py and runs standalone via `python -m
dragonboat_tpu.fuzz`)."""
import random

import pytest

from dragonboat_tpu import codec
from dragonboat_tpu.fuzz import (
    fuzz_codec_mutations,
    fuzz_codec_roundtrip,
    fuzz_tcp_frames,
    fuzz_wal_garbage,
    fuzz_wal_recovery,
)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_codec_roundtrip_fuzz(seed):
    assert fuzz_codec_roundtrip(random.Random(seed), 200) == 200


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_codec_mutation_fuzz(seed):
    # every decode either succeeds or raises CodecError — anything else
    # propagates and fails the test
    assert fuzz_codec_mutations(random.Random(seed), 400) > 0


def test_tcp_frame_fuzz():
    assert fuzz_tcp_frames(random.Random(21), 60) == 60


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_wal_recovery_fuzz(seed, tmp_path):
    # mutated/truncated WAL tails must recover to the state after some
    # prefix of committed record groups — never crash, never half-apply a
    # batch, never accept a corrupt record (asserted inside the campaign)
    assert fuzz_wal_recovery(random.Random(seed), 25, str(tmp_path)) == 25


def test_wal_garbage_fuzz():
    assert fuzz_wal_garbage(random.Random(41), 300) == 300


def test_wal_group_atomicity_half_written_batch(tmp_path):
    """Regression: a batch whose records landed but whose commit seal is
    missing (crash between flush and the seal reaching disk) must roll
    back WHOLLY — the old per-record replay surfaced half-applied
    batches."""
    import os
    import struct
    import zlib as _zlib

    from dragonboat_tpu.storage.kv import _REC, _OP_PUT, WalKV, WriteBatch

    d = str(tmp_path / "w")
    kv = WalKV(d, fsync=False)
    wb = WriteBatch()
    wb.put(b"committed", b"1")
    kv.commit_write_batch(wb)
    kv.close()
    # append two valid PUT records with NO commit seal (torn group)
    with open(os.path.join(d, "wal.log"), "ab") as f:
        for k, v in ((b"torn1", b"x"), (b"torn2", b"y")):
            rec = _REC.pack(
                _REC.size + len(k) + len(v) + 4, _OP_PUT, len(k), len(v)
            ) + k + v
            f.write(rec + struct.pack("<I", _zlib.crc32(rec)))
    kv2 = WalKV(d)
    assert kv2.get_value(b"committed") == b"1"
    assert kv2.get_value(b"torn1") is None
    assert kv2.get_value(b"torn2") is None
    # reopen truncated the torn tail, so a NEW batch's seal must not
    # resurrect the rolled-back records on the next replay
    wb2 = WriteBatch()
    wb2.put(b"after", b"2")
    kv2.commit_write_batch(wb2)
    kv2.close()
    kv3 = WalKV(d)
    assert kv3.get_value(b"after") == b"2"
    assert kv3.get_value(b"committed") == b"1"
    assert kv3.get_value(b"torn1") is None, "torn batch resurrected"
    assert kv3.get_value(b"torn2") is None, "torn batch resurrected"
    kv3.close()


def test_known_hostile_inputs():
    """Regression corpus: shapes that used to crash or hang the decoders
    before the bounds hardening."""
    # count field of 0xFFFFFFFF: would loop ~4e9 times building entries
    hostile = b"\xff\xff\xff\xff" + b"\x00" * 16
    with pytest.raises(codec.CodecError):
        codec.decode_entries(hostile)
    # entry cmd length beyond the buffer: used to silently return a SHORT
    # cmd instead of failing
    import struct

    ent = codec.encode_entry
    from dragonboat_tpu.types import Entry

    data = bytearray(ent(Entry(cmd=b"abcd")))
    struct.pack_into("<I", data, codec._ENTRY.size - 4, 1 << 30)
    with pytest.raises(codec.CodecError):
        codec.decode_entry(bytes(data))
    # truncated struct header
    with pytest.raises(codec.CodecError):
        codec.decode_message(b"\x01\x02")
    # bad enum value for message type
    from dragonboat_tpu.types import Message, MessageType

    bad = bytearray(codec.encode_message(Message(type=MessageType.HEARTBEAT)))
    bad[0] = 250
    with pytest.raises(codec.CodecError):
        codec.decode_message(bytes(bad))
