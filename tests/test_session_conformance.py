"""Client-session conformance matrix (cf. internal/rsm/session.go +
lrusession.go, matrices from session_test.go:28-200 and
lrusession_test.go:26-260): at-most-once response caching, cumulative
clearing, LRU eviction with order preserved across snapshot
save/restore, and registration lifecycle."""
from dragonboat_tpu.rsm.session import Session, SessionManager
from dragonboat_tpu.statemachine import Result


class TestResponseCache:
    def test_response_can_be_added_and_fetched(self):
        s = Session(client_id=7)
        s.add_response(1, Result(value=100))
        r, hit = s.get_response(1)
        assert hit and r.value == 100
        _, miss = s.get_response(2)
        assert not miss

    def test_clear_to_is_cumulative(self):
        """clear_to(n) drops every cached response at or below n — the
        client's responded_to watermark frees server memory
        (session_test.go:59-89)."""
        s = Session(client_id=7)
        for i in range(1, 6):
            s.add_response(i, Result(value=i))
        s.clear_to(3)
        for i in (1, 2, 3):
            assert not s.get_response(i)[1]
        for i in (4, 5):
            r, hit = s.get_response(i)
            assert hit and r.value == i

    def test_has_responded_tracks_watermark(self):
        """Queries at or below the cleared watermark report 'already
        responded' even though the payload is gone
        (session_test.go:91-119)."""
        s = Session(client_id=7)
        for i in range(1, 4):
            s.add_response(i, Result(value=i))
        s.clear_to(2)
        assert s.has_responded(1)
        assert s.has_responded(2)
        assert not s.has_responded(3) or s.get_response(3)[1]

    def test_session_save_load_roundtrip(self):
        s = Session(client_id=9)
        s.add_response(4, Result(value=44, data=b"blob"))
        s.clear_to(2)
        blob = s.save()
        s2, _ = Session.load(blob)
        assert s2.client_id == 9
        r, hit = s2.get_response(4)
        assert hit and r.value == 44 and r.data == b"blob"
        assert s2.has_responded(2)


class TestSessionManagerLRU:
    def test_eviction_is_lru_ordered(self):
        """Filling past capacity evicts the LEAST recently used client,
        and touching a session refreshes it (lrusession_test.go:26-118)."""
        m = SessionManager(max_sessions=3)
        for cid in (1, 2, 3):
            m.register_client_id(cid)
        # touch 1 so 2 becomes the LRU
        assert m.get_registered_client(1) is not None
        m.register_client_id(4)  # evicts 2
        assert m.get_registered_client(2) is None
        for cid in (1, 3, 4):
            assert m.get_registered_client(cid) is not None, cid

    def test_sessions_are_mutable_in_place(self):
        """Responses added through the manager land on the SAME session
        object it stores (lrusession_test.go:63-92)."""
        m = SessionManager(max_sessions=4)
        m.register_client_id(5)
        s = m.get_registered_client(5)
        m.add_response(s, 1, Result(value=77))
        again = m.get_registered_client(5)
        assert again.get_response(1)[1]
        assert again.get_response(1)[0].value == 77

    def test_save_restore_preserves_lru_order(self):
        """After snapshot save/load the eviction order must be the SAME —
        replicas diverge otherwise (lrusession_test.go:120-193)."""
        m = SessionManager(max_sessions=3)
        for cid in (1, 2, 3):
            m.register_client_id(cid)
        m.get_registered_client(1)  # order now: 2 (LRU), 3, 1

        m2 = SessionManager(max_sessions=3)
        m2.load(m.save())
        assert len(m2) == 3
        m2.register_client_id(9)  # must evict 2, as the original would
        assert m2.get_registered_client(2) is None
        for cid in (1, 3, 9):
            assert m2.get_registered_client(cid) is not None, cid

    def test_save_restore_hash_stable(self):
        """Identical session state must hash identically across replicas
        (the chaos suite compares session hashes)."""
        m = SessionManager(max_sessions=4)
        m.register_client_id(1)
        s = m.get_registered_client(1)
        m.add_response(s, 3, Result(value=5))
        m2 = SessionManager(max_sessions=4)
        m2.load(m.save())
        assert m.hash() == m2.hash()

    def test_empty_manager_roundtrip(self):
        m = SessionManager(max_sessions=2)
        m2 = SessionManager(max_sessions=2)
        m2.load(m.save())
        assert len(m2) == 0

    def test_unregister_removes_session(self):
        m = SessionManager(max_sessions=4)
        m.register_client_id(1)
        m.unregister_client_id(1)
        assert m.get_registered_client(1) is None
        # unregistering an unknown client reports rejection, not a crash
        r = m.unregister_client_id(42)
        assert r is not None
