"""Regression net for the load-sensitive `overload_no_urgent_shed`
longhaul verdict (PR 9 gate run: seed 0x8693C4A3DB1A failed on clean
HEAD on a loaded 2-cpu box).

Root cause: the urgent ledger conflated POLICY sheds (the admission
plane refusing urgent work — the contract violation) with CAPACITY
effects (admitted urgent reads completing slowly on a loaded box). The
fix splits the ledger (`urgent_shed` vs `urgent_stalled`) and anchors
the wait budget to the round's measured on-box baseline
(serving/storm.py _probe_urgent_baseline) — a slow box reads as
latency, never as a shed.
"""
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.faults import FaultPlane
from dragonboat_tpu.requests import ErrSystemBusy
from dragonboat_tpu.serving.admission import ErrTenantThrottled
from dragonboat_tpu.serving.storm import (
    StormReport,
    _offer_window,
    _wait_urgent,
    storm_burst,
)
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

pytestmark = pytest.mark.serving

# the PR 9 gate's failing round seed — kept as the named regression
# anchor (the longhaul round derives every storm window from it)
TRIAGE_SEED = 0x8693C4A3DB1A


class KV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        w.write(b"{}")

    def recover_from_snapshot(self, r, files, done):
        r.read()


class _ShedFront:
    """Front stub: every read is refused — once by POLICY (typed
    ErrOverloaded subclass), once by downstream CAPACITY (plain
    ErrSystemBusy). Bulk proposes complete instantly."""

    def __init__(self, read_exc):
        self._read_exc = read_exc

    def read(self, tenant, cluster_id, timeout_s):
        raise self._read_exc

    def propose(self, tenant, cluster_id, cmd, timeout_s):
        class _T:
            def wait(self):
                class _R:
                    completed = True

                return _R()

        return _T()


def _offer(front):
    rep = StormReport(seed=1)
    _offer_window(
        front, 1, (1,), {1: 10}, urgent_tenant=9, urgent_every=2,
        cmd_for=lambda i: b"k=v", rep=rep, op_base=0, timeout_s=1.0,
    )
    return rep


def test_policy_shed_vs_capacity_refusal_classification():
    rep = _offer(_ShedFront(ErrTenantThrottled(0.1)))
    assert rep.urgent_shed > 0 and rep.urgent_stalled == 0
    rep = _offer(_ShedFront(ErrSystemBusy()))
    assert rep.urgent_shed == 0 and rep.urgent_stalled > 0


def test_wait_urgent_counts_stalls_not_sheds():
    class _NeverDone:
        def wait(self, t):
            class _R:
                completed = False

            return _R()

    rep = StormReport(seed=1)
    rep.urgent_wait_s = 0.05
    _wait_urgent([_NeverDone(), _NeverDone()], rep)
    assert rep.urgent_stalled == 2 and rep.urgent_shed == 0


def test_triage_seed_burst_no_false_urgent_shed(tmp_path):
    """The named seed, replayed twice through storm_burst on a live
    host: zero POLICY sheds both times, a capacity-aware wait budget
    anchored to the measured baseline, and a bit-identical window
    signature (same-seed replay)."""
    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=4, rtt_millisecond=5, raft_address="st1:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=32, max_peers=4, log_window=64
            ),
        )
    )
    try:
        nh.start_cluster(
            {1: "st1:1"}, False, lambda c, n: KV(),
            Config(cluster_id=1, node_id=1, election_rtt=20,
                   heartbeat_rtt=4),
        )
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        outs = []
        for _ in range(2):
            fp = FaultPlane(TRIAGE_SEED)
            outs.append(
                storm_burst(
                    nh, 1, fp, burst_s=0.25, capacity_rate=400.0,
                    timeout_s=4.0,
                )
            )
        for out in outs:
            assert out["urgent_shed"] == 0, out
            # the budget anchors to the measured on-box baseline and can
            # only be MORE generous than the raw timeout
            assert out["urgent_wait_s"] >= 4.0
            assert out["urgent_baseline_s"] > 0.0
        assert outs[0]["signature"] == outs[1]["signature"]
        assert outs[0]["offered"] == outs[1]["offered"]
    finally:
        nh.stop()
