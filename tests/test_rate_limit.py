"""Rate limiter tests (cf. internal/server/rate.go:32-137 and the
reference's rate-limit flow raft.go:543-683, 1779-1785): limiter
semantics, InMemory byte accounting, follower->leader reporting through
RATE_LIMIT messages in the scalar core, and end-to-end ErrSystemBusy
behavior on a NodeHost for BOTH engines."""
import time

import pytest

from dragonboat_tpu.core.rate import (
    ENTRY_OVERHEAD_BYTES,
    RateLimiter,
    entries_mem_size,
)
from dragonboat_tpu.types import Entry


def _e(index: int, payload: bytes = b"") -> Entry:
    return Entry(index=index, term=1, cmd=payload)


class TestRateLimiter:
    def test_disabled_when_unset(self):
        rl = RateLimiter(0)
        assert not rl.enabled
        rl.set(1 << 40)
        assert not rl.rate_limited()

    def test_local_size_limits(self):
        rl = RateLimiter(100)
        assert rl.enabled
        rl.set(100)
        assert not rl.rate_limited()  # bound is exclusive
        rl.increase(1)
        assert rl.rate_limited()
        rl.decrease(50)
        assert not rl.rate_limited()

    def test_follower_state_limits_leader(self):
        rl = RateLimiter(100)
        rl.set(10)
        rl.set_follower_state(2, 500)
        assert rl.rate_limited()
        rl.set_follower_state(2, 20)
        assert not rl.rate_limited()

    def test_stale_follower_reports_age_out(self):
        """A partitioned follower must not wedge the leader as limited
        (rate.go:102-127 gc)."""
        rl = RateLimiter(100)
        rl.set_follower_state(2, 500)
        assert rl.rate_limited()
        for _ in range(RateLimiter.GC_TICK + 1):
            rl.tick()
        assert not rl.rate_limited()
        # and the stale record is actually gone, not just ignored
        assert not rl.rate_limited()

    def test_reset_follower_state(self):
        rl = RateLimiter(100)
        rl.set_follower_state(2, 500)
        rl.reset_follower_state()
        assert not rl.rate_limited()


class TestInMemoryByteTracking:
    def _inmem(self, rl):
        from dragonboat_tpu.core.logentry import InMemory

        im = InMemory(0)
        im.set_rate_limiter(rl)
        return im

    def test_merge_append_and_apply(self):
        rl = RateLimiter(1 << 30)
        im = self._inmem(rl)
        im.merge([_e(1, b"x" * 10), _e(2, b"y" * 20)])
        assert rl.get() == 2 * ENTRY_OVERHEAD_BYTES + 30
        im.merge([_e(3, b"z" * 5)])
        assert rl.get() == 3 * ENTRY_OVERHEAD_BYTES + 35
        im.applied_log_to(2)  # new marker: entry 1 dropped, 2 and 3 kept
        assert rl.get() == 2 * ENTRY_OVERHEAD_BYTES + 25
        assert rl.get() == entries_mem_size(im.entries)

    def test_merge_conflict_truncates_size(self):
        rl = RateLimiter(1 << 30)
        im = self._inmem(rl)
        im.merge([_e(1, b"a" * 10), _e(2, b"b" * 10), _e(3, b"c" * 10)])
        # conflicting suffix replaces entries >= 2
        im.merge([_e(2, b"d" * 100)])
        assert rl.get() == entries_mem_size(im.entries)
        assert len(im.entries) == 2

    def test_restore_resets_size(self):
        from dragonboat_tpu.types import Snapshot

        rl = RateLimiter(1 << 30)
        im = self._inmem(rl)
        im.merge([_e(1, b"a" * 10)])
        im.restore(Snapshot(index=5, term=2))
        assert rl.get() == 0


class TestScalarCoreReporting:
    """Follower -> leader RATE_LIMIT flow on the raft core harness."""

    def _mk(self, max_bytes):
        from tests.raft_harness import Network, new_test_raft

        rafts = {
            i: new_test_raft(i, [1, 2, 3], max_in_mem_log_size=max_bytes)
            for i in (1, 2, 3)
        }
        return Network(rafts)

    def test_follower_report_limits_leader(self):
        from dragonboat_tpu.types import Message, MessageType as MT

        net = self._mk(1000)
        net.elect(1)
        leader = net.rafts[1]
        assert not leader.rl.rate_limited()
        # follower 2 reports an oversized in-mem log directly (the wire
        # path for the report message itself)
        leader.handle(Message(type=MT.RATE_LIMIT, from_=2, to=1, hint=5000,
                              term=leader.term))
        assert leader.rl.rate_limited()
        # leader ticks age the report out after GC_TICK limiter ticks
        for _ in range(leader.election_timeout * (RateLimiter.GC_TICK + 1)):
            leader.tick()
        assert not leader.rl.rate_limited()

    def test_follower_sends_report_when_over(self):
        net = self._mk(200)
        net.elect(1)
        f = net.rafts[2]
        # inflate the follower's tracked size past the bound
        f.rl.set(10_000)
        sent = []
        for _ in range(f.election_timeout * 2):
            f.tick()
            sent.extend(m for m in f.msgs if m.type.name == "RATE_LIMIT")
            f.msgs.clear()
        assert sent, "follower never reported"
        assert all(m.to == 1 for m in sent)
        assert any(m.hint > 0 for m in sent)


@pytest.mark.slow
@pytest.mark.parametrize("engine", ["scalar", "vector"])
def test_nodehost_rate_limit_e2e(tmp_path, engine):
    """A tiny max_in_mem_log_size makes a proposal burst hit
    ErrSystemBusy, and the node accepts work again once drained."""
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.requests import (
        ErrClusterNotReady,
        ErrSystemBusy,
        ErrTimeout,
    )
    from dragonboat_tpu.statemachine import IStateMachine, Result
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    class SlowSM(IStateMachine):
        def __init__(self, *a):
            self.n = 0

        def update(self, data):
            time.sleep(0.002)  # keep entries in-mem long enough to pile up
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, fc, done):
            w.write(b"\0")

        def recover_from_snapshot(self, r, fc, done):
            r.read()

        def close(self):
            pass

    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=81, rtt_millisecond=5, raft_address="rl1:1",
        nodehost_dir=str(tmp_path / "nh1"),
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(kind=engine, max_groups=4, max_peers=4,
                            log_window=64),
    ))
    try:
        nh.start_cluster(
            {1: "rl1:1"}, False, lambda c, n: SlowSM(),
            Config(cluster_id=1, node_id=1, election_rtt=20,
                   heartbeat_rtt=2, max_in_mem_log_size=2048),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        assert ok

        s = nh.get_noop_session(1)
        busy = False
        inflight = []
        for i in range(4000):
            try:
                inflight.append(nh.propose(s, b"p" * 256, 30.0))
            except ErrSystemBusy:
                busy = True
                break
        assert busy, "burst never tripped the rate limiter"

        # drain, then the node must accept proposals again
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                r = nh.sync_propose(s, b"after", timeout_s=5.0)
                if r is not None:
                    break
            except (ErrSystemBusy, ErrClusterNotReady, ErrTimeout):
                # busy / transiently dropped mid-drain: retry like a real
                # client
                time.sleep(0.1)
        else:
            raise AssertionError("node never recovered from rate limit")
    finally:
        nh.stop()
