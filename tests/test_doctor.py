"""tools.doctor tests: the stall-diagnosis rule engine and its CLI.

Three planes of coverage, mirroring how the doctor is actually used:

* synthetic rule tests — hand-built history samples drive every verdict
  kind in the taxonomy through ``diagnose_data`` and assert BOTH that
  the expected verdict fires and that the others stay quiet (a doctor
  that diagnoses everything diagnoses nothing);
* live seeded single-fault scenarios — a real fault (partition, fsync
  stall via the FaultPlane's WAL wrapper, tick-clock step jump,
  admission overload) injected into live NodeHosts, sampled with the
  same ``sample_host`` the history ring uses, and diagnosed;
* the checked-in failure-bundle fixture (tests/data/doctor_bundle)
  rendered through the real CLI subprocess, pinning the bundle loader
  and the report schema an operator actually sees.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.faults import ClockPlane, FaultPlane, FaultSpec
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.profile import sample_host
from dragonboat_tpu.serving import AdmissionConfig, ErrOverloaded, TenantSpec
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.storage import ShardedLogDB
from dragonboat_tpu.storage.kv import WalKV
from dragonboat_tpu.tools.doctor import (
    diagnose,
    diagnose_data,
    diagnosis_report,
    load_bundle,
    top_verdict_line,
)
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(os.path.dirname(__file__), "data", "doctor_bundle")

CLUSTER = 1

# the seeded-fault kinds the live scenarios must discriminate between:
# each scenario asserts its own kind fired and NONE of the other four
FAULT_KINDS = frozenset({
    "no_quorum_partition",
    "election_churn",
    "wal_fsync_stall",
    "clock_anomaly",
    "admission_shed_storm",
})


# ------------------------------------------------------- sample builders
def lane(leader=1, gap=0, started=0, won=0, node=1, term=2):
    """One capped-lane-table row shaped like profile.sample_host emits:
    lane_stats fields + the hot counters subdict doctor's deltas read."""
    return {
        "node_id": node,
        "leader_id": leader,
        "term": term,
        "commit_gap": gap,
        "counters": {"elections_started": started, "elections_won": won},
    }


def mk(host, t, lanes=None, **over):
    """A minimal-but-complete history sample: every plane present and
    quiet, so a test overrides exactly the plane its rule reads."""
    s = {
        "event": "history_sample",
        "schema": 1,
        "t": float(t),
        "host": host,
        "cluster": 0,
        "lanes": dict(lanes or {}),
        "lanes_total": len(lanes or {}),
        "lanes_dropped": 0,
        "counters": {},
        "pressure": {},
        "lease": {"local": 0, "fallback": 0},
        "census": {
            "hbm_bytes_total": 0, "hbm_waste_ratio": 0.0, "lanes_active": 0,
        },
        "fairness_gap_s": 0.0,
        "clock_anomalies": 0,
        "wal": {
            "ewma_s": 0.0, "last_s": 0.0, "last_wave_s": 0.0,
            "inflight": 0, "barriers": 0,
        },
        "serving": {
            "admitted": 0, "shed": 0, "queue_depth": 0, "saturation": 0.0,
        },
        "migrations": {"started": 0, "completed": 0, "aborted": 0,
                       "active": 0},
    }
    s.update(over)
    return s


def kinds(verdicts):
    return [v.kind for v in verdicts]


# ------------------------------------------------- synthetic rule tests
def test_rule_healthy_idle_is_the_empty_verdict():
    hist = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 0.5, {"1": lane()}),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["healthy_idle"]
    assert vs[0].severity == 0
    assert vs[0].hosts == ["a"]
    assert vs[0].evidence["samples"] == 2


def test_rule_no_quorum_partition():
    hist = [
        mk("a", 0.0, {"1": lane(leader=0, started=0)}),
        mk("b", 0.0, {"1": lane(leader=0, started=0)}),
        mk("a", 1.0, {"1": lane(leader=0, started=3)}),
        mk("b", 1.0, {"1": lane(leader=0, started=2)}),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["no_quorum_partition"]
    v = vs[0]
    assert v.lanes == ["1"]
    assert v.evidence["elections_started_delta"] == 5
    assert v.evidence["elections_won_delta"] == 0
    assert sorted(v.evidence["leaderless_hosts"]) == ["a", "b"]


def test_rule_election_churn_needs_wins_not_just_campaigns():
    # three WON elections in the window: flapping leadership, not a
    # partition (somebody keeps winning) — and a leader is present at
    # the window's end, so the no-quorum rule must stay quiet
    hist = [
        mk("a", 0.0, {"1": lane(leader=1, started=0, won=0)}),
        mk("a", 1.0, {"1": lane(leader=2, started=4, won=3)}),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["election_churn"]
    assert vs[0].evidence["elections_won_delta"] == 3
    # two wins is a normal failover, not churn
    calm = [
        mk("a", 0.0, {"1": lane(leader=1, won=0)}),
        mk("a", 1.0, {"1": lane(leader=2, started=2, won=2)}),
    ]
    assert kinds(diagnose_data(calm)) == ["healthy_idle"]


def test_rule_wal_fsync_stall():
    wal = {"ewma_s": 0.18, "last_s": 0.2, "last_wave_s": 0.2,
           "inflight": 1, "barriers": 40}
    hist = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 1.0, {"1": lane()}, wal=wal),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["wal_fsync_stall"]
    assert vs[0].evidence["fsync_ewma_max_s"] == pytest.approx(0.18)
    assert vs[0].evidence["barriers_delta"] == 40


def test_rule_clock_anomaly_delta_and_single_sample_forms():
    hist = [
        mk("a", 0.0, {"1": lane()}, clock_anomalies=1),
        mk("a", 1.0, {"1": lane()}, clock_anomalies=3),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["clock_anomaly"]
    assert vs[0].evidence["clock_anomalies_delta"] == 2
    # a single-sample series (crashed ring tail) falls back to the
    # cumulative count — one sample of evidence beats none
    solo = [mk("a", 0.0, {"1": lane()}, clock_anomalies=4)]
    vs = diagnose_data(solo)
    assert "clock_anomaly" in kinds(vs)
    assert vs[kinds(vs).index("clock_anomaly")].evidence[
        "clock_anomalies_delta"] == 4


def test_rule_admission_shed_storm():
    hist = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 1.0, {"1": lane()},
           serving={"admitted": 3, "shed": 9, "queue_depth": 2,
                    "saturation": 0.8}),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["admission_shed_storm"]
    ev = vs[0].evidence
    assert ev["shed_delta"] == 9
    assert ev["admitted_delta"] == 3
    assert ev["saturation_max"] == pytest.approx(0.8)
    # four sheds is backpressure doing its job, not a storm
    calm = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 1.0, {"1": lane()},
           serving={"admitted": 9, "shed": 4, "queue_depth": 0,
                    "saturation": 0.2}),
    ]
    assert kinds(diagnose_data(calm)) == ["healthy_idle"]


def test_rule_lease_fallback_storm_subsumed_by_clock_anomaly():
    stormy = dict(lease={"local": 1, "fallback": 8})
    hist = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 1.0, {"1": lane()}, **stormy),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["lease_fallback_storm"]
    assert vs[0].evidence["lease_fallback_delta"] == 8
    assert vs[0].evidence["lease_local_delta"] == 1
    # the SAME fallback storm with a clock fault in the window is the
    # lease plane working as designed: clock_anomaly alone must fire
    explained = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 1.0, {"1": lane()}, clock_anomalies=1, **stormy),
    ]
    vs = diagnose_data(explained)
    assert "clock_anomaly" in kinds(vs)
    assert "lease_fallback_storm" not in kinds(vs)


def test_rule_migration_wedged_requires_zero_progress():
    hist = [
        mk("a", 0.0, {"1": lane()},
           migrations={"started": 2, "completed": 1, "aborted": 0,
                       "active": 1}),
        mk("a", 1.0, {"1": lane()},
           migrations={"started": 2, "completed": 1, "aborted": 0,
                       "active": 1}),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["migration_wedged"]
    assert vs[0].evidence["migrations_active"] == 1
    # one completion in the window = progress, however slow
    moving = [
        mk("a", 0.0, {"1": lane()},
           migrations={"started": 2, "completed": 1, "aborted": 0,
                       "active": 1}),
        mk("a", 1.0, {"1": lane()},
           migrations={"started": 2, "completed": 2, "aborted": 0,
                       "active": 1}),
    ]
    assert kinds(diagnose_data(moving)) == ["healthy_idle"]


def test_rule_lane_leak_needs_monotone_growth():
    hist = [
        mk("a", 0.0, {}, lanes_total=2),
        mk("a", 0.5, {}, lanes_total=7),
        mk("a", 1.0, {}, lanes_total=12),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["lane_leak"]
    assert vs[0].evidence["lanes_first"] == 2
    assert vs[0].evidence["lanes_last"] == 12
    # a dip in the middle means churn is REAPING — growth alone is fine
    churny = [
        mk("a", 0.0, {}, lanes_total=2),
        mk("a", 0.5, {}, lanes_total=14),
        mk("a", 1.0, {}, lanes_total=12),
    ]
    assert kinds(diagnose_data(churny)) == ["healthy_idle"]


def test_rule_snapshot_parked_remote_needs_flight_corroboration():
    frozen = [
        mk("a", 0.0, {"7": lane(leader=1, gap=6)}),
        mk("a", 1.0, {"7": lane(leader=1, gap=6)}),
    ]
    # a frozen gap with NO transfer evidence stays undiagnosed: the
    # history plane alone cannot tell "parked" from "just slow"
    assert kinds(diagnose_data(frozen)) == ["healthy_idle"]
    flight = [{"event": "snapshot_stream_aborted", "cluster": 7, "t": 0.4}]
    vs = diagnose_data(frozen, flight=flight)
    assert kinds(vs) == ["snapshot_parked_remote"]
    v = vs[0]
    assert v.lanes == ["7"]
    assert v.evidence["commit_gap_frozen"] == 6
    assert v.evidence["snapshot_events"]["snapshot_stream_aborted"] == 1
    # requested-but-never-installed is the other parked shape
    flight2 = [
        {"event": "snapshot_requested", "cluster": 7, "t": 0.1},
    ]
    assert kinds(diagnose_data(frozen, flight=flight2)) == [
        "snapshot_parked_remote"
    ]


def test_verdicts_rank_most_severe_first_and_footer_line():
    hist = [
        mk("a", 0.0, {"1": lane(leader=0)}),
        mk("a", 1.0, {"1": lane(leader=0, started=4)},
           serving={"admitted": 0, "shed": 9, "queue_depth": 0,
                    "saturation": 0.9}),
    ]
    vs = diagnose_data(hist)
    assert kinds(vs) == ["no_quorum_partition", "admission_shed_storm"]
    assert vs[0].severity > vs[1].severity
    line = top_verdict_line(vs)
    assert line == "doctor: no_quorum_partition sev=95 hosts=a lanes=1"
    assert top_verdict_line([]) == "doctor: (no verdicts)"


def test_diagnosis_report_schema():
    hist = [
        mk("a", 0.0, {"1": lane()}),
        mk("a", 0.75, {"1": lane()}),
    ]
    rep = diagnosis_report(hist, source="round-001")
    assert rep["schema"] == 1
    assert rep["source"] == "round-001"
    assert rep["samples"] == 2
    assert rep["hosts"] == ["a"]
    assert rep["window_s"] == pytest.approx(0.75)
    assert [v["kind"] for v in rep["verdicts"]] == ["healthy_idle"]
    json.dumps(rep)  # the bundle artifact must be JSON-serializable


# ------------------------------------------------- live fault scenarios
class KV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=len(self.d))

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, tmp, logdb_factory=None):
    cfg = NodeHostConfig(
        deployment_id=7,
        rtt_millisecond=5,
        nodehost_dir=os.path.join(tmp, f"h{nid}"),
        raft_address=f"d{nid}:1",
        raft_rpc_factory=lambda listen, reg=reg: loopback_factory(
            listen, reg
        ),
        logdb_factory=logdb_factory,
        engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4),
    )
    return NodeHost(cfg)


def _group_cfg(nid):
    return Config(
        cluster_id=CLUSTER, node_id=nid, election_rtt=10, heartbeat_rtt=2
    )


def _wait(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _assert_single_fault(verdicts, expected):
    """The discrimination contract: the seeded fault's kind fired, the
    other seeded-fault kinds did not, and the fleet is not 'healthy'."""
    ks = set(kinds(verdicts))
    assert expected in ks, f"{expected} missing from {sorted(ks)}"
    assert not (ks & (FAULT_KINDS - {expected})), (
        f"cross-diagnosis: {sorted(ks & (FAULT_KINDS - {expected}))}"
    )
    assert "healthy_idle" not in ks


def test_live_healthy_host_diagnoses_idle(tmp_path):
    reg = _Registry()
    nh = _mk_host(1, reg, str(tmp_path))
    try:
        nh.start_cluster({1: "d1:1"}, False, lambda *_a: KV(), _group_cfg(1))
        assert _wait(lambda: nh.get_leader_id(CLUSTER)[1])
        for i in range(4):
            nh.sync_propose(
                nh.get_noop_session(CLUSTER), b"k=%d" % i, timeout_s=10.0
            )
        vs = diagnose({1: nh}, window_s=0.4, interval_s=0.1, flight=[])
        assert kinds(vs) == ["healthy_idle"]
        assert vs[0].hosts == ["d1:1"]
    finally:
        nh.stop()


def test_live_partition_diagnoses_no_quorum(tmp_path):
    reg = _Registry()
    hosts = {n: _mk_host(n, reg, str(tmp_path)) for n in (1, 2, 3)}
    members = {n: f"d{n}:1" for n in (1, 2, 3)}
    try:
        for n, nh in hosts.items():
            nh.start_cluster(members, False, lambda *_a: KV(), _group_cfg(n))
        assert _wait(
            lambda: any(
                nh.get_leader_id(CLUSTER)[1] for nh in hosts.values()
            )
        )
        for nh in hosts.values():
            nh.set_partitioned(True)
        # past a few election RTTs: every island has started (and lost)
        # at least one campaign by the time the window opens
        time.sleep(0.8)
        s1 = [sample_host(nh) for nh in hosts.values()]
        time.sleep(1.0)
        s2 = [sample_host(nh) for nh in hosts.values()]
        vs = diagnose_data(s1 + s2, flight=[])
        _assert_single_fault(vs, "no_quorum_partition")
        v = vs[kinds(vs).index("no_quorum_partition")]
        assert v.evidence["elections_started_delta"] > 0
        assert v.evidence["elections_won_delta"] == 0
        assert v.lanes == [str(CLUSTER)]
    finally:
        for nh in hosts.values():
            nh.stop()


def test_live_fsync_stall_diagnoses_wal(tmp_path):
    fp = FaultPlane(0xD0C)

    def logdb_factory(d):
        return ShardedLogDB(
            os.path.join(d, "logdb"),
            kv_factory=fp.kv_factory("fsync:doc", WalKV),
        )

    reg = _Registry()
    nh = _mk_host(1, reg, str(tmp_path), logdb_factory=logdb_factory)
    try:
        nh.start_cluster({1: "d1:1"}, False, lambda *_a: KV(), _group_cfg(1))
        assert _wait(lambda: nh.get_leader_id(CLUSTER)[1])
        nh.sync_propose(nh.get_noop_session(CLUSTER), b"w=0", timeout_s=10.0)
        s1 = sample_host(nh)
        # every barrier now stalls 80ms: the ewma (alpha .2) crosses the
        # 50ms stall threshold after ~5 barriers
        fp.set_spec(FaultSpec(fsync_stall=1.0, fsync_stall_s=(0.08, 0.08)))
        for i in range(10):
            nh.sync_propose(
                nh.get_noop_session(CLUSTER), b"k=%d" % i, timeout_s=30.0
            )
        s2 = sample_host(nh)
        assert s2["wal"]["ewma_s"] > s1["wal"]["ewma_s"]
        vs = diagnose_data([s1, s2], flight=[])
        _assert_single_fault(vs, "wal_fsync_stall")
        v = vs[kinds(vs).index("wal_fsync_stall")]
        assert v.evidence["fsync_ewma_max_s"] >= 0.05
        assert v.evidence["barriers_delta"] > 0
    finally:
        fp.set_spec(FaultSpec())
        nh.stop()


def test_live_clock_jump_diagnoses_clock_anomaly(tmp_path):
    reg = _Registry()
    cp = ClockPlane(FaultPlane(0xC10))
    nh = _mk_host(1, reg, str(tmp_path))
    try:
        nh.set_tick_clock(cp.clock_fn("h1"))
        nh.start_cluster({1: "d1:1"}, False, lambda *_a: KV(), _group_cfg(1))
        assert _wait(lambda: nh.get_leader_id(CLUSTER)[1])
        nh.sync_propose(nh.get_noop_session(CLUSTER), b"k=v", timeout_s=10.0)
        s1 = sample_host(nh)
        assert s1["clock_anomalies"] == 0
        cp.step_jump("h1", 5.0)
        assert _wait(lambda: nh.clock_anomalies() >= 1, timeout=5.0)
        s2 = sample_host(nh)
        vs = diagnose_data([s1, s2], flight=[])
        _assert_single_fault(vs, "clock_anomaly")
        v = vs[kinds(vs).index("clock_anomaly")]
        assert v.evidence["clock_anomalies_delta"] >= 1
    finally:
        nh.stop()


def test_live_overload_storm_diagnoses_shed_storm(tmp_path):
    reg = _Registry()
    nh = _mk_host(1, reg, str(tmp_path))
    try:
        nh.start_cluster({1: "d1:1"}, False, lambda *_a: KV(), _group_cfg(1))
        assert _wait(lambda: nh.get_leader_id(CLUSTER)[1])
        # a starved bucket: ~2 admits then synchronous typed sheds
        front = nh.serving_front(
            admission=AdmissionConfig(
                default=TenantSpec(rate=1.0, burst=2.0)
            )
        )
        s1 = sample_host(nh)
        tickets, shed = [], 0
        for i in range(30):
            try:
                tickets.append(
                    front.propose(11, CLUSTER, b"s=%d" % i, 10.0)
                )
            except ErrOverloaded:
                shed += 1
        assert shed >= 5
        for t in tickets:
            t.wait()
        s2 = sample_host(nh)
        vs = diagnose_data([s1, s2], flight=[])
        _assert_single_fault(vs, "admission_shed_storm")
        v = vs[kinds(vs).index("admission_shed_storm")]
        assert v.evidence["shed_delta"] >= 5
    finally:
        nh.stop()


# --------------------------------------------------- bundle fixture/CLI
def test_fixture_bundle_loads_both_planes():
    bundle = load_bundle(_FIXTURE)
    assert bundle["source"] == "doctor_bundle"
    assert len(bundle["history"]) == 3
    assert all(
        s["event"] == "history_sample" for s in bundle["history"]
    )
    assert any(
        e["event"].startswith("snapshot_") for e in bundle["flight"]
    )
    vs = diagnose_data(bundle["history"], flight=bundle["flight"])
    assert kinds(vs) == ["snapshot_parked_remote"]


def test_doctor_cli_renders_fixture_bundle():
    proc = subprocess.run(
        [sys.executable, "-m", "dragonboat_tpu.tools.doctor", _FIXTURE],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "raft-doctor:" in out
    assert "snapshot_parked_remote" in out
    assert "commit_gap_frozen=6" in out
    assert "hint:" in out


def test_doctor_cli_json_mode():
    proc = subprocess.run(
        [
            sys.executable, "-m", "dragonboat_tpu.tools.doctor",
            _FIXTURE, "--json",
        ],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(proc.stdout)
    assert rep["schema"] == 1
    assert rep["source"] == "doctor_bundle"
    assert rep["verdicts"][0]["kind"] == "snapshot_parked_remote"
    assert rep["verdicts"][0]["severity"] == 70
    assert rep["verdicts"][0]["evidence"]["commit_gap_frozen"] == 6


def test_doctor_cli_rejects_garbage_input(tmp_path):
    proc = subprocess.run(
        [
            sys.executable, "-m", "dragonboat_tpu.tools.doctor",
            os.path.join(str(tmp_path), "nope.ring"),
        ],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    assert proc.returncode == 2
    assert "doctor:" in proc.stderr


def test_top_history_renders_fixture_with_doctor_footer():
    hist = os.path.join(_FIXTURE, "history.jsonl")
    proc = subprocess.run(
        [
            sys.executable, "-m", "dragonboat_tpu.tools.top",
            "--history", hist,
        ],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    # the fixture's frozen gap needs flight corroboration to diagnose —
    # the history-only footer stays honest and reports idle
    assert "doctor: healthy_idle" in proc.stdout
    assert "fix1:1" in proc.stdout
