"""Leader-lease reads (ISSUE 17 tentpole): scalar conformance, kernel
differential, and the clock-fault degradation path.

Four layers:

  * scalar-core conformance — a quorum of tag-matched heartbeat acks
    grants a lease bounded STRICTLY below the minimum election timeout
    minus the skew margin; any _reset (step-down, new term), an
    in-flight transfer, or a host clock-anomaly report revokes it; a
    live lease serves a linearizable read locally (no quorum round) and
    an expired/suspect lease falls back to ReadIndex — degradation, not
    danger;
  * lease-off bit-identity guard — with `Config.lease_read` at its
    default the kernel's lease tensors never move and the heartbeat
    wire tag stays 0 (the whole pre-existing differential suite pins
    the rest of the off-path);
  * kernel differential — the vectorized kernel with leases ON agrees
    with the scalar oracle replica-for-replica (roles/terms/commit AND
    lease validity + served/fallback counters) across seeded randomized
    fault schedules;
  * the NodeHost tick plane — a ClockPlane step-jump on a live leader
    is detected as a CLOCK fault (not a scheduling stall): the lease
    goes on suspect hold (reads degrade to ReadIndex and still
    linearize), the fairness gauge is not tripped, and the phantom tick
    backlog is shed instead of burst-replayed.
"""
import os
import time

import numpy as np
import pytest

from dragonboat_tpu.config import Config, ConfigError, EngineConfig, NodeHostConfig
from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft
from dragonboat_tpu.core.remote import Remote
from dragonboat_tpu.faults import ClockPlane, FaultPlane
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.ops.loopback import LoopbackCluster
from dragonboat_tpu.ops.state import ROLE, _mix
from dragonboat_tpu.requests import ErrLeaseExpired, ErrSystemBusy
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
from dragonboat_tpu.types import Entry, Message, MessageType as MT, is_local_message

N = 3
ELECTION = 10
HEARTBEAT = 2


def mk_raft(nid, lease_read=True, full=(1, 2, 3), **kw):
    r = Raft(
        Config(
            node_id=nid, cluster_id=1, election_rtt=ELECTION,
            heartbeat_rtt=HEARTBEAT, lease_read=lease_read, **kw,
        ),
        InMemLogDB(),
    )
    for p in full:
        r.remotes[p] = Remote(next=1)
    return r


def mk_leader(lease_read=True, **kw):
    r = mk_raft(1, lease_read=lease_read, **kw)
    r.handle(Message(type=MT.ELECTION, from_=1))
    for p in (2, 3):
        r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=p, to=1, term=r.term))
    assert r.is_leader()
    # commit the leader noop so ReadIndex is legal at this term
    for p in (2, 3):
        r.handle(
            Message(
                type=MT.REPLICATE_RESP, from_=p, to=1, term=r.term,
                log_index=r.log.last_index(),
            )
        )
    r.msgs.clear()
    return r


def heartbeat_round(r):
    """Tick until the periodic heartbeat fires; return the round's tag."""
    for _ in range(2 * HEARTBEAT + 1):
        r.tick()
        hbs = [m for m in r.msgs if m.type == MT.HEARTBEAT]
        if hbs:
            r.msgs.clear()
            return hbs[0].log_index
    raise AssertionError("no heartbeat fired")


def ack(r, frm, tag):
    r.handle(
        Message(type=MT.HEARTBEAT_RESP, from_=frm, to=1, term=r.term,
                log_index=tag)
    )


class TestScalarLease:
    def test_quorum_of_tagged_acks_grants_bounded_lease(self):
        r = mk_leader()
        tag = heartbeat_round(r)
        assert tag == r.tick_count  # the round opens at the current tick
        assert not r.lease_valid()  # no acks yet
        ack(r, 2, tag)
        assert r.lease_valid()  # quorum = leader + one voter
        # bounded strictly below the MINIMUM randomized election timeout
        # minus the margin: no rival can win an election inside the lease
        assert r.lease_until == tag + ELECTION - r.lease_margin
        assert r.lease_margin == HEARTBEAT  # default margin = heartbeat_rtt
        assert r.lease_until - tag < ELECTION

    def test_stale_round_tag_does_not_count(self):
        r = mk_leader()
        tag = heartbeat_round(r)
        ack(r, 2, tag - 1)  # echo of an older round
        ack(r, 2, 0)  # leases-off echo
        assert not r.lease_valid()
        ack(r, 2, tag)
        assert r.lease_valid()

    def test_lease_expires_at_bound(self):
        r = mk_leader()
        tag = heartbeat_round(r)
        ack(r, 2, tag)
        while r.tick_count < r.lease_until - 1:
            r.tick()
            r.msgs.clear()
        assert r.lease_valid()
        r.tick()
        assert not r.lease_valid()

    def test_step_down_and_transfer_revoke(self):
        r = mk_leader()
        ack(r, 2, heartbeat_round(r))
        assert r.lease_valid()
        r.handle(
            Message(type=MT.LEADER_TRANSFER, from_=1, to=1, hint=2)
        )
        assert r.leader_transfering() and not r.lease_valid()
        r2 = mk_leader()
        ack(r2, 2, heartbeat_round(r2))
        # a higher-term message forces step-down: _reset clears the lease
        r2.handle(
            Message(type=MT.HEARTBEAT, from_=3, to=1, term=r2.term + 5)
        )
        assert not r2.is_leader()
        assert r2.lease_until == 0 and r2.lease_round_tick == 0
        assert not r2.lease_valid()

    def test_clock_suspect_revokes_and_blocks_regrant(self):
        r = mk_leader()
        ack(r, 2, heartbeat_round(r))
        assert r.lease_valid()
        r.set_clock_suspect(100)
        assert not r.lease_valid()
        # a fresh quorum round inside the hold must NOT re-grant
        ack(r, 2, heartbeat_round(r))
        assert not r.lease_valid()
        # after the hold expires, the next full round re-earns the lease
        while r.tick_count < r.clock_suspect_until:
            r.tick()
            r.msgs.clear()
        ack(r, 2, heartbeat_round(r))
        assert r.lease_valid()

    def test_live_lease_serves_read_locally(self):
        r = mk_leader()
        ack(r, 2, heartbeat_round(r))
        r.handle(Message(type=MT.READ_INDEX, from_=1, hint=7))
        assert r.lease_served == 1 and r.lease_fallback == 0
        assert [rr.system_ctx.low for rr in r.ready_to_read] == [7]
        # no quorum round was opened for the read
        assert not [m for m in r.msgs if m.type == MT.HEARTBEAT]

    def test_expired_lease_falls_back_to_readindex(self):
        r = mk_leader()  # lease never granted
        r.handle(Message(type=MT.READ_INDEX, from_=1, hint=9))
        assert r.lease_served == 0 and r.lease_fallback == 1
        assert r.ready_to_read == []  # quorum confirmation pending
        hbs = [m for m in r.msgs if m.type == MT.HEARTBEAT]
        assert hbs and hbs[0].hint == 9  # the ReadIndex round went out
        # the fallback still completes: quorum of ctx echoes releases it
        r.handle(
            Message(type=MT.HEARTBEAT_RESP, from_=2, to=1, term=r.term,
                    hint=9)
        )
        assert [rr.system_ctx.low for rr in r.ready_to_read] == [9]

    def test_lease_off_heartbeats_carry_no_tag(self):
        r = mk_leader(lease_read=False)
        for _ in range(HEARTBEAT + 1):
            r.tick()
        hbs = [m for m in r.msgs if m.type == MT.HEARTBEAT]
        assert hbs and all(m.log_index == 0 for m in hbs)
        ack(r, 2, 0)
        assert not r.lease_valid() and r.lease_until == 0

    def test_config_rejects_bad_lease_shapes(self):
        def cfg(**kw):
            return Config(node_id=1, cluster_id=1, election_rtt=10,
                          heartbeat_rtt=2, lease_read=True, **kw)

        with pytest.raises(ConfigError):
            cfg(lease_margin_rtt=9).validate()
        with pytest.raises(ConfigError):
            cfg(lease_margin_rtt=-1).validate()
        with pytest.raises(ConfigError):
            cfg(is_witness=True).validate()
        with pytest.raises(ConfigError):
            cfg(is_observer=True).validate()
        cfg().validate()  # margin defaults to heartbeat_rtt: legal
        cfg(lease_margin_rtt=7).validate()  # < election - heartbeat


# --------------------------------------------------------------------------
# kernel: lease-off bit-identity guard + lease-on behavior
# --------------------------------------------------------------------------


def _elect(kc, max_rounds=300):
    for _ in range(max_rounds):
        kc.step()
        kc.settle()
        lead = kc.leader_of(0)
        if lead is not None:
            return lead
    raise AssertionError("no leader elected")


def test_kernel_lease_off_tensors_never_move():
    """Default-off guard: a full election + heartbeat + read workload
    leaves every lease tensor at zero and every heartbeat tag at 0 —
    the off-path is bit-identical to a pre-lease kernel."""
    kc = LoopbackCluster(
        n_replicas=N, n_groups=1, election=ELECTION, heartbeat=HEARTBEAT,
    )
    lead = _elect(kc)
    kc.propose(lead, 0, 2)
    kc.settle()
    kc.read_index(lead, 0, ctx=5)
    for _ in range(3 * HEARTBEAT):
        kc.step()
        kc.settle()
    for h in range(N):
        st = kc.states[h]
        for name in ("lease_on", "lease_until", "hb_round_tick",
                     "hb_ack_bits", "lease_margin"):
            assert not np.asarray(getattr(st, name)).any(), name
        o = kc.last_outputs[h]
        assert not np.asarray(o.lease_round).any()
        assert not np.asarray(o.lease_ok).any()
        assert not np.asarray(o.lease_served).any()
        assert not np.asarray(o.lease_fallback).any()


def test_kernel_lease_grant_and_local_read():
    """Lease ON: the periodic heartbeat round earns the lease from
    quorum acks; a ReadIndex then rides the immediate-ready path (served
    in the SAME step, no quorum round) and the served counter moves."""
    kc = LoopbackCluster(
        n_replicas=N, n_groups=1, election=ELECTION, heartbeat=HEARTBEAT,
        lease_read=True, lease_margin=HEARTBEAT,
    )
    lead = _elect(kc)
    kc.propose(lead, 0, 1)
    kc.settle()
    # run heartbeat rounds until the acks land and the lease is granted
    for _ in range(4 * HEARTBEAT):
        kc.step()
        kc.settle()
        if bool(np.asarray(kc.last_outputs[lead].lease_ok)[0]):
            break
    st = kc.states[lead]
    assert bool(np.asarray(kc.last_outputs[lead].lease_ok)[0])
    assert int(np.asarray(st.lease_until)[0]) > int(np.asarray(st.tick_count)[0])
    margin = int(np.asarray(st.lease_margin)[0])
    round_tick = int(np.asarray(st.hb_round_tick)[0])
    assert int(np.asarray(st.lease_until)[0]) <= round_tick + ELECTION - margin
    kc.ready_reads[lead].clear()
    kc.read_index(lead, 0, ctx=42)
    served_before = 0
    kc.step(tick=False)  # ONE step: no heartbeat round may be needed
    served = int(np.asarray(kc.last_outputs[lead].lease_served)[0])
    assert served == served_before + 1
    assert [ctx for (_g, ctx, _i, _c2) in kc.ready_reads[lead]] == [42]


# --------------------------------------------------------------------------
# kernel differential with leases ON (mirrors test_prevote's structure)
# --------------------------------------------------------------------------


class ScalarLeaseCluster:
    def __init__(self, seed_of_group):
        self.rafts = {}
        for nid in range(1, N + 1):
            r = Raft(
                Config(
                    node_id=nid, cluster_id=1, election_rtt=ELECTION,
                    heartbeat_rtt=HEARTBEAT, lease_read=True,
                ),
                InMemLogDB(),
            )
            for p in range(1, N + 1):
                r.remotes[p] = Remote(next=1)
            slot = nid - 1

            def patched(r=r, slot=slot):
                r.randomized_election_timeout = r.election_timeout + _mix(
                    seed_of_group, r.term, slot
                ) % r.election_timeout

            r.set_randomized_election_timeout = patched
            patched()
            self.rafts[nid] = r
        self.dropped_links = set()
        self.isolated = set()

    def tick_all(self):
        for r in self.rafts.values():
            r.tick()

    def _deliverable(self, m) -> bool:
        f, t = m.from_ - 1, m.to - 1
        if (f, t) in self.dropped_links:
            return False
        return f not in self.isolated and t not in self.isolated

    def settle(self, rounds=20):
        for _ in range(rounds):
            msgs = []
            for r in self.rafts.values():
                msgs.extend(m for m in r.msgs if not is_local_message(m.type))
                r.msgs = []
            if not msgs:
                return
            for m in msgs:
                if m.to in self.rafts and self._deliverable(m):
                    self.rafts[m.to].handle(m)

    def propose(self, nid, n=1):
        self.rafts[nid].handle(
            Message(
                type=MT.PROPOSE, from_=nid,
                entries=[Entry(cmd=b"p%d" % i) for i in range(n)],
            )
        )

    def read(self, nid, ctx):
        self.rafts[nid].handle(
            Message(type=MT.READ_INDEX, from_=nid, hint=ctx)
        )

    def observables(self):
        res = []
        for nid in range(1, N + 1):
            r = self.rafts[nid]
            res.append(
                {
                    "role": int(r.state),
                    "term": r.term,
                    "leader": r.leader_id - 1 if r.leader_id else -1,
                    "committed": r.log.committed,
                    "last": r.log.last_index(),
                    "lease": r.lease_valid(),
                }
            )
        return res

    def lease_counters(self):
        served = sum(r.lease_served for r in self.rafts.values())
        fb = sum(r.lease_fallback for r in self.rafts.values())
        return served, fb


def _kernel_lease_valid(st, g=0):
    return bool(
        np.asarray(st.lease_on)[g]
        and np.asarray(st.clock_ok)[g]
        and int(np.asarray(st.role)[g]) == ROLE.LEADER
        and int(np.asarray(st.tick_count)[g]) < int(np.asarray(st.lease_until)[g])
        and int(np.asarray(st.transfer_to)[g]) == 0
    )


def _kernel_observables(kc, g=0):
    res = []
    for h in range(kc.n_replicas):
        st = kc.states[h]
        res.append(
            {
                "role": int(np.asarray(st.role)[g]),
                "term": int(np.asarray(st.term)[g]),
                "leader": int(np.asarray(st.leader)[g]) - 1,
                "committed": int(np.asarray(st.committed)[g]),
                "last": int(np.asarray(st.last_index)[g]),
                "lease": _kernel_lease_valid(st, g),
            }
        )
    return res


@pytest.mark.parametrize("seed", [5, 23])
def test_differential_lease_randomized_faults(seed):
    """Kernel (lease ON) vs scalar oracle under a seeded schedule of
    link faults, isolation windows, proposals and reads: roles, terms,
    commit state, LEASE VALIDITY and the served/fallback counters must
    agree replica-for-replica after every settled round."""
    import random

    rng = random.Random(seed)
    kc = LoopbackCluster(
        n_replicas=N, n_groups=1, election=ELECTION, heartbeat=HEARTBEAT,
        lease_read=True, lease_margin=HEARTBEAT, seed=0,
    )
    seed_of_group = int(np.asarray(kc.states[0].seed)[0])
    sc = ScalarLeaseCluster(seed_of_group)
    totals = {"served": 0, "fallback": 0}
    orig_step = kc.step

    def counting_step(tick=True):
        orig_step(tick=tick)
        for h in range(N):
            o = kc.last_outputs[h]
            totals["served"] += int(np.asarray(o.lease_served).sum())
            totals["fallback"] += int(np.asarray(o.lease_fallback).sum())

    kc.step = counting_step
    next_ctx = [100]

    def run_round(proposals=0, reads=0):
        kc.step(tick=True)
        kc.settle()
        sc.tick_all()
        sc.settle()
        lead = kc.leader_of(0)
        if lead is not None:
            if proposals:
                kc.propose(lead, 0, proposals)
                sc.propose(lead + 1, proposals)
            for _ in range(reads):
                next_ctx[0] += 1
                kc.read_index(lead, 0, ctx=next_ctx[0])
                sc.read(lead + 1, next_ctx[0])
            if proposals or reads:
                kc.settle()
                sc.settle()

    for step in range(120):
        if rng.random() < 0.08:
            a, b = rng.sample(range(N), 2)
            kc.dropped_links.add((a, b))
            sc.dropped_links.add((a, b))
        if rng.random() < 0.08:
            kc.dropped_links.clear()
            sc.dropped_links.clear()
        if rng.random() < 0.04 and not kc.isolated:
            v = rng.randrange(N)
            kc.isolated.add(v)
            sc.isolated.add(v)
        if rng.random() < 0.10:
            kc.isolated.clear()
            sc.isolated.clear()
        run_round(
            proposals=1 if rng.random() < 0.25 else 0,
            reads=1 if rng.random() < 0.35 else 0,
        )
        ko = _kernel_observables(kc)
        so = sc.observables()
        assert ko == so, f"seed {seed} diverged at step {step}:\n{ko}\n{so}"
        assert (totals["served"], totals["fallback"]) == sc.lease_counters(), (
            f"seed {seed} lease counters diverged at step {step}"
        )
    # the schedule must actually have exercised the lease read path
    assert totals["served"] + totals["fallback"] > 0


# --------------------------------------------------------------------------
# NodeHost: the lease probe API + clock-fault degradation end to end
# --------------------------------------------------------------------------


class _KV(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.d = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        import json

        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        import json

        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, workdir, engine_kind, cp=None, rtt_ms=5):
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=rtt_ms,
            raft_address=f"lease:{nid}",
            nodehost_dir=os.path.join(workdir, f"nh{nid}"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(
                kind=engine_kind, max_groups=8, max_peers=4, log_window=64,
                share_scope="lease-test" if engine_kind == "vector" else None,
            ),
        )
    )
    if cp is not None:
        nh.set_tick_clock(cp.clock_fn(str(nid)))
    return nh


def _start_cluster(hosts, lease_read=True):
    members = {nid: f"lease:{nid}" for nid in hosts}
    for nid, nh in hosts.items():
        nh.start_cluster(
            dict(members), False, lambda c, n: _KV(c, n),
            Config(
                node_id=nid, cluster_id=1, election_rtt=20, heartbeat_rtt=4,
                lease_read=lease_read,
            ),
        )


def _wait(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _leader_host(hosts):
    for nid, nh in hosts.items():
        lid, ok = nh.get_leader_id(1)
        if ok and lid in hosts:
            return lid
    return None


@pytest.mark.parametrize("engine_kind", ["scalar", "vector"])
def test_lease_probe_api_and_fallback(tmp_path, engine_kind):
    """`NodeHost.lease_read` (the explicit lease-only probe): serves off
    a live leader lease, raises the typed ErrLeaseExpired (an
    ErrSystemBusy: transient, retriable) everywhere else — while plain
    sync_read NEVER fails for lease reasons, it just falls back."""
    reg = _Registry()
    hosts = {n: _mk_host(n, reg, str(tmp_path), engine_kind) for n in (1, 2, 3)}
    try:
        _start_cluster(hosts)
        assert _wait(lambda: _leader_host(hosts) is not None)
        lead = _leader_host(hosts)
        sess = hosts[lead].get_noop_session(1)
        hosts[lead].sync_propose(sess, b"k=v", timeout_s=10.0)
        assert _wait(
            lambda: hosts[lead].engine.lease_valid(1), timeout=10.0
        ), "leader never earned its lease from quorum heartbeat acks"
        assert hosts[lead].lease_read(1, "k", timeout_s=10.0) == "v"
        follower = next(n for n in hosts if n != lead)
        with pytest.raises(ErrLeaseExpired) as ei:
            hosts[follower].lease_read(1, "k")
        assert isinstance(ei.value, ErrSystemBusy)
        assert ei.value.retry_after_s > 0
        # the non-probe read path on the same follower degrades, never
        # fails: it rides ReadIndex through the leader
        assert hosts[follower].sync_read(1, "k", timeout_s=10.0) == "v"
    finally:
        for nh in hosts.values():
            nh.stop()


def test_clock_jump_sheds_backlog_and_degrades_lease(tmp_path):
    """A ClockPlane step-jump on the leader's tick clock is detected as
    a clock ANOMALY: the lease goes on suspect hold (reads degrade to
    ReadIndex, still linearizable), the fairness gauge is NOT tripped
    (no phantom stall), and the phantom tick backlog is shed rather
    than burst-replayed through the election timers."""
    reg = _Registry()
    fp = FaultPlane(0xC10C)
    cp = ClockPlane(fp)
    hosts = {
        n: _mk_host(n, reg, str(tmp_path), "scalar", cp=cp) for n in (1, 2, 3)
    }
    try:
        _start_cluster(hosts)
        assert _wait(lambda: _leader_host(hosts) is not None)
        lead = _leader_host(hosts)
        nh = hosts[lead]
        nh.sync_propose(nh.get_noop_session(1), b"k=v1", timeout_s=10.0)
        assert _wait(lambda: nh.engine.lease_valid(1), timeout=10.0)
        ticks_before = nh.engine._nodes[1].peer.raft.tick_count
        term_before = nh.engine._nodes[1].peer.raft.term
        # +5s at rtt 5ms is a 1000-tick phantom backlog; the divergence
        # detector must fire LONG before the burst clamp would matter
        cp.step_jump(str(lead), 5.0)
        assert _wait(lambda: nh._clock_anomalies >= 1, timeout=5.0)
        assert not nh.engine.lease_valid(1)  # suspect hold revoked it
        time.sleep(0.3)
        ticks_after = nh.engine._nodes[1].peer.raft.tick_count
        # backlog shed: tick advance stays wall-clock-ish, nowhere near
        # the 1000 phantom ticks a naive replay would mint
        assert ticks_after - ticks_before < 300
        wd = nh.engine.fairness_stats()
        assert wd["clock_anomalies"] >= 1
        # the phantom gap was discarded from the stall gauge window
        assert wd["recent_max_gap_s"] < 1.0
        # no election was provoked: the quorum never saw a stall
        assert nh.engine._nodes[1].peer.raft.term == term_before
        # reads still linearize (served via ReadIndex fallback)
        assert hosts[lead].sync_read(1, "k", timeout_s=10.0) == "v1"
        # the healed clock re-earns the lease after the suspect hold
        cp.clear(str(lead))
        assert _wait(lambda: nh.engine.lease_valid(1), timeout=10.0)
        assert nh.lease_read(1, "k", timeout_s=10.0) == "v1"
    finally:
        for nh in hosts.values():
            nh.stop()
