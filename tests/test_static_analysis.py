"""Tier-1 gate + meta-tests for `dragonboat_tpu.analysis`.

Two halves:

  * the GATE — the full analyzer over the real `dragonboat_tpu/` tree
    must report zero unsuppressed findings (exactly what
    `python -m dragonboat_tpu.tools.check` enforces, and the CLI itself
    is exercised via subprocess);
  * the META-TESTS — one known-bad snippet per rule family, asserting
    the engine reports exactly the seeded violations (a broken linter
    silently passing everything is worse than no linter — the
    `test_*_catches_regressions` pattern from the legacy embedded lint).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from dragonboat_tpu.analysis import (
    ALL_RULES,
    FAMILIES,
    RULES_VERSION,
    build_analyzer,
    unsuppressed,
)
from dragonboat_tpu.analysis.engine import SourceModule
from dragonboat_tpu.analysis.targets import DEFAULT_TARGETS, Targets

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(snippet: str, relpath: str, families=None):
    a = build_analyzer(families=families)
    return a.run_module(SourceModule.from_snippet(snippet, relpath))


def _ids(findings):
    return sorted(f.rule for f in findings if not f.suppressed)


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def test_tree_has_zero_unsuppressed_findings():
    findings = build_analyzer().run()
    bad = unsuppressed(findings)
    assert not bad, "\n" + "\n".join(f.render() for f in bad)


def test_every_rule_documents_itself():
    for r in ALL_RULES:
        assert r.id and "/" in r.id, r
        assert r.doc, r.id
        assert r.motivation, r.id
    assert len({r.id for r in ALL_RULES}) == len(ALL_RULES)
    # the interprocedural layer (ISSUE 20) is registered, and the rule
    # version reflects it — stored baselines pin WHICH engine judged them
    ids = {r.id for r in ALL_RULES}
    assert {
        "locks/cross-function-order",
        "locks/locked-callee-unheld",
        "locks/blocking-under-hot-lock",
        "retrace/cross-function-taint",
        "device-sync/cross-function",
    } <= ids
    assert RULES_VERSION.startswith("2.")


def test_cli_clean_tree_exits_zero():
    p = subprocess.run(
        [sys.executable, "-m", "dragonboat_tpu.tools.check"],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_flags_seeded_bad_file_per_family(tmp_path):
    """One known-bad snippet per family, written into a file the CLI is
    pointed at under the relpath each family watches — non-zero exit and
    the family's rule id in --json output (the acceptance criterion)."""
    cases = {
        "columnar": (
            "engine/vector.py",
            "class VectorEngine:\n"
            "    def _decode(self, worked, packs, o):\n"
            "        for g in gs:\n"
            "            x = o['term'][g].item()\n",
        ),
        "device-sync": (
            "engine/vector.py",
            "class VectorEngine:\n"
            "    def _decode(self, worked, packs, o):\n"
            "        x = jax.device_get(self._state.term)\n",
        ),
        "retrace": (
            "ops/kernel.py",
            "def step_batch(s, inbox, ticks, cfg):\n"
            "    if s.term > 0:\n"
            "        return s\n",
        ),
        "locks": (
            "transport/transport.py",
            "class _SendQueue:\n"
            "    def put_many(self, msgs):\n"
            "        for m in msgs:\n"
            "            with self._cv:\n"
            "                pass\n",
        ),
        "telemetry": (
            "transport/transport.py",
            "class Transport:\n"
            "    def send_many(self, msgs):\n"
            "        self.metrics.observe('x', (0, 0), 1.0)\n"
            "        flight_recorder().record('evt')\n",
        ),
        "trace": (
            "engine/node.py",
            "class Node:\n"
            "    def propose(self, session, cmd, timeout_ticks):\n"
            "        entry.trace_id = mint_trace_id()\n",
        ),
    }
    for family, (relpath, src) in cases.items():
        root = tmp_path / family
        target = root / relpath
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(src)
        p = subprocess.run(
            [
                sys.executable,
                "-m",
                "dragonboat_tpu.tools.check",
                "--json",
                "--root",
                str(root),
                str(root),
            ],
            cwd=_REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert p.returncode == 1, (family, p.stdout, p.stderr)
        out = json.loads(p.stdout)
        fams = {f["rule"].split("/")[0] for f in out["findings"]}
        assert family in fams, (family, out)


def test_cli_list_rules_renders_table():
    p = subprocess.run(
        [sys.executable, "-m", "dragonboat_tpu.tools.check", "--list-rules"],
        cwd=_REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert p.returncode == 0
    for r in ALL_RULES:
        assert r.id in p.stdout


# ---------------------------------------------------------------------------
# meta-tests: columnar family
# ---------------------------------------------------------------------------


def test_columnar_catches_regressions():
    got = _run(
        """
        def gather_post_sends(o, gs):
            for g in gs.tolist():
                x = int(o['term'][g])
                y = o['match'][g].tolist()
                z = o['vote'][g].item()
        """,
        "engine/vector.py",
        families=("columnar",),
    )
    # iterator .tolist() is the allowed fast idiom; the three loop-body
    # reads are the banned per-element patterns
    assert _ids(got) == [
        "columnar/item-in-loop",
        "columnar/item-in-loop",
        "columnar/scalar-index-in-loop",
    ], got


# ---------------------------------------------------------------------------
# meta-tests: device-sync family
# ---------------------------------------------------------------------------


def test_device_sync_catches_regressions():
    got = _run(
        """
        class VectorEngine:
            def _decode(self, worked, packs, o):
                a = jax.device_get(self._state.term)
                self._state.match.block_until_ready()
                b = int(self._state.last_index[3])
                c = np.asarray(self._state.commit)
                for g in gs:
                    d = self._state.term[g]
        """,
        "engine/vector.py",
        families=("device-sync",),
    )
    assert _ids(got) == [
        "device-sync/device-get",
        "device-sync/device-get",
        "device-sync/host-array",
        "device-sync/index-in-loop",
        "device-sync/scalar-read",
    ], got


def test_device_sync_blessed_seam_stays_allowed():
    got = _run(
        """
        class VectorEngine:
            def _fetch_output(self, out):
                return jax.device_get(out)._asdict()
        """,
        "engine/vector.py",
        families=("device-sync",),
    )
    assert not _ids(got), got


# ---------------------------------------------------------------------------
# meta-tests: retrace family
# ---------------------------------------------------------------------------


def test_retrace_catches_regressions():
    got = _run(
        """
        def step_batch(s, inbox, ticks, cfg):
            if s.term.sum() > 0:
                x = 1
            derived = s.last_index + 1
            while derived > 0:
                pass
            n = int(s.committed)
            m = np.asarray(ticks)
            for k, v in inbox.items():
                pass
        """,
        "ops/kernel.py",
        families=("retrace",),
    )
    assert _ids(got) == [
        "retrace/concretize-traced",
        "retrace/concretize-traced",
        "retrace/dict-iter-in-traced",
        "retrace/python-branch-on-traced",
        "retrace/python-branch-on-traced",
        "retrace/python-branch-on-traced",
    ], got


def test_retrace_static_escapes_stay_allowed():
    # shape/dtype/len are Python values at trace time; branching on them
    # is how shape-specialized kernels are written. `cfg` is static, and
    # identity comparison never reads a traced value.
    got = _run(
        """
        def step_batch(s, inbox, ticks, cfg):
            W = s.log_term.shape[1]
            if W > 8:
                x = 1
            if cfg.peers > 2:
                y = 2
            if len(ticks) > 4:
                z = 3
            for i in range(cfg.peers):
                pass
            def sel(n, o):
                if n is o:
                    return o
        """,
        "ops/kernel.py",
        families=("retrace",),
    )
    assert not _ids(got), got


def test_retrace_taint_flows_out_of_nested_blocks():
    """Fixpoint propagation: an assignment inside a loop body taints
    later top-level uses (ast.walk order is not source order — a single
    pass missed this)."""
    got = _run(
        """
        def step_batch(s, inbox, ticks, cfg):
            for i in range(3):
                y = s.term + i
            z = y
            if z > 0:
                pass
        """,
        "ops/kernel.py",
        families=("retrace",),
    )
    assert _ids(got) == ["retrace/python-branch-on-traced"], got


def test_retrace_scan_body_length_must_be_static():
    """The multi-step engine's K (steps per kernel launch) MUST be a
    compile-time constant: driving the scanned step body off a traced
    length parameter rebuilds the executable per distinct K (or fails
    at trace time). A non-static name taints and flags; the blessed
    spelling — the `steps` static param make_multi_step_fn closes over
    — stays clean (targets.static_param_names carries "steps")."""
    got = _run(
        """
        def multi_step_batch(s, inbox, ticks, cfg, k):
            for _ in range(k):
                s2 = step_batch(s, inbox, ticks, cfg)
            if k > 0:
                pass
        """,
        "ops/kernel.py",
        families=("retrace",),
    )
    assert _ids(got) == [
        "retrace/python-branch-on-traced",
        "retrace/python-branch-on-traced",
    ], got
    got = _run(
        """
        def multi_step_batch(s, inbox, ticks, cfg, steps):
            for _ in range(steps):
                pass
            out = jax.lax.scan(None, s, None, length=steps)
        """,
        "ops/kernel.py",
        families=("retrace",),
    )
    assert not _ids(got), got


def test_retrace_jit_in_hot_function():
    got = _run(
        """
        class VectorEngine:
            def _run_once(self):
                f = jax.jit(lambda s: s)
                g = make_step_fn(self.kcfg)
        """,
        "engine/vector.py",
        families=("retrace",),
    )
    assert _ids(got) == ["retrace/jit-in-hot", "retrace/jit-in-hot"], got


# ---------------------------------------------------------------------------
# meta-tests: locks family
# ---------------------------------------------------------------------------


def test_lock_order_catches_inversion():
    got = _run(
        """
        class _Shard:
            def save(self, ud):
                with self._mu:
                    with self._wmu:
                        pass
            def ok(self, ud):
                with self._wmu:
                    with self._mu:
                        pass
        """,
        "storage/logdb.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/order"], got


def test_lock_order_catches_two_instance_inversion():
    """self._mu then other._mu on another instance of the SAME class is
    the classic AB/BA deadlock (undefined instance order) and must flag
    even though both resolve to one LockSpec."""
    got = _run(
        """
        class Node:
            def transfer(self, node):
                with self._mu:
                    with node._mu:
                        pass
        """,
        "engine/node.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/order"], got
    assert "two instances" in got[0].message


def test_guarded_state_catches_unlocked_writes():
    got = _run(
        """
        class _SendQueue:
            def poke(self, m):
                self._bulk.append(m)
                self._closed = True
                with self._cv:
                    self._urgent.append(m)
            def _admit_locked(self, m):
                self._bulk.append(m)
        """,
        "transport/transport.py",
        families=("locks",),
    )
    # the two unlocked writes in poke(); the with-guarded append and the
    # *_locked-suffix method are both allowed
    assert _ids(got) == [
        "locks/guarded-state",
        "locks/guarded-state",
    ], got


def test_guarded_state_nested_def_does_not_inherit_lock():
    got = _run(
        """
        class _SendQueue:
            def poke(self, m):
                with self._cv:
                    def later():
                        self._bulk.append(m)
        """,
        "transport/transport.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/guarded-state"], got


def test_lock_in_hot_loop_catches_regressions():
    got = _run(
        """
        class _SendQueue:
            def put_many(self, msgs):
                n = 0
                for m in msgs:
                    with self._cv:
                        n += 1
                with self._cv:
                    pass
                return n
        """,
        "transport/transport.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/lock-in-hot-loop"], got


# ---------------------------------------------------------------------------
# meta-tests: telemetry + trace families
# ---------------------------------------------------------------------------


def test_telemetry_catches_regressions():
    got = _run(
        """
        class Transport:
            def send_many(self, msgs):
                for m in msgs:
                    self.metrics.observe('x', (0, 0), 1.0)
                recorder.record('evt', a=1)
                if self.profiler.sampling:
                    self.metrics.observe('x', (0, 0), 1.0)
                if lat_sampler.sample():
                    recorder.record('evt')
        """,
        "transport/transport.py",
        families=("telemetry",),
    )
    assert _ids(got) == [
        "telemetry/unguarded",
        "telemetry/unguarded",
    ], got


def test_trace_stamp_catches_regressions():
    got = _run(
        """
        class Node:
            def propose(self, session, cmd, timeout_ticks):
                entry.trace_id = mint_trace_id()
                recorder.record('propose_enqueue', trace=entry.trace_id)
                if self._req_sampler.sample():
                    entry.trace_id = mint_trace_id()
                    recorder.record('propose_enqueue')
                if entry.trace_id:
                    recorder.record('replicate_send')
        """,
        "engine/node.py",
        families=("trace",),
    )
    # unguarded: the stamp, the mint inside it, and the record
    assert _ids(got) == [
        "trace/unguarded-stamp",
        "trace/unguarded-stamp",
        "trace/unguarded-stamp",
    ], got


def test_profiler_stamp_guard_catches_regressions():
    """PR 6 perf attribution plane: the profiler's stamping seams
    (trace.Profiler.end/add, profile.PhasePlane.on_phase) are watched —
    a phase timer whose Sample.record / histogram observe / recorder
    span lands OUTSIDE the `if self.sampling` gate makes every step pay
    telemetry and is a finding."""
    got = _run(
        """
        class Profiler:
            def end(self, stage):
                s = self.samples[stage]
                s.record(1.0)

            def add(self, stage, dt):
                self.samples[stage].record(dt)
        """,
        "trace.py",
        families=("telemetry",),
    )
    assert _ids(got) == [
        "telemetry/unguarded",
        "telemetry/unguarded",
    ], got
    got = _run(
        """
        class PhasePlane:
            def on_phase(self, engine, phase, dt, sampling):
                h = self._hists[(engine, phase)]
                h.observe(dt)
                recorder.record('phase_span', engine=engine, phase=phase)
        """,
        "profile.py",
        families=("telemetry",),
    )
    assert _ids(got) == [
        "telemetry/unguarded",
        "telemetry/unguarded",
    ], got


def test_profiler_stamp_guarded_stays_clean():
    """The shipped shape — stamps under the sampling gate — is the
    allowed idiom (the real trace.py/profile.py must keep passing)."""
    got = _run(
        """
        class Profiler:
            def end(self, stage):
                if self.sampling and self._t0 is not None:
                    self.samples[stage].record(1.0)
        """,
        "trace.py",
        families=("telemetry",),
    )
    assert _ids(got) == [], got
    got = _run(
        """
        class PhasePlane:
            def on_phase(self, engine, phase, dt, sampling):
                if sampling:
                    self._hists[(engine, phase)].observe(dt)
                    recorder.record('phase_span', engine=engine)
        """,
        "profile.py",
        families=("telemetry",),
    )
    assert _ids(got) == [], got


# ---------------------------------------------------------------------------
# suppression pragmas
# ---------------------------------------------------------------------------


def test_pragma_suppresses_with_reason():
    got = _run(
        """
        class VectorEngine:
            def _decode(self, worked, packs, o):
                for g in gs:
                    x = o['t'][g].item()  # lint: allow(columnar/item-in-loop) rare lane, bounded
        """,
        "engine/vector.py",
        families=("columnar",),
    )
    assert not _ids(got)
    assert len(got) == 1 and got[0].suppressed
    assert "rare lane" in got[0].suppress_reason


def test_standalone_pragma_covers_next_code_line_with_continuation():
    got = _run(
        """
        class VectorEngine:
            def _decode(self, worked, packs, o):
                for g in gs:
                    # lint: allow(columnar) quiesce exit is bounded by the
                    # number of wake events, not messages
                    x = o['t'][g].item()
        """,
        "engine/vector.py",
        families=("columnar",),
    )
    assert not _ids(got)
    assert len(got) == 1 and got[0].suppressed
    assert "wake events" in got[0].suppress_reason


def test_pragma_without_reason_is_itself_a_finding():
    got = _run(
        """
        class VectorEngine:
            def _decode(self, worked, packs, o):
                for g in gs:
                    x = o['t'][g].item()  # lint: allow(columnar/item-in-loop)
        """,
        "engine/vector.py",
        families=("columnar",),
    )
    assert _ids(got) == ["pragma/missing-reason"], got


def test_legacy_hot_path_mark_still_suppresses():
    got = _run(
        """
        class Transport:
            def send_many(self, msgs):
                recorder.record('evt')  # hot-path: ok (anomaly-only)
        """,
        "transport/transport.py",
        families=("telemetry",),
    )
    assert not _ids(got)
    assert len(got) == 1 and got[0].suppressed


def test_unrelated_pragma_does_not_suppress():
    got = _run(
        """
        class VectorEngine:
            def _decode(self, worked, packs, o):
                for g in gs:
                    x = o['t'][g].item()  # lint: allow(locks) wrong family
        """,
        "engine/vector.py",
        families=("columnar",),
    )
    assert _ids(got) == ["columnar/item-in-loop"], got


# ---------------------------------------------------------------------------
# config drift
# ---------------------------------------------------------------------------


def test_missing_target_is_reported(tmp_path):
    """A watched hot function disappearing must surface as a finding, not
    as a silently-unenforced rule (the legacy lint failed the same way)."""
    pkg = tmp_path / "pkg"
    (pkg / "engine").mkdir(parents=True)
    (pkg / "engine" / "vector.py").write_text(
        "class VectorEngine:\n    def _renamed(self):\n        pass\n"
    )
    a = build_analyzer(root=str(pkg))
    findings = a.run()
    drift = [f for f in findings if f.rule == "config/missing-target"]
    assert drift, findings
    assert any("VectorEngine._decode" in f.message for f in drift)


def test_nonexistent_path_fails_loudly():
    """A typo'd path must NOT report a clean gate that checked nothing."""
    findings = build_analyzer().run(["no/such/dir"])
    assert [f.rule for f in findings] == ["config/no-such-path"], findings


def test_relative_paths_resolve_against_package_root():
    """`tools.check engine/` works from any cwd: paths missing from the
    cwd are retried under the analyzer root."""
    findings = build_analyzer().run(["engine"])
    assert not [f for f in findings if f.rule == "config/no-such-path"]


def test_families_cover_issue_contract():
    """The PR contract: four migrated legacy families + three new
    analyzer families, all registered."""
    assert set(FAMILIES) >= {
        "columnar",
        "locks",
        "telemetry",
        "trace",
        "device-sync",
        "retrace",
    }


# ---------------------------------------------------------------------------
# meta-tests: restart-plane targets (ISSUE 7)
# ---------------------------------------------------------------------------


def test_restart_plane_locks_are_declared():
    """The restart plane's shared state is covered by the lock config:
    NodeHost._nodes_mu ranks OUTSIDE every engine/node lock (stop/crash/
    restart take it first, then talk to the engine), and the engine's
    lane free list / g->lane table / route are declared _lanes_mu-guarded."""
    nh = DEFAULT_TARGETS.lock_rank("NodeHost", "_nodes_mu")
    assert nh is not None, "NodeHost._nodes_mu missing from the hierarchy"
    node_mu = DEFAULT_TARGETS.lock_rank("Node", "_mu")
    lanes_mu = DEFAULT_TARGETS.lock_rank("VectorEngine", "_lanes_mu")
    assert nh.rank < node_mu.rank < lanes_mu.rank
    g = DEFAULT_TARGETS.guarded_state
    assert g["nodehost.py"]["NodeHost"]["_launch_specs"] == "_nodes_mu"
    assert g["nodehost.py"]["NodeHost"]["_nodes"] == "_nodes_mu"
    for fld in ("_free", "_lane_by_g", "_route"):
        assert g["engine/vector.py"]["VectorEngine"][fld] == "_lanes_mu"


def test_device_census_targets_are_declared():
    """ISSUE 18: the HBM census plane is covered by the lock config — a
    leaf at the same rank as the other profile singletons, and its
    plane table is declared _mu-guarded so an unlocked write flags."""
    dc = DEFAULT_TARGETS.lock_rank("DeviceCensus", "_mu")
    assert dc is not None, "DeviceCensus._mu missing from the hierarchy"
    cw = DEFAULT_TARGETS.lock_rank("CompileWatch", "_mu")
    assert dc.rank == cw.rank  # leaf rank, alongside the profile peers
    g = DEFAULT_TARGETS.guarded_state
    assert g["profile.py"]["DeviceCensus"]["_planes"] == "_mu"


def test_restart_plane_guarded_state_catches_unlocked_free_list():
    """A lane free-list (or route/launch-spec) mutation outside its lock
    is exactly the double-free / stale-route restart bug class; the
    seeded violations must flag and the locked idiom must stay clean."""
    got = _run(
        """
        class VectorEngine:
            def remove_node(self, key):
                self._free.append(key)
                self._route[key] = None
                with self._lanes_mu:
                    self._free.append(key)
        """,
        "engine/vector.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/guarded-state", "locks/guarded-state"], got
    got = _run(
        """
        class NodeHost:
            def restart_cluster(self, cid):
                self._launch_specs[cid] = ()
            def _detach_cluster(self, cid):
                with self._nodes_mu:
                    self._nodes.pop(cid, None)
        """,
        "nodehost.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/guarded-state"], got


def test_restart_plane_lock_order_nodes_mu_before_node_mu():
    """Restart-vs-step-loop ordering: _nodes_mu is declared OUTER, so
    taking it while holding a node's protocol lock (the inversion a
    restart path deadlocking against the step loop would need) flags."""
    got = _run(
        """
        class NodeHost:
            def bad(self, node):
                with node._mu:
                    with self._nodes_mu:
                        pass
            def good(self, node):
                with self._nodes_mu:
                    pass
                with node._mu:
                    pass
        """,
        "nodehost.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/order"], got


def test_serving_plane_locks_are_declared():
    """ISSUE 8: the serving overload plane's shared state is covered by
    the lock config — front queue table outside the admission ledger
    outside the saturation cache, token bucket + barrier gauge as
    leaves, and all of them INSIDE the host/engine locks the pump path
    releases before calling into."""
    front = DEFAULT_TARGETS.lock_rank("ServingFront", "_mu")
    adm = DEFAULT_TARGETS.lock_rank("AdmissionController", "_mu")
    mon = DEFAULT_TARGETS.lock_rank("SaturationMonitor", "_mu")
    bucket = DEFAULT_TARGETS.lock_rank("TokenBucket", "_mu")
    barrier = DEFAULT_TARGETS.lock_rank("_BarrierStats", "_mu")
    for spec in (front, adm, mon, bucket, barrier):
        assert spec is not None, "serving lock missing from the hierarchy"
    node_mu = DEFAULT_TARGETS.lock_rank("Node", "_mu")
    assert node_mu.rank < front.rank < adm.rank < mon.rank < bucket.rank
    g = DEFAULT_TARGETS.guarded_state
    assert g["serving/front.py"]["ServingFront"]["_queues"] == "_mu"
    assert g["serving/admission.py"]["AdmissionController"]["_tenants"] == "_mu"
    assert g["serving/admission.py"]["TokenBucket"]["tokens"] == "_mu"
    assert g["serving/backpressure.py"]["SaturationMonitor"]["_cached"] == "_mu"
    assert g["storage/kv.py"]["_BarrierStats"]["inflight"] == "_mu"


def test_serving_guarded_state_catches_unlocked_ledger_writes():
    """An admit/shed ledger or tenant-queue mutation outside its lock is
    the lost-increment / torn-decision admission bug class; seeded
    violations must flag and the locked idiom must stay clean."""
    got = _run(
        """
        class AdmissionController:
            def admit(self, tid):
                self._tenants[tid] = object()
                with self._mu:
                    self._tenants[tid] = object()
        class TokenBucket:
            def take(self, n):
                self.tokens -= n
        """,
        "serving/admission.py",
        families=("locks",),
    )
    assert _ids(got) == [
        "locks/guarded-state", "locks/guarded-state",
    ], got
    got = _run(
        """
        class ServingFront:
            def propose(self, tid, op):
                self._queues.setdefault(tid, []).append(op)
            def queue_depths(self):
                with self._mu:
                    return {t: len(q) for t, q in self._queues.items()}
        """,
        "serving/front.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/guarded-state"], got


def test_serving_lock_order_front_inside_node_flags():
    """The pump must NEVER hold the front's queue lock while taking a
    node/host lock ranked outer — that inversion is how a saturated
    engine deadlocks its own shedding path."""
    got = _run(
        """
        class ServingFront:
            def bad(self, node):
                with self._mu:
                    with node._mu:
                        pass
            def good(self, node):
                with node._mu:
                    pass
                with self._mu:
                    pass
        """,
        "serving/front.py",
        families=("locks",),
    )
    assert _ids(got) == ["locks/order"], got
