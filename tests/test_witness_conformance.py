"""Witness conformance matrix (cf. the reference's witness suite,
internal/raft/raft_test.go:724-1010, raft thesis 11.7.2): a witness votes
and counts toward quorum but never campaigns, never holds payloads, can
never leave witness-hood, serves no reads, and receives witness-shaped
(metadata/dummy) replication and snapshots."""
import pytest

from dragonboat_tpu.core.remote import Remote
from dragonboat_tpu.types import (
    EntryType,
    Membership,
    Message,
    MessageType as MT,
    Snapshot,
)
from tests.raft_harness import Network, new_test_raft


def new_witness(node_id=3, full=(1, 2)):
    """A witness raft instance: voting members `full`, self as witness."""
    w = new_test_raft(node_id, [], is_witness=True)
    for p in full:
        w.remotes[p] = Remote(next=1)
    w.witnesses[node_id] = Remote(next=1)
    return w


class TestStateTransitions:
    def test_witness_cannot_become_observer(self):
        w = new_witness()
        with pytest.raises(RuntimeError):
            w.become_observer(1, 1)

    def test_witness_cannot_become_follower(self):
        w = new_witness()
        with pytest.raises(RuntimeError):
            w.become_follower(1, 1)

    def test_witness_cannot_become_candidate(self):
        w = new_witness()
        with pytest.raises(RuntimeError):
            w.become_candidate()

    def test_witness_cannot_be_promoted_to_full_member(self):
        w = new_witness()
        with pytest.raises(RuntimeError):
            w.add_node(w.node_id)

    def test_non_witness_cannot_add_self_as_witness(self):
        r = new_test_raft(1, [1, 2])
        with pytest.raises(RuntimeError):
            r.add_witness(1)


class TestElections:
    def test_witness_never_starts_election(self):
        w = new_witness()
        for _ in range(20 * w.election_timeout):
            w.tick()
        assert w.msgs == []
        assert w.is_witness()

    def test_witness_votes_in_election(self):
        w = new_witness()
        w.handle(Message(type=MT.REQUEST_VOTE, from_=2, to=3, term=100,
                         log_term=100, log_index=100))
        votes = [m for m in w.msgs if m.type == MT.REQUEST_VOTE_RESP]
        assert len(votes) == 1
        assert not votes[0].reject

    def test_witness_counts_toward_commit_quorum(self):
        """1 full member + 1 witness: the witness's ack is required and
        sufficient for commit (quorum of 2)."""
        leader = new_test_raft(1, [1])
        leader.witnesses[3] = Remote(next=1)
        w = new_witness(3, full=(1,))
        net = Network({1: leader, 3: w})
        net.elect(1)
        assert leader.is_leader()
        net.propose(1, b"x")
        assert leader.log.committed == w.log.committed
        assert leader.log.committed >= 2  # noop + proposal


class TestReplication:
    def test_witness_receives_metadata_entries_only(self):
        """Replication toward a witness strips payloads to METADATA
        entries (raft_test.go:833-889 / :991-1010)."""
        leader = new_test_raft(1, [1, 2])
        leader.witnesses[3] = Remote(next=1)
        peer2 = new_test_raft(2, [1, 2])
        peer2.witnesses[3] = Remote(next=1)
        w = new_witness(3)
        net = Network({1: leader, 2: peer2, 3: w})
        net.elect(1)
        net.propose(1, b"payload-bytes")
        ents = w.log.get_entries(1, w.log.last_index() + 1, 1 << 30)
        assert ents, "witness received nothing"
        assert all(e.type == EntryType.METADATA for e in ents)
        assert all(e.cmd == b"" for e in ents)
        # the real members hold the payload
        real = peer2.log.get_entries(1, peer2.log.last_index() + 1, 1 << 30)
        assert any(e.cmd == b"payload-bytes" for e in real)

    def test_witness_accepts_metadata_replicate_directly(self):
        from dragonboat_tpu.types import Entry

        w = new_witness(2, full=(1,))
        m = Message(type=MT.REPLICATE, from_=1, to=2, term=1,
                    log_index=0, log_term=0, commit=0,
                    entries=[Entry(index=i, term=1, type=EntryType.METADATA)
                             for i in (1, 2, 3)])
        w.handle(m)
        assert w.log.last_index() == 3
        assert w.log.committed == 0  # commit follows the leader's commit


class TestSnapshotsAndReads:
    def test_witness_receives_witness_snapshot(self):
        """InstallSnapshot toward a witness applies and acks at the
        snapshot index (raft_test.go:962-989); the leader sends a
        witness-shaped (dummy) image."""
        w = new_witness(3)
        mem = Membership(addresses={1: "a1", 2: "a2"}, witnesses={3: "w3"})
        ss = Snapshot(index=20, term=20, membership=mem, witness=True)
        w.handle(Message(type=MT.INSTALL_SNAPSHOT, from_=1, to=3, term=20,
                         snapshot=ss))
        assert w.log.committed == 20
        resps = [m for m in w.msgs if m.log_index == 20]
        assert resps, f"no snapshot ack at 20 in {w.msgs}"

    def test_leader_sends_witness_shaped_snapshot(self):
        """The snapshot the leader builds FOR a witness is marked witness
        (payload-free) (cf. raft.py _make_witness_snapshot)."""
        leader = new_test_raft(1, [1])
        leader.witnesses[3] = Remote(next=1)
        net = Network({1: leader})
        net.elect(1)
        leader.log.inmem.restore(Snapshot(index=10, term=leader.term))
        m, idx = leader.make_install_snapshot_message(3)
        assert idx == 10
        assert m.snapshot.witness

    def test_witness_ignores_read_index(self):
        """A witness neither serves nor forwards reads: the READ_INDEX is
        dropped outright — no response, no forward to the leader (a
        follower WOULD forward it)."""
        w = new_witness()
        w.set_leader_id(1)
        w.msgs.clear()
        w.handle(Message(type=MT.READ_INDEX, from_=3, to=3,
                         hint=12345, hint_high=1))
        assert w.ready_to_read == []
        assert w.msgs == [], f"witness produced {w.msgs}"
