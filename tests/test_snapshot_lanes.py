"""Snapshot stream caps (VERDICT r3 item 7): per-target + total outbound
lane limits and send/recv bandwidth throttles (cf. reference
internal/transport/lane.go:40-237 + config.go:299-306 StreamConnections /
SnapshotBytesPerSecond)."""
from __future__ import annotations

import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry
from dragonboat_tpu.transport.snapshotstream import RateLimiter
from dragonboat_tpu.types import Message, MessageType, Snapshot


class _SM(IStateMachine):
    def __init__(self, *a):
        self.n = 0

    def update(self, data):
        self.n += 1
        return Result(value=self.n)

    def lookup(self, q):
        return self.n

    def save_snapshot(self, w, fc, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, fc, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def test_rate_limiter_throttles():
    rl = RateLimiter(100_000, burst=10_000)  # 100KB/s, 10KB burst
    rl.acquire(10_000)  # drains the burst instantly
    t0 = time.monotonic()
    rl.acquire(20_000)  # needs ~0.2s of refill
    took = time.monotonic() - t0
    assert took >= 0.15, f"no throttling: {took:.3f}s"


def test_rate_limiter_unlimited_is_free():
    rl = RateLimiter(0)
    t0 = time.monotonic()
    for _ in range(1000):
        rl.acquire(1 << 20)
    assert time.monotonic() - t0 < 0.1


@pytest.fixture
def capped_host(tmp_path):
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        raft_address="lane:1", rtt_millisecond=10,
        nodehost_dir=str(tmp_path / "nh"),
        raft_rpc_factory=lambda a: loopback_factory(a, reg),
        max_snapshot_connections=3,
        max_snapshot_lanes_per_target=2,
        engine=EngineConfig(kind="vector", max_groups=4, max_peers=4,
                            log_window=64),
    ))
    yield nh, reg
    nh.stop()


def test_lane_caps_fail_fast_on_slow_sink(capped_host, tmp_path):
    """A sink that never drains chunks must not accumulate one thread per
    snapshot request: lanes over the cap report failure immediately via
    the snapshot-status path."""
    nh, reg = capped_host
    # a chunk handler that blocks forever = the slow sink
    release = threading.Event()

    def blocked_chunk_handler(chunk):
        release.wait(30)
        return True

    reg.register("lane:sink", lambda batch: None, blocked_chunk_handler)
    nh.transport.nodes.add_node(7, 99, "lane:sink")
    # a real snapshot file so lanes actually stream
    blob = tmp_path / "ss.gbsnap"
    blob.write_bytes(b"z" * 4096)
    statuses = []
    orig = nh._report_snapshot_status
    nh._report_snapshot_status = lambda c, n, f: statuses.append((c, n, f))
    before = threading.active_count()
    for _ in range(10):
        nh._async_send_snapshot(Message(
            type=MessageType.INSTALL_SNAPSHOT, cluster_id=7, to=99, from_=1,
            snapshot=Snapshot(
                cluster_id=7, index=5, term=1,
                filepath=str(blob), file_size=4096,
            ),
        ))
    # per-target cap is 2: at most 2 lanes run; 8 requests failed fast
    time.sleep(0.5)
    after = threading.active_count()
    assert after - before <= 2, f"{after - before} lane threads spawned"
    fails = [s for s in statuses if s[2]]
    assert len(fails) == 8, statuses
    with nh._lane_mu:
        assert nh._lanes_total <= 2
    release.set()
    nh._report_snapshot_status = orig
    # slots drain once the sink unblocks
    t0 = time.monotonic()
    while time.monotonic() - t0 < 10:
        with nh._lane_mu:
            if nh._lanes_total == 0:
                break
        time.sleep(0.05)
    with nh._lane_mu:
        assert nh._lanes_total == 0


def test_send_bandwidth_cap_applies(tmp_path):
    """With a byte/s cap, streaming a multi-chunk snapshot takes at least
    size/rate seconds."""
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        raft_address="bw:1", rtt_millisecond=10,
        nodehost_dir=str(tmp_path / "nh"),
        raft_rpc_factory=lambda a: loopback_factory(a, reg),
        max_snapshot_send_bytes_per_second=64 * 1024,
        engine=EngineConfig(kind="vector", max_groups=4, max_peers=4,
                            log_window=64),
    ))
    try:
        # burst = rate, so ~2x rate bytes need >= ~1s
        got = []
        done = threading.Event()

        def chunk_handler(chunk):
            got.append(chunk.chunk_size)
            if sum(got) >= 128 * 1024:
                done.set()
            return True

        reg.register("bw:sink", lambda batch: None, chunk_handler)
        nh.transport.nodes.add_node(9, 99, "bw:sink")
        blob = tmp_path / "big.gbsnap"
        blob.write_bytes(b"q" * (128 * 1024))
        nh._report_snapshot_status = lambda c, n, f: None
        t0 = time.monotonic()
        nh._async_send_snapshot(Message(
            type=MessageType.INSTALL_SNAPSHOT, cluster_id=9, to=99, from_=1,
            snapshot=Snapshot(
                cluster_id=9, index=5, term=1,
                filepath=str(blob), file_size=128 * 1024,
            ),
        ))
        assert done.wait(30), f"stream incomplete: {sum(got)} bytes"
        took = time.monotonic() - t0
        # 128KB at 64KB/s with a 64KB burst => at least ~0.7s
        assert took >= 0.6, f"bandwidth cap ignored: {took:.2f}s"
    finally:
        nh.stop()
