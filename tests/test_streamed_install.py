"""Streamed snapshot install: offset-resumable chunked transfer, the
typed abort error, and the engine-cadence bound during install.

  * Chunks resume protocol (unit): a mid-stream receiver death loses at
    most the in-flight chunk — the retry skips already-durable chunks
    (no rewrites), truncates a torn tail back to the recorded offset,
    and finalizes a valid image;
  * NodeHost.crash() mid-stream (e2e): the re-streamed install resumes
    from the recorded offset and the group converges (satellite:
    "chunked install resumes from the recorded offset after
    NodeHost.crash() mid-stream");
  * ErrSnapshotStreamAborted: aborted inbound streams open a fail-fast
    window on the receiving node (typed, retry-hinted — not a generic
    timeout) and serving.retry honors the hint;
  * FairnessWatchdog bound: a slow (seconds-long) SM restore does not
    stall the receiving engine's step cadence past 2x the no-install
    baseline.
"""
import json
import os
import threading
import time
import zlib

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import ErrSnapshotStreamAborted, ErrTimeout
from dragonboat_tpu.rsm.snapshotio import SnapshotHeader, SnapshotWriter
from dragonboat_tpu.serving.retry import call_with_retries
from dragonboat_tpu.settings import soft
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.chunks import Chunks
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
from dragonboat_tpu.transport.snapshotstream import (
    load_chunk_data,
    split_snapshot_message,
)
from dragonboat_tpu.types import Membership, Message, MessageType, Snapshot

CLUSTER = 5


class KV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


# --------------------------------------------------------------------------
# Chunks resume protocol (unit, no raft)
# --------------------------------------------------------------------------


class _FakeNH:
    """The minimal nodehost surface Chunks touches."""

    def __init__(self, root):
        self.root = root
        self.delivered = []
        self.acked = []
        self.aborts = []

    def snapshot_dir_root(self):
        return self.root

    def handle_message_batch(self, batch):
        self.delivered.extend(batch.requests)

    def handle_snapshot(self, cluster_id, node_id, from_):
        self.acked.append((cluster_id, node_id, from_))

    def _on_snapshot_stream_aborted(self, cluster_id, node_id, from_, reason):
        self.aborts.append((cluster_id, node_id, from_, reason))


def _make_image(path, index=50, payload=b"x" * (64 * 1024)):
    mem = Membership(addresses={1: "a:1", 2: "a:2"})
    with open(path, "wb") as f:
        with SnapshotWriter(
            f, SnapshotHeader(index=index, term=3, membership=mem),
            session=b"",
        ) as w:
            w.write(payload)
    return mem


def _chunks_for(path, mem, index=50, chunk_size=4096):
    ss = Snapshot(
        filepath=path,
        file_size=os.path.getsize(path),
        index=index,
        term=3,
        membership=mem,
        cluster_id=CLUSTER,
    )
    m = Message(
        type=MessageType.INSTALL_SNAPSHOT, cluster_id=CLUSTER,
        to=2, from_=1, snapshot=ss,
    )
    out = []
    for c in split_snapshot_message(m, chunk_size=chunk_size):
        out.append(load_chunk_data(c, chunk_size=chunk_size))
    return out


def test_chunks_resume_skips_durable_chunks(tmp_path):
    """Receiver dies mid-stream (tracker state lost, disk survives); the
    sender's retry restarts at chunk 0 and the new tracker SKIPS every
    already-durable chunk, finalizing a valid image."""
    img = tmp_path / "src.gbsnap"
    mem = _make_image(str(img))
    chunks = _chunks_for(str(img), mem)
    assert len(chunks) > 8
    nh = _FakeNH(str(tmp_path / "recv"))
    c1 = Chunks(nh)
    cut = len(chunks) // 2
    for c in chunks[:cut]:
        assert c1.add_chunk(c)
    # process death: a NEW tracker (fresh NodeHost) — only disk survives
    c2 = Chunks(nh)
    for c in _chunks_for(str(img), mem):  # sender retry from chunk 0
        assert c2.add_chunk(c)
    st = c2.stats()
    assert st["resumed_streams"] == 1
    assert st["skipped_chunks"] == cut, st
    assert st["completed_streams"] == 1
    # a sender retry of the SAME stream is the resume path, not an
    # abort: no counter bump, no client fail-fast window
    assert st["aborted_streams"] == 0 and nh.aborts == []
    assert len(nh.delivered) == 1
    ss = nh.delivered[0].snapshot
    assert ss.index == 50 and os.path.exists(ss.filepath)
    # the finalized dir must not carry the progress record
    assert not os.path.exists(
        os.path.join(os.path.dirname(ss.filepath), "stream-progress.json")
    )


def test_chunks_resume_truncates_torn_tail(tmp_path):
    """Bytes written past the recorded progress (a torn mid-chunk write)
    are rolled back on resume; the final image still validates."""
    img = tmp_path / "src.gbsnap"
    mem = _make_image(str(img))
    chunks = _chunks_for(str(img), mem)
    nh = _FakeNH(str(tmp_path / "recv"))
    c1 = Chunks(nh)
    cut = 5
    for c in chunks[:cut]:
        assert c1.add_chunk(c)
    # torn tail: half a chunk of garbage beyond the recorded offset
    part_dirs = []
    for root, dirs, files in os.walk(nh.root):
        for f in files:
            if f.endswith(".gbsnap"):
                part_dirs.append(os.path.join(root, f))
    assert part_dirs
    with open(part_dirs[0], "ab") as f:
        f.write(b"\xde\xad" * 1000)
    c2 = Chunks(nh)
    for c in _chunks_for(str(img), mem):
        assert c2.add_chunk(c)
    assert c2.stats()["completed_streams"] == 1
    assert len(nh.delivered) == 1  # finalize validated the image


def test_chunks_incompatible_partial_starts_clean(tmp_path):
    """A surviving partial of a DIFFERENT stream shape (other term) is
    discarded, not resumed."""
    img = tmp_path / "src.gbsnap"
    mem = _make_image(str(img))
    nh = _FakeNH(str(tmp_path / "recv"))
    c1 = Chunks(nh)
    for c in _chunks_for(str(img), mem)[:4]:
        assert c1.add_chunk(c)
    # same index, different term -> incompatible
    img2 = tmp_path / "src2.gbsnap"
    mem2 = _make_image(str(img2))
    chunks2 = _chunks_for(str(img2), mem2)
    for c in chunks2:
        c.term = 9
    c2 = Chunks(nh)
    for c in chunks2:
        assert c2.add_chunk(c)
    st = c2.stats()
    assert st["resumed_streams"] == 0 and st["completed_streams"] == 1


def test_chunks_validation_failure_purges_partial(tmp_path):
    """A stream whose assembled image fails validation must NOT leave a
    resumable partial behind: the retry would skip past every (corrupt)
    chunk and re-fail forever. The purge forces a clean re-transfer,
    which then succeeds."""
    img = tmp_path / "src.gbsnap"
    mem = _make_image(str(img))
    nh = _FakeNH(str(tmp_path / "recv"))
    ch = Chunks(nh)
    bad = _chunks_for(str(img), mem)
    # corrupt a mid-stream chunk's payload (sizes preserved)
    bad[3].data = bytes(len(bad[3].data))
    for c in bad[:-1]:
        assert ch.add_chunk(c)
    assert not ch.add_chunk(bad[-1])  # finalize fails validation
    assert ch.stats()["aborted_streams"] == 1
    # the corrupt partial is GONE: the clean retry starts fresh and lands
    for c in _chunks_for(str(img), mem):
        assert ch.add_chunk(c)
    st = ch.stats()
    assert st["resumed_streams"] == 0 and st["skipped_chunks"] == 0
    assert st["completed_streams"] == 1
    assert len(nh.delivered) == 1


def test_chunks_abort_notifies_nodehost(tmp_path):
    """A dropped stream (chunk gap) reports through the abort hook with a
    reason — the seam the typed client error hangs off."""
    img = tmp_path / "src.gbsnap"
    mem = _make_image(str(img))
    chunks = _chunks_for(str(img), mem)
    nh = _FakeNH(str(tmp_path / "recv"))
    ch = Chunks(nh)
    assert ch.add_chunk(chunks[0])
    assert not ch.add_chunk(chunks[3])  # gap -> stream dropped
    assert ch.stats()["aborted_streams"] == 1
    assert nh.aborts and nh.aborts[0][0] == CLUSTER
    assert nh.aborts[0][3] == "out_of_order"


# --------------------------------------------------------------------------
# typed abort error
# --------------------------------------------------------------------------


def test_err_snapshot_stream_aborted_fails_reads_fast(tmp_path):
    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=3, rtt_millisecond=5, raft_address="sa1:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=32, max_peers=4, log_window=64
            ),
        )
    )
    try:
        nh.start_cluster(
            {1: "sa1:1"}, False, lambda c, n: KV(),
            Config(cluster_id=CLUSTER, node_id=1, election_rtt=20,
                   heartbeat_rtt=4),
        )
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            lid, ok = nh.get_leader_id(CLUSTER)
            if ok:
                break
            time.sleep(0.02)
        node = nh._get_node(CLUSTER)
        # an install stream this replica needed aborted: reads fail fast
        # with the typed, retry-hinted error for the re-stream window
        node.notify_install_aborted(retry_after_s=1.5)
        with pytest.raises(ErrSnapshotStreamAborted) as ei:
            nh.read_index(CLUSTER, timeout_s=2.0)
        assert ei.value.retry_after_s == 1.5
        # restore completed: ops flow again
        node.clear_install_aborted()
        rs = nh.read_index(CLUSTER, timeout_s=5.0)
        assert rs.wait(5.0).completed
    finally:
        nh.stop()


def test_call_with_retries_honors_abort_hint():
    """ErrSnapshotStreamAborted is ErrSystemBusy-family: retried, with
    the server hint as the backoff floor."""
    clock = [0.0]
    sleeps = []

    def fake_clock():
        return clock[0]

    def fake_sleep(s):
        sleeps.append(s)
        clock[0] += s

    calls = [0]

    def fn(remaining):
        calls[0] += 1
        if calls[0] == 1:
            raise ErrSnapshotStreamAborted(retry_after_s=0.4)
        return "ok"

    out = call_with_retries(
        fn, 10.0, clock=fake_clock, sleep=fake_sleep
    )
    assert out == "ok" and calls[0] == 2
    assert sleeps and sleeps[0] >= 0.4  # hint floored the backoff

    # a hint past the deadline raises ErrTimeout without sleeping
    calls[0] = 0
    sleeps.clear()

    def fn2(remaining):
        raise ErrSnapshotStreamAborted(retry_after_s=99.0)

    with pytest.raises(ErrTimeout):
        call_with_retries(fn2, 1.0, clock=fake_clock, sleep=fake_sleep)
    assert sleeps == []


# --------------------------------------------------------------------------
# e2e: crash mid-stream, resume from the recorded offset
# --------------------------------------------------------------------------


def _mk_host(nid, reg, run_dir, recv_rate=0):
    return NodeHost(
        NodeHostConfig(
            deployment_id=6,
            rtt_millisecond=5,
            nodehost_dir=os.path.join(run_dir, f"h{nid}"),
            raft_address=f"si{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            max_snapshot_recv_bytes_per_second=recv_rate,
            engine=EngineConfig(
                kind="vector", max_groups=32, max_peers=4, log_window=64
            ),
        )
    )


def _grp_cfg(nid):
    # pre_vote + check_quorum (the canonical pairing): the poll keeps a
    # rejoiner's term from inflating, and the leader LEASE refuses polls
    # from a live quorum's members — without the lease, a load-delayed
    # heartbeat lets an up-to-date follower win a poll and legally move
    # leadership mid-test (observed on the 2-cpu box). Election timeouts
    # are generous for the same reason: a whole-host crash teardown can
    # starve the surviving pair for ~100ms.
    return Config(
        cluster_id=CLUSTER, node_id=nid, election_rtt=60, heartbeat_rtt=10,
        snapshot_entries=20, compaction_overhead=5, pre_vote=True,
        check_quorum=True,
    )


@pytest.mark.slow
def test_install_resumes_after_host_crash_mid_stream(tmp_path, monkeypatch):
    """The satellite verdict: a lagging member rejoining via snapshot
    install loses its HOST (NodeHost.crash) mid-stream; after restart the
    re-streamed install RESUMES from the receiver's recorded offset
    (skipped chunks > 0) and the group converges."""
    monkeypatch.setattr(soft, "sent_snapshot_chunk_size", 8 * 1024)
    reg = _Registry()
    members = {n: f"si{n}:1" for n in (1, 2, 3)}
    # the victim throttles its receive side so the stream reliably spans
    # the crash point
    hosts = {
        n: _mk_host(n, reg, str(tmp_path), recv_rate=150_000 if n == 3 else 0)
        for n in (1, 2, 3)
    }
    try:
        for n in (1, 2, 3):
            hosts[n].start_cluster(members, False, lambda c, n_: KV(), _grp_cfg(n))
        deadline = time.monotonic() + 30
        leader = None
        while leader is None and time.monotonic() < deadline:
            for n in (1, 2, 3):
                lid, ok = hosts[n].get_leader_id(CLUSTER)
                if ok and lid == n:
                    leader = n
                    break
            time.sleep(0.02)
        assert leader is not None and leader != 3 or True
        if leader == 3:
            hosts[leader].request_leader_transfer(CLUSTER, 1)
            time.sleep(0.5)
            leader = 1
        # victim node goes down; traffic makes its log unreachable
        hosts[3].crash_cluster(CLUSTER)
        s = hosts[leader].get_noop_session(CLUSTER)
        blob = "b" * 4096
        for i in range(60):
            hosts[leader].sync_propose(
                s, f"big{i}={blob}".encode(), timeout_s=5.0
            )
        # snapshot BOTH live members AT THE SAME applied index: whoever
        # streams (should leadership still move under load) then serves
        # the identical image, so the retry resumes the same stream
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            idx = {
                n: hosts[n].get_applied_index(CLUSTER) for n in (1, 2)
            }
            if len(set(idx.values())) == 1:
                break
            time.sleep(0.05)
        for n in (1, 2):
            hosts[n].sync_request_snapshot(CLUSTER, timeout_s=10.0)
        # rejoin -> install stream starts (slow, throttled)
        hosts[3].restart_cluster(CLUSTER)
        # wait for the stream to make SOME durable progress, then kill
        # the whole receiving host mid-stream
        part_root = hosts[3].snapshot_dir_root()
        deadline = time.monotonic() + 30
        started = False
        while time.monotonic() < deadline:
            for root, dirs, files in os.walk(part_root):
                if "stream-progress.json" in files:
                    started = True
            if started:
                break
            time.sleep(0.05)
        assert started, "install stream never started"
        hosts[3].crash()
        hosts[3] = _mk_host(3, reg, str(tmp_path), recv_rate=0)
        hosts[3].start_cluster(members, False, lambda c, n_: KV(), _grp_cfg(3))
        # the re-streamed install resumes from the recorded offset and
        # the group converges
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            st = hosts[3]._chunks.stats()
            if st["resumed_streams"] >= 1 and st["completed_streams"] >= 1:
                break
            time.sleep(0.1)
        st = hosts[3]._chunks.stats()
        assert st["resumed_streams"] >= 1, st
        assert st["skipped_chunks"] > 0, st
        want = hosts[leader].get_sm_hash(CLUSTER)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if hosts[3].get_sm_hash(CLUSTER) == want:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        assert hosts[3].get_sm_hash(CLUSTER) == want, "rejoiner diverged"
    finally:
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass


# --------------------------------------------------------------------------
# engine cadence during install
# --------------------------------------------------------------------------

_SLOW_RECOVER = {"sleep": 0.0}


class SlowKV(KV):
    def recover_from_snapshot(self, r, files, done):
        if _SLOW_RECOVER["sleep"]:
            time.sleep(_SLOW_RECOVER["sleep"])
        super().recover_from_snapshot(r, files, done)


def test_install_does_not_stall_engine_cadence(tmp_path):
    """The watchdog bound: while one lane's snapshot restore takes
    SECONDS, the engine's step cadence (FairnessWatchdog recent_max_gap)
    stays under 2x the no-install baseline — the install runs off the
    step loop (record persist + SM rebuild both on the snapshot worker)."""
    _SLOW_RECOVER["sleep"] = 0.0
    reg = _Registry()
    members = {n: f"si{n}:1" for n in (1, 2, 3)}
    hosts = {n: _mk_host(n, reg, str(tmp_path)) for n in (1, 2, 3)}
    try:
        for n in (1, 2, 3):
            hosts[n].start_cluster(
                members, False, lambda c, n_: SlowKV(), _grp_cfg(n)
            )
        deadline = time.monotonic() + 30
        leader = None
        while leader is None and time.monotonic() < deadline:
            for n in (1, 2, 3):
                lid, ok = hosts[n].get_leader_id(CLUSTER)
                if ok and lid == n:
                    leader = n
                    break
            time.sleep(0.02)
        assert leader is not None
        victim = 2 if leader != 2 else 3
        s = hosts[leader].get_noop_session(CLUSTER)
        # ---- no-install baseline window on the victim's engine --------
        wd = hosts[victim].engine.watchdog
        wd.reset_window()
        for i in range(30):
            hosts[leader].sync_propose(s, f"k{i}=v{i}".encode(), 5.0)
        baseline = max(wd.stats()["recent_max_gap_s"], 0.02)
        # ---- lag the victim, force the install path --------------------
        hosts[victim].crash_cluster(CLUSTER)
        for i in range(40):
            hosts[leader].sync_propose(s, f"l{i}=w{i}".encode(), 5.0)
        hosts[leader].sync_request_snapshot(CLUSTER, timeout_s=10.0)
        _SLOW_RECOVER["sleep"] = 3.0
        wd.reset_window()
        hosts[victim].restart_cluster(CLUSTER)
        # wait out the (slow) install
        want = hosts[leader].get_sm_hash(CLUSTER)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if hosts[victim].get_sm_hash(CLUSTER) == want:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        assert hosts[victim].get_sm_hash(CLUSTER) == want
        gap = hosts[victim].engine.fairness_stats()["recent_max_gap_s"]
        bound = max(2 * baseline, 1.0)  # CI noise floor; recover sleeps 3s
        assert gap < bound, (
            f"engine stalled during install: gap={gap:.3f}s "
            f"baseline={baseline:.3f}s bound={bound:.3f}s"
        )
    finally:
        _SLOW_RECOVER["sleep"] = 0.0
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass


# --------------------------------------------------------------------------
# Leader-side SNAPSHOT parking recovery (regression)
# --------------------------------------------------------------------------


def test_parked_snapshot_remote_unwedges_without_receiver_ack(tmp_path):
    """Regression: a streamed install whose receiver host dies after the
    chunks leave the sender produces neither a transport failure (the
    SnapshotLane completed cleanly) nor a SNAPSHOT_RECEIVED ack (the
    receiver is gone). The scalar leader's Remote used to park in
    RemoteState.SNAPSHOT forever — is_paused() blocks replication and no
    heartbeat response can move a SNAPSHOT-state remote — so the rejoiner
    was never replicated to again (longhaul streamed_install_under_crash
    hit this as a convergence stall). Node._snapshot_feedback must feed a
    synthetic rejected SnapshotStatus past the retry window, mirroring
    the vector engine's _run_snapshot_feedback."""
    from dragonboat_tpu.core.remote import Remote, RemoteState

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=6,
            rtt_millisecond=2,
            nodehost_dir=os.path.join(str(tmp_path), "h1"),
            raft_address="wedge1:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4),
        )
    )
    try:
        nh.start_cluster(
            {1: "wedge1:1"},
            False,
            lambda c, n_: KV(),
            Config(
                cluster_id=CLUSTER, node_id=1,
                election_rtt=10, heartbeat_rtt=2,
            ),
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            lid, ok = nh.get_leader_id(CLUSTER)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        node = nh.engine._nodes[CLUSTER]
        r = node.peer.raft
        assert r.is_leader()
        # park a phantom follower exactly as _send_snapshot_message
        # leaves it after handing the stream to the transport
        rm = Remote(match=0, next=1)
        rm.become_snapshot(100)
        r.remotes[99] = rm
        assert rm.state == RemoteState.SNAPSHOT
        # retry window: max(4 * election_rtt, 16) = 40 ticks at 2ms rtt;
        # the node's own LOCAL_TICK stream must un-park it with no
        # receiver ack and no transport failure ever arriving
        deadline = time.monotonic() + 10
        while rm.state == RemoteState.SNAPSHOT and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rm.state != RemoteState.SNAPSHOT, (
            "leader remote stayed parked in SNAPSHOT past the retry "
            "window with no ack/failure feedback"
        )
        assert rm.snapshot_index == 0  # rejected status clears the pending
    finally:
        nh.stop()
