"""Protocol conformance tests for the scalar Raft core.

Scenarios are modeled on the reference's ported etcd suites
(internal/raft/raft_etcd_test.go, raft_etcd_paper_test.go) — each test notes
the Raft paper/thesis behavior it validates.
"""
import random

import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft, RaftNodeState
from dragonboat_tpu.types import Entry, EntryType, Message, MessageType, SystemCtx

from dragonboat_tpu.core.remote import Remote

from raft_harness import Network, make_cluster, new_test_raft

MT = MessageType
F, C, L = RaftNodeState.FOLLOWER, RaftNodeState.CANDIDATE, RaftNodeState.LEADER


def tick_until_election(r: Raft):
    for _ in range(2 * r.election_timeout):
        r.tick()


# ---------------------------------------------------------------- elections


def test_initial_state_is_follower():
    r = new_test_raft(1, [1, 2, 3])
    assert r.state == F
    assert r.term == 0


def test_follower_starts_election_after_timeout():
    """Paper section 5.2: follower campaigns when election timeout elapses."""
    r = new_test_raft(1, [1, 2, 3])
    tick_until_election(r)
    assert r.state == C
    assert r.term == 1
    assert r.vote == 1
    vote_reqs = [m for m in r.msgs if m.type == MT.REQUEST_VOTE]
    assert {m.to for m in vote_reqs} == {2, 3}
    assert all(m.term == 1 for m in vote_reqs)


def test_single_node_becomes_leader_immediately():
    r = new_test_raft(1, [1])
    tick_until_election(r)
    assert r.state == L
    # noop entry appended on promotion (thesis p72)
    assert r.log.last_index() == 1


def test_leader_election_in_three_node_cluster():
    nt = make_cluster(3)
    nt.elect(1)
    assert nt.rafts[1].state == L
    assert nt.rafts[2].state == F
    assert nt.rafts[3].state == F
    assert all(r.term == 1 for r in nt.rafts.values())
    assert all(r.leader_id == 1 for r in nt.rafts.values())


def test_election_with_isolated_majority_fails():
    nt = make_cluster(3)
    nt.isolate(2)
    nt.isolate(3)
    nt.elect(1)
    assert nt.rafts[1].state == C  # no quorum of votes


def test_vote_granted_once_per_term():
    """Paper section 5.2: at most one vote per term, first-come-first-served."""
    r = new_test_raft(1, [1, 2, 3])
    r.handle(Message(type=MT.REQUEST_VOTE, from_=2, to=1, term=1, log_index=0, log_term=0))
    resp = r.msgs[-1]
    assert resp.type == MT.REQUEST_VOTE_RESP and not resp.reject
    assert r.vote == 2
    r.handle(Message(type=MT.REQUEST_VOTE, from_=3, to=1, term=1, log_index=0, log_term=0))
    resp = r.msgs[-1]
    assert resp.reject  # already voted for 2 this term


def test_vote_rejected_for_stale_log():
    """Paper section 5.4.1: candidate with less up-to-date log is rejected."""
    nt = make_cluster(3)
    nt.elect(1)
    nt.propose(1)
    # node 1 and followers have entries; a fresh candidate with empty log at
    # a higher term must not win votes from up-to-date peers
    r2 = nt.rafts[2]
    r2.handle(
        Message(type=MT.REQUEST_VOTE, from_=9, to=2, term=5, log_index=0, log_term=0)
    )
    resp = [m for m in r2.msgs if m.type == MT.REQUEST_VOTE_RESP][-1]
    assert resp.reject


def test_candidate_steps_down_on_leader_heartbeat():
    """Paper section 5.2 paragraph 4: candidate reverts to follower when it
    receives Heartbeat/Replicate from a current-term leader."""
    r = new_test_raft(1, [1, 2, 3])
    tick_until_election(r)
    assert r.state == C
    r.handle(Message(type=MT.HEARTBEAT, from_=2, to=1, term=1, commit=0))
    assert r.state == F
    assert r.leader_id == 2


def test_higher_term_message_converts_to_follower():
    """Paper section 5.1: stale term => update term, become follower."""
    nt = make_cluster(3)
    nt.elect(1)
    r1 = nt.rafts[1]
    r1.handle(Message(type=MT.HEARTBEAT, from_=3, to=1, term=10))
    assert r1.state == F
    assert r1.term == 10


def test_candidate_becomes_follower_on_majority_rejection():
    r = new_test_raft(1, [1, 2, 3])
    tick_until_election(r)
    r.msgs.clear()
    r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=2, to=1, term=1, reject=True))
    r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=3, to=1, term=1, reject=True))
    assert r.state == F


def test_disruption_defense_drops_high_term_request_vote():
    """Paper section 6 last paragraph: with check-quorum, a node that has
    heard from a live leader recently ignores higher-term RequestVote."""
    nt = make_cluster(3)
    for r in nt.rafts.values():
        r.check_quorum = True
    nt.elect(1)
    # heartbeat establishes leader recency on node 2
    nt.send(Message(type=MT.LEADER_HEARTBEAT, to=1, from_=1))
    r2 = nt.rafts[2]
    term_before = r2.term
    r2.handle(
        Message(type=MT.REQUEST_VOTE, from_=3, to=2, term=term_before + 5,
                log_index=10, log_term=10)
    )
    assert r2.term == term_before  # dropped, no term bump


def test_leader_transfer_hint_bypasses_disruption_defense():
    nt = make_cluster(3)
    for r in nt.rafts.values():
        r.check_quorum = True
    nt.elect(1)
    r2 = nt.rafts[2]
    term = r2.term
    # hint == from marks a sanctioned leadership-transfer election (thesis p42)
    r2.handle(
        Message(type=MT.REQUEST_VOTE, from_=3, to=2, term=term + 1,
                log_index=100, log_term=term, hint=3)
    )
    assert r2.term == term + 1


# ---------------------------------------------------------------- replication


def test_proposal_replicates_and_commits():
    nt = make_cluster(3)
    nt.elect(1)
    nt.propose(1, b"hello")
    lead = nt.rafts[1]
    # noop(1) + proposal(2)
    assert lead.log.committed == 2
    for r in nt.rafts.values():
        assert r.log.committed == 2
        ents = r.log.get_entries(2, 3, 1 << 30)
        assert ents[0].cmd == b"hello"


def test_proposal_forwarded_by_follower():
    nt = make_cluster(3)
    nt.elect(1)
    nt.propose(2, b"via-follower")
    assert nt.rafts[1].log.committed == 2


def test_proposal_dropped_without_leader():
    r = new_test_raft(1, [1, 2, 3])
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    assert len(r.dropped_entries) == 1


def test_old_term_entries_not_committed_by_counting():
    """Paper section 5.4.2 / figure 8: leader only commits entries from its
    own term by counting replicas."""
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    # append an entry at term 1 that does NOT replicate (drop all)
    nt.isolate(2)
    nt.isolate(3)
    nt.propose(1, b"stranded")
    assert lead.log.committed == 1  # only the noop
    nt.heal()
    # network partitions heal; node 2 becomes leader at term 2
    nt.elect(2)
    assert nt.rafts[2].state == L
    # old leader rejoins as follower, its stranded entry is overwritten
    nt.propose(2, b"new-term")
    assert nt.rafts[2].log.committed >= 3
    for r in nt.rafts.values():
        assert r.log.committed == nt.rafts[2].log.committed


def test_log_conflict_resolution():
    """Paper section 5.3: follower's conflicting suffix is overwritten."""
    nt = make_cluster(3)
    nt.elect(1)
    nt.isolate(3)
    for i in range(3):
        nt.propose(1, b"a%d" % i)
    nt.heal()
    # catch node 3 up via heartbeat-triggered replicate
    nt.send(Message(type=MT.LEADER_HEARTBEAT, to=1, from_=1))
    r3 = nt.rafts[3]
    assert r3.log.committed == nt.rafts[1].log.committed
    ents3 = r3.log.get_entries(2, r3.log.committed + 1, 1 << 30)
    ents1 = nt.rafts[1].log.get_entries(2, r3.log.committed + 1, 1 << 30)
    assert [e.cmd for e in ents3] == [e.cmd for e in ents1]


def test_commit_advances_with_quorum_only():
    nt = make_cluster(5)
    nt.elect(1)
    nt.isolate(4)
    nt.isolate(5)
    nt.propose(1, b"q")  # 3/5 still a quorum
    assert nt.rafts[1].log.committed == 2
    nt.isolate(3)
    nt.propose(1, b"no-quorum")
    assert nt.rafts[1].log.committed == 2  # 2/5 is not a quorum


def test_follower_commit_capped_by_replicate_window():
    r = new_test_raft(2, [1, 2, 3])
    ents = [Entry(index=1, term=1, cmd=b"a"), Entry(index=2, term=1, cmd=b"b")]
    r.handle(
        Message(type=MT.REPLICATE, from_=1, to=2, term=1, log_index=0,
                log_term=0, entries=ents, commit=100)
    )
    # commit index must not exceed what this follower actually holds
    assert r.log.committed == 2


def test_heartbeat_commit_capped_by_match():
    """Heartbeat carries commit=min(match, committed) so a lagging follower
    never learns a commit index beyond its log (raft.go:810-816)."""
    nt = make_cluster(3)
    nt.elect(1)
    nt.isolate(3)
    nt.propose(1, b"x")
    nt.heal()
    lead = nt.rafts[1]
    lead.msgs.clear()
    lead.handle(Message(type=MT.LEADER_HEARTBEAT, from_=1, to=1))
    hb3 = [m for m in lead.msgs if m.type == MT.HEARTBEAT and m.to == 3][0]
    assert hb3.commit <= nt.rafts[3].log.last_index()


def test_stale_replicate_resp_ignored():
    nt = make_cluster(3)
    nt.elect(1)
    nt.propose(1, b"x")
    lead = nt.rafts[1]
    match_before = lead.remotes[2].match
    lead.handle(
        Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=lead.term, log_index=0)
    )
    assert lead.remotes[2].match == match_before


def test_duplicate_replicate_is_idempotent():
    r = new_test_raft(2, [1, 2, 3])
    ents = [Entry(index=1, term=1, cmd=b"a")]
    m = Message(type=MT.REPLICATE, from_=1, to=2, term=1, log_index=0,
                log_term=0, entries=list(ents), commit=1)
    r.handle(m)
    li = r.log.last_index()
    r.handle(
        Message(type=MT.REPLICATE, from_=1, to=2, term=1, log_index=0,
                log_term=0, entries=list(ents), commit=1)
    )
    assert r.log.last_index() == li


def test_rejected_replicate_decrements_next():
    """Paper section 5.3: leader decrements nextIndex on rejection and
    retries."""
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    r2 = nt.rafts[2]
    # while node 2 is unreachable the leader keeps optimistically advancing
    # next (pipelining); node 2's log stays short
    nt.isolate(2)
    for i in range(3):
        nt.propose(1, b"m%d" % i)
    nt.heal()
    assert lead.remotes[2].next > r2.log.last_index() + 1
    lead.msgs.clear()
    lead.send_replicate_message(2)
    msg = lead.msgs[-1]
    assert msg.type == MT.REPLICATE
    assert msg.log_index > r2.log.last_index()
    r2.handle(msg)
    resp = [m for m in r2.msgs if m.type == MT.REPLICATE_RESP][-1]
    assert resp.reject
    assert resp.hint == r2.log.last_index()
    lead.handle(resp)
    assert lead.remotes[2].next <= r2.log.last_index() + 1
    nt.deliver_all()
    # after retry node 2 converges
    assert r2.log.last_index() == lead.log.last_index()


# ---------------------------------------------------------------- check quorum


def test_check_quorum_leader_steps_down():
    """Thesis p69: leader steps down when it cannot reach a quorum."""
    nt = make_cluster(3)
    for r in nt.rafts.values():
        r.check_quorum = True
    nt.elect(1)
    lead = nt.rafts[1]
    # no responses arrive; after election_timeout ticks the check fires
    for _ in range(lead.election_timeout + 1):
        lead.tick()
        lead.msgs.clear()
    # first check: remotes were marked active at election; one more period
    for _ in range(lead.election_timeout + 1):
        lead.tick()
        lead.msgs.clear()
    assert lead.state == F


def test_check_quorum_leader_stays_with_active_followers():
    nt = make_cluster(3)
    for r in nt.rafts.values():
        r.check_quorum = True
    nt.elect(1)
    lead = nt.rafts[1]
    for _ in range(3 * lead.election_timeout):
        lead.tick()
        for m in lead.msgs:
            if m.to in nt.rafts and m.type == MT.HEARTBEAT:
                nt.rafts[m.to].handle(m)
        lead.msgs.clear()
        for nid in (2, 3):
            for m in nt.rafts[nid].msgs:
                if m.to == 1:
                    lead.handle(m)
            nt.rafts[nid].msgs.clear()
    assert lead.state == L


# ---------------------------------------------------------------- read index


def test_read_index_single_node():
    r = new_test_raft(1, [1])
    tick_until_election(r)
    assert r.state == L
    ctx = SystemCtx(low=7, high=9)
    r.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=7, hint_high=9))
    assert len(r.ready_to_read) == 1
    assert r.ready_to_read[0].system_ctx == ctx


def test_read_index_quorum_confirmation():
    """Thesis section 6.4: leader confirms leadership via heartbeat quorum
    before releasing the read index."""
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=42, hint_high=0))
    assert not lead.ready_to_read  # not confirmed yet
    hb = [m for m in lead.msgs if m.type == MT.HEARTBEAT and m.hint == 42]
    assert len(hb) == 2
    nt.deliver_all()
    assert len(lead.ready_to_read) == 1
    assert lead.ready_to_read[0].index == lead.log.committed


def test_read_index_dropped_without_current_term_commit():
    """Thesis 6.4 step 1: leader must have committed in its term first."""
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    # fake situation: bump term without committing in it
    lead.become_follower(lead.term + 1, 0)
    lead.state = RaftNodeState.CANDIDATE
    lead.state = RaftNodeState.LEADER
    lead._reset(lead.term)
    lead.set_leader_id(lead.node_id)
    lead.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=5))
    assert len(lead.dropped_read_indexes) == 1


def test_follower_read_index_forwarded_to_leader():
    nt = make_cluster(3)
    nt.elect(1)
    r2 = nt.rafts[2]
    r2.handle(Message(type=MT.READ_INDEX, from_=2, to=2, hint=11, hint_high=3))
    fwd = [m for m in r2.msgs if m.type == MT.READ_INDEX]
    assert fwd and fwd[0].to == 1
    nt.deliver_all()
    # leader confirmed with quorum, follower got ReadIndexResp
    assert any(rtr.system_ctx.low == 11 for rtr in r2.ready_to_read)


# ---------------------------------------------------------------- membership


def test_add_node_updates_membership():
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.handle(
        Message(type=MT.CONFIG_CHANGE_EVENT, hint=4, hint_high=0)  # ADD_NODE
    )
    assert 4 in lead.remotes
    assert lead.num_voting_members() == 4


def test_remove_node_and_leader_steps_down_when_removed():
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.handle(Message(type=MT.CONFIG_CHANGE_EVENT, hint=1, hint_high=1))
    assert lead.state == F
    assert 1 not in lead.remotes


def test_remove_node_may_advance_commit():
    """Removing a slow node can make previously-uncommitted entries reach
    quorum within the smaller membership."""
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    nt.isolate(3)
    nt.propose(1, b"only-2-of-3")
    # 2/3 replicated -> committed already; now isolate 2 as well
    nt.isolate(2)
    nt.propose(1, b"only-1-of-3")
    before = lead.log.committed
    lead.handle(Message(type=MT.CONFIG_CHANGE_EVENT, hint=3, hint_high=1))
    lead.handle(Message(type=MT.CONFIG_CHANGE_EVENT, hint=2, hint_high=1))
    assert lead.log.committed > before


def test_single_pending_config_change_invariant():
    """raft.go:1242-1295: at most one uncommitted config change in flight;
    extras are replaced with regular entries and reported dropped."""
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    cc_entry = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"cc1")
    lead.handle(Message(type=MT.PROPOSE, from_=1, entries=[cc_entry]))
    assert lead.pending_config_change
    cc2 = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"cc2")
    lead.handle(Message(type=MT.PROPOSE, from_=1, entries=[cc2]))
    assert len(lead.dropped_entries) == 1
    # the second proposal went in as a plain application entry
    last = lead.log.get_entries(
        lead.log.last_index(), lead.log.last_index() + 1, 1 << 30
    )[0]
    assert last.type == EntryType.APPLICATION


def test_election_skipped_with_unapplied_config_change():
    r = new_test_raft(1, [1, 2, 3])
    r.has_not_applied_config_change = lambda: True
    tick_until_election(r)
    assert r.state == F  # campaign skipped


# ---------------------------------------------------------------- transfer


def test_leader_transfer_to_up_to_date_follower():
    """Thesis p29: transfer target receives TimeoutNow and campaigns with
    the transfer hint set."""
    nt = make_cluster(3)
    nt.elect(1)
    nt.propose(1, b"x")
    nt.send(Message(type=MT.LEADER_TRANSFER, to=1, from_=2, hint=2))
    assert nt.rafts[2].state == L
    assert nt.rafts[1].state == F


def test_leader_transfer_waits_for_target_catchup():
    nt = make_cluster(3)
    nt.elect(1)
    nt.isolate(3)
    nt.propose(1, b"x")
    nt.heal()
    lead = nt.rafts[1]
    lead.msgs.clear()
    # node 3 lags; transfer should defer until it catches up
    lead.handle(Message(type=MT.LEADER_TRANSFER, from_=3, to=1, term=lead.term, hint=3))
    assert not any(m.type == MT.TIMEOUT_NOW for m in lead.msgs)
    assert lead.leader_transfer_target == 3
    nt.deliver_all()
    # replication catches 3 up; ReplicateResp triggers TimeoutNow
    nt.send(Message(type=MT.LEADER_HEARTBEAT, to=1, from_=1))
    assert nt.rafts[3].state == L


def test_leader_transfer_aborts_after_election_timeout():
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.remotes[2].match = 0  # pretend behind
    lead.handle(Message(type=MT.LEADER_TRANSFER, from_=2, to=1, term=lead.term, hint=2))
    assert lead.leader_transfering()
    for _ in range(lead.election_timeout + 1):
        lead.tick()
    assert not lead.leader_transfering()


def test_proposals_dropped_while_transferring():
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.remotes[2].match = 0
    lead.handle(Message(type=MT.LEADER_TRANSFER, from_=2, to=1, term=lead.term, hint=2))
    lead.handle(Message(type=MT.PROPOSE, from_=1, entries=[Entry(cmd=b"z")]))
    assert len(lead.dropped_entries) == 1


# ---------------------------------------------------------------- observers


def test_observer_does_not_campaign():
    r = new_test_raft(1, [], is_observer=True)
    r.observers[1] = Remote(next=1)
    r.remotes[2] = Remote(next=1)
    tick_until_election(r)
    assert r.state == RaftNodeState.OBSERVER


def test_observer_receives_replication():
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.handle(Message(type=MT.CONFIG_CHANGE_EVENT, hint=4, hint_high=2))
    assert 4 in lead.observers
    obs = new_test_raft(4, [], is_observer=True)
    obs.observers[4] = Remote(next=1)
    nt.rafts[4] = obs
    nt.propose(1, b"to-observer")
    assert obs.log.committed == nt.rafts[1].log.committed


def test_observer_promotion_to_full_member():
    nt = make_cluster(3)
    nt.elect(1)
    lead = nt.rafts[1]
    lead.handle(Message(type=MT.CONFIG_CHANGE_EVENT, hint=4, hint_high=2))
    n_before = lead.num_voting_members()
    lead.handle(Message(type=MT.CONFIG_CHANGE_EVENT, hint=4, hint_high=0))
    assert 4 in lead.remotes and 4 not in lead.observers
    assert lead.num_voting_members() == n_before + 1


# ---------------------------------------------------------------- witnesses


def test_witness_votes_but_gets_metadata_entries():
    """Thesis 11.7.2: witness participates in quorum but does not hold real
    log payloads."""
    nt = make_cluster(2)
    nt.rafts[1].witnesses[3] = Remote(next=1)
    nt.rafts[2].witnesses[3] = Remote(next=1)
    wit = new_test_raft(3, [], is_witness=True)
    wit.witnesses[3] = Remote(next=1)
    wit.remotes[1] = Remote(next=1)
    wit.remotes[2] = Remote(next=1)
    nt.rafts[3] = wit
    nt.elect(1)
    assert nt.rafts[1].state == L
    nt.propose(1, b"payload")
    # witness holds metadata-only entries
    ents = wit.log.get_entries(2, wit.log.last_index() + 1, 1 << 30)
    assert all(e.type == EntryType.METADATA for e in ents)
    assert all(e.cmd == b"" for e in ents)
    # but count toward commit quorum
    assert wit.log.committed == nt.rafts[1].log.committed


# ---------------------------------------------------------------- quiesce


def test_quiesced_tick_does_not_campaign():
    r = new_test_raft(1, [1, 2, 3])
    for _ in range(5 * r.election_timeout):
        r.quiesced_tick()
    assert r.state == F
    assert r.quiesced


# ---------------------------------------------------------------- randomized


def test_randomized_convergence_with_drops():
    """Randomized smoke: with 20% message drops a 3-node cluster still makes
    progress; all replica logs converge on a prefix."""
    nt = make_cluster(3)
    nt.elect(1)
    nt.drop_rate = 0.2
    rng = random.Random(7)
    for i in range(50):
        nid = rng.choice([1, 2, 3])
        r = nt.rafts[nid]
        if r.state == L:
            r.handle(
                Message(type=MT.PROPOSE, from_=nid, entries=[Entry(cmd=b"%d" % i)])
            )
        for rr in nt.rafts.values():
            rr.tick()
        nt.deliver_all()
    nt.drop_rate = 0.0
    for _ in range(30):
        for rr in nt.rafts.values():
            rr.tick()
        nt.deliver_all()
    commits = {r.log.committed for r in nt.rafts.values()}
    assert len(commits) == 1
    c = commits.pop()
    assert c > 1
    logs = [
        [(e.term, e.cmd) for e in r.log.get_entries(1, c + 1, 1 << 30)]
        for r in nt.rafts.values()
    ]
    assert logs[0] == logs[1] == logs[2]


# ------------------------------------- committed>applied config-change scan


def _raft_with_window(cc_at=(), n=6, payload=16):
    """A raft with n committed-but-unapplied entries (config changes at
    the 1-based indexes in cc_at)."""
    r = new_test_raft(1, [1, 2, 3])
    ents = [
        Entry(
            index=i,
            term=1,
            type=(
                EntryType.CONFIG_CHANGE
                if i in cc_at
                else EntryType.APPLICATION
            ),
            cmd=b"x" * payload,
        )
        for i in range(1, n + 1)
    ]
    r.log.append(ents)
    r.log.committed = n
    assert r.applied == 0
    return r


def test_unapplied_window_scan_is_precise():
    """The committed>applied scan (raft.go:1461-1470 notes it as a TODO
    and conservatively always refuses): a window WITHOUT a config change
    must not block campaigning, one WITH must."""
    assert not _raft_with_window()._has_config_change_to_apply()
    assert _raft_with_window(cc_at=(3,))._has_config_change_to_apply()
    assert _raft_with_window(cc_at=(6,))._has_config_change_to_apply()


def test_unapplied_window_scan_crosses_max_size_batches(monkeypatch):
    """Regression: the scan must CONTINUE past a max_entry_size-limited
    first batch — a config change at the window's tail must be found."""
    from dragonboat_tpu import settings

    # ~2 entries per batch (entry size = len(cmd) + 48)
    monkeypatch.setattr(settings.soft, "max_entry_size", 150)
    r = _raft_with_window(cc_at=(6,), n=6, payload=16)
    assert r._has_config_change_to_apply()
    r2 = _raft_with_window(n=6, payload=16)
    monkeypatch.setattr(settings.soft, "max_entry_size", 150)
    assert not r2._has_config_change_to_apply()


def test_unapplied_window_unfetchable_is_conservative(monkeypatch):
    """Regression for the imprecise fallback: when part of the window
    cannot be read (storage truncated a batch to nothing, or the scan
    raced a compaction), the answer must be the reference's conservative
    True (refuse to campaign) — an unseen entry might be a config
    change. The old fallback answered False and allowed campaigning
    across a possibly-pending quorum change."""
    from dragonboat_tpu.core.logentry import EntryLog, ErrCompacted

    r = _raft_with_window()
    monkeypatch.setattr(EntryLog, "get_entries", lambda self, lo, hi, mx: [])
    assert r._has_config_change_to_apply()

    def boom(self, lo, hi, mx):
        raise ErrCompacted

    monkeypatch.setattr(EntryLog, "get_entries", boom)
    assert r._has_config_change_to_apply()


def test_election_skipped_while_config_change_unapplied_via_scan():
    """End to end through the election handler: the precise scan (not
    the injected has_not_applied_config_change callback) refuses the
    campaign while a committed config change awaits apply, and allows
    it once the window is clean."""
    r = _raft_with_window(cc_at=(2,))
    tick_until_election(r)
    assert r.state == F  # campaign refused by the scan
    r.applied = r.log.committed  # window drained: free to campaign
    tick_until_election(r)
    assert r.state in (C, L)
