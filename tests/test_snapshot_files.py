"""External snapshot files end-to-end (cf. statemachine/files.go +
the reference's snapshot chunk file_info transfer): an SM adds an external
file during save_snapshot; the file must survive (a) local restart
recovery and (b) network snapshot install on a lagging peer, arriving in
the peer's snapshot dir with its metadata."""
import os
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

CLUSTER = 1


class ExtFileSM(IStateMachine):
    """Counter SM whose snapshot payload rides an EXTERNAL file: the main
    stream holds only the count; the values live in ext-file records."""

    def __init__(self, workdir):
        self.workdir = workdir
        self.values = []
        self.recovered_meta = b""

    def update(self, data):
        self.values.append(data.decode())
        return Result(value=len(self.values))

    def lookup(self, q):
        if q == b"meta":
            return self.recovered_meta
        return "|".join(self.values).encode()

    def save_snapshot(self, w, files, done):
        path = os.path.join(self.workdir, f"ext-{id(self)}-{len(self.values)}.dat")
        with open(path, "w") as f:
            f.write("|".join(self.values))
        files.add_file(7, path, b"ext-meta-v1")
        w.write(len(self.values).to_bytes(8, "little"))

    def recover_from_snapshot(self, r, files, done):
        n = int.from_bytes(r.read(8), "little")
        assert len(files) == 1, f"expected 1 external file, got {files!r}"
        f = files[0]
        assert f.file_id == 7
        self.recovered_meta = f.metadata
        with open(f.filepath) as fh:
            blob = fh.read()
        self.values = blob.split("|") if blob else []
        assert len(self.values) == n

    def close(self):
        pass


def _mk(nid, reg, tmp, restart=False):
    nh = NodeHost(NodeHostConfig(
        deployment_id=61, rtt_millisecond=5,
        nodehost_dir=f"{tmp}/h{nid}", raft_address=f"x{nid}:1",
        raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        engine=EngineConfig(kind="vector", max_groups=8, max_peers=4,
                            log_window=32),
    ))
    members = {1: "x1:1", 2: "x2:1", 3: "x3:1"}
    nh.start_cluster(
        {} if restart else members, False,
        lambda c, n, tmp=tmp: ExtFileSM(str(tmp)),
        Config(cluster_id=CLUSTER, node_id=nid, election_rtt=20,
               heartbeat_rtt=2, snapshot_entries=20, compaction_overhead=3),
    )
    return nh


def _wait_leader(hosts, deadline_s=60):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for nid, nh in hosts.items():
            if nh is None:
                continue
            lid, ok = nh.get_leader_id(CLUSTER)
            if ok and lid in hosts and hosts[lid] is not None:
                return lid
        time.sleep(0.02)
    return None


@pytest.mark.slow
def test_external_files_transfer_on_install(tmp_path):
    reg = _Registry()
    hosts = {nid: _mk(nid, reg, tmp_path) for nid in (1, 2, 3)}
    try:
        leader = _wait_leader(hosts)
        assert leader

        # stop host 3, then commit far past the snapshot+compaction point
        # so its catch-up NEEDS a snapshot install
        hosts[3].stop()
        hosts[3] = None
        leader = _wait_leader(hosts)
        assert leader
        s = hosts[leader].get_noop_session(CLUSTER)
        committed = 0
        deadline = time.time() + 120
        while committed < 80 and time.time() < deadline:
            try:
                hosts[leader].sync_propose(
                    s, f"w{committed}".encode(), timeout_s=5.0)
                committed += 1
            except Exception:
                leader = _wait_leader(hosts)
                s = hosts[leader].get_noop_session(CLUSTER)
        assert committed >= 80

        # restart host 3: replays its short log, then the leader installs
        # a snapshot carrying the external file
        hosts[3] = _mk(3, reg, tmp_path, restart=True)
        deadline = time.time() + 90
        value = None
        while time.time() < deadline:
            try:
                v = hosts[3].stale_read(CLUSTER, b"")
                if v is not None and f"w{committed - 1}" in v.decode():
                    value = v
                    break
            except Exception:
                pass
            time.sleep(0.1)
        assert value is not None, "lagging host never caught up via install"
        # the external file's metadata went through recover on host 3
        meta = hosts[3].stale_read(CLUSTER, b"meta")
        assert meta == b"ext-meta-v1"
        # and the received external file landed under host 3's snapshot dir
        snapdir = hosts[3].snapshot_dir_root()
        found = []
        for root, _dirs, names in os.walk(snapdir):
            found += [os.path.join(root, n) for n in names
                      if n.startswith("external-file-")]
        assert found, "no received external file on the installed host"
    finally:
        for nh in hosts.values():
            if nh is not None:
                nh.stop()


def test_external_files_survive_local_restart(tmp_path):
    """Restart recovery from a local snapshot must hand the SM its
    external files too."""
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=62, rtt_millisecond=5,
        nodehost_dir=f"{tmp_path}/solo", raft_address="solo:1",
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
    ))
    nh.start_cluster(
        {1: "solo:1"}, False, lambda c, n: ExtFileSM(str(tmp_path)),
        Config(cluster_id=CLUSTER, node_id=1, election_rtt=20,
               heartbeat_rtt=2),
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        _, ok = nh.get_leader_id(CLUSTER)
        if ok:
            break
        time.sleep(0.02)
    s = nh.get_noop_session(CLUSTER)
    for i in range(5):
        nh.sync_propose(s, f"v{i}".encode(), timeout_s=5.0)
    assert nh.sync_request_snapshot(CLUSTER, timeout_s=15.0) > 0
    nh.stop()

    nh = NodeHost(NodeHostConfig(
        deployment_id=62, rtt_millisecond=5,
        nodehost_dir=f"{tmp_path}/solo", raft_address="solo:1",
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
    ))
    nh.start_cluster(
        {}, False, lambda c, n: ExtFileSM(str(tmp_path)),
        Config(cluster_id=CLUSTER, node_id=1, election_rtt=20,
               heartbeat_rtt=2),
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if nh.stale_read(CLUSTER, b"meta") == b"ext-meta-v1":
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert nh.stale_read(CLUSTER, b"meta") == b"ext-meta-v1"
        assert b"v4" in nh.stale_read(CLUSTER, b"")
    finally:
        nh.stop()
