"""The vector-scale session plane (serving/sessions.py) — tier-1 gate.

ISSUE 14 tentpole (a): at-most-once sessions multiplexed per tenant over
ServingFront. The contract under test:

  * batched registration/retirement: one wave registers N sessions with
    one urgent admission and one completion pass;
  * end-to-end dedup through the front's session lane: a retried
    proposal that already applied returns the RSM's CACHED result (same
    value, no second apply) — differential-tested across a leader
    change, a crash/restart, and a snapshot-install rejoin (the session
    image rides the replicated snapshot);
  * retry safety: SessionManager.propose re-asks indeterminate outcomes
    under the SAME series id (retry.call_with_retries session
    propagation), and the checked-out session pool sheds typed
    retryable errors when exhausted.

Run alone with `-m serving`.
"""
import json
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.serving import (
    ErrSessionExhausted,
    SessionManager,
)
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

pytestmark = pytest.mark.serving

CLUSTER = 300


class SeqKV(IStateMachine):
    """KV whose every apply gets a globally unique sequence number and
    whose per-op apply counts are queryable: the dedup differential's
    measuring instrument. A deduped retry returns the ORIGINAL seq; a
    double apply would mint a fresh, higher one and bump the count."""

    def __init__(self, cluster_id=0, node_id=0):
        self.d = {}
        self.counts = {}
        self.seq = 0

    def update(self, cmd: bytes) -> Result:
        k, v = cmd.decode().split("=", 1)
        self.seq += 1
        self.d[k] = v
        self.counts[k] = self.counts.get(k, 0) + 1
        return Result(value=self.seq)

    def lookup(self, q):
        if q == ("count",):
            return dict(self.counts)
        if isinstance(q, tuple) and q[0] == "count":
            return self.counts.get(q[1], 0)
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        w.write(json.dumps([self.d, self.counts, self.seq]).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d, self.counts, self.seq = json.loads(r.read().decode())


def mk_host(addr, registry, engine_kind="scalar", rtt_ms=5, **cfg_kw):
    return NodeHost(
        NodeHostConfig(
            deployment_id=14,
            rtt_millisecond=rtt_ms,
            raft_address=addr,
            raft_rpc_factory=lambda listen: loopback_factory(
                listen, registry
            ),
            engine=EngineConfig(
                kind=engine_kind, max_groups=32, max_peers=4, log_window=64
            ),
            **cfg_kw,
        )
    )


def group_config(cluster_id, node_id, **kw):
    base = dict(
        cluster_id=cluster_id,
        node_id=node_id,
        election_rtt=10,
        heartbeat_rtt=2,
    )
    base.update(kw)
    return Config(**base)


def wait_for(pred, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def mk_trio(registry, engine_kind, **cfg_kw):
    members = {n: f"s{n}:1" for n in (1, 2, 3)}
    hosts = {
        n: mk_host(f"s{n}:1", registry, engine_kind) for n in (1, 2, 3)
    }
    for n, nh in hosts.items():
        nh.start_cluster(
            members, False, SeqKV, group_config(CLUSTER, n, **cfg_kw)
        )
    return hosts


def leader_of(hosts, cluster=CLUSTER):
    for n, nh in hosts.items():
        lid, ok = nh.get_leader_id(cluster)
        if ok:
            return lid
    return 0


def apply_count(nh, key, cluster=CLUSTER):
    return nh.stale_read(cluster, ("count", key))


def transfer_until(hosts, target, timeout=45.0):
    """Drive leadership onto `target`, re-issuing the (best-effort)
    transfer request until it sticks — the raft TimeoutNow only fires
    once the target's match catches the leader, and an unlucky election
    can land elsewhere first (dragonboat callers observe and retry)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        views = {n: h.get_leader_id(CLUSTER) for n, h in hosts.items()}
        if all(v == (target, True) for v in views.values()):
            return True
        lid = leader_of(hosts)
        if lid and lid != target:
            try:
                hosts[lid].request_leader_transfer(CLUSTER, target)
            except Exception:
                pass  # a pending transfer is still in flight
        time.sleep(0.3)
    return False


@pytest.fixture(params=["scalar", "vector"])
def engine_kind(request):
    return request.param


# ---------------------------------------------------------------------------
# lifecycle: batched register / retire
# ---------------------------------------------------------------------------


def test_batched_register_and_retire(engine_kind):
    reg = _Registry()
    nh = mk_host("a:1", reg, engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, SeqKV, group_config(CLUSTER, 1))
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        mgr = SessionManager(nh.serving_front())
        n = mgr.register(7, CLUSTER, count=8, timeout_s=20.0)
        assert n == 8
        assert mgr.pool_sizes()[(7, CLUSTER)] == 8
        # the whole wave was ONE urgent admission of 8
        c = nh.serving_front().admission.counters()[7]
        assert c["admitted"]["urgent"] == 8
        st = mgr.stats()
        assert st["registered"] == 8 and st["register_failed"] == 0
        # retirement drains the pool in one wave too
        assert mgr.retire(7, CLUSTER, timeout_s=20.0) == 8
        assert mgr.pool_sizes()[(7, CLUSTER)] == 0
        assert mgr.stats()["retired"] == 8
    finally:
        nh.stop()


def test_propose_at_most_once_happy_path(engine_kind):
    reg = _Registry()
    nh = mk_host("a:1", reg, engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, SeqKV, group_config(CLUSTER, 1))
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        mgr = SessionManager(nh.serving_front())
        assert mgr.register(7, CLUSTER, count=2, timeout_s=20.0) == 2
        r1 = mgr.propose(7, CLUSTER, b"k=1", 20.0)
        r2 = mgr.propose(7, CLUSTER, b"k=2", 20.0)
        assert r2.value == r1.value + 1  # sequential applies
        assert apply_count(nh, "k") == 2
        assert mgr.stats()["proposals"] == 2
    finally:
        nh.stop()


def test_checkout_exhaustion_is_typed_and_retryable():
    reg = _Registry()
    nh = mk_host("a:1", reg, "scalar")
    try:
        nh.start_cluster({1: "a:1"}, False, SeqKV, group_config(CLUSTER, 1))
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        mgr = SessionManager(nh.serving_front())
        assert mgr.register(7, CLUSTER, count=1, timeout_s=20.0) == 1
        with mgr.checkout(7, CLUSTER):
            with pytest.raises(ErrSessionExhausted) as ei:
                with mgr.checkout(7, CLUSTER):
                    pass
            assert ei.value.retry_after_s > 0  # machine-readable hint
        # returned to the pool on exit
        with mgr.checkout(7, CLUSTER):
            pass
    finally:
        nh.stop()


# ---------------------------------------------------------------------------
# the dedup differential: retry-after-apply returns the cached result
# ---------------------------------------------------------------------------


def _propose_no_ack(front, tenant, session, cmd, timeout=20.0):
    """One session-lane proposal WITHOUT acknowledging the session —
    the client-side state after a completed apply whose response was
    lost (the deadline-retry shape retry.py produces)."""
    t = front.propose_session(tenant, CLUSTER, session, cmd, timeout)
    r = t.wait()
    assert r is not None and r.completed, r
    return r.result


def test_dedup_plain_retry_after_apply(engine_kind):
    """The base case: same series re-proposed after a completed apply
    returns the CACHED result — same seq value, apply count stays 1."""
    reg = _Registry()
    nh = mk_host("a:1", reg, engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, SeqKV, group_config(CLUSTER, 1))
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        mgr = SessionManager(front)
        assert mgr.register(7, CLUSTER, count=1, timeout_s=20.0) == 1
        with mgr.checkout(7, CLUSTER) as sess:
            first = _propose_no_ack(front, 7, sess, b"x=1")
            again = _propose_no_ack(front, 7, sess, b"x=1")
            assert again.value == first.value  # the cached result
            assert apply_count(nh, "x") == 1  # no second apply
            sess.proposal_completed()
            # the next series applies fresh
            nxt = _propose_no_ack(front, 7, sess, b"x=2")
            assert nxt.value == first.value + 1
            sess.proposal_completed()
        assert apply_count(nh, "x") == 2
    finally:
        nh.stop()


def test_dedup_across_leader_change(engine_kind):
    """Differential: apply through the old leader, lose the ack, retry
    through the NEW leader's front — the replicated session cache
    answers with the original result on every replica."""
    reg = _Registry()
    hosts = mk_trio(reg, engine_kind)
    try:
        assert wait_for(lambda: leader_of(hosts) != 0)
        lid = leader_of(hosts)
        mgr = SessionManager(hosts[lid].serving_front())
        assert mgr.register(7, CLUSTER, count=1, timeout_s=30.0) == 1
        with mgr.checkout(7, CLUSTER) as sess:
            first = _propose_no_ack(
                hosts[lid].serving_front(), 7, sess, b"m=1", timeout=30.0
            )
            # move leadership to another member
            target = next(n for n in hosts if n != lid)
            hosts[lid].request_leader_transfer(CLUSTER, target)
            assert wait_for(
                lambda: leader_of(hosts) not in (0, lid), timeout=30
            ), "leadership never moved"
            new_lid = leader_of(hosts)
            # adopt the same session on the new leader's host (failover:
            # the dedup state is replicated, not host-local)
            mgr2 = SessionManager(hosts[new_lid].serving_front())
            mgr2.adopt(7, CLUSTER, sess)
            again = _propose_no_ack(
                hosts[new_lid].serving_front(), 7, sess, b"m=1", timeout=30.0
            )
            assert again.value == first.value
        # converged: every replica applied m exactly once
        assert wait_for(
            lambda: all(
                apply_count(h, "m") == 1 for h in hosts.values()
            ),
            timeout=30,
        ), {n: apply_count(h, "m") for n, h in hosts.items()}
    finally:
        for nh in hosts.values():
            nh.stop()


def test_dedup_across_crash_restart(tmp_path):
    """Differential: the session cache survives a node crash — WAL
    recovery replays the register + the applied proposal, so the retry
    after restart still dedups."""
    reg = _Registry()
    nh = mk_host(
        "a:1", reg, "vector", nodehost_dir=str(tmp_path / "nh")
    )
    try:
        nh.start_cluster({1: "a:1"}, False, SeqKV, group_config(CLUSTER, 1))
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        mgr = SessionManager(front)
        assert mgr.register(7, CLUSTER, count=1, timeout_s=30.0) == 1
        with mgr.checkout(7, CLUSTER) as sess:
            first = _propose_no_ack(front, 7, sess, b"c=1", timeout=30.0)
            nh.crash_cluster(CLUSTER)
            nh.restart_cluster(CLUSTER)
            assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1], timeout=60)
            again = _propose_no_ack(front, 7, sess, b"c=1", timeout=30.0)
            assert again.value == first.value
            assert apply_count(nh, "c") == 1
    finally:
        nh.stop()


def test_dedup_across_snapshot_install_rejoin(engine_kind, tmp_path):
    """Differential: a rejoiner whose log was compacted past receives
    the session image INSIDE the streamed snapshot install, then — made
    leader — answers the retry from that installed cache. The deepest
    way dedup can survive a move, and exactly the path a live migration
    (serving/placement.py) rides."""
    reg = _Registry()
    hosts = mk_trio(
        reg, engine_kind, snapshot_entries=20, compaction_overhead=5
    )
    try:
        assert wait_for(lambda: leader_of(hosts) != 0)
        lid = leader_of(hosts)
        front = hosts[lid].serving_front()
        mgr = SessionManager(front)
        assert mgr.register(7, CLUSTER, count=1, timeout_s=30.0) == 1
        with mgr.checkout(7, CLUSTER) as sess:
            first = _propose_no_ack(front, 7, sess, b"s=1", timeout=30.0)
            victim = next(n for n in hosts if n != lid)
            hosts[victim].crash_cluster(CLUSTER)
            # drive traffic past the snapshot threshold and force
            # compaction past the victim's index
            s = hosts[lid].get_noop_session(CLUSTER)
            for i in range(40):
                hosts[lid].sync_propose(s, f"fill=v{i}".encode(), 20.0)
            try:
                hosts[lid].sync_request_snapshot(CLUSTER, timeout_s=20.0)
            except Exception:
                pass  # a periodic snapshot may already cover it
            hosts[victim].restart_cluster(CLUSTER)
            assert wait_for(
                lambda: hosts[victim].get_applied_index(CLUSTER)
                >= hosts[lid].get_applied_index(CLUSTER) - 2,
                timeout=60,
            ), "rejoiner never caught up"
            # one fresh commit so the rejoiner acks the true last index
            # (the leader's match for a snapshot-installed peer refreshes
            # on the next REPLICATE_RESP, which gates TimeoutNow)
            for _ in range(10):
                cur = leader_of(hosts)
                try:
                    hosts[cur].sync_propose(
                        hosts[cur].get_noop_session(CLUSTER),
                        b"poke=1", 10.0,
                    )
                    break
                except Exception:
                    time.sleep(0.3)
            # make the rejoiner the leader: the retry must be answered
            # from ITS installed session image
            assert transfer_until(hosts, victim), (
                "transfer to rejoiner never completed"
            )
            mgr2 = SessionManager(hosts[victim].serving_front())
            mgr2.adopt(7, CLUSTER, sess)
            again = _propose_no_ack(
                hosts[victim].serving_front(), 7, sess, b"s=1", timeout=30.0
            )
            assert again.value == first.value
        # the rejoiner got s's effect via the snapshot, never a 2nd apply
        assert apply_count(hosts[victim], "s") <= 1
        assert wait_for(
            lambda: all(
                apply_count(h, "s") <= 1 for h in hosts.values()
            ),
            timeout=30,
        )
    finally:
        for nh in hosts.values():
            nh.stop()


# ---------------------------------------------------------------------------
# SessionManager.propose retry loop (indeterminate -> same-series re-ask)
# ---------------------------------------------------------------------------


def test_propose_completes_an_already_applied_series():
    """The deadline-retry-after-apply shape end to end: a previous
    attempt applied series k but the ack was lost (session back in the
    pool unacknowledged); the next propose() submits the SAME series and
    must complete with the FIRST apply's cached result, then advance the
    session normally."""
    reg = _Registry()
    nh = mk_host("a:1", reg, "scalar")
    try:
        nh.start_cluster({1: "a:1"}, False, SeqKV, group_config(CLUSTER, 1))
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        mgr = SessionManager(front)
        assert mgr.register(7, CLUSTER, count=1, timeout_s=20.0) == 1
        with mgr.checkout(7, CLUSTER) as sess:
            first = _propose_no_ack(front, 7, sess, b"r=1")
            # checkout exits WITHOUT proposal_completed: the lost-ack state
        res = mgr.propose(7, CLUSTER, b"r=1", 30.0)
        assert res.value == first.value  # the cached result, not a re-apply
        assert apply_count(nh, "r") == 1
        # the session advanced: the next op is a fresh series
        nxt = mgr.propose(7, CLUSTER, b"r=2", 30.0)
        assert nxt.value == first.value + 1
    finally:
        nh.stop()


class _ScriptedFront:
    """Minimal ServingFront stand-in: propose_session pops scripted
    ticket outcomes, recording (session, series_id) per attempt — the
    deterministic harness for the same-series retry loop."""

    class _Cfg:
        pump_interval_s = 0.0001

    class _Ticket:
        def __init__(self, result):
            self._r = result

        def wait(self, timeout=None):
            return self._r

    def __init__(self, outcomes):
        from dragonboat_tpu.serving.admission import AdmissionController

        self.config = self._Cfg()
        self.admission = AdmissionController()
        self._nh = None
        self.outcomes = list(outcomes)
        self.attempts = []

    def propose_session(self, tenant_id, cluster_id, session, cmd, budget):
        self.attempts.append((session, session.series_id))
        return self._Ticket(self.outcomes.pop(0))


def test_indeterminate_final_failure_poisons_the_session():
    """If the whole deadline is spent with the outcome still UNKNOWN,
    the session must NOT return to the pool: the series may be applied
    server-side, and a future (different) op reusing it would collect
    THIS op's cached result — the one silent mis-attribution this API
    could make. The poisoned session is discarded and counted."""
    from dragonboat_tpu.client import Session
    from dragonboat_tpu.requests import (
        ErrTimeout,
        REQUEST_TIMEOUT,
        RequestResult,
    )

    front = _ScriptedFront(
        [RequestResult(code=REQUEST_TIMEOUT)] * 2000
    )
    mgr = SessionManager(front)
    sess = Session.new_session(CLUSTER)
    sess.prepare_for_propose()
    mgr.adopt(7, CLUSTER, sess)
    with pytest.raises(ErrTimeout):
        mgr.propose(7, CLUSTER, b"k=v", 0.05)
    assert mgr.pool_sizes().get((7, CLUSTER), 0) == 0, (
        "an indeterminate session went back to the pool"
    )
    assert mgr.stats()["discarded"] == 1
    # a shed BEFORE submission leaves the session clean and reusable
    class _SheddingFront(_ScriptedFront):
        def propose_session(self, *a, **kw):
            from dragonboat_tpu.serving.admission import ErrBackpressure

            raise ErrBackpressure(retry_after_s=10.0)

    front2 = _SheddingFront([])
    mgr2 = SessionManager(front2)
    sess2 = Session.new_session(CLUSTER)
    sess2.prepare_for_propose()
    mgr2.adopt(7, CLUSTER, sess2)
    with pytest.raises(ErrTimeout):
        mgr2.propose(7, CLUSTER, b"k=v", 0.05)
    assert mgr2.pool_sizes()[(7, CLUSTER)] == 1  # never submitted: clean
    assert mgr2.stats()["discarded"] == 0


def test_propose_retry_loop_reuses_same_series():
    """Unit differential for the retry loop itself: attempt 1 times out
    (indeterminate), attempt 2 completes — both attempts MUST carry the
    same session object and the same series id (the no-accidental-new-
    series rule of retry.call_with_retries' session propagation)."""
    from dragonboat_tpu.client import Session
    from dragonboat_tpu.requests import (
        REQUEST_COMPLETED,
        REQUEST_TIMEOUT,
        RequestResult,
    )
    from dragonboat_tpu.statemachine import Result

    front = _ScriptedFront(
        [
            RequestResult(code=REQUEST_TIMEOUT),
            RequestResult(code=REQUEST_COMPLETED, result=Result(value=42)),
        ]
    )
    mgr = SessionManager(front)
    sess = Session.new_session(CLUSTER)
    sess.prepare_for_propose()
    mgr.adopt(7, CLUSTER, sess)
    res = mgr.propose(7, CLUSTER, b"k=v", 10.0)
    assert res.value == 42
    assert len(front.attempts) == 2
    (s1, series1), (s2, series2) = front.attempts
    assert s1 is sess and s2 is sess
    assert series1 == series2, "retry minted a new series (double-apply)"
    assert mgr.stats()["safe_retries"] == 1
    # acknowledged exactly once, after the completed attempt
    assert sess.responded_to == series1
    assert sess.series_id == series1 + 1
