"""Embedding C ABI test: builds native/binding (libdbtpu.so + embed_demo)
and runs the pure-C++ demo app — NodeHost lifecycle, cluster start with a
C++ SM plugin, propose, linearizable read, missing-key read, stop — all
through the flat C API with no Python in the app
(cf. reference binding/binding.go + binding/cpp tests)."""
import os
import subprocess

import pytest

_NATIVE = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "native"))
_DEMO = os.path.join(_NATIVE, "build", "embed_demo")
_OO_DEMO = os.path.join(_NATIVE, "build", "oo_demo")
_PLUGIN = os.path.join(_NATIVE, "build", "libkvstore_sm.so")
_ONDISK_PLUGIN = os.path.join(_NATIVE, "build", "libdiskkv_sm.so")


def _built() -> bool:
    import shutil

    if shutil.which("g++") is None or shutil.which("python3-config") is None:
        return False  # genuinely no toolchain: skip
    # toolchain present: a build FAILURE must fail loudly, not skip —
    # except a missing libpython dev install, which is a missing optional
    # dependency like an absent compiler
    proc = subprocess.run(
        ["make", "-C", _NATIVE, "all", "embed"],
        capture_output=True, text=True, timeout=300,
    )
    if proc.returncode != 0:
        if "Python.h" in proc.stderr:
            return False
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    return os.path.exists(_DEMO) and os.path.exists(_PLUGIN)


pytestmark = pytest.mark.skipif(
    not _built(), reason="native toolchain unavailable"
)


@pytest.mark.slow
def test_embed_demo_runs(tmp_path):
    env = dict(os.environ)
    repo = os.path.abspath(os.path.join(_NATIVE, ".."))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [_DEMO, str(tmp_path), _PLUGIN],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "EMBED DEMO PASS" in proc.stdout


@pytest.mark.slow
def test_oo_demo_runs(tmp_path):
    """Pure-C++ app over the OO wrapper (dragonboat_tpu.hpp): sessions,
    sync/async proposals (RequestState + Event), ReadIndex/ReadLocal,
    membership + observer add, snapshot request, restart with the on-disk
    C++ plugin recovering its applied index (cf. reference dragonboat.h
    NodeHost/Session/RequestState surface)."""
    env = dict(os.environ)
    repo = os.path.abspath(os.path.join(_NATIVE, ".."))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["DBTPU_DISKKV_DIR"] = str(tmp_path / "diskkv")
    proc = subprocess.run(
        [_OO_DEMO, str(tmp_path), _ONDISK_PLUGIN],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "OO DEMO PASS" in proc.stdout
