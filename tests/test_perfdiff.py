"""The bench regression gate (`tools.perfdiff`) over checked-in golden
fixtures: pass on identical runs, fail on an injected >=20% phase
regression, refuse (incomparable) a scaled-down run vs a nominal one —
the three verdicts the `-m perf` tier-1 gate certifies, plus the
directory (trajectory) mode and the honesty rules' unit semantics.

jax-free and sub-second: perfdiff reads JSON only, like `tools.check`.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

import pytest

from dragonboat_tpu.tools.perfdiff import (
    FAIL,
    INCOMPARABLE,
    PASS,
    compare,
    compare_config,
    load_record,
    main,
    phase_regressed,
    render,
)

pytestmark = pytest.mark.perf

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DATA = os.path.join(_REPO, "tests", "data")
BASE = os.path.join(_DATA, "perfdiff_base.json")
REGRESS = os.path.join(_DATA, "perfdiff_regress.json")
NOMINAL = os.path.join(_DATA, "perfdiff_nominal.json")
HBM = os.path.join(_DATA, "perfdiff_hbm.json")


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "dragonboat_tpu.tools.perfdiff", *args],
        cwd=_REPO, capture_output=True, text=True, timeout=60,
    )


# ---------------------------------------------------------------------------
# the three gate verdicts (acceptance criteria), via the real CLI
# ---------------------------------------------------------------------------


def test_gate_identical_runs_exit_zero():
    p = _cli(BASE, BASE, "--gate")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "PASS" in p.stdout


def test_gate_flags_injected_phase_regression():
    """The regress fixture's config-1 'save' phase grew 2.0s -> 2.6s
    (+30% >= the 20% default threshold): non-zero exit, and the output
    names the phase."""
    p = _cli(BASE, REGRESS, "--gate")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "save" in p.stdout
    assert "REGRESSED" in p.stdout
    assert "FAIL" in p.stdout


def test_gate_threshold_is_honored():
    # at a 40% threshold the +30% save growth is not a regression
    p = _cli(BASE, REGRESS, "--gate", "--threshold-pct", "40")
    assert p.returncode == 0, p.stdout + p.stderr


def test_refuses_scaled_down_vs_nominal():
    """Bench honesty: config 3 ran 256 groups standing in for the 10k
    nominal regime in the base fixture, and at nominal scale in the
    other — comparing them would measure different workloads. Exit 2,
    gate or not."""
    for extra in ((), ("--gate",)):
        p = _cli(BASE, NOMINAL, *extra)
        assert p.returncode == 2, p.stdout + p.stderr
        assert "INCOMPARABLE" in p.stdout
        assert "scaled_down" in p.stdout


def test_json_report_shape():
    p = _cli(BASE, REGRESS, "--json")
    rep = json.loads(p.stdout.splitlines()[0])
    assert rep["verdict"] == FAIL
    c1 = rep["configs"]["1"]
    assert c1["verdict"] == FAIL
    assert c1["phases"]["save"]["regressed"] is True
    assert c1["phases"]["save"]["delta_pct"] == pytest.approx(30.0)
    # untouched config stays comparable and clean
    assert rep["configs"]["3"]["verdict"] == PASS


# ---------------------------------------------------------------------------
# API semantics
# ---------------------------------------------------------------------------


def test_hbm_and_counter_deltas_are_informational():
    """ISSUE 18: census keys and counter totals surface as deltas but
    NEVER gate — doubling the waste ratio and 10x-ing every counter
    still passes, and the render labels the section (info)."""
    with open(HBM) as f:
        rec = json.load(f)
    worse = json.loads(json.dumps(rec))
    for cfg in worse["configs"].values():
        cfg["hbm_bytes_total"] *= 2
        cfg["hbm_waste_ratio"] = min(0.99, cfg["hbm_waste_ratio"] * 1.3)
        cfg["counters"] = {k: v * 10 for k, v in cfg["counters"].items()}
    rep = compare(rec, worse)
    assert rep["verdict"] == PASS
    c1 = rep["configs"]["1"]
    assert c1["hbm"]["hbm_bytes_total"]["delta_pct"] == pytest.approx(100.0)
    assert c1["counters"]["heartbeats_sent"]["new"] == 84000
    assert not c1["reasons"]
    assert "hbm (info)" in render(rep)
    # CLI golden-fixture check: identical hbm-stamped runs gate clean
    p = _cli(HBM, HBM, "--gate")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "hbm (info)" in p.stdout


def test_legacy_records_without_census_keys_keep_comparing():
    """A legacy record (no hbm_*/counters keys) against an hbm-stamped
    one compares exactly as before: same verdict, no hbm/counters
    section, no refusal — the census is an annotation, not a schema
    break."""
    rep = compare(load_record(BASE), load_record(HBM))
    assert rep["verdict"] == PASS
    for c in rep["configs"].values():
        assert "hbm" not in c
        assert "counters" not in c
    p = _cli(BASE, HBM, "--gate")
    assert p.returncode == 0, p.stdout + p.stderr


def test_phase_regression_rule():
    # relative threshold
    assert phase_regressed(1.0, 1.3, 20.0, 0.001)
    assert not phase_regressed(1.0, 1.1, 20.0, 0.001)
    # absolute noise floor: a near-zero phase jittering stays clean...
    assert not phase_regressed(0.0001, 0.0005, 20.0, 0.001)
    # ...but growth from zero past the floor is always a regression
    assert phase_regressed(0.0, 0.01, 20.0, 0.001)
    # improvements never regress
    assert not phase_regressed(2.0, 1.0, 20.0, 0.001)


def test_out_of_seam_sync_growth_fails():
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    b["device_syncs"]["out_of_seam"] = 3
    b["device_syncs"]["sites"] = {"engine/vector.py:9:_decode": 3}
    r = compare_config(a, b)
    assert r["verdict"] == FAIL
    assert any("out-of-seam" in s for s in r["reasons"])


def test_watched_function_retrace_fails():
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    b["compile_events"]["total"] = 3
    b["compile_events"]["per_function"] = {"step_batch[g4]": 3}
    r = compare_config(a, b)
    assert r["verdict"] == FAIL
    assert any("retraces" in s for s in r["reasons"])


def test_unwatched_lazy_compile_does_not_gate():
    """A one-time lazy compile of a rare maintenance op (total grows,
    no watched function retraced) is NOT a regression — it would make
    the gate flaky across warm/cold compile caches."""
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    b["compile_events"]["total"] = 1
    r = compare_config(a, b)
    assert r["verdict"] == PASS


def test_refuses_steps_per_sync_mismatch():
    """The K honesty rule (same shape as the scaled-down refusal): a
    K=8 multi-step run measures a different engine than a K=1 run —
    per-phase host seconds and client latency are not comparable, so
    the diff refuses instead of printing a fake win/regression."""
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    b["steps_per_sync"] = 8
    r = compare_config(a, b)
    assert r["verdict"] == INCOMPARABLE
    assert any("steps_per_sync" in s for s in r["reasons"])
    # and in reverse (new side predates the stamp -> implicit K=1)
    r = compare_config(b, a)
    assert r["verdict"] == INCOMPARABLE


def test_refuses_front_vs_raw_workload_mismatch():
    """The through-front honesty rule (ISSUE 14, same shape as the K
    refusal): an ADMITTED-throughput run (SessionManager/ServingFront in
    the path) measures a different machine than a raw propose_batch run
    — the diff refuses instead of reading the admission stack's cost as
    a regression. A missing stamp means raw (the pre-front trajectory
    keeps comparing), and session_mode alone implies through_front."""
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    b["workload"] = "through_front"
    b["session_mode"] = "sessions"
    b["placement_enabled"] = True
    r = compare_config(a, b)
    assert r["verdict"] == INCOMPARABLE
    assert any("workload" in s for s in r["reasons"])
    r = compare_config(b, a)
    assert r["verdict"] == INCOMPARABLE
    # front-vs-front compares normally (the config-7 trajectory gates
    # against itself)
    b2 = json.loads(json.dumps(b))
    assert compare_config(b, b2)["verdict"] == PASS
    # a legacy record with only the session_mode stamp still refuses
    legacy_front = json.loads(json.dumps(a))
    legacy_front["session_mode"] = "sessions"
    assert compare_config(a, legacy_front)["verdict"] == INCOMPARABLE


def test_refuses_mesh_shape_mismatch():
    """The mesh honesty rule (same shape as the scaled-down / K /
    workload refusals): a run sharded over 8 devices measures a
    different device topology than a 1-device run — the diff refuses
    instead of reading the topology change as a win or regression.
    Golden-fixture CLI check plus both API directions."""
    mesh8 = os.path.join(_DATA, "perfdiff_mesh8.json")
    for extra in ((), ("--gate",)):
        p = _cli(BASE, mesh8, *extra)
        assert p.returncode == 2, p.stdout + p.stderr
        assert "INCOMPARABLE" in p.stdout
        assert "mesh" in p.stdout
    a = load_record(BASE)["configs"]["1"]
    b = load_record(mesh8)["configs"]["1"]
    r = compare_config(a, b)
    assert r["verdict"] == INCOMPARABLE
    assert any("mesh" in s for s in r["reasons"])
    # and in reverse (new side predates the stamp -> implicit 1 device)
    r = compare_config(b, a)
    assert r["verdict"] == INCOMPARABLE
    # mesh-vs-same-mesh compares normally: the sharded trajectory gates
    # against its own baseline without refusal
    b2 = json.loads(json.dumps(b))
    assert compare_config(b, b2)["verdict"] == PASS
    # a legacy record with no stamp is a 1-device run by construction,
    # comparable with a modern explicit 1-device stamp
    a1 = json.loads(json.dumps(a))
    a1["n_devices"] = 1
    a1["mesh_shape"] = [1]
    assert compare_config(a, a1)["verdict"] == PASS


def test_refuses_lease_vs_readindex_reads():
    """The read-mode honesty rule (ISSUE 17, same shape as the K /
    workload / mesh refusals): a lease-read run serves reads locally at
    the leader while a ReadIndex run pays a quorum confirmation per
    read batch — diffing them would read the lease win as a ReadIndex
    regression (or vice versa). Golden-fixture CLI check plus both API
    directions; a missing stamp means ReadIndex (every pre-lease record
    keeps comparing)."""
    lease = os.path.join(_DATA, "perfdiff_lease.json")
    for extra in ((), ("--gate",)):
        p = _cli(BASE, lease, *extra)
        assert p.returncode == 2, p.stdout + p.stderr
        assert "INCOMPARABLE" in p.stdout
        assert "read_mode" in p.stdout
    a = load_record(BASE)["configs"]["1"]
    b = load_record(lease)["configs"]["1"]
    r = compare_config(a, b)
    assert r["verdict"] == INCOMPARABLE
    assert any("read_mode" in s for s in r["reasons"])
    # and in reverse (new side predates the stamp -> implicit readindex)
    r = compare_config(b, a)
    assert r["verdict"] == INCOMPARABLE
    assert any("read_mode" in s for s in r["reasons"])
    # lease-vs-lease compares normally: the lease trajectory gates
    # against its own baseline without refusal
    b2 = json.loads(json.dumps(b))
    assert compare_config(b, b2)["verdict"] == PASS
    # a legacy record with no stamp is a ReadIndex run by construction,
    # comparable with a modern explicit readindex stamp
    a1 = json.loads(json.dumps(a))
    a1["read_mode"] = "readindex"
    assert compare_config(a, a1)["verdict"] == PASS


def test_same_steps_per_sync_stays_comparable():
    """Two runs at the SAME K>1 diff normally (the K=8 trajectory can
    gate against itself), and a missing stamp means the classic K=1
    engine, comparable with an explicit K=1 stamp."""
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    a8 = json.loads(json.dumps(a))
    a8["steps_per_sync"] = 8
    b8 = json.loads(json.dumps(b))
    b8["steps_per_sync"] = 8
    assert compare_config(a8, b8)["verdict"] == PASS
    explicit1 = json.loads(json.dumps(b))
    explicit1["steps_per_sync"] = 1
    assert compare_config(a, explicit1)["verdict"] == PASS


def test_refuses_cross_host_records():
    """The box honesty rule: two records stamped with different host
    fingerprints measure hardware, not code — whole-record refusal
    before any config is compared. One-sided stamps refuse too (the
    unstamped side's provenance is unknown)."""
    a = load_record(BASE)
    b = json.loads(json.dumps(a))
    a["host"] = {"id": "box-a/8cpu", "calib_s": 0.1}
    b["host"] = {"id": "box-b/64cpu", "calib_s": 0.03}
    r = compare(a, b)
    assert r["verdict"] == INCOMPARABLE
    assert any("host mismatch" in s for s in r["reasons"])
    assert "box-a/8cpu" in render(r)
    # one-sided: legacy old vs stamped new (the r05 -> r06 seam)
    legacy = load_record(BASE)
    r = compare(legacy, b)
    assert r["verdict"] == INCOMPARABLE
    assert any("provenance unknown" in s for s in r["reasons"])
    r = compare(b, legacy)
    assert r["verdict"] == INCOMPARABLE


def test_same_host_and_legacy_pairs_stay_comparable():
    """Same fingerprint diffs normally (the gate's steady state), and
    two legacy records (neither stamped) keep comparing — the pre-stamp
    trajectory loses nothing retroactively."""
    a = load_record(BASE)
    b = json.loads(json.dumps(a))
    assert compare(a, b)["verdict"] == PASS  # legacy vs legacy
    a["host"] = {"id": "box-a/8cpu", "calib_s": 0.1}
    b["host"] = {"id": "box-a/8cpu", "calib_s": 0.4}  # load differs: ok
    assert compare(a, b)["verdict"] == PASS


def test_both_scaled_to_different_widths_incomparable():
    a = load_record(BASE)["configs"]["3"]
    b = json.loads(json.dumps(a))
    b["actual_groups"] = 128
    r = compare_config(a, b)
    assert r["verdict"] == INCOMPARABLE


def test_throughput_drop_fails():
    a = load_record(BASE)["configs"]["1"]
    b = json.loads(json.dumps(a))
    b["value"] = a["value"] * 0.7  # -30%
    r = compare_config(a, b)
    assert r["verdict"] == FAIL
    assert any("throughput" in s for s in r["reasons"])


def test_legacy_vs_modern_normalizes_renamed_phases():
    """Across the PR 6 rename boundary a legacy record's 'step' stage is
    the modern 'fetch', and its 'apply' covered decode phases 4+5 — so
    the modern side's apply+reads fold together. A real fetch/apply
    regression must not hide behind the vocabulary change."""
    legacy = {"configs": {"2": {"value": 100.0, "host_stage_total_s": {
        "step": 1.0, "apply": 1.0, "save": 0.5}}}}
    modern = {"configs": {"2": {"value": 100.0, "phase_breakdown": {
        "fetch": 1.5, "apply": 0.9, "reads": 0.4, "save": 0.5}}}}
    rep = compare(legacy, modern)
    c = rep["configs"]["2"]
    # old 'step' diffed against new 'fetch': +50% -> regression
    assert c["phases"]["fetch"]["regressed"] is True
    # old combined apply(1.0) vs new apply+reads(1.3): +30% -> regression
    assert c["phases"]["apply"]["regressed"] is True
    assert "reads" not in c["phases"]
    assert not c["phases"]["save"].get("regressed")


def test_legacy_records_fall_back_to_host_stage_totals():
    """Pre-attribution-plane BENCH records carry host_stage_total_s but
    no phase_breakdown: the shared phases still diff."""
    a = {"configs": {"2": {"value": 100.0,
                           "host_stage_total_s": {"save": 1.0, "pack": 0.5}}}}
    b = {"configs": {"2": {"value": 100.0,
                           "host_stage_total_s": {"save": 1.5, "pack": 0.5}}}}
    rep = compare(a, b)
    assert rep["verdict"] == FAIL
    assert rep["configs"]["2"]["phases"]["save"]["regressed"] is True
    assert "save" in render(rep)


def test_trajectory_directory_mode(tmp_path):
    """One directory argument: consecutive BENCH_*.json pairs diff, the
    gate rides the newest pair."""
    shutil.copy(BASE, tmp_path / "BENCH_r01.json")
    shutil.copy(BASE, tmp_path / "BENCH_r02.json")
    shutil.copy(REGRESS, tmp_path / "BENCH_r03.json")
    assert main([str(tmp_path), "--gate"]) == 1
    # with the regression as the OLDER step and a recovery as newest,
    # the gate passes (it certifies the newest transition)
    shutil.copy(BASE, tmp_path / "BENCH_r04.json")
    assert main([str(tmp_path), "--gate"]) == 0


def test_real_bench_trajectory_is_loadable():
    """The checked-in BENCH_r0x trajectory parses and diffs (legacy
    schema: no phase_breakdown, no gate expectations — just no crash)."""
    paths = sorted(
        os.path.join(_REPO, f)
        for f in os.listdir(_REPO)
        if f.startswith("BENCH_r") and f.endswith(".json")
    )
    if len(paths) < 2:
        pytest.skip("no trajectory checked in")
    rep = compare(load_record(paths[-2]), load_record(paths[-1]))
    assert rep["verdict"] in (PASS, FAIL, INCOMPARABLE)
    assert render(rep)
