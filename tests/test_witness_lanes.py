"""Witness/observer LANE VARIANTS at vector scale (thesis 4.2.1 /
11.7.2 — the scalar conformance lives in test_witness_conformance /
test_observer_conformance; this file proves the vector engine's per-lane
role tensors + payload-stripped replication end to end):

  * a witness joined through the membership-change API votes/acks and
    counts toward the commit quorum while storing ZERO payload bytes
    (lane_stats probe) and never mutating its SM;
  * an observer replicates the full log (SM converges) but never
    campaigns or votes, and promotes to a full member via add_node;
  * both lane flavors survive removal and re-join (the membership-change
    scenario family at vector scale).
"""
import json
import threading
import time
import zlib

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.ops.state import ROLE
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

CLUSTER = 7


class KV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, engine_kind="vector"):
    return NodeHost(
        NodeHostConfig(
            deployment_id=9,
            rtt_millisecond=5,
            raft_address=f"wl{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(
                kind=engine_kind, max_groups=32, max_peers=4, log_window=64
            ),
        )
    )


def _cfg(nid, **kw):
    base = dict(
        cluster_id=CLUSTER, node_id=nid, election_rtt=20, heartbeat_rtt=4
    )
    base.update(kw)
    return Config(**base)


def _wait_leader(hosts, deadline_s=30.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for nid, nh in hosts.items():
            try:
                lid, ok = nh.get_leader_id(CLUSTER)
            except Exception:
                continue
            if ok and lid == nid:
                return nid
        time.sleep(0.02)
    raise AssertionError("no leader")


def _propose_n(nh, n, tag, timeout_s=5.0):
    s = nh.get_noop_session(CLUSTER)
    for i in range(n):
        nh.sync_propose(s, f"k{i % 4}={tag}{i}".encode(), timeout_s=timeout_s)


@pytest.fixture
def two_plus_witness():
    """Hosts 1,2 full members; host 3 joins as a WITNESS through the
    membership-change API (request_add_witness + join start)."""
    reg = _Registry()
    hosts = {nid: _mk_host(nid, reg) for nid in (1, 2, 3)}
    members = {1: "wl1:1", 2: "wl2:1"}
    for nid in (1, 2):
        hosts[nid].start_cluster(
            members, False, lambda c, n: KV(), _cfg(nid)
        )
    leader = _wait_leader({n: hosts[n] for n in (1, 2)})
    hosts[leader].sync_request_add_witness(
        CLUSTER, 3, "wl3:1", timeout_s=10.0
    )
    hosts[3].start_cluster(
        {}, True, lambda c, n: KV(), _cfg(3, is_witness=True)
    )
    try:
        yield hosts, leader
    finally:
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass


def test_witness_lane_zero_payload_and_role(two_plus_witness):
    """Across a seeded traffic run the witness lane reports the WITNESS
    role and ZERO resident payload bytes, and its SM never applies a
    client update (the empty-SM hash)."""
    hosts, leader = two_plus_witness
    _propose_n(hosts[leader], 60, "w")
    # let replication toward the witness settle
    deadline = time.monotonic() + 20
    stats = None
    while time.monotonic() < deadline:
        stats = hosts[3].engine.lane_stats().get(CLUSTER)
        if stats is not None and stats["term"] > 0:
            break
        time.sleep(0.05)
    assert stats is not None, "witness lane never activated"
    assert stats["role"] == ROLE.WITNESS, stats
    assert stats["payload_bytes"] == 0, (
        f"witness lane stored payload bytes: {stats}"
    )
    # the witness SM never saw a client update
    empty_hash = KV().get_hash()
    assert hosts[3].get_sm_hash(CLUSTER) == empty_hash
    # the full members DID apply the payloads
    assert hosts[leader].get_sm_hash(CLUSTER) != empty_hash


def test_witness_counts_toward_commit_quorum(two_plus_witness):
    """2 full members + 1 witness = 3 voters, quorum 2. With one full
    member down, commit requires the WITNESS ack — proposals that still
    commit prove the witness is a live quorum participant."""
    hosts, leader = two_plus_witness
    _propose_n(hosts[leader], 10, "pre")
    # wait until the witness is an acking member (its lane is active and
    # past the join): commit with follower down requires it
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        st = hosts[3].engine.lane_stats().get(CLUSTER)
        if st is not None and st["leader_id"] == leader:
            break
        time.sleep(0.05)
    follower = 2 if leader == 1 else 1
    hosts[follower].stop_cluster(CLUSTER)
    try:
        # leader + witness form the quorum now
        _propose_n(hosts[leader], 5, "q", timeout_s=10.0)
    finally:
        hosts[follower].restart_cluster(CLUSTER)
    st = hosts[3].engine.lane_stats().get(CLUSTER)
    assert st is not None and st["payload_bytes"] == 0


def test_observer_replicates_without_voting_then_promotes():
    """An observer lane replicates + applies the full log (SM hash
    converges) but never votes or campaigns; add_node promotes it to a
    full member in place."""
    reg = _Registry()
    hosts = {nid: _mk_host(nid, reg) for nid in (1, 2, 3)}
    members = {1: "wl1:1", 2: "wl2:1"}
    try:
        for nid in (1, 2):
            hosts[nid].start_cluster(
                members, False, lambda c, n: KV(), _cfg(nid)
            )
        leader = _wait_leader({n: hosts[n] for n in (1, 2)})
        hosts[leader].sync_request_add_observer(
            CLUSTER, 3, "wl3:1", timeout_s=10.0
        )
        hosts[3].start_cluster(
            {}, True, lambda c, n: KV(), _cfg(3, is_observer=True)
        )
        _propose_n(hosts[leader], 40, "o")
        # the observer applies the full payload log: hash convergence
        want = hosts[leader].get_sm_hash(CLUSTER)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                if hosts[3].get_sm_hash(CLUSTER) == want:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert hosts[3].get_sm_hash(CLUSTER) == want, "observer diverged"
        st = hosts[3].engine.lane_stats().get(CLUSTER)
        assert st is not None and st["role"] == ROLE.OBSERVER
        # observers never campaign: leadership stayed where it was
        lid, ok = hosts[leader].get_leader_id(CLUSTER)
        assert ok and lid == leader
        # promote to full member, in place
        hosts[leader].sync_request_add_node(
            CLUSTER, 3, "wl3:1", timeout_s=10.0
        )
        _propose_n(hosts[leader], 5, "p")
        deadline = time.monotonic() + 20
        role = None
        while time.monotonic() < deadline:
            st = hosts[3].engine.lane_stats().get(CLUSTER)
            role = st["role"] if st else None
            if role == ROLE.FOLLOWER:
                break
            time.sleep(0.05)
        assert role == ROLE.FOLLOWER, f"observer not promoted: role={role}"
    finally:
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass


def test_witness_removal_and_rejoin():
    """The churn half: remove the witness, re-add a FRESH witness id, and
    the group keeps committing throughout (membership change over lane
    variants at vector scale)."""
    reg = _Registry()
    hosts = {nid: _mk_host(nid, reg) for nid in (1, 2, 3)}
    members = {1: "wl1:1", 2: "wl2:1"}
    try:
        for nid in (1, 2):
            hosts[nid].start_cluster(
                members, False, lambda c, n: KV(), _cfg(nid)
            )
        leader = _wait_leader({n: hosts[n] for n in (1, 2)})
        hosts[leader].sync_request_add_witness(
            CLUSTER, 3, "wl3:1", timeout_s=10.0
        )
        hosts[3].start_cluster(
            {}, True, lambda c, n: KV(), _cfg(3, is_witness=True)
        )
        _propose_n(hosts[leader], 10, "a")
        hosts[leader].sync_request_delete_node(CLUSTER, 3, timeout_s=10.0)
        hosts[3].stop_cluster(CLUSTER)
        _propose_n(hosts[leader], 10, "b")
        # fresh witness id on the same host (removed ids never rejoin)
        hosts[leader].sync_request_add_witness(
            CLUSTER, 4, "wl3:1", timeout_s=10.0
        )
        hosts[3].start_cluster(
            {}, True, lambda c, n: KV(),
            _cfg(4, is_witness=True),
        )
        _propose_n(hosts[leader], 10, "c")
        m = hosts[leader].get_cluster_membership(CLUSTER)
        assert 4 in m.witnesses and 3 not in m.witnesses
        st = hosts[3].engine.lane_stats().get(CLUSTER)
        assert st is not None and st["payload_bytes"] == 0
    finally:
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass


def test_witness_zero_payload_on_cohosted_multistep(tmp_path):
    """The device-routing bypass regression: on a SHARED-core engine at
    steps_per_sync>1, co-hosted replication is routed on device — but
    witness-bound traffic must stay on the (payload-stripping) host
    path, or full client payloads land in the witness arena. Route
    tables exclude wit_slots; this asserts the zero-payload contract in
    exactly that configuration."""
    reg = _Registry()
    scope = "wl-multistep"
    members = {1: "wms1:1", 2: "wms2:1"}

    def mk(nid):
        return NodeHost(
            NodeHostConfig(
                deployment_id=9,
                rtt_millisecond=10,
                nodehost_dir=str(tmp_path / f"wms{nid}"),
                raft_address=f"wms{nid}:1",
                raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
                engine=EngineConfig(
                    kind="vector", max_groups=8, max_peers=4, log_window=64,
                    inbox_depth=8, max_entries_per_msg=8, share_scope=scope,
                    steps_per_sync=4,
                ),
            )
        )

    hosts = {nid: mk(nid) for nid in (1, 2, 3)}
    try:
        for nid in (1, 2):
            hosts[nid].start_cluster(
                members, False, lambda c, n: KV(), _cfg(nid)
            )
        leader = _wait_leader({n: hosts[n] for n in (1, 2)}, deadline_s=120)
        hosts[leader].sync_request_add_witness(
            CLUSTER, 3, "wms3:1", timeout_s=15.0
        )
        hosts[3].start_cluster(
            {}, True, lambda c, n: KV(), _cfg(3, is_witness=True)
        )
        _propose_n(hosts[leader], 40, "co", timeout_s=10.0)
        deadline = time.monotonic() + 30
        st = None
        while time.monotonic() < deadline:
            st = hosts[3].engine.lane_stats().get(CLUSTER)
            if st is not None and st["term"] > 0 and st["leader_id"] == leader:
                break
            time.sleep(0.05)
        assert st is not None and st["role"] == ROLE.WITNESS, st
        assert st["payload_bytes"] == 0, (
            f"co-hosted device routing leaked payload into the witness "
            f"lane: {st}"
        )
    finally:
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass
