"""Observer conformance matrix (cf. internal/raft/raft_test.go:318-723,
raft thesis 4.2.1): a non-voting member replicates and can forward
proposals/reads but never votes or campaigns; it can be promoted to a
voting member (including via a snapshot whose membership lists it as
full), and a full member can never be demoted back by a stale snapshot."""
import pytest

from dragonboat_tpu.core.raft import RaftNodeState
from dragonboat_tpu.core.remote import Remote
from dragonboat_tpu.types import (
    Membership,
    Message,
    MessageType as MT,
    Snapshot,
)
from tests.raft_harness import Network, new_test_raft


def cluster_with_observer():
    """2 voting members + node 3 as observer, leader elected."""
    r1 = new_test_raft(1, [1, 2])
    r2 = new_test_raft(2, [1, 2])
    for r in (r1, r2):
        r.observers[3] = Remote(next=1)
    obs = new_test_raft(3, [], is_observer=True)
    obs.remotes[1] = Remote(next=1)
    obs.remotes[2] = Remote(next=1)
    obs.observers[3] = Remote(next=1)
    net = Network({1: r1, 2: r2, 3: obs})
    net.elect(1)
    assert r1.is_leader()
    return net, r1, obs


def test_observer_will_not_start_election():
    _, _, obs = cluster_with_observer()
    obs.msgs.clear()
    for _ in range(20 * obs.election_timeout):
        obs.tick()
    assert [m for m in obs.msgs if m.type == MT.REQUEST_VOTE] == []


def test_observer_vote_not_counted():
    """An observer may answer a vote request, but a candidate cannot win
    with observer support alone: quorum counts voting members only."""
    r1 = new_test_raft(1, [1, 2, 4])  # 2 and 4 never respond
    r1.observers[3] = Remote(next=1)
    net = Network({1: r1})
    net.elect(1)  # self-vote only: 1 of 3 voting members
    assert not r1.is_leader()
    # an (erroneous or stale) grant FROM THE OBSERVER must not tip the
    # count: quorum is over voting members (raft.go vote-resp handler)
    r1.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=3, to=1,
                      term=r1.term))
    assert not r1.is_leader()
    # the same grant from a real voting member completes the quorum
    r1.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=2, to=1,
                      term=r1.term))
    assert r1.is_leader()


def test_observer_replicates_payloads():
    net, leader, obs = cluster_with_observer()
    net.propose(1, b"observer-sees-this")
    ents = obs.log.get_entries(1, obs.log.last_index() + 1, 1 << 30)
    assert any(e.cmd == b"observer-sees-this" for e in ents)
    assert obs.log.committed == leader.log.committed


def test_observer_forwards_proposal_to_leader():
    from dragonboat_tpu.types import Entry

    net, leader, obs = cluster_with_observer()
    before = leader.log.last_index()
    obs.handle(Message(type=MT.PROPOSE, from_=3, to=3,
                       entries=[Entry(cmd=b"via-observer")]))
    net.deliver_all()
    assert leader.log.last_index() > before
    ents = leader.log.get_entries(1, leader.log.last_index() + 1, 1 << 30)
    assert any(e.cmd == b"via-observer" for e in ents)


def test_observer_promotion_to_voting_member():
    """ADD_NODE on an observer id promotes it; afterwards it votes and can
    win elections (raft_test.go:346-414)."""
    net, leader, obs = cluster_with_observer()
    for r in net.rafts.values():
        r.add_node(3)
    assert 3 in leader.remotes and 3 not in leader.observers
    assert obs.state != RaftNodeState.OBSERVER
    # the promoted node can now be elected
    net.elect(3)
    assert net.rafts[3].is_leader()


def test_observer_can_receive_snapshot():
    _, _, obs = cluster_with_observer()
    mem = Membership(addresses={1: "a1", 2: "a2"}, observers={3: "o3"})
    obs.handle(Message(type=MT.INSTALL_SNAPSHOT, from_=1, to=3, term=20,
                       snapshot=Snapshot(index=20, term=20, membership=mem)))
    assert obs.log.committed == 20


def test_observer_promoted_by_snapshot_membership():
    """A snapshot whose membership lists the observer as a full member
    promotes it during restore (raft_test.go:612-668)."""
    _, _, obs = cluster_with_observer()
    mem = Membership(addresses={1: "a1", 2: "a2", 3: "a3"})
    ss = Snapshot(index=20, term=20, membership=mem)
    obs.handle(Message(type=MT.INSTALL_SNAPSHOT, from_=1, to=3, term=20,
                       snapshot=ss))
    assert obs.log.committed == 20
    # the engine applies the snapshot's membership after SM recovery
    # (node._do_recover_snapshot -> peer.restore_remotes)
    obs.restore_remotes(ss)
    assert 3 in obs.remotes
    assert obs.state != RaftNodeState.OBSERVER


def test_full_member_cannot_be_demoted_by_snapshot():
    """restore() refuses a snapshot that would move a voting member back
    to observer (raft_test.go:670-693)."""
    r1 = new_test_raft(1, [1, 2])
    net = Network({1: r1, 2: new_test_raft(2, [1, 2])})
    net.elect(1)
    follower = net.rafts[2]
    mem = Membership(addresses={1: "a1"}, observers={2: "o2"})
    with pytest.raises(RuntimeError,
                       match="converting non-observer to observer"):
        follower.handle(Message(
            type=MT.INSTALL_SNAPSHOT, from_=1, to=2, term=20,
            snapshot=Snapshot(index=20, term=20, membership=mem),
        ))


def test_observer_add_and_remove():
    net, leader, obs = cluster_with_observer()
    # add another observer
    for r in net.rafts.values():
        r.add_observer(4)
    assert 4 in leader.observers
    # remove the first one
    for r in net.rafts.values():
        r.remove_node(3)
    assert 3 not in leader.observers
