"""Restart/rejoin as a first-class fault: the restart-plane tests.

Covers the ISSUE 7 tentpole end to end:

  * stop_cluster / restart_cluster detach a node from a live engine and
    rejoin it through WAL recovery + leader catch-up;
  * crash_cluster is SIGKILL-equivalent (no flush) and a restarted node
    that the leader compacted past rejoins via SNAPSHOT INSTALL;
  * lane hygiene: 50x start/stop/restart cycles leak no lanes (the
    VectorEngine free list returns to its initial size — ISSUE 7
    satellite: zero the freed lane's planes, return the index);
  * seeded crash_restart decision streams replay bit-identically
    (FaultPlane.crash_restart_schedule schedule-signature match);
  * graceful degradation: while one replica is down or catching up, the
    surviving quorum's throughput stays within 20% of the 3-healthy
    baseline and the fairness watchdog reports no stall (tier-1).
"""
import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.faults import FaultPlane, FaultSpec
from dragonboat_tpu.lincheck import HistoryRecorder, check_kv_history
from dragonboat_tpu.nodehost import ErrClusterAlreadyExist, NodeHost
from dragonboat_tpu.requests import ErrClusterNotFound, RequestError
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.trace import flight_recorder
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

CLUSTER = 1
HOSTS = (1, 2, 3)


class KVSM(IStateMachine):
    def __init__(self, cluster_id=0, node_id=0):
        self.d = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        import json
        import zlib

        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        import json

        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        import json

        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, tmp, engine_kind, snapshot_entries=0,
             compaction_overhead=0):
    cfg = NodeHostConfig(
        deployment_id=5,
        rtt_millisecond=5,
        nodehost_dir=f"{tmp}/h{nid}",
        raft_address=f"c{nid}:1",
        raft_rpc_factory=lambda listen, reg=reg: loopback_factory(listen, reg),
        engine=EngineConfig(
            kind=engine_kind, max_groups=32, max_peers=4, log_window=64
        ),
    )
    nh = NodeHost(cfg)
    nh.start_cluster(
        {h: f"c{h}:1" for h in HOSTS},
        False,
        lambda c, n: KVSM(c, n),
        Config(
            cluster_id=CLUSTER, node_id=nid, election_rtt=20,
            heartbeat_rtt=4, snapshot_entries=snapshot_entries,
            compaction_overhead=compaction_overhead,
        ),
    )
    return nh


def _find_leader(hosts, deadline_s=30.0, exclude=()):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for nid, nh in hosts.items():
            if nh is None or nid in exclude:
                continue
            try:
                lid, ok = nh.get_leader_id(CLUSTER)
            except Exception:
                continue
            if ok and lid == nid:
                return nid
        time.sleep(0.02)
    return None


def _propose_until(hosts, n, prefix, deadline_s=60.0, exclude=()):
    """Drive n committed writes through whatever leader exists."""
    done = 0
    deadline = time.monotonic() + deadline_s
    while done < n and time.monotonic() < deadline:
        leader = _find_leader(hosts, deadline_s=10.0, exclude=exclude)
        if leader is None:
            continue
        nh = hosts[leader]
        try:
            s = nh.get_noop_session(CLUSTER)
            nh.sync_propose(s, f"{prefix}{done}=v{done}".encode(), 2.0)
            done += 1
        except Exception:
            time.sleep(0.05)
    assert done == n, f"only {done}/{n} proposals committed"


def _wait_converged(hosts, deadline_s=45.0):
    deadline = time.monotonic() + deadline_s
    idx = {}
    while time.monotonic() < deadline:
        try:
            idx = {nid: nh.get_applied_index(CLUSTER)
                   for nid, nh in hosts.items()}
        except Exception:
            time.sleep(0.1)
            continue
        if len(set(idx.values())) == 1:
            hashes = {nid: nh.get_sm_hash(CLUSTER)
                      for nid, nh in hosts.items()}
            if len(set(hashes.values())) == 1:
                return True
        time.sleep(0.05)
    raise AssertionError(f"replicas never converged: {idx}")


# ---------------------------------------------------------------------------
# stop/restart rejoin + crash/restart with snapshot install
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("engine_kind", ["scalar", "vector"])
def test_stop_restart_cluster_rejoins_live_group(tmp_path, engine_kind):
    """Graceful detach + in-process restart: the restarted node replays
    its WAL, catches up from the leader and converges."""
    reg = _Registry()
    hosts = {n: _mk_host(n, reg, str(tmp_path), engine_kind) for n in HOSTS}
    try:
        _propose_until(hosts, 5, "a")
        leader = _find_leader(hosts)
        victim = next(n for n in HOSTS if n != leader)
        hosts[victim].stop_cluster(CLUSTER)
        assert not hosts[victim].has_node(CLUSTER)
        # double stop raises, restart of a running cluster raises
        with pytest.raises(ErrClusterNotFound):
            hosts[victim].stop_cluster(CLUSTER)
        with pytest.raises(ErrClusterAlreadyExist):
            hosts[leader].restart_cluster(CLUSTER)
        # quorum keeps serving while the victim is down
        _propose_until(hosts, 10, "b", exclude=(victim,))
        hosts[victim].restart_cluster(CLUSTER)
        assert hosts[victim].has_node(CLUSTER)
        _propose_until(hosts, 3, "c")
        _wait_converged(hosts)
    finally:
        for nh in hosts.values():
            nh.stop()


@pytest.mark.parametrize("engine_kind", ["vector"])
def test_crash_restart_with_snapshot_install(tmp_path, engine_kind):
    """Crash a follower, commit enough for the leader to snapshot and
    compact past the crashed node's log, restart: the rejoiner MUST take
    the snapshot-install path (flight-recorder `snapshot_installed`) and
    still converge. Live proposals run throughout; the recorded history
    stays linearizable."""
    reg = _Registry()
    hosts = {
        n: _mk_host(n, reg, str(tmp_path), engine_kind,
                    snapshot_entries=25, compaction_overhead=5)
        for n in HOSTS
    }
    rec = HistoryRecorder()
    try:
        _propose_until(hosts, 5, "w")
        leader = _find_leader(hosts)
        victim = next(n for n in HOSTS if n != leader)
        crash_index = hosts[victim].get_applied_index(CLUSTER)
        hosts[victim].crash_cluster(CLUSTER)
        # drive well past snapshot_entries so the leader compacts past
        # the victim's index while it is down (recorded for lincheck)
        for i in range(60):
            leader = _find_leader(hosts, exclude=(victim,))
            nh = hosts[leader]
            op = rec.invoke(0, ("put", "k", f"v{i}"))
            try:
                s = nh.get_noop_session(CLUSTER)
                nh.sync_propose(s, f"k=v{i}".encode(), 2.0)
                rec.complete(op, None)
            except RequestError:
                rec.unknown(op)
        hosts[victim].restart_cluster(CLUSTER)
        _propose_until(hosts, 3, "z")
        _wait_converged(hosts, deadline_s=60.0)
        assert hosts[victim].get_applied_index(CLUSTER) > crash_index
        installs = [
            e for e in flight_recorder().dump(cluster_id=CLUSTER)
            if e["event"] == "snapshot_installed"
            and e.get("node") == victim and e.get("index", 0) > crash_index
        ]
        assert installs, (
            "rejoiner caught up without the snapshot-install path — the "
            "leader should have compacted past its index"
        )
        assert check_kv_history(rec.history(), max_states=2_000_000)
    finally:
        for nh in hosts.values():
            nh.stop()


@pytest.mark.slow
@pytest.mark.parametrize("engine_kind", ["scalar", "vector"])
def test_crash_restart_cycles_every_node(tmp_path, engine_kind):
    """Drummer-style: N crash/restart cycles of EACH node under live
    client traffic — lincheck green, replicas converged after every
    cycle completes."""
    reg = _Registry()
    hosts = {
        n: _mk_host(n, reg, str(tmp_path), engine_kind,
                    snapshot_entries=40, compaction_overhead=10)
        for n in HOSTS
    }
    rec = HistoryRecorder()
    stop = threading.Event()
    seq = [0]

    def client():
        cid = 1
        while not stop.is_set():
            leader = _find_leader(hosts, deadline_s=5.0)
            if leader is None:
                continue
            nh = hosts.get(leader)
            if nh is None:
                continue
            seq[0] += 1
            op = rec.invoke(cid, ("put", "key", f"v{seq[0]}"))
            try:
                s = nh.get_noop_session(CLUSTER)
                nh.sync_propose(s, f"key=v{seq[0]}".encode(), 2.0)
                rec.complete(op, None)
            except Exception:
                rec.unknown(op)
            time.sleep(0.005)

    t = threading.Thread(target=client, daemon=True)
    t.start()
    try:
        for cycle in range(2):
            for victim in HOSTS:
                hosts[victim].crash_cluster(CLUSTER)
                # the surviving quorum must commit while the victim is
                # down — not merely survive
                _propose_until(
                    hosts, 2, f"c{cycle}n{victim}-", deadline_s=30.0,
                    exclude=(victim,),
                )
                hosts[victim].restart_cluster(CLUSTER)
                time.sleep(0.2)
        stop.set()
        t.join(timeout=5)
        _propose_until(hosts, 3, "fin")
        _wait_converged(hosts, deadline_s=60.0)
        history = rec.history()
        assert len(history) > 5, "client landed no traffic across cycles"
        assert check_kv_history(history, max_states=5_000_000)
    finally:
        stop.set()
        for nh in hosts.values():
            nh.stop()


# ---------------------------------------------------------------------------
# seeded crash_restart decision streams replay bit-identically
# ---------------------------------------------------------------------------


def test_crash_restart_schedule_replays_bit_identically():
    spec = FaultSpec(tear_tail=0.4)

    def draw(seed):
        fp = FaultPlane(seed, spec)
        sched = []
        gen = fp.crash_restart_schedule("crash", HOSTS, total_s=10.0)
        for victim, down, idle, tear in gen:
            sched.append((victim, round(down, 9), round(idle, 9), tear))
        return sched, fp.schedule_signature()

    s1, sig1 = draw(0x5EED)
    s2, sig2 = draw(0x5EED)
    s3, sig3 = draw(0x5EED + 1)
    assert s1 == s2 and sig1 == sig2, "same seed must replay bit-identically"
    assert len(s1) >= 10  # a 10s budget yields many windows
    assert any(t for *_, t in s1) and not all(t for *_, t in s1), (
        "tear_tail=0.4 should fire on some but not all windows"
    )
    assert s3 != s1 and sig3 != sig1, "different seed must diverge"


def test_tear_wal_tails_sweeps_shards(tmp_path):
    """tear_wal_tails chops a seeded tail off every shard WAL under a
    closed logdb root, and recovery rolls back to sealed groups."""
    import os

    from dragonboat_tpu.storage.kv import WalKV, WriteBatch

    root = str(tmp_path / "logdb")
    for i in range(2):
        kv = WalKV(os.path.join(root, f"shard-{i}"))
        wb = WriteBatch()
        wb.put(b"k1", b"v1")
        kv.commit_write_batch(wb)
        kv.close()
    fp = FaultPlane(0xC0FFEE)
    removed = fp.tear_wal_tails(root, "tear")
    assert removed > 0
    # recovery still serves the sealed prefix (or an empty store — never
    # a crash)
    for i in range(2):
        kv = WalKV(os.path.join(root, f"shard-{i}"))
        assert kv.get_value(b"k1") in (b"v1", None)
        kv.close()


# ---------------------------------------------------------------------------
# lane hygiene: 50x restart cycles leak nothing
# ---------------------------------------------------------------------------


def test_vector_lane_reuse_50_restarts_no_growth(tmp_path):
    """ISSUE 7 satellite: start/stop/restart a cluster 50x on one vector
    engine — the free list returns to its initial size every time, the
    lane registry stays empty after stops, and the node still serves."""
    reg = _Registry()
    cfg = NodeHostConfig(
        deployment_id=5,
        rtt_millisecond=5,
        nodehost_dir=str(tmp_path / "h1"),
        raft_address="c1:1",
        raft_rpc_factory=lambda listen: loopback_factory(listen, reg),
        engine=EngineConfig(
            kind="vector", max_groups=32, max_peers=4, log_window=64
        ),
    )
    nh = NodeHost(cfg)
    core = nh.engine.core
    try:
        nh.start_cluster(
            {1: "c1:1"}, False, lambda c, n: KVSM(c, n),
            Config(cluster_id=CLUSTER, node_id=1, election_rtt=10,
                   heartbeat_rtt=2),
        )
        core.drain()
        with core._lanes_mu:
            free0 = len(core._free)
            lanes0 = len(core._lanes)
        assert lanes0 == 1
        for i in range(50):
            if i % 2:
                nh.crash_cluster(CLUSTER)
            else:
                nh.stop_cluster(CLUSTER)
            # stop_cluster/crash_cluster drain: the lane must already be
            # back on the free list — no settling sleep allowed here
            with core._lanes_mu:
                assert len(core._free) == free0 + 1, f"cycle {i}: lane leaked"
                assert len(core._lanes) == 0
                assert all(x is None for x in core._lane_by_g)
            nh.restart_cluster(CLUSTER)
            with core._lanes_mu:
                assert len(core._free) == free0, f"cycle {i}: free-list grew"
                assert len(core._lanes) == 1
        # the 50x-recycled lane still serves proposals
        deadline = time.monotonic() + 30
        while True:
            try:
                s = nh.get_noop_session(CLUSTER)
                nh.sync_propose(s, b"alive=yes", 2.0)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        assert nh.sync_read(CLUSTER, "alive") == "yes"
    finally:
        nh.stop()


# ---------------------------------------------------------------------------
# graceful degradation: quorum throughput + fairness while a peer is down
# ---------------------------------------------------------------------------


def _throughput(nh, seconds):
    """Committed proposals/second via pipelined batch waves on one host."""
    end = time.monotonic() + seconds
    done = 0
    while time.monotonic() < end:
        s = nh.get_noop_session(CLUSTER)
        try:
            brs = nh.propose_batch_async(
                s, [b"tp=%d" % done] * 64, timeout_s=2.0
            )
            brs.wait(3.0)
            done += brs.completed
        except Exception:
            time.sleep(0.02)
    return done / seconds


def test_quorum_throughput_and_fairness_while_peer_down(tmp_path):
    """ISSUE 7 acceptance: while one node is down (then catching up),
    the surviving quorum's throughput stays within 20% of the 3-healthy
    baseline and the fairness watchdog reports no starvation stall."""
    reg = _Registry()
    hosts = {n: _mk_host(n, reg, str(tmp_path), "vector") for n in HOSTS}
    try:
        _propose_until(hosts, 5, "warm")  # settle leadership + compile
        leader = _find_leader(hosts)
        lnh = hosts[leader]
        victim = next(n for n in HOSTS if n != leader)
        # windows are medians of 3 sub-windows: on shared CI boxes a
        # single window is too noisy for a 20% assertion
        base = sorted(_throughput(lnh, 1.0) for _ in range(3))[1]
        assert base > 0, "baseline produced no commits"
        for wd_host in hosts.values():
            wd = getattr(wd_host.engine, "watchdog", None)
            if wd is not None:
                wd.reset_window()
        hosts[victim].crash_cluster(CLUSTER)
        down = sorted(_throughput(lnh, 1.0) for _ in range(3))[1]
        # rejoin and measure DURING catch-up as well
        hosts[victim].restart_cluster(CLUSTER)
        catchup = _throughput(lnh, 1.0)
        assert down >= 0.8 * base, (
            f"quorum throughput collapsed while peer down: "
            f"{down:.0f}/s vs baseline {base:.0f}/s"
        )
        assert catchup >= 0.8 * base * 0.5 or catchup >= 0.8 * base, (
            f"throughput collapsed during catch-up: {catchup:.0f}/s "
            f"vs baseline {base:.0f}/s"
        )
        # watchdog-asserted: no surviving engine loop stalled while the
        # peer was down or catching up
        for nid in HOSTS:
            if nid == victim:
                continue
            stats = hosts[nid].engine.fairness_stats()
            assert stats["recent_max_gap_s"] < 2.0, (
                f"host {nid} engine loop stalled: {stats}"
            )
        _wait_converged(hosts, deadline_s=60.0)
    finally:
        for nh in hosts.values():
            nh.stop()
