"""EntryLog matrix tests in the etcd table style
(cf. internal/raft/logentry_etcd_test.go:43-300 FindConflict /
TestLogMaybeAppend / TestIsUpToDate, :483-711 bounds/term/slice tables):
each case drives the two-tier log view (stable ILogDB + in-memory) through
one row of inputs and checks the full outcome."""
import pytest

from dragonboat_tpu.core.logentry import (
    EntryLog,
    ErrCompacted,
    ErrUnavailable,
    InMemLogDB,
)
from dragonboat_tpu.types import Entry, Snapshot


def ent(index, term, cmd=b""):
    return Entry(index=index, term=term, cmd=cmd)


def mk_log(terms=(1, 2, 3)):
    """EntryLog whose inmem holds entries 1..n with the given terms."""
    log = EntryLog(InMemLogDB())
    log.append([ent(i + 1, t) for i, t in enumerate(terms)])
    return log


# ---------------------------------------------------------- find conflict
@pytest.mark.parametrize(
    "incoming,expected",
    [
        # no conflict, all match -> 0
        ([(1, 1), (2, 2), (3, 3)], 0),
        # no conflict, proper subset -> 0
        ([(2, 2), (3, 3)], 0),
        # new entries past the end conflict at the first new index
        ([(1, 1), (2, 2), (3, 3), (4, 4)], 4),
        ([(4, 4), (5, 5)], 4),
        # diverging term conflicts at the first mismatch
        ([(1, 1), (2, 4)], 2),
        ([(2, 1), (3, 4)], 2),
        ([(3, 1)], 3),
    ],
)
def test_find_conflict_matrix(incoming, expected):
    log = mk_log((1, 2, 3))
    ents = [ent(i, t) for i, t in incoming]
    assert log.get_conflict_index(ents) == expected


# ------------------------------------------------------------- up-to-date
@pytest.mark.parametrize(
    "index,term,expected",
    [
        # higher term wins regardless of index
        (1, 4, True),
        (99, 4, True),
        # lower term loses regardless of index
        (99, 2, False),
        # equal term: index decides (>= last index)
        (3, 3, True),
        (4, 3, True),
        (2, 3, False),
    ],
)
def test_up_to_date_matrix(index, term, expected):
    log = mk_log((1, 2, 3))
    assert log.up_to_date(index, term) is expected


# ------------------------------------------------------------ try append
@pytest.mark.parametrize(
    "prev_index,ents,ok,last_after",
    [
        # append right at the tail
        (3, [(4, 3)], True, 4),
        # conflicting suffix truncates then appends
        (1, [(2, 3), (3, 3)], True, 3),
        # stale append below the tail with matching content: nothing to
        # do -> False (the replicate handler still acks via match_term;
        # holes never reach try_append — the message layer rejects a
        # prev_index beyond the local tail first)
        (0, [(1, 1)], False, 3),
    ],
)
def test_try_append_matrix(prev_index, ents, ok, last_after):
    log = mk_log((1, 2, 3))
    got = log.try_append(prev_index, [ent(i, t) for i, t in ents])
    assert got is ok
    assert log.last_index() == last_after


# ------------------------------------------------- bounds / slice limits
def test_get_entries_bounds():
    log = mk_log((1, 2, 3, 4, 5))
    with pytest.raises(ErrCompacted):
        log.get_entries(0, 3, 1 << 30)
    with pytest.raises((ErrUnavailable, RuntimeError)):
        log.get_entries(4, 99, 1 << 30)
    got = log.get_entries(2, 5, 1 << 30)
    assert [e.index for e in got] == [2, 3, 4]


def test_get_entries_max_size_truncates_but_returns_first():
    log = EntryLog(InMemLogDB())
    log.append([ent(i, 1, b"x" * 100) for i in range(1, 6)])
    got = log.get_entries(1, 6, 1)  # budget below even one entry
    assert [e.index for e in got] == [1]  # always at least one
    got = log.get_entries(1, 6, 250)
    assert 1 <= len(got) < 5


# ------------------------------------------------------- term edge cases
def test_term_at_snapshot_boundary():
    log = EntryLog(InMemLogDB())
    log.inmem.restore(Snapshot(index=10, term=7))
    assert log.term(10) == 7  # the snapshot's own position
    # below the window: 0, matching the reference's (0, nil) return
    assert log.term(9) == 0
    log.append([ent(11, 8)])
    assert log.term(11) == 8
    assert log.last_term() == 8
    assert log.first_index() == 11


def test_restore_resets_cursors():
    log = mk_log((1, 2, 3))
    log.commit_to(2)
    log.inmem.restore(Snapshot(index=50, term=9))
    log.committed = 50
    log.processed = 50
    assert log.last_index() == 50
    assert not log.has_entries_to_apply()


# ----------------------------------------------------------- commit rules
@pytest.mark.parametrize(
    "commit_to,ok",
    [(2, True), (3, True)],
)
def test_commit_to_within_log(commit_to, ok):
    log = mk_log((1, 2, 3))
    log.commit_to(commit_to)
    assert log.committed == commit_to


def test_commit_to_never_regresses():
    log = mk_log((1, 2, 3))
    log.commit_to(3)
    log.commit_to(1)  # stale smaller commit: ignored
    assert log.committed == 3
