"""tools.timeline tests: multi-dump merge by mono-offset negotiation,
filtering, causal-chain rendering, and the CLI smoke test over the
checked-in two-node fixture dump (tests/data/timeline_node*.jsonl — a
leader-side dump and a follower-side dump whose raw monotonic clocks are
4.5s apart; only the negotiated offsets interleave them correctly)."""
import io
import json
import os
from contextlib import redirect_stdout

from dragonboat_tpu.tools import timeline

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
N1 = os.path.join(DATA, "timeline_node1.jsonl")
N2 = os.path.join(DATA, "timeline_node2.jsonl")
TRACE = 0x0123456789ABCDEF


def test_merge_negotiates_clock_offsets():
    merged = timeline.merge_dumps([N1, N2])
    assert [e["event"] for e in merged] == [
        "leader_changed",       # n1 wall 1000.9
        "partition_window",     # n2 wall 1000.9 (raw t=5.4!)
        "propose_enqueue",      # n1 wall 1001.000001
        "replicate_send",       # n1 wall 1001.0004
        "replicate_recv",       # n2 wall 1001.001 — between send and commit
        "replicate_ack",        # n2 wall 1001.0015
        "quorum_commit",        # n1 wall 1001.0021
        "proposal_applied",     # n1 wall 1001.0026
    ]
    # raw t ordering would have been wrong (n2's monotonic base differs)
    raw = sorted(merged, key=lambda e: e["t"])
    assert [e["event"] for e in raw] != [e["event"] for e in merged]
    assert {e["_src"] for e in merged} == {"n1", "n2"}


def test_filters_and_chains():
    merged = timeline.merge_dumps([N1, N2])
    only_group = timeline.filter_events(merged, cluster=2)
    assert all(e["cluster"] == 2 for e in only_group)
    assert len(only_group) == len(merged) - 1  # partition_window is host-level
    by_kind = timeline.filter_events(merged, kinds={"replicate_recv"})
    assert len(by_kind) == 1 and by_kind[0]["node"] == 2
    chains = timeline.causal_chains(merged)
    assert set(chains) == {TRACE}
    chain = chains[TRACE]
    assert [e["event"] for e in chain] == [
        "propose_enqueue", "replicate_send", "replicate_recv",
        "replicate_ack", "quorum_commit", "proposal_applied",
    ]
    assert {e["node"] for e in chain} == {1, 2}


def _run_cli(args):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = timeline.main(args)
    return rc, buf.getvalue()


def test_cli_smoke_over_fixture_dump():
    rc, out = _run_cli([N1, N2])
    assert rc == 0
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 8
    assert lines[0].startswith("+") and "[n1] leader_changed" in lines[0]
    assert "replicate_recv" in out and "[n2]" in out

    rc, out = _run_cli([N1, N2, "--chains"])
    assert rc == 0
    assert f"trace {TRACE:#x}" in out
    assert "nodes [1, 2]" in out
    assert out.index("propose_enqueue") < out.index("quorum_commit")

    rc, out = _run_cli(
        [N1, N2, "--trace", hex(TRACE), "--event", "quorum_commit", "--json"]
    )
    assert rc == 0
    rows = [json.loads(ln) for ln in out.splitlines() if ln.strip()]
    assert len(rows) == 1
    assert rows[0]["event"] == "quorum_commit"
    assert rows[0]["trace"] == TRACE

    rc, out = _run_cli([N1, "--cluster", "2", "--event", "nonexistent"])
    assert rc == 0
    assert "(no events)" in out


def test_cli_handles_torn_tail_lines(tmp_path):
    p = tmp_path / "torn.jsonl"
    with open(N1) as f:
        content = f.read()
    p.write_text(content + '{"t": 9.9, "event": "trunc')  # torn tail
    merged = timeline.merge_dumps([str(p)])
    assert len(merged) == 5  # meta consumed, torn line skipped
