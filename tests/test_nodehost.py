"""End-to-end NodeHost tests: the minimum slice from SURVEY.md §7 step 3 —
propose → step → commit → apply → notify on single- and multi-replica
deployments over the loopback transport (cf. nodehost_test.go patterns).

Every test runs twice: once with the scalar per-group engine and once with
the vector engine (the device kernel advancing all groups per step)."""
import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import ErrRejected, ErrTimeout
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory


@pytest.fixture(params=["scalar", "vector"])
def engine_kind(request):
    return request.param


class KVSM(IStateMachine):
    """In-memory KV test SM (cf. internal/tests/kvtest.go, sans chaos)."""

    instances = []

    def __init__(self, cluster_id, node_id):
        self.cluster_id = cluster_id
        self.node_id = node_id
        self.data = {}
        self.update_count = 0
        KVSM.instances.append(self)

    def update(self, cmd: bytes) -> Result:
        k, v = cmd.decode().split("=", 1)
        self.data[k] = v
        self.update_count += 1
        return Result(value=self.update_count)

    def lookup(self, q):
        return self.data.get(q)

    def save_snapshot(self, w, files, done):
        import json

        w.write(json.dumps([self.data, self.update_count]).encode())

    def recover_from_snapshot(self, r, files, done):
        import json

        self.data, self.update_count = json.loads(r.read().decode())


def mk_nodehost(addr, registry, rtt_ms=5, nodehost_dir="", engine_kind="scalar"):
    cfg = NodeHostConfig(
        deployment_id=1,
        rtt_millisecond=rtt_ms,
        raft_address=addr,
        nodehost_dir=nodehost_dir,
        raft_rpc_factory=lambda listen: loopback_factory(listen, registry),
        # one canonical shape for every vector-engine test so the whole
        # suite shares a single compiled kernel (make_step_fn lru cache)
        engine=EngineConfig(
            kind=engine_kind, max_groups=32, max_peers=4, log_window=64
        ),
    )
    return NodeHost(cfg)


def group_config(cluster_id, node_id, **kw):
    return Config(
        cluster_id=cluster_id,
        node_id=node_id,
        election_rtt=10,
        heartbeat_rtt=2,
        **kw,
    )


def wait_for(pred, timeout=30.0):
    # default must comfortably cover the vector engine's cold kernel
    # compile (~10s on a busy 1-cpu box): elections cannot complete until
    # the first step_fn compilation returns
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture(autouse=True)
def clear_instances():
    KVSM.instances = []
    yield
    KVSM.instances = []


def test_single_node_propose_and_read(engine_kind):
    reg = _Registry()
    nh = mk_nodehost("a:1", reg, engine_kind=engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, KVSM, group_config(100, 1))
        assert wait_for(lambda: nh.get_leader_id(100)[1])
        s = nh.get_noop_session(100)
        r = nh.sync_propose(s, b"k1=v1", timeout_s=20.0)
        assert r.value == 1
        assert nh.sync_read(100, "k1", timeout_s=20.0) == "v1"
        # a second propose
        r2 = nh.sync_propose(s, b"k2=v2")
        assert r2.value == 2
        assert nh.sync_read(100, "k2") == "v2"
    finally:
        nh.stop()


def test_three_replicas_replicate(engine_kind):
    reg = _Registry()
    members = {1: "a:1", 2: "b:2", 3: "c:3"}
    nhs = [mk_nodehost(addr, reg, engine_kind=engine_kind) for addr in members.values()]
    try:
        for nid, nh in zip(members, nhs):
            nh.start_cluster(members, False, KVSM, group_config(5, nid))
        assert wait_for(
            lambda: any(nh.get_leader_id(5)[1] for nh in nhs), timeout=45
        )
        # find leader host
        def leader_nh():
            for nh in nhs:
                lid, ok = nh.get_leader_id(5)
                if ok:
                    nid = {v: k for k, v in members.items()}[nh.raft_address()]
                    if lid == nid:
                        return nh
            return None

        assert wait_for(lambda: leader_nh() is not None, timeout=45)
        lnh = leader_nh()
        s = lnh.get_noop_session(5)
        res = lnh.sync_propose(s, b"x=42", timeout_s=20.0)
        assert res.value == 1
        # all three replicas converge
        assert wait_for(
            lambda: sum(1 for sm in KVSM.instances if sm.data.get("x") == "42") == 3
        )
        # linearizable read from the leader host
        assert lnh.sync_read(5, "x") == "42"
    finally:
        for nh in nhs:
            nh.stop()


def test_many_groups_one_nodehost(engine_kind):
    reg = _Registry()
    nh = mk_nodehost("a:1", reg, engine_kind=engine_kind)
    n_groups = 16
    try:
        for g in range(1, n_groups + 1):
            nh.start_cluster({1: "a:1"}, False, KVSM, group_config(g, 1))
        assert wait_for(
            lambda: all(nh.get_leader_id(g)[1] for g in range(1, n_groups + 1)),
            timeout=20,
        )
        for g in range(1, n_groups + 1):
            s = nh.get_noop_session(g)
            nh.sync_propose(s, b"g=%d" % g)
        for g in range(1, n_groups + 1):
            assert nh.sync_read(g, "g") == str(g)
    finally:
        nh.stop()


def test_session_dedup_e2e(engine_kind):
    reg = _Registry()
    nh = mk_nodehost("a:1", reg, engine_kind=engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, KVSM, group_config(7, 1))
        assert wait_for(lambda: nh.get_leader_id(7)[1])
        s = nh.sync_get_session(7)
        r1 = nh.sync_propose(s, b"a=1")
        # NOT calling proposal_completed: retry of same series must dedup
        rs = nh.propose(s, b"a=SHOULD-NOT-APPLY", 4.0)
        r2 = rs.wait(5.0)
        assert r2.completed
        assert r2.result == r1
        sm = KVSM.instances[-1]  # instances[0] is the start-time type probe
        assert sm.data["a"] == "1"
        s.proposal_completed()
        r3 = nh.sync_propose(s, b"b=2")
        assert sm.data["b"] == "2"
        s.proposal_completed()
        nh.sync_close_session(s)
        # proposing on closed session rejected
        s.series_id = 99
        with pytest.raises(ErrRejected):
            nh.sync_propose(s, b"c=3")
    finally:
        nh.stop()


def test_membership_change_e2e(engine_kind):
    reg = _Registry()
    members = {1: "a:1", 2: "b:2", 3: "c:3"}
    nhs = {nid: mk_nodehost(addr, reg, engine_kind=engine_kind) for nid, addr in members.items()}
    try:
        for nid in (1, 2):
            nhs[nid].start_cluster(
                {1: "a:1", 2: "b:2"}, False, KVSM, group_config(9, nid)
            )
        assert wait_for(
            lambda: any(nhs[n].get_leader_id(9)[1] for n in (1, 2)), timeout=45
        )
        lid = next(
            nhs[n].get_leader_id(9)[0] for n in (1, 2) if nhs[n].get_leader_id(9)[1]
        )
        lnh = nhs[lid]
        lnh.sync_request_add_node(9, 3, "c:3", timeout_s=25.0)
        m = lnh.get_cluster_membership(9)
        assert m.addresses.get(3) == "c:3"
        # node 3 joins
        nhs[3].start_cluster({}, True, KVSM, group_config(9, 3))
        s = lnh.get_noop_session(9)
        lnh.sync_propose(s, b"after=join")
        assert wait_for(
            lambda: sum(
                1 for sm in KVSM.instances if sm.data.get("after") == "join"
            )
            == 3,
            timeout=45,
        )
        # remove node 3 again
        lnh.sync_request_delete_node(9, 3, timeout_s=25.0)
        m2 = lnh.get_cluster_membership(9)
        assert 3 not in m2.addresses
    finally:
        for nh in nhs.values():
            nh.stop()


def test_restart_replay(tmp_path, engine_kind):
    reg = _Registry()
    d = str(tmp_path)
    nh = mk_nodehost("a:1", reg, nodehost_dir=d, engine_kind=engine_kind)
    try:
        nh.start_cluster({1: "a:1"}, False, KVSM, group_config(3, 1))
        assert wait_for(lambda: nh.get_leader_id(3)[1])
        s = nh.get_noop_session(3)
        for i in range(5):
            nh.sync_propose(s, b"k%d=%d" % (i, i))
    finally:
        nh.stop()
    # restart: log replay restores the SM
    reg2 = _Registry()
    nh2 = mk_nodehost("a:1", reg2, nodehost_dir=d, engine_kind=engine_kind)
    try:
        nh2.start_cluster({1: "a:1"}, False, KVSM, group_config(3, 1))
        assert wait_for(lambda: nh2.get_leader_id(3)[1], timeout=45)
        assert wait_for(
            lambda: nh2.stale_read(3, "k4") == "4", timeout=30
        )
    finally:
        nh2.stop()


def test_leader_transfer(engine_kind):
    reg = _Registry()
    members = {1: "a:1", 2: "b:2", 3: "c:3"}
    nhs = {nid: mk_nodehost(addr, reg, engine_kind=engine_kind) for nid, addr in members.items()}
    try:
        for nid, nh in nhs.items():
            nh.start_cluster(members, False, KVSM, group_config(11, nid))
        def current_leader():
            for nid, nh in nhs.items():
                lid, ok = nh.get_leader_id(11)
                if ok and lid == nid:
                    return nid
            return None

        assert wait_for(lambda: current_leader() is not None, timeout=45)
        old = current_leader()
        target = next(n for n in (1, 2, 3) if n != old)
        nhs[old].request_leader_transfer(11, target)
        assert wait_for(lambda: current_leader() == target, timeout=45)
    finally:
        for nh in nhs.values():
            nh.stop()


def test_ping_pong_rtt_and_nodehost_info(tmp_path):
    """RTT probing (cf. nodehost.go:2069-2088) + aggregate introspection
    (cf. nodehost.go:1289-1302 GetNodeHostInfo with log info)."""
    import time as _t
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    reg = _Registry()
    members = {1: "rtt:1", 2: "rtt:2", 3: "rtt:3"}
    hosts = {}
    for nid, addr in members.items():
        hosts[nid] = NodeHost(NodeHostConfig(
            deployment_id=77, rtt_millisecond=5, raft_address=addr,
            nodehost_dir=str(tmp_path / f"nh{nid}"),
            raft_rpc_factory=lambda l, r=reg: loopback_factory(l, r),
            engine=EngineConfig(kind="vector", max_groups=4, max_peers=4,
                                log_window=64),
        ))
    try:
        for nid in members:
            hosts[nid].start_cluster(
                dict(members), False, lambda c, n: KVSM(c, n),
                Config(cluster_id=1, node_id=nid, election_rtt=20,
                       heartbeat_rtt=2))
        deadline = _t.time() + 60
        while _t.time() < deadline:
            if any(hosts[n].get_leader_id(1)[1] for n in members):
                break
            _t.sleep(0.02)
        sent = hosts[1].ping_peers()
        assert sent == 2
        deadline = _t.time() + 10
        while _t.time() < deadline and len(hosts[1].get_rtt_samples()) < 2:
            _t.sleep(0.05)
        samples = hosts[1].get_rtt_samples()
        assert set(samples) == {(1, 2), (1, 3)}, samples
        for vals in samples.values():
            assert len(vals) >= 1
            assert 0 <= vals[0] < 10_000_000  # microseconds, sane bound
        # aggregate info: cluster list + logdb inventory, iterable for
        # backwards compatibility
        info = hosts[1].get_nodehost_info()
        assert info.raft_address == "rtt:1"
        cis = list(info)
        assert len(cis) == 1 and cis[0].cluster_id == 1
        assert any(
            ni.cluster_id == 1 and ni.node_id == 1 for ni in info.log_info
        )
        lean = hosts[1].get_nodehost_info(skip_log_info=True)
        assert lean.log_info == []
    finally:
        for nh in hosts.values():
            nh.stop()
