"""Sharded lane-mesh differential tests: the K-step kernel with its
group axis spread over the device mesh must be BYTE-IDENTICAL to the
unsharded K=1 reference — same protocol state, same per-step output
planes, same route plans, same carried residual — across seeded traffic
that covers elections, a config-change commit mid-window, and a leader
change mid-window. All protocol state is int32/bool, so bit equality is
the contract, not a tolerance.

Layered like test_multistep:
  1. property test: the cross-shard router (_shard_route under
     shard_map) vs the per-element host-dispatch reference router, on
     randomized states/outputs whose destinations span shards;
  2. scenario differential: sharded K-step super-steps vs K sequential
     unsharded steps glued by the reference router.

conftest pins an 8-device CPU platform; with 8 lanes each lane lives on
its own device, so every routed co-hosted message crosses a shard
boundary — the strongest setting for the exchange+replay path.
"""
from __future__ import annotations

import functools
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

import test_multistep as tm
from test_multistep import (
    _empty_inbox_np,
    _jnp_inbox,
    _merge_inbox,
    _np_tree,
    _ref_route,
)

from dragonboat_tpu.ops.kernel import (
    _shard_route,
    make_sharded_multi_step_fn,
    make_step_fn,
)
from dragonboat_tpu.ops.state import (
    MSG,
    KernelConfig,
    configure_group,
    init_state,
    make_empty_inbox,
)

N_DEV = jax.device_count()

# the canonical test shape at the smallest lane count the mesh divides:
# one lane per device on the conftest's 8-device CPU platform
SKCFG = KernelConfig(
    groups=8, peers=4, log_window=32, inbox_depth=4,
    max_entries_per_msg=4, readindex_depth=4,
)

needs_mesh = pytest.mark.skipif(
    N_DEV < 2 or SKCFG.groups % N_DEV != 0,
    reason="needs a multi-device mesh that divides the lane count",
)


def _mesh():
    return Mesh(np.asarray(jax.devices()), ("groups",))


# ---------------------------------------------------------------------------
# 1. cross-shard router property test vs the host-dispatch reference
# ---------------------------------------------------------------------------


@needs_mesh
@pytest.mark.parametrize("seed", range(6))
def test_shard_route_matches_reference(seed, monkeypatch):
    """_shard_route under shard_map — exchange every shard's candidate
    columns, replay the global scatter, keep the local slice — must
    reproduce the reference router bit for bit, including candidates
    whose destination lane lives on another shard."""
    from jax.experimental.shard_map import shard_map

    # reuse test_multistep's randomized state/output generator at this
    # file's lane count (it reads the module-global KCFG)
    monkeypatch.setattr(tm, "KCFG", SKCFG)
    rng = random.Random(7000 + seed)
    G, P = SKCFG.groups, SKCFG.peers
    s, o_np, out = tm._random_state_and_output(rng)
    route = np.full((G, P), -1, np.int32)
    rdelta = np.zeros((G, P), np.int32)
    self_slot = np.asarray(s.self_slot)
    for g in range(G):
        for p in range(P):
            if p == self_slot[g]:
                continue
            if rng.random() < 0.6:
                route[g, p] = rng.randrange(G)  # GLOBAL lane index
                rdelta[g, p] = rng.choice([0, 0, 0, 2, -2, -40])

    lane = PartitionSpec("groups")
    fn = shard_map(
        functools.partial(
            _shard_route, cfg=SKCFG, axis_name="groups", n_shards=N_DEV
        ),
        mesh=_mesh(),
        in_specs=(lane,) * 4,
        out_specs=(lane, lane),
        check_rep=False,
    )
    nxt, plan = jax.jit(fn)(s, out, jnp.asarray(route), jnp.asarray(rdelta))
    nxt = _np_tree(nxt)._asdict()
    plan = _np_tree(plan)._asdict()
    ref_nxt, ref_masks = _ref_route(s, o_np, route, rdelta, SKCFG)
    for k in ref_masks:
        assert np.array_equal(plan[k], ref_masks[k]), (seed, k)
    for k in ref_nxt:
        assert np.array_equal(nxt[k], ref_nxt[k]), (seed, k)
    # the trial must actually cross shard boundaries: count accepted
    # peer-plane candidates whose destination lane lives on another shard
    Gl = G // N_DEV
    cross = sum(
        int(ref_masks[kind][g, p])
        for kind in ("rep", "vote", "hb", "tn")
        for g in range(G)
        for p in range(P)
        if route[g, p] >= 0 and route[g, p] // Gl != g // Gl
    )
    assert cross > 0, "seed routed nothing across shards"


# ---------------------------------------------------------------------------
# 2. sharded super-step differential vs unsharded K=1 + reference router
# ---------------------------------------------------------------------------


def _cluster_state8():
    """test_multistep's canonical cluster layout at this file's lane
    count: 3 co-hosted replicas of cluster A on lanes 0/1/2, a
    single-voter lane 3, a partial cluster on lanes 4/5 with a
    cross-host third slot, and two unconfigured lanes (6/7) that must
    stay inert — the padded-lane shape the sharded engine produces."""
    s = init_state(SKCFG)
    for g, slot in ((0, 0), (1, 1), (2, 2)):
        s = configure_group(
            s, g, slot, (0, 1, 2), election_timeout=10, heartbeat_timeout=2
        )
    s = configure_group(s, 3, 0, (0,), election_timeout=10)
    for g, slot in ((4, 0), (5, 1)):
        s = configure_group(
            s, g, slot, (0, 1, 2), election_timeout=10, heartbeat_timeout=2
        )
    G, P = SKCFG.groups, SKCFG.peers
    route = np.full((G, P), -1, np.int32)
    for g, slot in ((0, 0), (1, 1), (2, 2)):
        for p, pg in ((0, 0), (1, 1), (2, 2)):
            if pg != g:
                route[g, p] = pg
    route[4, 1] = 5
    route[5, 0] = 4  # slot 2 of lanes 4/5 is cross-host: stays -1
    rdelta = np.zeros((G, P), np.int32)
    return s, route, rdelta


def _host_events8(window, counts):
    """test_multistep's 4-window scenario (election; proposals + a
    config change that commits mid-window; leader change; post-change
    proposal) padded out to this file's lane count."""
    h6 = tm._host_events(window, counts)
    out = _empty_inbox_np(SKCFG)
    for k in out:
        out[k][: tm.KCFG.groups] = h6[k]
    return out


@needs_mesh
def test_sharded_superstep_matches_k1_reference():
    """The sharded K-step super-step must be byte-identical to K
    sequential UNSHARDED one-step kernel calls glued by the reference
    router: final protocol state, every per-step output plane, the
    route plans, and the carried residual inbox — across a scenario
    with an election, a config-change commit mid-window, and a leader
    change mid-window (the traffic shapes the on-device cross-shard
    exchange must not perturb)."""
    steps = 4
    windows = 4
    G = SKCFG.groups
    s_sh, route, rdelta = _cluster_state8()
    s_seq = jax.tree.map(lambda x: x, s_sh)  # same initial values
    smulti = make_sharded_multi_step_fn(SKCFG, steps, _mesh(), donate=False)
    step = make_step_fn(SKCFG, donate=False)
    route_j, rdelta_j = jnp.asarray(route), jnp.asarray(rdelta)
    ticks = jnp.zeros((G,), jnp.int32)

    resid_np = _empty_inbox_np(SKCFG)  # seq side's carried residual
    resid_sh = make_empty_inbox(SKCFG)
    for window in range(windows):
        counts = [
            int((resid_np["mtype"][g] != MSG.NONE).sum()) for g in range(G)
        ]
        host = _host_events8(window, counts)
        # ---- sharded path: one kernel launch over the mesh ---------------
        s_sh, outs, plans, resid_sh, rc = smulti(
            s_sh, _jnp_inbox(host), ticks, resid_sh, route_j, rdelta_j
        )
        # the state really lives spread over the mesh between windows
        assert len(s_sh.term.sharding.device_set) == N_DEV
        outs = _np_tree(outs)._asdict()
        plans = _np_tree(plans)._asdict()
        rc = np.asarray(jax.device_get(rc))
        # ---- reference path: K unsharded steps + reference routing -------
        inbox = _merge_inbox(resid_np, host)
        for t in range(steps):
            s_seq, out = step(s_seq, _jnp_inbox(inbox), ticks)
            o = _np_tree(out)._asdict()
            nxt, masks = _ref_route(s_seq, o, route, rdelta, SKCFG)
            for k in o:
                assert np.array_equal(outs[k][t], o[k]), (window, t, k)
            for k in masks:
                assert np.array_equal(plans[k][t], masks[k]), (window, t, k)
            inbox = nxt
        resid_np = inbox
        rm = _np_tree(resid_sh)._asdict()
        for k in resid_np:
            assert np.array_equal(rm[k], resid_np[k]), (window, k)
        exp_rc = (resid_np["mtype"] != MSG.NONE).sum(axis=1)
        assert np.array_equal(rc, exp_rc), window
        sm = _np_tree(s_sh)._asdict()
        sq = _np_tree(s_seq)._asdict()
        for k in sm:
            assert np.array_equal(sm[k], sq[k]), (window, k)

    # the scenario really exercised what it claims (same verdicts as
    # test_multistep's unsharded differential): cluster A elected in
    # window 0, committed entries (incl. the cc) mid-window in window 1,
    # changed leader in window 2 — and the unconfigured tail lanes that
    # model engine padding stayed inert
    final = _np_tree(s_sh)._asdict()
    assert final["leader"][0] == 2  # lane 1 (slot 1) led after window 2
    assert final["term"][0] == 2
    assert final["committed"][1] >= 6
    assert final["committed"][3] >= 4
    assert final["term"][6] == 0 and final["term"][7] == 0
    assert final["committed"][6] == 0 and final["committed"][7] == 0
