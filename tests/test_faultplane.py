"""FaultPlane tests: deterministic replay (the ISSUE 2 acceptance bar —
two same-seeded runs produce identical fault schedules), wire-fault
semantics at the batch hook, storage fault injection, and the acceptance
chaos run: a 3-host cluster under a 30% drop + partition schedule must
converge with zero linearizability violations while transport metrics
show no heartbeat-class message was dropped from a full send queue."""
import json
import threading
import time
import zlib

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.faults import REPLICATION_TYPES, FaultPlane, FaultSpec
from dragonboat_tpu.lincheck import HistoryRecorder, check_kv_history
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import RequestError
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.storage.kv import WalKV, WriteBatch
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
from dragonboat_tpu.types import Entry, Message, MessageBatch, MessageType


# ------------------------------------------------------ deterministic replay
def _drive(fp: FaultPlane) -> list:
    """A fixed multi-site query sequence, partly from worker threads (each
    site is only ever touched by one thread, like the real seams)."""
    out = []

    def worker(site):
        for i in range(200):
            fp.decide(site, "drop", 0.3)
            if i % 7 == 0:
                fp.uniform(site, "delay_s", 0.001, 0.02)

    threads = [
        threading.Thread(target=worker, args=(f"wire:h{i}",)) for i in (1, 2, 3)
    ]
    for t in threads:
        t.start()
    for _ in range(50):
        out.append(fp.choice("faultloop", "fault", ["a", "b", "c", "none"]))
        fp.uniform("faultloop", "window", 0.3, 0.8)
    for t in threads:
        t.join()
    return out


def test_same_seed_identical_schedule():
    fp1, fp2 = FaultPlane(1234), FaultPlane(1234)
    seq1, seq2 = _drive(fp1), _drive(fp2)
    assert seq1 == seq2
    assert fp1.schedule_signature() == fp2.schedule_signature()
    # per-site logs are identical element-for-element, not just as a set
    def by_site(fp):
        d = {}
        for site, kind, n, v in fp.schedule_log():
            d.setdefault(site, []).append((kind, n, v))
        return d

    assert by_site(fp1) == by_site(fp2)


def test_different_seed_different_schedule():
    fp1, fp2 = FaultPlane(1234), FaultPlane(4321)
    _drive(fp1), _drive(fp2)
    assert fp1.schedule_signature() != fp2.schedule_signature()


# ----------------------------------------------------------- wire semantics
def mk_batch(n=6, mtype=MessageType.REPLICATE):
    return MessageBatch(
        requests=[
            Message(
                type=mtype,
                cluster_id=1,
                to=2,
                from_=1,
                entries=[Entry(index=i + 1, term=1, cmd=b"p%d" % i)],
            )
            for i in range(n)
        ]
    )


def test_batch_hook_drop_duplicate_reorder_replay():
    spec = FaultSpec(drop=0.3, duplicate=0.2, reorder=0.2, reorder_hold=1)

    def run(seed):
        fp = FaultPlane(seed, spec)
        hook = fp.batch_hook("wire:h1")
        shipped = []
        for _ in range(40):
            b = mk_batch()
            if hook(b):
                shipped.append([m.entries[0].index for m in b.requests])
            else:
                shipped.append([])
        return shipped

    a, b = run(99), run(99)
    assert a == b  # bit-identical replay of the shipped sequence
    c = run(100)
    assert c != a
    flat = [i for batch in a for i in batch]
    assert flat, "everything was dropped"
    # duplicates happened and total drop rate is in a plausible band
    total_in = 40 * 6
    assert len(flat) < total_in  # some drops
    assert any(flat[i] == flat[i + 1] for i in range(len(flat) - 1)) or (
        len(set(flat)) < len(flat)
    )


def test_batch_hook_only_types_shields_control_plane():
    fp = FaultPlane(7, FaultSpec(drop=1.0, only_types=REPLICATION_TYPES))
    hook = fp.batch_hook("wire:h1")
    b = mk_batch(3, MessageType.HEARTBEAT)
    assert hook(b) and len(b.requests) == 3  # heartbeats untouched
    b2 = mk_batch(3, MessageType.REPLICATE)
    assert not hook(b2)  # replication all dropped


def test_reordered_messages_resurface():
    fp = FaultPlane(5, FaultSpec(reorder=1.0, reorder_hold=1))
    hook = fp.batch_hook("wire:h1")
    b1 = mk_batch(2)
    assert not hook(b1)  # both held back
    fp.set_spec(FaultSpec())  # close the fault window
    # the pen drains on the next batch: a held message is never leaked
    b2 = mk_batch(1)
    assert hook(b2)
    got = [m.entries[0].index for m in b2.requests]
    assert got == [1, 2, 1]  # held messages jump the queue, then the new one


# ---------------------------------------------------------- storage faults
def test_faulty_kv_fsync_error_and_stall(tmp_path):
    fp = FaultPlane(3, FaultSpec(fsync_error=1.0))
    kv = fp.wrap_kv(WalKV(str(tmp_path / "w"), fsync=False), "fsync:h1")
    wb = WriteBatch()
    wb.put(b"a", b"1")
    with pytest.raises(IOError):
        kv.commit_write_batch(wb)
    fp.set_spec(FaultSpec())  # heal
    kv.commit_write_batch(wb)
    assert kv.get_value(b"a") == b"1"
    fp.set_spec(FaultSpec(fsync_stall=1.0, fsync_stall_s=(0.01, 0.011)))
    t0 = time.monotonic()
    kv.sync()
    assert time.monotonic() - t0 >= 0.009
    kv.close()


def test_tear_wal_tail_rolls_back_to_sealed_group(tmp_path):
    d = str(tmp_path / "w")
    for seed in (1, 2, 3, 4):
        kv = WalKV(d, fsync=False)
        wb = WriteBatch()
        wb.put(b"stable", b"yes")
        kv.commit_write_batch(wb)
        wb2 = WriteBatch()
        wb2.put(b"tail", b"maybe")
        wb2.put(b"tail2", b"maybe")
        kv.commit_write_batch(wb2)
        kv.close()
        fp = FaultPlane(seed)
        assert fp.tear_wal_tail(d, "tear") > 0
        kv2 = WalKV(d)
        assert kv2.get_value(b"stable") == b"yes"
        # group atomicity: the second batch is either fully there or gone
        assert (kv2.get_value(b"tail") is None) == (
            kv2.get_value(b"tail2") is None
        )
        kv2.close()
        import shutil

        shutil.rmtree(d)


# ------------------------------------------------- acceptance: chaos run
class HashKV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


CLUSTER = 1
HOSTS = (1, 2, 3)


def _mk_host(nid, reg, tmp):
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=9,
            rtt_millisecond=5,
            nodehost_dir=f"{tmp}/h{nid}",
            raft_address=f"fp{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=32, max_peers=4, log_window=64
            ),
        )
    )
    nh.start_cluster(
        {h: f"fp{h}:1" for h in HOSTS},
        False,
        lambda c, n: HashKV(),
        Config(
            cluster_id=CLUSTER,
            node_id=nid,
            election_rtt=20,
            heartbeat_rtt=4,
            snapshot_entries=50,
            compaction_overhead=10,
        ),
    )
    return nh


def _find_leader(hosts, deadline_s=20):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for nid, nh in list(hosts.items()):
            if nh is None:
                continue
            try:
                lid, ok = nh.get_leader_id(CLUSTER)
            except Exception:
                continue
            if ok and lid == nid and not nh.is_partitioned():
                return nid
        time.sleep(0.02)
    return None


@pytest.mark.chaos
def test_acceptance_drop_and_partition_schedule(tmp_path):
    """ISSUE 2 acceptance: 30% drop + partitions from one seed; converge,
    linearizable, and no heartbeat-class message dropped from a full
    queue."""
    seed = 0xACCE97
    print(f"CHAOS SEED=0x{seed:X} (rerun: FaultPlane({seed}))")
    fp = FaultPlane(seed, FaultSpec(drop=0.30))
    reg = _Registry()
    hosts = {nid: _mk_host(nid, reg, str(tmp_path)) for nid in HOSTS}
    rec = HistoryRecorder()
    stop = threading.Event()
    seq = [0]
    seq_mu = threading.Lock()

    def client_main(client_id):
        import random as _r

        crng = _r.Random(seed + client_id)
        while not stop.is_set():
            leader = _find_leader(hosts, deadline_s=1)
            nh = hosts.get(leader)
            if nh is None:
                continue
            key = crng.choice(["a", "b", "c"])
            if crng.random() < 0.6:
                with seq_mu:
                    seq[0] += 1
                    val = f"v{seq[0]}"
                op = rec.invoke(client_id, ("put", key, val))
                try:
                    nh.sync_propose(
                        nh.get_noop_session(CLUSTER),
                        f"{key}={val}".encode(),
                        timeout_s=2.0,
                    )
                    rec.complete(op, None)
                except Exception:
                    rec.unknown(op)
            else:
                op = rec.invoke(client_id, ("get", key))
                try:
                    rec.complete(op, nh.sync_read(CLUSTER, key, timeout_s=2.0))
                except Exception:
                    rec.fail(op)
            time.sleep(crng.random() * 0.01)

    clients = [
        threading.Thread(target=client_main, args=(i,), daemon=True)
        for i in range(3)
    ]
    for t in clients:
        t.start()

    # 30% drop on every host's wire for the whole schedule
    for nid, nh in hosts.items():
        fp.install(nh, f"h{nid}")
    # plus partitions from the seeded schedule
    for victim, window, idle in fp.partition_schedule(
        "faultloop", HOSTS, total_s=8.0
    ):
        nh = hosts[victim]
        nh.set_partitioned(True)
        time.sleep(window)
        nh.set_partitioned(False)
        time.sleep(idle)

    fp.uninstall_all()
    for nh in hosts.values():
        nh.set_partitioned(False)
    # a healed tail window so the recorded history also carries clean ops;
    # adaptive: a loaded CI box needs longer for the ops to land
    deadline = time.time() + 30
    while len(rec.history()) < 30 and time.time() < deadline:
        time.sleep(0.5)
    stop.set()
    for t in clients:
        t.join(timeout=5)

    # settle: one final write must commit
    deadline = time.time() + 60
    while True:
        leader = _find_leader(hosts, deadline_s=30)
        assert leader is not None, "cluster did not recover a leader"
        try:
            hosts[leader].sync_propose(
                hosts[leader].get_noop_session(CLUSTER), b"final=done", 5.0
            )
            break
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(0.2)

    deadline = time.time() + 30
    while time.time() < deadline:
        idx = {n: hosts[n].get_applied_index(CLUSTER) for n in HOSTS}
        if len(set(idx.values())) == 1:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"applied indexes never converged: {idx}")
    hashes = {n: hosts[n].get_sm_hash(CLUSTER) for n in HOSTS}
    assert len(set(hashes.values())) == 1, f"replica SMs diverged: {hashes}"

    history = rec.history()
    assert len(history) > 20, f"too few ops ({len(history)})"
    assert check_kv_history(history, max_states=5_000_000), (
        f"linearizability violation (CHAOS SEED=0x{seed:X})"
    )

    # the hardened send queue never sacrificed control-plane traffic
    for nid, nh in hosts.items():
        m = nh.transport.metrics()
        assert m["queue_dropped_urgent"] == 0, (nid, m)
    for nh in hosts.values():
        nh.stop()


def test_faulty_kv_append_error_never_half_seals_group(tmp_path):
    """ISSUE 17 satellite: a write-path EIO mid-batch (append_error, not
    fsync_error) must never leave a half-sealed record group — after the
    failure AND after reopen the failed batch is invisible as a unit,
    earlier data is intact, and the store keeps working."""
    from dragonboat_tpu.faults import FaultPlane as FP

    d = str(tmp_path / "w")
    kv = WalKV(d, fsync=False)
    wb0 = WriteBatch()
    wb0.put(b"stable", b"yes")
    kv.commit_write_batch(wb0)
    # a counting fault: fail on the SECOND record of the batch, so the
    # first record is already in the file when the group unwinds
    calls = {"n": 0}

    def fault():
        calls["n"] += 1
        if calls["n"] == 2:
            raise IOError("injected append error")

    kv.set_append_fault(fault)
    wb = WriteBatch()
    wb.put(b"half", b"a")
    wb.put(b"half2", b"b")
    with pytest.raises(IOError):
        kv.commit_write_batch(wb)
    kv.set_append_fault(None)
    # in the LIVE store: nothing of the failed group is visible and the
    # unwind did not eat the earlier sealed group
    assert kv.get_value(b"half") is None and kv.get_value(b"half2") is None
    assert kv.get_value(b"stable") == b"yes"
    # the truncated tail accepts new groups cleanly
    wb2 = WriteBatch()
    wb2.put(b"after", b"ok")
    kv.commit_write_batch(wb2)
    kv.close()
    # after REOPEN (the WAL replay): same story, no half-sealed group
    kv2 = WalKV(d)
    assert kv2.get_value(b"half") is None and kv2.get_value(b"half2") is None
    assert kv2.get_value(b"stable") == b"yes"
    assert kv2.get_value(b"after") == b"ok"
    kv2.close()
    # the seeded plane arms the same seam through wrap_kv
    fp = FP(9, FaultSpec(append_error=1.0))
    kv3 = fp.wrap_kv(WalKV(d, fsync=False), "crash:h1")
    wb3 = WriteBatch()
    wb3.put(b"nope", b"x")
    with pytest.raises(IOError):
        kv3.commit_write_batch(wb3)
    fp.set_spec(FaultSpec())  # heal
    kv3.commit_write_batch(wb3)
    assert kv3.get_value(b"nope") == b"x"
    kv3.close()


# ------------------------------------------------------------- clock plane
def test_clock_plane_skew_drift_jump_math():
    """ClockPlane.now continuity rules: mutations re-anchor first (no
    retroactive jumps), clear() heals the RATE but keeps the accrued
    offset (heal without a jump), reset() drops state (and IS a jump)."""
    from dragonboat_tpu.faults import ClockPlane, FaultPlane as FP

    cp = ClockPlane(FP(1))
    h = "h1"
    t0 = cp.now(h)
    assert abs(t0 - time.monotonic()) < 0.05  # default: real monotonic
    cp.step_jump(h, 2.0)
    assert cp.now(h) - time.monotonic() > 1.9
    cp.set_drift(h, 3.0)  # 3x fast from NOW (offset preserved)
    base = cp.now(h)
    time.sleep(0.05)
    faulted = cp.now(h) - base
    assert faulted > 0.12  # ~3x of >=0.05 real elapsed
    cp.clear(h)  # rate back to 1.0, offset KEPT
    still_ahead = cp.now(h) - time.monotonic()
    assert still_ahead > 1.9
    before = cp.now(h)
    time.sleep(0.02)
    assert 0.015 < cp.now(h) - before < 0.2  # 1x rate again
    cp.reset(h)  # drop state: back to real time = a backward jump
    assert abs(cp.now(h) - time.monotonic()) < 0.05


@pytest.mark.chaos
def test_clock_plane_chaos_schedule_replays_bit_identical():
    """The ClockPlane rides its owning FaultPlane's decision streams:
    two same-seeded planes draw the IDENTICAL chaos schedule (the
    crash_restart_schedule replay contract, extended to clocks)."""
    from dragonboat_tpu.faults import ClockPlane, FaultPlane as FP

    def draw(seed):
        fp = FP(seed)
        cp = ClockPlane(fp)
        gen = cp.chaos_schedule("longhaul", ["h1", "h2", "h3"], total_s=3.0)
        return [ev for ev in gen]

    a, b = draw(0x77), draw(0x77)
    assert a == b and len(a) > 0
    kinds = {ev[1] for ev in a}
    assert kinds <= {"skew", "drift", "jump"}
    c = draw(0x78)
    assert c != a  # a different seed draws a different schedule
