"""Differential testing: the vectorized kernel against the scalar oracle.

Both implementations run the same 3-replica scenario in lockstep rounds
(tick-all, then deliver to quiescence). The scalar side's randomized election
timeout is patched to the kernel's deterministic (seed, term, slot) hash, so
elections resolve identically; after every round the protocol observables —
role, term, leader, commit index, last log index, and per-entry log terms —
must agree replica-for-replica. This mirrors the reference's use of the etcd
test suites as a second implementation to diff against (docs/test.md:4), with
the scalar core as the oracle (SURVEY.md §4 implication note)."""
import numpy as np
import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft, RaftNodeState
from dragonboat_tpu.core.remote import Remote
from dragonboat_tpu.ops.loopback import LoopbackCluster
from dragonboat_tpu.ops.state import CTR, CTR_NAMES, _mix
from dragonboat_tpu.types import Entry, Message, MessageType, is_local_message

MT = MessageType
N = 3
ELECTION = 10
HEARTBEAT = 2


class ScalarCluster:
    """Scalar oracle wired to the kernel's timeout derivation and driven
    with the same round structure as LoopbackCluster. Supports the same
    link-level fault injection so randomized traces stay comparable."""

    def __init__(self, seed_of_group, g: int = 0):
        self.dropped_links: set = set()  # (from_slot, to_slot)
        self.isolated: set = set()  # slots
        self._init_rafts(seed_of_group, g)

    def _init_rafts(self, seed_of_group, g: int = 0):
        self.rafts = {}
        seed = seed_of_group
        for nid in range(1, N + 1):
            r = Raft(
                Config(
                    node_id=nid,
                    cluster_id=1,
                    election_rtt=ELECTION,
                    heartbeat_rtt=HEARTBEAT,
                ),
                InMemLogDB(),
            )
            for p in range(1, N + 1):
                r.remotes[p] = Remote(next=1)
            slot = nid - 1

            def patched(r=r, slot=slot):
                r.randomized_election_timeout = r.election_timeout + _mix(
                    seed, r.term, slot
                ) % r.election_timeout

            r.set_randomized_election_timeout = patched
            patched()
            self.rafts[nid] = r

    def tick_all(self):
        for r in self.rafts.values():
            r.tick()

    def _deliverable(self, m) -> bool:
        f, t = m.from_ - 1, m.to - 1  # slots
        if (f, t) in self.dropped_links:
            return False
        if f in self.isolated or t in self.isolated:
            return False
        return True

    def settle(self, rounds=20):
        for _ in range(rounds):
            msgs = []
            for r in self.rafts.values():
                msgs.extend(m for m in r.msgs if not is_local_message(m.type))
                r.msgs = []
            if not msgs:
                return
            for m in msgs:
                if m.to in self.rafts and self._deliverable(m):
                    self.rafts[m.to].handle(m)

    def propose(self, nid, n=1):
        self.rafts[nid].handle(
            Message(
                type=MT.PROPOSE,
                from_=nid,
                entries=[Entry(cmd=b"p%d" % i) for i in range(n)],
            )
        )

    def observables(self):
        res = []
        for nid in range(1, N + 1):
            r = self.rafts[nid]
            res.append(
                {
                    "role": int(r.state),
                    "term": r.term,
                    "leader": r.leader_id - 1 if r.leader_id else -1,
                    "committed": r.log.committed,
                    "last": r.log.last_index(),
                }
            )
        return res

    def log_terms(self, nid, lo, hi):
        ents = self.rafts[nid].log.get_entries(lo, hi + 1, 1 << 30)
        return [e.term for e in ents]


@pytest.fixture(scope="module")
def clusters():
    kc = LoopbackCluster(
        n_replicas=N, n_groups=1, election=ELECTION, heartbeat=HEARTBEAT
    )
    seed = int(np.asarray(kc.states[0].seed)[0])
    sc = ScalarCluster(seed_of_group=seed)
    return kc, sc


def kernel_observables(kc):
    res = []
    for h in range(N):
        st = kc.states[h]
        res.append(
            {
                "role": int(np.asarray(st.role)[0]),
                "term": int(np.asarray(st.term)[0]),
                "leader": int(np.asarray(st.leader)[0]) - 1,
                "committed": int(np.asarray(st.committed)[0]),
                "last": int(np.asarray(st.last_index)[0]),
            }
        )
    return res


def run_round(kc, sc, proposals=0):
    if proposals:
        klead = kc.leader_of(0)
        slead = [nid for nid, r in sc.rafts.items() if r.is_leader()]
        # both must agree on the leader before proposing
        assert klead is not None and slead and slead[0] - 1 == klead
        kc.propose(klead, 0, n=proposals)
        sc.propose(slead[0], n=proposals)
        kc.settle(10)
        sc.settle(10)
    kc.step(tick=True)
    kc.settle(10)
    sc.tick_all()
    sc.settle(10)


def test_differential_election_and_replication(clusters):
    kc, sc = clusters
    script = {12: 2, 15: 1, 20: 3, 26: 2, 33: 1}  # round -> proposals
    for rnd in range(40):
        run_round(kc, sc, proposals=script.get(rnd, 0))
        ko = kernel_observables(kc)
        so = sc.observables()
        assert ko == so, f"round {rnd}: kernel={ko} scalar={so}"
    # final log-term-by-index comparison over the full committed log
    hi = so[0]["committed"]
    assert hi >= 8
    for h in range(N):
        assert kc.ring_terms(h, 0, 1, hi) == sc.log_terms(h + 1, 1, hi)


def _compare_group(kc, scs, g, tag):
    ko = []
    for h in range(N):
        st = kc.states[h]
        ko.append(
            {
                "role": int(np.asarray(st.role)[g]),
                "term": int(np.asarray(st.term)[g]),
                "leader": int(np.asarray(st.leader)[g]) - 1,
                "committed": int(np.asarray(st.committed)[g]),
                "last": int(np.asarray(st.last_index)[g]),
            }
        )
    so = scs[g].observables()
    assert ko == so, f"{tag} g={g}: kernel={ko} scalar={so}"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [3, 17])
def test_differential_randomized_faults(seed):
    """Randomized trace differ (round-3): thousands of seeded group-rounds
    with link drops, replica isolation (partitions), proposals and leader
    transfers — the kernel must track the scalar oracle observable-for-
    observable through every fault schedule. 16 groups x 350 rounds x 2
    seeds = 11,200 randomized group-trajectory rounds."""
    import random

    G, ROUNDS = 16, 350
    rng = random.Random(seed)
    kc = LoopbackCluster(
        n_replicas=N, n_groups=G, election=ELECTION, heartbeat=HEARTBEAT,
        seed=seed,
    )
    seeds = np.asarray(kc.states[0].seed)
    scs = [ScalarCluster(seed_of_group=int(seeds[g])) for g in range(G)]
    prop_count = [0] * G
    fault_until = 0
    for rnd in range(ROUNDS):
        # ---- fault schedule (identical on both sides) --------------------
        if rnd >= fault_until:
            kc.dropped_links.clear()
            kc.isolated.clear()
            roll = rng.random()
            if roll < 0.12:
                kc.isolated.add(rng.randrange(N))
                fault_until = rnd + rng.randrange(2, 8)
            elif roll < 0.22:
                a, b = rng.sample(range(N), 2)
                kc.dropped_links.add((a, b))
                if rng.random() < 0.5:
                    kc.dropped_links.add((b, a))
                fault_until = rnd + rng.randrange(2, 8)
            for sc in scs:
                sc.dropped_links = set(kc.dropped_links)
                sc.isolated = set(kc.isolated)
        # ---- injections --------------------------------------------------
        if rng.random() < 0.5:
            g = rng.randrange(G)
            lead = kc.leader_of(g)
            slead = [h for h, r in scs[g].rafts.items() if r.is_leader()]
            if (
                lead is not None
                and slead
                and slead[0] - 1 == lead
                and lead not in kc.isolated
                and prop_count[g] < 300
            ):
                n = rng.randrange(1, 4)
                prop_count[g] += n
                kc.propose(lead, g, n=n)
                scs[g].propose(lead + 1, n=n)
        if rng.random() < 0.03:
            g = rng.randrange(G)
            lead = kc.leader_of(g)
            if lead is not None and lead not in kc.isolated:
                target = rng.randrange(N)
                if target != lead:
                    kc.transfer_leader(lead, g, target)
                    scs[g].rafts[lead + 1].handle(
                        Message(
                            type=MT.LEADER_TRANSFER, to=lead + 1,
                            from_=target + 1,
                            term=scs[g].rafts[lead + 1].term,
                            hint=target + 1,
                        )
                    )
        # ---- advance both sides identically ------------------------------
        kc.settle(20)
        for sc in scs:
            sc.settle(20)
        kc.step(tick=True)
        kc.settle(20)
        for sc in scs:
            sc.tick_all()
            sc.settle(20)
        for g in range(G):
            _compare_group(kc, scs, g, f"rnd={rnd}")
    # after the storm: heal, re-elect where needed, and verify full logs
    kc.dropped_links.clear()
    kc.isolated.clear()
    for sc in scs:
        sc.dropped_links = set()
        sc.isolated = set()
    for _ in range(4 * ELECTION):
        kc.step(tick=True)
        kc.settle(20)
        for sc in scs:
            sc.tick_all()
            sc.settle(20)
    for g in range(G):
        _compare_group(kc, scs, g, "final")
        hi = scs[g].observables()[0]["committed"]
        for h in range(N):
            if hi >= 1:
                assert kc.ring_terms(h, g, 1, hi) == scs[g].log_terms(
                    h + 1, 1, hi
                ), f"g={g} h={h} log terms diverged"


def test_differential_counters_match_scalar():
    """The on-device event-counter plane against the scalar twin: after a
    lockstep trace with elections, replication and rejects, every
    replica's cumulative kernel counters must equal the scalar core's
    event counts EXACTLY — same events, counted at the same protocol
    points (commit_advances compares in index units by design)."""
    kc = LoopbackCluster(
        n_replicas=N, n_groups=1, election=ELECTION, heartbeat=HEARTBEAT
    )
    seed = int(np.asarray(kc.states[0].seed)[0])
    sc = ScalarCluster(seed_of_group=seed)
    script = {12: 2, 15: 1, 20: 3, 26: 2}
    for rnd in range(32):
        run_round(kc, sc, proposals=script.get(rnd, 0))
        ko = kernel_observables(kc)
        so = sc.observables()
        assert ko == so, f"round {rnd}: kernel={ko} scalar={so}"
    for h in range(N):
        r = sc.rafts[h + 1]
        kernel = {
            name: int(kc.counters[h][0][i])
            for i, name in enumerate(CTR_NAMES)
        }
        scalar = {
            "elections_started": r.elections_started,
            "elections_won": r.elections_won,
            "heartbeats_sent": r.heartbeats_sent,
            "replicate_rejects": r.replicate_rejects,
            "commit_advances": r.commit_advances,
            "lease_served": r.lease_served,
            "lease_fallback": r.lease_fallback,
            "read_confirmations": r.read_confirmations,
        }
        assert kernel == scalar, f"replica {h}: {kernel} != {scalar}"
    # the trace actually exercised the plane: exactly the elections that
    # were won are counted, the leader heartbeated, commits advanced
    won = sum(int(kc.counters[h][0][CTR.ELECTIONS_WON]) for h in range(N))
    assert won >= 1
    assert any(
        int(kc.counters[h][0][CTR.HEARTBEATS_SENT]) > 0 for h in range(N)
    )
    assert all(
        int(kc.counters[h][0][CTR.COMMIT_ADVANCES]) >= 8 for h in range(N)
    )


def test_differential_leader_transfer(clusters):
    kc, sc = clusters
    lead = kc.leader_of(0)
    target = (lead + 1) % N
    kc.transfer_leader(lead, 0, target)
    sc.rafts[lead + 1].handle(
        Message(
            type=MT.LEADER_TRANSFER,
            to=lead + 1,
            from_=target + 1,
            term=sc.rafts[lead + 1].term,
            hint=target + 1,
        )
    )
    for rnd in range(8):
        run_round(kc, sc)
        ko = kernel_observables(kc)
        so = sc.observables()
        assert ko == so, f"transfer round {rnd}: kernel={ko} scalar={so}"
    assert kc.leader_of(0) == target
