"""Linearizability checker unit tests — known-good and known-bad histories
(the checker must catch violations, not just bless everything)."""
import pytest

from dragonboat_tpu.lincheck import (
    HistoryRecorder,
    LincheckBudgetExceeded,
    Model,
    Operation,
    check_kv_history,
    check_linearizable,
    kv_model,
    register_model,
)


def op(client, inp, out, inv, ret):
    o = Operation(client=client, input=inp, output=out, invoke=inv, ret=ret)
    o.op_id = id(o)
    return o


def test_sequential_register_ok():
    h = [
        op(0, ("w", 1), None, 0, 1),
        op(0, ("r",), 1, 2, 3),
        op(0, ("w", 2), None, 4, 5),
        op(0, ("r",), 2, 6, 7),
    ]
    assert check_linearizable(register_model(), h)


def test_stale_read_rejected():
    h = [
        op(0, ("w", 1), None, 0, 1),
        op(0, ("w", 2), None, 2, 3),
        op(1, ("r",), 1, 4, 5),  # reads overwritten value AFTER w2 returned
    ]
    assert not check_linearizable(register_model(), h)


def test_concurrent_read_may_see_either_value():
    # read overlaps the write: both old and new value are linearizable
    h_new = [
        op(0, ("w", 1), None, 0, 1),
        op(0, ("w", 2), None, 2, 6),
        op(1, ("r",), 2, 3, 4),
    ]
    h_old = [
        op(0, ("w", 1), None, 0, 1),
        op(0, ("w", 2), None, 2, 6),
        op(1, ("r",), 1, 3, 4),
    ]
    assert check_linearizable(register_model(), h_new)
    assert check_linearizable(register_model(), h_old)


def test_read_from_the_future_rejected():
    # read returns a value whose write is invoked strictly later
    h = [
        op(0, ("r",), 9, 0, 1),
        op(1, ("w", 9), None, 2, 3),
    ]
    assert not check_linearizable(register_model(), h)


def test_unknown_outcome_write_may_or_may_not_apply():
    # timed-out write; later read sees it => must linearize it
    h1 = [
        op(0, ("w", 1), None, 0, 1),
        op(1, ("w", 2), None, 2, float("inf")),  # unknown
        op(0, ("r",), 2, 5, 6),
    ]
    # ...or the read still sees the old value => write never happened (yet)
    h2 = [
        op(0, ("w", 1), None, 0, 1),
        op(1, ("w", 2), None, 2, float("inf")),
        op(0, ("r",), 1, 5, 6),
    ]
    assert check_linearizable(register_model(), h1)
    assert check_linearizable(register_model(), h2)


def test_nonoverlapping_reads_cannot_flipflop():
    # two sequential reads around nothing: second can't resurrect older value
    h = [
        op(0, ("w", 1), None, 0, 1),
        op(1, ("w", 2), None, 2, 3),
        op(2, ("r",), 2, 4, 5),
        op(2, ("r",), 1, 6, 7),  # older value after newer was read
    ]
    assert not check_linearizable(register_model(), h)


def test_kv_history_partitions_by_key():
    h = [
        op(0, ("put", "a", 1), None, 0, 1),
        op(0, ("put", "b", 9), None, 0.5, 1.5),
        op(1, ("get", "a"), 1, 2, 3),
        op(1, ("get", "b"), 9, 2, 3),
    ]
    assert check_kv_history(h)
    bad = h + [op(2, ("get", "a"), 777, 10, 11)]
    assert not check_kv_history(bad)


def test_recorder_roundtrip():
    rec = HistoryRecorder()
    a = rec.invoke(1, ("put", "x", 1))
    rec.complete(a, None)
    b = rec.invoke(1, ("get", "x"))
    rec.complete(b, 1)
    c = rec.invoke(2, ("put", "x", 2))
    rec.unknown(c)  # timeout: stays with ret=INF
    d = rec.invoke(2, ("put", "x", 3))
    rec.fail(d)  # definite rejection: dropped
    h = rec.history()
    assert len(h) == 3
    assert h[0].invoke <= h[1].invoke <= h[2].invoke
    assert not h[2].completed
    assert check_kv_history(h)


def test_budget_exceeded_raises():
    # big all-concurrent UNSATISFIABLE history (read of a never-written
    # value): the exhaustive refutation must abort on budget, not hang
    h = [op(i, ("w", i), None, 0, 100) for i in range(12)]
    h.append(op(99, ("r",), 999, 0, 100))
    with pytest.raises(LincheckBudgetExceeded):
        check_linearizable(register_model(), h, max_states=50)


def test_checker_respects_model_preconditions():
    # a model where "inc" only applies when state is even; odd-state inc is
    # rejected => history needs correct interleaving
    def init():
        return 0

    def step(state, inp, output):
        if inp == "inc":
            return state % 2 == 0, state + 1
        if inp == "odd-inc":
            return state % 2 == 1, state + 1
        return True, state

    m = Model(init=init, step=step)
    ok = [
        op(0, "inc", None, 0, 10),
        op(1, "odd-inc", None, 0, 10),
    ]
    assert check_linearizable(m, ok)
    bad = [
        op(0, "odd-inc", None, 0, 1),  # returns before inc is invoked
        op(1, "inc", None, 2, 3),
    ]
    assert not check_linearizable(m, bad)
