"""In-memory multi-peer harness for driving scalar Raft protocol scenarios,
modeled on the network-free approach of the reference's raft tests
(cf. internal/raft/raft_test.go: tests drive multiple raft instances purely
through the message interface with a stub ILogDB)."""
from __future__ import annotations

import random
from typing import Dict, List, Optional

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft, RaftNodeState
from dragonboat_tpu.core.remote import Remote
from dragonboat_tpu.types import (
    Entry,
    Message,
    MessageType,
    is_local_message,
)

MT = MessageType


def make_config(node_id: int, election: int = 10, heartbeat: int = 1, **kw) -> Config:
    return Config(
        node_id=node_id,
        cluster_id=1,
        election_rtt=election,
        heartbeat_rtt=heartbeat,
        **kw,
    )


def new_test_raft(
    node_id: int,
    peers: List[int],
    election: int = 10,
    heartbeat: int = 1,
    logdb: Optional[InMemLogDB] = None,
    seed: int = 0,
    **kw,
) -> Raft:
    logdb = logdb if logdb is not None else InMemLogDB()
    r = Raft(
        make_config(node_id, election, heartbeat, **kw),
        logdb,
        rng=random.Random(seed + node_id),
    )
    if not r.remotes:
        for p in peers:
            r.remotes[p] = Remote(next=1)
    return r


class Network:
    """Routes messages between raft instances; supports drops/isolation."""

    def __init__(self, rafts: Dict[int, Raft]):
        self.rafts = rafts
        self.dropped: set = set()  # (from, to) pairs
        self.isolated: set = set()
        self.drop_rate = 0.0
        self.rng = random.Random(42)

    def drop(self, frm: int, to: int) -> None:
        self.dropped.add((frm, to))

    def isolate(self, node_id: int) -> None:
        self.isolated.add(node_id)

    def heal(self) -> None:
        self.dropped.clear()
        self.isolated.clear()

    def _deliverable(self, m: Message) -> bool:
        if (m.from_, m.to) in self.dropped:
            return False
        if m.from_ in self.isolated or m.to in self.isolated:
            return False
        if self.drop_rate > 0 and self.rng.random() < self.drop_rate:
            return False
        return True

    def collect(self) -> List[Message]:
        msgs: List[Message] = []
        for r in self.rafts.values():
            msgs.extend(r.msgs)
            r.msgs = []
        return msgs

    def deliver_all(self, max_rounds: int = 100) -> None:
        """Deliver messages until quiescent."""
        for _ in range(max_rounds):
            msgs = self.collect()
            pending = [m for m in msgs if not is_local_message(m.type)]
            if not pending:
                return
            for m in pending:
                if m.to in self.rafts and self._deliverable(m):
                    self.rafts[m.to].handle(m)

    def send(self, m: Message) -> None:
        """Inject a message then run to quiescence (like etcd's nt.send)."""
        self.rafts[m.to].handle(m)
        self.deliver_all()

    def elect(self, node_id: int) -> None:
        self.send(Message(type=MT.ELECTION, to=node_id, from_=node_id))

    def propose(self, node_id: int, cmd: bytes = b"x") -> None:
        self.send(
            Message(
                type=MT.PROPOSE,
                to=node_id,
                from_=node_id,
                entries=[Entry(cmd=cmd)],
            )
        )


def make_cluster(n: int, election: int = 10, heartbeat: int = 1) -> Network:
    ids = list(range(1, n + 1))
    rafts = {}
    for nid in ids:
        r = new_test_raft(nid, ids, election, heartbeat)
        rafts[nid] = r
    return Network(rafts)


def state_of(r: Raft) -> RaftNodeState:
    return r.state
