"""Vector-scale chaos (VERDICT r3 item 4, drummer-lite): 256 Raft groups x 3
replicas advancing in ONE shared device state while faults land — host
partitions, randomized replication drops over the co-hosted path, and a
full NodeHost kill+restart from its durable dir.

The defining risk of a vectorized multi-group core is cross-lane bleed in
masked updates; the single-group chaos test (test_chaos.py) can never see
it. Invariants at the end, per the reference's monkey-test methodology
(docs/test.md:11-33):

  1. EVERY group's replicas converge: applied index + SM content hash
  2. linearizability holds on the sampled groups' recorded histories
  3. persisted logs obey Log Matching below the commit point (logdb
     cross-check over every sampled group)
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
import zlib

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.faults import REPLICATION_TYPES, FaultPlane, FaultSpec
from dragonboat_tpu.lincheck import HistoryRecorder, check_kv_history
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import RequestError
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

GROUPS = 256
HOSTS = (1, 2, 3)
SAMPLED = (3, 64, 129, 230)  # lincheck'd groups; the rest carry bulk load
KEYS = [f"k{i}" for i in range(3)]
SCOPE = "chaos-scale"
SEED = int(os.environ.get("CHAOS_SEED", str(0xC0FFEE)), 0)


class HashKV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, tmp):
    nh = NodeHost(NodeHostConfig(
        deployment_id=4, rtt_millisecond=10,
        nodehost_dir=f"{tmp}/h{nid}",
        raft_address=f"cs{nid}:1",
        raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        engine=EngineConfig(
            kind="vector", max_groups=3 * GROUPS, max_peers=4,
            log_window=128, inbox_depth=4, max_entries_per_msg=16,
            share_scope=SCOPE,
        ),
    ))
    members = {h: f"cs{h}:1" for h in HOSTS}
    nh.start_clusters([
        (
            dict(members), False, lambda c, n: HashKV(),
            Config(
                cluster_id=c, node_id=nid, election_rtt=60,
                heartbeat_rtt=10, snapshot_entries=200,
                compaction_overhead=20,
            ),
        )
        for c in range(1, GROUPS + 1)
    ])
    return nh


def _leaders(hosts):
    for nh in hosts.values():
        if nh is None:
            continue
        snap = getattr(nh.engine, "leader_snapshot", None)
        if snap is not None:
            return {c: l for c, (l, _t) in snap().items() if l}
    return {}


@pytest.mark.slow
def test_chaos_at_vector_scale(tmp_path):
    print(f"CHAOS SEED=0x{SEED:X} (replay: CHAOS_SEED=0x{SEED:X})")
    # co-hosted replication drops draw from the plane's "local:core"
    # stream; orchestration (fault kind, victim, windows) from "faultloop"
    fp = FaultPlane(
        SEED, FaultSpec(drop=0.25, only_types=REPLICATION_TYPES)
    )
    reg = _Registry()
    # instrument snapshot streaming for diagnosis
    from collections import Counter
    snap_stats = Counter()
    orig_send = NodeHost._async_send_snapshot
    orig_report = NodeHost._report_snapshot_status

    def counting_send(self, m):
        snap_stats[("attempt", m.cluster_id, m.to)] += 1
        return orig_send(self, m)

    def counting_report(self, cid, nid, failed):
        snap_stats[("fail" if failed else "ok", cid, nid)] += 1
        return orig_report(self, cid, nid, failed)

    NodeHost._async_send_snapshot = counting_send
    NodeHost._report_snapshot_status = counting_report
    request = None  # patched methods restored in the finally below
    try:
        hosts = {nid: _mk_host(nid, reg, str(tmp_path)) for nid in HOSTS}
        # bring-up: all groups elect
        t0 = time.monotonic()
        leaders = {}
        while len(leaders) < GROUPS and time.monotonic() - t0 < 180:
            leaders = _leaders(hosts)
            time.sleep(0.05)
        assert len(leaders) == GROUPS, f"{len(leaders)}/{GROUPS} elected"

        stop = threading.Event()
        recorders = {c: HistoryRecorder() for c in SAMPLED}
        seqs = {c: [0] for c in SAMPLED}
        bulk_done = [0]

        def sampled_client(client_id, c):
            rec = recorders[c]
            crng = random.Random(client_id * 7919 + c)
            while not stop.is_set():
                live = {n: h for n, h in hosts.items() if h is not None}
                lid = _leaders(live).get(c)
                nh = live.get(lid)
                if nh is None:
                    time.sleep(0.05)
                    continue
                key = crng.choice(KEYS)
                if crng.random() < 0.6:
                    seqs[c][0] += 1
                    val = f"v{client_id}.{seqs[c][0]}"
                    op = rec.invoke(client_id, ("put", key, val))
                    try:
                        nh.sync_propose(
                            nh.get_noop_session(c), f"{key}={val}".encode(), 2.0
                        )
                        rec.complete(op, None)
                    except RequestError:
                        rec.unknown(op)
                    except Exception:
                        rec.unknown(op)
                else:
                    op = rec.invoke(client_id, ("get", key))
                    try:
                        v = nh.sync_read(c, key, timeout_s=2.0)
                        rec.complete(op, v)
                    except Exception:
                        rec.fail(op)
                time.sleep(crng.random() * 0.02)

        def bulk_client():
            # pipelined load over the non-sampled groups: lane interference is
            # only real if OTHER lanes are busy while faults land
            crng = random.Random(4242)
            inflight = {}
            while not stop.is_set():
                live = {n: h for n, h in hosts.items() if h is not None}
                lmap = _leaders(live)
                progressed = False
                for c in range(1, GROUPS + 1):
                    if c in SAMPLED or stop.is_set():
                        continue
                    h = inflight.get(c)
                    if h is not None and not h.finished:
                        continue
                    if h is not None:
                        bulk_done[0] += h.completed
                    nh = live.get(lmap.get(c))
                    if nh is None:
                        continue
                    k = crng.choice(KEYS)
                    try:
                        inflight[c] = nh.propose_batch_async(
                            nh.get_noop_session(c),
                            [f"{k}=b{bulk_done[0]}".encode()] * 8, 10,
                        )
                        progressed = True
                    except Exception:
                        pass
                if not progressed:
                    time.sleep(0.02)

        clients = [
            threading.Thread(target=sampled_client, args=(i, c), daemon=True)
            for c in SAMPLED
            for i in (0, 1)
        ]
        clients.append(threading.Thread(target=bulk_client, daemon=True))
        for t in clients:
            t.start()

        # -------- fault injection over the busy fleet -------------------------
        core = hosts[1].engine.core
        t_end = time.monotonic() + 25
        while time.monotonic() - t_end < 0:
            fault = fp.choice(
                "faultloop", "fault", ["partition", "drop", "restart", "none"]
            )
            victim = fp.choice("faultloop", "victim", HOSTS)
            nh = hosts.get(victim)
            if nh is None:
                continue
            if fault == "partition":
                nh.set_partitioned(True)
                time.sleep(fp.uniform("faultloop", "window", 0.4, 1.0))
                nh2 = hosts.get(victim)
                if nh2 is not None:
                    nh2.set_partitioned(False)
            elif fault == "drop":
                # 25% of co-hosted REPLICATE/REPLICATE_RESP traffic drops
                # (the spec's only_types shields the control plane)
                core.set_local_drop_hook(fp.message_hook("local:core"))
                time.sleep(fp.uniform("faultloop", "window", 0.4, 1.0))
                core.set_local_drop_hook(None)
            elif fault == "restart":
                hosts[victim] = None
                nh.stop()
                time.sleep(fp.uniform("faultloop", "window", 0.2, 0.5))
                hosts[victim] = _mk_host(victim, reg, str(tmp_path))
            else:
                time.sleep(0.4)

        # -------- settle & verify ---------------------------------------------
        # healed tail window, adaptive: on a slow box the fault schedule
        # can leave a sampled group's recorder thin — keep the clients
        # running fault-free until every sampled history is deep enough
        # for a meaningful lincheck
        core.set_local_drop_hook(None)
        for nid in HOSTS:
            if hosts[nid] is not None:
                hosts[nid].set_partitioned(False)
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline and any(
            len(recorders[c].history()) <= 12 for c in SAMPLED
        ):
            time.sleep(0.5)
        stop.set()
        for t in clients:
            t.join(timeout=10)
        core = None
        for nid in HOSTS:
            if hosts[nid] is not None:
                hosts[nid].set_partitioned(False)
            else:
                hosts[nid] = _mk_host(nid, reg, str(tmp_path))
        hosts[1].engine.core.set_local_drop_hook(None)

        # a final write on EVERY group forces commit-index convergence
        deadline = time.monotonic() + 120
        remaining = set(range(1, GROUPS + 1))
        handles = {}
        while remaining and time.monotonic() < deadline:
            lmap = _leaders(hosts)
            for c in list(remaining):
                h = handles.get(c)
                if h is not None:
                    if not h.finished:
                        continue
                    if h.completed:
                        remaining.discard(c)
                        continue
                nh = hosts.get(lmap.get(c))
                if nh is None:
                    continue
                try:
                    handles[c] = nh.propose_batch_async(
                        nh.get_noop_session(c), [b"final=done"], 10
                    )
                except Exception:
                    pass
            time.sleep(0.05)
        assert not remaining, f"{len(remaining)} groups never recovered: " \
                              f"{sorted(remaining)[:10]}"

        # every group: applied indexes + SM hashes converge across replicas
        deadline = time.monotonic() + 90
        diverged = dict.fromkeys(range(1, GROUPS + 1))
        while diverged and time.monotonic() < deadline:
            for c in list(diverged):
                idx = {n: hosts[n].get_applied_index(c) for n in HOSTS}
                if len(set(idx.values())) == 1:
                    del diverged[c]
                else:
                    diverged[c] = idx
            if diverged:
                time.sleep(0.1)
        if diverged:
            for c in list(diverged)[:3]:
                print("DBG snap_stats", c, {k: v for k, v in snap_stats.items() if k[1] == c})
            print("DBG totals", sum(v for k, v in snap_stats.items() if k[0]=="attempt"),
                  "fails", sum(v for k, v in snap_stats.items() if k[0]=="fail"),
                  "oks", sum(v for k, v in snap_stats.items() if k[0]=="ok"))
            core = hosts[1].engine.core
            o = getattr(core, "last_output", None)
            for c in list(diverged)[:3]:
                for nid in HOSTS:
                    lane = core._route.get((c, nid))
                    if lane is None or o is None:
                        continue
                    g = lane.g
                    print(
                        f"DBG c={c} n={nid} g={g} role={int(o['role'][g])} "
                        f"term={int(o['term'][g])} last={int(o['last_index'][g])} "
                        f"match={o['match'][g].tolist()} "
                        f"rstate={o['rstate'][g].tolist()} "
                        f"logrange={lane.node.log_reader.get_range()} "
                        f"applied={lane.node.sm.last_applied_index()} "
                        f"catchup={lane.catchup} snapinfl={lane.snap_inflight} "
                        f"recovering={lane.recovering}"
                    )
        assert not diverged, (
            f"{len(diverged)} groups never converged; sample: "
            f"{dict(list(diverged.items())[:3])}"
        )
        bad_hash = {}
        for c in range(1, GROUPS + 1):
            hs = {n: hosts[n].get_sm_hash(c) for n in HOSTS}
            if len(set(hs.values())) != 1:
                bad_hash[c] = hs
        assert not bad_hash, f"SM divergence: {dict(list(bad_hash.items())[:3])}"

        # linearizability on the sampled groups
        for c in SAMPLED:
            history = recorders[c].history()
            assert len(history) > 10, f"group {c}: too few ops ({len(history)})"
            assert check_kv_history(history, max_states=5_000_000), (
                f"linearizability violation on group {c}"
            )

        # log-matching cross-check on the sampled groups' persisted logs
        from dragonboat_tpu.tools.logdbcheck import check_logdb_consistency

        for c in SAMPLED:
            report = check_logdb_consistency(
                {nid: hosts[nid].logdb for nid in HOSTS}, c
            )
            assert report.ok, f"group {c} logdb violations: {report.violations}"

        assert bulk_done[0] > 0, "bulk load never committed anything"
        for nh in hosts.values():
            nh.stop()
    finally:
        NodeHost._async_send_snapshot = orig_send
        NodeHost._report_snapshot_status = orig_report
