"""ReadIndex tracker conformance matrix (cf. the reference's readIndex
struct internal/raft/readindex.go and its matrix readindex_test.go:30-200):
dedup of duplicate contexts, monotone index discipline, quorum
confirmation releasing every request queued at or before the confirmed
context, and reset on raft state change."""
import pytest

from dragonboat_tpu.core.readindex import ReadIndexTracker
from dragonboat_tpu.types import SystemCtx


def ctx(n: int) -> SystemCtx:
    return SystemCtx(low=n, high=n + 1)


def test_same_ctx_cannot_be_added_twice():
    t = ReadIndexTracker()
    t.add_request(10, ctx(1), 2)
    t.add_request(99, ctx(1), 3)  # duplicate: ignored, index unchanged
    assert len(t.queue) == 1
    assert t.pending[(1, 2)].index == 10


def test_index_must_be_monotone_along_queue():
    t = ReadIndexTracker()
    t.add_request(10, ctx(1), 2)
    with pytest.raises(RuntimeError):
        t.add_request(9, ctx(2), 2)


def test_requests_queue_in_order():
    t = ReadIndexTracker()
    for i in range(5):
        t.add_request(10 + i, ctx(i), 2)
    assert t.has_pending_request()
    assert t.peep_ctx() == ctx(4)  # newest context is what heartbeats carry


def test_confirmation_requires_quorum():
    t = ReadIndexTracker()
    t.add_request(10, ctx(1), 2)
    # quorum=3: leader counts as one, so TWO distinct acks are needed
    assert t.confirm(ctx(1), 2, 3) is None
    assert t.confirm(ctx(1), 2, 3) is None  # same voter again: still 1
    ready = t.confirm(ctx(1), 3, 3)
    assert ready is not None and len(ready) == 1
    assert ready[0].index == 10
    assert not t.has_pending_request()


def test_confirming_later_ctx_releases_earlier_requests_at_its_index():
    """Everything queued at or before the confirmed context reads at the
    CONFIRMED index (indexes are monotone along the queue), exactly the
    batch-release the reference performs (readindex_test.go:125-162)."""
    t = ReadIndexTracker()
    t.add_request(10, ctx(1), 2)
    t.add_request(12, ctx(2), 2)
    t.add_request(15, ctx(3), 2)
    ready = t.confirm(ctx(2), 2, 2)
    assert [r.ctx for r in ready] == [ctx(1), ctx(2)]
    assert [r.index for r in ready] == [12, 12]
    # ctx(3) is still outstanding
    assert t.has_pending_request()
    assert t.peep_ctx() == ctx(3)
    ready = t.confirm(ctx(3), 2, 2)
    assert [r.index for r in ready] == [15]


def test_confirm_unknown_ctx_is_stale():
    t = ReadIndexTracker()
    t.add_request(10, ctx(1), 2)
    assert t.confirm(ctx(9), 2, 2) is None
    assert t.has_pending_request()


def test_tracker_resets_on_state_change():
    """The raft core drops pending reads when leadership/term changes — a
    fresh tracker replaces the old one (readindex_test.go:164-200). Verify
    via the core: a follower stepping to candidate clears ready_to_read
    and pending read contexts."""
    from tests.raft_harness import Network, new_test_raft

    rafts = {i: new_test_raft(i, [1, 2, 3]) for i in (1, 2, 3)}
    net = Network(rafts)
    net.elect(1)
    leader = rafts[1]
    from dragonboat_tpu.types import Message, MessageType as MT

    leader.handle(Message(type=MT.READ_INDEX, from_=1, to=1,
                          hint=7, hint_high=8))
    assert leader.read_index.has_pending_request()
    # a higher-term vote request dethrones the leader mid-read
    net.elect(2)
    assert not rafts[1].is_leader()
    assert not rafts[1].read_index.has_pending_request()


def test_full_width_ctx_no_collision_kernel():
    """Two ReadIndex contexts identical in their LOW 24 bits must release
    independently: the device carries the upper half in the ri_ctx2 plane
    (cf. reference requests.go:365-381 full-width SystemCtx; round-3 carried
    only 24 bits and collided under load)."""
    from dragonboat_tpu.ops.loopback import LoopbackCluster

    c = LoopbackCluster(n_replicas=3, n_groups=1)
    c.run(30)
    lead = c.leader_of(0)
    c.propose(lead, 0, n=1)
    c.run(6)
    # same low plane value, different upper halves
    c.read_index(lead, 0, ctx=0x123456, ctx_high=1)
    c.run(6)
    c.read_index(lead, 0, ctx=0x123456, ctx_high=2)
    c.run(6)
    got = [
        (r[1], r[3]) for r in c.ready_reads[lead] if r[0] == 0
    ]
    assert (0x123456, 1) in got, got
    assert (0x123456, 2) in got, got
