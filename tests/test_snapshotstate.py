"""Snapshot FSM tests (cf. snapshotstate.go:28-214): slot discipline,
flags, index bookkeeping, and the node-level behaviors the FSM drives —
duplicate snapshot requests ignored, periodic saves finalized through the
step loop, recovery gating."""
import time

import pytest

from dragonboat_tpu.engine.snapshotstate import SnapshotState, TaskSlot


class TestTaskSlot:
    def test_set_take(self):
        s = TaskSlot()
        assert not s.occupied()
        assert s.set("a")
        assert s.occupied()
        assert not s.set("b")  # occupied: rejected, not overwritten
        task, had = s.take()
        assert had and task == "a"
        task, had = s.take()
        assert not had and task is None
        assert s.set("b")  # free again


class TestSnapshotState:
    def test_flags(self):
        ss = SnapshotState()
        assert not ss.busy()
        ss.set_taking_snapshot()
        assert ss.taking_snapshot() and ss.busy()
        ss.clear_taking_snapshot()
        ss.set_recovering_from_snapshot()
        assert ss.recovering_from_snapshot() and ss.busy()
        ss.clear_recovering_from_snapshot()
        # streaming is a counter: overlapping lanes to different peers
        ss.begin_stream()
        ss.begin_stream()
        assert ss.streaming_snapshot() and not ss.busy()
        ss.end_stream()
        assert ss.streaming_snapshot()
        ss.end_stream()
        assert not ss.streaming_snapshot()
        assert not ss.busy()

    def test_compact_log_to_swap_read(self):
        ss = SnapshotState()
        assert not ss.has_compact_log_to()
        ss.set_compact_log_to(42)
        assert ss.has_compact_log_to()
        assert ss.get_compact_log_to() == 42
        assert ss.get_compact_log_to() == 0  # swap cleared it

    def test_indexes(self):
        ss = SnapshotState()
        ss.set_snapshot_index(7)
        ss.set_req_snapshot_index(9)
        assert ss.get_snapshot_index() == 7
        assert ss.get_req_snapshot_index() == 9


def _counter_sm():
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class SM(IStateMachine):
        def __init__(self):
            self.n = 0

        def update(self, data):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, fc, done):
            w.write(self.n.to_bytes(8, "little"))

        def recover_from_snapshot(self, r, fc, done):
            self.n = int.from_bytes(r.read(8), "little")

        def close(self):
            pass

    return SM


@pytest.mark.parametrize("engine", ["scalar", "vector"])
def test_duplicate_snapshot_request_ignored(tmp_path, engine):
    """A second user snapshot request with nothing newly applied is
    rejected instead of writing an identical image (cf. node.go:1085-1091
    reqSnapshotIndex check)."""
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.requests import ErrRejected
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    SM = _counter_sm()
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=91, rtt_millisecond=5, raft_address="ssf1:1",
        nodehost_dir=str(tmp_path / "nh1"),
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(kind=engine, max_groups=4, max_peers=4,
                            log_window=64),
    ))
    try:
        nh.start_cluster({1: "ssf1:1"}, False, lambda c, n: SM(),
                         Config(cluster_id=1, node_id=1, election_rtt=20,
                                heartbeat_rtt=2))
        deadline = time.time() + 60
        while time.time() < deadline:
            _, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        assert ok
        s = nh.get_noop_session(1)
        for i in range(5):
            nh.sync_propose(s, b"x", timeout_s=5.0)

        idx = nh.sync_request_snapshot(1, timeout_s=15.0)
        assert idx > 0
        with pytest.raises(ErrRejected):
            nh.sync_request_snapshot(1, timeout_s=15.0)
        # new applies make the next request meaningful again
        nh.sync_propose(s, b"y", timeout_s=5.0)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                idx2 = nh.sync_request_snapshot(1, timeout_s=15.0)
                break
            except ErrRejected:
                time.sleep(0.1)  # applied cursor catching up
        assert idx2 > idx
        # FSM settled: flags clear, snapshot index recorded
        node = nh._get_node(1)
        assert not node.ss.busy()
        assert node.ss.get_snapshot_index() == idx2
    finally:
        nh.stop()


def test_periodic_snapshot_finalizes_through_step_loop(tmp_path):
    """snapshot_entries-triggered saves must finish through the completed
    slot: pending request acked, taking flag cleared, log compacted, and
    a restart recovers from the image."""
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    SM = _counter_sm()
    reg = _Registry()

    def mk(restart=False):
        nh = NodeHost(NodeHostConfig(
            deployment_id=92, rtt_millisecond=5, raft_address="ssp1:1",
            nodehost_dir=str(tmp_path / "nh1"),
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4,
                                log_window=64),
        ))
        nh.start_cluster({} if restart else {1: "ssp1:1"}, False,
                         lambda c, n: SM(),
                         Config(cluster_id=1, node_id=1, election_rtt=20,
                                heartbeat_rtt=2, snapshot_entries=10,
                                compaction_overhead=3))
        return nh

    nh = mk()
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            _, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        assert ok
        s = nh.get_noop_session(1)
        for i in range(25):  # crosses snapshot_entries twice
            nh.sync_propose(s, b"x", timeout_s=5.0)
        node = nh._get_node(1)
        deadline = time.time() + 30
        while time.time() < deadline:
            if node.ss.get_snapshot_index() > 0 and not node.ss.busy():
                break
            time.sleep(0.05)
        assert node.ss.get_snapshot_index() > 0
        assert not node.ss.taking_snapshot()
    finally:
        nh.stop()

    nh = mk(restart=True)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if nh.stale_read(1, None) == 25:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert nh.stale_read(1, None) == 25
    finally:
        nh.stop()
