"""Transport tests: framing, loopback + TCP delivery, batching, breaker +
unreachable fanout (cf. internal/transport/transport_test.go patterns)."""
import socket
import threading
import time

import pytest

from dragonboat_tpu.raftio import IMessageHandler
from dragonboat_tpu.transport import Transport, loopback_factory
from dragonboat_tpu.transport.loopback import _Registry
from dragonboat_tpu.transport.tcp import tcp_factory
from dragonboat_tpu.types import Entry, Message, MessageType


class CollectingHandler(IMessageHandler):
    def __init__(self):
        self.batches = []
        self.unreachable = []
        self.event = threading.Event()

    def handle_message_batch(self, batch):
        self.batches.append(batch)
        self.event.set()
        return 0, len(batch.requests)

    def handle_unreachable(self, cluster_id, node_id):
        self.unreachable.append((cluster_id, node_id))

    def handle_snapshot_status(self, cluster_id, node_id, failed):
        pass

    def handle_snapshot(self, cluster_id, node_id, from_):
        pass


def wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def mk_msg(cid=1, to=2, frm=1, n=1):
    return Message(
        type=MessageType.REPLICATE,
        cluster_id=cid,
        to=to,
        from_=frm,
        term=3,
        entries=[Entry(index=i + 1, term=3, cmd=b"payload") for i in range(n)],
    )


def mk_pair(registry, a_addr="hostA:1", b_addr="hostB:2", deployment_id=7):
    ha, hb = CollectingHandler(), CollectingHandler()
    ta = Transport(a_addr, deployment_id, loopback_factory(a_addr, registry))
    tb = Transport(b_addr, deployment_id, loopback_factory(b_addr, registry))
    ta.set_message_handler(ha)
    tb.set_message_handler(hb)
    ta.start()
    tb.start()
    return ta, tb, ha, hb


def test_loopback_roundtrip():
    reg = _Registry()
    ta, tb, ha, hb = mk_pair(reg)
    try:
        ta.nodes.add_node(1, 2, "hostB:2")
        assert ta.send(mk_msg())
        assert wait_for(lambda: hb.batches)
        got = hb.batches[0]
        assert got.source_address == "hostA:1"
        assert got.requests[0].entries[0].cmd == b"payload"
    finally:
        ta.stop()
        tb.stop()


def test_send_unresolvable_reports_unreachable():
    reg = _Registry()
    ta, tb, ha, hb = mk_pair(reg)
    try:
        assert not ta.send(mk_msg(cid=9, to=9))
        assert (9, 9) in ha.unreachable
    finally:
        ta.stop()
        tb.stop()


def test_deployment_id_gating():
    reg = _Registry()
    ha, hb = CollectingHandler(), CollectingHandler()
    ta = Transport("a:1", 7, loopback_factory("a:1", reg))
    tb = Transport("b:2", 8, loopback_factory("b:2", reg))  # different deployment
    ta.set_message_handler(ha)
    tb.set_message_handler(hb)
    ta.start()
    tb.start()
    try:
        ta.nodes.add_node(1, 2, "b:2")
        ta.send(mk_msg())
        time.sleep(0.3)
        assert hb.batches == []  # dropped at receive
    finally:
        ta.stop()
        tb.stop()


def test_breaker_trips_and_unreachable_fanout():
    reg = _Registry()
    ta, tb, ha, hb = mk_pair(reg)
    try:
        ta.nodes.add_node(1, 2, "hostB:2")
        ta.nodes.add_node(3, 5, "hostB:2")
        ta.rpc.blocked = True  # outbound sends now fail
        ta.send(mk_msg())
        assert wait_for(lambda: (1, 2) in ha.unreachable and (3, 5) in ha.unreachable)
        # breaker open: send is refused immediately
        assert wait_for(lambda: not ta.send(mk_msg()))
        ta.rpc.blocked = False
        time.sleep(1.1)  # cooldown
        assert ta.send(mk_msg())
        assert wait_for(lambda: hb.batches)
    finally:
        ta.stop()
        tb.stop()


def test_learned_remote_addresses():
    reg = _Registry()
    ta, tb, ha, hb = mk_pair(reg)
    try:
        ta.nodes.add_node(1, 2, "hostB:2")
        ta.send(mk_msg(cid=1, to=2, frm=5))
        assert wait_for(lambda: hb.batches)
        # B learned that (1,5) lives at hostA:1 and can reply without config
        assert tb.nodes.resolve(1, 5) == "hostA:1"
        tb.send(Message(type=MessageType.REPLICATE_RESP, cluster_id=1, to=5, from_=2))
        assert wait_for(lambda: ha.batches)
    finally:
        ta.stop()
        tb.stop()


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_tcp_transport_roundtrip():
    pa, pb = free_port(), free_port()
    addr_a, addr_b = f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"
    ha, hb = CollectingHandler(), CollectingHandler()
    ta = Transport(addr_a, 7, tcp_factory(addr_a))
    tb = Transport(addr_b, 7, tcp_factory(addr_b))
    ta.set_message_handler(ha)
    tb.set_message_handler(hb)
    ta.start()
    tb.start()
    try:
        ta.nodes.add_node(1, 2, addr_b)
        tb.nodes.add_node(1, 1, addr_a)
        big = mk_msg(n=50)
        assert ta.send(big)
        assert wait_for(lambda: hb.batches)
        assert len(hb.batches[0].requests[0].entries) == 50
        # reply direction over its own connection
        tb.send(Message(type=MessageType.REPLICATE_RESP, cluster_id=1, to=1, from_=2))
        assert wait_for(lambda: ha.batches)
    finally:
        ta.stop()
        tb.stop()


def test_tcp_many_messages_batching():
    pa, pb = free_port(), free_port()
    addr_a, addr_b = f"127.0.0.1:{pa}", f"127.0.0.1:{pb}"
    ha, hb = CollectingHandler(), CollectingHandler()
    ta = Transport(addr_a, 0, tcp_factory(addr_a))
    tb = Transport(addr_b, 0, tcp_factory(addr_b))
    ta.set_message_handler(ha)
    tb.set_message_handler(hb)
    ta.start()
    tb.start()
    try:
        ta.nodes.add_node(1, 2, addr_b)
        for _ in range(200):
            ta.send(mk_msg())
        assert wait_for(
            lambda: sum(len(b.requests) for b in hb.batches) == 200
        )
        # batching must have coalesced (fewer batches than messages)
        assert len(hb.batches) < 200
    finally:
        ta.stop()
        tb.stop()
