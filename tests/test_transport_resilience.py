"""Transport resilience tests: breaker backoff growth, jittered
cooldowns, half-open probe success/failure, and heartbeat-over-bulk
priority/eviction order in the send queue (ISSUE 2 satellite)."""
import random
import threading
import time

import pytest

from dragonboat_tpu.transport.transport import (
    URGENT_TYPES,
    _Breaker,
    _SendQueue,
    Transport,
)
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
from dragonboat_tpu.types import Entry, Message, MessageType


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def mk_breaker(**kw):
    clock = FakeClock()
    b = _Breaker(
        base_cooldown=0.5,
        max_cooldown=8.0,
        jitter=0.25,
        rng=random.Random(7),
        clock=clock,
        **kw,
    )
    return b, clock


# --------------------------------------------------------------- breaker
def test_breaker_backoff_growth_and_jitter():
    b, clock = mk_breaker()
    nominals = []
    cooldowns = []
    for _ in range(6):
        b.fail()
        snap = b.snapshot()
        nominals.append(snap["nominal_cooldown_s"])
        cooldowns.append(snap["cooldown_s"])
        clock.advance(snap["cooldown_s"] + 0.01)
        assert b.allow_probe()  # half-open: probe granted, then fails again
    # nominal cooldown doubles per reopen up to the cap
    assert nominals == [0.5, 1.0, 2.0, 4.0, 8.0, 8.0]
    # actual cooldowns are jittered within ±25% of nominal, and not all
    # equal to nominal (the jitter is real)
    for nom, cd in zip(nominals, cooldowns):
        assert 0.75 * nom <= cd <= 1.25 * nom
    assert any(abs(cd - nom) > 1e-6 for nom, cd in zip(nominals, cooldowns))


def test_breaker_half_open_single_probe_then_close():
    b, clock = mk_breaker()
    b.fail()
    assert b.is_open()
    assert not b.allow_probe()  # still cooling
    assert not b.allow_enqueue()
    clock.advance(b.snapshot()["cooldown_s"] + 0.01)
    assert b.allow_enqueue()  # half-open window admits traffic
    assert b.allow_probe()  # exactly ONE probe
    assert not b.allow_probe()  # concurrent probe refused
    b.success()
    assert not b.is_open()
    assert b.allow_probe()  # closed again: all traffic flows
    assert b.snapshot()["nominal_cooldown_s"] == 0.5  # backoff reset


def test_breaker_probe_failure_reopens_with_doubled_cooldown():
    b, clock = mk_breaker()
    b.fail()
    cd1 = b.snapshot()["cooldown_s"]
    clock.advance(cd1 + 0.01)
    assert b.allow_probe()
    b.fail()  # probe failed
    snap = b.snapshot()
    assert snap["state"] == "open"
    assert snap["nominal_cooldown_s"] == 1.0
    assert snap["probe_failures"] == 1
    assert not b.allow_probe()  # cooling again, from the failure time


# ------------------------------------------------------------ send queue
def hb(to=2):
    return Message(type=MessageType.HEARTBEAT, cluster_id=1, to=to, from_=1)


def vote(to=2):
    return Message(type=MessageType.REQUEST_VOTE, cluster_id=1, to=to, from_=1)


def bulk(i=0):
    return Message(
        type=MessageType.REPLICATE,
        cluster_id=1,
        to=2,
        from_=1,
        entries=[Entry(index=i + 1, term=1, cmd=b"x" * 32)],
    )


def drain(sq):
    out = []
    while True:
        m = sq.get_nowait()
        if m is None:
            return out
        out.append(m)


def test_urgent_pops_before_bulk():
    sq = _SendQueue(16)
    assert sq.try_put(bulk(0))
    assert sq.try_put(bulk(1))
    assert sq.try_put(hb())
    assert sq.try_put(vote())
    got = drain(sq)
    assert [m.type for m in got] == [
        MessageType.HEARTBEAT,
        MessageType.REQUEST_VOTE,
        MessageType.REPLICATE,
        MessageType.REPLICATE,
    ]
    # relative order within each class is preserved
    assert [m.entries[0].index for m in got[2:]] == [1, 2]


def test_full_queue_urgent_evicts_oldest_bulk():
    sq = _SendQueue(3)
    for i in range(3):
        assert sq.try_put(bulk(i))
    assert sq.try_put(hb())  # queue full: evicts bulk(0)
    assert sq.evicted_bulk == 1
    assert sq.dropped_urgent == 0
    got = drain(sq)
    assert got[0].type == MessageType.HEARTBEAT
    assert [m.entries[0].index for m in got[1:]] == [2, 3]


def test_full_queue_bulk_is_dropped_not_urgent():
    sq = _SendQueue(2)
    assert sq.try_put(bulk(0))
    assert sq.try_put(bulk(1))
    assert not sq.try_put(bulk(2))  # bulk refused at full
    assert sq.dropped_bulk == 1
    assert sq.try_put(hb())  # urgent still admitted (evicts)
    assert sq.dropped_urgent == 0


def test_urgent_exempt_from_byte_backpressure():
    # tiny byte budget: bulk is rate-limited out, heartbeats still flow
    sq = _SendQueue(64, max_bytes=100)
    assert sq.try_put(bulk(0))
    assert not sq.try_put(bulk(1))  # over the byte budget
    assert sq.try_put(hb())
    assert sq.try_put(vote())
    assert sq.dropped_bulk == 1
    assert sq.dropped_urgent == 0


def test_put_many_counts_and_wakes_once():
    sq = _SendQueue(4)
    msgs = [bulk(0), hb(), bulk(1), bulk(2), bulk(3)]  # one over capacity
    assert sq.put_many(msgs) == 4
    assert sq.dropped_bulk == 1
    got = drain(sq)
    assert got[0].type == MessageType.HEARTBEAT


def test_urgent_types_cover_the_control_plane():
    assert MessageType.HEARTBEAT in URGENT_TYPES
    assert MessageType.HEARTBEAT_RESP in URGENT_TYPES
    assert MessageType.REQUEST_VOTE in URGENT_TYPES
    assert MessageType.REQUEST_VOTE_RESP in URGENT_TYPES
    assert MessageType.TIMEOUT_NOW in URGENT_TYPES
    assert MessageType.REPLICATE not in URGENT_TYPES


# --------------------------------------------- end-to-end breaker recovery
class CollectingHandler:
    def __init__(self):
        self.batches = []
        self.unreachable = []

    def handle_message_batch(self, batch):
        self.batches.append(batch)
        return 0, len(batch.requests)

    def handle_unreachable(self, cluster_id, node_id):
        self.unreachable.append((cluster_id, node_id))

    def handle_snapshot_status(self, *a):
        pass

    def handle_snapshot(self, *a):
        pass


def wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_transport_breaker_metrics_and_recovery():
    reg = _Registry()
    ha, hb_ = CollectingHandler(), CollectingHandler()
    ta = Transport("hostA:1", 7, loopback_factory("hostA:1", reg))
    tb = Transport("hostB:2", 7, loopback_factory("hostB:2", reg))
    ta.set_message_handler(ha)
    tb.set_message_handler(hb_)
    ta.start()
    tb.start()
    try:
        ta.nodes.add_node(1, 2, "hostB:2")
        ta.rpc.blocked = True
        ta.send(bulk(0))
        assert wait_for(lambda: ta.metrics()["breakers_open"] == 1)
        assert ta.metrics()["breaker_opens"] >= 1
        states = ta.breaker_states()
        assert states["hostB:2"]["state"] == "open"
        ta.rpc.blocked = False
        # within a few cooldowns the half-open probe closes the breaker
        assert wait_for(lambda: ta.send(hb()) and hb_.batches, timeout=8)
        assert wait_for(lambda: ta.metrics()["breakers_open"] == 0, timeout=8)
        assert ta.breaker_states()["hostB:2"]["probes"] >= 1
    finally:
        ta.stop()
        tb.stop()
