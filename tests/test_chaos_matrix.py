"""Bounded FaultPlane seed matrix (`-m chaos`): each seed drives a SHORT
drop+partition schedule against a 3-host shared-core vector cluster and
asserts recovery + convergence. Small enough for the tier-1 budget; the
seed prints at the start so any CI failure replays bit-identically by
pinning CHAOS_SEED.

The long free-form chaos runs stay in test_chaos.py / test_chaos_scale.py
(marked slow); this matrix is the fast regression net over the FaultPlane
seams themselves.
"""
import json
import os
import threading
import time
import zlib

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.faults import REPLICATION_TYPES, FaultPlane, FaultSpec
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

CLUSTER = 2
HOSTS = (1, 2, 3)

SEEDS = [11, 29, 47]
_env_seed = os.environ.get("CHAOS_SEED")
if _env_seed:
    SEEDS = [int(_env_seed, 0)]


class KV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, tmp, seed):
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=11,
            rtt_millisecond=5,
            nodehost_dir=f"{tmp}/h{nid}",
            raft_address=f"cm{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector",
                max_groups=32,
                max_peers=4,
                log_window=64,
                share_scope=f"chaos-matrix-{seed}",
            ),
        )
    )
    nh.start_cluster(
        {h: f"cm{h}:1" for h in HOSTS},
        False,
        lambda c, n: KV(),
        Config(
            cluster_id=CLUSTER,
            node_id=nid,
            election_rtt=20,
            heartbeat_rtt=4,
            snapshot_entries=0,
        ),
    )
    return nh


@pytest.mark.chaos
@pytest.mark.parametrize("seed", SEEDS)
def test_seed_matrix_drop_partition_converges(tmp_path, seed):
    print(f"CHAOS SEED={seed} (replay: CHAOS_SEED={seed} pytest -m chaos)")
    # drops target replication only on the co-hosted path — the control
    # plane stays lossless so the matrix stresses data-plane recovery
    fp = FaultPlane(seed, FaultSpec(drop=0.3, only_types=REPLICATION_TYPES))
    reg = _Registry()
    hosts = {nid: _mk_host(nid, reg, str(tmp_path), seed) for nid in HOSTS}
    core = hosts[1].engine.core
    try:
        deadline = time.monotonic() + 60
        leader = None
        while leader is None and time.monotonic() < deadline:
            for nid, nh in hosts.items():
                lid, ok = nh.get_leader_id(CLUSTER)
                if ok and lid == nid:
                    leader = nid
                    break
            time.sleep(0.02)
        assert leader is not None, f"no leader elected (seed={seed})"

        # writer thread keeps proposing through the fault window
        stop = threading.Event()
        committed = [0]

        def writer():
            n = 0
            while not stop.is_set():
                for nid, nh in hosts.items():
                    lid, ok = nh.get_leader_id(CLUSTER)
                    if not ok or lid != nid or nh.is_partitioned():
                        continue
                    n += 1
                    try:
                        nh.sync_propose(
                            nh.get_noop_session(CLUSTER),
                            f"k{n % 4}=v{n}".encode(),
                            timeout_s=1.0,
                        )
                        committed[0] += 1
                    except Exception:
                        pass
                    break
                else:
                    time.sleep(0.05)

        t = threading.Thread(target=writer, daemon=True)
        t.start()

        core.set_local_drop_hook(fp.message_hook("local:core"))
        for victim, window, idle in fp.partition_schedule(
            "faultloop", HOSTS, total_s=4.0, min_window_s=0.2, max_window_s=0.5
        ):
            hosts[victim].set_partitioned(True)
            time.sleep(window)
            hosts[victim].set_partitioned(False)
            time.sleep(idle)
        core.set_local_drop_hook(None)
        for nh in hosts.values():
            nh.set_partitioned(False)
        # healed window, adaptive for loaded CI boxes: the writer keeps
        # going until at least one proposal commits
        deadline = time.monotonic() + 30
        while committed[0] == 0 and time.monotonic() < deadline:
            time.sleep(0.2)
        stop.set()
        t.join(timeout=5)
        assert committed[0] > 0, f"nothing committed under seed {seed}"

        # final write + full convergence
        deadline = time.monotonic() + 45
        while True:
            try:
                for nid, nh in hosts.items():
                    lid, ok = nh.get_leader_id(CLUSTER)
                    if ok and lid == nid:
                        nh.sync_propose(
                            nh.get_noop_session(CLUSTER), b"final=done", 5.0
                        )
                        raise StopIteration
                time.sleep(0.1)
            except StopIteration:
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            idx = {n: hosts[n].get_applied_index(CLUSTER) for n in HOSTS}
            if len(set(idx.values())) == 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"seed {seed}: applied indexes never converged: {idx}"
            )
        hashes = {n: hosts[n].get_sm_hash(CLUSTER) for n in HOSTS}
        assert len(set(hashes.values())) == 1, (
            f"seed {seed}: SM divergence {hashes}"
        )
        # control-plane protection held under backpressure
        for nid, nh in hosts.items():
            assert nh.transport.metrics()["queue_dropped_urgent"] == 0
    finally:
        for nh in hosts.values():
            nh.stop()


@pytest.mark.chaos
def test_rebalance_under_load_scenario(tmp_path):
    """ISSUE 14: the `rebalance_under_load` longhaul scenario in the
    `-m chaos` matrix — hot-tenant skew on a throw-away group, a live
    migration (member swap onto the churn host over transfer + the
    streamed install path) mid-round, and the round's verdict set
    including migration_lincheck + migration_no_urgent_shed asserted
    green. Replay any failure by pinning CHAOS_SEED."""
    from dragonboat_tpu.tools.longhaul import Options, run_longhaul

    seed = int(os.environ.get("CHAOS_SEED", "0") or "0", 0) or 0x5EED14
    print(f"CHAOS SEED={seed:#x} (replay: CHAOS_SEED={seed:#x} pytest -m chaos)")
    report = run_longhaul(
        Options(
            budget_s=60.0,
            rounds_max=1,
            round_s=5.0,
            engine="vector",
            out_dir=str(tmp_path / "lh"),
            seed=seed,
            rotate=False,
            ring=False,
            scenarios=("rebalance_under_load", "none"),
        )
    )
    rounds = report["rounds"]
    assert rounds, "no round ran"
    res = rounds[0]
    assert res.ok, (
        f"seed {seed:#x} verdicts="
        f"{sorted(k for k, v in res.verdicts.items() if not v)} "
        f"error={res.error} bundle={res.bundle}"
    )
    assert res.scenarios.get("rebalance_under_load", 0) > 0, res.scenarios
    # the migration verdicts actually fired
    assert "migration_lincheck" in res.verdicts
    assert "migration_no_urgent_shed" in res.verdicts


@pytest.mark.chaos
def test_rejoin_plane_scenario_family(tmp_path):
    """The rejoin-without-disruption scenario family in the `-m chaos`
    matrix: one seeded longhaul round restricted to
    observer_witness_churn / prevote_rejoin_storm /
    streamed_install_under_crash, with the round's full verdict set
    (lincheck, convergence, fairness, plus the scenario verdicts:
    prevote_no_disturbance, ow_witness_zero_payload) asserted green.
    Replay any failure by pinning CHAOS_SEED."""
    from dragonboat_tpu.tools.longhaul import Options, run_longhaul

    seed = int(os.environ.get("CHAOS_SEED", "0") or "0", 0) or 0x5EED13
    print(f"CHAOS SEED={seed:#x} (replay: CHAOS_SEED={seed:#x} pytest -m chaos)")
    report = run_longhaul(
        Options(
            budget_s=40.0,
            rounds_max=1,
            round_s=6.0,
            engine="vector",
            out_dir=str(tmp_path / "lh"),
            seed=seed,
            rotate=False,
            ring=False,
            scenarios=(
                "observer_witness_churn",
                "prevote_rejoin_storm",
                "streamed_install_under_crash",
                "none",
            ),
        )
    )
    rounds = report["rounds"]
    assert rounds, "no round ran"
    res = rounds[0]
    assert res.ok, (
        f"seed {seed:#x} verdicts="
        f"{sorted(k for k, v in res.verdicts.items() if not v)} "
        f"error={res.error} bundle={res.bundle}"
    )
    # the family actually fired
    assert sum(
        res.scenarios.get(k, 0)
        for k in (
            "observer_witness_churn",
            "prevote_rejoin_storm",
            "streamed_install_under_crash",
        )
    ) > 0, res.scenarios
