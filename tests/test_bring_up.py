"""Fleet bring-up (VERDICT r3 item 5): batched StartCluster + vectorized
leadership readout. The 50k-group regime from BASELINE.json comes up in
~42s on one CPU core (.verify/dbg_bringup.py measured run: start_clusters
25.6s + elections 15.7s); this test guards the mechanism at CI-friendly
scale with CI-generous bounds."""
from __future__ import annotations

import time

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry


class _SM(IStateMachine):
    def __init__(self, *a):
        self.n = 0

    def update(self, data):
        self.n += 1
        return Result(value=self.n)

    def lookup(self, q):
        return self.n

    def save_snapshot(self, w, fc, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, fc, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def test_bulk_fleet_bring_up(tmp_path):
    """2048 single-replica groups: bulk start (one bootstrap fsync per
    shard) + self-election + one vectorized leadership snapshot."""
    G = 2048
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        raft_address="bu:1", rtt_millisecond=10,
        nodehost_dir=str(tmp_path / "nh"),
        raft_rpc_factory=lambda a: loopback_factory(a, reg),
        engine=EngineConfig(kind="vector", max_groups=G, max_peers=4,
                            log_window=64, inbox_depth=4,
                            max_entries_per_msg=16)))
    try:
        t0 = time.monotonic()
        nh.start_clusters([
            ({1: "bu:1"}, False, lambda cid, n: _SM(),
             Config(node_id=1, cluster_id=c, election_rtt=20,
                    heartbeat_rtt=2))
            for c in range(1, G + 1)
        ])
        leaders = {}
        while len(leaders) < G and time.monotonic() - t0 < 120:
            snap = nh.engine.leader_snapshot()
            leaders = {c: l for c, (l, _t) in snap.items() if l}
            time.sleep(0.05)
        took = time.monotonic() - t0
        assert len(leaders) == G, f"{len(leaders)}/{G} elected in {took:.1f}s"
        # every group is led by its only replica
        assert set(leaders.values()) == {1}
        # the fleet is live: a proposal commits on an arbitrary group
        r = nh.sync_propose(nh.get_noop_session(G // 2), b"x", 15.0)
        assert r.value == 1
    finally:
        nh.stop()


def test_bulk_start_matches_incremental(tmp_path):
    """start_clusters and start_cluster produce identical on-disk
    bootstraps: a fleet-started node restarts through the normal path."""
    reg = _Registry()

    def mk():
        return NodeHost(NodeHostConfig(
            raft_address="bu2:1", rtt_millisecond=10,
            nodehost_dir=str(tmp_path / "nh"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(kind="vector", max_groups=8, max_peers=4,
                                log_window=64)))

    nh = mk()
    nh.start_clusters([
        ({1: "bu2:1"}, False, lambda cid, n: _SM(),
         Config(node_id=1, cluster_id=c, election_rtt=20, heartbeat_rtt=2))
        for c in (1, 2)
    ])
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60:
        if all(nh.get_leader_id(c)[1] for c in (1, 2)):
            break
        time.sleep(0.02)
    for c in (1, 2):
        nh.sync_propose(nh.get_noop_session(c), b"p", 15.0)
    nh.stop()
    # restart through the INCREMENTAL path: bootstrap records must validate
    nh = mk()
    try:
        for c in (1, 2):
            nh.start_cluster({1: "bu2:1"}, False, lambda cid, n: _SM(),
                             Config(node_id=1, cluster_id=c,
                                    election_rtt=20, heartbeat_rtt=2))
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            if all(nh.stale_read(c, None) >= 1 for c in (1, 2)):
                break
            time.sleep(0.05)
        for c in (1, 2):
            assert nh.stale_read(c, None) >= 1
    finally:
        nh.stop()
