"""Scale tests for the VectorEngine host loop (round-3 acceptance):

- >=1024 lanes elect leaders and commit end-to-end on one NodeHost,
- idle lanes with quiesce enabled stop producing host work entirely
  (cf. reference quiesce.go:23-123 — the device analogue freezes timers
  so idle leaders emit no heartbeats and the engine skips kernel steps).
"""
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry


class CountSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.n = 0

    def update(self, data):
        self.n += 1
        return Result(value=self.n)

    def lookup(self, q):
        return self.n

    def save_snapshot(self, w, fc, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, fc, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


def _mk_host(groups, quiesce=False):
    reg = _Registry()
    cfg = NodeHostConfig(
        raft_address="scale:1",
        rtt_millisecond=2,
        raft_rpc_factory=lambda addr: loopback_factory(addr, reg),
        engine=EngineConfig(
            kind="vector", max_groups=groups, max_peers=4, log_window=64
        ),
    )
    nh = NodeHost(cfg)
    for c in range(1, groups + 1):
        nh.start_cluster(
            {1: "scale:1"},
            False,
            lambda cid, nid: CountSM(cid, nid),
            Config(
                node_id=1,
                cluster_id=c,
                election_rtt=10,
                heartbeat_rtt=2,
                quiesce=quiesce,
            ),
        )
    return nh


def _wait_leaders(nh, groups, deadline_s):
    t0 = time.monotonic()
    pending = set(range(1, groups + 1))
    while pending and time.monotonic() - t0 < deadline_s:
        pending -= {c for c in pending if nh.get_leader_id(c)[1]}
        if pending:
            time.sleep(0.05)
    return pending


@pytest.mark.slow
def test_1024_lanes_elect_and_commit():
    groups = 1024
    nh = _mk_host(groups)
    try:
        pending = _wait_leaders(nh, groups, 150)
        assert not pending, f"{len(pending)} lanes never elected a leader"
        # one committed proposal per lane, pipelined
        outstanding = [
            nh.propose(nh.get_noop_session(c), b"payload-16-byte", 30)
            for c in range(1, groups + 1)
        ]
        for rs in outstanding:
            r = rs.wait(timeout=30)
            assert r is not None and r.completed, r
    finally:
        nh.stop()


@pytest.mark.slow
def test_idle_quiesced_lanes_cost_no_host_work():
    groups = 256
    nh = _mk_host(groups, quiesce=True)
    eng = nh.engine
    try:
        pending = _wait_leaders(nh, groups, 150)
        assert not pending
        # commit one proposal per lane so there is real log state
        for c in range(1, groups + 1):
            nh.sync_propose(nh.get_noop_session(c), b"x", 10.0)
        # quiesce threshold is 10*election_rtt ticks = 100 ticks * 2ms;
        # wait for every lane to freeze
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if bool((eng._m_quiesced | ~eng._m_active).all()):
                break
            time.sleep(0.1)
        assert bool((eng._m_quiesced | ~eng._m_active).all()), (
            "lanes never quiesced"
        )
        # a fully-quiesced fleet skips kernel steps entirely: the send
        # planes stay silent and the transport sees zero traffic
        sent_before = dict(nh.transport.metrics())
        time.sleep(1.0)
        sent_after = dict(nh.transport.metrics())
        assert sent_before == sent_after, (sent_before, sent_after)
        # a fresh proposal wakes the lane back up and commits
        r = nh.sync_propose(nh.get_noop_session(1), b"wake", 10.0)
        assert r is not None
        assert not bool(eng._m_quiesced[nh._get_node(1)._vec_lane.g])
    finally:
        nh.stop()
