"""Back-compat conformance shim over `dragonboat_tpu.analysis`.

The four rule families that used to live HERE as ~460 lines of ad-hoc
AST walking — columnar (PR 1), lock-amortization (PR 2), telemetry-guard
(PR 3), trace-guard (PR 4) — now run on the shared rule engine
(dragonboat_tpu/analysis/, targets declared in analysis/targets.py,
suppression via `# lint: allow(rule) reason` pragmas). This file keeps
the historical test names alive as thin assertions over the engine so
existing CI habits (`pytest tests/test_hot_path_lint.py`) keep guarding
exactly the same regressions; the full gate (all seven families + the
meta-tests) is tests/test_static_analysis.py and
`python -m dragonboat_tpu.tools.check`.
"""
from __future__ import annotations

import pytest

from dragonboat_tpu.analysis import build_analyzer, unsuppressed
from dragonboat_tpu.analysis.engine import SourceModule
from dragonboat_tpu.analysis.targets import DEFAULT_TARGETS

pytestmark = pytest.mark.lint

# back-compat names: the target lists now live in analysis/targets.py
HOT_FUNCTIONS = sorted(DEFAULT_TARGETS.hot_functions)
HOT_LOCK_FUNCTIONS = sorted(DEFAULT_TARGETS.hot_lock_functions)
HOT_TELEMETRY_FUNCTIONS = sorted(DEFAULT_TARGETS.hot_telemetry_functions)
HOT_TRACE_FUNCTIONS = sorted(DEFAULT_TARGETS.hot_trace_functions)


def _family_clean(*families):
    findings = unsuppressed(build_analyzer(families=families).run())
    assert not findings, "\n" + "\n".join(f.render() for f in findings)


def _snippet(src, relpath, *families):
    a = build_analyzer(families=families)
    return [
        f
        for f in a.run_module(SourceModule.from_snippet(src, relpath))
        if not f.suppressed
    ]


def test_hot_path_stays_columnar():
    _family_clean("columnar")


def test_transport_send_path_amortizes_locks():
    _family_clean("locks")


def test_hot_path_telemetry_is_sampling_guarded():
    _family_clean("telemetry")


def test_trace_stamping_is_sampling_guarded():
    _family_clean("trace")


def test_lint_catches_regressions():
    """The lint itself must flag the banned patterns (meta-test: a broken
    linter silently passing everything is worse than no linter)."""
    got = _snippet(
        """
        def gather_post_sends(o, gs):
            for g in gs.tolist():
                x = int(o['term'][g])
                y = o['match'][g].tolist()
                z = o['vote'][g].item()
        """,
        "engine/vector.py",
        "columnar",
    )
    assert len(got) == 3, got


def test_lock_lint_catches_regressions():
    got = _snippet(
        """
        class _SendQueue:
            def put_many(self, msgs):
                n = 0
                for m in msgs:
                    with self._cv:
                        n += 1
                with self._cv:
                    pass
                return n
        """,
        "transport/transport.py",
        "locks",
    )
    assert len(got) == 1, got


def test_telemetry_lint_catches_regressions():
    got = _snippet(
        """
        class Transport:
            def send_many(self, msgs):
                for m in msgs:
                    self.metrics.observe('x', (0, 0), 1.0)
                recorder.record('evt', a=1)
                if self.profiler.sampling:
                    self.metrics.observe('x', (0, 0), 1.0)
                if lat_sampler.sample():
                    recorder.record('evt')
        """,
        "transport/transport.py",
        "telemetry",
    )
    assert len(got) == 2, got


def test_trace_lint_catches_regressions():
    got = _snippet(
        """
        class Node:
            def propose(self, session, cmd, timeout_ticks):
                entry.trace_id = mint_trace_id()
                recorder.record('propose_enqueue', trace=entry.trace_id)
                if self._req_sampler.sample():
                    entry.trace_id = mint_trace_id()
                    recorder.record('propose_enqueue')
                if entry.trace_id:
                    recorder.record('replicate_send')
        """,
        "engine/node.py",
        "trace",
    )
    assert len(got) == 3, got


def test_bench_json_carries_commit_latency_keys():
    """BENCH JSON schema smoke test: the per-config latency report always
    carries commit_latency_p50_s / commit_latency_p99_s (0.0 when no
    samples landed), and real observations produce real percentiles."""
    import bench
    from dragonboat_tpu.events import MetricsRegistry

    class FakeNH:
        def __init__(self):
            self.metrics = MetricsRegistry()

    nh = FakeNH()
    for v in (0.001, 0.002, 0.004, 0.008):
        nh.metrics.observe("proposal_commit_latency_seconds", (1, 1), v)
        nh.metrics.observe("proposal_apply_latency_seconds", (1, 1), 2 * v)
    r = bench._latency_report({1: nh})
    assert set(r) >= {
        "commit_latency_p50_s",
        "commit_latency_p99_s",
        "commit_latency_samples",
        "apply_latency_p99_s",
        "fsync_latency_p99_s",
    }
    assert r["commit_latency_samples"] == 4
    assert 0 < r["commit_latency_p50_s"] <= r["commit_latency_p99_s"]
    # schema stability: keys exist even with zero hosts / zero samples
    r0 = bench._latency_report({})
    assert r0["commit_latency_p50_s"] == 0.0
    assert r0["commit_latency_p99_s"] == 0.0
