"""Static guard over the engine's and transport's step hot paths.

The columnar host fan-out replaced per-(group, peer) Python — per-element
`int(arr[g, p])` reads, `.item()` calls and `.tolist()` conversions inside
loops — with whole-column gathers done ONCE per plane outside any loop.
This lint fails if those patterns creep back into the hot functions, which
silently reintroduces O(messages) host work per step (the 340x
kernel-vs-e2e gap this architecture closed).

Rules, applied to each function in HOT_FUNCTIONS (and any loop nested in
them):

  * no `.tolist()` or `.item()` calls inside a for/while body —
    column-level `.tolist()` OUTSIDE loops is the fast idiom and stays
    allowed;
  * no `int(x[...])` scalar conversions of subscripted values inside a
    for/while body (a per-element device-mirror read).

The transport's send path (HOT_LOCK_FUNCTIONS) has its own banned
pattern: no `with <lock>` acquisition inside a for/while body. The bulk
seam exists so one queue lock + one breaker check covers a whole target
batch (_SendQueue.put_many / Transport.send_many); a per-message lock
acquisition silently reintroduces O(messages) synchronization per step.

The observability plane adds a third rule (HOT_TELEMETRY_FUNCTIONS): no
`Histogram.observe(...)` / flight-recorder `.record(...)` call in a hot
function unless it sits under a sampling guard (an `if` whose condition
mentions a sampler/latency gate) — per-message unconditional telemetry
is exactly the O(messages) host work the columnar refactor removed.

Slow paths (catchup, snapshot feedback, reconciles, rebase, `_maintain`)
are intentionally NOT listed: they run on rare lanes and may use
per-element access. A genuinely unavoidable exception inside a hot
function can be whitelisted with a trailing `# hot-path: ok` comment —
none exist today, so think twice.
"""
from __future__ import annotations

import ast
import inspect

import dragonboat_tpu.engine.node as enode
import dragonboat_tpu.engine.vector as vector
import dragonboat_tpu.transport.transport as transport

# the step hot path: every function here runs once per engine step on the
# loop thread (pack -> dispatch -> fetch -> decode/fan-out -> save)
HOT_FUNCTIONS = [
    ("VectorEngine", "_run_once"),
    ("VectorEngine", "_pack"),
    ("VectorEngine", "_pack_wire"),
    ("VectorEngine", "_stage_row"),
    ("VectorEngine", "_flush_staged_rows"),
    ("VectorEngine", "_fetch_output"),
    ("VectorEngine", "_decode"),
    ("VectorEngine", "_dispatch_sends"),
    ("VectorEngine", "_save_updates"),
    ("VectorEngine", "try_local_deliver_many"),
    (None, "gather_replicate_sends"),
    (None, "gather_post_sends"),
    (None, "gather_resp_sends"),
    (None, "build_save_updates"),
]

# the transport send hot path: one lock/breaker-check per TARGET BATCH,
# never per message (the send-queue prioritization must stay amortized)
HOT_LOCK_FUNCTIONS = [
    (transport, "Transport", "send_many"),
    (transport, "_SendQueue", "put_many"),
]

# functions where histogram observation / flight-recorder appends must be
# sampling-guarded: the whole VectorEngine step loop plus the transport's
# bulk send seams INCLUDING the per-message admission helper they call
# (its intentional anomaly-only records carry the whitelist mark)
HOT_TELEMETRY_FUNCTIONS = [
    (vector, cls, fn) for cls, fn in HOT_FUNCTIONS
] + [
    (transport, "Transport", "send_many"),
    (transport, "_SendQueue", "put_many"),
    (transport, "_SendQueue", "_admit_locked"),
]

# functions where causal-trace stamping (mint_trace_id calls, .trace_id
# attribute writes, flight-recorder .record appends) must sit behind the
# sampling guard: the request entry points that mint, and the decode/send
# phases that propagate. Unsampled requests must stay allocation- and
# event-free (ISSUE 4: trace ids ride the sampled LatencyTrace path only).
HOT_TRACE_FUNCTIONS = [
    (enode, "Node", "propose"),
    (enode, "Node", "propose_batch"),
    (enode, "Node", "propose_batch_async"),
    (enode, "Node", "apply_raft_update"),
    (vector, None, "gather_replicate_sends"),
    (vector, None, "gather_resp_sends"),
    (vector, "VectorEngine", "_pack_wire"),
    (vector, "VectorEngine", "_decode"),
    (transport, "Transport", "send_many"),
]

WHITELIST_MARK = "hot-path: ok"


def _resolve(cls_name, fn_name, module=vector):
    obj = module if cls_name is None else getattr(module, cls_name)
    return getattr(obj, fn_name)


def _function_ast(fn):
    src = inspect.getsource(fn)
    # dedent for methods
    import textwrap

    tree = ast.parse(textwrap.dedent(src))
    node = tree.body[0]
    assert isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    return node, inspect.getsourcelines(fn)


def _violations_in(fn_node, src_lines, first_lineno, fn_label):
    out = []

    def line_of(node):
        # node.lineno is relative to the dedented source
        return src_lines[node.lineno - 1]

    def check_loop_body(loop):
        # only the BODY is hot-per-iteration; the iterator expression runs
        # once and is exactly where column-level .tolist() belongs
        for stmt in loop.body + loop.orelse:
            yield from ast.walk(stmt)

    def check_loop(loop):
        for sub in check_loop_body(loop):
            if isinstance(sub, ast.Call):
                # .tolist() / .item() inside a loop body
                if isinstance(sub.func, ast.Attribute) and sub.func.attr in (
                    "tolist",
                    "item",
                ):
                    if WHITELIST_MARK not in line_of(sub):
                        out.append(
                            f"{fn_label}:{first_lineno + sub.lineno - 1}: "
                            f".{sub.func.attr}() inside a hot loop: "
                            f"{line_of(sub).strip()}"
                        )
                # int(x[...]) inside a loop body
                elif (
                    isinstance(sub.func, ast.Name)
                    and sub.func.id == "int"
                    and sub.args
                    and isinstance(sub.args[0], ast.Subscript)
                ):
                    if WHITELIST_MARK not in line_of(sub):
                        out.append(
                            f"{fn_label}:{first_lineno + sub.lineno - 1}: "
                            f"per-element int(x[...]) inside a hot loop: "
                            f"{line_of(sub).strip()}"
                        )

    for node in ast.walk(fn_node):
        if isinstance(node, (ast.For, ast.While)):
            check_loop(node)
    return out


def _lock_violations_in(fn_node, src_lines, first_lineno, fn_label):
    """Flag `with <anything>` inside a for/while body: in the transport's
    bulk send functions every lock acquisition must cover the whole batch,
    so no with-statement belongs inside a per-message loop."""
    out = []
    for node in ast.walk(fn_node):
        if not isinstance(node, (ast.For, ast.While)):
            continue
        for stmt in node.body + node.orelse:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.With):
                    line = src_lines[sub.lineno - 1]
                    if WHITELIST_MARK not in line:
                        out.append(
                            f"{fn_label}:{first_lineno + sub.lineno - 1}: "
                            f"lock acquisition inside a per-message loop: "
                            f"{line.strip()}"
                        )
    return out


_TELEMETRY_CALLS = ("observe", "record")
# identifier fragments that mark a sampling/latency gate in an `if` test
# ("trace": trace-id truthiness gates — nonzero only on sampled requests)
_GUARD_HINTS = ("sampl", "lat", "sstats", "trace")


def _telemetry_violations_in(fn_node, src_lines, first_lineno, fn_label):
    """Flag `.observe(...)` / `.record(...)` calls not nested under an
    `if` whose condition references a sampling gate. Telemetry in a hot
    function must be 1-in-N, never per-call."""
    out = []

    def guarded_by(test_node) -> bool:
        dump = ast.dump(test_node).lower()
        return any(h in dump for h in _GUARD_HINTS)

    def visit(node, guarded):
        if isinstance(node, ast.If):
            g = guarded or guarded_by(node.test)
            for c in node.body:
                visit(c, g)
            for c in node.orelse:
                visit(c, guarded)
            return
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _TELEMETRY_CALLS
            and not guarded
        ):
            line = src_lines[node.lineno - 1]
            if WHITELIST_MARK not in line:
                out.append(
                    f"{fn_label}:{first_lineno + node.lineno - 1}: "
                    f"unguarded .{node.func.attr}() telemetry in a hot "
                    f"function: {line.strip()}"
                )
        for c in ast.iter_child_nodes(node):
            visit(c, guarded)

    visit(fn_node, False)
    return out


def _trace_violations_in(fn_node, src_lines, first_lineno, fn_label):
    """Flag unguarded trace-id stamping in a hot function: mint_trace_id()
    calls, `<x>.trace_id = ...` attribute writes, and flight-recorder
    `.record(...)` appends must all sit under an `if` whose condition
    references a sampling gate (sampler / latency trace / trace-id
    truthiness). Everything else — including passing a zero trace id
    through a constructor — is free and allowed."""
    out = []

    def guarded_by(test_node) -> bool:
        dump = ast.dump(test_node).lower()
        return any(h in dump for h in _GUARD_HINTS)

    def flag(node, what):
        line = src_lines[node.lineno - 1]
        if WHITELIST_MARK not in line:
            out.append(
                f"{fn_label}:{first_lineno + node.lineno - 1}: "
                f"unguarded {what} in a hot function: {line.strip()}"
            )

    def visit(node, guarded):
        if isinstance(node, ast.If):
            g = guarded or guarded_by(node.test)
            for c in node.body:
                visit(c, g)
            for c in node.orelse:
                visit(c, guarded)
            return
        if not guarded:
            if isinstance(node, ast.Call):
                fn = node.func
                name = (
                    fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else ""
                )
                if name == "mint_trace_id":
                    flag(node, "mint_trace_id() call")
                elif name in _TELEMETRY_CALLS and isinstance(
                    fn, ast.Attribute
                ):
                    flag(node, f".{name}() telemetry")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute) and t.attr == "trace_id":
                        flag(node, ".trace_id stamp")
        for c in ast.iter_child_nodes(node):
            visit(c, guarded)

    visit(fn_node, False)
    return out


def test_hot_path_stays_columnar():
    problems = []
    for cls_name, fn_name in HOT_FUNCTIONS:
        label = f"{cls_name + '.' if cls_name else ''}{fn_name}"
        try:
            fn = _resolve(cls_name, fn_name)
        except AttributeError:
            problems.append(
                f"{label}: hot function no longer exists — update the "
                f"HOT_FUNCTIONS list (and keep its replacement columnar)"
            )
            continue
        fn_node, (src_lines, first_lineno) = _function_ast(fn)
        problems.extend(
            _violations_in(fn_node, src_lines, first_lineno, label)
        )
    assert not problems, "\n".join(problems)


def test_transport_send_path_amortizes_locks():
    problems = []
    for module, cls_name, fn_name in HOT_LOCK_FUNCTIONS:
        label = f"{cls_name + '.' if cls_name else ''}{fn_name}"
        try:
            fn = _resolve(cls_name, fn_name, module)
        except AttributeError:
            problems.append(
                f"{label}: hot function no longer exists — update the "
                f"HOT_LOCK_FUNCTIONS list (and keep its replacement "
                f"batch-amortized)"
            )
            continue
        fn_node, (src_lines, first_lineno) = _function_ast(fn)
        problems.extend(
            _lock_violations_in(fn_node, src_lines, first_lineno, label)
        )
    assert not problems, "\n".join(problems)


def test_hot_path_telemetry_is_sampling_guarded():
    problems = []
    for module, cls_name, fn_name in HOT_TELEMETRY_FUNCTIONS:
        label = f"{cls_name + '.' if cls_name else ''}{fn_name}"
        try:
            fn = _resolve(cls_name, fn_name, module)
        except AttributeError:
            problems.append(
                f"{label}: hot function no longer exists — update the "
                f"HOT_TELEMETRY_FUNCTIONS list"
            )
            continue
        fn_node, (src_lines, first_lineno) = _function_ast(fn)
        problems.extend(
            _telemetry_violations_in(fn_node, src_lines, first_lineno, label)
        )
    assert not problems, "\n".join(problems)


def test_trace_stamping_is_sampling_guarded():
    problems = []
    for module, cls_name, fn_name in HOT_TRACE_FUNCTIONS:
        label = f"{cls_name + '.' if cls_name else ''}{fn_name}"
        try:
            fn = _resolve(cls_name, fn_name, module)
        except AttributeError:
            problems.append(
                f"{label}: hot function no longer exists — update the "
                f"HOT_TRACE_FUNCTIONS list"
            )
            continue
        fn_node, (src_lines, first_lineno) = _function_ast(fn)
        problems.extend(
            _trace_violations_in(fn_node, src_lines, first_lineno, label)
        )
    assert not problems, "\n".join(problems)


def test_trace_lint_catches_regressions():
    bad_src = (
        "def f(self, entry):\n"
        "    entry.trace_id = mint_trace_id()\n"  # BANNED x2 (unguarded)
        "    recorder.record('propose_enqueue', trace=entry.trace_id)\n"  # BANNED
        "    if self._req_sampler.sample():\n"
        "        entry.trace_id = mint_trace_id()\n"  # guarded: fine
        "        recorder.record('propose_enqueue')\n"  # guarded: fine
        "    if entry.trace_id:\n"
        "        recorder.record('replicate_send')\n"  # trace-gated: fine
    )
    tree = ast.parse(bad_src)
    lines = bad_src.split("\n")
    got = _trace_violations_in(tree.body[0], lines, 1, "f")
    assert len(got) == 3, got


def test_telemetry_lint_catches_regressions():
    bad_src = (
        "def f(self, msgs):\n"
        "    for m in msgs:\n"
        "        self.metrics.observe('x', (0, 0), 1.0)\n"  # BANNED
        "    recorder.record('evt', a=1)\n"  # BANNED (unguarded)
        "    if self.profiler.sampling:\n"
        "        self.metrics.observe('x', (0, 0), 1.0)\n"  # guarded: fine
        "    if lat_sampler.sample():\n"
        "        recorder.record('evt')\n"  # guarded: fine
    )
    tree = ast.parse(bad_src)
    lines = bad_src.split("\n")
    got = _telemetry_violations_in(tree.body[0], lines, 1, "f")
    assert len(got) == 2, got


def test_bench_json_carries_commit_latency_keys():
    """BENCH JSON schema smoke test: the per-config latency report always
    carries commit_latency_p50_s / commit_latency_p99_s (0.0 when no
    samples landed), and real observations produce real percentiles."""
    import bench
    from dragonboat_tpu.events import MetricsRegistry

    class FakeNH:
        def __init__(self):
            self.metrics = MetricsRegistry()

    nh = FakeNH()
    for v in (0.001, 0.002, 0.004, 0.008):
        nh.metrics.observe("proposal_commit_latency_seconds", (1, 1), v)
        nh.metrics.observe("proposal_apply_latency_seconds", (1, 1), 2 * v)
    r = bench._latency_report({1: nh})
    assert set(r) >= {
        "commit_latency_p50_s",
        "commit_latency_p99_s",
        "commit_latency_samples",
        "apply_latency_p99_s",
        "fsync_latency_p99_s",
    }
    assert r["commit_latency_samples"] == 4
    assert 0 < r["commit_latency_p50_s"] <= r["commit_latency_p99_s"]
    # schema stability: keys exist even with zero hosts / zero samples
    r0 = bench._latency_report({})
    assert r0["commit_latency_p50_s"] == 0.0
    assert r0["commit_latency_p99_s"] == 0.0


def test_lock_lint_catches_regressions():
    bad_src = (
        "def f(self, msgs):\n"
        "    n = 0\n"
        "    for m in msgs:\n"
        "        with self._cv:\n"  # per-message lock: BANNED
        "            n += 1\n"
        "    with self._cv:\n"  # batch-level lock outside the loop: fine
        "        pass\n"
        "    return n\n"
    )
    tree = ast.parse(bad_src)
    lines = bad_src.split("\n")
    got = _lock_violations_in(tree.body[0], lines, 1, "f")
    assert len(got) == 1, got


def test_lint_catches_regressions():
    """The lint itself must flag the banned patterns (meta-test: a broken
    linter silently passing everything is worse than no linter)."""
    bad_src = (
        "def f(o, gs):\n"
        "    for g in gs.tolist():\n"  # iterator tolist: ALLOWED
        "        x = int(o['term'][g])\n"
        "        y = o['match'][g].tolist()\n"
        "        z = o['vote'][g].item()\n"
    )
    tree = ast.parse(bad_src)
    lines = bad_src.split("\n")
    got = _violations_in(tree.body[0], lines, 1, "f")
    assert len(got) == 3, got
