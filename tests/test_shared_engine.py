"""Shared-core engine: several NodeHosts in one process advancing all their
replicas in ONE device state (EngineConfig.share_scope), with co-hosted
message exchange short-circuiting the transport.

This is the TPU-native deployment shape from SURVEY §7 ("co-hosted replica
exchange"): one engine per accelerator host, many NodeHost replicas on it.
The reference has no equivalent — its execengine is per-process
(execengine.go:474-560) and all replica traffic rides the NIC.
"""
from __future__ import annotations

import os
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry

GROUPS = 4
MEMBERS = {1: "shared:1", 2: "shared:2", 3: "shared:3"}


class _CounterSM(IStateMachine):
    def __init__(self, cluster_id, node_id):
        self.n = 0

    def update(self, data):
        self.n += 1
        return Result(value=self.n)

    def lookup(self, q):
        return self.n

    def save_snapshot(self, w, fc, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, fc, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


@pytest.fixture
def hosts(tmp_path):
    reg = _Registry()
    hs = {}
    for nid, addr in MEMBERS.items():
        cfg = NodeHostConfig(
            raft_address=addr,
            rtt_millisecond=10,
            nodehost_dir=str(tmp_path / f"nh{nid}"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(
                kind="vector",
                max_groups=3 * GROUPS,
                max_peers=4,
                log_window=64,
                inbox_depth=4,
                max_entries_per_msg=16,
                share_scope="test-shared",
            ),
        )
        hs[nid] = NodeHost(cfg)
    yield hs
    for nh in hs.values():
        nh.stop()


def _bring_up(hosts):
    for c in range(1, GROUPS + 1):
        for nid in MEMBERS:
            hosts[nid].start_cluster(
                dict(MEMBERS),
                False,
                lambda cid, nid_: _CounterSM(cid, nid_),
                Config(
                    node_id=nid, cluster_id=c, election_rtt=20, heartbeat_rtt=2
                ),
            )
    t0 = time.monotonic()
    leaders = {}
    while len(leaders) < GROUPS and time.monotonic() - t0 < 90:
        snap = hosts[1].engine.leader_snapshot()
        leaders = {c: l for c, (l, _t) in snap.items() if l}
        time.sleep(0.02)
    assert len(leaders) == GROUPS, f"elected {len(leaders)}/{GROUPS}"
    return leaders


def test_shared_core_identity(hosts):
    core = hosts[1].engine.core
    assert hosts[2].engine.core is core
    assert hosts[3].engine.core is core
    # distinct host ids per handle
    assert len({hosts[n].engine.host for n in MEMBERS}) == 3


def test_shared_commit_and_read(hosts):
    leaders = _bring_up(hosts)
    total = 0
    for c in range(1, GROUPS + 1):
        nh = hosts[leaders[c]]
        sess = nh.get_noop_session(c)
        rss = nh.propose_batch(sess, [b"x" * 16] * 32, 10)
        rss[-1].wait(10)
        total += sum(1 for rs in rss if rs.result and rs.result.completed)
    assert total == GROUPS * 32
    # all protocol traffic between the three hosts short-circuited the wire
    for nh in hosts.values():
        assert nh.transport.metrics()["sent"] == 0
    # linearizable read through the shared core
    v = hosts[leaders[1]].sync_read(1, None)
    assert v == 32
    # every replica applied (stale reads on the followers converge)
    deadline = time.monotonic() + 10
    for nid in MEMBERS:
        while (
            hosts[nid].stale_read(1, None) != 32
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert hosts[nid].stale_read(1, None) == 32


def test_shared_release_keeps_core_alive(hosts):
    _bring_up(hosts)
    core = hosts[1].engine.core
    # stopping one host must not stop the shared core
    hosts.pop(1).stop()
    assert not core._stopped.is_set()
    # remaining hosts' lanes are still registered
    assert any(k[0] == hosts[2].engine.host for k in core._lanes)


def test_overlapped_decode_pipeline(tmp_path):
    """Forced overlap_decode (the accelerator default): dispatch step t,
    decode t-1 while the device computes. Commits and reads must flow
    unchanged through the pipelined loop."""
    reg = _Registry()
    hs = {}
    for nid, addr in MEMBERS.items():
        hs[nid] = NodeHost(NodeHostConfig(
            raft_address=addr.replace("shared", "ovl"),
            rtt_millisecond=10,
            nodehost_dir=str(tmp_path / f"ovl{nid}"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(
                kind="vector", max_groups=3 * GROUPS, max_peers=4,
                log_window=64, inbox_depth=4, max_entries_per_msg=16,
                share_scope="test-overlap", overlap_decode=True,
            ),
        ))
    try:
        assert hs[1].engine.core._overlap is True
        for c in range(1, GROUPS + 1):
            for nid in MEMBERS:
                hs[nid].start_cluster(
                    {n: a.replace("shared", "ovl") for n, a in MEMBERS.items()},
                    False,
                    lambda cid, nid_: _CounterSM(cid, nid_),
                    Config(node_id=nid, cluster_id=c, election_rtt=20,
                           heartbeat_rtt=2),
                )
        t0 = time.monotonic()
        leaders = {}
        while len(leaders) < GROUPS and time.monotonic() - t0 < 90:
            snap = hs[1].engine.leader_snapshot()
            leaders = {c: l for c, (l, _t) in snap.items() if l}
            time.sleep(0.02)
        assert len(leaders) == GROUPS
        for c in range(1, GROUPS + 1):
            nh = hs[leaders[c]]
            h = nh.propose_batch_async(
                nh.get_noop_session(c), [b"x"] * 96, 20
            )
            assert h.wait(20) and h.completed == 96, (c, h.completed, h.dropped)
        assert hs[leaders[1]].sync_read(1, None) == 96
    finally:
        for nh in hs.values():
            nh.stop()
