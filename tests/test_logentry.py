"""Tests for the two-tier log (EntryLog/InMemory) and the Peer update
contract, modeled on internal/raft/logentry_etcd_test.go and
inmemory_etcd_test.go scenarios."""
import pytest

from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import (
    EntryLog,
    ErrCompacted,
    InMemLogDB,
    InMemory,
)
from dragonboat_tpu.core.peer import Peer, PeerAddress
from dragonboat_tpu.types import Entry, Membership, Snapshot, State


def ents(*pairs):
    return [Entry(index=i, term=t) for i, t in pairs]


# ------------------------------------------------------------------ InMemory


def test_inmemory_merge_append():
    im = InMemory(0)
    im.merge(ents((1, 1), (2, 1)))
    assert [e.index for e in im.entries] == [1, 2]
    im.merge(ents((3, 1)))
    assert [e.index for e in im.entries] == [1, 2, 3]


def test_inmemory_merge_replace_all():
    im = InMemory(0)
    im.merge(ents((1, 1), (2, 1)))
    im.saved_to = 2
    im.merge(ents((1, 2)))
    assert [e.term for e in im.entries] == [2]
    assert im.marker_index == 1
    assert im.saved_to == 0  # rewound: new entries must be saved again


def test_inmemory_merge_truncate_tail():
    im = InMemory(0)
    im.merge(ents((1, 1), (2, 1), (3, 1)))
    im.saved_to = 3
    im.merge(ents((3, 2), (4, 2)))
    assert [(e.index, e.term) for e in im.entries] == [(1, 1), (2, 1), (3, 2), (4, 2)]
    assert im.saved_to == 2


def test_inmemory_entries_to_save_watermark():
    im = InMemory(0)
    im.merge(ents((1, 1), (2, 1)))
    assert [e.index for e in im.entries_to_save()] == [1, 2]
    im.saved_log_to(2, 1)
    assert im.entries_to_save() == []
    # wrong term: watermark does not advance
    im.merge(ents((3, 2)))
    im.saved_log_to(3, 9)
    assert [e.index for e in im.entries_to_save()] == [3]


def test_inmemory_applied_log_to_shrinks():
    im = InMemory(0)
    im.merge(ents((1, 1), (2, 1), (3, 1)))
    im.applied_log_to(2)
    assert im.marker_index == 2
    assert [e.index for e in im.entries] == [2, 3]


def test_inmemory_restore_snapshot():
    im = InMemory(0)
    im.merge(ents((1, 1)))
    ss = Snapshot(index=10, term=3, membership=Membership())
    im.restore(ss)
    assert im.marker_index == 11
    assert im.entries == []
    assert im.get_term(10) == 3


# ------------------------------------------------------------------ EntryLog


def make_log(db_entries=(), marker=(0, 0)):
    db = InMemLogDB()
    if marker != (0, 0):
        db.apply_snapshot(Snapshot(index=marker[0], term=marker[1]))
    if db_entries:
        db.append(list(db_entries))
    return EntryLog(db), db


def test_entrylog_term_merges_tiers():
    log, db = make_log(ents((1, 1), (2, 2)))
    assert log.term(1) == 1
    assert log.term(2) == 2
    log.append(ents((3, 3)))
    assert log.term(3) == 3
    assert log.last_index() == 3
    assert log.last_term() == 3


def test_entrylog_up_to_date():
    log, _ = make_log(ents((1, 1), (2, 2)))
    assert log.up_to_date(2, 3)  # higher term wins
    assert log.up_to_date(2, 2)  # same term, same index
    assert log.up_to_date(5, 2)  # same term, longer log
    assert not log.up_to_date(1, 2)  # same term, shorter log
    assert not log.up_to_date(5, 1)  # lower term loses regardless of length


def test_entrylog_try_append_conflict():
    log, _ = make_log()
    log.append(ents((1, 1), (2, 1), (3, 1)))
    log.commit_to(1)
    # conflicting suffix from index 2 at term 2
    assert log.try_append(1, ents((2, 2), (3, 2)))
    assert log.term(2) == 2
    assert log.term(3) == 2
    # matching entries: no-op
    assert not log.try_append(1, ents((2, 2)))


def test_entrylog_try_append_conflict_below_committed_panics():
    log, _ = make_log()
    log.append(ents((1, 1), (2, 1)))
    log.commit_to(2)
    with pytest.raises(RuntimeError):
        log.try_append(0, ents((1, 2), (2, 2)))


def test_entrylog_try_commit_current_term_only():
    log, _ = make_log()
    log.append(ents((1, 1), (2, 2)))
    # quorum at index 1 but term 2 is current: old-term entry not committed
    assert not log.try_commit(1, 2)
    assert log.try_commit(2, 2)
    assert log.committed == 2


def test_entrylog_compaction_error():
    log, db = make_log(ents((5, 1), (6, 1)), marker=(4, 1))
    assert log.first_index() == 5
    with pytest.raises(ErrCompacted):
        log.get_entries(3, 7, 1 << 30)
    assert log.term(4) == 1  # marker term accessible


def test_entrylog_commit_beyond_last_panics():
    log, _ = make_log(ents((1, 1)))
    with pytest.raises(RuntimeError):
        log.commit_to(5)


# ------------------------------------------------------------------ Peer


def launch_single():
    db = InMemLogDB()
    cfg = Config(node_id=1, cluster_id=7, election_rtt=10, heartbeat_rtt=2)
    return (
        Peer.launch(
            cfg,
            db,
            addresses=[PeerAddress(node_id=1, address="a1")],
            initial=True,
            new_node=True,
        ),
        db,
    )


def test_peer_bootstrap_writes_config_change_entries():
    p, _ = launch_single()
    r = p.raft
    assert r.log.committed == 1  # one bootstrap entry per member
    assert 1 in r.remotes
    ud = p.get_update(True, 0)
    assert len(ud.entries_to_save) == 1
    assert ud.committed_entries  # bootstrap entry ready to apply
    assert ud.state.term == 1


def drain(p: Peer):
    """Run one get_update/apply/commit round like the engine does."""
    ud = p.get_update(True, p.raft.applied)
    if ud.committed_entries:
        p.notify_raft_last_applied(ud.committed_entries[-1].index)
        ud.last_applied = ud.committed_entries[-1].index
        ud.update_commit.last_applied = ud.last_applied
    p.commit(ud)
    return ud


def test_peer_update_commit_cycle():
    p, _ = launch_single()
    drain(p)  # applies the bootstrap config-change entry
    # elect self
    for _ in range(30):
        p.tick()
    assert p.raft.is_leader()
    p.propose_entries([Entry(cmd=b"job")])
    ud = p.get_update(True, 0)
    assert ud.entries_to_save
    assert ud.update_commit.stable_log_to == ud.entries_to_save[-1].index
    p.commit(ud)
    # after commit, nothing new to save
    ud2 = p.get_update(True, ud.update_commit.processed)
    assert ud2.entries_to_save == []


def test_peer_fast_apply_disabled_when_overlap():
    p, _ = launch_single()
    drain(p)
    for _ in range(30):
        p.tick()
    p.propose_entries([Entry(cmd=b"x")])
    ud = p.get_update(True, 0)
    # committed entries overlap entries_to_save (single node commits its own
    # entries instantly) => fast apply unsafe
    if ud.committed_entries and ud.entries_to_save:
        assert not ud.fast_apply


def test_peer_has_update():
    p, _ = launch_single()
    ud = p.get_update(True, 0)
    p.commit(ud)
    assert not p.has_update(True)
    p.tick()
    p.propose_entries([Entry(cmd=b"y")])  # dropped or appended
    # single node: if not yet leader the proposal is dropped => still update
    assert p.has_update(True) or p.raft.is_leader()
