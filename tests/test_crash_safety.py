"""Crash-safety disciplines (round 3): nodehost dir locks
(cf. internal/server/context.go:72-333) and ref-counted SM offload
(cf. internal/rsm/offload.go:48-133)."""
import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import ErrDirLocked, NodeHost
from dragonboat_tpu.rsm.manager import From, OffloadedStatus
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry


def _cfg(tmp_path, addr="L:1"):
    reg = _Registry()
    return NodeHostConfig(
        deployment_id=88, rtt_millisecond=5, raft_address=addr,
        nodehost_dir=str(tmp_path),
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(max_groups=8, max_peers=4, log_window=64),
    )


def test_second_nodehost_same_dir_fails_fast(tmp_path):
    nh = NodeHost(_cfg(tmp_path))
    try:
        with pytest.raises(ErrDirLocked):
            NodeHost(_cfg(tmp_path))
    finally:
        nh.stop()
    # the lock dies with the holder: reopening after stop works
    nh2 = NodeHost(_cfg(tmp_path))
    nh2.stop()


def test_different_dirs_do_not_conflict(tmp_path):
    nh1 = NodeHost(_cfg(tmp_path / "a", addr="L:1"))
    nh2 = NodeHost(_cfg(tmp_path / "b", addr="L:2"))
    nh1.stop()
    nh2.stop()


def test_offloaded_status_refcounting():
    st = OffloadedStatus()
    st.set_loaded(From.COMMIT_WORKER)
    st.set_loaded(From.SNAPSHOT_WORKER)
    # teardown requested while workers still hold references: no destroy
    assert st.set_offloaded(From.NODEHOST) is False
    assert st.set_offloaded(From.COMMIT_WORKER) is False
    # the LAST release triggers the destroy, exactly once
    assert st.set_offloaded(From.SNAPSHOT_WORKER) is True
    assert st.set_offloaded(From.SNAPSHOT_WORKER) is False
    assert st.set_offloaded(From.NODEHOST) is False


def test_offload_before_teardown_never_destroys():
    st = OffloadedStatus()
    st.set_loaded(From.COMMIT_WORKER)
    assert st.set_offloaded(From.COMMIT_WORKER) is False
    st.set_loaded(From.COMMIT_WORKER)
    assert st.set_offloaded(From.COMMIT_WORKER) is False
    assert st.set_offloaded(From.NODEHOST) is True


class DestroySM(IStateMachine):
    destroyed = 0

    def __init__(self, cluster_id, node_id):
        pass

    def update(self, data):
        return Result(value=1)

    def lookup(self, q):
        return None

    def save_snapshot(self, w, fc, done):
        w.write(b"\x00")

    def recover_from_snapshot(self, r, fc, done):
        pass

    def close(self):
        DestroySM.destroyed += 1


def test_sm_destroyed_exactly_once_on_stop(tmp_path):
    DestroySM.destroyed = 0
    nh = NodeHost(_cfg(tmp_path))
    nh.start_cluster(
        {1: "L:1"}, False, DestroySM,
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
    )
    s = nh.get_noop_session(1)
    nh.sync_propose(s, b"x", 10.0)
    nh.stop()
    # one live SM instance destroyed once (the type-probe instance is
    # closed at start_cluster separately, see nodehost.start_cluster)
    assert DestroySM.destroyed >= 1
