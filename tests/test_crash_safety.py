"""Crash-safety disciplines (round 3): nodehost dir locks
(cf. internal/server/context.go:72-333) and ref-counted SM offload
(cf. internal/rsm/offload.go:48-133)."""
import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import ErrDirLocked, NodeHost
from dragonboat_tpu.rsm.manager import From, OffloadedStatus
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import loopback_factory, _Registry


def _cfg(tmp_path, addr="L:1"):
    reg = _Registry()
    return NodeHostConfig(
        deployment_id=88, rtt_millisecond=5, raft_address=addr,
        nodehost_dir=str(tmp_path),
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(max_groups=8, max_peers=4, log_window=64),
    )


def test_second_nodehost_same_dir_fails_fast(tmp_path):
    nh = NodeHost(_cfg(tmp_path))
    try:
        with pytest.raises(ErrDirLocked):
            NodeHost(_cfg(tmp_path))
    finally:
        nh.stop()
    # the lock dies with the holder: reopening after stop works
    nh2 = NodeHost(_cfg(tmp_path))
    nh2.stop()


def test_different_dirs_do_not_conflict(tmp_path):
    nh1 = NodeHost(_cfg(tmp_path / "a", addr="L:1"))
    nh2 = NodeHost(_cfg(tmp_path / "b", addr="L:2"))
    nh1.stop()
    nh2.stop()


def test_offloaded_status_refcounting():
    st = OffloadedStatus()
    st.set_loaded(From.COMMIT_WORKER)
    st.set_loaded(From.SNAPSHOT_WORKER)
    # teardown requested while workers still hold references: no destroy
    assert st.set_offloaded(From.NODEHOST) is False
    assert st.set_offloaded(From.COMMIT_WORKER) is False
    # the LAST release triggers the destroy, exactly once
    assert st.set_offloaded(From.SNAPSHOT_WORKER) is True
    assert st.set_offloaded(From.SNAPSHOT_WORKER) is False
    assert st.set_offloaded(From.NODEHOST) is False


def test_offload_before_teardown_never_destroys():
    st = OffloadedStatus()
    st.set_loaded(From.COMMIT_WORKER)
    assert st.set_offloaded(From.COMMIT_WORKER) is False
    st.set_loaded(From.COMMIT_WORKER)
    assert st.set_offloaded(From.COMMIT_WORKER) is False
    assert st.set_offloaded(From.NODEHOST) is True


class DestroySM(IStateMachine):
    destroyed = 0

    def __init__(self, cluster_id, node_id):
        pass

    def update(self, data):
        return Result(value=1)

    def lookup(self, q):
        return None

    def save_snapshot(self, w, fc, done):
        w.write(b"\x00")

    def recover_from_snapshot(self, r, fc, done):
        pass

    def close(self):
        DestroySM.destroyed += 1


def test_sm_destroyed_exactly_once_on_stop(tmp_path):
    DestroySM.destroyed = 0
    nh = NodeHost(_cfg(tmp_path))
    nh.start_cluster(
        {1: "L:1"}, False, DestroySM,
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
    )
    s = nh.get_noop_session(1)
    nh.sync_propose(s, b"x", 10.0)
    nh.stop()
    # one live SM instance destroyed once (the type-probe instance is
    # closed at start_cluster separately, see nodehost.start_cluster)
    assert DestroySM.destroyed >= 1


# ---------------------------------------------------------------------------
# restart-while-snapshotting (ISSUE 7 satellite): crash a node MID
# save_snapshot, restart it in process, and the rejoined node must come
# back clean — the half-written snapshot never becomes the recovery
# point, the abandoned save thread cannot corrupt the restarted node,
# and the recorded client history stays linearizable.
# ---------------------------------------------------------------------------
import json
import threading
import time

from dragonboat_tpu.lincheck import HistoryRecorder, check_kv_history
from dragonboat_tpu.requests import RequestError


class SlowSnapSM(IStateMachine):
    """KV SM whose save_snapshot parks on a gate so the test can crash
    the node while the save is provably in flight."""

    gate = threading.Event()
    saving = threading.Event()

    def __init__(self, cluster_id=0, node_id=0):
        self.d = {}

    def update(self, cmd):
        k, v = cmd.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        import zlib

        return zlib.crc32(json.dumps(sorted(self.d.items())).encode())

    def save_snapshot(self, w, files, done):
        SlowSnapSM.saving.set()
        SlowSnapSM.gate.wait(timeout=10.0)  # never hang the suite
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _mk_snap_host(nid, reg, tmp, members):
    cfg = NodeHostConfig(
        deployment_id=88, rtt_millisecond=5, raft_address=f"s{nid}:1",
        nodehost_dir=f"{tmp}/h{nid}",
        raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        engine=EngineConfig(
            kind="vector", max_groups=32, max_peers=4, log_window=64
        ),
    )
    nh = NodeHost(cfg)
    nh.start_cluster(
        members, False, lambda c, n: SlowSnapSM(c, n),
        Config(cluster_id=1, node_id=nid, election_rtt=20, heartbeat_rtt=4),
    )
    return nh


def test_crash_mid_save_snapshot_then_restart_rejoins(tmp_path):
    SlowSnapSM.gate.clear()
    SlowSnapSM.saving.clear()
    reg = _Registry()
    members = {n: f"s{n}:1" for n in (1, 2, 3)}
    hosts = {
        n: _mk_snap_host(n, reg, str(tmp_path), members) for n in (1, 2, 3)
    }
    rec = HistoryRecorder()

    def put(i):
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            for nid, nh in hosts.items():
                try:
                    lid, ok = nh.get_leader_id(1)
                except Exception:
                    continue
                if not ok or lid != nid:
                    continue
                op = rec.invoke(0, ("put", "k", f"v{i}"))
                try:
                    s = nh.get_noop_session(1)
                    nh.sync_propose(s, f"k=v{i}".encode(), 2.0)
                    rec.complete(op, None)
                    return
                except RequestError:
                    rec.unknown(op)
            time.sleep(0.05)
        raise AssertionError(f"put {i} never committed")

    try:
        for i in range(5):
            put(i)
        # park a user snapshot save on the victim, then crash it mid-save
        leader, _ = hosts[1].get_leader_id(1)
        victim = next(n for n in (1, 2, 3) if n != leader)
        hosts[victim].request_snapshot(1, timeout_s=10.0)
        assert SlowSnapSM.saving.wait(timeout=20.0), "save never started"
        hosts[victim].crash_cluster(1)
        for i in range(5, 10):
            put(i)
        # restart with the save STILL parked: the rejoin must not depend
        # on (or be corrupted by) the abandoned save thread
        hosts[victim].restart_cluster(1)
        SlowSnapSM.gate.set()  # release the zombie save
        for i in range(10, 13):
            put(i)
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            idx = {n: hosts[n].get_applied_index(1) for n in (1, 2, 3)}
            if len(set(idx.values())) == 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"rejoiner never converged: {idx}")
        hashes = {hosts[n].get_sm_hash(1) for n in (1, 2, 3)}
        assert len(hashes) == 1, "replica SMs diverged after mid-save crash"
        # the half-written snapshot must never surface as a recovery
        # point: whatever snapshot exists on the victim must be loadable
        node = hosts[victim]._get_node(1)
        ss = node.snapshotter.get_most_recent_snapshot()
        assert ss is None or ss.is_empty() or ss.index >= 0
        assert check_kv_history(rec.history(), max_states=2_000_000)
    finally:
        SlowSnapSM.gate.set()
        for nh in hosts.values():
            nh.stop()
