"""Load-aware placement + live migration (serving/placement.py).

ISSUE 14 tentpole (b): the placement plane folds the saturation score,
the per-lane gauges and the per-tenant serving histograms into a load
model, plans which hot groups leave a saturated host, and executes live
migration = member swap over leadership transfer + the streamed
(resume-capable) snapshot install path — admission-aware, abortable
with the typed retry-hinted ErrMigrationAborted, fully off the engine
step loop.

The e2e here is the ISSUE's acceptance scenario: under seeded
hot-tenant load, a saturated group live-migrates to a cold host with
zero urgent-class sheds, a lincheck-clean client history, and dedup
holding across the move (no op applied twice, no admitted op lost).

Run alone with `-m serving`.
"""
import json
import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.lincheck import HistoryRecorder, check_kv_history
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import ErrMigrationAborted
from dragonboat_tpu.serving import (
    MIGRATION_TENANT,
    MigrationTarget,
    PlacementConfig,
    PlacementPlane,
    SessionManager,
    host_target,
)
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

pytestmark = pytest.mark.serving

CLUSTER = 400
HOSTS = (1, 2, 3)
TARGET_HOST = 4


class CountKV(IStateMachine):
    """KV + per-key apply counts + a global apply sequence — the no-op-
    applied-twice / no-op-lost measuring instrument."""

    def __init__(self, cluster_id=0, node_id=0):
        self.d = {}
        self.counts = {}
        self.seq = 0

    def update(self, cmd: bytes) -> Result:
        k, v = cmd.decode().split("=", 1)
        self.seq += 1
        self.d[k] = v
        self.counts[k] = self.counts.get(k, 0) + 1
        return Result(value=self.seq)

    def lookup(self, q):
        if q == ("counts",):
            return dict(self.counts)
        if q == ("data",):
            return dict(self.d)
        return self.d.get(q)

    def get_hash(self):
        import zlib

        return zlib.crc32(
            json.dumps(sorted(self.d.items())).encode()
        )

    def save_snapshot(self, w, files, done):
        w.write(json.dumps([self.d, self.counts, self.seq]).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d, self.counts, self.seq = json.loads(r.read().decode())


def mk_host(nid, registry, engine_kind="vector", rtt_ms=5):
    return NodeHost(
        NodeHostConfig(
            deployment_id=14,
            rtt_millisecond=rtt_ms,
            raft_address=f"p{nid}:1",
            raft_rpc_factory=lambda listen: loopback_factory(
                listen, registry
            ),
            engine=EngineConfig(
                kind=engine_kind, max_groups=32, max_peers=4, log_window=64
            ),
        )
    )


def group_config(cluster_id, node_id, **kw):
    base = dict(
        cluster_id=cluster_id,
        node_id=node_id,
        election_rtt=10,
        heartbeat_rtt=2,
    )
    base.update(kw)
    return Config(**base)


def wait_for(pred, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def leader_of(hosts, cluster=CLUSTER):
    for n, nh in hosts.items():
        if nh is None or not nh.has_node(cluster):
            continue
        try:
            lid, ok = nh.get_leader_id(cluster)
        except Exception:
            continue
        if ok:
            return lid
    return 0


def host_of_node(hosts, node_id):
    for n, nh in hosts.items():
        if nh is None or not nh.has_node(CLUSTER):
            continue
        try:
            if nh.local_node_id(CLUSTER) == node_id:
                return n
        except Exception:
            continue
    return None


# ---------------------------------------------------------------------------
# load model + planning
# ---------------------------------------------------------------------------


def test_load_model_folds_score_lanes_and_tenants():
    reg = _Registry()
    nh = mk_host(1, reg, "vector")
    try:
        nh.start_cluster(
            {1: "p1:1"}, False, CountKV, group_config(CLUSTER, 1)
        )
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        # real traffic so lanes show ingest and the tenant histogram fills
        assert front.sync_propose(5, CLUSTER, b"a=1", 20.0) is not None
        plane = nh.placement_plane(targets=[])
        m0 = plane.load_model()
        assert CLUSTER in m0["groups"]
        g = m0["groups"][CLUSTER]
        assert set(g) == {"ingest_rate", "commit_gap", "heat"}
        # the tenant's bulk p99 reached the fold
        assert 5 in m0["tenant_p99_s"]
        assert m0["worst_tenant_p99_s"] > 0
        # score rides the front's monitor (override drills included)
        front.monitor.set_override(0.77)
        assert plane.load_model()["score"] == pytest.approx(0.77)
        # a second fold's ingest is a DELTA, not the absolute index
        front.sync_propose(5, CLUSTER, b"a=2", 20.0)
        m1 = plane.load_model()
        assert m1["groups"][CLUSTER]["ingest_rate"] >= 0
    finally:
        nh.stop()


def test_plan_triggers_on_saturation_and_respects_headroom():
    reg = _Registry()
    nh = mk_host(1, reg, "vector")
    try:
        nh.start_cluster(
            {1: "p1:1"}, False, CountKV, group_config(CLUSTER, 1)
        )
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        cold = MigrationTarget(
            address="cold:1",
            start_replica=lambda c, n: None,
            applied_index=lambda c: 0,
            load=lambda: 0.0,
        )
        hot = MigrationTarget(
            address="hot:1",
            start_replica=lambda c, n: None,
            applied_index=lambda c: 0,
            load=lambda: 0.9,
        )
        plane = nh.placement_plane(targets=[hot, cold])
        # below the trigger: no plans
        front.monitor.set_override(0.1)
        assert plane.plan() == []
        # above it: ONE plan, routed to the COLD target, fresh node id
        front.monitor.set_override(0.8)
        plans = plane.plan()
        assert len(plans) == 1
        p = plans[0]
        assert p.cluster_id == CLUSTER
        assert p.target is cold  # the hot target has no headroom
        assert p.local_node_id == 1
        assert p.new_node_id == 2  # past the membership's max id
        assert "score=0.80" in p.reason
    finally:
        nh.stop()


def test_abort_is_typed_and_retry_hinted():
    reg = _Registry()
    nh = mk_host(1, reg, "scalar")
    try:
        nh.start_cluster(
            {1: "p1:1"}, False, CountKV, group_config(CLUSTER, 1)
        )
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        front.monitor.set_override(0.8)
        target = MigrationTarget(
            address="t:1",
            start_replica=lambda c, n: None,
            applied_index=lambda c: 0,
        )
        plane = nh.placement_plane(targets=[target])
        plane.abort()
        plans = plane.plan(force=True)
        assert plans
        with pytest.raises(ErrMigrationAborted) as ei:
            plane.execute(plans[0])
        assert ei.value.retry_after_s > 0
        assert "operator abort" in ei.value.reason
        c = plane.counters()
        assert c["migrations_started"] == 1
        assert c["migrations_aborted"] == 1
        assert c["migrations_completed"] == 0
        assert not nh.is_migrating(CLUSTER)  # tag cleaned up on abort
        # resume() re-arms the plane
        plane.resume()
        assert plane.plan(force=True)
    finally:
        nh.stop()


def test_admission_shed_aborts_migration_with_hint():
    """Migration traffic rides the BULK class of the reserved tenant:
    past the hard shed line it is refused like any bulk op, and the
    migration aborts with the shed's own retry hint — urgent traffic
    never had a competitor."""
    reg = _Registry()
    nh = mk_host(1, reg, "scalar")
    try:
        nh.start_cluster(
            {1: "p1:1"}, False, CountKV, group_config(CLUSTER, 1)
        )
        assert wait_for(lambda: nh.get_leader_id(CLUSTER)[1])
        front = nh.serving_front()
        front.monitor.set_override(0.95)  # past shed_bulk_at
        target = MigrationTarget(
            address="t:1",
            start_replica=lambda c, n: None,
            applied_index=lambda c: 0,
        )
        plane = nh.placement_plane(targets=[target])
        plans = plane.plan(force=True)
        assert plans
        with pytest.raises(ErrMigrationAborted) as ei:
            plane.execute(plans[0])
        assert "admission shed" in ei.value.reason
        assert ei.value.retry_after_s > 0
        # the shed landed on the migration tenant's bulk ledger
        c = front.admission.counters()[MIGRATION_TENANT]
        assert c["shed"]["bulk"] >= 1
        # urgent admission was never involved
        assert c["shed"]["urgent"] == 0
    finally:
        nh.stop()


# ---------------------------------------------------------------------------
# the acceptance e2e: live migration under seeded hot-tenant load
# ---------------------------------------------------------------------------


def test_live_migration_under_hot_tenant_load():
    """Under hot-tenant load against a (score-forced) saturated host,
    the plane live-migrates the group to the cold target host via
    add-member -> streamed snapshot catch-up -> leadership transfer ->
    member removal, with:

      * zero urgent-class sheds anywhere (the no-starvation verdict),
      * a linearizable client history across the move,
      * dedup holding: the session-lane op applies exactly once even
        when retried across the migration, and no admitted op is lost,
      * the install stream counted as a MIGRATION stream on the target
        (transport/chunks tagging).
    """
    reg = _Registry()
    hosts = {
        n: mk_host(n, reg, "vector") for n in HOSTS + (TARGET_HOST,)
    }
    members = {n: f"p{n}:1" for n in HOSTS}
    rec = HistoryRecorder()
    stop = threading.Event()
    seq = [0]
    seq_mu = threading.Lock()

    def sm_factory(c, n):
        return CountKV(c, n)

    def client_main(client_id):
        import random

        rng = random.Random(1000 + client_id)
        while not stop.is_set():
            lid = leader_of(hosts)
            hn = host_of_node(hosts, lid)
            if hn is None:
                time.sleep(0.05)
                continue
            front = hosts[hn].serving_front()
            key = f"k{rng.randrange(3)}"
            if rng.random() < 0.7:
                with seq_mu:
                    seq[0] += 1
                    val = f"v{seq[0]}"
                op = rec.invoke(client_id, ("put", key, val))
                try:
                    front.sync_propose(
                        9, CLUSTER, f"{key}={val}".encode(), 5.0
                    )
                    rec.complete(op, None)
                except Exception:
                    rec.unknown(op)
            else:
                # urgent linearizable reads ride THROUGH the migration:
                # the history's lost-write detector AND the traffic the
                # zero-urgent-shed verdict protects
                op = rec.invoke(client_id, ("get", key))
                try:
                    v = front.sync_read(9, CLUSTER, key, 5.0)
                    rec.complete(op, v)
                except Exception:
                    rec.fail(op)  # reads have no side effect
            time.sleep(rng.random() * 0.01)

    try:
        for n in HOSTS:
            hosts[n].start_cluster(
                members, False, sm_factory,
                group_config(
                    CLUSTER, n, snapshot_entries=20, compaction_overhead=5
                ),
            )
        assert wait_for(lambda: leader_of(hosts) != 0)
        lid = leader_of(hosts)
        src = host_of_node(hosts, lid)
        src_nh = hosts[src]
        front = src_nh.serving_front()
        # --- session lane: register + one unacknowledged apply (the
        # dedup-across-the-move probe)
        mgr = SessionManager(front)
        assert mgr.register(7, CLUSTER, count=1, timeout_s=30.0) == 1
        with mgr.checkout(7, CLUSTER) as sess:
            t = front.propose_session(7, CLUSTER, sess, b"dedup=1", 30.0)
            r = t.wait()
            assert r.completed
            first_val = r.result.value
            # --- hot-tenant load + compaction past the joiner's index
            clients = [
                threading.Thread(target=client_main, args=(i,), daemon=True)
                for i in range(2)
            ]
            for c in clients:
                c.start()
            # let the log grow past snapshot_entries, then compact
            deadline = time.monotonic() + 30
            while (
                src_nh.get_applied_index(CLUSTER) < 30
                and time.monotonic() < deadline
            ):
                time.sleep(0.1)
            try:
                src_nh.sync_request_snapshot(CLUSTER, timeout_s=20.0)
            except Exception:
                pass  # periodic snapshot may already cover it
            # --- placement: source is "saturated", target is cold
            front.monitor.set_override(0.8)
            target = host_target(
                hosts[TARGET_HOST], sm_factory,
                lambda c, n: group_config(c, n),
            )
            plane = src_nh.placement_plane(
                targets=[target],
                config=PlacementConfig(
                    rebalance_at=0.6,
                    catchup_timeout_s=90.0,
                    transfer_timeout_s=60.0,
                ),
            )
            done = plane.rebalance_once()
            assert len(done) == 1, "migration did not complete"
            stop.set()
            for c in clients:
                c.join(timeout=5)
            # --- the swap really happened (membership is applied state:
            # the freshly-joined member's SM view converges, not flips)
            assert not src_nh.has_node(CLUSTER)
            assert hosts[TARGET_HOST].has_node(CLUSTER)

            def swapped():
                # the LEADER's applied membership is the authoritative
                # post-swap view (the fresh joiner's SM may still be
                # replaying the config-change entries)
                cur = leader_of(hosts)
                hn = host_of_node(hosts, cur)
                if hn is None:
                    return False
                try:
                    m = hosts[hn].get_cluster_membership(CLUSTER)
                except Exception:
                    return False
                return (
                    done[0].new_node_id in m.addresses
                    and lid not in m.addresses
                )

            assert wait_for(swapped, timeout=30), "membership never swapped"
            c = plane.counters()
            assert c["migrations_completed"] == 1
            assert c["migrations_aborted"] == 0
            # the install stream was tagged migration on the target
            assert (
                hosts[TARGET_HOST]._chunks.stats()["migration_streams"] >= 1
            ), hosts[TARGET_HOST]._chunks.stats()
            # migration tags are cleaned up
            assert not src_nh.is_migrating(CLUSTER)
            assert not hosts[TARGET_HOST].is_migrating(CLUSTER)
            # --- zero urgent sheds anywhere
            for nh in hosts.values():
                f = getattr(nh, "_serving", None)
                if f is None:
                    continue
                for tid, counters in f.admission.counters().items():
                    assert counters["shed"]["urgent"] == 0, (
                        tid, counters,
                    )
            # --- dedup holds ACROSS the move: retry the unacknowledged
            # series through the migrated topology
            new_lid = leader_of(hosts)
            new_hn = host_of_node(hosts, new_lid)
            mgr2 = SessionManager(hosts[new_hn].serving_front())
            mgr2.adopt(7, CLUSTER, sess)
            t2 = hosts[new_hn].serving_front().propose_session(
                7, CLUSTER, sess, b"dedup=1", 30.0
            )
            r2 = t2.wait()
            assert r2.completed
            assert r2.result.value == first_val, "retry re-applied"
        # --- convergence + no-op-applied-twice / no-op-lost
        live = [
            nh for nh in hosts.values() if nh.has_node(CLUSTER)
        ]
        assert len(live) == 3
        # one final write forces commit-index convergence across the
        # post-swap membership (the longhaul _verify idiom)
        final_deadline = time.monotonic() + 30
        while time.monotonic() < final_deadline:
            cur = leader_of(hosts)
            hn = host_of_node(hosts, cur)
            if hn is None:
                time.sleep(0.2)
                continue
            try:
                hosts[hn].sync_propose(
                    hosts[hn].get_noop_session(CLUSTER), b"final=done", 5.0
                )
                break
            except Exception:
                time.sleep(0.2)
        assert wait_for(
            lambda: len(
                {nh.get_applied_index(CLUSTER) for nh in live}
            ) == 1,
            timeout=60,
        ), "applied index never converged after the move"
        counts = live[0].stale_read(CLUSTER, ("counts",))
        assert counts.get("dedup") == 1, counts
        # every COMPLETED put applied (no admitted op lost) and nothing
        # applied more often than the client asked (the only slack is
        # ops whose outcome the client never learned)
        history = rec.history()
        puts = [
            o for o in history
            if isinstance(o.input, tuple) and o.input[0] == "put"
        ]
        n_completed = sum(1 for o in puts if o.completed)
        n_unknown = len(puts) - n_completed
        total_applied = sum(
            v for k, v in counts.items() if k.startswith("k")
        )
        assert n_completed <= total_applied <= n_completed + n_unknown, (
            n_completed, total_applied, n_unknown,
        )
        # mixed put/get history stays linearizable ACROSS the move
        assert check_kv_history(history, max_states=5_000_000), (
            "client history not linearizable across the migration"
        )
    finally:
        stop.set()
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass
