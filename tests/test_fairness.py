"""Tick-fairness watchdog tests: starvation gauge semantics, the enforced
yield when a long iteration starved a co-scheduled peer loop, the tick
burst clamp that keeps randomized election timers spread through a stall,
and the NodeHost gauge export (ISSUE 2 tentpole, ROADMAP seed flake)."""
import time

import numpy as np

from dragonboat_tpu.engine.fairness import FairnessWatchdog, peer_count


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_starvation_gauge_tracks_inter_iteration_gap():
    clock = FakeClock()
    wd = FairnessWatchdog("a", tick_period_s=0.005, clock=clock)
    try:
        t0 = wd.iter_begin()
        clock.t += 0.004
        wd.iter_end(t0)
        assert wd.stats()["starvation_ratio"] < 1.0
        # a 2-second stall: the gauge spikes to gap / tick_period
        t0 = wd.iter_begin()
        clock.t += 2.0
        wd.iter_end(t0)
        s = wd.stats()
        assert s["max_gap_s"] >= 2.0
        assert s["starvation_ratio"] >= 2.0 / 0.005 - 1
        # the windowed max keeps the stall visible on later fast iters
        for _ in range(10):
            t0 = wd.iter_begin()
            clock.t += 0.001
            wd.iter_end(t0)
        assert wd.stats()["starvation_ratio"] > 100
    finally:
        wd.close()


def test_yield_enforced_only_when_a_peer_starved():
    clock = FakeClock()
    a = FairnessWatchdog("a", 0.005, yield_s=1e-4, clock=clock)
    b = FairnessWatchdog("b", 0.005, yield_s=1e-4, clock=clock)
    try:
        # b keeps up: its beat is fresher than a's iteration start
        t0 = a.iter_begin()
        clock.t += 0.5  # long step for a...
        b.iter_end(b.iter_begin())  # ...but b ran meanwhile
        assert not a.iter_end(t0)
        assert a.stats()["fairness_yields"] == 0
        # b starves: no beat since before a's long iteration began
        clock.t += 0.001
        t0 = a.iter_begin()
        clock.t += 0.5
        assert a.iter_end(t0)  # yield enforced
        assert a.stats()["fairness_yields"] == 1
    finally:
        a.close()
        b.close()


def test_no_yield_without_peers_or_below_threshold():
    clock = FakeClock()
    a = FairnessWatchdog("solo", 0.005, yield_s=1e-4, clock=clock)
    try:
        t0 = a.iter_begin()
        clock.t += 5.0
        assert not a.iter_end(t0)  # nobody to be fair to
    finally:
        a.close()
    clock2 = FakeClock()
    c = FairnessWatchdog("c", 0.005, yield_s=1e-4, clock=clock2)
    d = FairnessWatchdog("d", 0.005, yield_s=1e-4, clock=clock2)
    try:
        t0 = c.iter_begin()
        clock2.t += 0.001  # fast iteration: below the yield threshold
        assert not c.iter_end(t0)
    finally:
        c.close()
        d.close()


def test_closed_watchdog_leaves_registry():
    n0 = peer_count()
    wd = FairnessWatchdog("tmp", 0.005)
    assert peer_count() == n0 + 1
    wd.close()
    assert peer_count() == n0
    wd.close()  # idempotent


def test_tick_burst_clamp_preserves_election_spread():
    """The engine-level invariant behind the seed-flake fix: the per-lane
    tick replay cap must stay BELOW the election RTT, so a coalesced
    backlog cannot cross rand_timeout ∈ [et, 2et) for every lane in the
    same step."""
    from dragonboat_tpu.config import Config, NodeHostConfig, EngineConfig
    from dragonboat_tpu.engine.vector import VectorEngine
    from dragonboat_tpu.storage.logdb import ShardedLogDB

    cfg = NodeHostConfig(
        rtt_millisecond=5,
        raft_address="wd:1",
        engine=EngineConfig(max_groups=8, max_peers=4, log_window=32),
    )
    eng = VectorEngine(ShardedLogDB(), nh_config=cfg)
    try:
        # simulate what _compute_activation writes for a lane with the
        # default test timings (election_rtt=20, heartbeat_rtt=4)
        assert eng._catchup_tick_cap == 0  # auto
        # auto clamp = heartbeat RTT, far below the election RTT
        g = 0
        hb, et = 4, 20
        burst = eng._catchup_tick_cap or hb
        eng._m_tick_cap[g] = max(1, min(et, burst))
        assert int(eng._m_tick_cap[g]) == 4
        # a 2-second stall backlog (400 ticks at 5ms) replays at <= 4 per
        # step: reaching even the minimum rand_timeout takes >= 5 steps,
        # so per-lane randomization (spread over [et, 2et)) still
        # staggers campaigns across steps
        backlog = 400
        per_step = int(np.minimum(eng._m_tick_cap[g], backlog))
        assert per_step * 2 < et  # two post-stall steps cannot expire it
        assert eng.fairness_stats()["tick_period_s"] == 0.005
    finally:
        eng.stop()


def test_nodehost_exports_starvation_gauges():
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    class SM(IStateMachine):
        def update(self, data):
            return Result(value=1)

        def lookup(self, q):
            return None

        def save_snapshot(self, w, files, done):
            w.write(b"{}")

        def recover_from_snapshot(self, r, files, done):
            pass

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=5,
            raft_address="wdx:1",
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector", max_groups=8, max_peers=4, log_window=32
            ),
        )
    )
    try:
        nh.start_cluster(
            {1: "wdx:1"},
            False,
            lambda c, n: SM(),
            Config(cluster_id=7, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.monotonic() + 5
        key = (0, 0)
        while time.monotonic() < deadline:
            if nh.metrics.gauge_value(
                "engine_tick_starvation_ratio", key
            ) is not None:
                break
            time.sleep(0.05)
        assert nh.metrics.gauge_value(
            "engine_tick_starvation_ratio", key
        ) is not None
        assert nh.metrics.gauge_value("transport_breakers_open", key) == 0.0
        # the Prometheus exposition carries them too
        import io

        buf = io.StringIO()
        nh.write_health_metrics(buf)
        text = buf.getvalue()
        assert "engine_tick_starvation_ratio" in text
    finally:
        nh.stop()


def test_clock_anomaly_discards_phantom_gap_but_keeps_lifetime_max():
    """A tick-plane clock anomaly (step-jump/backward read) mints a
    PHANTOM gap in the stall gauge; note_clock_anomaly must discard the
    window (the fault is a lying clock, not a starved loop — chaos runs'
    fairness_no_stall verdict must not trip on it) while the lifetime
    max and the anomaly counter stay honest."""
    clock = FakeClock()
    wd = FairnessWatchdog("a", tick_period_s=0.005, clock=clock)
    try:
        t0 = wd.iter_begin()
        clock.t += 5.0  # the jumped clock mints a 1000-period gap
        wd.iter_end(t0)
        assert wd.stats()["starvation_ratio"] > 100
        wd.note_clock_anomaly()
        s = wd.stats()
        assert s["clock_anomalies"] == 1
        assert s["recent_max_gap_s"] == 0.0  # phantom gap discarded
        assert s["starvation_ratio"] == 0.0
        assert s["max_gap_s"] >= 5.0  # lifetime max stays honest
        # the re-anchored beat measures fresh gaps normally afterwards
        t0 = wd.iter_begin()
        clock.t += 0.004
        wd.iter_end(t0)
        assert 0 < wd.stats()["starvation_ratio"] < 1.0
    finally:
        wd.close()
