"""C++ state machine SDK tests: plugin load, update/lookup/hash, snapshot
round-trip across the ABI, and a full cluster run with snapshot-based
catch-up (mirrors internal/cpp/wrapper_test.go coverage)."""
import io
import os
import subprocess
import threading
import time

import pytest

_SO = os.path.join(os.path.dirname(__file__), "..", "native", "build",
                   "libkvstore_sm.so")


def _built() -> bool:
    import shutil

    if os.path.exists(_SO):
        return True
    if shutil.which("g++") is None:
        return False  # genuinely no toolchain: skip
    proc = subprocess.run(
        ["make", "-C", os.path.join(os.path.dirname(__file__), "..", "native")],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    return os.path.exists(_SO)


pytestmark = pytest.mark.skipif(not _built(), reason="native toolchain unavailable")


class _Abort:
    def check(self):
        pass


def _factory():
    from dragonboat_tpu.cpp_sm import CppStateMachineFactory

    return CppStateMachineFactory(os.path.abspath(_SO))


def test_update_lookup_hash():
    sm = _factory()(1, 1)
    assert sm.update(b"a=1").value == 1
    assert sm.update(b"b=2").value == 2
    assert sm.update(b"a=3").value == 2  # overwrite, size unchanged
    assert sm.lookup(b"a") == b"3"
    assert sm.lookup(b"missing") is None
    h1 = sm.get_hash()
    sm.update(b"c=4")
    assert sm.get_hash() != h1
    sm.close()


def test_hash_is_content_deterministic():
    f = _factory()
    a, b = f(1, 1), f(1, 2)
    for cmd in (b"x=1", b"y=2"):
        a.update(cmd)
    for cmd in (b"y=2", b"x=1"):  # different order, same content
        b.update(cmd)
    assert a.get_hash() == b.get_hash()
    a.close()
    b.close()


def test_snapshot_roundtrip_across_abi():
    f = _factory()
    src = f(1, 1)
    for i in range(100):
        src.update(f"key{i:03d}=value{i}".encode())
    buf = io.BytesIO()
    src.save_snapshot(buf, None, _Abort())
    assert buf.tell() > 0

    dst = f(1, 2)
    dst.update(b"junk=state")  # must be cleared by recover
    buf.seek(0)
    dst.recover_from_snapshot(buf, None, _Abort())
    assert dst.lookup(b"key042") == b"value42"
    assert dst.lookup(b"junk") is None
    assert dst.get_hash() == src.get_hash()
    src.close()
    dst.close()


def test_writer_error_propagates():
    f = _factory()
    sm = f(1, 1)
    sm.update(b"k=v")

    class Boom(io.RawIOBase):
        def write(self, data):
            raise OSError("disk gone")

    with pytest.raises(OSError, match="disk gone"):
        sm.save_snapshot(Boom(), None, _Abort())
    sm.close()


@pytest.mark.slow
def test_cpp_sm_cluster_end_to_end(tmp_path):
    """3-host cluster running the C++ KV plugin: propose, linearizable
    read, cross-replica hash equality, restart + replay."""
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    factory = _factory()
    reg = _Registry()
    hosts = {}

    def mk(nid, restart=False):
        cfg = NodeHostConfig(
            deployment_id=31, rtt_millisecond=5,
            nodehost_dir=f"{tmp_path}/h{nid}", raft_address=f"q{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        )
        nh = NodeHost(cfg)
        nh.start_cluster(
            {} if restart else {1: "q1:1", 2: "q2:1", 3: "q3:1"},
            False, factory,
            Config(cluster_id=1, node_id=nid, election_rtt=20,
                   heartbeat_rtt=2, snapshot_entries=30,
                   compaction_overhead=5),
        )
        return nh

    for nid in (1, 2, 3):
        hosts[nid] = mk(nid)

    leader = None
    # generous: the first user of this engine shape pays the jit compile
    deadline = time.time() + 60
    while time.time() < deadline and leader is None:
        for nid, nh in hosts.items():
            lid, ok = nh.get_leader_id(1)
            if ok and lid == nid:
                leader = nid
        time.sleep(0.02)
    assert leader

    s = hosts[leader].get_noop_session(1)
    for i in range(60):  # crosses the snapshot_entries=30 threshold
        hosts[leader].sync_propose(s, f"k{i}=v{i}".encode(), timeout_s=5.0)
    assert hosts[leader].sync_read(1, b"k59", timeout_s=5.0) == b"v59"

    deadline = time.time() + 20
    while time.time() < deadline:
        hashes = {n: hosts[n].get_sm_hash(1) for n in hosts}
        if len(set(hashes.values())) == 1:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"C++ SM replicas diverged: {hashes}")

    # restart one host: C++ SM state rebuilt from snapshot + log replay
    victim = [n for n in hosts if n != leader][0]
    hosts[victim].stop()
    hosts[victim] = mk(victim, restart=True)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if hosts[victim].stale_read(1, b"k59") == b"v59":
                break
        except Exception:
            pass
        time.sleep(0.05)
    else:
        raise AssertionError("restarted C++ SM host did not recover")

    for nh in hosts.values():
        nh.stop()
