"""C++ state machine SDK tests: plugin load, update/lookup/hash, snapshot
round-trip across the ABI, and a full cluster run with snapshot-based
catch-up (mirrors internal/cpp/wrapper_test.go coverage)."""
import io
import os
import subprocess
import threading
import time

import pytest

_BUILD = os.path.join(os.path.dirname(__file__), "..", "native", "build")
_SO = os.path.join(_BUILD, "libkvstore_sm.so")
_SO_CONCURRENT = os.path.join(_BUILD, "libconcurrent_sm.so")
_SO_ONDISK = os.path.join(_BUILD, "libdiskkv_sm.so")


def _built() -> bool:
    import shutil

    if all(os.path.exists(p) for p in (_SO, _SO_CONCURRENT, _SO_ONDISK)):
        return True
    if shutil.which("g++") is None:
        return False  # genuinely no toolchain: skip
    proc = subprocess.run(
        ["make", "-C", os.path.join(os.path.dirname(__file__), "..", "native")],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    return os.path.exists(_SO)


pytestmark = pytest.mark.skipif(not _built(), reason="native toolchain unavailable")


class _Abort:
    def check(self):
        pass


def _propose_retry(hosts, leader, cluster_id, cmd, attempts=4):
    """Propose with leader re-resolution on timeout: on a 1-cpu box an
    election can churn between the leader probe and the propose, and a
    proposal handed to a just-deposed leader times out — real clients
    (and the reference's tests) retry against the new leader. Returns
    (result, leader)."""
    from dragonboat_tpu.requests import ErrTimeout

    last = None
    for _ in range(attempts):
        try:
            s = hosts[leader].get_noop_session(cluster_id)
            return hosts[leader].sync_propose(s, cmd, timeout_s=5.0), leader
        except ErrTimeout as e:
            last = e
            for nid, nh in hosts.items():
                lid, ok = nh.get_leader_id(cluster_id)
                if ok and lid in hosts:
                    leader = lid
                    break
    raise last


def _factory(so=_SO):
    from dragonboat_tpu.cpp_sm import CppStateMachineFactory

    return CppStateMachineFactory(os.path.abspath(so))


def test_update_lookup_hash():
    sm = _factory()(1, 1)
    assert sm.update(b"a=1").value == 1
    assert sm.update(b"b=2").value == 2
    assert sm.update(b"a=3").value == 2  # overwrite, size unchanged
    assert sm.lookup(b"a") == b"3"
    assert sm.lookup(b"missing") is None
    h1 = sm.get_hash()
    sm.update(b"c=4")
    assert sm.get_hash() != h1
    sm.close()


def test_hash_is_content_deterministic():
    f = _factory()
    a, b = f(1, 1), f(1, 2)
    for cmd in (b"x=1", b"y=2"):
        a.update(cmd)
    for cmd in (b"y=2", b"x=1"):  # different order, same content
        b.update(cmd)
    assert a.get_hash() == b.get_hash()
    a.close()
    b.close()


def test_snapshot_roundtrip_across_abi():
    f = _factory()
    src = f(1, 1)
    for i in range(100):
        src.update(f"key{i:03d}=value{i}".encode())
    buf = io.BytesIO()
    src.save_snapshot(buf, None, _Abort())
    assert buf.tell() > 0

    dst = f(1, 2)
    dst.update(b"junk=state")  # must be cleared by recover
    buf.seek(0)
    dst.recover_from_snapshot(buf, None, _Abort())
    assert dst.lookup(b"key042") == b"value42"
    assert dst.lookup(b"junk") is None
    assert dst.get_hash() == src.get_hash()
    src.close()
    dst.close()


def test_writer_error_propagates():
    f = _factory()
    sm = f(1, 1)
    sm.update(b"k=v")

    class Boom(io.RawIOBase):
        def write(self, data):
            raise OSError("disk gone")

    with pytest.raises(OSError, match="disk gone"):
        sm.save_snapshot(Boom(), None, _Abort())
    sm.close()


def test_concurrent_plugin_detected_and_batched():
    """The concurrent plugin exports dbtpu_sm_type()=CONCURRENT; the loader
    returns an IConcurrentStateMachine whose update takes SMEntry batches
    (cf. reference concurrent.h BatchedUpdate)."""
    from dragonboat_tpu.statemachine import (
        SM_TYPE_CONCURRENT,
        IConcurrentStateMachine,
        SMEntry,
    )

    f = _factory(_SO_CONCURRENT)
    assert f.sm_type == SM_TYPE_CONCURRENT
    sm = f(1, 1)
    assert isinstance(sm, IConcurrentStateMachine)
    ents = [
        SMEntry(index=1, cmd=b"a=1"),
        SMEntry(index=2, cmd=b"b=2"),
        SMEntry(index=3, cmd=b"bad"),
    ]
    sm.update(ents)
    assert [e.result.value for e in ents] == [1, 2, 0]
    assert sm.lookup(b"b") == b"2"
    sm.close()


def test_concurrent_plugin_snapshot_is_point_in_time():
    """prepare_snapshot captures the state; updates applied between prepare
    and save must not leak into the image."""
    from dragonboat_tpu.statemachine import SMEntry

    f = _factory(_SO_CONCURRENT)
    src = f(1, 1)
    src.update([SMEntry(index=1, cmd=b"k=old")])
    ctx = src.prepare_snapshot()
    src.update([SMEntry(index=2, cmd=b"k=new"),
                SMEntry(index=3, cmd=b"late=1")])
    buf = io.BytesIO()
    src.save_snapshot(ctx, buf, None, _Abort())

    dst = f(1, 2)
    buf.seek(0)
    dst.recover_from_snapshot(buf, None, _Abort())
    assert dst.lookup(b"k") == b"old"
    assert dst.lookup(b"late") is None
    src.close()
    dst.close()


def test_ondisk_plugin_open_replays_and_survives_restart(tmp_path):
    """The on-disk plugin persists applies under DBTPU_DISKKV_DIR; a fresh
    instance's open() replays them and reports the last applied index
    (cf. reference ondisk.h Open contract)."""
    from dragonboat_tpu.statemachine import (
        SM_TYPE_ONDISK,
        AbortSignal,
        IOnDiskStateMachine,
        SMEntry,
    )

    os.environ["DBTPU_DISKKV_DIR"] = str(tmp_path)
    try:
        f = _factory(_SO_ONDISK)
        assert f.sm_type == SM_TYPE_ONDISK
        sm = f(7, 1)
        assert isinstance(sm, IOnDiskStateMachine)
        assert sm.open(AbortSignal()) == 0
        sm.update([SMEntry(index=i, cmd=f"k{i}=v{i}".encode())
                   for i in range(1, 11)])
        sm.sync()
        h = sm.get_hash()
        sm.close()

        again = f(7, 1)
        assert again.open(AbortSignal()) == 10
        assert again.lookup(b"k10") == b"v10"
        assert again.get_hash() == h
        again.close()
    finally:
        del os.environ["DBTPU_DISKKV_DIR"]


def test_ondisk_plugin_snapshot_roundtrip(tmp_path):
    from dragonboat_tpu.statemachine import AbortSignal, SMEntry

    os.environ["DBTPU_DISKKV_DIR"] = str(tmp_path)
    try:
        f = _factory(_SO_ONDISK)
        src = f(8, 1)
        src.open(AbortSignal())
        src.update([SMEntry(index=i, cmd=f"k{i}=v{i}".encode())
                    for i in range(1, 6)])
        ctx = src.prepare_snapshot()
        src.update([SMEntry(index=6, cmd=b"k1=mutated")])
        buf = io.BytesIO()
        src.save_snapshot(ctx, buf, _Abort())

        dst = f(8, 2)
        dst.open(AbortSignal())
        buf.seek(0)
        dst.recover_from_snapshot(buf, _Abort())
        assert dst.lookup(b"k1") == b"v1"  # point-in-time, pre-mutation
        # the install rebuilt dst's local log: a restart must see it
        dst.sync()
        dst.close()
        back = f(8, 2)
        assert back.open(AbortSignal()) == 5
        assert back.lookup(b"k3") == b"v3"
        back.close()
        src.close()
    finally:
        del os.environ["DBTPU_DISKKV_DIR"]


@pytest.mark.slow
def test_ondisk_cluster_restart_resumes_from_applied(tmp_path):
    """3-host cluster on the C++ on-disk plugin: propose, restart one host,
    its SM reopens at the persisted applied index and serves reads."""
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    os.environ["DBTPU_DISKKV_DIR"] = str(tmp_path / "diskkv")
    try:
        factory = _factory(_SO_ONDISK)
        reg = _Registry()
        hosts = {}

        def mk(nid, restart=False):
            cfg = NodeHostConfig(
                deployment_id=32, rtt_millisecond=5,
                nodehost_dir=f"{tmp_path}/h{nid}", raft_address=f"d{nid}:1",
                raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            )
            nh = NodeHost(cfg)
            nh.start_cluster(
                {} if restart else {1: "d1:1", 2: "d2:1", 3: "d3:1"},
                False, factory,
                Config(cluster_id=1, node_id=nid, election_rtt=20,
                       heartbeat_rtt=2),
            )
            return nh

        for nid in (1, 2, 3):
            hosts[nid] = mk(nid)

        leader = None
        deadline = time.time() + 60
        while time.time() < deadline and leader is None:
            for nid, nh in hosts.items():
                lid, ok = nh.get_leader_id(1)
                if ok and lid == nid:
                    leader = nid
            time.sleep(0.02)
        assert leader

        for i in range(20):
            _, leader = _propose_retry(hosts, leader, 1,
                                       f"k{i}=v{i}".encode())
        assert hosts[leader].sync_read(1, b"k19", timeout_s=5.0) == b"v19"

        victim = [n for n in hosts if n != leader][0]
        hosts[victim].stop()
        hosts[victim] = mk(victim, restart=True)
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if hosts[victim].stale_read(1, b"k19") == b"v19":
                    break
            except Exception:
                pass
            time.sleep(0.05)
        else:
            raise AssertionError("restarted on-disk C++ SM did not recover")

        for nh in hosts.values():
            nh.stop()
    finally:
        del os.environ["DBTPU_DISKKV_DIR"]


@pytest.mark.slow
def test_cpp_sm_cluster_end_to_end(tmp_path):
    """3-host cluster running the C++ KV plugin: propose, linearizable
    read, cross-replica hash equality, restart + replay."""
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    factory = _factory()
    reg = _Registry()
    hosts = {}

    def mk(nid, restart=False):
        cfg = NodeHostConfig(
            deployment_id=31, rtt_millisecond=5,
            nodehost_dir=f"{tmp_path}/h{nid}", raft_address=f"q{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        )
        nh = NodeHost(cfg)
        nh.start_cluster(
            {} if restart else {1: "q1:1", 2: "q2:1", 3: "q3:1"},
            False, factory,
            Config(cluster_id=1, node_id=nid, election_rtt=20,
                   heartbeat_rtt=2, snapshot_entries=30,
                   compaction_overhead=5),
        )
        return nh

    for nid in (1, 2, 3):
        hosts[nid] = mk(nid)

    leader = None
    # generous: the first user of this engine shape pays the jit compile
    deadline = time.time() + 60
    while time.time() < deadline and leader is None:
        for nid, nh in hosts.items():
            lid, ok = nh.get_leader_id(1)
            if ok and lid == nid:
                leader = nid
        time.sleep(0.02)
    assert leader

    for i in range(60):  # crosses the snapshot_entries=30 threshold
        _, leader = _propose_retry(hosts, leader, 1, f"k{i}=v{i}".encode())
    assert hosts[leader].sync_read(1, b"k59", timeout_s=5.0) == b"v59"

    deadline = time.time() + 20
    while time.time() < deadline:
        hashes = {n: hosts[n].get_sm_hash(1) for n in hosts}
        if len(set(hashes.values())) == 1:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"C++ SM replicas diverged: {hashes}")

    # restart one host: C++ SM state rebuilt from snapshot + log replay
    victim = [n for n in hosts if n != leader][0]
    hosts[victim].stop()
    hosts[victim] = mk(victim, restart=True)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if hosts[victim].stale_read(1, b"k59") == b"v59":
                break
        except Exception:
            pass
        time.sleep(0.05)
    else:
        raise AssertionError("restarted C++ SM host did not recover")

    for nh in hosts.values():
        nh.stop()
