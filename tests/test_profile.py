"""Perf attribution plane tests (dragonboat_tpu.profile).

Four subjects:

  * sampling discipline — unsampled profiler iterations must stay
    allocation- and event-free with the phase plane wired in (zero
    recorder events, zero Histogram observations on the off path);
  * the runtime device-sync audit — call-site attribution, blessed-seam
    classification, install/uninstall hygiene;
  * the compile watch — per-jitted-function retrace attribution;
  * the tier-1 acceptance assertion (`-m perf`): a live vector-engine
    scenario performs ZERO out-of-seam device syncs and ZERO
    steady-state XLA compiles, while the phase plane, the gauges and the
    Prometheus exposition all carry the attribution.
"""
from __future__ import annotations

import gzip
import io
import json
import os
import time

import pytest

from dragonboat_tpu.profile import (
    EXEC_PHASES,
    VECTOR_PHASES,
    PhasePlane,
    compile_watch,
    diff_compiles,
    diff_sync,
    phase_plane,
    sync_audit,
    write_exposition,
)
from dragonboat_tpu.trace import Profiler, flight_recorder


# ---------------------------------------------------------------------------
# sampling discipline (satellite: the off path stays event-free)
# ---------------------------------------------------------------------------


def test_unsampled_iterations_stay_event_free():
    plane = PhasePlane()
    prof = Profiler(sample_ratio=4)
    prof.attach_phase_plane(plane, "vector")
    rec = flight_recorder()
    rec.reset()
    for _ in range(3):  # iterations 1..3 of ratio 4: never sampled
        prof.new_iteration(1)
        assert not prof.sampling
        prof.start()
        prof.end("pack")
        prof.add("deliver", 0.001)
    assert plane.total_observations() == 0, "histogram observed off-path"
    assert len(rec) == 0, "recorder event on the unsampled path"
    # iteration 4 IS sampled: histograms fill — but at SPARSE sampling
    # no flight-recorder spans are emitted (they would crowd the ring's
    # bounded forensic history at the always-on production default)
    prof.new_iteration(1)
    assert prof.sampling
    prof.start()
    prof.end("pack")
    prof.add("deliver", 0.001)
    assert plane.histogram("vector", "pack").count == 1
    assert plane.histogram("vector", "deliver").count == 1
    assert len(rec) == 0, "phase_span recorded at sparse sampling"


def test_full_sampling_emits_recorder_spans():
    """Spans reach the flight recorder only at ratio 1 (the bench/debug
    opt-in, EngineConfig.profile_sample_ratio=1)."""
    plane = PhasePlane()
    prof = Profiler(sample_ratio=1)
    prof.attach_phase_plane(plane, "vector")
    rec = flight_recorder()
    rec.reset()
    prof.new_iteration(1)
    prof.start()
    prof.end("pack")
    prof.add("deliver", 0.001)
    events = [e for e in rec.dump() if e["event"] == "phase_span"]
    assert {e["phase"] for e in events} == {"pack", "deliver"}
    assert all(e["engine"] == "vector" for e in events)


def test_phase_vocabulary_covers_both_engines():
    # the canonical keys bench zero-fills; decode phases 0-6 all named
    for p in ("pack", "dispatch", "fetch", "place", "send_rep", "save",
              "send_resp", "apply", "reads", "maintain", "deliver"):
        assert p in VECTOR_PHASES
    for p in ("step", "fast_apply", "send", "save", "apply", "exec"):
        assert p in EXEC_PHASES


def test_plane_exposition_is_conformant():
    from tests.test_observability import _parse_exposition

    plane = PhasePlane()
    plane.record_spans = False
    plane.on_phase("vector", "pack", 0.002, True)
    plane.on_phase("vector", "save", 0.004, True)
    plane.on_phase("exec", "step", 0.001, True)
    out = io.StringIO()
    plane.write(out)
    types, samples = _parse_exposition(out.getvalue())
    assert types["dragonboat_tpu_engine_phase_seconds"] == "histogram"
    engines = {lb.get("engine") for _, lb, _, _ in samples}
    phases = {lb.get("phase") for _, lb, _, _ in samples}
    assert engines == {"vector", "exec"}
    assert {"pack", "save", "step"} <= phases
    for name, _, _, keys in samples:
        assert keys == sorted(keys), f"unsorted label keys in {name}"
    counts = [
        float(v) for n, lb, v, _ in samples
        if n.endswith("_count") and lb.get("phase") == "pack"
    ]
    assert counts == [1.0]


# ---------------------------------------------------------------------------
# runtime device-sync audit
# ---------------------------------------------------------------------------


def test_sync_audit_attributes_out_of_seam_sites():
    import jax.numpy as jnp

    sa = sync_audit()
    before = sa.snapshot()
    sa.install()
    try:
        import jax

        jax.device_get(jnp.zeros(2))  # out-of-seam: this very line
        jax.block_until_ready(jnp.zeros(2))
    finally:
        sa.uninstall()
    after = sa.snapshot()
    d = diff_sync(before, after)
    assert d["out_of_seam"] == 2
    assert any("test_profile.py" in s for s in d["sites"])
    # the test file is NOT package code: the tier-1 filter excludes it
    own = {
        s: n for s, n in sa.out_of_seam_in_package().items()
        if "test_profile.py" in s
    }
    assert not own
    # uninstall really restored the originals
    import jax

    assert not sa.installed
    jax.device_get(jnp.zeros(2))
    assert sa.snapshot()["out_of_seam"] == after["out_of_seam"]


def test_compile_watch_attributes_retraces_per_function():
    import jax
    import jax.numpy as jnp

    cw = compile_watch().install()
    fn = jax.jit(lambda x: x * 2)
    cw.register("test_fn", fn)
    cw.register("test_fn", fn)  # idempotent: no double counting
    mark = cw.snapshot()
    fn(jnp.ones(3))
    fn(jnp.ones(3))  # warm: no new trace
    d1 = diff_compiles(mark, cw.snapshot())
    assert d1["per_function"].get("test_fn") == 1
    assert d1["total"] >= 1
    fn(jnp.ones(5))  # RETRACE: new shape
    d2 = diff_compiles(mark, cw.snapshot())
    assert d2["per_function"].get("test_fn") == 2
    assert d2["total"] > d1["total"]
    # weakly held: dropping the function must release it (the watch
    # never pins a dead engine's compiled executables) and its entry
    # reads zero rather than a stale cache size
    del fn
    import gc

    gc.collect()
    assert cw.per_function().get("test_fn", 0) == 0


# ---------------------------------------------------------------------------
# live vector-engine scenario: the tier-1 acceptance assertions
# ---------------------------------------------------------------------------


@pytest.fixture()
def vec_host(tmp_path):
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory
    from tests.test_nodehost import KVSM

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=5,
            raft_address="perf1:1",
            nodehost_dir=str(tmp_path),
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            enable_metrics=True,
            engine=EngineConfig(
                kind="vector",
                max_groups=8,
                max_peers=4,
                log_window=64,
                profile_sample_ratio=1,  # sample EVERY step
            ),
        )
    )
    try:
        nh.start_cluster(
            {1: "perf1:1"},
            False,
            lambda c, n: KVSM(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("no leader")
        yield nh
    finally:
        nh.stop()


@pytest.mark.perf
def test_vector_scenario_runtime_audit_clean(vec_host):
    """Acceptance: during a live vector-engine scenario the ONLY
    device->host transfers are the blessed `_fetch_output` seam's, and
    steady state compiles nothing — the runtime twins of the `-m lint`
    device-sync/retrace gates, asserted on real behavior."""
    nh = vec_host
    sa = sync_audit().install()
    cw = compile_watch().install()
    try:
        sess = nh.get_noop_session(1)
        # warm: first proposals may still trigger legitimate lazy
        # compiles (activation scatters etc.)
        for i in range(4):
            nh.sync_propose(sess, f"w{i}=v".encode(), timeout_s=10.0)
        sync_mark = sa.snapshot()
        pkg_mark = dict(sa.out_of_seam_in_package())
        compile_mark = cw.snapshot()
        for i in range(8):
            nh.sync_propose(sess, f"k{i}=v".encode(), timeout_s=10.0)
        rs = nh.read_index(1, 5.0)
        assert rs.wait(10.0).completed
        sync_now = sa.snapshot()
        # the seam kept transferring (the engine stepped)...
        assert sync_now["in_seam"] > sync_mark["in_seam"]
        # ...and NOTHING ELSE in the package synced the device
        new_pkg = {
            s: n for s, n in sa.out_of_seam_in_package().items()
            if n > pkg_mark.get(s, 0)
        }
        assert not new_pkg, f"out-of-seam device syncs at {new_pkg}"
        # zero steady-state retraces, attributed per jitted function
        d = diff_compiles(compile_mark, cw.snapshot())
        assert d["total"] == 0, f"steady-state XLA compiles: {d}"
        assert not d["per_function"]
    finally:
        sa.uninstall()
    # the phase plane saw every vector step phase that ran
    plane = phase_plane()
    for phase in ("pack", "dispatch", "fetch", "place", "save", "apply"):
        h = plane.histogram("vector", phase)
        assert h is not None and h.count > 0, f"phase {phase} unattributed"
    # gauges + exposition carry the audit
    nh._export_health_gauges()
    m = nh.metrics
    assert m.gauge_value("engine_device_syncs_total", (0, 0)) > 0
    assert m.gauge_value("engine_device_syncs_out_of_seam", (0, 0)) is not None
    assert m.gauge_value("engine_compile_events_total", (0, 0)) is not None
    out = io.StringIO()
    nh.write_health_metrics(out)
    text = out.getvalue()
    assert "engine_phase_seconds_bucket" in text
    assert 'phase="fetch"' in text
    assert "engine_compile_cache_entries" in text
    # registered jitted functions are named in the exposition
    assert "step_batch[g8]" in text


@pytest.mark.perf
def test_census_and_counters_add_zero_syncs(vec_host):
    """Acceptance (ISSUE 18): reading the HBM census and the counter
    plane on a LIVE vector scenario adds ZERO out-of-seam device syncs
    and zero steady-state retraces — census physical bytes come from
    init-time tensor metadata, logical fill and counters fold from the
    decode-maintained numpy mirrors."""
    nh = vec_host
    sa = sync_audit().install()
    cw = compile_watch().install()
    try:
        sess = nh.get_noop_session(1)
        for i in range(4):
            nh.sync_propose(sess, f"c{i}=v".encode(), timeout_s=10.0)
        pkg_mark = dict(sa.out_of_seam_in_package())
        compile_mark = cw.snapshot()
        census = counters = lanes = None
        for i in range(4):
            census = nh.engine.device_census()
            counters = nh.engine.counter_stats()
            lanes = nh.engine.lane_counters()
            nh.sync_propose(sess, f"z{i}=v".encode(), timeout_s=10.0)
        new_pkg = {
            s: n for s, n in sa.out_of_seam_in_package().items()
            if n > pkg_mark.get(s, 0)
        }
        assert not new_pkg, f"telemetry read synced the device at {new_pkg}"
        d = diff_compiles(compile_mark, cw.snapshot())
        assert d["total"] == 0, f"telemetry read retraced: {d}"
    finally:
        sa.uninstall()
    # the census reports this engine's real planes + this lane's fill
    assert census["hbm_bytes_total"] > 0
    assert 0 < census["hbm_log_bytes"] < census["hbm_bytes_total"]
    assert census["lanes_active"] == 1
    assert census["log_window"] == 64
    assert 0.0 < census["log_fill_p50"] <= 1.0
    assert 0.0 <= census["hbm_waste_ratio"] < 1.0
    assert "state.log_term" in census["planes"]
    # the counter plane moved: this lane elected itself and committed
    from dragonboat_tpu.ops.state import CTR_NAMES

    assert set(counters) == set(CTR_NAMES)
    assert counters["elections_won"] >= 1
    assert counters["commit_advances"] >= 8
    assert set(lanes) == {1}
    assert lanes[1]["commit_advances"] == counters["commit_advances"]


@pytest.mark.perf
def test_history_sampler_adds_zero_syncs_and_zero_retraces(vec_host, tmp_path):
    """Acceptance (ISSUE 19 tentpole): a LIVE HistorySampler ticking at
    a hot cadence over a vector host adds ZERO out-of-seam device syncs
    and zero steady-state retraces — every snapshotted source is a
    zero-sync stat export (decode-maintained numpy mirrors / plain
    ints) and the ring write is pure host-side json+mmap."""
    from dragonboat_tpu.profile import (
        HISTORY_STATS_KEYS,
        HistorySampler,
        read_history,
    )

    nh = vec_host
    sa = sync_audit().install()
    cw = compile_watch().install()
    ring = str(tmp_path / "hist" / "history.ring")
    os.makedirs(os.path.dirname(ring))
    sampler = None
    try:
        sess = nh.get_noop_session(1)
        for i in range(4):
            nh.sync_propose(sess, f"w{i}=v".encode(), timeout_s=10.0)
        pkg_mark = dict(sa.out_of_seam_in_package())
        compile_mark = cw.snapshot()
        sampler = HistorySampler(ring, {0: nh}, interval_s=0.02).start()
        try:
            for i in range(8):
                nh.sync_propose(sess, f"h{i}=v".encode(), timeout_s=10.0)
            time.sleep(0.1)  # several sampler ticks land mid-traffic
        finally:
            sampler.stop()
        new_pkg = {
            s: n for s, n in sa.out_of_seam_in_package().items()
            if n > pkg_mark.get(s, 0)
        }
        assert not new_pkg, f"history sampling synced the device at {new_pkg}"
        d = diff_compiles(compile_mark, cw.snapshot())
        assert d["total"] == 0, f"history sampling retraced: {d}"
    finally:
        sa.uninstall()
    st = sampler.stats()
    assert list(st) == list(HISTORY_STATS_KEYS)
    assert st["samples_total"] >= 2 and st["errors_total"] == 0
    _meta, samples = read_history(ring)
    assert len(samples) == st["samples_total"]
    last = samples[-1]
    assert last["event"] == "history_sample" and last["schema"] == 1
    assert last["host"] == "perf1:1"
    lane = last["lanes"]["1"]  # json object keys stringify
    assert lane["leader_id"] == 1 and lane["commit_gap"] >= 0
    assert lane["counters"]["commit_advances"] >= 8
    assert last["counters"]["elections_won"] >= 1
    assert last["census"]["hbm_bytes_total"] > 0
    assert last.get("errors", []) == []


@pytest.mark.perf
def test_bench_attribution_fold_schema():
    """Acceptance: every bench config JSON always contains
    phase_breakdown (ALL canonical phase keys, zero when the phase never
    ran), device_syncs and compile_events — even on the zero-host /
    bring-up-failed path."""
    import bench

    r = bench._attribution_report({}, None, None)
    assert set(r["phase_breakdown"]) == set(VECTOR_PHASES)
    assert all(v == 0.0 for v in r["phase_breakdown"].values())
    assert r["device_syncs"] == {"in_seam": 0, "out_of_seam": 0, "sites": {}}
    assert r["compile_events"]["total"] == 0
    assert r["compile_events"]["per_function"] == {}


@pytest.mark.perf
def test_bench_census_fold_schema():
    """Acceptance (ISSUE 18): every bench config JSON always carries the
    HBM census keys and the counter totals — zero-filled on the
    zero-host / bring-up-failed path, so perfdiff and the paged-arena
    baseline read a stable schema from any artifact."""
    import bench
    from dragonboat_tpu.ops.state import CTR_NAMES
    from dragonboat_tpu.profile import CENSUS_KEYS

    r = bench._census_report({})
    assert set(r) == set(CENSUS_KEYS) | {"counters"}
    assert r["hbm_bytes_total"] == 0
    assert r["hbm_log_bytes"] == 0
    assert r["log_fill_p50"] == 0.0
    assert r["log_fill_p99"] == 0.0
    assert r["hbm_waste_ratio"] == 0.0
    assert set(r["counters"]) == set(CTR_NAMES)
    assert all(v == 0 for v in r["counters"].values())


@pytest.mark.perf
def test_bench_history_fold_schema(tmp_path):
    """Acceptance (ISSUE 19): every bench config JSON always carries the
    history_* sampler keys — zero-filled when the sampler never started
    (bring-up-failed path) so perfdiff's informational history section
    reads a stable schema; a live sampler reports its real counts."""
    import bench
    from dragonboat_tpu.profile import HISTORY_STATS_KEYS

    r = bench._history_report(None)
    assert set(r) == {f"history_{k}" for k in HISTORY_STATS_KEYS}
    assert r["history_samples_total"] == 0
    assert r["history_errors_total"] == 0
    assert r["history_sample_cost_seconds_total"] == 0.0
    assert r["history_interval_seconds"] == 0.0
    sampler = bench._start_history(str(tmp_path), {})
    assert sampler is not None
    try:
        sampler.sample_once()
    finally:
        sampler.stop(final_sample=False)
    live = bench._history_report(sampler)
    assert set(live) == set(r)
    assert live["history_interval_seconds"] > 0.0


@pytest.mark.perf
def test_write_exposition_standalone():
    out = io.StringIO()
    write_exposition(out)  # whatever the process accumulated so far
    # never raises; emits nothing or conformant families only
    for ln in out.getvalue().splitlines():
        assert ln.startswith("#") or "dragonboat_tpu_" in ln


# ---------------------------------------------------------------------------
# dump_flight artifact discipline (satellite: cap + gzip rotation) and
# the timeline CLI's transparent .gz / --spans rendering
# ---------------------------------------------------------------------------


def test_dump_flight_cap_and_gzip_rotation(vec_host, tmp_path):
    from dragonboat_tpu.tools import timeline

    rec = flight_recorder()
    for i in range(400):
        rec.record("noise", cluster=1, seq=i, pad="x" * 64)
    path = str(tmp_path / "dump.jsonl")
    vec_host.dump_flight(path, max_bytes=8192)
    assert os.path.getsize(path) <= 8192 + 512  # meta line slack
    with open(path) as f:
        meta = json.loads(f.readline())
    assert meta["event"] == "_meta"
    assert meta["dropped_events"] > 0
    # the kept tail is the NEWEST events
    evs = timeline.load_dump(path)
    noise = [e for e in evs if e["event"] == "noise"]
    assert noise and noise[-1]["seq"] == 399
    # second dump rotates the first to a gzip artifact
    vec_host.dump_flight(path, max_bytes=8192)
    rotated = path + ".1.gz"
    assert os.path.exists(rotated)
    with gzip.open(rotated, "rt") as f:
        assert json.loads(f.readline())["event"] == "_meta"
    # timeline reads the rotated .gz transparently (by magic, not name)
    evs_gz = timeline.load_dump(rotated)
    assert any(e["event"] == "noise" for e in evs_gz)
    # and a dump written STRAIGHT to .gz round-trips too
    gzpath = str(tmp_path / "direct.jsonl.gz")
    vec_host.dump_flight(gzpath)
    assert any(e["event"] == "noise" for e in timeline.load_dump(gzpath))


def test_timeline_spans_interleave_with_chain_stages(tmp_path, capsys):
    from dragonboat_tpu.tools import timeline

    dump = tmp_path / "spans.jsonl"
    lines = [
        {"event": "_meta", "mono_offset": 0.0, "source": "n1"},
        {"event": "propose_enqueue", "t": 10.0005, "cluster": 1,
         "node": 1, "trace": 7},
        # recorded at span END (t=10.002) with dur 0.004 -> starts 9.998,
        # BEFORE the propose despite the later record time
        {"event": "phase_span", "t": 10.002, "cluster": 0,
         "engine": "vector", "phase": "dispatch", "dur": 0.004},
        {"event": "quorum_commit", "t": 10.003, "cluster": 1,
         "node": 1, "trace": 7},
        {"event": "leader_changed", "t": 10.004, "cluster": 1, "node": 1,
         "leader": 1},
    ]
    dump.write_text("\n".join(json.dumps(l) for l in lines) + "\n")
    rc = timeline.main([str(dump), "--spans"])
    assert rc == 0
    out = capsys.readouterr().out
    span_ln = [l for l in out.splitlines() if "|--" in l]
    assert len(span_ln) == 1 and "vector/dispatch" in span_ln[0]
    assert "4000.0us" in span_ln[0]
    # interleaving: the span line is re-anchored to its START, so it
    # prints before the propose; the default filter keeps chain stages
    # and drops unrelated events
    order = [l.split()[2] for l in out.splitlines() if l.startswith("+")]
    assert order[0].startswith("|--") or "propose_enqueue" in out.splitlines()[1]
    assert "leader_changed" not in out
    assert "propose_enqueue" in out and "quorum_commit" in out
