"""Batch proposal API tests (propose_batch: one lock round-trip per wave;
the engines already replicate/persist/apply in batches — this extends
batching to the client boundary)."""
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import ErrInvalidSession
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory


class CounterSM(IStateMachine):
    def __init__(self, *a):
        self.n = 0

    def update(self, data):
        self.n += 1
        return Result(value=self.n)

    def lookup(self, q):
        return self.n

    def save_snapshot(self, w, fc, done):
        w.write(self.n.to_bytes(8, "little"))

    def recover_from_snapshot(self, r, fc, done):
        self.n = int.from_bytes(r.read(8), "little")

    def close(self):
        pass


@pytest.mark.parametrize("engine", ["scalar", "vector"])
def test_propose_batch_commits_in_order(tmp_path, engine):
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=88, rtt_millisecond=5, raft_address="pb1:1",
        nodehost_dir=str(tmp_path / "nh"),
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(kind=engine, max_groups=4, max_peers=4,
                            log_window=64),
    ))
    try:
        nh.start_cluster({1: "pb1:1"}, False, lambda c, n: CounterSM(),
                         Config(cluster_id=1, node_id=1, election_rtt=20,
                                heartbeat_rtt=2))
        deadline = time.time() + 60
        while time.time() < deadline:
            _, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        assert ok
        s = nh.get_noop_session(1)
        rss = nh.propose_batch(s, [b"x%d" % i for i in range(50)], 30.0)
        assert len(rss) == 50
        results = [rs.wait(30.0) for rs in rss]
        assert all(r.completed for r in results)
        # applied in submission order: update counter is sequential
        values = [r.result.value for r in results]
        assert values == sorted(values)
        assert nh.stale_read(1, None) == 50

        # a registered session may NOT batch: at-most-once bookkeeping is
        # strictly sequential
        sess = nh.sync_get_session(1, timeout_s=10.0)
        with pytest.raises(ErrInvalidSession):
            nh.propose_batch(sess, [b"a", b"b"], 10.0)
        nh.sync_close_session(sess, timeout_s=10.0)
    finally:
        nh.stop()


def test_propose_batch_overflow_drops_tail(tmp_path):
    """Past the incoming-queue capacity the tail completes as DROPPED
    (ErrClusterNotReady on unwrap) instead of failing the whole batch."""
    from dragonboat_tpu.settings import soft

    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=89, rtt_millisecond=5, raft_address="pb2:1",
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4,
                            log_window=64),
    ))
    try:
        nh.start_cluster({1: "pb2:1"}, False, lambda c, n: CounterSM(),
                         Config(cluster_id=1, node_id=1, election_rtt=20,
                                heartbeat_rtt=2))
        deadline = time.time() + 60
        while time.time() < deadline:
            _, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        assert ok, "no leader elected"
        s = nh.get_noop_session(1)
        n = soft.incoming_proposal_queue_length + 64
        rss = nh.propose_batch(s, [b"y"] * n, 30.0)
        assert len(rss) == n
        dropped = sum(
            1 for rs in rss if rs.wait(60.0).dropped
        )
        completed = sum(1 for rs in rss if rs.result and rs.result.completed)
        assert dropped > 0
        assert completed > 0
        assert dropped + completed == n
    finally:
        nh.stop()


@pytest.mark.parametrize("engine", ["scalar", "vector"])
def test_propose_batch_async_handle(tmp_path, engine):
    """propose_batch_async: ONE BatchRequestState for the whole batch,
    completion counted in runs (batch keys route by (batch_id, seq))."""
    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=89, rtt_millisecond=5, raft_address="pba1:1",
        nodehost_dir=str(tmp_path / "nh"),
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(kind=engine, max_groups=4, max_peers=4,
                            log_window=64),
    ))
    try:
        nh.start_cluster({1: "pba1:1"}, False, lambda c, n: CounterSM(),
                         Config(cluster_id=1, node_id=1, election_rtt=20,
                                heartbeat_rtt=2))
        deadline = time.time() + 60
        while time.time() < deadline:
            _, ok = nh.get_leader_id(1)
            if ok:
                break
            time.sleep(0.02)
        assert ok
        s = nh.get_noop_session(1)
        h = nh.propose_batch_async(s, [b"y%d" % i for i in range(200)], 30.0)
        assert h.wait(30.0)
        assert h.completed == 200
        assert h.dropped == 0
        assert nh.stale_read(1, None) == 200
        # a second batch reuses nothing from the first
        h2 = nh.propose_batch_async(s, [b"z"] * 10, 30.0)
        assert h2.wait(30.0)
        assert h2.completed == 10
        assert nh.stale_read(1, None) == 210
        # registered sessions may not batch
        sess = nh.sync_get_session(1, timeout_s=10.0)
        with pytest.raises(ErrInvalidSession):
            nh.propose_batch_async(sess, [b"a", b"b"], 5.0)
        nh.sync_close_session(sess, timeout_s=10.0)
    finally:
        nh.stop()
