"""Operator tools tests: import_snapshot quorum repair + checkdisk."""
import json
import os
import time

import pytest

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.tools import (
    ErrIncompleteSnapshot,
    ErrInvalidMembers,
    ErrPathNotExist,
    check_disk,
    import_snapshot,
)
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

CLUSTER = 1


class KV(IStateMachine):
    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _nh_config(nid, tmp, reg):
    return NodeHostConfig(
        deployment_id=11, rtt_millisecond=5,
        nodehost_dir=f"{tmp}/h{nid}",
        raft_address=f"t{nid}:1",
        raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
    )


def _wait_leader(hosts, deadline_s=60):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for nid, nh in hosts.items():
            lid, ok = nh.get_leader_id(CLUSTER)
            if ok and lid == nid:
                return nid
        time.sleep(0.02)
    raise AssertionError("no leader")


def test_check_disk(tmp_path):
    out = check_disk(str(tmp_path), count=20, payload_size=512)
    assert out["count"] == 20
    assert out["fsync_p50_us"] > 0
    assert out["synced_writes_per_sec"] > 0
    assert os.listdir(str(tmp_path)) == []  # probe file removed


def test_import_snapshot_quorum_repair(tmp_path):
    """The full repair story: 3-node cluster loses 2 nodes permanently; an
    exported snapshot is imported on the survivor with a single-member
    membership; the survivor restarts alone with all data."""
    reg = _Registry()
    hosts = {}
    members = {n: f"t{n}:1" for n in (1, 2, 3)}
    for nid in (1, 2, 3):
        nh = NodeHost(_nh_config(nid, str(tmp_path), reg))
        nh.start_cluster(
            members, False, lambda c, n: KV(),
            Config(cluster_id=CLUSTER, node_id=nid,
                   election_rtt=20, heartbeat_rtt=4),
        )
        hosts[nid] = nh
    leader = _wait_leader(hosts)
    s = hosts[leader].get_noop_session(CLUSTER)
    for i in range(10):
        hosts[leader].sync_propose(s, f"k{i}=v{i}".encode(), timeout_s=45.0)

    export_root = str(tmp_path / "export")
    os.makedirs(export_root)
    hosts[leader].sync_request_snapshot(
        CLUSTER, export_path=export_root, timeout_s=30.0
    )
    exported = [
        os.path.join(export_root, d) for d in os.listdir(export_root)
    ]
    assert len(exported) == 1, exported
    src = exported[0]
    assert os.path.exists(os.path.join(src, "snapshot.metadata"))

    # catastrophe: all hosts stop; 2 and 3 are gone forever
    for nh in hosts.values():
        nh.stop()

    # operator repairs node 1 with a single-member cluster
    cfg1 = _nh_config(1, str(tmp_path), reg)
    ss = import_snapshot(cfg1, src, {1: "t1:1"}, 1)
    assert ss.imported and ss.membership.addresses == {1: "t1:1"}
    assert ss.membership.removed.keys() >= {2, 3}

    # survivor restarts alone and owns all the data
    nh1 = NodeHost(_nh_config(1, str(tmp_path), reg))
    nh1.start_cluster(
        {}, False, lambda c, n: KV(),
        Config(cluster_id=CLUSTER, node_id=1,
               election_rtt=20, heartbeat_rtt=4),
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        lid, ok = nh1.get_leader_id(CLUSTER)
        if ok and lid == 1:
            break
        time.sleep(0.02)
    else:
        raise AssertionError("survivor never became single-node leader")
    assert nh1.sync_read(CLUSTER, "k9", timeout_s=30.0) == "v9"
    m = nh1.get_cluster_membership(CLUSTER)
    assert set(m.addresses) == {1}
    # and it can still make progress
    s = nh1.get_noop_session(CLUSTER)
    nh1.sync_propose(s, b"post=repair", timeout_s=30.0)
    assert nh1.sync_read(CLUSTER, "post", timeout_s=30.0) == "repair"
    nh1.stop()


def test_import_snapshot_validation(tmp_path):
    cfg = NodeHostConfig(
        deployment_id=1, rtt_millisecond=5,
        nodehost_dir=str(tmp_path / "nh"), raft_address="v1:1",
    )
    with pytest.raises(ErrInvalidMembers):
        import_snapshot(cfg, str(tmp_path), {2: "v2:1"}, 1)  # 1 not a member
    with pytest.raises(ErrPathNotExist):
        import_snapshot(cfg, str(tmp_path / "nope"), {1: "v1:1"}, 1)
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ErrIncompleteSnapshot):
        import_snapshot(cfg, str(empty), {1: "v1:1"}, 1)


def test_export_does_not_compact_own_history(tmp_path):
    """Regression: an exported snapshot must leave the node's own log and
    snapshot records alone — with compaction_overhead set, a restart after
    export must still replay (the export writes no logdb record, so
    compacting against it would strand the node)."""
    reg = _Registry()
    nh = NodeHost(_nh_config(1, str(tmp_path), reg))
    nh.start_cluster(
        {1: "t1:1"}, False, lambda c, n: KV(),
        Config(cluster_id=CLUSTER, node_id=1, election_rtt=20,
               heartbeat_rtt=2, compaction_overhead=3),
    )
    _wait_leader({1: nh})
    s = nh.get_noop_session(CLUSTER)
    for i in range(20):
        nh.sync_propose(s, f"e{i}=x{i}".encode(), timeout_s=5.0)
    exp = tmp_path / "exp"
    exp.mkdir()
    nh.sync_request_snapshot(CLUSTER, export_path=str(exp), timeout_s=30.0)
    nh.stop()

    nh2 = NodeHost(_nh_config(1, str(tmp_path), reg))
    nh2.start_cluster(
        {}, False, lambda c, n: KV(),
        Config(cluster_id=CLUSTER, node_id=1, election_rtt=20,
               heartbeat_rtt=2, compaction_overhead=3),
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if nh2.stale_read(CLUSTER, "e19") == "x19":
                break
        except Exception:
            pass
        time.sleep(0.02)
    else:
        raise AssertionError("node failed to recover after export")
    nh2.stop()


def test_request_snapshot_bad_export_path(tmp_path):
    from dragonboat_tpu.nodehost import ErrDirNotExist

    reg = _Registry()
    nh = NodeHost(_nh_config(1, str(tmp_path), reg))
    nh.start_cluster(
        {1: "t1:1"}, False, lambda c, n: KV(),
        Config(cluster_id=CLUSTER, node_id=1, election_rtt=20,
               heartbeat_rtt=2),
    )
    try:
        with pytest.raises(ErrDirNotExist):
            nh.request_snapshot(CLUSTER, export_path=str(tmp_path / "missing"))
    finally:
        nh.stop()


def test_raft_top_renders_checked_in_snapshot_via_cli():
    """ISSUE 18 acceptance: `python -m dragonboat_tpu.tools.top` renders
    the checked-in snapshot fixture — header census/counter panel, lanes
    ranked hottest-first (the churning lane with 6 elections and a
    40-entry commit gap outranks everything), --json and --sort modes."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fixture = os.path.join(repo, "tests", "data", "top_snapshot.json")

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "dragonboat_tpu.tools.top", *args],
            cwd=repo, capture_output=True, text=True, timeout=60,
        )

    p = cli(fixture)
    assert p.returncode == 0, p.stdout + p.stderr
    out = p.stdout.splitlines()
    assert out[0].startswith("raft-top  lanes=4")
    assert "hbm=52.0MiB" in out[0]
    assert "waste=0.69" in out[0]
    assert "elections 6/5" in out[1]
    assert "backlog 3" in out[1]
    # the table is ranked: the churning lane 101 leads
    first_row = out[3].split()
    assert first_row[1] == "101"
    # --sort ingest re-ranks (all rates are 0 on a frozen view: stable)
    assert cli(fixture, "--sort", "ingest").returncode == 0
    # --limit truncates rows but keeps the header
    p = cli(fixture, "--limit", "1")
    assert len(p.stdout.splitlines()) == 4
    # --json emits the ranked snapshot for downstream tooling
    p = cli(fixture, "--json")
    snap = json.loads(p.stdout)
    assert snap["lanes"][0]["cluster_id"] == 101
    assert snap["lanes"][0]["heat"] > snap["lanes"][-1]["heat"]
    assert snap["census"]["hbm_waste_ratio"] == 0.69
    # a non-snapshot file refuses cleanly
    p = cli(os.path.join(repo, "tests", "data", "perfdiff_base.json"))
    assert p.returncode == 2
    assert "error" in p.stderr


def test_raft_top_collects_and_ranks_from_live_host(tmp_path):
    """collect_snapshot folds a live host's lane_stats/lane_counters/
    census/pressure into the snapshot schema the CLI renders, and the
    two-snapshot delta path derives ingest rates."""
    from dragonboat_tpu.config import EngineConfig
    from dragonboat_tpu.tools.top import collect_snapshot, rank_lanes, render
    from tests.test_nodehost import KVSM
    import io as _io

    reg = _Registry()
    nh = NodeHost(
        NodeHostConfig(
            deployment_id=1,
            rtt_millisecond=5,
            raft_address="top1:1",
            raft_rpc_factory=lambda l: loopback_factory(l, reg),
            engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4),
        )
    )
    try:
        nh.start_cluster(
            {1: "top1:1"}, False, lambda c, n: KVSM(c, n),
            Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
        )
        deadline = time.time() + 60
        while time.time() < deadline:
            lid, ok = nh.get_leader_id(1)
            if ok and lid == 1:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("no leader")
        sess = nh.get_noop_session(1)
        first = collect_snapshot({1: nh})
        for i in range(4):
            nh.sync_propose(sess, f"k{i}=v".encode(), timeout_s=10.0)
        snap = collect_snapshot({1: nh})
        assert snap["schema"] == 1
        rows = snap["lanes"]
        assert len(rows) == 1 and rows[0]["cluster_id"] == 1
        assert rows[0]["counters"]["commit_advances"] >= 4
        assert snap["census"]["hbm_bytes_total"] == 0  # scalar engine
        assert snap["counters"]["elections_won"] >= 1
        # delta ranking derives a positive ingest rate from two snapshots
        snap["ts"] = first["ts"] + 2.0  # pin dt: no wall-clock flake
        ranked = rank_lanes(snap, prev=first)
        assert ranked[0]["ingest_rate"] > 0
        buf = _io.StringIO()
        render(snap, prev=first, out=buf)
        assert "raft-top  lanes=1" in buf.getvalue()
    finally:
        nh.stop()


def test_logdb_checker_accepts_replicas_and_detects_divergence():
    """The logdb consistency checker passes identical replica logs and
    flags a committed-range divergence / commit-beyond-log violation
    (Log Matching, raft paper 5.3)."""
    from dragonboat_tpu.storage.kv import MemKV
    from dragonboat_tpu.storage.logdb import ShardedLogDB
    from dragonboat_tpu.tools.logdbcheck import check_logdb_consistency
    from dragonboat_tpu.types import Entry, State, Update

    def mk_db(node_id, cmds, commit, divergent_at=None):
        db = ShardedLogDB(kv_factory=lambda shard: MemKV())
        ents = []
        for i, cmd in enumerate(cmds, start=1):
            term = 2 if (divergent_at is not None and i >= divergent_at) else 1
            ents.append(Entry(index=i, term=term, cmd=cmd))
        db.save_raft_state([
            Update(
                cluster_id=CLUSTER, node_id=node_id,
                state=State(term=2, vote=1, commit=commit),
                entries_to_save=ents,
            )
        ])
        return db

    cmds = [f"c{i}".encode() for i in range(1, 8)]
    dbs = {nid: mk_db(nid, cmds, commit=7) for nid in (1, 2)}
    report = check_logdb_consistency(dbs, CLUSTER)
    assert report.ok, report.violations
    assert len(report.replicas) == 2

    # replica 3 diverges at index 5 while both claim commit=7: violation
    dbs[3] = mk_db(3, cmds, commit=7, divergent_at=5)
    report = check_logdb_consistency(dbs, CLUSTER)
    assert not report.ok
    assert any("divergence" in v for v in report.violations)

    # commit beyond the persisted log is a per-replica violation
    dbs2 = {1: mk_db(1, cmds, commit=99)}
    report = check_logdb_consistency(dbs2, CLUSTER)
    assert any("beyond last persisted" in v for v in report.violations)
