"""Ported etcd/raft paper-conformance scenarios against the scalar core
(round-3 expansion; companion to test_raft_etcd_conformance.py).

The reference vendors etcd's raft tests for corner-case parity
(internal/raft/raft_etcd_paper_test.go — each test names the Raft paper
section it validates — plus raft_etcd_test.go matrices; docs/test.md:4).
These re-express those matrices against our scalar core through the same
message-level interface. Citations name the etcd test and paper section.
"""
import pytest

from dragonboat_tpu.core.logentry import InMemLogDB
from dragonboat_tpu.core.raft import Raft, RaftNodeState
from dragonboat_tpu.types import (
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
    SystemCtx,
)

from tests.raft_harness import Network, make_cluster, new_test_raft

MT = MessageType
F, C, L = RaftNodeState.FOLLOWER, RaftNodeState.CANDIDATE, RaftNodeState.LEADER
OBS, WIT = RaftNodeState.OBSERVER, RaftNodeState.WITNESS


def logdb_with_terms(*terms: int) -> InMemLogDB:
    db = InMemLogDB()
    db.append([Entry(index=i + 1, term=t) for i, t in enumerate(terms)])
    return db


def terms_of(r: Raft):
    first, last = r.log.first_index(), r.log.last_index()
    return [r.log.term(i) for i in range(first, last + 1)]


def tick_until_election(r: Raft) -> None:
    for _ in range(2 * r.election_timeout):
        r.tick()


def make_leader(r: Raft) -> None:
    tick_until_election(r)
    for nid in list(r.remotes):
        if nid != r.node_id:
            r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=nid, to=r.node_id,
                             term=r.term, reject=False))
            if r.is_leader():
                break
    assert r.is_leader()


# ---------------------------------------------------------------------------
# etcd TestUpdateTermFromMessage (paper §5.1): any state adopts a higher term
# and becomes follower.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("start", ["follower", "candidate", "leader"])
def test_update_term_from_message(start):
    r = new_test_raft(1, [1, 2, 3])
    if start == "candidate":
        tick_until_election(r)
    elif start == "leader":
        make_leader(r)
    r.handle(Message(type=MT.REPLICATE, from_=2, to=1, term=10))
    assert r.term == 10
    assert r.state == F


# ---------------------------------------------------------------------------
# etcd TestStartAsFollower (paper §5.2)
# ---------------------------------------------------------------------------
def test_start_as_follower():
    r = new_test_raft(1, [1, 2, 3])
    assert r.state == F and r.term == 0


# ---------------------------------------------------------------------------
# etcd TestLeaderBcastBeat (paper §5.2): heartbeat_timeout ticks -> beats to
# every voting peer, carrying no entries.
# ---------------------------------------------------------------------------
def test_leader_bcast_beat_carries_no_entries():
    r = new_test_raft(1, [1, 2, 3], election=10, heartbeat=1)
    make_leader(r)
    r.msgs = []
    r.tick()
    beats = [m for m in r.msgs if m.type == MT.HEARTBEAT]
    assert {m.to for m in beats} == {2, 3}
    assert all(not m.entries for m in beats)


# ---------------------------------------------------------------------------
# etcd TestLeaderElectionInOneRoundRPC (paper §5.2): vote outcomes decide
# the election in one round.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "size,votes,want_state",
    [
        (1, {}, L),
        (3, {2: True, 3: True}, L),
        (3, {2: True}, L),
        (5, {2: True, 3: True, 4: True, 5: True}, L),
        (5, {2: True, 3: True}, L),
        (3, {2: False, 3: False}, F),
        (5, {2: False, 3: False, 4: False, 5: False}, F),
        (3, {}, C),
        (5, {2: True}, C),
        (5, {2: False, 3: False}, C),
    ],
)
def test_leader_election_in_one_round(size, votes, want_state):
    r = new_test_raft(1, list(range(1, size + 1)))
    tick_until_election(r)
    for nid, grant in votes.items():
        r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=nid, to=1,
                         term=r.term, reject=not grant))
    assert r.state == want_state


# ---------------------------------------------------------------------------
# etcd TestFollowerVote (paper §5.2): an existing vote binds the follower.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "prior_vote,candidate,want_reject",
    [
        (0, 1, False),
        (0, 2, False),
        (1, 1, False),  # repeat grant to the same candidate
        (2, 2, False),
        (1, 2, True),   # already voted for someone else this term
        (2, 1, True),
    ],
)
def test_follower_vote_binding(prior_vote, candidate, want_reject):
    r = new_test_raft(3, [1, 2, 3])
    r.term = 1
    r.vote = prior_vote
    r.handle(Message(type=MT.REQUEST_VOTE, from_=candidate, to=3, term=1,
                     log_index=0, log_term=0))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP][-1]
    assert resp.reject == want_reject


# ---------------------------------------------------------------------------
# etcd TestCandidateFallback (paper §5.2): Replicate at >= candidate's term
# demotes the candidate.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("dterm", [0, 1])
def test_candidate_fallback(dterm):
    r = new_test_raft(1, [1, 2, 3])
    tick_until_election(r)
    assert r.state == C and r.term == 1
    r.handle(Message(type=MT.REPLICATE, from_=2, to=1, term=r.term + dterm))
    assert r.state == F
    assert r.leader_id == 2


# ---------------------------------------------------------------------------
# etcd TestLeaderStartReplication (paper §5.3): propose appends locally and
# broadcasts Replicate with the correct prev position.
# ---------------------------------------------------------------------------
def test_leader_start_replication():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    prev = r.log.last_index()
    prev_term = r.log.last_term()
    # ack the new-leader noop so both remotes leave the probe (WAIT) state —
    # a paused remote receives no optimistic Replicates (remote.go:173-186)
    for nid in (2, 3):
        r.handle(Message(type=MT.REPLICATE_RESP, from_=nid, to=1, term=r.term,
                         log_index=prev))
    r.msgs = []
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1,
                     entries=[Entry(cmd=b"some data")]))
    assert r.log.last_index() == prev + 1
    reps = [m for m in r.msgs if m.type == MT.REPLICATE]
    assert {m.to for m in reps} == {2, 3}
    for m in reps:
        assert m.log_index == prev
        assert m.log_term == prev_term
        assert [e.cmd for e in m.entries] == [b"some data"]


# ---------------------------------------------------------------------------
# etcd TestLeaderCommitEntry / TestLeaderAcknowledgeCommit (paper §5.3):
# the entry commits once a quorum acks it.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "size,ackers,want_commit",
    [
        (1, set(), True),
        (3, set(), False),
        (3, {2}, True),
        (3, {2, 3}, True),
        (5, set(), False),
        (5, {2}, False),
        (5, {2, 3}, True),
        (5, {2, 3, 4}, True),
    ],
)
def test_leader_acknowledge_commit(size, ackers, want_commit):
    r = new_test_raft(1, list(range(1, size + 1)))
    make_leader(r)
    base = r.log.committed
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    li = r.log.last_index()
    for nid in ackers:
        r.handle(Message(type=MT.REPLICATE_RESP, from_=nid, to=1, term=r.term,
                         log_index=li))
    assert (r.log.committed > base and r.log.committed == li) == want_commit


# ---------------------------------------------------------------------------
# etcd TestLeaderCommitPrecedingEntries (paper §5.3): committing a new entry
# commits everything before it.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("prior", [(), (2,), (1,), (1, 1)], ids=["0", "t2", "t1", "t1t1"])
def test_leader_commit_preceding_entries(prior):
    db = logdb_with_terms(*prior)
    db.set_state(State(term=2))
    r = new_test_raft(1, [1, 2, 3], logdb=db)
    r.term = 2
    tick_until_election(r)
    r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=2, to=1, term=r.term,
                     reject=False))
    assert r.is_leader()
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    li = r.log.last_index()
    r.handle(Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=r.term,
                     log_index=li))
    assert r.log.committed == li  # everything through li is committed


# ---------------------------------------------------------------------------
# etcd TestFollowerCommitEntry (paper §5.3): follower commits min(leader
# commit, last new entry).
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "n_ents,commit",
    [(1, 1), (2, 2), (2, 1), (3, 2)],
)
def test_follower_commit_entry(n_ents, commit):
    r = new_test_raft(2, [1, 2, 3])
    ents = [Entry(index=i + 1, term=1, cmd=b"e%d" % i) for i in range(n_ents)]
    r.handle(Message(type=MT.REPLICATE, from_=1, to=2, term=1, log_index=0,
                     log_term=0, commit=commit, entries=ents))
    assert r.log.committed == commit
    assert r.log.last_index() == n_ents


# ---------------------------------------------------------------------------
# etcd TestFollowerCheckMsgApp (paper §5.3): the log-matching check on
# (prev_index, prev_term).
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "prev_term,prev_index,want_reject,want_hint",
    [
        (0, 0, False, 0),   # empty prev always matches
        (1, 1, False, 0),   # matches an existing entry
        (2, 2, False, 0),
        (1, 2, True, 2),    # term mismatch at index 2
        (2, 3, True, 2),    # beyond the log; hint = follower last index
        (3, 3, True, 2),
    ],
)
def test_follower_check_replicate(prev_term, prev_index, want_reject, want_hint):
    db = logdb_with_terms(1, 2)
    db.set_state(State(term=2, commit=1))
    r = new_test_raft(2, [1, 2, 3], logdb=db)
    r.term = 2
    r.handle(Message(type=MT.REPLICATE, from_=1, to=2, term=2,
                     log_index=prev_index, log_term=prev_term))
    resp = [m for m in r.msgs if m.type == MT.REPLICATE_RESP][-1]
    assert resp.reject == want_reject
    if want_reject:
        assert resp.hint == want_hint


# ---------------------------------------------------------------------------
# etcd TestFollowerAppendEntries (paper §5.3): conflicting suffixes are
# truncated and rewritten.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "prev_index,prev_term,ents,want",
    [
        (2, 2, [(3, 3)], [1, 2, 3]),
        (1, 1, [(2, 3), (3, 4)], [1, 3, 4]),
        (0, 0, [(1, 1)], [1, 2]),          # duplicate of existing prefix
        (0, 0, [(1, 3)], [3]),             # full rewrite from index 1
    ],
)
def test_follower_append_entries(prev_index, prev_term, ents, want):
    db = logdb_with_terms(1, 2)
    db.set_state(State(term=2))
    r = new_test_raft(2, [1, 2, 3], logdb=db)
    r.term = 2
    r.handle(Message(
        type=MT.REPLICATE, from_=1, to=2, term=2,
        log_index=prev_index, log_term=prev_term,
        entries=[Entry(index=i, term=t) for i, t in ents],
    ))
    assert terms_of(r) == want


# ---------------------------------------------------------------------------
# etcd TestHandleHeartbeat: heartbeat commit is bounded by the follower's
# last index; it never regresses commit.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("hb_commit,want", [(3, 3), (1, 2), (2, 2)])
def test_handle_heartbeat_commit_bounds(hb_commit, want):
    # a heartbeat commit NEVER exceeds the follower's log: the sender caps
    # it at min(match, committed) (raft.go:810-816); etcd's commitTo panics
    # if that invariant is violated, so only in-range values are tested
    db = logdb_with_terms(1, 2, 3)
    db.set_state(State(term=3, commit=2))
    r = new_test_raft(2, [1, 2], logdb=db)
    r.term = 3
    r.log.commit_to(2)
    r.handle(Message(type=MT.HEARTBEAT, from_=1, to=2, term=3,
                     commit=hb_commit))
    assert r.log.committed == want
    resp = [m for m in r.msgs if m.type == MT.HEARTBEAT_RESP]
    assert resp, "heartbeat must be acked"


# ---------------------------------------------------------------------------
# etcd TestLeaderAppResp: accepted/rejected ReplicateResp moves match/next.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize(
    "reject,resp_index,hint,want_match,want_next",
    [
        (False, 2, 0, 2, 3),    # ack moves match and next
        (False, 0, 0, 0, 1),    # stale ack: no movement below current
        (True, 3, 0, 0, 1),     # probe reject at next-1 backs off
    ],
)
def test_leader_replicate_resp_progress(reject, resp_index, hint,
                                        want_match, want_next):
    db = logdb_with_terms(1, 1)
    db.set_state(State(term=1))
    r = new_test_raft(1, [1, 2, 3], logdb=db)
    r.term = 1
    r.state = C
    r.become_leader()
    rp = r.remotes[2]
    rp.match, rp.next = 0, r.log.last_index() + 1
    r.handle(Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=r.term,
                     log_index=resp_index, reject=reject, hint=hint))
    assert r.remotes[2].match == want_match
    assert r.remotes[2].next >= want_next


# ---------------------------------------------------------------------------
# etcd TestRecvMsgBeat equivalent: only a leader emits heartbeats on its
# heartbeat timer; followers' ticks emit nothing.
# ---------------------------------------------------------------------------
def test_follower_tick_emits_no_heartbeats():
    r = new_test_raft(1, [1, 2, 3], election=50)
    for _ in range(5):
        r.tick()
    assert [m for m in r.msgs if m.type == MT.HEARTBEAT] == []


# ---------------------------------------------------------------------------
# etcd TestStepIgnoreConfig: a second config-change proposal while one is
# pending is replaced by an empty application entry.
# ---------------------------------------------------------------------------
def test_second_config_change_stripped():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    cc = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"cc1")
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[cc]))
    assert r.pending_config_change
    i1 = r.log.last_index()
    cc2 = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"cc2")
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[cc2]))
    ents = r.log.entries(i1 + 1, 1 << 20)
    assert len(ents) == 1
    assert ents[0].type == EntryType.APPLICATION  # stripped to a noop
    assert r.pending_config_change  # still just the first one pending


# ---------------------------------------------------------------------------
# etcd TestNewLeaderPendingConfig: an uncommitted config-change entry in the
# log re-arms the pending flag on promotion.
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("has_uncommitted_cc", [False, True])
def test_new_leader_rearms_pending_config(has_uncommitted_cc):
    db = InMemLogDB()
    if has_uncommitted_cc:
        db.append([Entry(index=1, term=1, type=EntryType.CONFIG_CHANGE)])
    r = new_test_raft(1, [1, 2, 3], logdb=db)
    r.term = 1
    tick_until_election(r)
    r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=2, to=1, term=r.term,
                     reject=False))
    assert r.is_leader()
    assert r.pending_config_change == has_uncommitted_cc


# ---------------------------------------------------------------------------
# etcd TestAddNode / TestRemoveNode / TestAddObserver semantics.
# ---------------------------------------------------------------------------
def test_add_node_creates_remote():
    r = new_test_raft(1, [1])
    make_leader(r)
    r.add_node(2)
    assert set(r.remotes) == {1, 2}
    assert r.remotes[2].next == r.log.last_index() + 1
    assert not r.pending_config_change


def test_add_node_promotes_observer_with_progress():
    r = new_test_raft(1, [1])
    make_leader(r)
    r.add_observer(2)
    r.observers[2].match = 5
    r.add_node(2)
    assert 2 in r.remotes and 2 not in r.observers
    assert r.remotes[2].match == 5  # progress carried over


def test_remove_node_drops_remote_and_recommits():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    li = r.log.last_index()
    # only replica 2 acked; quorum of 3 not reached
    r.handle(Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=r.term,
                     log_index=li))
    committed_before = r.log.committed
    # removing node 3 shrinks the quorum to 2/2 -> the entry commits now
    r.remove_node(3)
    assert 3 not in r.remotes
    assert r.log.committed == li >= committed_before


def test_remove_self_leader_steps_down():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.remove_node(1)
    assert not r.is_leader()


# ---------------------------------------------------------------------------
# etcd TestLeaderTransfer matrices (thesis §3.10).
# ---------------------------------------------------------------------------
def test_transfer_to_up_to_date_follower_sends_timeout_now():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.remotes[2].match = r.log.last_index()
    r.msgs = []
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=2, to=1, hint=2))
    assert [m.to for m in r.msgs if m.type == MT.TIMEOUT_NOW] == [2]


def test_transfer_to_lagging_follower_waits_for_catchup():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    r.msgs = []
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=2, to=1, hint=2))
    assert [m for m in r.msgs if m.type == MT.TIMEOUT_NOW] == []
    # proposals are dropped during a transfer (raft thesis §3.10)
    li = r.log.last_index()
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"y")]))
    assert r.log.last_index() == li
    # the target catching up triggers the TimeoutNow
    r.handle(Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=r.term,
                     log_index=li))
    assert [m.to for m in r.msgs if m.type == MT.TIMEOUT_NOW] == [2]


def test_transfer_to_self_is_noop():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.msgs = []
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=1, to=1, hint=1))
    assert not r.leader_transfering()


def test_second_transfer_ignored_while_transferring():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=2, to=1, hint=2))
    assert r.leader_transfer_target == 2
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=3, to=1, hint=3))
    assert r.leader_transfer_target == 2


def test_transfer_aborts_after_election_timeout():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    r.handle(Message(type=MT.LEADER_TRANSFER, from_=2, to=1, hint=2))
    assert r.leader_transfering()
    for _ in range(r.election_timeout + 1):
        r.tick()
    assert not r.leader_transfering()
    assert r.is_leader()  # still leader; transfer just timed out


def test_timeout_now_triggers_immediate_campaign():
    """etcd TestLeaderTransferReceiveHigherTermVote leg: TimeoutNow makes the
    target campaign regardless of its election timer."""
    r = new_test_raft(2, [1, 2, 3])
    r.term = 1
    r.handle(Message(type=MT.TIMEOUT_NOW, from_=1, to=2, term=1))
    assert r.state == C
    assert r.term == 2
    # the vote requests carry the transfer hint so the disruption defense
    # does not drop them (raft.go:1387-1409)
    reqs = [m for m in r.msgs if m.type == MT.REQUEST_VOTE]
    assert reqs and all(m.hint == 2 for m in reqs)


# ---------------------------------------------------------------------------
# Check-quorum (etcd TestLeaderStepdownWhenQuorumLost/Active, §6.2).
# ---------------------------------------------------------------------------
def test_leader_steps_down_when_quorum_lost():
    r = new_test_raft(1, [1, 2, 3], check_quorum=True)
    make_leader(r)
    for _ in range(r.election_timeout + 1):
        r.tick()
    assert r.state == F


def test_leader_stays_when_quorum_active():
    r = new_test_raft(1, [1, 2, 3], check_quorum=True)
    make_leader(r)
    for i in range(r.election_timeout + 1):
        r.handle(Message(type=MT.HEARTBEAT_RESP, from_=2, to=1, term=r.term))
        r.tick()
    assert r.state == L


def test_free_stuck_candidate_with_check_quorum():
    """etcd TestFreeStuckCandidateWithCheckQuorum: a NOOP response frees a
    candidate stuck at a higher term behind a partition."""
    nt = make_cluster(3)
    for r in nt.rafts.values():
        r.check_quorum = True
    nt.elect(1)
    nt.isolate(3)
    nt.elect(3)  # partitioned: term rises, no votes arrive
    nt.elect(3)
    r3 = nt.rafts[3]
    assert r3.state == C and r3.term > nt.rafts[1].term
    nt.heal()
    # leader contact at lower term makes 3 send a NOOP carrying its term,
    # which forces a re-election at 3's term instead of wedging
    nt.send(Message(type=MT.HEARTBEAT, from_=1, to=3,
                    term=nt.rafts[1].term))
    assert nt.rafts[1].term >= r3.term


# ---------------------------------------------------------------------------
# Disruption defense (reference raft.go:1387-1409): a fresh leader lease
# drops non-transfer RequestVotes from higher terms.
# ---------------------------------------------------------------------------
def test_fresh_leader_lease_drops_higher_term_vote():
    r = new_test_raft(1, [1, 2, 3], check_quorum=True)
    r.term = 1
    r.leader_id = 3
    r.election_tick = 0
    r.handle(Message(type=MT.REQUEST_VOTE, from_=2, to=1, term=5,
                     log_index=10, log_term=5))
    assert r.term == 1  # dropped: term not adopted
    r.handle(Message(type=MT.REQUEST_VOTE, from_=2, to=1, term=5,
                     log_index=10, log_term=5, hint=2))  # transfer-hinted
    assert r.term == 5  # transfer votes bypass the lease


# ---------------------------------------------------------------------------
# Observers (etcd learner semantics).
# ---------------------------------------------------------------------------
def test_observer_never_campaigns():
    r = new_test_raft(1, [1, 2], is_observer=True)
    db_state = r.state
    assert db_state == OBS
    for _ in range(5 * r.election_timeout):
        r.tick()
    assert r.state == OBS
    assert [m for m in r.msgs if m.type == MT.REQUEST_VOTE] == []


def test_observer_receives_entries_but_has_no_vote():
    r = new_test_raft(2, [1], is_observer=True)
    r.handle(Message(type=MT.REPLICATE, from_=1, to=2, term=1, log_index=0,
                     log_term=0, commit=1, entries=[Entry(index=1, term=1)]))
    assert r.log.last_index() == 1
    assert r.log.committed == 1


def test_witness_votes_but_never_campaigns():
    r = new_test_raft(3, [1, 2], is_witness=True)
    assert r.state == WIT
    for _ in range(5 * r.election_timeout):
        r.tick()
    assert r.state == WIT
    r.handle(Message(type=MT.REQUEST_VOTE, from_=1, to=3, term=2,
                     log_index=5, log_term=2))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP][-1]
    assert resp.reject is False


# ---------------------------------------------------------------------------
# ReadIndex (thesis §6.4).
# ---------------------------------------------------------------------------
def test_read_index_requires_current_term_commit():
    db = logdb_with_terms(1)  # committed entry from an OLD term only
    db.set_state(State(term=1, commit=1))
    r = new_test_raft(1, [1, 2, 3], logdb=db)
    r.term = 1
    r.log.commit_to(1)
    tick_until_election(r)
    r.handle(Message(type=MT.REQUEST_VOTE_RESP, from_=2, to=1, term=r.term,
                     reject=False))
    assert r.is_leader()
    r.msgs = []
    r.ready_to_read = []
    # no entry committed at the NEW term yet: the read must be dropped
    r.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=7, hint_high=1))
    assert r.ready_to_read == []
    # commit the new-term noop, then the read goes through with hints
    li = r.log.last_index()
    r.handle(Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=r.term,
                     log_index=li))
    assert r.log.committed == li
    r.msgs = []
    r.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=9, hint_high=1))
    beats = [m for m in r.msgs if m.type == MT.HEARTBEAT]
    assert beats and all(m.hint == 9 for m in beats)


def test_read_index_single_node_immediate():
    r = new_test_raft(1, [1])
    make_leader(r)
    r.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=5, hint_high=1))
    assert r.ready_to_read
    assert r.ready_to_read[-1].system_ctx.low == 5


def test_read_index_quorum_confirmation_releases():
    r = new_test_raft(1, [1, 2, 3])
    make_leader(r)
    li = r.log.last_index()
    r.handle(Message(type=MT.REPLICATE_RESP, from_=2, to=1, term=r.term,
                     log_index=li))
    assert r.log.committed == li
    r.handle(Message(type=MT.READ_INDEX, from_=1, to=1, hint=11, hint_high=1))
    assert not r.ready_to_read
    # one follower echoing the ctx in a HeartbeatResp completes the quorum
    r.handle(Message(type=MT.HEARTBEAT_RESP, from_=2, to=1, term=r.term,
                     hint=11, hint_high=1))
    assert r.ready_to_read
    assert r.ready_to_read[-1].index == li


def test_follower_forwards_read_index_to_leader():
    r = new_test_raft(2, [1, 2, 3])
    r.term = 1
    r.handle(Message(type=MT.HEARTBEAT, from_=1, to=2, term=1))
    assert r.leader_id == 1
    r.msgs = []
    r.handle(Message(type=MT.READ_INDEX, from_=2, to=2, hint=3, hint_high=1))
    fwd = [m for m in r.msgs if m.type == MT.READ_INDEX]
    assert fwd and fwd[-1].to == 1


# ---------------------------------------------------------------------------
# etcd TestRestoreIgnoreSnapshot: a snapshot at or below the commit index is
# rejected (fast-acked instead).
# ---------------------------------------------------------------------------
def test_restore_ignores_stale_snapshot():
    db = logdb_with_terms(1, 1, 1)
    db.set_state(State(term=1, commit=3))
    r = new_test_raft(1, [1, 2], logdb=db)
    r.term = 1
    r.log.commit_to(3)
    ss = Snapshot(index=2, term=1,
                  membership=Membership(addresses={1: "a", 2: "b"}))
    assert r.restore(ss) is False
    assert r.log.committed == 3


# ---------------------------------------------------------------------------
# etcd TestSlowNodeRestore path: after restore the follower acks at the
# snapshot index so the leader can resume replication from there.
# ---------------------------------------------------------------------------
def test_follower_acks_snapshot_index_after_restore():
    r = new_test_raft(2, [1, 2])
    ss = Snapshot(index=7, term=3,
                  membership=Membership(addresses={1: "a", 2: "b"}))
    r.handle(Message(type=MT.INSTALL_SNAPSHOT, from_=1, to=2, term=3,
                     snapshot=ss))
    resp = [m for m in r.msgs if m.type == MT.REPLICATE_RESP][-1]
    assert resp.log_index == 7
    assert not resp.reject


# ---------------------------------------------------------------------------
# Unreachable / flow control (etcd TestMsgUnreachable).
# ---------------------------------------------------------------------------
def test_unreachable_resets_replicate_to_retry():
    from dragonboat_tpu.core.remote import RemoteState

    r = new_test_raft(1, [1, 2])
    make_leader(r)
    r.handle(Message(type=MT.PROPOSE, from_=1, to=1, entries=[Entry(cmd=b"x")]))
    rp = r.remotes[2]
    rp.become_replicate()
    r.handle(Message(type=MT.UNREACHABLE, from_=2, to=1))
    assert rp.state == RemoteState.RETRY


def test_snapshot_status_failure_enters_wait():
    from dragonboat_tpu.core.remote import RemoteState

    r = new_test_raft(1, [1, 2])
    make_leader(r)
    rp = r.remotes[2]
    rp.become_snapshot(9)
    r.handle(Message(type=MT.SNAPSHOT_STATUS, from_=2, to=1, reject=True))
    assert rp.state == RemoteState.WAIT
    assert rp.snapshot_index == 0  # cleared for retry


# ---------------------------------------------------------------------------
# Full-network integration matrices (etcd TestLeaderElection /
# TestLogReplication shapes).
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("n", [1, 3, 5])
def test_cluster_elects_exactly_one_leader(n):
    nt = make_cluster(n)
    nt.elect(1)
    leaders = [r for r in nt.rafts.values() if r.is_leader()]
    assert len(leaders) == 1 and leaders[0].node_id == 1


@pytest.mark.parametrize("proposer", [1, 2, 3])
def test_log_replication_from_any_node(proposer):
    nt = make_cluster(3)
    nt.elect(1)
    nt.propose(proposer, b"data")
    committed = {r.log.committed for r in nt.rafts.values()}
    assert len(committed) == 1
    for r in nt.rafts.values():
        ents = r.log.entries(1, 1 << 20)
        assert any(e.cmd == b"data" for e in ents)


def test_minority_partition_cannot_commit():
    nt = make_cluster(5)
    nt.elect(1)
    for nid in (4, 5):
        nt.isolate(nid)
    before = nt.rafts[1].log.committed
    nt.propose(1, b"maj")
    assert nt.rafts[1].log.committed == before + 1  # 3/5 still commits
    # now isolate down to a minority: no further commits
    nt.isolate(3)
    nt.isolate(2)
    before = nt.rafts[1].log.committed
    nt.propose(1, b"min")
    assert nt.rafts[1].log.committed == before


def test_partitioned_leader_rejoins_and_converges():
    nt = make_cluster(3)
    nt.elect(1)
    nt.isolate(1)
    nt.elect(2)  # majority side elects at a higher term
    assert nt.rafts[2].is_leader()
    nt.propose(2, b"while-partitioned")
    nt.heal()
    # old leader rejoins; new leader's heartbeat demotes it
    nt.send(Message(type=MT.HEARTBEAT, from_=2, to=1,
                    term=nt.rafts[2].term))
    nt.propose(2, b"after-heal")
    assert not nt.rafts[1].is_leader()
    assert terms_of(nt.rafts[1]) == terms_of(nt.rafts[2])
