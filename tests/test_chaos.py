"""Chaos-mode integration test (in-tree drummer-lite).

Mirrors the reference's monkey-test methodology (docs/test.md:11-33,
monkey.go): a 3-host loopback cluster runs client traffic while faults are
injected — transport message drops, full partitions of one host at a time,
and a NodeHost kill+restart from its durable dir. All fault decisions come
from ONE seeded FaultPlane (dragonboat_tpu/faults.py), printed at test
start: a CI failure replays by re-running with CHAOS_SEED=<printed seed>.
Invariants checked at the end (after fault injection stops and the cluster
settles):

  1. no linearizability violation in the recorded client history
  2. all replicas' state machines converge to the same content hash
  3. applied indexes converge

cf. SURVEY.md §4: "no linearizability violation, SMs in sync".
"""
import json
import os
import random
import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
from dragonboat_tpu.faults import FaultPlane, FaultSpec
from dragonboat_tpu.lincheck import HistoryRecorder, check_kv_history
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.requests import RequestError
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

CLUSTER = 1
HOSTS = (1, 2, 3)
KEYS = [f"k{i}" for i in range(4)]
SEED = int(os.environ.get("CHAOS_SEED", str(0xD5A60)), 0)


class HashKV(IStateMachine):
    """KV SM with a content hash (cf. internal/tests/kvtest.go sans delays)."""

    def __init__(self):
        self.d = {}

    def update(self, data):
        k, v = data.decode().split("=", 1)
        self.d[k] = v
        return Result(value=1)

    def lookup(self, q):
        return self.d.get(q)

    def get_hash(self):
        blob = json.dumps(sorted(self.d.items())).encode()
        import zlib

        return zlib.crc32(blob)

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read().decode())


def _mk_host(nid, reg, tmp, engine_kind="scalar"):
    cfg = NodeHostConfig(
        deployment_id=3, rtt_millisecond=5,
        nodehost_dir=f"{tmp}/h{nid}",
        raft_address=f"c{nid}:1",
        raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        engine=EngineConfig(
            kind=engine_kind, max_groups=32, max_peers=4, log_window=64
        ),
    )
    nh = NodeHost(cfg)
    members = {h: f"c{h}:1" for h in HOSTS}
    nh.start_cluster(
        members, False, lambda c, n: HashKV(),
        # election timeout must comfortably exceed the in-process 3-engine
        # message RTT even on a loaded CI machine, or elections split-vote
        # through the whole chaos window (cf. config.go RTT guidance)
        Config(
            cluster_id=CLUSTER, node_id=nid, election_rtt=20, heartbeat_rtt=4,
            snapshot_entries=50, compaction_overhead=10,
        ),
    )
    return nh


def _find_leader(hosts, deadline_s=20):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for nid, nh in list(hosts.items()):
            if nh is None:
                continue
            try:
                lid, ok = nh.get_leader_id(CLUSTER)
            except Exception:
                continue
            if ok and lid == nid and not nh.is_partitioned():
                return nid
        time.sleep(0.02)
    return None


@pytest.mark.slow
@pytest.mark.parametrize("engine_kind", ["scalar", "vector"])
def test_chaos_linearizable_and_converged(tmp_path, engine_kind):
    print(f"CHAOS SEED=0x{SEED:X} (replay: CHAOS_SEED=0x{SEED:X})")
    # ~30% outbound message drop while a drop window is armed on a victim
    fp = FaultPlane(SEED, FaultSpec(drop=0.3))
    reg = _Registry()
    hosts = {
        nid: _mk_host(nid, reg, str(tmp_path), engine_kind) for nid in HOSTS
    }
    rec = HistoryRecorder()
    stop = threading.Event()
    seq = [0]
    seq_mu = threading.Lock()

    def client_main(client_id):
        # per-thread RNG: the shared seed stays reproducible per client
        crng = random.Random(SEED + client_id)
        while not stop.is_set():
            leader = _find_leader(hosts, deadline_s=5)
            if leader is None:
                continue
            nh = hosts.get(leader)
            if nh is None:
                continue
            key = crng.choice(KEYS)
            if crng.random() < 0.6:
                with seq_mu:
                    seq[0] += 1
                    val = f"v{seq[0]}"
                op_id = rec.invoke(client_id, ("put", key, val))
                try:
                    s = nh.get_noop_session(CLUSTER)
                    nh.sync_propose(s, f"{key}={val}".encode(), timeout_s=2.0)
                    rec.complete(op_id, None)
                except RequestError:
                    rec.unknown(op_id)  # may or may not have applied
                except Exception as e:  # restart races (host stopping): also
                    # indeterminate, but surface unexpected types
                    print(f"chaos client: unexpected {type(e).__name__}: {e}")
                    rec.unknown(op_id)
            else:
                op_id = rec.invoke(client_id, ("get", key))
                try:
                    v = nh.sync_read(CLUSTER, key, timeout_s=2.0)
                    rec.complete(op_id, v)
                except RequestError:
                    rec.fail(op_id)  # reads have no side effect: drop
                except Exception as e:
                    print(f"chaos client: unexpected {type(e).__name__}: {e}")
                    rec.fail(op_id)
            time.sleep(crng.random() * 0.01)

    clients = [
        threading.Thread(target=client_main, args=(i,), daemon=True)
        for i in range(3)
    ]
    for t in clients:
        t.start()

    # -------- fault injection: drops, partitions, kill+restart ------------
    # every decision below draws from the FaultPlane's seeded "faultloop"
    # stream; the per-message drop schedule draws from the armed victim's
    # own "wire:h<N>" stream (single-threaded per transport worker)
    t_end = time.time() + 20
    while time.time() < t_end:
        fault = fp.choice(
            "faultloop", "fault", ["partition", "drop", "restart", "none"]
        )
        victim = fp.choice("faultloop", "victim", HOSTS)
        nh = hosts.get(victim)
        if nh is None:
            continue
        if fault == "partition":
            nh.set_partitioned(True)
            time.sleep(fp.uniform("faultloop", "window", 0.3, 0.8))
            nh2 = hosts.get(victim)
            if nh2 is not None:
                nh2.set_partitioned(False)
        elif fault == "drop":
            fp.install(nh, f"h{victim}")
            time.sleep(fp.uniform("faultloop", "window", 0.3, 0.8))
            nh2 = hosts.get(victim)
            if nh2 is not None:
                fp.uninstall(nh2)
        elif fault == "restart":
            hosts[victim] = None
            nh.stop()
            time.sleep(fp.uniform("faultloop", "window", 0.1, 0.3))
            hosts[victim] = _mk_host(victim, reg, str(tmp_path), engine_kind)
        else:
            time.sleep(0.3)

    # -------- settle & verify --------------------------------------------
    stop.set()
    for t in clients:
        t.join(timeout=5)
    fp.uninstall_all()
    for nid in HOSTS:
        if hosts[nid] is not None:
            hosts[nid].set_partitioned(False)
            hosts[nid].transport.set_pre_send_batch_hook(None)
        else:
            hosts[nid] = _mk_host(nid, reg, str(tmp_path), engine_kind)

    # one final write forces convergence of the commit index; leadership can
    # still be settling right after the fault phase, so retry across hosts
    deadline = time.time() + 60
    while True:
        leader = _find_leader(hosts, deadline_s=30)
        assert leader is not None, "cluster did not recover a leader"
        try:
            s = hosts[leader].get_noop_session(CLUSTER)
            hosts[leader].sync_propose(s, b"final=done", timeout_s=5.0)
            break
        except Exception:
            if time.time() > deadline:
                raise
            time.sleep(0.2)

    # wait for all replicas to apply to the same index
    deadline = time.time() + 30
    while time.time() < deadline:
        idx = {nid: hosts[nid].get_applied_index(CLUSTER) for nid in HOSTS}
        if len(set(idx.values())) == 1:
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"applied indexes never converged: {idx}")

    hashes = {nid: hosts[nid].get_sm_hash(CLUSTER) for nid in HOSTS}
    assert len(set(hashes.values())) == 1, f"replica SMs diverged: {hashes}"

    history = rec.history()
    n_ops = len(history)
    assert n_ops > 20, f"chaos run produced too few ops ({n_ops})"
    assert check_kv_history(history, max_states=5_000_000), (
        "linearizability violation in recorded history"
    )

    # invariant 4: persisted logs obey Log Matching below the common
    # commit point (cf. the reference monkeytest's logdb cross-check)
    from dragonboat_tpu.tools.logdbcheck import check_logdb_consistency

    report = check_logdb_consistency(
        {nid: hosts[nid].logdb for nid in HOSTS}, CLUSTER
    )
    assert report.ok, f"logdb consistency violations: {report.violations}"
    assert len(report.replicas) == len(HOSTS)

    for nh in hosts.values():
        nh.stop()
