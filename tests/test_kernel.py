"""Tests for the vectorized Raft kernel: protocol behavior on the loopback
simulation cluster, plus invariant checks across randomized runs."""
import numpy as np
import pytest

from dragonboat_tpu.ops import KernelConfig, ROLE
from dragonboat_tpu.ops.loopback import LoopbackCluster


def make(n=3, groups=2, **kw):
    return LoopbackCluster(n_replicas=n, n_groups=groups, **kw)


# ---------------------------------------------------------------- elections


def test_kernel_single_leader_emerges():
    c = make()
    c.run(30)
    for g in range(c.n_groups):
        roles = c.roles(g)
        assert roles.count(ROLE.LEADER) == 1, f"group {g}: {roles}"
        terms = c.field("term", g)
        assert len(set(terms)) == 1


def test_kernel_all_groups_elect_independently():
    c = make(groups=8)
    c.run(40)
    for g in range(8):
        assert c.leader_of(g) is not None


def test_kernel_leader_stable_after_election():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    term = c.field("term", 0)[lead]
    c.run(30)
    assert c.leader_of(0) == lead
    assert c.field("term", 0)[lead] == term  # no spurious re-elections


def test_kernel_reelection_after_leader_isolated():
    c = make()
    c.run(30)
    old = c.leader_of(0)
    c.isolated.add(old)
    c.run(35)
    survivors = [h for h in range(3) if h != old]
    new_leaders = [h for h in survivors if c.roles(0)[h] == ROLE.LEADER]
    assert len(new_leaders) == 1
    # heal: old leader rejoins and steps down
    c.isolated.clear()
    c.run(10)
    assert c.roles(0).count(ROLE.LEADER) == 1
    assert c.roles(0)[old] != ROLE.LEADER


# ---------------------------------------------------------------- replication


def test_kernel_propose_commits_everywhere():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    c.propose(lead, 0, n=3)
    c.run(3)
    commits = c.field("committed", 0)
    lasts = c.field("last_index", 0)
    assert len(set(commits)) == 1
    assert commits[0] == lasts[0] == 4  # noop + 3 proposals
    # log terms identical across replicas
    t0 = c.ring_terms(0, 0, 1, 4)
    assert t0 == c.ring_terms(1, 0, 1, 4) == c.ring_terms(2, 0, 1, 4)


def test_kernel_save_ranges_reported():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    c.propose(lead, 0, n=2)
    c.step(tick=False)
    out = c.last_outputs[lead]
    sf, st_ = int(np.asarray(out.save_from)[0]), int(np.asarray(out.save_to)[0])
    assert sf > 0 and st_ >= sf  # the two new entries must be persisted


def test_kernel_commit_requires_quorum():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    others = [h for h in range(3) if h != lead]
    c.isolated.update(others)  # leader alone: no quorum
    before = c.field("committed", 0)[lead]
    c.propose(lead, 0, n=1)
    for _ in range(5):
        c.step(tick=False)
    assert c.field("committed", 0)[lead] == before
    c.isolated.clear()
    c.run(3)
    assert c.field("committed", 0)[lead] == before + 1


def test_kernel_divergent_follower_converges():
    """A replica that accepted uncommitted entries from a deposed leader must
    overwrite them with the new leader's log (paper 5.3)."""
    c = make()
    c.run(30)
    old = c.leader_of(0)
    # strand proposals on the old leader only
    c.isolated.update(h for h in range(3) if h != old)
    c.propose(old, 0, n=3)
    for _ in range(3):
        c.step(tick=False)
    assert c.field("last_index", 0)[old] > c.field("committed", 0)[old]
    # partition flips: old leader cut off, others elect
    c.isolated.clear()
    c.isolated.add(old)
    c.run(35)
    new = [h for h in range(3) if h != old and c.roles(0)[h] == ROLE.LEADER][0]
    c.propose(new, 0, n=2)
    c.run(3)
    # heal; old leader must converge to the new log
    c.isolated.clear()
    c.run(12)
    lasts = c.field("last_index", 0)
    commits = c.field("committed", 0)
    assert len(set(commits)) == 1
    hi = commits[0]
    ref = c.ring_terms(new, 0, 1, hi)
    assert c.ring_terms(old, 0, 1, hi) == ref


def test_kernel_follower_catchup_from_empty():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    straggler = [h for h in range(3) if h != lead][0]
    c.isolated.add(straggler)
    for _ in range(4):
        c.propose(lead, 0, n=2)
        c.run(2)
    c.isolated.clear()
    c.run(12)
    assert c.field("last_index", 0)[straggler] == c.field("last_index", 0)[lead]
    assert c.field("committed", 0)[straggler] == c.field("committed", 0)[lead]


# ---------------------------------------------------------------- readindex


def test_kernel_readindex_quorum_roundtrip():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    c.read_index(lead, 0, ctx=4242)
    c.run(3)
    hits = [r for r in c.ready_reads[lead] if r[0] == 0 and r[1] == 4242]
    assert hits, f"no ready read: {c.ready_reads[lead]}"
    assert hits[0][2] == c.field("committed", 0)[lead]


def test_kernel_readindex_multiple_ctxs_fifo():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    c.read_index(lead, 0, ctx=11)
    c.read_index(lead, 0, ctx=12)
    c.run(4)
    ctxs = [r[1] for r in c.ready_reads[lead] if r[0] == 0]
    assert ctxs[:2] == [11, 12]


# ---------------------------------------------------------------- transfer


def test_kernel_leader_transfer():
    c = make()
    c.run(30)
    lead = c.leader_of(0)
    target = [h for h in range(3) if h != lead][0]
    c.transfer_leader(lead, 0, target)
    c.run(8)
    assert c.leader_of(0) == target
    assert c.roles(0)[lead] != ROLE.LEADER


# ---------------------------------------------------------------- witnesses


def test_kernel_witness_in_quorum():
    """2 full replicas + 1 witness: witness vote/ack counts toward quorum."""
    c = make(n=3, witnesses=(2,))
    c.run(40)
    lead = c.leader_of(0)
    assert lead in (0, 1)
    assert c.roles(0)[2] == ROLE.WITNESS
    # kill the other full replica: leader + witness still form a quorum
    other = 1 - lead
    c.isolated.add(other)
    before = c.field("committed", 0)[lead]
    c.propose(lead, 0, n=1)
    c.run(4)
    assert c.field("committed", 0)[lead] == before + 1


def test_kernel_observer_replicates_without_voting():
    c = make(n=3, observers=(2,))
    c.run(40)
    lead = c.leader_of(0)
    assert lead in (0, 1)
    assert c.roles(0)[2] == ROLE.OBSERVER
    c.propose(lead, 0, n=2)
    c.run(4)
    # observer received the data
    assert c.field("last_index", 0)[2] == c.field("last_index", 0)[lead]
    # but quorum is the 2 voting members: isolating the other full member
    # blocks commit even though the observer acks
    other = 1 - lead
    c.isolated.add(other)
    before = c.field("committed", 0)[lead]
    c.propose(lead, 0, n=1)
    c.run(4)
    assert c.field("committed", 0)[lead] == before


# ---------------------------------------------------------------- check quorum


def test_kernel_check_quorum_step_down():
    c = make(check_quorum=True)
    c.run(30)
    lead = c.leader_of(0)
    c.isolated.update(h for h in range(3) if h != lead)
    # two election periods without responses => step down
    for _ in range(25):
        c.step(tick=True)
    assert c.roles(0)[lead] != ROLE.LEADER


# ---------------------------------------------------------------- randomized


def test_kernel_randomized_chaos_invariants():
    """Random drops/partitions/proposals; at all times: at most one leader
    per term, committed prefixes never diverge, commit never regresses."""
    rng = np.random.default_rng(3)
    c = make(groups=2)
    c.run(30)
    max_commit = {g: 0 for g in range(2)}
    for it in range(60):
        # random link chaos
        c.dropped_links.clear()
        for _ in range(rng.integers(0, 3)):
            a, b = rng.integers(0, 3, 2)
            if a != b:
                c.dropped_links.add((int(a), int(b)))
        for g in range(2):
            lead = c.leader_of(g)
            if lead is not None and rng.random() < 0.7:
                c.propose(lead, g, n=int(rng.integers(1, 4)))
        c.step(tick=True)
        if rng.random() < 0.5:
            c.settle(5)
        for g in range(2):
            commits = c.field("committed", g)
            terms = c.field("term", g)
            # at most one leader per term
            lt = [
                (terms[h], h)
                for h in range(3)
                if c.roles(g)[h] == ROLE.LEADER
            ]
            assert len({t for t, _ in lt}) == len(lt), f"two leaders one term: {lt}"
            # committed prefix equality on the common committed prefix
            m = min(commits)
            if m >= 1:
                r0 = c.ring_terms(0, g, 1, m)
                assert r0 == c.ring_terms(1, g, 1, m) == c.ring_terms(2, g, 1, m)
            assert max(commits) >= max_commit[g]
            max_commit[g] = max(commits)
    # heal and converge
    c.dropped_links.clear()
    c.run(20)
    for g in range(2):
        assert len(set(c.field("committed", g))) == 1
