"""Storage layer tests: KV stores, sharded LogDB, LogReader window
(cf. internal/logdb/rdb_test.go, logreader_test.go patterns)."""
import os

import pytest

from dragonboat_tpu.core.logentry import ErrCompacted, ErrUnavailable
from dragonboat_tpu.raftio import ErrNoBootstrapInfo, ErrNoSavedLog
from dragonboat_tpu.storage import LogReader, MemKV, ShardedLogDB, WalKV, WriteBatch
from dragonboat_tpu.types import Bootstrap, Entry, Snapshot, State, Update


def mk_update(cid, nid, entries=(), state=None, snapshot=None):
    return Update(
        cluster_id=cid,
        node_id=nid,
        entries_to_save=list(entries),
        state=state or State(),
        snapshot=snapshot,
    )


def ent(index, term=1, cmd=b""):
    return Entry(index=index, term=term, cmd=cmd)


# ------------------------------------------------------------------- KV
def test_memkv_ordered_iteration():
    kv = MemKV()
    wb = WriteBatch()
    for i in (3, 1, 2, 9):
        wb.put(bytes([i]), b"v%d" % i)
    kv.commit_write_batch(wb)
    seen = []
    kv.iterate_value(b"\x01", b"\x09", False, lambda k, v: (seen.append(k), True)[1])
    assert seen == [b"\x01", b"\x02", b"\x03"]
    kv.iterate_value(b"\x01", b"\x09", True, lambda k, v: (seen.append(k), True)[1])
    assert seen[-1] == b"\x09"


def test_walkv_durability(tmp_path):
    d = str(tmp_path / "wal")
    kv = WalKV(d)
    wb = WriteBatch()
    wb.put(b"a", b"1")
    wb.put(b"b", b"2")
    kv.commit_write_batch(wb)
    wb2 = WriteBatch()
    wb2.delete(b"a")
    kv.commit_write_batch(wb2)
    kv.close()
    kv2 = WalKV(d)
    assert kv2.get_value(b"a") is None
    assert kv2.get_value(b"b") == b"2"
    kv2.close()


def test_walkv_torn_tail_discarded(tmp_path):
    d = str(tmp_path / "wal")
    kv = WalKV(d)
    wb = WriteBatch()
    wb.put(b"k1", b"v1")
    kv.commit_write_batch(wb)
    kv.close()
    # simulate a crash mid-append: garbage tail
    with open(os.path.join(d, "wal.log"), "ab") as f:
        f.write(b"\x40\x00\x00\x00\x00garbage")
    kv2 = WalKV(d)
    assert kv2.get_value(b"k1") == b"v1"
    kv2.close()


def test_walkv_compaction_preserves_data(tmp_path):
    d = str(tmp_path / "wal")
    kv = WalKV(d)
    wb = WriteBatch()
    for i in range(100):
        wb.put(b"k%03d" % i, b"v%d" % i)
    kv.commit_write_batch(wb)
    kv.full_compaction()
    wb2 = WriteBatch()
    wb2.put(b"post", b"compact")
    kv.commit_write_batch(wb2)
    kv.close()
    kv2 = WalKV(d)
    assert kv2.get_value(b"k050") == b"v50"
    assert kv2.get_value(b"post") == b"compact"
    kv2.close()


# ---------------------------------------------------------------- LogDB
@pytest.fixture(params=["mem", "wal"])
def logdb(request, tmp_path):
    if request.param == "mem":
        db = ShardedLogDB(num_shards=4)
    else:
        db = ShardedLogDB(str(tmp_path / "db"), num_shards=2, fsync=False)
    yield db
    db.close()


def test_logdb_save_read_state(logdb):
    st = State(term=3, vote=2, commit=5)
    logdb.save_raft_state(
        [mk_update(1, 1, [ent(i, 3) for i in range(1, 6)], state=st)]
    )
    rs = logdb.read_raft_state(1, 1, 0)
    assert rs.state == st
    assert rs.first_index == 1 and rs.entry_count == 5
    ents, size = logdb.iterate_entries(1, 1, 1, 6, 2**32)
    assert [e.index for e in ents] == [1, 2, 3, 4, 5]


def test_logdb_no_state_raises(logdb):
    with pytest.raises(ErrNoSavedLog):
        logdb.read_raft_state(9, 9, 0)


def test_logdb_entry_overwrite_suffix(logdb):
    # conflicting suffix overwrite: later save wins
    logdb.save_raft_state([mk_update(1, 1, [ent(i, 1) for i in range(1, 6)], State(term=1, commit=0))])
    logdb.save_raft_state([mk_update(1, 1, [ent(i, 2) for i in range(3, 5)], State(term=2, commit=0))])
    ents, _ = logdb.iterate_entries(1, 1, 1, 10, 2**32)
    # the batched layout's merge drops the stale suffix that shared the
    # rewritten batch (cf. batch.go:60-126: old entries survive only
    # below the rewrite point), so the stale term-1 entry 5 is GONE
    assert [(e.index, e.term) for e in ents] == [
        (1, 1), (2, 1), (3, 2), (4, 2)
    ]
    rs = logdb.read_raft_state(1, 1, 0)
    assert rs.entry_count == 4


def test_logdb_compaction(logdb):
    logdb.save_raft_state([mk_update(1, 1, [ent(i, 1) for i in range(1, 11)], State(term=1, commit=0))])
    logdb.remove_entries_to(1, 1, 5)
    ents, _ = logdb.iterate_entries(1, 1, 1, 11, 2**32)
    assert ents == [] or ents[0].index == 6
    ents6, _ = logdb.iterate_entries(1, 1, 6, 11, 2**32)
    assert [e.index for e in ents6] == [6, 7, 8, 9, 10]


def test_logdb_bootstrap(logdb):
    b = Bootstrap(addresses={1: "a:1"}, join=False, type=1)
    logdb.save_bootstrap_info(7, 1, b)
    got = logdb.get_bootstrap_info(7, 1)
    assert got == b
    with pytest.raises(ErrNoBootstrapInfo):
        logdb.get_bootstrap_info(7, 2)
    infos = logdb.list_node_info()
    assert any(i.cluster_id == 7 and i.node_id == 1 for i in infos)


def test_logdb_snapshots(logdb):
    ss = Snapshot(index=10, term=2, cluster_id=1, filepath="/s/10")
    u = mk_update(1, 1, snapshot=ss)
    logdb.save_snapshots([u])
    got = logdb.list_snapshots(1, 1, 2**62)
    assert len(got) == 1 and got[0].index == 10
    logdb.delete_snapshot(1, 1, 10)
    assert logdb.list_snapshots(1, 1, 2**62) == []


def test_logdb_remove_node_data(logdb):
    logdb.save_raft_state([mk_update(1, 1, [ent(1), ent(2)], State(term=1, commit=0))])
    logdb.save_bootstrap_info(1, 1, Bootstrap(addresses={1: "a"}))
    logdb.remove_node_data(1, 1)
    with pytest.raises(ErrNoSavedLog):
        logdb.read_raft_state(1, 1, 0)
    ents, _ = logdb.iterate_entries(1, 1, 1, 10, 2**32)
    assert ents == []


def test_logdb_multi_group_single_batch(logdb):
    ups = [
        mk_update(c, 1, [ent(1, 1, b"g%d" % c)], State(term=1, commit=0))
        for c in range(1, 9)
    ]
    logdb.save_raft_state(ups)
    for c in range(1, 9):
        ents, _ = logdb.iterate_entries(c, 1, 1, 2, 2**32)
        assert ents[0].cmd == b"g%d" % c


def test_logdb_restart_recovery(tmp_path):
    d = str(tmp_path / "db")
    db = ShardedLogDB(d, num_shards=2, fsync=False)
    db.save_raft_state(
        [mk_update(3, 2, [ent(i, 1) for i in range(1, 4)], State(term=1, vote=2, commit=2))]
    )
    db.close()
    db2 = ShardedLogDB(d, num_shards=2, fsync=False)
    rs = db2.read_raft_state(3, 2, 0)
    assert rs.state.vote == 2 and rs.entry_count == 3
    db2.close()


# -------------------------------------------------------------- LogReader
def test_logreader_window():
    db = ShardedLogDB(num_shards=1)
    lr = LogReader(1, 1, db)
    first, last = lr.get_range()
    assert (first, last) == (1, 0)
    ents = [ent(i, 1) for i in range(1, 6)]
    db.save_raft_state([mk_update(1, 1, ents, State(term=1, commit=0))])
    lr.append(ents)
    assert lr.get_range() == (1, 5)
    assert lr.term(3) == 1
    assert lr.entries(2, 6, 2**32)[0].index == 2
    with pytest.raises(ErrUnavailable):
        lr.term(6)


def test_logreader_compact_and_snapshot():
    db = ShardedLogDB(num_shards=1)
    lr = LogReader(1, 1, db)
    ents = [ent(i, 1) for i in range(1, 11)]
    db.save_raft_state([mk_update(1, 1, ents, State(term=1, commit=0))])
    lr.append(ents)
    lr.compact(5)
    with pytest.raises(ErrCompacted):
        lr.entries(4, 8, 2**32)
    assert lr.term(5) == 1  # marker term preserved
    assert lr.get_range() == (6, 10)
    ss = Snapshot(index=20, term=3)
    lr.apply_snapshot(ss)
    assert lr.get_range() == (21, 20)
    assert lr.term(20) == 3
    assert lr.snapshot().index == 20


def test_logreader_load_from_disk(tmp_path):
    d = str(tmp_path / "db")
    db = ShardedLogDB(d, num_shards=1, fsync=False)
    ents = [ent(i, 2) for i in range(1, 8)]
    db.save_raft_state([mk_update(5, 3, ents, State(term=2, vote=1, commit=6))])
    db.close()
    db2 = ShardedLogDB(d, num_shards=1, fsync=False)
    lr = LogReader(5, 3, db2)
    lr.load(None)
    st, _ = lr.node_state()
    assert st.commit == 6
    assert lr.get_range() == (1, 7)
    db2.close()


# ------------------------------------------------------ sqlite backend
def _kv_backends(tmp_path):
    from dragonboat_tpu.storage.sqlite_kv import SqliteKV

    return {
        "mem": MemKV(),
        "wal": WalKV(str(tmp_path / "wal")),
        "sqlite": SqliteKV(str(tmp_path / "sq")),
    }


def test_kv_contract_parity_across_backends(tmp_path):
    """Every IKVStore backend must agree on the ordered-KV contract
    (cf. kv.go:28-74 + the reference's kv_test.go run against each of
    rocksdb/leveldb/pebble)."""
    for name, kv in _kv_backends(tmp_path).items():
        wb = WriteBatch()
        for i in (5, 1, 3, 2, 9):
            wb.put(bytes([i]), b"v%d" % i)
        wb.delete(bytes([3]))
        kv.commit_write_batch(wb)
        assert kv.get_value(bytes([1])) == b"v1", name
        assert kv.get_value(bytes([3])) is None, name
        seen = []
        kv.iterate_value(bytes([1]), bytes([9]), False,
                         lambda k, v: (seen.append(k), True)[1])
        assert seen == [bytes([1]), bytes([2]), bytes([5])], name
        # range delete [1, 5)
        kv.bulk_remove_entries(bytes([1]), bytes([5]))
        assert kv.get_value(bytes([2])) is None, name
        assert kv.get_value(bytes([5])) == b"v5", name
        kv.close()


def test_sqlite_kv_durability(tmp_path):
    from dragonboat_tpu.storage.sqlite_kv import SqliteKV

    d = str(tmp_path / "sq")
    kv = SqliteKV(d)
    wb = WriteBatch()
    wb.put(b"alpha", b"1")
    wb.put(b"beta", b"2")
    kv.commit_write_batch(wb)
    kv.close()

    kv2 = SqliteKV(d)
    assert kv2.get_value(b"alpha") == b"1"
    assert kv2.get_value(b"beta") == b"2"
    kv2.full_compaction()
    assert kv2.get_value(b"beta") == b"2"
    kv2.close()


def test_sqlite_logdb_restart_recovery(tmp_path):
    """The full LogDB stack over the sqlite backend: save entries + state,
    reopen, read them back (mirrors test_logdb_restart_recovery)."""
    from dragonboat_tpu.storage.sqlite_kv import sqlite_logdb_factory

    d = str(tmp_path / "db")
    db = sqlite_logdb_factory(d, num_shards=2)
    db.save_raft_state([
        mk_update(1, 1, entries=[ent(i, term=2, cmd=b"x%d" % i)
                                 for i in range(1, 9)],
                  state=State(term=2, vote=3, commit=8)),
    ])
    db.close()

    db2 = sqlite_logdb_factory(d, num_shards=2)
    rs = db2.read_raft_state(1, 1, 0)
    assert rs.state.term == 2 and rs.state.commit == 8
    ents, _ = db2.iterate_entries(1, 1, 1, 9, 1 << 40)
    assert [e.index for e in ents] == list(range(1, 9))
    assert ents[3].cmd == b"x4"
    db2.close()


@pytest.mark.slow
def test_nodehost_on_sqlite_backend_restart(tmp_path):
    """A NodeHost running entirely on the sqlite LogDB backend via the
    logdb_factory seam (cf. config.go LogDBFactory): propose, restart,
    replay from sqlite."""
    import time

    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result
    from dragonboat_tpu.storage.sqlite_kv import sqlite_logdb_factory
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    class SM(IStateMachine):
        def __init__(self, *a):
            self.n = 0

        def update(self, data):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, fc, done):
            w.write(self.n.to_bytes(8, "little"))

        def recover_from_snapshot(self, r, fc, done):
            self.n = int.from_bytes(r.read(8), "little")

        def close(self):
            pass

    reg = _Registry()

    def mk(restart=False):
        nh = NodeHost(NodeHostConfig(
            deployment_id=55, rtt_millisecond=5, raft_address="sq1:1",
            nodehost_dir=str(tmp_path / "nh"),
            logdb_factory=lambda d: sqlite_logdb_factory(d, num_shards=2),
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
        ))
        nh.start_cluster({} if restart else {1: "sq1:1"}, False,
                         lambda c, n: SM(),
                         Config(cluster_id=1, node_id=1, election_rtt=20,
                                heartbeat_rtt=2))
        return nh

    nh = mk()
    deadline = time.time() + 60
    while time.time() < deadline:
        _, ok = nh.get_leader_id(1)
        if ok:
            break
        time.sleep(0.02)
    assert ok
    s = nh.get_noop_session(1)
    for _ in range(12):
        nh.sync_propose(s, b"x", timeout_s=5.0)
    # the seam really selected sqlite: its database files are on disk
    # (NodeHost namespaces its dir by raft address: nh/<addr>/logdb-sqlite)
    assert os.path.exists(
        str(tmp_path / "nh" / "sq1-1" / "logdb-sqlite" / "shard-0"
            / "logdb.sqlite")
    )
    nh.stop()

    nh = mk(restart=True)
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if nh.stale_read(1, None) == 12:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        assert nh.stale_read(1, None) == 12
    finally:
        nh.stop()


def test_compaction_append_race_keeps_tail_entries():
    """remove_entries_to's boundary-batch rewrite vs a concurrent tail
    append (snapshot worker vs step worker): the rewrite is a
    read-modify-write of the batch record the append path is extending,
    and an unserialized interleaving wrote the pre-append content back —
    silently deleting just-appended entries (restart replay then stalls at
    the hole with commit far ahead). The barrier KV below parks the
    remover on its boundary read while an append commits; with the shard
    writer lock the two serialize and no entry is lost in either order."""
    import threading

    from dragonboat_tpu.storage.logdb import _Shard

    class RaceKV(MemKV):
        def __init__(self):
            super().__init__()
            self.hold = threading.Event()
            self.resume = threading.Event()
            self.armed = False

        def get_value(self, key):
            v = super().get_value(key)
            if self.armed and threading.current_thread().name == "remover":
                self.armed = False
                self.hold.set()
                self.resume.wait(0.5)
            return v

    kv = RaceKV()
    sh = _Shard(kv)
    B = sh.BATCH

    def save(lo, hi):
        ents = [Entry(index=i, term=1, cmd=b"x") for i in range(lo, hi + 1)]
        sh.save_raft_state(
            [
                Update(
                    cluster_id=1,
                    node_id=1,
                    state=State(term=1, vote=1, commit=hi),
                    entries_to_save=ents,
                )
            ]
        )

    # fill past two batch boundaries so the compaction cut lands inside a
    # batch record that is ALSO the append tail
    last = 2 * B + B // 2 + 1  # e.g. B=8 -> 21
    save(1, last)
    cut = 2 * B + 1  # boundary batch [2B .. 3B-1] straddles the cut
    kv.armed = True
    t = threading.Thread(
        target=lambda: sh.remove_entries_to(1, 1, cut), name="remover"
    )
    t.start()
    assert kv.hold.wait(5)
    save(last + 1, last + 2)  # tail append into the same boundary batch
    kv.resume.set()
    t.join(5)
    assert not t.is_alive()
    ents, _ = sh.iterate_entries(1, 1, cut + 1, last + 3, 1 << 30)
    assert [e.index for e in ents] == list(range(cut + 1, last + 3))
