"""Keep examples/ honest: helloworld must run end to end (real TCP,
election, proposals, follower read, transfer, outage, restart)."""
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_helloworld_example(tmp_path):
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "helloworld.py")],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(tmp_path),  # its data dir lands here, not in the repo
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "HELLOWORLD PASS" in proc.stdout
