"""Native C++ WAL KV backend tests (native/walkv.cc via ctypes).

Mirrors the reference's kv backend test surface
(internal/logdb/kv/kv_test.go style: batch commit, iteration bounds, range
delete, compaction, reopen/recovery) plus format interop with the
pure-Python WalKV.
"""
import os

import pytest

from dragonboat_tpu.storage.kv import WalKV, WriteBatch
from dragonboat_tpu.storage.native_kv import NativeWalKV, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


def test_put_get_delete(tmp_path):
    kv = NativeWalKV(str(tmp_path / "kv"))
    kv.put_value(b"a", b"1")
    kv.put_value(b"b", b"2")
    assert kv.get_value(b"a") == b"1"
    assert kv.get_value(b"missing") is None
    kv.delete_value(b"a")
    assert kv.get_value(b"a") is None
    assert kv.count() == 1
    kv.close()


def test_batch_atomic_and_empty_values(tmp_path):
    kv = NativeWalKV(str(tmp_path / "kv"))
    wb = WriteBatch()
    wb.put(b"k1", b"")
    wb.put(b"k2", b"v" * 4096)
    wb.delete(b"k1")
    kv.commit_write_batch(wb)
    assert kv.get_value(b"k1") is None
    assert kv.get_value(b"k2") == b"v" * 4096
    kv.close()


def test_iterate_bounds(tmp_path):
    kv = NativeWalKV(str(tmp_path / "kv"))
    for i in range(10):
        kv.put_value(bytes([i]), str(i).encode())
    seen = []
    kv.iterate_value(bytes([2]), bytes([5]), False, lambda k, v: (seen.append(k), True)[1])
    assert seen == [bytes([2]), bytes([3]), bytes([4])]
    seen = []
    kv.iterate_value(bytes([2]), bytes([5]), True, lambda k, v: (seen.append(k), True)[1])
    assert seen == [bytes([2]), bytes([3]), bytes([4]), bytes([5])]
    # early stop
    seen = []
    kv.iterate_value(bytes([0]), bytes([9]), True, lambda k, v: (seen.append(k), len(seen) < 2)[1])
    assert len(seen) == 2
    kv.close()


def test_range_delete(tmp_path):
    kv = NativeWalKV(str(tmp_path / "kv"))
    for i in range(10):
        kv.put_value(bytes([i]), b"x")
    kv.bulk_remove_entries(bytes([3]), bytes([7]))
    left = []
    kv.iterate_value(bytes([0]), bytes([9]), True, lambda k, v: (left.append(k[0]), True)[1])
    assert left == [0, 1, 2, 7, 8, 9]
    kv.close()


def test_reopen_recovers(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeWalKV(d)
    for i in range(100):
        kv.put_value(f"key-{i:04d}".encode(), f"val-{i}".encode())
    kv.bulk_remove_entries(b"key-0000", b"key-0050")
    kv.close()

    kv2 = NativeWalKV(d)
    assert kv2.get_value(b"key-0049") is None
    assert kv2.get_value(b"key-0050") == b"val-50"
    assert kv2.count() == 50
    kv2.close()


def test_compaction_preserves_state(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeWalKV(d)
    for i in range(50):
        kv.put_value(f"k{i:03d}".encode(), b"v" * 100)
    kv.full_compaction()
    # WAL truncated, table.log holds the image
    assert os.path.getsize(os.path.join(d, "wal.log")) == 0
    assert os.path.getsize(os.path.join(d, "table.log")) > 0
    kv.put_value(b"after", b"compact")
    kv.close()

    kv2 = NativeWalKV(d)
    assert kv2.count() == 51
    assert kv2.get_value(b"k049") == b"v" * 100
    assert kv2.get_value(b"after") == b"compact"
    kv2.close()


def test_torn_tail_discarded(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeWalKV(d)
    kv.put_value(b"good", b"1")
    kv.put_value(b"alsogood", b"2")
    kv.close()
    # corrupt the tail: chop bytes off the last record
    path = os.path.join(d, "wal.log")
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 3)
    kv2 = NativeWalKV(d)
    assert kv2.get_value(b"good") == b"1"
    assert kv2.get_value(b"alsogood") is None
    kv2.close()


def test_interop_python_reads_native(tmp_path):
    d = str(tmp_path / "kv")
    kv = NativeWalKV(d)
    for i in range(20):
        kv.put_value(f"n{i}".encode(), f"v{i}".encode())
    kv.delete_value(b"n3")
    kv.close()

    py = WalKV(d)
    assert py.get_value(b"n4") == b"v4"
    assert py.get_value(b"n3") is None
    py.close()


def test_interop_native_reads_python(tmp_path):
    d = str(tmp_path / "kv")
    py = WalKV(d)
    for i in range(20):
        py.put_value(f"p{i}".encode(), f"v{i}".encode())
    py.full_compaction()
    py.put_value(b"tail", b"wal")
    py.close()

    kv = NativeWalKV(d)
    assert kv.get_value(b"p7") == b"v7"
    assert kv.get_value(b"tail") == b"wal"
    kv.close()


def test_logdb_over_native_kv(tmp_path):
    """ShardedLogDB accepts the native store through its kv_factory seam."""
    from dragonboat_tpu.storage.logdb import ShardedLogDB
    from dragonboat_tpu.types import Entry, EntryType, State, Update

    db = ShardedLogDB(
        dirname=str(tmp_path / "db"),
        kv_factory=lambda d: NativeWalKV(d),
    )
    ud = Update(
        cluster_id=7,
        node_id=1,
        state=State(term=3, vote=2, commit=1),
        entries_to_save=[
            Entry(type=EntryType.APPLICATION, index=1, term=3, cmd=b"x"),
            Entry(type=EntryType.APPLICATION, index=2, term=3, cmd=b"y"),
        ],
    )
    db.save_raft_state([ud])
    ents, _ = db.iterate_entries(7, 1, 1, 3, 1 << 30)
    assert [e.index for e in ents] == [1, 2]
    st = db.read_raft_state(7, 1, 0)
    assert st.state.term == 3
    db.close()


def test_logdb_reopen_native(tmp_path):
    from dragonboat_tpu.storage.logdb import ShardedLogDB
    from dragonboat_tpu.types import Entry, EntryType, State, Update

    d = str(tmp_path / "db")
    db = ShardedLogDB(dirname=d, kv_factory=lambda p: NativeWalKV(p))
    ud = Update(
        cluster_id=1,
        node_id=1,
        state=State(term=2, vote=1, commit=5),
        entries_to_save=[
            Entry(type=EntryType.APPLICATION, index=i, term=2, cmd=b"z")
            for i in range(1, 6)
        ],
    )
    db.save_raft_state([ud])
    db.close()

    db2 = ShardedLogDB(dirname=d, kv_factory=lambda p: NativeWalKV(p))
    ents, _ = db2.iterate_entries(1, 1, 1, 6, 1 << 30)
    assert len(ents) == 5
    db2.close()


def test_segmented_compaction_roll_and_replay(tmp_path):
    """Round-3 segmented compaction: sealing the WAL is an O(1) rename;
    state survives restart across table.log + segments + live WAL."""
    d = str(tmp_path / "seg")
    kv = NativeWalKV(d)
    for i in range(20):
        kv.put_value(b"k%03d" % i, b"v%d" % i)
    assert kv.segment_count() == 0
    kv.roll_segment()
    assert kv.segment_count() == 1
    for i in range(20, 40):
        kv.put_value(b"k%03d" % i, b"v%d" % i)
    kv.delete_value(b"k001")
    kv.roll_segment()
    assert kv.segment_count() == 2
    kv.put_value(b"tail", b"t")
    kv.close()
    # restart: replay table + segments + wal in order
    kv2 = NativeWalKV(d)
    assert kv2.get_value(b"k000") == b"v0"
    assert kv2.get_value(b"k001") is None
    assert kv2.get_value(b"k039") == b"v39"
    assert kv2.get_value(b"tail") == b"t"
    assert kv2.segment_count() == 2
    kv2.close()


def test_segment_tier_merge_bounds_segment_count(tmp_path):
    """Crossing the segment bound merges the oldest tier; live data
    survives, deletions from newer segments still apply on replay."""
    d = str(tmp_path / "tier")
    kv = NativeWalKV(d)
    for round_ in range(12):
        for i in range(8):
            kv.put_value(b"r%02d-%d" % (round_, i), b"x" * 32)
        if round_ == 5:
            kv.delete_value(b"r00-0")
        kv.roll_segment()
    # force the tier merge through the maybe-compact path: one pending op
    # crosses threshold=1, rolls the WAL, and segment_count > 8 merges the
    # oldest half into ONE compacted segment
    kv.put_value(b"final", b"y")
    before = kv.segment_count()
    kv.maybe_compact(threshold=1)
    assert kv.segment_count() < before
    kv.close()
    kv2 = NativeWalKV(d)
    assert kv2.get_value(b"r00-0") is None
    assert kv2.get_value(b"r00-1") == b"x" * 32
    assert kv2.get_value(b"r11-7") == b"x" * 32
    assert kv2.get_value(b"final") == b"y"
    kv2.close()


def test_full_compaction_clears_segments(tmp_path):
    d = str(tmp_path / "full")
    kv = NativeWalKV(d)
    for i in range(30):
        kv.put_value(b"f%03d" % i, b"v")
        if i % 10 == 9:
            kv.roll_segment()
    assert kv.segment_count() == 3
    kv.full_compaction()
    assert kv.segment_count() == 0
    kv.close()
    kv2 = NativeWalKV(d)
    assert kv2.count() == 30
    assert kv2.get_value(b"f029") == b"v"
    kv2.close()
