"""Forensic observability tests (ISSUE 4): causal trace propagation, the
crash-persistent mmap flight ring, server-side recorder filtering, and the
post-crash recovery path.

The heavyweight acceptance scenarios live here too:

  * a 3-node shared-core vector cluster under a seeded FaultPlane
    partition schedule, whose merged per-node dumps reconstruct one
    sampled proposal's causal chain (propose -> replicate -> quorum ->
    apply) across >= 2 nodes keyed by a single trace id;
  * a subprocess NodeHost SIGKILL'd mid-chaos whose recovered mmap ring
    still holds the last leader-change and fault-injection events in
    order.
"""
import json
import os
import signal
import struct
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from dragonboat_tpu.tools import timeline
from dragonboat_tpu.trace import (
    FlightRecorder,
    MmapRing,
    flight_recorder,
    mint_trace_id,
    read_mmap_ring,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# trace ids
# ---------------------------------------------------------------------------


def test_mint_trace_id_unique_and_compact():
    ids = {mint_trace_id() for _ in range(1000)}
    assert len(ids) == 1000
    assert all(0 < i < 2**64 for i in ids)
    # one process's ids share the salt (merging keys on the full u64)
    assert len({i >> 32 for i in ids}) == 1


def test_entry_and_message_carry_trace_id_on_the_wire():
    from dragonboat_tpu.codec import (
        decode_entry,
        decode_message,
        encode_entry,
        encode_message,
    )
    from dragonboat_tpu.types import Entry, Message, MessageType

    tid = mint_trace_id()
    e = Entry(term=3, index=9, cmd=b"k=v", trace_id=tid)
    got, _ = decode_entry(encode_entry(e))
    assert got.trace_id == tid
    m = Message(
        type=MessageType.REPLICATE, cluster_id=2, to=2, from_=1,
        term=3, trace_id=tid, entries=[e],
    )
    gm, _ = decode_message(encode_message(m))
    assert gm.trace_id == tid
    assert gm.entries[0].trace_id == tid
    # unsampled default stays zero
    assert decode_entry(encode_entry(Entry(cmd=b"x")))[0].trace_id == 0


# ---------------------------------------------------------------------------
# recorder filtering + mandatory cluster field (server-side dump filters)
# ---------------------------------------------------------------------------


def test_recorder_events_carry_mandatory_cluster_field():
    rec = FlightRecorder(capacity=16)
    rec.record("host_level_thing", addr="a:1")
    rec.record("group_thing", cluster=7, node=1)
    d = rec.dump()
    assert all("cluster" in e for e in d)
    assert d[0]["cluster"] == 0  # host-level default
    assert d[1]["cluster"] == 7


def test_recorder_dump_filters():
    rec = FlightRecorder(capacity=64)
    t1, t2 = mint_trace_id(), mint_trace_id()
    rec.record("propose_enqueue", cluster=1, node=1, trace=t1)
    rec.record("propose_enqueue", cluster=2, node=1, trace=t2)
    rec.record("quorum_commit", cluster=2, node=1, trace=t2)
    rec.record("breaker_open", addr="x:1")
    assert len(rec.dump()) == 4
    assert [e["cluster"] for e in rec.dump(cluster_id=2)] == [2, 2]
    assert [e["event"] for e in rec.dump(trace_id=t2)] == [
        "propose_enqueue", "quorum_commit",
    ]
    assert len(rec.dump(event="breaker_open")) == 1
    assert rec.dump(cluster_id=2, event="quorum_commit")[0]["trace"] == t2


def test_dump_atomic_vs_concurrent_record():
    """Satellite: list(deque) during concurrent mutation can raise
    RuntimeError under free-threaded runs — dump() must snapshot
    atomically (retry loop). Two-thread hammer: one floods record(),
    the other dumps continuously; no exception may escape."""
    rec = FlightRecorder(capacity=128)
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        while not stop.is_set():
            rec.record("hammer", i=i)
            i += 1

    def reader():
        try:
            for _ in range(500):
                for e in rec.dump():
                    assert e["event"] == "hammer"
                rec.to_jsonl(meta={"source": "hammer"})
        except Exception as exc:  # pragma: no cover - the regression
            errs.append(exc)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        reader()
    finally:
        stop.set()
        t.join(timeout=5)
    assert not errs, errs


# ---------------------------------------------------------------------------
# mmap ring
# ---------------------------------------------------------------------------


def test_mmap_ring_roundtrip_and_wraparound(tmp_path):
    path = str(tmp_path / "r.ring")
    ring = MmapRing(path, capacity=8, slot_size=256)
    for i in range(11):  # wraps: only the last 8 survive
        ring.write(json.dumps({"t": i / 10, "event": "e", "i": i}).encode())
    ring.close()
    meta, events = read_mmap_ring(path)
    assert [e["i"] for e in events] == list(range(3, 11))
    assert "mono_offset" in meta


def test_mmap_ring_survives_torn_and_unsealed_slots(tmp_path):
    path = str(tmp_path / "torn.ring")
    ring = MmapRing(path, capacity=8, slot_size=128)
    for i in range(5):
        ring.write(json.dumps({"event": "e", "i": i}).encode())
    ring.close()
    hdr = 64
    with open(path, "r+b") as f:
        # slot 2: seal present but payload garbage (torn mid-write)
        f.seek(hdr + 2 * 128 + 12)
        f.write(b"\xff\xfegarbage")
        # slot 3: unsealed (the write a SIGKILL interrupted)
        f.seek(hdr + 3 * 128)
        f.write(struct.pack("<Q", 0))
    _meta, events = read_mmap_ring(path)
    assert [e["i"] for e in events] == [0, 1, 4]  # the rest stays valid


def test_recorder_tees_into_attached_ring(tmp_path):
    path = str(tmp_path / "tee.ring")
    rec = FlightRecorder(capacity=32)
    rec.attach_mmap(path, capacity=16, slot_size=256)
    try:
        rec.record("leader_changed", cluster=3, node=1, leader=2, term=5)
        rec.record("fault_injected", site="wire:x", kind="drop")
        # attach is idempotent for the same path (NodeHost + harness)
        r1 = rec.attach_mmap(path)
        assert r1 is rec._ring
    finally:
        rec.detach_mmap()
    _meta, events = read_mmap_ring(path)
    assert [e["event"] for e in events] == ["leader_changed", "fault_injected"]
    assert events[0]["cluster"] == 3 and events[1]["cluster"] == 0


def test_mmap_ring_oversized_event_degrades_to_marker(tmp_path):
    """An event bigger than a slot must survive recovery as a JSON-safe
    `_truncated` marker (when/what/which group), never as a dropped
    torn slot."""
    path = str(tmp_path / "big.ring")
    ring = MmapRing(path, capacity=8, slot_size=256)
    big = {"t": 1.5, "event": "_test_start", "cluster": 0,
           "nodeid": "x" * 50, "noise": "y" * 500}
    ring.write(json.dumps(big).encode())
    ring.write(json.dumps({"t": 2.0, "event": "small", "cluster": 0}).encode())
    ring.close()
    _meta, events = read_mmap_ring(path)
    assert [e["event"] for e in events] == ["_test_start", "small"]
    assert events[0]["_truncated"] is True
    assert events[0]["t"] == 1.5 and events[0]["nodeid"] == "x" * 50
    assert "noise" not in events[0]
    # a tiny slot sheds progressively but still keeps when/what
    tiny = str(tmp_path / "tiny.ring")
    ring = MmapRing(tiny, capacity=4, slot_size=80)
    ring.write(json.dumps(big).encode())
    ring.close()
    _meta, events = read_mmap_ring(tiny)
    assert len(events) == 1
    assert events[0]["event"] == "_test_start"
    assert events[0]["_truncated"] is True


def test_attach_rotates_previous_crash_ring(tmp_path):
    """Satellite/review fix: a restart's auto-attach (env var, session
    ring) must NOT truncate the previous — possibly SIGKILL'd — process's
    timeline; the old ring rotates to <path>.prev and stays readable."""
    path = str(tmp_path / "r.ring")
    crashed = FlightRecorder(capacity=8)
    crashed.attach_mmap(path, capacity=8, slot_size=256)
    crashed.record("leader_changed", cluster=1, node=1, leader=1, term=2)
    crashed.detach_mmap()  # stand-in for the process dying
    restarted = FlightRecorder(capacity=8)
    restarted.attach_mmap(path, capacity=8, slot_size=256)
    try:
        restarted.record("fresh_event")
    finally:
        restarted.detach_mmap()
    _m, prev = read_mmap_ring(path + ".prev")
    assert [e["event"] for e in prev] == ["leader_changed"]
    _m, cur = read_mmap_ring(path)
    assert [e["event"] for e in cur] == ["fresh_event"]


def test_session_ring_covers_timeout_kills():
    """Satellite: the conftest-attached session ring must already hold this
    test's `_test_start` marker — the mechanism that leaves a readable
    artifact when pytest-timeout / `timeout -k` SIGKILLs the run before
    any JSONL failure dump can be written."""
    rec = flight_recorder()
    ring = rec._ring
    if ring is None:
        pytest.skip("session ring not attached (FLIGHT_RING_PATH unset?)")
    rec.flush()
    _meta, events = read_mmap_ring(ring.path)
    markers = [
        e for e in events
        if e.get("event") == "_test_start"
        and "test_session_ring_covers_timeout_kills" in str(e.get("nodeid"))
    ]
    assert markers, "session ring is missing this test's _test_start marker"


# ---------------------------------------------------------------------------
# post-crash recovery: SIGKILL a NodeHost mid-chaos, recover the ring
# ---------------------------------------------------------------------------

_CHILD = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result
    from dragonboat_tpu.transport.loopback import _Registry, loopback_factory

    class SM(IStateMachine):
        def __init__(self):
            self.v = 0
        def update(self, data):
            self.v += 1
            return Result(value=self.v)
        def lookup(self, q):
            return self.v
        def save_snapshot(self, w, files, done):
            w.write(b"0")
        def recover_from_snapshot(self, r, files, done):
            pass

    reg = _Registry()
    nh = NodeHost(NodeHostConfig(
        deployment_id=1, rtt_millisecond=5, raft_address="kill1:1",
        raft_rpc_factory=lambda l: loopback_factory(l, reg),
        engine=EngineConfig(kind="scalar", max_groups=4, max_peers=4),
    ))
    nh.start_cluster(
        {{1: "kill1:1"}}, False, lambda c, n: SM(),
        Config(cluster_id=1, node_id=1, election_rtt=10, heartbeat_rtt=2),
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        lid, ok = nh.get_leader_id(1)
        if ok:
            break
        time.sleep(0.02)
    else:
        print("NOLEADER", flush=True)
        sys.exit(2)
    # mid-chaos: a fired fault lands in the ring after the leader change
    from dragonboat_tpu.faults import FaultPlane, FaultSpec
    fp = FaultPlane(99, FaultSpec(drop=1.0))
    assert fp.decide("kill:wire", "drop", 1.0)
    print("READY", flush=True)
    time.sleep(120)  # parent SIGKILLs us here
    """
)


def test_sigkilled_nodehost_leaves_recoverable_ring(tmp_path):
    ring_path = str(tmp_path / "crash.ring")
    script = tmp_path / "child.py"
    script.write_text(_CHILD.format(repo=REPO))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DRAGONBOAT_FLIGHT_RING"] = ring_path
    p = subprocess.Popen(
        [sys.executable, str(script)],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        env=env,
        text=True,
    )
    try:
        line = ""
        deadline = time.time() + 90
        while time.time() < deadline:
            line = p.stdout.readline()
            if "READY" in line or "NOLEADER" in line or not line:
                break
        assert "READY" in line, f"child never came up: {line!r}"
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=10)
    # recover the dead process's timeline through the NodeHost path
    from dragonboat_tpu.nodehost import NodeHost

    events = NodeHost.recover_flight_ring(ring_path)
    kinds = [e["event"] for e in events]
    assert "leader_changed" in kinds, kinds
    assert "fault_injected" in kinds, kinds
    # the LAST leader change (node 1 won its own election) precedes the
    # fault injection in the recovered order
    last_lead = max(i for i, k in enumerate(kinds) if k == "leader_changed")
    first_fault = kinds.index("fault_injected")
    assert last_lead < first_fault
    lead = events[last_lead]
    assert lead["cluster"] == 1 and lead["leader"] == 1
    # and the timeline CLI renders the recovered ring as an ordered view
    merged = timeline.merge_dumps([ring_path])
    assert [e["event"] for e in merged] == kinds
    ts = [e["_tw"] for e in merged]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# end-to-end causal chain: 3 nodes, partition seed, merged dumps
# ---------------------------------------------------------------------------

CLUSTER = 2
HOSTS = (1, 2, 3)


def _mk_host(nid, reg, tmp, scope):
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import loopback_factory

    nh = NodeHost(
        NodeHostConfig(
            deployment_id=31,
            rtt_millisecond=5,
            nodehost_dir=f"{tmp}/h{nid}",
            raft_address=f"ca{nid}:1",
            raft_rpc_factory=lambda l, reg=reg: loopback_factory(l, reg),
            engine=EngineConfig(
                kind="vector",
                max_groups=16,
                max_peers=4,
                log_window=64,
                share_scope=scope,
                profile_sample_ratio=1,  # sample (and trace) EVERY request
            ),
        )
    )
    nh.start_cluster(
        {h: f"ca{h}:1" for h in HOSTS},
        False,
        lambda c, n: _kvsm(),
        Config(
            cluster_id=CLUSTER,
            node_id=nid,
            election_rtt=20,
            heartbeat_rtt=4,
            snapshot_entries=0,
        ),
    )
    return nh


def _kvsm():
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class KV(IStateMachine):
        def __init__(self):
            self.d = {}

        def update(self, data):
            k, v = data.decode().split("=", 1)
            self.d[k] = v
            return Result(value=1)

        def lookup(self, q):
            return self.d.get(q)

        def save_snapshot(self, w, files, done):
            w.write(json.dumps(self.d).encode())

        def recover_from_snapshot(self, r, files, done):
            self.d = json.loads(r.read().decode())

    return KV()


def _wait_leader(hosts, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        for nid, nh in hosts.items():
            lid, ok = nh.get_leader_id(CLUSTER)
            if ok and lid == nid:
                return nid
        time.sleep(0.02)
    return None


def test_e2e_causal_chain_across_nodes_under_partition(tmp_path):
    from dragonboat_tpu.faults import FaultPlane, FaultSpec
    from dragonboat_tpu.transport.loopback import _Registry

    seed = int(os.environ.get("CHAOS_SEED", "1789"), 0)
    print(f"CHAOS SEED={seed} (replay: CHAOS_SEED={seed})")
    fp = FaultPlane(seed, FaultSpec())
    reg = _Registry()
    rec = flight_recorder()
    hosts = {
        nid: _mk_host(nid, reg, str(tmp_path), f"causal-{seed}")
        for nid in HOSTS
    }
    try:
        assert _wait_leader(hosts) is not None, "no leader elected"
        # seeded partition windows (the chaos context the timeline must
        # survive), then heal and wait for a stable leader again
        for victim, window, idle in fp.partition_schedule(
            "causal", HOSTS, total_s=1.2, min_window_s=0.1, max_window_s=0.3
        ):
            hosts[victim].set_partitioned(True)
            time.sleep(window)
            hosts[victim].set_partitioned(False)
            time.sleep(idle)
        for nh in hosts.values():
            nh.set_partitioned(False)
        deadline = time.monotonic() + 45
        committed = False
        while not committed and time.monotonic() < deadline:
            leader = _wait_leader(hosts, 30.0)
            if leader is None:
                continue
            nh = hosts[leader]
            try:
                nh.sync_propose(
                    nh.get_noop_session(CLUSTER), b"causal=1", timeout_s=5.0
                )
                committed = True
            except Exception:
                time.sleep(0.1)
        assert committed, "no proposal committed after heal"
        time.sleep(0.3)  # let trailing ack/apply events land

        # per-node dumps, exactly as N separate hosts would produce them
        events = rec.dump(cluster_id=CLUSTER)
        paths = []
        for nid in HOSTS:
            p = str(tmp_path / f"node{nid}.jsonl")
            with open(p, "w") as f:
                f.write(
                    json.dumps(
                        {
                            "event": "_meta",
                            "mono_offset": rec.mono_offset,
                            "source": f"n{nid}",
                        }
                    )
                    + "\n"
                )
                for e in events:
                    if e.get("node") == nid:
                        f.write(json.dumps(e, sort_keys=True) + "\n")
            paths.append(p)

        merged = timeline.merge_dumps(paths)
        chains = timeline.causal_chains(merged)
        assert chains, "no trace-stamped events survived the run"
        need = (
            "propose_enqueue", "replicate_send", "quorum_commit",
            "proposal_applied",
        )
        good = None
        for tid, evs in chains.items():
            stages = [e["event"] for e in evs]
            nodes = {e.get("node") for e in evs}
            if not all(s in stages for s in need) or len(nodes) < 2:
                continue
            pos = [stages.index(s) for s in need]
            if pos == sorted(pos):
                good = tid
                break
        assert good is not None, (
            "no causal chain with >=4 ordered stages across >=2 nodes; "
            f"chains: { {hex(t): [e['event'] for e in c] for t, c in chains.items()} }"
        )
        # the CLI renders the chain
        import io
        from contextlib import redirect_stdout

        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = timeline.main(paths + ["--chains", "--trace", hex(good)])
        assert rc == 0
        out = buf.getvalue()
        assert f"trace {good:#x}" in out
        assert "propose_enqueue" in out and "quorum_commit" in out
    finally:
        for nh in hosts.values():
            nh.stop()
