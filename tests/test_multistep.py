"""Device-resident multi-step engine: K protocol steps per kernel launch.

Three layers of coverage (test_fanout_columnar.py style — every fast path
is compared against a straightforward per-element reference):

  1. route_step_output fuzz — the kernel's on-device co-hosted routing
     (stable-sort slot assignment, per-type field translation, overflow
     fallback) must match a per-element numpy reference that mirrors the
     host path's dispatch order and _pack_wire's per-type staging,
     across randomized StepOutputs, routes and window-base deltas.

  2. super-step differential — multi_step_batch over K inner steps must
     produce BYTE-IDENTICAL protocol state, per-step output planes (the
     send set and save directives), route plans and residual inbox to K
     sequential step_batch calls glued by the reference router, across
     seeded traffic that includes an election completing mid-window, a
     leader change mid-window and a config-change entry committing
     mid-window.

  3. live engine e2e at steps_per_sync=4 — a 3-replica shared-core
     cluster elects, commits, serves forwarded reads, moves ZERO host
     Message objects for co-hosted traffic, and (the `-m perf` gate at
     K>1) performs zero out-of-seam device syncs with a measured
     steps-per-sync ratio of K and no steady-state retraces.
"""
from __future__ import annotations

import os
import random
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dragonboat_tpu.ops.kernel import (
    make_multi_step_fn,
    make_step_fn,
    route_step_output,
    step_batch,
)
from dragonboat_tpu.ops.state import (
    CTR,
    MSG,
    SEND_HEARTBEAT,
    SEND_REPLICATE,
    SEND_TIMEOUT_NOW,
    SEND_VOTE_REQ,
    Inbox,
    KernelConfig,
    StepOutput,
    configure_group,
    init_state,
    make_empty_inbox,
)

KCFG = KernelConfig(
    groups=6, peers=4, log_window=32, inbox_depth=4,
    max_entries_per_msg=4, readindex_depth=4,
)


# ---------------------------------------------------------------------------
# per-element reference router (mirrors host dispatch order + _pack_wire)
# ---------------------------------------------------------------------------


def _empty_inbox_np(cfg):
    G, K, E = cfg.groups, cfg.inbox_depth, cfg.max_entries_per_msg
    return {
        "mtype": np.full((G, K), MSG.NONE, np.int32),
        "from_slot": np.zeros((G, K), np.int32),
        "term": np.zeros((G, K), np.int32),
        "log_index": np.zeros((G, K), np.int32),
        "log_term": np.zeros((G, K), np.int32),
        "commit": np.zeros((G, K), np.int32),
        "reject": np.zeros((G, K), bool),
        "hint": np.zeros((G, K), np.int32),
        "hint_high": np.zeros((G, K), np.int32),
        "n_entries": np.zeros((G, K), np.int32),
        "entry_terms": np.zeros((G, K, E), np.int32),
        "entry_cc": np.zeros((G, K, E), bool),
    }


def _ref_route(s, o, route, rdelta, cfg):
    """Reference routing: candidates in the host decode's dispatch order
    (Replicates, votes, heartbeats, TimeoutNow, response plane,
    forwarded-read responses; row-major within each kind), FIFO'd into
    each destination lane's K inbox slots with _pack_wire's per-type
    field staging. Returns (next inbox planes, routed masks)."""
    G, P = route.shape
    K = cfg.inbox_depth
    R = cfg.readindex_depth
    W = cfg.log_window
    nxt = _empty_inbox_np(cfg)
    counts = [0] * G
    masks = {
        "rep": np.zeros((G, P), bool),
        "vote": np.zeros((G, P), bool),
        "hb": np.zeros((G, P), bool),
        "tn": np.zeros((G, P), bool),
        "resp": np.zeros((G, K), bool),
        "rir": np.zeros((G, R), bool),
    }
    self_slot = np.asarray(s.self_slot)
    log_term = np.asarray(s.log_term)
    log_cc = np.asarray(s.log_is_cc)
    term = o["term"]

    def stage(d, mtype, from_slot, term, log_index=0, log_term_=0,
              commit=0, reject=False, hint=0, hint_high=0, n_entries=0,
              entry_terms=(), entry_cc=()):
        k = counts[d]
        if k >= K:
            return False
        counts[d] = k + 1
        nxt["mtype"][d, k] = mtype
        nxt["from_slot"][d, k] = from_slot
        nxt["term"][d, k] = term
        nxt["log_index"][d, k] = log_index
        nxt["log_term"][d, k] = log_term_
        nxt["commit"][d, k] = commit
        nxt["reject"][d, k] = reject
        nxt["hint"][d, k] = hint
        nxt["hint_high"][d, k] = hint_high
        nxt["n_entries"][d, k] = n_entries
        for i, t in enumerate(entry_terms):
            nxt["entry_terms"][d, k, i] = t
        for i, c in enumerate(entry_cc):
            nxt["entry_cc"][d, k, i] = c
        return True

    flags = o["send_flags"]
    for g in range(G):
        for p in range(P):
            d = route[g, p]
            if d < 0 or not (flags[g, p] & SEND_REPLICATE):
                continue
            delta = int(rdelta[g, p])
            prev = int(o["send_prev_index"][g, p])
            n = int(o["send_n_entries"][g, p])
            terms = [int(log_term[g, (prev + 1 + i) % W]) for i in range(n)]
            ccs = [bool(log_cc[g, (prev + 1 + i) % W]) for i in range(n)]
            if stage(
                d, MSG.REPLICATE, int(self_slot[g]), int(term[g]),
                log_index=prev + delta,
                log_term_=int(o["send_prev_term"][g, p]),
                commit=max(int(o["send_commit"][g, p]) + delta, 0),
                n_entries=n, entry_terms=terms, entry_cc=ccs,
            ):
                masks["rep"][g, p] = True
    for g in range(G):
        for p in range(P):
            d = route[g, p]
            if d < 0 or not (flags[g, p] & SEND_VOTE_REQ):
                continue
            if stage(
                d, MSG.REQUEST_VOTE, int(self_slot[g]), int(term[g]),
                log_index=int(o["vote_last_index"][g]) + int(rdelta[g, p]),
                log_term_=int(o["vote_last_term"][g]),
                hint=int(o["send_hint"][g, p]),
            ):
                masks["vote"][g, p] = True
    for g in range(G):
        for p in range(P):
            d = route[g, p]
            if d < 0 or not (flags[g, p] & SEND_HEARTBEAT):
                continue
            if stage(
                d, MSG.HEARTBEAT, int(self_slot[g]), int(term[g]),
                # the lease round tag rides log_index UNTRANSLATED (an
                # opaque tick stamp, not an index — no rdelta)
                log_index=int(o["lease_round"][g]),
                commit=max(
                    int(o["send_hb_commit"][g, p]) + int(rdelta[g, p]), 0
                ),
                hint=int(o["send_hint"][g, p]),
                hint_high=int(o["send_hint2"][g, p]),
            ):
                masks["hb"][g, p] = True
    for g in range(G):
        for p in range(P):
            d = route[g, p]
            if d < 0 or not (flags[g, p] & SEND_TIMEOUT_NOW):
                continue
            if stage(d, MSG.TIMEOUT_NOW, int(self_slot[g]), int(term[g])):
                masks["tn"][g, p] = True
    for g in range(G):
        for k in range(K):
            t = int(o["resp_type"][g, k])
            if t == MSG.NONE:
                continue
            to = int(o["resp_to"][g, k])
            if to < 0 or to >= P or to == int(self_slot[g]):
                continue
            d = route[g, to]
            if d < 0:
                continue
            delta = int(rdelta[g, to])
            rej = bool(o["resp_reject"][g, k])
            if t == MSG.REPLICATE_RESP:
                if rej and int(o["resp_hint"][g, k]) + delta < 0:
                    continue  # below-window reject stays host-side
                ok = stage(
                    d, t, int(self_slot[g]), int(o["resp_term"][g, k]),
                    log_index=int(o["resp_log_index"][g, k]) + delta,
                    reject=rej,
                    hint=max(int(o["resp_hint"][g, k]) + delta, 0),
                )
            elif t == MSG.REQUEST_VOTE_RESP:
                ok = stage(
                    d, t, int(self_slot[g]), int(o["resp_term"][g, k]),
                    reject=rej,
                )
            elif t == MSG.HEARTBEAT_RESP:
                ok = stage(
                    d, t, int(self_slot[g]), int(o["resp_term"][g, k]),
                    # echoes the lease round tag untranslated (no delta)
                    log_index=int(o["resp_log_index"][g, k]),
                    hint=int(o["resp_hint"][g, k]),
                    hint_high=int(o["resp_hint2"][g, k]),
                )
            else:  # NOOP
                ok = stage(
                    d, t, int(self_slot[g]), int(o["resp_term"][g, k])
                )
            if ok:
                masks["resp"][g, k] = True
    for g in range(G):
        for r in range(int(o["ready_count"][g])):
            ctx = int(o["ready_ctx"][g, r])
            if ctx == 0:
                continue
            origin = (ctx >> 24) - 1
            if origin < 0 or origin == int(self_slot[g]) or origin >= P:
                continue
            d = route[g, origin]
            if d < 0:
                continue
            if stage(
                d, MSG.READ_INDEX_RESP, int(self_slot[g]), int(term[g]),
                log_index=int(o["ready_index"][g, r]) + int(rdelta[g, origin]),
                hint=ctx, hint_high=int(o["ready_ctx2"][g, r]),
            ):
                masks["rir"][g, r] = True
    return nxt, masks


# ---------------------------------------------------------------------------
# 1. route_step_output fuzz vs the reference
# ---------------------------------------------------------------------------


def _rng_i32(rng, shape, lo, hi):
    n = int(np.prod(shape))
    return np.asarray(
        [rng.randint(lo, hi) for _ in range(n)], np.int32
    ).reshape(shape)


def _random_state_and_output(rng):
    G, P, K = KCFG.groups, KCFG.peers, KCFG.inbox_depth
    R, E, W = KCFG.readindex_depth, KCFG.max_entries_per_msg, KCFG.log_window
    s = init_state(KCFG)
    s = s._replace(
        self_slot=jnp.asarray(_rng_i32(rng, (G,), 0, P - 1)),
        log_term=jnp.asarray(_rng_i32(rng, (G, W), 1, 5)),
        log_is_cc=jnp.asarray(_rng_i32(rng, (G, W), 0, 1).astype(bool)),
    )
    z = dict.fromkeys(StepOutput._fields)
    flag_choices = (
        0, 0, SEND_REPLICATE, SEND_HEARTBEAT, SEND_VOTE_REQ,
        SEND_TIMEOUT_NOW, SEND_REPLICATE | SEND_HEARTBEAT,
        SEND_VOTE_REQ | SEND_TIMEOUT_NOW,
    )
    resp_choices = (
        int(MSG.NONE), int(MSG.NONE), int(MSG.REPLICATE_RESP),
        int(MSG.REQUEST_VOTE_RESP), int(MSG.HEARTBEAT_RESP), int(MSG.NOOP),
    )
    flags = np.asarray(
        [[rng.choice(flag_choices) for _ in range(P)] for _ in range(G)],
        np.int32,
    )
    resp_type = np.asarray(
        [[rng.choice(resp_choices) for _ in range(K)] for _ in range(G)],
        np.int32,
    )
    ready_count = _rng_i32(rng, (G,), 0, R)
    ready_ctx = np.asarray(
        [
            [
                rng.choice([0, ((rng.randint(1, P)) << 24) | rng.randint(0, 99)])
                for _ in range(R)
            ]
            for _ in range(G)
        ],
        np.int32,
    )
    o = dict(
        send_flags=flags,
        send_prev_index=_rng_i32(rng, (G, P), 0, W - E - 2),
        send_prev_term=_rng_i32(rng, (G, P), 0, 5),
        send_n_entries=_rng_i32(rng, (G, P), 0, E),
        send_commit=_rng_i32(rng, (G, P), 0, W - 2),
        send_hb_commit=_rng_i32(rng, (G, P), 0, W - 2),
        send_hint=_rng_i32(rng, (G, P), 0, 1 << 20),
        send_hint2=_rng_i32(rng, (G, P), 0, 1 << 20),
        vote_last_index=_rng_i32(rng, (G,), 0, W - 2),
        vote_last_term=_rng_i32(rng, (G,), 0, 5),
        term=_rng_i32(rng, (G,), 1, 6),
        resp_type=resp_type,
        resp_to=_rng_i32(rng, (G, K), 0, P - 1),
        resp_term=_rng_i32(rng, (G, K), 1, 6),
        resp_log_index=_rng_i32(rng, (G, K), 0, W - 2),
        resp_reject=_rng_i32(rng, (G, K), 0, 1).astype(bool),
        resp_hint=_rng_i32(rng, (G, K), 0, W - 2),
        resp_hint2=_rng_i32(rng, (G, K), 0, 1 << 20),
        ready_count=ready_count,
        ready_ctx=ready_ctx,
        ready_ctx2=_rng_i32(rng, (G, R), 0, 1 << 20),
        ready_index=_rng_i32(rng, (G, R), 0, W - 2),
        # opaque lease round tag: rides heartbeat log_index untranslated
        lease_round=_rng_i32(rng, (G,), 0, 1 << 16),
    )
    for f in StepOutput._fields:
        if z[f] is None and f not in o:
            # planes the router never reads: zero-filled with the right
            # shape so the NamedTuple constructs
            shape = {
                "save_from": (KCFG.groups,), "save_to": (KCFG.groups,),
                "apply_from": (KCFG.groups,), "apply_to": (KCFG.groups,),
                "commit_index": (KCFG.groups,),
                "hard_changed": (KCFG.groups,),
                "dropped_propose": (KCFG.groups,),
                "dropped_cc": (KCFG.groups,),
                "fwd_leader": (KCFG.groups,),
                "noop_appended": (KCFG.groups,),
                "noop_term": (KCFG.groups,),
                "log_full": (KCFG.groups,),
                "prop_base": (KCFG.groups, K),
                "rep_base": (KCFG.groups, K),
                "leader": (KCFG.groups,), "vote": (KCFG.groups,),
                "role": (KCFG.groups,),
                "match": (KCFG.groups, P), "rstate": (KCFG.groups, P),
                "last_index": (KCFG.groups,),
                "quiesced": (KCFG.groups,),
                "lease_round": (KCFG.groups,),
                "lease_ok": (KCFG.groups,),
                "lease_served": (KCFG.groups,),
                "lease_fallback": (KCFG.groups,),
                "counters": (KCFG.groups, CTR.COUNT),
            }[f]
            o[f] = np.zeros(shape, np.int32)
    out = StepOutput(**{f: jnp.asarray(o[f]) for f in StepOutput._fields})
    return s, o, out


@pytest.mark.parametrize("seed", range(8))
def test_route_matches_reference(seed):
    rng = random.Random(4000 + seed)
    G, P = KCFG.groups, KCFG.peers
    s, o_np, out = _random_state_and_output(rng)
    route = np.full((G, P), -1, np.int32)
    rdelta = np.zeros((G, P), np.int32)
    self_slot = np.asarray(s.self_slot)
    for g in range(G):
        for p in range(P):
            if p == self_slot[g]:
                continue
            if rng.random() < 0.6:
                route[g, p] = rng.randrange(G)
                rdelta[g, p] = rng.choice([0, 0, 0, 2, -2, -40])
    nxt, plan = route_step_output(
        s, out, jnp.asarray(route), jnp.asarray(rdelta), KCFG
    )
    nxt = jax.device_get(nxt)._asdict()
    plan = {k: np.asarray(v) for k, v in jax.device_get(plan)._asdict().items()}
    ref_nxt, ref_masks = _ref_route(s, o_np, route, rdelta, KCFG)
    for k in ref_masks:
        assert np.array_equal(plan[k], ref_masks[k]), (seed, k)
    for k in ref_nxt:
        assert np.array_equal(np.asarray(nxt[k]), ref_nxt[k]), (seed, k)
    # the trial must exercise the router
    assert sum(int(m.sum()) for m in ref_masks.values()) > 0


# ---------------------------------------------------------------------------
# 2. super-step differential: multi_step_batch vs K sequential steps
# ---------------------------------------------------------------------------


def _cluster_state():
    """3 co-hosted replicas of cluster A on lanes 0/1/2 (slots 0/1/2),
    plus a single-voter lane 3 (different cluster: never routed) and a
    partial cluster whose third replica is 'cross-host' (lane 4 routes to
    lane 5 but slot 2 routes nowhere)."""
    s = init_state(KCFG)
    for g, slot in ((0, 0), (1, 1), (2, 2)):
        s = configure_group(
            s, g, slot, (0, 1, 2), election_timeout=10, heartbeat_timeout=2
        )
    s = configure_group(s, 3, 0, (0,), election_timeout=10)
    for g, slot in ((4, 0), (5, 1)):
        s = configure_group(
            s, g, slot, (0, 1, 2), election_timeout=10, heartbeat_timeout=2
        )
    G, P = KCFG.groups, KCFG.peers
    route = np.full((G, P), -1, np.int32)
    for g, slot in ((0, 0), (1, 1), (2, 2)):
        for p, pg in ((0, 0), (1, 1), (2, 2)):
            if pg != g:
                route[g, p] = pg
    route[4, 1] = 5
    route[5, 0] = 4  # slot 2 of lanes 4/5 is cross-host: stays -1
    rdelta = np.zeros((G, P), np.int32)
    return s, route, rdelta


def _merge_inbox(resid, host):
    out = {}
    occ = resid["mtype"] != MSG.NONE
    for k in resid:
        m = occ
        while m.ndim < resid[k].ndim:
            m = m[..., None]
        out[k] = np.where(m, resid[k], host[k])
    return out


def _jnp_inbox(planes):
    return Inbox(**{k: jnp.asarray(v) for k, v in planes.items()})


def _host_events(window, counts):
    """Seeded host events per super-step boundary, placed at the first
    free slot after the residual rows (exactly like _pack). Scenario:
    window 0 elects lane 0; window 1 proposes (incl. a config-change
    entry that commits MID-window via routed replication); window 2
    campaigns lane 1 — a leader change whose vote handshake and
    step-down land mid-window."""
    host = _empty_inbox_np(KCFG)

    def put(g, **fields):
        k = counts[g]
        assert k < KCFG.inbox_depth, "scenario overflowed the inbox"
        counts[g] += 1
        for name, v in fields.items():
            if name in ("entry_terms", "entry_cc"):
                for i, x in enumerate(v):
                    host[name][g, k, i] = x
            else:
                host[name][g, k] = v

    if window == 0:
        put(0, mtype=MSG.ELECTION)
        put(3, mtype=MSG.ELECTION)
        put(4, mtype=MSG.ELECTION)
    elif window == 1:
        # lane 0 is leader of cluster A by now: a 2-entry proposal and a
        # lone config-change proposal (the host invariant packs ccs alone)
        put(0, mtype=MSG.PROPOSE, from_slot=0, n_entries=2)
        put(
            0, mtype=MSG.PROPOSE, from_slot=0, n_entries=1,
            entry_cc=(True,),
        )
        put(3, mtype=MSG.PROPOSE, from_slot=0, n_entries=3)
    elif window == 2:
        put(1, mtype=MSG.ELECTION)  # leader change mid-window
    elif window == 3:
        # the NEW leader serves proposals after the mid-window change
        put(1, mtype=MSG.PROPOSE, from_slot=1, n_entries=1)
    return host


def _np_tree(x):
    return jax.tree.map(np.asarray, jax.device_get(x))


def test_superstep_differential():
    """A K-step super-step must be byte-identical to K sequential
    one-step kernel calls glued by the reference router: final protocol
    state, every per-step output plane (send set + save directives),
    the route plans and the carried residual inbox."""
    steps = 4
    windows = 4
    G = KCFG.groups
    s_multi, route, rdelta = _cluster_state()
    s_seq = jax.tree.map(lambda x: x, s_multi)  # same initial values
    multi = make_multi_step_fn(KCFG, steps, donate=False)
    step = make_step_fn(KCFG, donate=False)
    route_j, rdelta_j = jnp.asarray(route), jnp.asarray(rdelta)
    ticks = jnp.zeros((G,), jnp.int32)

    resid_np = _empty_inbox_np(KCFG)  # seq side's carried residual
    resid_multi = make_empty_inbox(KCFG)
    for window in range(windows):
        counts = [
            int((resid_np["mtype"][g] != MSG.NONE).sum()) for g in range(G)
        ]
        host = _host_events(window, counts)
        # ---- multi path: one kernel launch -------------------------------
        s_multi, outs, plans, resid_multi, rc = multi(
            s_multi, _jnp_inbox(host), ticks, resid_multi, route_j, rdelta_j
        )
        outs = _np_tree(outs)._asdict()
        plans = _np_tree(plans)._asdict()
        rc = np.asarray(jax.device_get(rc))
        # ---- seq path: K steps + reference routing -----------------------
        inbox = _merge_inbox(resid_np, host)
        for t in range(steps):
            tk = ticks  # all-zero either way; ticks enter step 0 only
            s_seq, out = step(s_seq, _jnp_inbox(inbox), tk)
            o = _np_tree(out)._asdict()
            nxt, masks = _ref_route(s_seq, o, route, rdelta, KCFG)
            for k in o:
                assert np.array_equal(outs[k][t], o[k]), (window, t, k)
            for k in masks:
                assert np.array_equal(plans[k][t], masks[k]), (window, t, k)
            inbox = nxt
        resid_np = inbox
        # residual + state must match bit for bit
        rm = _np_tree(resid_multi)._asdict()
        for k in resid_np:
            assert np.array_equal(rm[k], resid_np[k]), (window, k)
        exp_rc = (resid_np["mtype"] != MSG.NONE).sum(axis=1)
        assert np.array_equal(rc, exp_rc), window
        sm = _np_tree(s_multi)._asdict()
        sq = _np_tree(s_seq)._asdict()
        for k in sm:
            assert np.array_equal(sm[k], sq[k]), (window, k)

    # the scenario really exercised what it claims: cluster A elected in
    # window 0, committed entries (incl. the cc) mid-window in window 1,
    # and changed leader in window 2
    final = _np_tree(s_multi)._asdict()
    assert final["leader"][0] == 2  # lane 1 (slot 1) led after window 2
    assert final["term"][0] == 2
    # noop + 2 props + cc + new-term noop + post-change proposal
    assert final["committed"][1] >= 6
    assert final["committed"][3] >= 4  # the never-routed lane progressed too


def test_superstep_counters_exact_sum_at_k8():
    """The counter plane sums EXACTLY across inner steps at K=8: the
    cumulative fold an engine keeps from one K=8 launch (sum over the
    stacked (K, G, CTR.COUNT) output, the _decode_super path) equals the
    fold from 8 sequential one-step launches glued by the reference
    router — no event lost or double-counted at any launch boundary."""
    steps = 8
    G = KCFG.groups
    s_multi, route, rdelta = _cluster_state()
    s_seq = jax.tree.map(lambda x: x, s_multi)
    multi = make_multi_step_fn(KCFG, steps, donate=False)
    step = make_step_fn(KCFG, donate=False)
    route_j, rdelta_j = jnp.asarray(route), jnp.asarray(rdelta)
    ticks = jnp.zeros((G,), jnp.int32)
    resid_np = _empty_inbox_np(KCFG)
    resid_multi = make_empty_inbox(KCFG)
    tot_multi = np.zeros((G, CTR.COUNT), np.uint64)
    tot_seq = np.zeros((G, CTR.COUNT), np.uint64)
    for window in range(3):
        counts = [
            int((resid_np["mtype"][g] != MSG.NONE).sum()) for g in range(G)
        ]
        host = _host_events(window, counts)
        s_multi, outs, plans, resid_multi, rc = multi(
            s_multi, _jnp_inbox(host), ticks, resid_multi, route_j, rdelta_j
        )
        ctr = np.asarray(jax.device_get(outs.counters))
        assert ctr.shape == (steps, G, CTR.COUNT)
        assert ctr.dtype == np.uint32
        tot_multi += ctr.astype(np.uint64).sum(axis=0)
        inbox = _merge_inbox(resid_np, host)
        for _t in range(steps):
            s_seq, out = step(s_seq, _jnp_inbox(inbox), ticks)
            o = _np_tree(out)._asdict()
            tot_seq += o["counters"].astype(np.uint64)
            inbox, _masks = _ref_route(s_seq, o, route, rdelta, KCFG)
        resid_np = inbox
        assert np.array_equal(tot_multi, tot_seq), window
    # the scenario moved what it claims: window 0 elected lane 0, window
    # 1 committed proposals, window 2 handed leadership to lane 1
    assert int(tot_multi[0, CTR.ELECTIONS_WON]) >= 1
    assert int(tot_multi[1, CTR.ELECTIONS_WON]) >= 1
    assert int(tot_multi[:, CTR.COMMIT_ADVANCES].sum()) > 0


def test_superstep_consumes_residual_without_host_work():
    """Routed messages parked in the residual must drive the next
    super-step even when the host packs nothing (the engine's skip path
    dispatches a residual-only super-step)."""
    steps = 2
    s, route, rdelta = _cluster_state()
    multi = make_multi_step_fn(KCFG, steps, donate=False)
    route_j, rdelta_j = jnp.asarray(route), jnp.asarray(rdelta)
    ticks = jnp.zeros((KCFG.groups,), jnp.int32)
    host = _empty_inbox_np(KCFG)
    host["mtype"][0, 0] = MSG.ELECTION
    resid = make_empty_inbox(KCFG)
    s, outs, plans, resid, rc = multi(
        s, _jnp_inbox(host), ticks, resid, route_j, rdelta_j
    )
    # with K=2 the vote responses are still in flight: carried as residual
    assert int(np.asarray(jax.device_get(rc)).sum()) > 0
    empty = _empty_inbox_np(KCFG)
    for _ in range(3):
        s, outs, plans, resid, rc = multi(
            s, _jnp_inbox(empty), ticks, resid, route_j, rdelta_j
        )
    assert int(np.asarray(s.leader)[0]) == 1  # election completed
    assert int(np.asarray(s.committed)[0]) >= 1


# ---------------------------------------------------------------------------
# 3. live engine e2e at steps_per_sync=4
# ---------------------------------------------------------------------------


class _CounterSM:
    pass


def _make_sm_cls():
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class SM(IStateMachine):
        def __init__(self, cluster_id, node_id):
            self.n = 0

        def update(self, data):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, fc, done):
            w.write(self.n.to_bytes(8, "little"))

        def recover_from_snapshot(self, r, fc, done):
            self.n = int.from_bytes(r.read(8), "little")

        def close(self):
            pass

    return SM


def _bring_up(tmp_path, scope, k, members):
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.transport.loopback import loopback_factory, _Registry

    reg = _Registry()
    sm_cls = _make_sm_cls()
    hosts = {}
    for nid, addr in members.items():
        cfg = NodeHostConfig(
            raft_address=addr,
            rtt_millisecond=10,
            nodehost_dir=str(tmp_path / f"nh-{scope}-{nid}"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(
                kind="vector", max_groups=8, max_peers=4, log_window=64,
                inbox_depth=8, max_entries_per_msg=8, share_scope=scope,
                steps_per_sync=k,
            ),
        )
        hosts[nid] = NodeHost(cfg)
    for nid in members:
        hosts[nid].start_clusters([
            (
                dict(members), False,
                lambda c, n: sm_cls(c, n),
                Config(
                    node_id=nid, cluster_id=1, election_rtt=20,
                    heartbeat_rtt=2,
                ),
            )
        ])
    deadline = time.monotonic() + 120
    lead = 0
    while time.monotonic() < deadline:
        lid, ok = hosts[1].get_leader_id(1)
        if ok and lid:
            lead = lid
            break
        time.sleep(0.02)
    assert lead, "no leader elected"
    return hosts, lead


@pytest.mark.perf
def test_multistep_engine_e2e(tmp_path):
    """K=4 shared-core cluster: commits, forwarded reads, ZERO host
    Message objects for co-hosted traffic, one blessed sync per K steps,
    zero out-of-seam syncs, zero steady-state retraces."""
    from dragonboat_tpu.profile import compile_watch, sync_audit

    members = {1: "ms4:1", 2: "ms4:2", 3: "ms4:3"}
    hosts, lead = _bring_up(tmp_path, "test-multistep4", 4, members)
    try:
        core = hosts[1].engine.core
        assert core._multi == 4
        assert core._overlap is False  # super-steps replace overlap
        sess = hosts[lead].get_noop_session(1)
        # warm steady state, then mark the audit window
        for i in range(5):
            assert hosts[lead].propose(sess, b"warm%d" % i, 10).wait(10)
        sync_mark = sync_audit().snapshot()
        compile_mark = compile_watch().snapshot()
        stats_mark = core.step_stats()
        ok = 0
        for i in range(30):
            r = hosts[lead].propose(sess, b"x%d" % i, timeout_s=10).wait(10)
            if r is not None and r.completed:
                ok += 1
        assert ok == 30
        # forwarded linearizable read from a follower host: the routed
        # READ_INDEX / READ_INDEX_RESP round trip
        fol = [n for n in members if n != lead][0]
        r = hosts[fol].read_index(1, 10).wait(10)
        assert r is not None and r.completed
        stats = core.step_stats()
        # zero host Messages for co-hosted traffic in the whole window
        for key in ("msgs_replicate", "msgs_broadcast", "msgs_resp"):
            assert stats[key] == stats_mark[key], (key, stats)
        assert stats["msgs_routed_device"] > stats_mark["msgs_routed_device"]
        # one blessed sync per K protocol steps, nothing out of seam
        from dragonboat_tpu.profile import diff_sync

        d = diff_sync(sync_mark, sync_audit().snapshot())
        assert d["in_seam"] > 0
        assert d["engine_steps"] == 4 * d["in_seam"]
        bad = {
            s: n
            for s, n in sync_audit().out_of_seam_in_package().items()
        }
        assert not bad, bad
        # steady state compiles nothing (the scanned kernel is warm)
        from dragonboat_tpu.profile import diff_compiles

        dc = diff_compiles(compile_mark, compile_watch().snapshot())
        assert not dc["per_function"], dc
    finally:
        for nh in hosts.values():
            nh.stop()


@pytest.mark.slow
def test_multistep_matches_k1_outcome(tmp_path):
    """The same workload through a K=1 and a K=4 cluster converges to
    the same applied SM state (the engine-level half of the
    differential: the kernel-level one proves byte equality, this one
    proves the host decode orchestration commits the same history)."""
    results = {}
    for k, scope, members in (
        (1, "test-ms-k1", {1: "msk1:1", 2: "msk1:2", 3: "msk1:3"}),
        (4, "test-ms-k4", {1: "msk4:1", 2: "msk4:2", 3: "msk4:3"}),
    ):
        hosts, lead = _bring_up(tmp_path, scope, k, members)
        try:
            sess = hosts[lead].get_noop_session(1)
            vals = []
            for i in range(40):
                r = hosts[lead].propose(sess, b"p%d" % i, 10).wait(10)
                assert r is not None and r.completed, (k, i)
                vals.append(r.result.value)
            results[k] = vals
        finally:
            for nh in hosts.values():
                nh.stop()
    assert results[1] == results[4]
