"""Headline benchmark: vectorized Raft kernel proposal throughput.

Regime from BASELINE.md: the reference's peak is 9M proposals/s on 3×22-core
servers with 48 groups. The TPU target regime is 50k concurrent groups on one
chip. This bench drives the step kernel with 50k single-replica groups, a
full inbox of proposals every step, and host-style log compaction folded into
the compiled step (the engine compacts after apply, cf. reference
node.go:849-867). It prints ONE JSON line.

Run: python bench.py  (uses the default jax backend; CPU works but is slow —
pass --groups/--steps to shrink for smoke tests).
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import subprocess
import sys
import time


from dragonboat_tpu._jaxenv import pin_cpu


def _ensure_live_backend() -> str:
    """Probe JAX backend init in a subprocess before touching it in-process.

    The environment's 'axon' TPU-tunnel backend can hang or fail during
    client creation; an in-process hang would wedge jax's backend lock for
    good. Probe externally (backend init succeeds in seconds or hangs, so
    a short timeout suffices; retry once), and fall back to a guarded CPU
    backend if the accelerator is unreachable. Returns the platform name."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        pin_cpu()
        return "cpu"
    probe = (
        "import jax, sys; d = jax.devices(); "
        "sys.stdout.write(d[0].platform)"
    )
    for _ in range(2):
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=60,
            )
            if r.returncode == 0 and r.stdout.strip():
                platform = r.stdout.strip()
                if platform == "cpu":
                    # the probe fell back to cpu (axon failed fast there);
                    # drop the factory here too or our own init can wedge
                    pin_cpu()
                return platform
        except subprocess.TimeoutExpired:
            pass
    pin_cpu()
    return "cpu-fallback"


def _arm_watchdog(seconds: float, platform: str):
    """The probe can pass and the tunnel still wedge moments later at real
    backend init. Guarantee the driver one parseable JSON line either way:
    if the bench has not finished within the deadline, emit an error record
    and hard-exit. Returns the timer (cancel on success)."""
    import threading

    def fire() -> None:  # pragma: no cover - only on wedged backends
        print(
            json.dumps(
                {
                    "metric": "kernel_proposals_per_sec",
                    "value": 0.0,
                    "unit": "proposals/s",
                    "vs_baseline": 0.0,
                    "platform": platform,
                    "error": f"watchdog: no result within {seconds:.0f}s",
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


import jax
import jax.numpy as jnp

from dragonboat_tpu.ops.kernel import step_batch, _term_at
from dragonboat_tpu.ops.state import (
    MSG,
    KernelConfig,
    RaftTensors,
    configure_groups_uniform,
    init_state,
    make_empty_inbox,
)

BASELINE_PROPOSALS_PER_SEC = 9_000_000  # reference README.md:46 (3-node peak)


def bench_step(state: RaftTensors, inbox, ticks, cfg: KernelConfig):
    state, out = step_batch(state, inbox, ticks, cfg)
    # engine-side compaction: applied entries leave the device window
    state = state._replace(
        marker_term=_term_at(state, state.applied),
        first_index=state.applied + 1,
    )
    return state, out.commit_index


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=50_000)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--inbox-depth", type=int, default=8)
    ap.add_argument("--entries", type=int, default=8)
    ap.add_argument("--log-window", type=int, default=512)
    ap.add_argument("--peers", type=int, default=8)
    ap.add_argument("--watchdog-s", type=float, default=480.0)
    args = ap.parse_args()

    platform = _ensure_live_backend()
    if platform == "cpu-fallback":
        # accelerator was unreachable: run a reduced CPU workload so the
        # driver still records a parseable number instead of a timeout
        args.groups = min(args.groups, 2048)
        args.steps = min(args.steps, 10)
        args.log_window = min(args.log_window, 64)

    # only the accelerator path can wedge post-probe (pinned cpu has no
    # axon factory left); don't kill legitimately slow CPU runs
    watchdog = _arm_watchdog(args.watchdog_s, platform) if platform not in (
        "cpu", "cpu-fallback") else None

    cfg = KernelConfig(
        groups=args.groups, peers=args.peers, log_window=args.log_window,
        inbox_depth=args.inbox_depth, max_entries_per_msg=args.entries,
        readindex_depth=4,
    )
    G, K, E = cfg.groups, cfg.inbox_depth, cfg.max_entries_per_msg

    state = init_state(cfg)
    # one voting replica per group: commit is immediate, the bench measures
    # pure kernel throughput (the multi-replica path adds transport rounds,
    # not kernel work — every lane runs the full handler table regardless)
    state = configure_groups_uniform(state, self_slot=0, voting_slots=(0,))

    fn = jax.jit(functools.partial(bench_step, cfg=cfg), donate_argnums=(0,))

    # elect: one ELECTION message per group
    elect = make_empty_inbox(cfg)
    elect = elect._replace(
        mtype=elect.mtype.at[:, 0].set(MSG.ELECTION),
    )
    ticks = jnp.zeros((G,), jnp.int32)
    state, _ = fn(state, elect, ticks)

    # steady state: K proposals of E entries per group per step
    inbox = make_empty_inbox(cfg)
    inbox = inbox._replace(
        mtype=jnp.full_like(inbox.mtype, MSG.PROPOSE),
        n_entries=jnp.full_like(inbox.n_entries, E),
    )

    for _ in range(args.warmup):
        state, commit = fn(state, inbox, ticks)
    jax.block_until_ready(commit)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, commit = fn(state, inbox, ticks)
    jax.block_until_ready(commit)
    dt = time.perf_counter() - t0
    if watchdog is not None:
        watchdog.cancel()

    # every proposal committed: verify, then report
    expected = (args.warmup + args.steps) * K * E + 1  # +1 leader noop
    final_commit = int(jnp.min(commit))
    assert final_commit == expected, (final_commit, expected)

    proposals = args.steps * G * K * E
    value = proposals / dt
    print(
        json.dumps(
            {
                "metric": "kernel_proposals_per_sec",
                "value": round(value, 1),
                "unit": "proposals/s",
                "vs_baseline": round(value / BASELINE_PROPOSALS_PER_SEC, 3),
                "platform": platform,
            }
        )
    )


if __name__ == "__main__":
    main()
