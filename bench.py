"""Headline benchmark: END-TO-END framework proposal throughput.

Regime from BASELINE.md: the reference's peak is 9M proposals/s on 3x22-core
servers with 48 Raft groups, 3 replicas per group, fsync honored
(reference README.md:46). This bench measures the same THING the reference
measures — proposals committed through the full framework stack:

    propose -> leader engine packs -> device step kernel -> Replicate over
    the transport (codec-encoded loopback) -> follower engines ack ->
    quorum commit -> ONE batched fsynced logdb write -> SM apply ->
    completion notify

with 3 NodeHosts in one process, G groups x 3 replicas, 16B payloads and
disk-backed WAL persistence. The bare-kernel number (what the device alone
sustains, single-replica lanes; the round-1/2 headline) is reported as a
secondary metric in the same JSON line.

Prints ONE JSON line. Run: python bench.py
(CPU works but is slow — pass --groups/--duration to shrink for smoke tests.)
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time


from dragonboat_tpu._jaxenv import maybe_pin_cpu, pin_cpu

BASELINE_PROPOSALS_PER_SEC = 9_000_000  # reference README.md:46 (3-node peak)


def _ensure_live_backend() -> str:
    """Probe JAX backend init in a subprocess before touching it in-process.

    The environment's 'axon' TPU-tunnel backend can hang or fail during
    client creation; an in-process hang would wedge jax's backend lock for
    good. Probe externally (backend init succeeds in seconds or hangs, so
    a short timeout suffices; retry once), and fall back to a guarded CPU
    backend if the accelerator is unreachable. Returns the platform name."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        maybe_pin_cpu()
        return "cpu"
    probe = (
        "import jax, sys; d = jax.devices(); "
        "sys.stdout.write(d[0].platform)"
    )
    for _ in range(2):
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True, timeout=60,
            )
            if r.returncode == 0 and r.stdout.strip():
                platform = r.stdout.strip()
                if platform == "cpu":
                    # the probe fell back to cpu (axon failed fast there);
                    # drop the factory here too or our own init can wedge
                    pin_cpu()
                return platform
        except subprocess.TimeoutExpired:
            pass
    pin_cpu()
    return "cpu-fallback"


def _arm_watchdog(seconds: float, platform: str):
    """The probe can pass and the tunnel still wedge moments later at real
    backend init. Guarantee the driver one parseable JSON line either way:
    if the bench has not finished within the deadline, emit an error record
    and hard-exit. Returns the timer (cancel on success)."""
    import threading

    def fire() -> None:  # pragma: no cover - only on wedged backends
        print(
            json.dumps(
                {
                    "metric": "e2e_proposals_per_sec",
                    "value": 0.0,
                    "unit": "proposals/s",
                    "vs_baseline": 0.0,
                    "platform": platform,
                    "error": f"watchdog: no result within {seconds:.0f}s",
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


import jax
import jax.numpy as jnp

from dragonboat_tpu.ops.kernel import step_batch, _term_at
from dragonboat_tpu.ops.state import (
    MSG,
    KernelConfig,
    RaftTensors,
    configure_groups_uniform,
    init_state,
    make_empty_inbox,
)


# ---------------------------------------------------------------------------
# end-to-end framework benchmark
# ---------------------------------------------------------------------------


def _bench_sm_class():
    from dragonboat_tpu.statemachine import (
        IConcurrentStateMachine,
        Result,
    )

    class _BenchSM(IConcurrentStateMachine):
        """Minimal in-memory counter SM (the reference benches an in-mem
        KV, internal/tests/kvtest.go). Concurrent flavour: update() takes
        the whole committed batch in ONE call — the apply-side shape a
        throughput-focused SM should use on this framework."""

        def __init__(self, cluster_id, node_id):
            self.n = 0

        def update(self, entries):
            n = self.n
            for e in entries:
                n += 1
                e.result = Result(value=n)
            self.n = n
            return entries

        def lookup(self, q):
            return self.n

        def prepare_snapshot(self):
            return self.n

        def save_snapshot(self, ctx, w, fc, done):
            w.write(int(ctx).to_bytes(8, "little"))

        def recover_from_snapshot(self, r, fc, done):
            self.n = int.from_bytes(r.read(8), "little")

        def close(self):
            pass

    return _BenchSM


def bench_e2e(
    groups: int,
    duration_s: float,
    payload: int,
    workdir: str,
    shared: bool = True,
    wave: int = 128,
    inbox_depth: int = 4,
    entries_per_msg: int = 64,
    log_window: int = 256,
):
    """3 NodeHosts, G groups x 3 replicas, quorum + fsync + apply.

    shared=True co-hosts all three NodeHosts on ONE engine core (the
    TPU-native deployment shape: the whole replica fleet advances in one
    kernel step; messages between replicas ride the shared inbox, not the
    wire). shared=False keeps three independent engines talking over the
    codec-encoded loopback transport."""
    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import Result  # noqa: F401 (SM dep)
    from dragonboat_tpu.transport.loopback import loopback_factory, _Registry

    sm_cls = _bench_sm_class()
    reg = _Registry()
    members = {1: "bench:1", 2: "bench:2", 3: "bench:3"}
    hosts = {}
    # timers: the election timeout must comfortably exceed the in-process
    # 3-engine message RTT AND the worst-case GIL starvation of an engine
    # loop while the submitter thread bursts a wave, or heartbeat gaps
    # trigger spurious elections mid-bench — the same config rule the
    # reference documents for its RTT-derived timeouts (config.go:60-126).
    # 10ms ticks x 100 election RTT = 1-2s timeouts, 200ms heartbeats.
    for nid, addr in members.items():
        cfg = NodeHostConfig(
            raft_address=addr,
            rtt_millisecond=10,
            nodehost_dir=os.path.join(workdir, f"nh{nid}"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(
                kind="vector",
                max_groups=3 * groups if shared else groups,
                max_peers=4,
                log_window=log_window,
                inbox_depth=inbox_depth,
                max_entries_per_msg=entries_per_msg,
                share_scope="bench" if shared else None,
            ),
        )
        hosts[nid] = NodeHost(cfg)
    for c in range(1, groups + 1):
        for nid in members:
            hosts[nid].start_cluster(
                dict(members),
                False,
                lambda cid, nid_: sm_cls(cid, nid_),
                Config(
                    node_id=nid, cluster_id=c, election_rtt=100,
                    heartbeat_rtt=20,
                ),
            )
    # wait for every group to elect a leader — ONE vectorized leadership
    # readout per poll instead of per-group get_leader_id calls
    t0 = time.monotonic()
    leaders = {}
    pending = set(range(1, groups + 1))
    snap_fn = getattr(hosts[1].engine, "leader_snapshot", None)
    while pending and time.monotonic() - t0 < 180:
        if snap_fn is not None:
            snap = snap_fn()
            for c in list(pending):
                lid, _term = snap.get(c, (0, 0))
                if lid:
                    leaders[c] = lid
                    pending.discard(c)
        else:
            done = set()
            for c in pending:
                lid, ok = hosts[1].get_leader_id(c)
                if ok:
                    leaders[c] = lid
                    done.add(c)
            pending -= done
        if pending:
            time.sleep(0.05)
    bring_up_s = time.monotonic() - t0
    if pending:
        for nh in hosts.values():
            nh.stop()
        return {"error": f"{len(pending)} groups never elected", "value": 0.0}
    cmd = b"x" * payload
    sessions = {
        c: hosts[leaders[c]].get_noop_session(c) for c in range(1, groups + 1)
    }
    # per-group pipelined batches: each group keeps ONE async batch of WAVE
    # proposals in flight (propose_batch_async: one handle + one event per
    # batch); a group resubmits the moment its batch completes. There is no
    # global barrier, so a group wedged by leadership churn costs only its
    # own lane while every other group keeps streaming — the shape of the
    # reference's pipelined benchmark clients.
    WAVE = wave
    total = 0
    dropped = 0
    inflight: dict = {}
    wave_cmds = [cmd] * WAVE
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    next_leader_refresh = t0 + 0.5
    while time.perf_counter() < deadline:
        progressed = False
        for c, sess in sessions.items():
            h = inflight.get(c)
            if h is not None:
                if not h.finished:
                    continue
                total += h.completed
                dropped += h.dropped
            inflight[c] = hosts[leaders[c]].propose_batch_async(
                sess, wave_cmds, 15
            )
            progressed = True
        now = time.perf_counter()
        if now >= next_leader_refresh:
            next_leader_refresh = now + 0.5
            if snap_fn is not None:
                for c, (lid, _t) in snap_fn().items():
                    if lid and c in sessions:
                        leaders[c] = lid
            else:
                for c in sessions:
                    lid, ok = hosts[1].get_leader_id(c)
                    if ok:
                        leaders[c] = lid
        if not progressed:
            time.sleep(0.002)
    # settle the last in-flight batch per group (bounded)
    settle_deadline = time.perf_counter() + 10
    for c, h in inflight.items():
        h.wait(max(0.0, settle_deadline - time.perf_counter()))
        total += h.completed
        dropped += h.dropped
    dt = time.perf_counter() - t0
    for nh in hosts.values():
        nh.stop()
    return {
        "value": total / dt,
        "groups": groups,
        "replicas": 3,
        "payload_bytes": payload,
        "committed": total,
        "client_dropped": dropped,
        "seconds": round(dt, 2),
        "bring_up_s": round(bring_up_s, 2),
        "fsync": True,
        "shared_engine": shared,
        "wave": wave,
    }


# ---------------------------------------------------------------------------
# bare-kernel benchmark (secondary metric; the round-1/2 headline)
# ---------------------------------------------------------------------------


def kernel_step(state: RaftTensors, inbox, ticks, cfg: KernelConfig):
    state, out = step_batch(state, inbox, ticks, cfg)
    # engine-side compaction: applied entries leave the device window
    state = state._replace(
        marker_term=_term_at(state, state.applied),
        first_index=state.applied + 1,
    )
    return state, out.commit_index


def bench_kernel(groups: int, steps: int, warmup: int, log_window: int):
    cfg = KernelConfig(
        groups=groups, peers=8, log_window=log_window,
        inbox_depth=8, max_entries_per_msg=8, readindex_depth=4,
    )
    G, K, E = cfg.groups, cfg.inbox_depth, cfg.max_entries_per_msg
    state = init_state(cfg)
    # one voting replica per group: commit is immediate; this measures the
    # device ceiling (quorum/transport/fsync excluded BY DESIGN — the e2e
    # metric above is the honest framework number)
    state = configure_groups_uniform(state, self_slot=0, voting_slots=(0,))
    fn = jax.jit(functools.partial(kernel_step, cfg=cfg), donate_argnums=(0,))
    elect = make_empty_inbox(cfg)
    elect = elect._replace(mtype=elect.mtype.at[:, 0].set(MSG.ELECTION))
    ticks = jnp.zeros((G,), jnp.int32)
    state, _ = fn(state, elect, ticks)
    inbox = make_empty_inbox(cfg)
    inbox = inbox._replace(
        mtype=jnp.full_like(inbox.mtype, MSG.PROPOSE),
        n_entries=jnp.full_like(inbox.n_entries, E),
    )
    for _ in range(warmup):
        state, commit = fn(state, inbox, ticks)
    jax.block_until_ready(commit)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, commit = fn(state, inbox, ticks)
    jax.block_until_ready(commit)
    dt = time.perf_counter() - t0
    expected = (warmup + steps) * K * E + 1  # +1 leader noop
    final_commit = int(jnp.min(commit))
    assert final_commit == expected, (final_commit, expected)
    return steps * G * K * E / dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=1024,
                    help="e2e bench: 3-replica groups per NodeHost")
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--payload", type=int, default=16)
    ap.add_argument("--kernel-groups", type=int, default=50_000)
    ap.add_argument("--kernel-steps", type=int, default=50)
    ap.add_argument("--kernel-warmup", type=int, default=5)
    ap.add_argument("--kernel-log-window", type=int, default=512)
    ap.add_argument("--skip-kernel", action="store_true")
    ap.add_argument("--skip-e2e", action="store_true")
    ap.add_argument("--watchdog-s", type=float, default=480.0)
    args = ap.parse_args()

    platform = _ensure_live_backend()
    if platform == "cpu-fallback":
        # accelerator was unreachable: run a reduced CPU workload so the
        # driver still records a parseable number instead of a timeout
        args.groups = min(args.groups, 256)
        args.duration = min(args.duration, 10.0)
        args.kernel_groups = min(args.kernel_groups, 2048)
        args.kernel_steps = min(args.kernel_steps, 10)
        args.kernel_log_window = min(args.kernel_log_window, 64)

    # only the accelerator path can wedge post-probe (pinned cpu has no
    # axon factory left); don't kill legitimately slow CPU runs
    watchdog = _arm_watchdog(args.watchdog_s, platform) if platform not in (
        "cpu", "cpu-fallback") else None

    record = {
        "metric": "e2e_proposals_per_sec",
        "value": 0.0,
        "unit": "proposals/s",
        "vs_baseline": 0.0,
        "platform": platform,
    }
    if not args.skip_e2e:
        workdir = tempfile.mkdtemp(prefix="dbtpu-bench-")
        try:
            e2e = bench_e2e(args.groups, args.duration, args.payload, workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        record["value"] = round(e2e.pop("value", 0.0), 1)
        record["vs_baseline"] = round(
            record["value"] / BASELINE_PROPOSALS_PER_SEC, 6
        )
        record["e2e"] = e2e
    if not args.skip_kernel:
        kv = bench_kernel(
            args.kernel_groups, args.kernel_steps, args.kernel_warmup,
            args.kernel_log_window,
        )
        record["kernel_proposals_per_sec"] = round(kv, 1)
        record["kernel_vs_baseline"] = round(kv / BASELINE_PROPOSALS_PER_SEC, 3)
        if args.skip_e2e:
            record["metric"] = "kernel_proposals_per_sec"
            record["value"] = round(kv, 1)
            record["vs_baseline"] = round(kv / BASELINE_PROPOSALS_PER_SEC, 3)

    if watchdog is not None:
        watchdog.cancel()
    print(json.dumps(record))


if __name__ == "__main__":
    main()
