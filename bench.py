"""Headline benchmark: END-TO-END framework proposal throughput.

Regime from BASELINE.md: the reference's peak is 9M proposals/s on 3x22-core
servers with 48 Raft groups, 3 replicas per group, fsync honored
(reference README.md:46). This bench measures the same THING the reference
measures — proposals committed through the full framework stack:

    propose -> leader engine packs -> device step kernel -> Replicate over
    the transport (codec-encoded loopback) -> follower engines ack ->
    quorum commit -> ONE batched fsynced logdb write -> SM apply ->
    completion notify

with 3 NodeHosts in one process, G groups x 3 replicas, 16B payloads and
disk-backed WAL persistence. The bare-kernel number (what the device alone
sustains, single-replica lanes; the round-1/2 headline) is reported as a
secondary metric in the same JSON line.

Prints ONE JSON line. Run: python bench.py
(CPU works but is slow — pass --groups/--duration to shrink for smoke tests.)
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time


from dragonboat_tpu._jaxenv import enable_compile_cache, maybe_pin_cpu, pin_cpu

BASELINE_PROPOSALS_PER_SEC = 9_000_000  # reference README.md:46 (3-node peak)


def _host_stamp() -> dict:
    """Bench-honesty box fingerprint: hostname/cpu-count identity plus a
    timed fixed numpy spin (a human-readable load indicator for the
    trajectory). tools.perfdiff refuses to diff records whose ids differ
    — re-benching one commit on a second box of this repo's own
    trajectory showed a 1.65x throughput gap at identical code/shape."""
    import platform as _platform
    import numpy as _np

    t0 = time.perf_counter()
    a = _np.random.default_rng(0).random((256, 256))
    for _ in range(20):
        a = (a @ a) % 1.0
    calib = time.perf_counter() - t0
    return {
        "id": f"{_platform.node() or 'unknown'}/{os.cpu_count()}cpu",
        "calib_s": round(calib, 4),
    }


def _ensure_live_backend(max_wait_s: float = 300.0) -> str:
    """Probe JAX backend init in a subprocess before touching it in-process.

    The environment's 'axon' TPU-tunnel backend can hang or fail during
    client creation; an in-process hang would wedge jax's backend lock for
    good. Probe externally with escalating timeouts for up to ~max_wait_s
    (a wedged tunnel often recovers within minutes — round 3 lost its TPU
    number to a probe that gave up after 2x60s), then fall back to a
    guarded CPU backend. Returns the platform name."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        maybe_pin_cpu()
        return "cpu"
    probe = (
        "import jax, sys; d = jax.devices(); "
        "sys.stdout.write(d[0].platform)"
    )
    t0 = time.monotonic()
    attempt_timeout = 45.0
    while time.monotonic() - t0 < max_wait_s:
        budget = max_wait_s - (time.monotonic() - t0)
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True, text=True,
                timeout=min(attempt_timeout, max(budget, 5.0)),
            )
            if r.returncode == 0 and r.stdout.strip():
                platform = r.stdout.strip()
                if platform == "cpu":
                    # the probe fell back to cpu (axon failed fast there);
                    # drop the factory here too or our own init can wedge
                    pin_cpu()
                return platform
        except subprocess.TimeoutExpired:
            pass
        attempt_timeout = min(attempt_timeout * 2, 120.0)
        time.sleep(2.0)
    pin_cpu()
    return "cpu-fallback"


# results accumulate here as each ladder config finishes, so the watchdog
# can emit everything measured so far instead of an empty error record
RECORD: dict = {
    "metric": "e2e_proposals_per_sec",
    "value": 0.0,
    "unit": "proposals/s",
    "vs_baseline": 0.0,
}


def _arm_watchdog(seconds: float, platform: str):
    """The probe can pass and the tunnel still wedge moments later at real
    backend init — and a CPU run can wedge on a deadlock just the same.
    ALWAYS armed: guarantee the driver one parseable JSON line either way,
    carrying whatever partial ladder results landed before the hang."""
    import threading

    def fire() -> None:  # pragma: no cover - only on wedged runs
        try:
            snap = json.loads(json.dumps(RECORD, default=str))  # best-effort
            snap["platform"] = platform
            snap["error"] = f"watchdog: no result within {seconds:.0f}s"
            print(json.dumps(snap), flush=True)
        except Exception:
            # RECORD mutated mid-dump: still emit SOMETHING parseable
            print(
                json.dumps(
                    {
                        "metric": "e2e_proposals_per_sec",
                        "value": 0.0,
                        "unit": "proposals/s",
                        "vs_baseline": 0.0,
                        "platform": platform,
                        "error": f"watchdog: no result within {seconds:.0f}s",
                    }
                ),
                flush=True,
            )
        finally:
            os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


import jax
import jax.numpy as jnp

from dragonboat_tpu.ops.kernel import step_batch, _term_at
from dragonboat_tpu.ops.state import (
    MSG,
    KernelConfig,
    RaftTensors,
    configure_groups_uniform,
    init_state,
    make_empty_inbox,
)


# ---------------------------------------------------------------------------
# end-to-end framework benchmark
# ---------------------------------------------------------------------------


def _bench_sm_class():
    from dragonboat_tpu.statemachine import (
        IConcurrentStateMachine,
        Result,
    )

    class _BenchSM(IConcurrentStateMachine):
        """Minimal in-memory counter SM (the reference benches an in-mem
        KV, internal/tests/kvtest.go). Concurrent flavour: update() takes
        the whole committed batch in ONE call — the apply-side shape a
        throughput-focused SM should use on this framework."""

        def __init__(self, cluster_id, node_id):
            self.n = 0

        def update(self, entries):
            n = self.n
            for e in entries:
                n += 1
                e.result = Result(value=n)
            self.n = n
            return entries

        def lookup(self, q):
            return self.n

        def prepare_snapshot(self):
            return self.n

        def save_snapshot(self, ctx, w, fc, done):
            w.write(int(ctx).to_bytes(8, "little"))

        def recover_from_snapshot(self, r, fc, done):
            self.n = int.from_bytes(r.read(8), "little")

        def close(self):
            pass

    return _BenchSM


def bench_e2e(
    groups: int,
    duration_s: float,
    payload: int,
    workdir: str,
    shared: bool = True,
    wave: int = 128,
    inbox_depth: int = 4,
    entries_per_msg: int = 64,
    log_window: int = 256,
    replicas: int = 3,
    read_ratio: int = 0,
    read_mode: str = "readindex",
    drop_rate: float = 0.0,
    churn: bool = False,
    steps_per_sync: int = 1,
    through_front: bool = False,
    tenants: int = 0,
    shard_over_mesh: bool = False,
):
    """N NodeHosts, G groups x N replicas, quorum + fsync + apply.

    shared=True co-hosts all NodeHosts on ONE engine core (the TPU-native
    deployment shape: the whole replica fleet advances in one kernel step;
    messages between replicas ride the shared inbox, not the wire).
    shared=False keeps independent engines talking over the codec-encoded
    loopback transport.

    read_ratio=R submits R linearizable ReadIndex requests per write
    (BASELINE config 3's 9:1 mix). read_mode='lease' turns on
    Config.lease_read for every group: the SAME read API, but a leader
    holding a live quorum lease serves the read locally and an expired/
    suspect lease degrades to the ReadIndex quorum round (config 8's
    read_heavy A/B; the stamp makes tools.perfdiff refuse cross-mode
    diffs). drop_rate randomly drops that fraction
    of replication traffic (config 4's log-matching divergence stress).
    churn interleaves snapshot requests and membership changes during the
    measurement (config 5). steps_per_sync=K runs the device-resident
    multi-step engine: K protocol steps per kernel launch with co-hosted
    traffic routed on device (config 6 is config 2 at K=8).
    through_front drives the measurement THROUGH SessionManager/
    ServingFront (config 7): the headline becomes ADMITTED throughput —
    per-tenant admission + weighted-fair fan-in + at-most-once session
    traffic — with per-tenant latency percentiles and the dedup/
    migration counters in the JSON."""
    import random as _random

    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import Result  # noqa: F401 (SM dep)
    from dragonboat_tpu.transport.loopback import loopback_factory, _Registry
    from dragonboat_tpu.types import MessageType

    sm_cls = _bench_sm_class()
    reg = _Registry()
    members = {n: f"bench:{n}" for n in range(1, replicas + 1)}
    hosts = {}
    try:
        return _bench_e2e_body(
            hosts, members, reg, sm_cls, groups, duration_s, payload,
            workdir, shared, wave, inbox_depth, entries_per_msg, log_window,
            replicas, read_ratio, read_mode, drop_rate, churn,
            steps_per_sync, through_front, tenants, shard_over_mesh,
        )
    finally:
        # an exception must not leak NodeHosts: the share_scope='bench'
        # core would survive (refcount never reaching zero) and poison
        # every later ladder config with an engine-shape mismatch
        for nh in hosts.values():
            try:
                nh.stop()
            except Exception:
                pass


def _bench_e2e_body(
    hosts, members, reg, sm_cls, groups, duration_s, payload, workdir,
    shared, wave, inbox_depth, entries_per_msg, log_window, replicas,
    read_ratio, read_mode, drop_rate, churn, steps_per_sync=1,
    through_front=False, tenants=0, shard_over_mesh=False,
):
    import random as _random

    from dragonboat_tpu.config import Config, EngineConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.types import MessageType
    from dragonboat_tpu.transport.loopback import loopback_factory
    # timers: the election timeout must comfortably exceed the in-process
    # message RTT AND the worst-case GIL starvation of an engine loop
    # while the submitter thread bursts a wave, or heartbeat gaps trigger
    # spurious elections mid-bench — the same config rule the reference
    # documents for its RTT-derived timeouts (config.go:60-126).
    # 10ms ticks x 300 election RTT = 3-6s timeouts, 300ms heartbeats —
    # the submitter's initial burst (G x WAVE entry creations) can hold
    # the GIL for over a second at G=1024, and a heartbeat gap that long
    # must not depose live leaders.
    for nid, addr in members.items():
        cfg = NodeHostConfig(
            raft_address=addr,
            rtt_millisecond=10,
            nodehost_dir=os.path.join(workdir, f"nh{nid}"),
            raft_rpc_factory=lambda a: loopback_factory(a, reg),
            engine=EngineConfig(
                kind="vector",
                max_groups=replicas * groups if shared else groups,
                max_peers=max(replicas, 4),
                log_window=log_window,
                inbox_depth=inbox_depth,
                max_entries_per_msg=entries_per_msg,
                steps_per_sync=steps_per_sync,
                shard_over_mesh=shard_over_mesh,
                share_scope=(
                    f"bench-k{steps_per_sync}" if shared else None
                ),
                # full stage sampling: the BENCH JSON carries per-stage
                # host timings so the perf trajectory tracks where the
                # host half of each step goes
                profile_sample_ratio=1,
            ),
        )
        hosts[nid] = NodeHost(cfg)
    for nid in members:
        hosts[nid].start_clusters([
            (
                dict(members),
                False,
                lambda cid, nid_: sm_cls(cid, nid_),
                Config(
                    node_id=nid, cluster_id=c, election_rtt=300,
                    heartbeat_rtt=30, lease_read=(read_mode == "lease"),
                ),
            )
            for c in range(1, groups + 1)
        ])
    # wait for every group to elect a leader — ONE vectorized leadership
    # readout per poll instead of per-group get_leader_id calls
    t0 = time.monotonic()
    leaders = {}
    pending = set(range(1, groups + 1))
    snap_fn = getattr(hosts[1].engine, "leader_snapshot", None)
    # the bring-up budget scales with fleet size: a 250k-lane nominal
    # config legitimately needs minutes of elections on one host, and a
    # fixed 180s would fail it before the ladder's watchdog even matters
    election_wait = max(180.0, 0.004 * groups * replicas)
    if shard_over_mesh:
        # the sharded engine's bring-up is paced in LAUNCHES: the tick
        # plane clamps each launch's burst at the heartbeat RTT, so a
        # timeout expires after ~election_rtt/heartbeat_rtt launches no
        # matter the wall clock, and the split-vote tail across 10k+
        # independent clusters adds several re-election rounds on top.
        # Each launch pays the replicated cross-shard router: ~25-30s
        # at 50k lanes on 2 virtual CPU devices, linear in lanes.
        election_wait = max(1800.0, 0.04 * groups * replicas)
    while pending and time.monotonic() - t0 < election_wait:
        if snap_fn is not None:
            snap = snap_fn()
            for c in list(pending):
                lid, _term = snap.get(c, (0, 0))
                if lid:
                    leaders[c] = lid
                    pending.discard(c)
        else:
            done = set()
            for c in pending:
                lid, ok = hosts[1].get_leader_id(c)
                if ok:
                    leaders[c] = lid
                    done.add(c)
            pending -= done
        if pending:
            time.sleep(0.05)
    bring_up_s = time.monotonic() - t0
    if pending:
        err = {
            "error": f"{len(pending)} groups never elected",
            "value": 0.0,
            "steps_per_sync": steps_per_sync,
        }
        err.update(_mesh_report(hosts, shard_over_mesh))
        err.update(_attribution_report(hosts, None, None))
        err.update(_read_report(hosts, 0, 0.0, read_mode))
        err.update(_census_report(hosts))
        err.update(_history_report(None))
        return err
    if drop_rate > 0 and shared:
        # randomized replication drops over the co-hosted path (the wire
        # analogue is the transport pre-send hook); rejects/backoff and
        # re-replication must recover the divergence. Installed AFTER
        # bring-up: the stress targets replication during the measured
        # window, and a hook forces the multi-step engine off on-device
        # routing (every message must pass the host-side predicate) —
        # pre-install would put the election traffic on the slow path
        # for no measurement gain.
        rnd = _random.Random(1234)
        rep_types = (
            MessageType.REPLICATE,
            MessageType.REPLICATE_RESP,
        )

        def _drop(m, _rnd=rnd, _t=rep_types):
            return m.type in _t and _rnd.random() < drop_rate

        hosts[1].engine.core.set_local_drop_hook(_drop)
    # warmup: the first kernel compile stalls every engine and piles ticks;
    # the resulting election churn settles within ~2s. Measuring through it
    # records churn losses, not steady-state throughput.
    time.sleep(2.0)
    # runtime sync/retrace audit marks: the folds below report the
    # MEASUREMENT WINDOW's deltas (bring-up legitimately compiles; a
    # steady-state compile or stray sync is the regression signal)
    from dragonboat_tpu.profile import compile_watch, sync_audit

    sync_mark = sync_audit().snapshot()
    compile_mark = compile_watch().install().snapshot()
    # the history sampler runs through the measured window: its cost is
    # part of the reported number, the attribution fold proves it stays
    # sync- and retrace-free
    hist = _start_history(workdir, hosts)
    if snap_fn is not None:
        for c, (lid, _t) in snap_fn().items():
            if lid and c in leaders:
                leaders[c] = lid
    cmd = b"x" * payload
    if through_front:
        out = _front_measure(
            hosts, leaders, snap_fn, groups, duration_s, cmd, wave,
            max(tenants, 1), bring_up_s, steps_per_sync,
        )
        if hist is not None:
            try:
                hist.stop()
            except Exception:
                pass
        out.update(_mesh_report(hosts, shard_over_mesh))
        out.update(_host_stage_report(hosts))
        out.update(_attribution_report(hosts, sync_mark, compile_mark))
        out.update(_latency_report(hosts))
        out.update(_lane_report(hosts))
        out.update(_serving_report(hosts))
        out.update(_read_report(hosts, 0, out["seconds"], read_mode))
        out.update(_census_report(hosts))
        out.update(_history_report(hist))
        return out
    sessions = {
        c: hosts[leaders[c]].get_noop_session(c) for c in range(1, groups + 1)
    }
    # per-group pipelined batches: each group keeps ONE async batch of WAVE
    # proposals in flight (propose_batch_async: one handle + one event per
    # batch); a group resubmits the moment its batch completes. There is no
    # global barrier, so a group wedged by leadership churn costs only its
    # own lane while every other group keeps streaming — the shape of the
    # reference's pipelined benchmark clients.
    WAVE = wave
    total = 0
    dropped = 0
    reads_done = 0
    reads_submitted = 0
    inflight: dict = {}
    read_inflight: dict = {c: [] for c in sessions} if read_ratio else {}
    wave_cmds = [cmd] * WAVE
    churn_state = {"snapshots": 0, "membership": 0, "next": 0.0, "rr": 0}
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    next_leader_refresh = t0 + 0.5
    while time.perf_counter() < deadline:
        progressed = False
        for c, sess in sessions.items():
            h = inflight.get(c)
            if h is not None:
                if not h.finished:
                    continue
                total += h.completed
                dropped += h.dropped
                if read_ratio:
                    rss = read_inflight[c]
                    reads_done += sum(
                        1
                        for rs in rss
                        if rs.result is not None and rs.result.completed
                    )
                    read_inflight[c] = []
            nh = hosts[leaders[c]]
            inflight[c] = nh.propose_batch_async(sess, wave_cmds, 15)
            if read_ratio:
                # R linearizable reads per write, riding the same cycle;
                # PendingReadIndex batches them under shared system ctxs
                n_reads = read_ratio * WAVE
                rss = read_inflight[c]
                for _ in range(n_reads):
                    rss.append(nh.read_index(c, 15))
                reads_submitted += n_reads
            progressed = True
        now = time.perf_counter()
        if churn and now >= churn_state["next"]:
            # BASELINE config 5: membership change + snapshot/compaction
            # interleaved with the write load
            churn_state["next"] = now + 0.5
            rr = churn_state["rr"] = churn_state["rr"] % groups + 1
            try:
                hosts[leaders[rr]].request_snapshot(rr, timeout_s=30.0)
                churn_state["snapshots"] += 1
            except Exception:
                pass
            try:
                # add-then-remove a (never-started) observer: the change
                # itself commits through the log; replication to the absent
                # node exercises the unreachable/breaker paths under load
                cyc = churn_state["membership"] % 2
                nh = hosts[leaders[rr]]
                if cyc == 0:
                    nh.request_add_observer(
                        rr, replicas + 1, "bench:absent", timeout_s=5.0
                    )
                else:
                    nh.request_delete_node(rr, replicas + 1, timeout_s=5.0)
                churn_state["membership"] += 1
            except Exception:
                pass
        if now >= next_leader_refresh:
            next_leader_refresh = now + 0.5
            if snap_fn is not None:
                for c, (lid, _t) in snap_fn().items():
                    if lid and c in sessions:
                        leaders[c] = lid
            else:
                for c in sessions:
                    lid, ok = hosts[1].get_leader_id(c)
                    if ok:
                        leaders[c] = lid
        if not progressed:
            time.sleep(0.002)
    # settle the last in-flight batch per group (bounded)
    settle_deadline = time.perf_counter() + 10
    for c, h in inflight.items():
        h.wait(max(0.0, settle_deadline - time.perf_counter()))
        total += h.completed
        dropped += h.dropped
    for c, rss in read_inflight.items():
        for rs in rss:
            if rs.result is not None and rs.result.completed:
                reads_done += 1
    dt = time.perf_counter() - t0
    if hist is not None:
        try:
            hist.stop()
        except Exception:
            pass
    host_stages = _host_stage_report(hosts)
    out = {
        "value": (total + reads_done) / dt,
        "groups": groups,
        "replicas": replicas,
        "payload_bytes": payload,
        "committed": total,
        "client_dropped": dropped,
        "seconds": round(dt, 2),
        "bring_up_s": round(bring_up_s, 2),
        "fsync": True,
        "shared_engine": shared,
        "wave": wave,
        # bench honesty: K is stamped on every config so tools.perfdiff
        # refuses to diff runs of different engines (K=1 vs K=8 measure
        # different machines, like scaled-down vs nominal does)
        "steps_per_sync": steps_per_sync,
    }
    out.update(_mesh_report(hosts, shard_over_mesh))
    if read_ratio:
        out["reads_completed"] = reads_done
        out["reads_submitted"] = reads_submitted
        out["read_ratio"] = read_ratio
    if drop_rate:
        out["drop_rate"] = drop_rate
    if churn:
        out["snapshots_requested"] = churn_state["snapshots"]
        out["membership_changes"] = churn_state["membership"]
    if host_stages:
        out.update(host_stages)
    out.update(_attribution_report(hosts, sync_mark, compile_mark))
    out.update(_latency_report(hosts))
    out.update(_lane_report(hosts))
    out.update(_serving_report(hosts))
    out.update(_read_report(hosts, reads_done, dt, read_mode))
    out.update(_census_report(hosts))
    out.update(_history_report(hist))
    return out


def _read_report(hosts, reads_done: int, dt: float, read_mode: str) -> dict:
    """Read-path honesty fold, ALWAYS present in every config JSON so the
    schema is stable and tools.perfdiff can apply its read_mode refusal:
    which read path the run measured ('readindex' quorum confirmation vs
    'lease' local serves with automatic ReadIndex fallback), the read
    throughput, and the engines' lease serve/fallback ledger (distinct
    engines only — a shared core hands every host the same counters)."""
    seen = {}
    for nh in hosts.values():
        eng = getattr(nh, "engine", None)
        fn = getattr(eng, "lease_stats", None)
        if fn is not None:
            seen[id(getattr(eng, "core", eng))] = fn
    local = fallback = 0
    for fn in seen.values():
        try:
            d = fn()
        except Exception:
            continue
        local += d["local"]
        fallback += d["fallback"]
    return {
        "read_mode": read_mode,
        "reads_per_sec": round(reads_done / dt, 1) if dt > 0 else 0.0,
        "lease_reads_local": local,
        "lease_reads_fallback": fallback,
    }


def _census_report(hosts) -> dict:
    """HBM census + protocol-event counter fold, ALWAYS present in every
    config JSON — zero-filled when no engine reports (including the
    bring-up-failed path) so the schema stays stable for tools.perfdiff
    and the paged-arena ROADMAP item reads its sizing baseline straight
    off any bench artifact. Distinct engines only (same dedupe as
    _read_report); bytes sum across engines, fill/waste take the worst
    engine (percentiles don't sum)."""
    from dragonboat_tpu.ops.state import CTR_NAMES
    from dragonboat_tpu.profile import CENSUS_KEYS, DeviceCensus

    seen = {}
    for nh in hosts.values():
        eng = getattr(nh, "engine", None)
        if getattr(eng, "device_census", None) is not None:
            seen[id(getattr(eng, "core", eng))] = eng
    out = {k: DeviceCensus.empty()[k] for k in CENSUS_KEYS}
    counters = {name: 0 for name in CTR_NAMES}
    for eng in seen.values():
        try:
            c = eng.device_census()
        except Exception:
            continue
        out["hbm_bytes_total"] += int(c["hbm_bytes_total"])
        out["hbm_log_bytes"] += int(c["hbm_log_bytes"])
        for k in ("log_fill_p50", "log_fill_p99", "hbm_waste_ratio"):
            out[k] = max(out[k], float(c[k]))
        fn = getattr(eng, "counter_stats", None)
        if fn is not None:
            for name, v in fn().items():
                if name in counters:
                    counters[name] += int(v)
    out["counters"] = counters
    return out


def _history_report(sampler) -> dict:
    """Telemetry-history sampler fold, ALWAYS present in every config
    JSON (zero-filled when the sampler never started) so the schema
    stays stable for tools.perfdiff — which shows the sampler's cost
    informationally, never as a gate. The sampler runs LIVE through the
    measured window: its per-sample cost is part of the number the bench
    reports, and the runtime sync/retrace attribution below it proves
    the sampling added zero device syncs and zero recompiles."""
    from dragonboat_tpu.profile import HistorySampler

    stats = (
        sampler.stats() if sampler is not None
        else HistorySampler.empty_stats()
    )
    return {f"history_{k}": v for k, v in stats.items()}


def _start_history(workdir: str, hosts) -> object:
    from dragonboat_tpu.profile import HistorySampler

    try:
        return HistorySampler(
            os.path.join(workdir, "history.ring"), lambda: hosts
        ).start()
    except Exception:
        return None  # telemetry must never block the bench


def _front_measure(
    hosts, leaders, snap_fn, groups, duration_s, cmd, wave, tenants,
    bring_up_s, steps_per_sync,
):
    """The through_front measurement (BASELINE config 7): T tenants drive
    bulk waves through each leader host's ServingFront (admission +
    weighted-fair pump) and an at-most-once SESSION lane rides every few
    waves, so the headline is ADMITTED throughput with per-tenant
    latency percentiles and dedup/migration counters — the ladder's
    millions-of-users shape instead of raw propose_batch. A placement
    plane (no targets on one box, default thresholds) runs its pacer
    through the window so `placement_enabled` is an honest stamp."""
    import threading

    from dragonboat_tpu.serving import (
        AdmissionConfig,
        SessionManager,
        TenantSpec,
    )

    # bulk buckets sized far above capacity: the bench measures what the
    # stack ADMITS under healthy load, not an artificial bucket ceiling
    admission = AdmissionConfig(
        default=TenantSpec(rate=2_000_000.0, burst=200_000.0)
    )
    fronts = {nid: nh.serving_front(admission=admission)
              for nid, nh in hosts.items()}
    mgrs = {nid: SessionManager(front) for nid, front in fronts.items()}
    planes = [
        nh.placement_plane(targets=[]) for nh in hosts.values()
    ]
    for p in planes:
        p.start()
    # tenant t owns clusters {c : c % tenants == t}; register ONE session
    # per tenant on its first cluster's leader host (the dedup lane)
    sess_cluster = {}
    for t in range(tenants):
        own = [c for c in range(1, groups + 1) if c % tenants == t % tenants]
        if not own:
            continue
        c = own[0]
        if mgrs[leaders[c]].register(t, c, count=1, timeout_s=30.0):
            sess_cluster[t] = c
    stats = {
        "admitted": 0, "shed": 0, "session_ops": 0, "session_errors": 0,
    }
    stats_mu = threading.Lock()
    stop = threading.Event()

    def tenant_main(t: int) -> None:
        own = [c for c in range(1, groups + 1) if c % tenants == t % tenants]
        admitted = shed = s_ops = s_err = 0
        rounds = 0
        while not stop.is_set():
            for c in own:
                if stop.is_set():
                    break
                front = fronts[leaders[c]]
                tickets = []
                for _ in range(wave):
                    try:
                        tickets.append(front.propose(t, c, cmd, 15.0))
                    except Exception:
                        shed += 1
                for tk in tickets:
                    # Ticket.wait RE-RAISES pump-side sheds (engine
                    # busy / inbox overflow): count them, never let one
                    # kill the tenant worker mid-window
                    try:
                        r = tk.wait()
                    except Exception:
                        shed += 1
                        continue
                    if r is not None and r.completed:
                        admitted += 1
                    else:
                        shed += 1
            rounds += 1
            if t in sess_cluster and rounds % 4 == 0:
                # the at-most-once lane: one session proposal through the
                # same pump, deadline-retried under the SAME series
                c = sess_cluster[t]
                try:
                    mgrs[leaders[c]].propose(t, c, cmd, 10.0)
                    s_ops += 1
                except Exception:
                    s_err += 1
        with stats_mu:
            stats["admitted"] += admitted
            stats["shed"] += shed
            stats["session_ops"] += s_ops
            stats["session_errors"] += s_err

    workers = [
        threading.Thread(target=tenant_main, args=(t,), daemon=True)
        for t in range(tenants)
    ]
    t0 = time.perf_counter()
    for w in workers:
        w.start()
    deadline = t0 + duration_s
    while time.perf_counter() < deadline:
        time.sleep(0.25)
        if snap_fn is not None:
            for c, (lid, _t) in snap_fn().items():
                if lid and c in leaders:
                    leaders[c] = lid
    stop.set()
    for w in workers:
        w.join(timeout=20)
    dt = time.perf_counter() - t0
    session_stats = {"registered": 0, "retired": 0, "proposals": 0,
                     "safe_retries": 0, "register_failed": 0, "pooled": 0}
    for m in mgrs.values():
        for k, v in m.stats().items():
            session_stats[k] = session_stats.get(k, 0) + v
    total = stats["admitted"] + stats["session_ops"]
    return {
        "value": total / dt,
        "groups": groups,
        "replicas": len(hosts),
        "payload_bytes": len(cmd),
        "committed": total,
        "client_dropped": stats["shed"],
        "seconds": round(dt, 2),
        "bring_up_s": round(bring_up_s, 2),
        "fsync": True,
        "shared_engine": True,
        "wave": wave,
        "steps_per_sync": steps_per_sync,
        # ---- bench honesty: a front run measures a different machine
        # than raw propose_batch — perfdiff refuses cross-workload diffs
        "workload": "through_front",
        "session_mode": "sessions",
        "placement_enabled": True,
        "tenants": tenants,
        # ---- the session/dedup lane's ledger
        "session_registered_total": session_stats["registered"],
        "session_proposals_total": session_stats["proposals"],
        "session_safe_retries_total": session_stats["safe_retries"],
        "session_errors_total": stats["session_errors"],
    }


def _engine_profilers(hosts) -> dict:
    """Every DISTINCT engine profiler across the hosts (a shared core
    hands every host the same object — counted once; shared=False runs
    sum the per-host engines)."""
    profs = {}
    for nh in hosts.values():
        prof = getattr(getattr(nh, "engine", None), "profiler", None)
        if prof is not None:
            profs[id(prof)] = prof
    return profs


def _attribution_report(hosts, sync_mark, compile_mark) -> dict:
    """The perf attribution fold (tools.perfdiff's input): an ALWAYS-
    present `phase_breakdown` with every canonical phase key (zero when
    the phase never ran, so the JSON schema is stable across configs and
    the gate can diff any two runs), plus the measurement-window
    `device_syncs` / `compile_events` deltas from the runtime audit.
    `sync_mark`/`compile_mark` of None (the bring-up-failed path) report
    zero-delta audits so the schema still holds."""
    from dragonboat_tpu.profile import (
        VECTOR_PHASES,
        compile_watch,
        diff_compiles,
        diff_sync,
        sync_audit,
    )

    phases = {p: 0.0 for p in VECTOR_PHASES}
    for prof in _engine_profilers(hosts).values():
        for name, s in prof.summary().items():
            phases[name] = round(phases.get(name, 0.0) + s["total_s"], 4)
    out = {"phase_breakdown": phases}
    if sync_mark is None:
        out["device_syncs"] = {"in_seam": 0, "out_of_seam": 0, "sites": {}}
    else:
        out["device_syncs"] = diff_sync(sync_mark, sync_audit().snapshot())
    if compile_mark is None:
        out["compile_events"] = {
            "total": 0, "total_s": 0.0, "per_function": {},
        }
    else:
        out["compile_events"] = diff_compiles(
            compile_mark, compile_watch().snapshot()
        )
    return out


def _lane_report(hosts) -> dict:
    """Per-lane introspection fold (VectorEngine.lane_stats: derived from
    the numpy mirrors the decode phase maintains — zero device syncs).
    Keys are ALWAYS present so the BENCH JSON schema stays stable: lane
    count, leader coverage, and the worst/typical commit gap (how far any
    lane's accepted log runs ahead of its quorum commit at bench end)."""
    lanes_total = lanes_with_leader = 0
    gap_max = 0
    gaps = []
    for nh in hosts.values():
        lane_stats = getattr(getattr(nh, "engine", None), "lane_stats", None)
        if lane_stats is None:
            continue
        for _cid, s in lane_stats().items():
            lanes_total += 1
            if s["leader_id"]:
                lanes_with_leader += 1
            gaps.append(s["commit_gap"])
            gap_max = max(gap_max, s["commit_gap"])
    gaps.sort()
    return {
        "lanes_total": lanes_total,
        "lanes_with_leader": lanes_with_leader,
        "lane_commit_gap_max": gap_max,
        "lane_commit_gap_p50": gaps[len(gaps) // 2] if gaps else 0,
    }


def _serving_report(hosts) -> dict:
    """Serving-front overload fold (ISSUE 8): total admit/shed/wake
    counts across every tenant of every host that created a front, and
    the urgent/bulk serving latency percentiles merged across hosts from
    the (tenant, klass)-keyed histogram plane. Keys are ALWAYS present —
    zero when no front exists (the default harness drives propose_batch
    directly) — so the BENCH JSON schema is stable across configs."""
    from dragonboat_tpu.events import Histogram
    from dragonboat_tpu.serving import KLASS_BULK, KLASS_URGENT

    admitted = shed = wakes = 0
    lat = {KLASS_URGENT: Histogram(), KLASS_BULK: Histogram()}
    per_tenant = {}
    for nh in hosts.values():
        front = getattr(nh, "_serving", None)
        if front is not None:
            for c in front.admission.counters().values():
                admitted += sum(c["admitted"].values())
                shed += sum(c["shed"].values())
                wakes += c["wakes"]
        m = getattr(nh, "metrics", None)
        if m is None:
            continue
        for (tid, klass), h in m.histogram_items("serving_latency_seconds"):
            if klass in lat:
                lat[klass].merge(h)
            if klass == KLASS_BULK and h.count:
                agg = per_tenant.setdefault(str(tid), Histogram())
                agg.merge(h)
    # live-migration ledger (serving/placement.py planes + the chunk
    # tracker's migration-tagged install streams); zero when no plane ran
    mig = {"started": 0, "completed": 0, "aborted": 0}
    mig_streams = 0
    for nh in hosts.values():
        plane = getattr(nh, "_placement", None)
        if plane is not None:
            c = plane.counters()
            for k in mig:
                mig[k] += c[f"migrations_{k}"]
        chunks = getattr(nh, "_chunks", None)
        if chunks is not None:
            mig_streams += chunks.stats().get("migration_streams", 0)
    return {
        "serving_admitted_total": admitted,
        "serving_shed_total": shed,
        "serving_wakes_total": wakes,
        "serving_urgent_p99_s": round(lat[KLASS_URGENT].quantile(0.99), 6),
        "serving_bulk_p50_s": round(lat[KLASS_BULK].quantile(0.5), 6),
        "serving_bulk_p99_s": round(lat[KLASS_BULK].quantile(0.99), 6),
        # per-tenant commit percentiles through the front (empty for raw
        # runs; config 7's headline detail) — keys are ALWAYS present
        "serving_tenant_latency": {
            tid: {
                "p50_s": round(h.quantile(0.5), 6),
                "p99_s": round(h.quantile(0.99), 6),
            }
            for tid, h in sorted(per_tenant.items())
        },
        "migrations_started": mig["started"],
        "migrations_completed": mig["completed"],
        "migrations_aborted": mig["aborted"],
        "migration_streams": mig_streams,
    }


def _latency_report(hosts) -> dict:
    """Proposal-lifecycle latency percentiles from the hosts' sampled
    histograms (EngineConfig.profile_sample_ratio=1 in the bench config:
    one sampled proposal per submitted wave), merged across hosts into one
    distribution per metric. The commit-latency keys are ALWAYS present —
    0.0 when no sample landed — so the BENCH JSON schema is stable for
    every ladder config."""
    from dragonboat_tpu.events import Histogram

    def merged(name: str) -> Histogram:
        agg = Histogram()
        for nh in hosts.values():
            m = getattr(nh, "metrics", None)
            if m is None:
                continue
            for h in m.histograms(name):
                agg.merge(h)
        return agg

    commit = merged("proposal_commit_latency_seconds")
    apply_ = merged("proposal_apply_latency_seconds")
    fsync = merged("fsync_latency_seconds")
    out = {
        "commit_latency_p50_s": round(commit.quantile(0.5), 6),
        "commit_latency_p99_s": round(commit.quantile(0.99), 6),
        "commit_latency_samples": commit.count,
        "apply_latency_p99_s": round(apply_.quantile(0.99), 6),
        "fsync_latency_p99_s": round(fsync.quantile(0.99), 6),
    }
    # read-latency keys are ALWAYS present (0.0 with no read traffic):
    # config 8's lease-vs-readindex A/B diffs them, and a stable schema
    # is what lets perfdiff fold any two same-mode records. The histogram
    # is serve-path agnostic — Node.read() samples at submit and records
    # at completion whether the lease path or the quorum path served it.
    reads = merged("readindex_latency_seconds")
    out["read_latency_p50_s"] = round(reads.quantile(0.5), 6)
    out["read_latency_p99_s"] = round(reads.quantile(0.99), 6)
    out["read_latency_samples"] = reads.count
    if reads.count:
        out["readindex_latency_p99_s"] = round(reads.quantile(0.99), 6)
    return out


# vector-engine profiler stages making up the host fan-out half of a step
# (everything between the device fetch and the next pack; "deliver" is a
# sub-span nested inside the send/apply/reads phases, so it is excluded
# here to avoid double counting)
_FANOUT_STAGES = ("place", "send_rep", "send_resp", "apply", "reads")


def _mesh_report(hosts, shard_over_mesh: bool) -> dict:
    """Mesh honesty stamps for every config JSON: how many devices the
    engine actually sharded over (1 = unsharded), the mesh shape, and the
    ghost-lane count from the device-multiple round-up. tools.perfdiff
    refuses to diff configs whose mesh shapes differ, exactly like the
    scaled-down / K / workload refusals."""
    n_dev, padded = 0, 0
    try:
        ss = hosts[1].engine.step_stats()
        n_dev = int(ss.get("mesh_devices", 0) or 0)
        padded = int(ss.get("padded_groups", 0) or 0)
    except Exception:
        pass
    n_dev = n_dev or 1
    return {
        "shard_over_mesh": bool(shard_over_mesh),
        "n_devices": n_dev,
        "mesh_shape": [n_dev],
        "padded_groups": padded,
    }


def _host_stage_report(hosts) -> dict:
    """Per-stage host timings from the engine's stage profiler: total
    seconds per stage (pack / device dispatch+step / fan-out / save) plus
    the fan-out+pack share of step wall time — the number the columnar
    host dataflow is accountable to."""
    profs = _engine_profilers(hosts)
    totals_raw: dict = {}
    for prof in profs.values():
        for name, s in prof.summary().items():
            totals_raw[name] = totals_raw.get(name, 0.0) + s["total_s"]
    if not totals_raw:
        return {}
    totals = {name: round(v, 4) for name, v in totals_raw.items()}
    # "deliver" is a sub-span of the send/apply/reads phases: keep it out
    # of the wall sum or its seconds would count twice
    wall = sum(v for n, v in totals_raw.items() if n != "deliver")
    fanout = sum(totals_raw.get(n, 0.0) for n in _FANOUT_STAGES)
    pack = totals_raw.get("pack", 0.0)
    out = {"host_stage_total_s": totals}
    if wall > 0:
        out["fanout_pack_share"] = round((fanout + pack) / wall, 4)
    return out


# ---------------------------------------------------------------------------
# bare-kernel benchmark (secondary metric; the round-1/2 headline)
# ---------------------------------------------------------------------------


def kernel_step(state: RaftTensors, inbox, ticks, cfg: KernelConfig):
    state, out = step_batch(state, inbox, ticks, cfg)
    # engine-side compaction: applied entries leave the device window
    state = state._replace(
        marker_term=_term_at(state, state.applied),
        first_index=state.applied + 1,
    )
    return state, out.commit_index


def bench_kernel(groups: int, steps: int, warmup: int, log_window: int):
    cfg = KernelConfig(
        groups=groups, peers=8, log_window=log_window,
        inbox_depth=8, max_entries_per_msg=8, readindex_depth=4,
    )
    G, K, E = cfg.groups, cfg.inbox_depth, cfg.max_entries_per_msg
    state = init_state(cfg)
    # one voting replica per group: commit is immediate; this measures the
    # device ceiling (quorum/transport/fsync excluded BY DESIGN — the e2e
    # metric above is the honest framework number)
    state = configure_groups_uniform(state, self_slot=0, voting_slots=(0,))
    fn = jax.jit(functools.partial(kernel_step, cfg=cfg), donate_argnums=(0,))
    elect = make_empty_inbox(cfg)
    elect = elect._replace(mtype=elect.mtype.at[:, 0].set(MSG.ELECTION))
    ticks = jnp.zeros((G,), jnp.int32)
    state, _ = fn(state, elect, ticks)
    inbox = make_empty_inbox(cfg)
    inbox = inbox._replace(
        mtype=jnp.full_like(inbox.mtype, MSG.PROPOSE),
        n_entries=jnp.full_like(inbox.n_entries, E),
    )
    for _ in range(warmup):
        state, commit = fn(state, inbox, ticks)
    jax.block_until_ready(commit)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, commit = fn(state, inbox, ticks)
    jax.block_until_ready(commit)
    dt = time.perf_counter() - t0
    expected = (warmup + steps) * K * E + 1  # +1 leader noop
    final_commit = int(jnp.min(commit))
    assert final_commit == expected, (final_commit, expected)
    return steps * G * K * E / dt


# The BASELINE.json five-config ladder. `nominal` is the regime the
# baseline names; `scaled` is what an e2e run at that regime costs on one
# in-process box — group counts shrink so every config completes inside
# the watchdog budget (the 50k-group regime is covered at full scale by
# the kernel metric and the bring-up benchmark in tests/test_bring_up.py).
LADDER = {
    1: dict(
        label="3-node, 1 group, 16B (benchmark_test.go baseline)",
        nominal_groups=1, groups=1, replicas=3, payload=16, wave=512,
        duration=6.0,
    ),
    2: dict(
        label="3-node, 1024 groups, 16B, batched step",
        nominal_groups=1024, groups=1024, replicas=3, payload=16,
        wave=128, duration=10.0,
    ),
    3: dict(
        label="5-node, 10k groups, 9:1 ReadIndex:write, elections on",
        nominal_groups=10_000, groups=256, replicas=5, payload=16,
        wave=8, duration=8.0, read_ratio=9,
    ),
    4: dict(
        label="5-node, 50k groups, 128B, randomized follower drops",
        nominal_groups=50_000, groups=256, replicas=5, payload=128,
        wave=64, duration=8.0, drop_rate=0.01,
    ),
    5: dict(
        label="5-node, 50k groups, membership + snapshot interleave",
        nominal_groups=50_000, groups=128, replicas=5, payload=16,
        wave=64, duration=8.0, churn=True,
    ),
    # config 2's workload on the device-resident multi-step engine: K=8
    # protocol steps per kernel launch, co-hosted replica traffic routed
    # on device. Kept as its OWN config id so the perfdiff trajectory
    # never diffs it against a K=1 run of config 2 (the K honesty rule).
    6: dict(
        label="3-node, 1024 groups, 16B, K=8 device-resident super-steps",
        nominal_groups=1024, groups=1024, replicas=3, payload=16,
        wave=128, duration=10.0, steps_per_sync=8,
    ),
    # the millions-of-users shape: traffic THROUGH SessionManager/
    # ServingFront (admission control, weighted-fair fan-in, at-most-once
    # session lane, placement plane live) — the headline is ADMITTED
    # throughput with per-tenant p50/p99 and dedup/migration counters.
    # Its own config id: perfdiff refuses front-vs-raw comparisons.
    7: dict(
        label="3-node, 64 groups, 16B, through_front: sessions + "
              "admission + placement",
        nominal_groups=64, groups=64, replicas=3, payload=16,
        wave=32, duration=8.0, through_front=True, tenants=4,
    ),
    # read_heavy: config 2's fleet shape under a 9:1 read:write mix, run
    # TWICE — once with reads on the ReadIndex quorum path, once with
    # leader leases serving reads locally (automatic ReadIndex fallback
    # on expiry/suspect). The record is the LEASE run (stamped
    # read_mode='lease' so perfdiff refuses cross-mode diffs) carrying
    # the ReadIndex run's read numbers under `readindex_mode` plus the
    # reads/s speedup ratio — the lease read path's headline.
    8: dict(
        label="3-node, 1024 groups, 16B, read_heavy 9:1, "
              "lease vs ReadIndex reads",
        nominal_groups=1024, groups=1024, replicas=3, payload=16,
        wave=8, duration=10.0, read_ratio=9, both_read_modes=True,
    ),
}


def _run_ladder_config(
    n: int, spec: dict, cpu: bool, degraded: bool, explicit_groups: bool
) -> dict:
    groups = spec["groups"]
    duration = spec["duration"]
    if not explicit_groups:
        if cpu and spec["replicas"] >= 5:
            # the 5-replica configs carry 5 lanes/group; keep the host
            # half inside the watchdog budget on plain CPU boxes
            groups = min(groups, 128)
        if degraded:
            # accelerator unreachable: shrink so the whole ladder still
            # lands inside the watchdog budget on the fallback box
            groups = min(groups, 256)
            duration = min(duration, 6.0)
    def _run(read_mode: str) -> dict:
        workdir = tempfile.mkdtemp(prefix=f"dbtpu-bench-c{n}-")
        try:
            return bench_e2e(
                groups, duration, spec["payload"], workdir,
                wave=spec["wave"],
                entries_per_msg=spec.get("entries_per_msg", 64),
                replicas=spec["replicas"],
                read_ratio=spec.get("read_ratio", 0),
                read_mode=read_mode,
                drop_rate=spec.get("drop_rate", 0.0),
                churn=spec.get("churn", False),
                steps_per_sync=spec.get("steps_per_sync", 1),
                through_front=spec.get("through_front", False),
                tenants=spec.get("tenants", 0),
                shard_over_mesh=spec.get("shard_over_mesh", False),
            )
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    if spec.get("both_read_modes"):
        # the read_heavy A/B: ReadIndex-mode first (the baseline), then
        # the lease-mode run that IS the config record. Both halves ran
        # on the same box minutes apart, so the speedup ratio inside one
        # record is the honest same-host comparison perfdiff's
        # read_mode refusal would otherwise forbid across records.
        base = _run("readindex")
        r = _run("lease")
        r["readindex_mode"] = {
            k: base[k]
            for k in (
                "value", "reads_per_sec", "read_latency_p50_s",
                "read_latency_p99_s", "read_latency_samples", "committed",
                "seconds", "bring_up_s",
            )
            if k in base
        }
        rps, base_rps = r.get("reads_per_sec", 0), base.get("reads_per_sec")
        if base_rps:
            r["lease_vs_readindex_reads"] = round(rps / base_rps, 3)
    else:
        r = _run(spec.get("read_mode", "readindex"))
    r["label"] = spec["label"]
    # bench honesty: the JSON names BOTH the regime the ladder config
    # claims (nominal_groups) and what this run actually exercised
    # (actual_groups); a run standing in for a larger regime is stamped
    # scaled_down so tools.perfdiff refuses to compare it against a
    # nominal run of the same config
    r["nominal_groups"] = spec["nominal_groups"]
    r["actual_groups"] = groups
    r["scaled_down"] = groups != spec["nominal_groups"]
    r["entries_per_msg"] = spec.get("entries_per_msg", 64)
    return r


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, default=0,
                    choices=[0, 1, 2, 3, 4, 5, 6, 7, 8],
                    help="run ONE BASELINE.json ladder config (1-8) at its "
                         "declared scale instead of the full reduced sweep")
    ap.add_argument("--groups", type=int, default=0,
                    help="override group count (with --config)")
    ap.add_argument("--steps-per-sync", type=int, default=0,
                    help="override EngineConfig.steps_per_sync (with "
                         "--config): K protocol steps per kernel launch")
    ap.add_argument("--shard-over-mesh", action="store_true",
                    help="shard the engine's lane axis over every visible "
                         "device (EngineConfig.shard_over_mesh); composes "
                         "with --steps-per-sync")
    ap.add_argument("--devices", type=int, default=0,
                    help="pin N virtual CPU devices before backend init "
                         "(XLA host-platform device count; CPU only — on "
                         "an accelerator the real topology is used)")
    ap.add_argument("--entries-per-msg", type=int, default=0,
                    help="override the e2e engine's max_entries_per_msg "
                         "(with --config). The cross-shard router ships "
                         "2*E entry rows per candidate message, so E "
                         "dominates the routed-slab width; sharded CPU "
                         "runs use E=8 to keep the per-launch cost sane. "
                         "Stamped into the config record.")
    ap.add_argument("--duration", type=float, default=0.0)
    ap.add_argument("--kernel-groups", type=int, default=50_000)
    ap.add_argument("--kernel-steps", type=int, default=50)
    ap.add_argument("--kernel-warmup", type=int, default=5)
    ap.add_argument("--kernel-log-window", type=int, default=512)
    ap.add_argument("--skip-kernel", action="store_true")
    ap.add_argument("--skip-e2e", action="store_true")
    ap.add_argument("--watchdog-s", type=float, default=560.0)
    args = ap.parse_args()

    if args.devices > 0:
        # must land before anything touches the backend: XLA reads the
        # host-platform device count at first initialization only
        from dragonboat_tpu._jaxenv import pin_cpu

        pin_cpu(n_devices=args.devices)
    platform = _ensure_live_backend(
        max_wait_s=60.0 if args.config else 300.0
    )
    cpu = platform in ("cpu", "cpu-fallback")
    if platform == "cpu-fallback":
        args.kernel_groups = min(args.kernel_groups, 4096)
        args.kernel_steps = min(args.kernel_steps, 10)
        args.kernel_log_window = min(args.kernel_log_window, 64)

    # ALWAYS armed — a CPU run can wedge on a deadlock just like the
    # tunnel can post-probe; partial ladder results still get printed
    watchdog = _arm_watchdog(args.watchdog_s, platform)
    # warm XLA compiles across bench runs (each ladder config's engine
    # shape costs seconds of compile; the cache makes reruns start warm)
    enable_compile_cache()
    # runtime perf attribution: count XLA compile events and wrap
    # jax.device_get/block_until_ready so any transfer outside the
    # blessed _fetch_output seam lands in the device_syncs fold with its
    # call site (dragonboat_tpu.profile; the runtime twin of `-m lint`'s
    # device-sync/retrace families)
    from dragonboat_tpu.profile import compile_watch, sync_audit

    compile_watch().install()
    sync_audit().install()

    RECORD["platform"] = platform
    RECORD["host"] = _host_stamp()
    if platform == "cpu-fallback":
        RECORD["degraded"] = "accelerator unreachable; reduced CPU workload"
    if not args.skip_e2e:
        configs = {}
        RECORD["configs"] = configs
        to_run = [args.config] if args.config else list(LADDER)
        for n in to_run:
            spec = dict(LADDER[n])
            if args.config:
                if args.groups:
                    spec["groups"] = args.groups
                else:
                    spec["groups"] = spec["nominal_groups"]
                if args.duration:
                    spec["duration"] = args.duration
                if args.steps_per_sync:
                    spec["steps_per_sync"] = args.steps_per_sync
                if args.shard_over_mesh:
                    spec["shard_over_mesh"] = True
                if args.entries_per_msg:
                    spec["entries_per_msg"] = args.entries_per_msg
            try:
                configs[str(n)] = _run_ladder_config(
                    n, spec, cpu,
                    degraded=platform == "cpu-fallback",
                    explicit_groups=bool(args.config and args.groups),
                )
            except Exception as e:  # record and keep laddering
                configs[str(n)] = {"label": spec["label"], "error": repr(e)}
        headline = configs.get(str(args.config or 2), {})
        RECORD["value"] = round(headline.get("value", 0.0), 1)
        RECORD["vs_baseline"] = round(
            RECORD["value"] / BASELINE_PROPOSALS_PER_SEC, 6
        )
    if not args.skip_kernel:
        kv = bench_kernel(
            args.kernel_groups, args.kernel_steps, args.kernel_warmup,
            args.kernel_log_window,
        )
        RECORD["kernel_proposals_per_sec"] = round(kv, 1)
        RECORD["kernel_vs_baseline"] = round(
            kv / BASELINE_PROPOSALS_PER_SEC, 3
        )
        if args.skip_e2e:
            RECORD["metric"] = "kernel_proposals_per_sec"
            RECORD["value"] = round(kv, 1)
            RECORD["vs_baseline"] = round(kv / BASELINE_PROPOSALS_PER_SEC, 3)

    watchdog.cancel()
    print(json.dumps(RECORD))


if __name__ == "__main__":
    main()
