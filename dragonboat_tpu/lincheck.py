"""Linearizability checking of recorded client histories.

The reference's chaos harness (external lni/drummer repo, methodology at
docs/test.md:11-33) records client operation histories in Jepsen format and
checks them with Knossos/porcupine. This module is the in-tree equivalent:
a history recorder producing timestamped invoke/return intervals and a
Wing&Gong-style checker (the porcupine algorithm: DFS over candidate
linearization orders with (linearized-set, state) memoization, plus the
standard treatment of unknown-outcome operations — a timed-out op may be
linearized at any point after its invocation or dropped entirely).

Generic over a sequential model; `kv_model`/`register_model` plus
`partition_by_key` cover the KV histories the chaos tests record.
"""
from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

INF = float("inf")

# Sentinel output handed to Model.step for unknown-outcome operations —
# the model must not constrain state transitions on it.
UNKNOWN = object()


@dataclass(slots=True)
class Operation:
    """One client operation with its real-time interval."""

    client: int
    input: Any
    output: Any = None
    invoke: float = 0.0
    ret: float = INF  # INF => never returned (outcome unknown)
    op_id: int = 0

    @property
    def completed(self) -> bool:
        return self.ret != INF


@dataclass
class Model:
    """Sequential specification.

    init: () -> state
    step: (state, input, output) -> (ok, new_state); for an op with unknown
      outcome (ret=INF) the checker calls step with the UNKNOWN sentinel as
      output — models must not constrain the transition on it (check
      `output is UNKNOWN`, never `output is None`: None is a legitimate
      completed result, e.g. a get of an absent key).
    """

    init: Callable[[], Hashable]
    step: Callable[[Hashable, Any, Any], Tuple[bool, Hashable]]


class HistoryRecorder:
    """Thread-safe Jepsen-style op log."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._ops: Dict[int, Operation] = {}
        self._next = itertools.count()

    def invoke(self, client: int, inp: Any) -> int:
        op_id = next(self._next)
        op = Operation(
            client=client, input=inp, invoke=time.monotonic(), op_id=op_id
        )
        with self._mu:
            self._ops[op_id] = op
        return op_id

    def complete(self, op_id: int, output: Any) -> None:
        with self._mu:
            op = self._ops[op_id]
            op.output = output
            op.ret = time.monotonic()

    def fail(self, op_id: int) -> None:
        """Definite failure: the op did NOT take effect; drop it."""
        with self._mu:
            self._ops.pop(op_id, None)

    def unknown(self, op_id: int) -> None:
        """Timeout/indeterminate: keep with ret=INF (may have taken effect)."""
        pass  # the default state already encodes this

    def history(self) -> List[Operation]:
        with self._mu:
            return sorted(self._ops.values(), key=lambda o: o.invoke)


def check_linearizable(
    model: Model, history: List[Operation], max_states: int = 2_000_000
) -> bool:
    """True iff `history` is linearizable w.r.t. `model`.

    DFS over linearization prefixes. At each step any remaining op whose
    invocation precedes the earliest return among remaining *completed* ops
    may linearize next. Unknown-outcome ops may additionally be dropped
    (never linearized). Memoizes (frozenset(linearized), state).
    """
    ops = list(history)
    if not ops:
        return True
    all_ids = frozenset(op.op_id for op in ops)
    by_id = {op.op_id: op for op in ops}
    seen: set = set()
    budget = [max_states]

    def candidates(remaining: frozenset, state: Hashable):
        """Yield (remaining', state') for every op that may linearize next."""
        min_ret = min(by_id[i].ret for i in remaining)
        for i in remaining:
            op = by_id[i]
            if op.invoke > min_ret:
                continue  # some other remaining op fully precedes this one
            if op.completed:
                ok, ns = model.step(state, op.input, op.output)
                if ok:
                    yield remaining - {i}, ns
            else:
                # unknown outcome: "it happened" (output unconstrained,
                # models receive the UNKNOWN sentinel) ...
                ok, ns = model.step(state, op.input, UNKNOWN)
                if ok:
                    yield remaining - {i}, ns
                # ... or "it never happened"
                yield remaining - {i}, state

    # iterative DFS (histories can be thousands of ops deep)
    stack = [iter([(all_ids, model.init())])]
    while stack:
        nxt = next(stack[-1], None)
        if nxt is None:
            stack.pop()
            continue
        remaining, state = nxt
        if not remaining:
            return True
        key = (remaining, state)
        if key in seen:
            continue
        seen.add(key)
        if budget[0] <= 0:
            raise LincheckBudgetExceeded(max_states)
        budget[0] -= 1
        stack.append(candidates(remaining, state))
    return False


class LincheckBudgetExceeded(RuntimeError):
    """Search exceeded max_states — result indeterminate, not a violation."""


# ---------------------------------------------------------------- KV models
# inputs: ("put", key, value) | ("get", key); output: None for put,
# read value (or None) for get.

def kv_model() -> Model:
    def init() -> Hashable:
        return ()

    def step(state, inp, output):
        d = dict(state)
        if inp[0] == "put":
            d[inp[1]] = inp[2]
            return True, tuple(sorted(d.items()))
        # get: unknown-outcome reads don't constrain the state
        if output is UNKNOWN:
            return True, state
        return d.get(inp[1]) == output, state

    return Model(init=init, step=step)


def register_model() -> Model:
    """Single-value register: input ("w", v) or ("r",), output read value."""

    def init() -> Hashable:
        return None

    def step(state, inp, output):
        if inp[0] == "w":
            return True, inp[1]
        if output is UNKNOWN:
            return True, state
        return state == output, state

    return Model(init=init, step=step)


def partition_by_key(history: List[Operation]) -> List[List[Operation]]:
    """Split a KV history into independent per-key histories (each key is an
    independent register, so the product check is equivalent and the DFS
    stays tractable — the same optimization porcupine's KV model uses)."""
    parts: Dict[Any, List[Operation]] = {}
    for op in history:
        parts.setdefault(op.input[1], []).append(op)
    return list(parts.values())


def check_kv_history(history: List[Operation], max_states: int = 2_000_000) -> bool:
    """Convenience: per-key-partitioned KV linearizability check."""
    model = kv_model()
    for part in partition_by_key(history):
        if not check_linearizable(model, part, max_states):
            return False
    return True


__all__ = [
    "Operation", "Model", "HistoryRecorder", "check_linearizable",
    "check_kv_history", "kv_model", "register_model", "partition_by_key",
    "LincheckBudgetExceeded", "UNKNOWN",
]
