"""Load C++ state machine plugins through the SM SDK's C ABI.

TPU-era counterpart of the reference's Go->C++ SM wrapper
(internal/cpp/wrapper.go:268-424 RegularStateMachineWrapper and the plugin
loader NewStateMachineWrapperFromPlugin wrapper.go:226): a shared library
built against native/sm_sdk/dragonboat_tpu/statemachine.h exports one SM
type; CppStateMachine implements the Python IStateMachine contract by
calling through ctypes, streaming snapshots across the ABI with
callback-backed writer/reader bridges (no full-image buffering on the
boundary).

Usage:
    factory = CppStateMachineFactory("/path/to/libmysm.so")
    nh.start_cluster(members, False, factory, cfg)
"""
from __future__ import annotations

import ctypes
from typing import BinaryIO

from .statemachine import IStateMachine, Result

_WRITE_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)
_READ_FN = ctypes.CFUNCTYPE(
    ctypes.c_long, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)


def _bind(lib: ctypes.CDLL) -> None:
    lib.dbtpu_sm_create.restype = ctypes.c_void_p
    lib.dbtpu_sm_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.dbtpu_sm_destroy.argtypes = [ctypes.c_void_p]
    lib.dbtpu_sm_update.restype = ctypes.c_uint64
    lib.dbtpu_sm_update.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.dbtpu_sm_lookup.restype = ctypes.c_int
    lib.dbtpu_sm_lookup.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.dbtpu_sm_get_hash.restype = ctypes.c_uint64
    lib.dbtpu_sm_get_hash.argtypes = [ctypes.c_void_p]
    lib.dbtpu_sm_save_snapshot.restype = ctypes.c_int
    lib.dbtpu_sm_save_snapshot.argtypes = [
        ctypes.c_void_p, _WRITE_FN, ctypes.c_void_p,
    ]
    lib.dbtpu_sm_recover_snapshot.restype = ctypes.c_int
    lib.dbtpu_sm_recover_snapshot.argtypes = [
        ctypes.c_void_p, _READ_FN, ctypes.c_void_p,
    ]
    lib.dbtpu_sm_free.argtypes = [ctypes.c_void_p]


class CppStateMachine(IStateMachine):
    """IStateMachine over one plugin-exported C++ SM instance."""

    def __init__(self, lib: ctypes.CDLL, cluster_id: int, node_id: int):
        self._lib = lib
        self._h = lib.dbtpu_sm_create(cluster_id, node_id)
        if not self._h:
            raise RuntimeError("dbtpu_sm_create returned NULL")

    def update(self, data: bytes) -> Result:
        v = self._lib.dbtpu_sm_update(self._h, data, len(data))
        return Result(value=int(v))

    def lookup(self, query) -> object:
        q = query if isinstance(query, bytes) else str(query).encode()
        out = ctypes.c_void_p()
        outlen = ctypes.c_size_t()
        rc = self._lib.dbtpu_sm_lookup(
            self._h, q, len(q), ctypes.byref(out), ctypes.byref(outlen)
        )
        if rc != 0:
            return None
        try:
            return ctypes.string_at(out, outlen.value)
        finally:
            self._lib.dbtpu_sm_free(out)

    def get_hash(self) -> int:
        return int(self._lib.dbtpu_sm_get_hash(self._h))

    def save_snapshot(self, w: BinaryIO, files, done) -> None:
        error: list = []

        @_WRITE_FN
        def write_cb(ctx, data, n):
            try:
                done.check() if hasattr(done, "check") else None
                w.write(ctypes.string_at(data, n))
                return 0
            except Exception as e:  # surfaces as rc!=0 on the C++ side
                error.append(e)
                return -1

        rc = self._lib.dbtpu_sm_save_snapshot(self._h, write_cb, None)
        if error:
            raise error[0]
        if rc != 0:
            raise RuntimeError("C++ SaveSnapshot failed")

    def recover_from_snapshot(self, r: BinaryIO, files, done) -> None:
        error: list = []

        @_READ_FN
        def read_cb(ctx, buf, cap):
            try:
                chunk = r.read(cap)
                if not chunk:
                    return 0
                ctypes.memmove(buf, chunk, len(chunk))
                return len(chunk)
            except Exception as e:
                error.append(e)
                return -1

        rc = self._lib.dbtpu_sm_recover_snapshot(self._h, read_cb, None)
        if error:
            raise error[0]
        if rc != 0:
            raise RuntimeError("C++ RecoverFromSnapshot failed")

    def close(self) -> None:
        if self._h:
            self._lib.dbtpu_sm_destroy(self._h)
            self._h = None


class CppStateMachineFactory:
    """SM factory over a plugin .so; pass directly to start_cluster
    (cf. wrapper.go:226 NewStateMachineWrapperFromPlugin)."""

    def __init__(self, plugin_path: str) -> None:
        self._lib = ctypes.CDLL(plugin_path)
        _bind(self._lib)
        self.plugin_path = plugin_path

    def __call__(self, cluster_id: int, node_id: int) -> CppStateMachine:
        return CppStateMachine(self._lib, cluster_id, node_id)


__all__ = ["CppStateMachine", "CppStateMachineFactory"]
