"""Load C++ state machine plugins through the SM SDK's C ABI.

TPU-era counterpart of the reference's Go->C++ SM wrapper
(internal/cpp/wrapper.go:268-424 RegularStateMachineWrapper,
wrapper.go:426-610 Concurrent/OnDisk wrappers, and the plugin loader
NewStateMachineWrapperFromPlugin wrapper.go:226): a shared library built
against native/sm_sdk/dragonboat_tpu/statemachine.h exports one SM type;
the wrappers below implement the matching Python state-machine contract
(IStateMachine / IConcurrentStateMachine / IOnDiskStateMachine) by calling
through ctypes, streaming snapshots across the ABI with callback-backed
writer/reader bridges (no full-image buffering on the boundary).

The plugin kind is discovered from its exported dbtpu_sm_type() symbol
(values match statemachine.py SM_TYPE_*); plugins predating the symbol are
treated as regular SMs.

Usage:
    factory = CppStateMachineFactory("/path/to/libmysm.so")
    nh.start_cluster(members, False, factory, cfg)
"""
from __future__ import annotations

import ctypes
from typing import BinaryIO, List

from .statemachine import (
    SM_TYPE_CONCURRENT,
    SM_TYPE_ONDISK,
    SM_TYPE_REGULAR,
    IConcurrentStateMachine,
    IOnDiskStateMachine,
    IStateMachine,
    Result,
    SMEntry,
)

_WRITE_FN = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)
_READ_FN = ctypes.CFUNCTYPE(
    ctypes.c_long, ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_size_t,
)


def _bind_common(lib: ctypes.CDLL) -> None:
    lib.dbtpu_sm_create.restype = ctypes.c_void_p
    lib.dbtpu_sm_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.dbtpu_sm_destroy.argtypes = [ctypes.c_void_p]
    lib.dbtpu_sm_lookup.restype = ctypes.c_int
    lib.dbtpu_sm_lookup.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.dbtpu_sm_get_hash.restype = ctypes.c_uint64
    lib.dbtpu_sm_get_hash.argtypes = [ctypes.c_void_p]
    lib.dbtpu_sm_recover_snapshot.restype = ctypes.c_int
    lib.dbtpu_sm_recover_snapshot.argtypes = [
        ctypes.c_void_p, _READ_FN, ctypes.c_void_p,
    ]
    lib.dbtpu_sm_free.argtypes = [ctypes.c_void_p]


def _bind_regular(lib: ctypes.CDLL) -> None:
    lib.dbtpu_sm_update.restype = ctypes.c_uint64
    lib.dbtpu_sm_update.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.dbtpu_sm_save_snapshot.restype = ctypes.c_int
    lib.dbtpu_sm_save_snapshot.argtypes = [
        ctypes.c_void_p, _WRITE_FN, ctypes.c_void_p,
    ]


def _bind_batched(lib: ctypes.CDLL) -> None:
    lib.dbtpu_sm_batched_update.restype = ctypes.c_int
    lib.dbtpu_sm_batched_update.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
    ]
    lib.dbtpu_sm_prepare_snapshot.restype = ctypes.c_int
    lib.dbtpu_sm_prepare_snapshot.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
    ]
    lib.dbtpu_sm_save_snapshot_ctx.restype = ctypes.c_int
    lib.dbtpu_sm_save_snapshot_ctx.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, _WRITE_FN, ctypes.c_void_p,
    ]


def _bind_ondisk(lib: ctypes.CDLL) -> None:
    lib.dbtpu_sm_open.restype = ctypes.c_int
    lib.dbtpu_sm_open.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dbtpu_sm_sync.restype = ctypes.c_int
    lib.dbtpu_sm_sync.argtypes = [ctypes.c_void_p]


class _CppSMBase:
    """Shared ctypes plumbing over one plugin-exported SM instance."""

    def __init__(self, lib: ctypes.CDLL, cluster_id: int, node_id: int):
        self._lib = lib
        self._h = lib.dbtpu_sm_create(cluster_id, node_id)
        if not self._h:
            raise RuntimeError("dbtpu_sm_create returned NULL")

    def lookup(self, query) -> object:
        q = query if isinstance(query, bytes) else str(query).encode()
        out = ctypes.c_void_p()
        outlen = ctypes.c_size_t()
        rc = self._lib.dbtpu_sm_lookup(
            self._h, q, len(q), ctypes.byref(out), ctypes.byref(outlen)
        )
        if rc != 0:
            return None
        try:
            return ctypes.string_at(out, outlen.value)
        finally:
            self._lib.dbtpu_sm_free(out)

    def get_hash(self) -> int:
        return int(self._lib.dbtpu_sm_get_hash(self._h))

    def _recover(self, r: BinaryIO) -> None:
        error: list = []

        @_READ_FN
        def read_cb(ctx, buf, cap):
            try:
                chunk = r.read(cap)
                if not chunk:
                    return 0
                ctypes.memmove(buf, chunk, len(chunk))
                return len(chunk)
            except Exception as e:
                error.append(e)
                return -1

        rc = self._lib.dbtpu_sm_recover_snapshot(self._h, read_cb, None)
        if error:
            raise error[0]
        if rc != 0:
            raise RuntimeError("C++ RecoverFromSnapshot failed")

    def _save(self, fn, w, done, *pre_args) -> None:
        """Run a snapshot-save ABI fn(handle, *pre_args, write_cb, NULL)."""
        error: list = []

        @_WRITE_FN
        def write_cb(ctx, data, n):
            try:
                done.check() if hasattr(done, "check") else None
                w.write(ctypes.string_at(data, n))
                return 0
            except Exception as e:  # surfaces as rc!=0 on the C++ side
                error.append(e)
                return -1

        rc = fn(self._h, *pre_args, write_cb, None)
        if error:
            raise error[0]
        if rc != 0:
            raise RuntimeError("C++ SaveSnapshot failed")

    def _batched_update(self, entries: List[SMEntry]) -> List[SMEntry]:
        n = len(entries)
        if n == 0:
            return entries
        idxs = (ctypes.c_uint64 * n)(*[e.index for e in entries])
        cmds = (ctypes.c_char_p * n)(*[e.cmd for e in entries])
        lens = (ctypes.c_size_t * n)(*[len(e.cmd) for e in entries])
        results = (ctypes.c_uint64 * n)()
        rc = self._lib.dbtpu_sm_batched_update(
            self._h, idxs,
            ctypes.cast(cmds, ctypes.POINTER(ctypes.c_char_p)),
            lens, results, n,
        )
        if rc != 0:
            raise RuntimeError("C++ BatchedUpdate failed")
        for e, v in zip(entries, results):
            e.result = Result(value=int(v))
        return entries

    def _prepare_snapshot(self) -> object:
        ctx = ctypes.c_void_p()
        rc = self._lib.dbtpu_sm_prepare_snapshot(self._h, ctypes.byref(ctx))
        if rc != 0:
            raise RuntimeError("C++ PrepareSnapshot failed")
        return ctx

    def close(self) -> None:
        if self._h:
            self._lib.dbtpu_sm_destroy(self._h)
            self._h = None


class CppStateMachine(_CppSMBase, IStateMachine):
    """IStateMachine over a regular plugin SM."""

    def update(self, data: bytes) -> Result:
        v = self._lib.dbtpu_sm_update(self._h, data, len(data))
        return Result(value=int(v))

    def save_snapshot(self, w: BinaryIO, files, done) -> None:
        self._save(self._lib.dbtpu_sm_save_snapshot, w, done)

    def recover_from_snapshot(self, r: BinaryIO, files, done) -> None:
        self._recover(r)


class CppConcurrentStateMachine(_CppSMBase, IConcurrentStateMachine):
    """IConcurrentStateMachine over a concurrent plugin SM."""

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        return self._batched_update(entries)

    def prepare_snapshot(self) -> object:
        return self._prepare_snapshot()

    def save_snapshot(self, ctx, w: BinaryIO, files, done) -> None:
        self._save(self._lib.dbtpu_sm_save_snapshot_ctx, w, done, ctx)

    def recover_from_snapshot(self, r: BinaryIO, files, done) -> None:
        self._recover(r)


class CppOnDiskStateMachine(_CppSMBase, IOnDiskStateMachine):
    """IOnDiskStateMachine over an on-disk plugin SM."""

    def open(self, stopc) -> int:
        idx = ctypes.c_uint64()
        rc = self._lib.dbtpu_sm_open(self._h, ctypes.byref(idx))
        if rc != 0:
            raise RuntimeError("C++ Open failed")
        return int(idx.value)

    def update(self, entries: List[SMEntry]) -> List[SMEntry]:
        return self._batched_update(entries)

    def sync(self) -> None:
        if self._lib.dbtpu_sm_sync(self._h) != 0:
            raise RuntimeError("C++ Sync failed")

    def prepare_snapshot(self) -> object:
        return self._prepare_snapshot()

    def save_snapshot(self, ctx, w: BinaryIO, done) -> None:
        self._save(self._lib.dbtpu_sm_save_snapshot_ctx, w, done, ctx)

    def recover_from_snapshot(self, r: BinaryIO, done) -> None:
        self._recover(r)


class CppStateMachineFactory:
    """SM factory over a plugin .so; pass directly to start_cluster
    (cf. wrapper.go:226 NewStateMachineWrapperFromPlugin). The plugin's
    exported dbtpu_sm_type() selects which Python contract the created
    instances implement, so the runtime's managed-SM dispatch
    (statemachine.py sm_type_of) picks the right apply discipline."""

    def __init__(self, plugin_path: str) -> None:
        self._lib = ctypes.CDLL(plugin_path)
        self.plugin_path = plugin_path
        try:
            type_fn = self._lib.dbtpu_sm_type
        except AttributeError:
            self.sm_type = SM_TYPE_REGULAR  # pre-type plugin
        else:
            type_fn.restype = ctypes.c_int
            type_fn.argtypes = []
            self.sm_type = int(type_fn())
        _bind_common(self._lib)
        if self.sm_type == SM_TYPE_CONCURRENT:
            _bind_batched(self._lib)
            self._cls = CppConcurrentStateMachine
        elif self.sm_type == SM_TYPE_ONDISK:
            _bind_batched(self._lib)
            _bind_ondisk(self._lib)
            self._cls = CppOnDiskStateMachine
        else:
            _bind_regular(self._lib)
            self._cls = CppStateMachine

    def __call__(self, cluster_id: int, node_id: int):
        return self._cls(self._lib, cluster_id, node_id)


__all__ = [
    "CppStateMachine",
    "CppConcurrentStateMachine",
    "CppOnDiskStateMachine",
    "CppStateMachineFactory",
]
