"""Perf attribution plane: step-phase spans, runtime device-sync audit,
and JAX compile-event accounting.

The engine step loop's wall time is the product this repo optimizes, and
PR 1-5 taught the same lesson three times: a regression that does not
fail a test quietly becomes the new baseline. This module makes the
attribution itself a first-class, always-exported plane:

  * ``PhasePlane`` — phase-scoped span histograms. The engines' stage
    profilers (``trace.Profiler``) ride the existing
    ``EngineConfig.profile_sample_ratio`` sampler; on sampled iterations
    every stage duration is ALSO observed into an
    ``engine_phase_seconds{engine=...,phase=...}`` histogram
    (events.Histogram, Prometheus exposition via
    ``NodeHost.write_health_metrics``), and at FULL sampling (ratio 1,
    the bench/debug opt-in) recorded as a ``phase_span`` event in the
    FlightRecorder so ``tools.timeline --spans`` renders them
    interleaved with causal-trace stages — sparse production sampling
    fills histograms only, never crowding the forensic ring. Unsampled
    iterations stay allocation- and event-free (the profiler's
    start/end no-op there).

  * ``SyncAudit`` — the runtime twin of the static ``device-sync`` rule
    family (analysis/rules_device.py). The blessed seam
    (``VectorEngine._fetch_output``) self-reports each consolidated
    transfer through ``note_seam_sync()`` (one integer add per step,
    always on). ``install()`` additionally wraps ``jax.device_get`` /
    ``jax.block_until_ready`` process-wide so any OTHER transfer is
    counted with call-site attribution — a stray sync introduced at
    runtime shows up in ``engine_device_syncs_*`` metrics and fails the
    tier-1 assertion (tests/test_profile.py), not just the AST gate.

  * ``CompileWatch`` — the runtime twin of the static ``retrace`` family:
    a ``jax.monitoring`` listener counts every XLA backend compile, and
    jitted functions registered by the engine (``make_step_fn``, the
    activation scatters) expose their trace-cache sizes per function, so
    a retrace in steady state is attributable to the function that
    retraced (``engine_compile_events_total`` / per-function cache
    gauges; ``bench.py`` folds the measurement-window delta into every
    config's JSON and ``tools.perfdiff --gate`` fails on growth).

  * ``HistorySampler`` — the diagnosis plane's TIME axis: a background
    thread that, every ``interval_s`` (default 250ms, entirely off the
    step loop), snapshots every zero-sync stat surface a host exports —
    lane stats (capped to the hottest K lanes), protocol counters,
    pressure, HBM census, leases, clock anomalies, WAL barrier
    latencies, serving/placement gauges — into a crash-persistent
    ``MmapRing`` (trace.py framing, bigger slots) next to the flight
    ring. Lifetime counters become windowed rates, and a SIGKILL leaves
    the last N seconds of fleet state on disk for ``tools.doctor`` to
    read back. Samples are flight-compatible events
    (``event=history_sample``) so ``tools.timeline`` merges a history
    ring like any other forensic artifact.

jax is imported lazily (inside ``install()``) so this module — like the
analysis package — stays importable in jax-free contexts
(``tools.perfdiff`` reads bench JSONs without ever touching a backend).
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, Optional, Tuple

from .events import Histogram, write_histogram_series, _labels
from .trace import _RING_MAGIC, MmapRing, flight_recorder, read_mmap_ring

# canonical step-phase vocabulary. The vector engine's step loop
# (VectorEngine._run_once + _decode) times every stage of a kernel step;
# bench.py zero-fills phase_breakdown over VECTOR_PHASES so the JSON
# schema is stable even for configs where a phase never ran.
VECTOR_PHASES = (
    "pack",       # host-event staging -> inbox planes (one scatter/plane)
    "dispatch",   # device_put of (inbox, ticks) + jitted step dispatch
    "fetch",      # _fetch_output: THE consolidated device->host sync
    "place",      # decode phase 0: payloads at device-assigned indexes
    "send_rep",   # decode phase 1: Replicate sends (leave BEFORE fsync)
    "save",       # decode phase 2: batched fsync save wave
    "send_resp",  # decode phase 3: post-fsync sends (votes/acks/heartbeats)
    "apply",      # decode phase 4: committed entries -> RSM task queues
    "reads",      # decode phase 5: confirmed ReadIndex completions
    "maintain",   # decode phase 6: catchup/snapshot/compaction maintenance
    "deliver",    # bulk send/deliver seam (_dispatch_sends, sub-span of
                  # the send/apply/reads phases it runs inside)
)

# the scalar ExecEngine worker loop's stages (trace.STAGES order), timed
# by the same Profiler machinery so scalar/vector attribution reads on
# one scale in the exposition and the bench JSON
EXEC_PHASES = ("step", "fast_apply", "send", "save", "apply", "exec")

_PREFIX = "dragonboat_tpu"


class PhasePlane:
    """Process-global phase-span sink: (engine, phase) -> Histogram plus
    a FlightRecorder ``phase_span`` breadcrumb per sampled span.

    Fed exclusively from trace.Profiler's SAMPLED branch (attach via
    ``Profiler.attach_phase_plane``); the ``sampling`` argument mirrors
    the caller's gate so the off path stays event-free and the lint's
    telemetry rule can see the guard."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._hists: Dict[Tuple[str, str], Histogram] = {}
        # master switch for flight-recorder spans (timeline --spans);
        # disable for tests that assert exact recorder contents
        self.record_spans = True

    def on_phase(
        self,
        engine: str,
        phase: str,
        dt: float,
        sampling: bool,
        spans: bool = True,
    ) -> None:
        """`sampling` mirrors the calling profiler's 1-in-N gate (off
        path: nothing happens); `spans` is the producer's full-sampling
        gate (trace.Profiler sets it only at ratio 1, the bench/debug
        mode) — sparse production sampling fills histograms but must not
        crowd the forensic ring's bounded history with phase_span
        breadcrumbs."""
        if sampling:
            key = (engine, phase)
            with self._mu:
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = Histogram()
            h.observe(dt)
            if spans and self.record_spans:
                flight_recorder().record(
                    "phase_span", engine=engine, phase=phase,
                    dur=round(dt, 9),
                )

    def histogram(self, engine: str, phase: str) -> Optional[Histogram]:
        with self._mu:
            return self._hists.get((engine, phase))

    def total_observations(self) -> int:
        with self._mu:
            hists = list(self._hists.values())
        return sum(h.count for h in hists)

    def summary(self) -> Dict[str, Dict[str, float]]:
        """(engine, phase) -> {count, sum_s, p50_s, p99_s} for tooling."""
        with self._mu:
            items = list(self._hists.items())
        out: Dict[str, Dict[str, float]] = {}
        for (engine, phase), h in items:
            out[f"{engine}/{phase}"] = {
                "count": float(h.count),
                "sum_s": round(h.sum, 6),
                "p50_s": round(h.quantile(0.5), 6),
                "p99_s": round(h.quantile(0.99), 6),
            }
        return out

    def reset(self) -> None:
        with self._mu:
            self._hists.clear()

    def write(self, w, prefix: str = _PREFIX) -> None:
        """Prometheus exposition: one ``engine_phase_seconds`` histogram
        family, series labelled {engine=...,phase=...}."""
        with self._mu:
            items = sorted(self._hists.items())
        if not items:
            return
        full = f"{prefix}_engine_phase_seconds"
        w.write(f"# TYPE {full} histogram\n")
        for (engine, phase), h in items:
            write_histogram_series(
                w, full, (("engine", engine), ("phase", phase)), h
            )


class SyncAudit:
    """Runtime device->host transfer accounting.

    The blessed seam (``VectorEngine._fetch_output``) self-reports via
    ``note_seam_sync()`` unconditionally — one integer add per engine
    step. ``install()`` wraps ``jax.device_get`` and
    ``jax.block_until_ready`` so every call NOT made from a blessed
    frame is counted under its call site (``file.py:line:function``).
    Wrapping only patches the public ``jax`` attributes, so jax's own
    internals (which bind ``jax._src`` symbols directly) are unaffected;
    per-call overhead is one frame probe — noise next to the transfer
    itself."""

    # (path suffix, function name) pairs whose frames are the blessed
    # transfer seam — mirrors analysis/targets.blessed_device_get.
    # _fetch_output is the classic one-step seam; _fetch_super is the
    # multi-step engine's once-per-K-steps consolidated transfer.
    BLESSED = (
        ("engine/vector.py", "_fetch_output"),
        ("engine/vector.py", "_fetch_super"),
    )

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.seam = 0  # blessed-seam transfers (note_seam_sync)
        # protocol steps decoded (note_engine_steps): with the
        # multi-step engine one seam sync covers K of these, so
        # engine_steps / seam is the measured steps-per-sync ratio —
        # the honest denominator for "zero out-of-seam syncs per step"
        self.engine_steps = 0
        self._out: Dict[str, int] = {}
        self.installed = False
        self._orig_get = None
        self._orig_block = None

    # ------------------------------------------------------------- seam
    def note_seam(self) -> None:
        # GIL-atomic-enough: telemetry, not accounting
        self.seam += 1

    # ------------------------------------------------------------ wraps
    def install(self) -> "SyncAudit":
        if self.installed:
            return self
        import jax

        self._orig_get = orig_get = jax.device_get
        self._orig_block = orig_block = jax.block_until_ready

        def device_get(x, *a, **k):
            self._note_frame(sys._getframe(1))
            return orig_get(x, *a, **k)

        def block_until_ready(x, *a, **k):
            self._note_frame(sys._getframe(1))
            return orig_block(x, *a, **k)

        jax.device_get = device_get
        jax.block_until_ready = block_until_ready
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        import jax

        jax.device_get = self._orig_get
        jax.block_until_ready = self._orig_block
        self._orig_get = self._orig_block = None
        self.installed = False

    def _note_frame(self, frame) -> None:
        co = frame.f_code
        fname = co.co_filename.replace(os.sep, "/")
        for suffix, name in self.BLESSED:
            if co.co_name == name and fname.endswith(suffix):
                return  # the seam counts itself via note_seam()
        # package-internal sites keep their package-relative path so the
        # attribution names the offending module, not just a basename
        idx = fname.rfind("/dragonboat_tpu/")
        rel = fname[idx + 1 :] if idx >= 0 else os.path.basename(fname)
        site = f"{rel}:{frame.f_lineno}:{co.co_name}"
        with self._mu:
            self._out[site] = self._out.get(site, 0) + 1

    # --------------------------------------------------------- snapshots
    def snapshot(self) -> dict:
        with self._mu:
            sites = dict(self._out)
        steps = self.engine_steps
        return {
            "in_seam": self.seam,
            "out_of_seam": sum(sites.values()),
            "engine_steps": steps,
            "steps_per_sync": round(steps / self.seam, 3) if self.seam else 0.0,
            "sites": sites,
        }

    def out_of_seam_in_package(self) -> Dict[str, int]:
        """Out-of-seam sites attributed to dragonboat_tpu code only (the
        tier-1 assertion's subject; test/bench harness sites excluded)."""
        with self._mu:
            return {
                s: n
                for s, n in self._out.items()
                if s.startswith("dragonboat_tpu/")
            }

    def reset(self) -> None:
        with self._mu:
            self._out.clear()
        self.seam = 0
        self.engine_steps = 0


def diff_sync(before: dict, after: dict) -> dict:
    """Per-window delta of two SyncAudit.snapshot() dicts (bench folds
    the measurement window's delta, not process-lifetime totals)."""
    sites = {
        s: n - before.get("sites", {}).get(s, 0)
        for s, n in after.get("sites", {}).items()
        if n - before.get("sites", {}).get(s, 0) > 0
    }
    seam = after["in_seam"] - before["in_seam"]
    steps = after.get("engine_steps", 0) - before.get("engine_steps", 0)
    return {
        "in_seam": seam,
        "out_of_seam": after["out_of_seam"] - before["out_of_seam"],
        "engine_steps": steps,
        "steps_per_sync": round(steps / seam, 3) if seam > 0 else 0.0,
        "sites": sites,
    }


# the HBM census schema: ALWAYS-present bench-JSON / gauge keys (the
# ROADMAP paged-arena item's baseline). Zero-filled when no device
# engine ran (bring-up-failed path, scalar-only hosts).
CENSUS_KEYS = (
    "hbm_bytes_total",   # device-resident protocol-state bytes (all planes)
    "hbm_log_bytes",     # the dense per-lane log ring's share of the above
    "log_fill_p50",      # median per-lane logical fill of the W-slot ring
    "log_fill_p99",      # tail fill: the widest lane the dense ring is for
    "hbm_waste_ratio",   # 1 - logical/physical over the whole log plane
)


class DeviceCensus:
    """HBM census of one engine's device-resident state planes.

    Physical bytes are STATIC tensor metadata: the owning engine reports
    each plane's ``.nbytes`` (shape x dtype) once at allocation time via
    ``set_planes`` — shapes never change over an engine's life, so the
    census never touches the device to answer "how much HBM does the
    protocol state hold". Logical per-lane log fill is numpy arithmetic
    over the decode-maintained mirrors the engine passes to
    ``snapshot()`` (``_m_last`` / ``_m_devfirst`` / ``_m_active``) —
    also zero device syncs, by the same argument as ``lane_stats``.

    ``hbm_waste_ratio`` is the paged-arena item's headline: the dense
    ring allocates ``G x W`` slots (every lane pays the widest lane's
    budget); the ratio is the fraction of those slots holding no live
    log entry. Fill p50/p99 describe the raggedness a paged relayout
    would exploit.

    jax-free like the rest of this module: numpy is imported inside
    ``snapshot()`` only (the callers that pass mirrors already loaded
    it), so jax-free readers (``tools.perfdiff``) can import the class
    and its ``empty()`` schema without touching a backend."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._planes: Dict[str, int] = {}
        self._log_planes: Tuple[str, ...] = ()
        self._devices = 1
        self._log_window = 0
        self._host_staging_bytes = 0

    def set_planes(
        self,
        planes: Dict[str, int],
        log_planes: Tuple[str, ...] = (),
        devices: int = 1,
        log_window: int = 0,
        host_staging_bytes: int = 0,
    ) -> None:
        """Report the engine's device planes (plane name -> physical
        bytes). ``log_planes`` names the subset that is the per-lane log
        ring; ``host_staging_bytes`` is the host-side numpy staging the
        inbox pack path owns (reported for completeness, never counted
        as HBM)."""
        with self._mu:
            self._planes = dict(planes)
            self._log_planes = tuple(log_planes)
            self._devices = max(1, int(devices))
            self._log_window = int(log_window)
            self._host_staging_bytes = int(host_staging_bytes)

    def planes(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._planes)

    @staticmethod
    def empty() -> dict:
        """The zero-filled census schema: what a host with no device
        engine (or a bring-up-failed bench config) reports, so the JSON
        keys are ALWAYS present."""
        out = {
            "hbm_bytes_total": 0,
            "hbm_log_bytes": 0,
            "log_fill_p50": 0.0,
            "log_fill_p99": 0.0,
            "hbm_waste_ratio": 0.0,
        }
        out.update(
            hbm_bytes_per_device=0,
            host_staging_bytes=0,
            lanes_active=0,
            log_window=0,
            planes={},
        )
        return out

    def snapshot(self, last=None, devfirst=None, active=None) -> dict:
        """The census: physical bytes from the registered plane table,
        logical fill from the caller's numpy mirrors (device-unit last
        index, device-unit first live index, active mask). All three
        mirrors are optional — a caller with no lanes yet gets the
        physical half with zeroed fill stats."""
        import numpy as np

        with self._mu:
            planes = dict(self._planes)
            log_planes = self._log_planes
            devices = self._devices
            W = self._log_window
            host_staging = self._host_staging_bytes
        total = sum(planes.values())
        log_bytes = sum(planes.get(p, 0) for p in log_planes)
        out = self.empty()
        out["hbm_bytes_total"] = int(total)
        out["hbm_log_bytes"] = int(log_bytes)
        out["hbm_bytes_per_device"] = int(total // devices)
        out["host_staging_bytes"] = int(host_staging)
        out["log_window"] = int(W)
        out["planes"] = planes
        if last is None or active is None or W <= 0:
            return out
        act = np.asarray(active, bool)
        n_act = int(act.sum())
        out["lanes_active"] = n_act
        lastv = np.asarray(last)
        first = (
            np.asarray(devfirst) if devfirst is not None
            else np.ones_like(lastv)
        )
        # logical slots a lane holds in the ring: indexes
        # [first, last] in device units, clipped to the window
        fill = np.clip(lastv - first + 1, 0, W)
        live = fill[act] / float(W) if n_act else np.zeros(0)
        if n_act:
            out["log_fill_p50"] = round(float(np.percentile(live, 50)), 6)
            out["log_fill_p99"] = round(float(np.percentile(live, 99)), 6)
        # waste over the DENSE allocation: every allocated lane (active
        # or not) pays W slots — that is exactly the dense-vs-ragged
        # accounting the paged-arena relayout would change
        total_slots = lastv.size * W
        logical = float(fill[act].sum()) if n_act else 0.0
        if total_slots:
            out["hbm_waste_ratio"] = round(1.0 - logical / total_slots, 6)
        return out


_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileWatch:
    """XLA compile-event accounting: a global ``jax.monitoring`` duration
    listener counts every backend compile (and its seconds), and jitted
    functions registered by their owners expose ``_cache_size()`` so
    growth is attributable per function. ``install()`` is idempotent;
    the listener cannot be unregistered (jax.monitoring has no removal
    API short of clearing everyone's), so it stays cheap: two adds per
    compile, nothing per step."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self.total = 0
        self.total_s = 0.0
        self._fns: Dict[str, list] = {}
        self.installed = False

    def install(self) -> "CompileWatch":
        if self.installed:
            return self
        import jax.monitoring as monitoring

        def _on_duration(event, duration, **kw):
            if event == _COMPILE_EVENT:
                with self._mu:
                    self.total += 1
                    self.total_s += duration

        monitoring.register_event_duration_secs_listener(_on_duration)
        self.installed = True
        return self

    def register(self, name: str, fn):
        """Track a jitted function's trace cache under ``name``; returns
        ``fn`` so call sites can wrap in place. Functions without a
        ``_cache_size`` probe (plain callables) are ignored. Held by
        WEAK reference: the watch must never pin a dead engine's
        compiled executables (falls back to a strong ref only for the
        rare non-weakrefable callable)."""
        if not hasattr(fn, "_cache_size"):
            return fn
        import weakref

        try:
            ref = weakref.ref(fn)
        except TypeError:
            ref = lambda _fn=fn: _fn  # noqa: E731 - constant closure
        with self._mu:
            refs = self._fns.setdefault(name, [])
            if all(r() is not fn for r in refs):
                refs.append(ref)
        return fn

    def per_function(self) -> Dict[str, int]:
        with self._mu:
            items = {k: list(v) for k, v in self._fns.items()}
        out: Dict[str, int] = {}
        dead: Dict[str, list] = {}
        for name, refs in sorted(items.items()):
            n = 0
            for r in refs:
                f = r()
                if f is None:
                    dead.setdefault(name, []).append(r)
                    continue
                try:
                    n += int(f._cache_size())
                except Exception:
                    pass  # a deleted executable must not break telemetry
            out[name] = n
        if dead:
            with self._mu:
                for name, gone in dead.items():
                    refs = self._fns.get(name)
                    if refs is None:
                        continue
                    self._fns[name] = [r for r in refs if r not in gone]
        return out

    def snapshot(self) -> dict:
        return {
            "total": self.total,
            "total_s": round(self.total_s, 4),
            "per_function": self.per_function(),
        }

    def reset_counts(self) -> None:
        with self._mu:
            self.total = 0
            self.total_s = 0.0


def diff_compiles(before: dict, after: dict) -> dict:
    """Measurement-window delta of two CompileWatch.snapshot() dicts:
    steady state compiles nothing, so any positive delta IS a retrace."""
    per = {
        k: n - before.get("per_function", {}).get(k, 0)
        for k, n in after.get("per_function", {}).items()
        if n - before.get("per_function", {}).get(k, 0) > 0
    }
    return {
        "total": after["total"] - before["total"],
        "total_s": round(after["total_s"] - before["total_s"], 4),
        "per_function": per,
    }


# ---------------------------------------------------------------------------
# telemetry history ring (the diagnosis plane's time axis)
# ---------------------------------------------------------------------------

# every history sample is a flight-compatible event: it carries `t`
# (monotonic seconds) and `event`, so tools.timeline merges a history
# ring into a forensic timeline like any other swept artifact, and
# tools.doctor filters the samples back out by event name
HISTORY_EVENT = "history_sample"
HISTORY_SCHEMA = 1
# sampler defaults: 250ms cadence; ring sized so a 4-host fleet keeps
# ~60s of history (one slot per host per tick). Slots are 16x the flight
# ring's 512B because one sample is a whole host snapshot, not a
# breadcrumb — the capped lane table is what keeps it under one slot.
HISTORY_INTERVAL_S = 0.25
HISTORY_MAX_LANES = 16
HISTORY_RING_CAPACITY = 1024
HISTORY_RING_SLOT = 8192

# the counter columns a hot-lane row carries (joined per lane by the
# engines' hot_lane_stats): exactly the per-lane inputs of tools.top's
# heat formula plus the election-outcome pair tools.doctor's quorum
# rules difference — NOT all of CTR_NAMES, so K lane rows stay small
# enough that a full sample fits one history slot
HOT_LANE_COUNTERS = (
    "elections_started",
    "elections_won",
    "replicate_rejects",
    "commit_advances",
    "lease_fallback",
)

# the always-present sampler gauge schema (engine_history_* in the
# Prometheus exposition, `history` fold in the bench JSON): zero-filled
# when no sampler is attached so consumers never branch
HISTORY_STATS_KEYS = (
    "samples_total",
    "errors_total",
    "last_sample_seconds",
    "sample_cost_seconds_total",
    "interval_seconds",
)


def _capped_lanes(eng, max_lanes: int):
    """(rows, total_active) from the engine's capped hot-lane accessor,
    falling back to a full lane_stats fold for engines that predate it.
    Rows are stringified-cluster-id keyed (JSON object keys)."""
    hot = getattr(eng, "hot_lane_stats", None)
    if callable(hot):
        rows, total = hot(max_lanes)
    else:
        stats = eng.lane_stats()
        total = len(stats)
        hottest = sorted(
            stats.items(),
            key=lambda kv: kv[1].get("commit_gap", 0),
            reverse=True,
        )[: max(1, int(max_lanes))]
        rows = dict(hottest)
    out = {}
    for key, row in rows.items():
        if isinstance(key, tuple):  # core-level (host, cluster_id) key
            key = f"{key[0]}:{key[1]}"
        out[str(key)] = row
    return out, int(total)


def sample_host(nh, max_lanes: int = HISTORY_MAX_LANES) -> dict:
    """One bounded snapshot of a live NodeHost's zero-sync stat surfaces
    — the HistorySampler's unit of work, also usable synchronously
    (tools.doctor's in-process ``diagnose`` takes two of these and
    differences them).

    Zero-sync by construction: every source below reads decode-
    maintained numpy mirrors or plain host ints (lane_stats /
    counter_stats / pressure_stats / device_census / lease_stats
    contracts), the WAL barrier ledger, and the serving/placement
    planes' Python counters. Nothing here may touch the device — the
    ``-m perf`` audit in tests/test_profile.py pins it. Sources that
    fail (engine mid-teardown, no serving front) zero-fill and are named
    in the sample's ``errors`` list rather than raising."""
    d = {
        "event": HISTORY_EVENT,
        "schema": HISTORY_SCHEMA,
        "t": round(time.monotonic(), 6),
        "host": getattr(getattr(nh, "config", None), "raft_address", ""),
        "cluster": 0,  # host-level event (flight-recorder convention)
    }
    errors = []
    eng = getattr(nh, "engine", None)

    def _take(name, fn, default):
        try:
            d[name] = fn()
        except Exception:
            d[name] = default
            errors.append(name)

    if eng is not None:
        try:
            rows, total = _capped_lanes(eng, max_lanes)
            d["lanes"] = rows
            d["lanes_total"] = total
            d["lanes_dropped"] = max(0, total - len(rows))
        except Exception:
            d["lanes"], d["lanes_total"], d["lanes_dropped"] = {}, 0, 0
            errors.append("lanes")
        _take("counters", lambda: dict(eng.counter_stats()), {})
        _take("pressure", lambda: dict(eng.pressure_stats()), {})
        _take(
            "lease",
            lambda: dict(eng.lease_stats()),
            {"local": 0, "fallback": 0},
        )

        def _census_lite():
            c = eng.device_census()
            return {
                "hbm_bytes_total": int(c.get("hbm_bytes_total", 0)),
                "hbm_waste_ratio": float(c.get("hbm_waste_ratio", 0.0)),
                "lanes_active": int(c.get("lanes_active", 0)),
            }

        _take("census", _census_lite, {})

        def _fairness_gap():
            fairness = getattr(eng, "fairness_stats", None)
            if fairness is None:
                return 0.0
            return float(fairness().get("recent_max_gap_s", 0.0))

        _take("fairness_gap_s", _fairness_gap, 0.0)
    # host-level clock-fault ledger (tick worker's divergence detector)
    _take(
        "clock_anomalies",
        lambda: int(nh.clock_anomalies()),
        0,
    )
    # WAL durability-barrier ledger: ewma/last fsync-wave latency —
    # tools.doctor's wal_fsync_stall signal
    _take(
        "wal",
        lambda: {
            k: round(float(v), 6) if isinstance(v, float) else int(v)
            for k, v in nh.logdb.barrier_stats().items()
        },
        {},
    )

    # serving/placement planes: observe-only — `_serving`/`_placement`
    # are read lock-free exactly like NodeHost._export_health_gauges
    # does (the sampler must never instantiate a front on an idle host)
    def _serving_fold():
        front = getattr(nh, "_serving", None)
        if front is None:
            return {"admitted": 0, "shed": 0, "queue_depth": 0,
                    "saturation": 0.0}
        admitted = shed = 0
        for row in front.admission.counters().values():
            admitted += sum(row.get("admitted", {}).values())
            shed += sum(row.get("shed", {}).values())
        queue = sum(front.queue_depths().values())
        return {
            "admitted": int(admitted),
            "shed": int(shed),
            "queue_depth": int(queue),
            "saturation": round(float(front.monitor.score()), 6),
        }

    _take(
        "serving",
        _serving_fold,
        {"admitted": 0, "shed": 0, "queue_depth": 0, "saturation": 0.0},
    )

    def _migration_fold():
        plane = getattr(nh, "_placement", None)
        if plane is None:
            return {"started": 0, "completed": 0, "aborted": 0, "active": 0}
        c = plane.counters()
        return {
            "started": int(c.get("migrations_started", 0)),
            "completed": int(c.get("migrations_completed", 0)),
            "aborted": int(c.get("migrations_aborted", 0)),
            "active": int(c.get("active", 0)),
        }

    _take(
        "migrations",
        _migration_fold,
        {"started": 0, "completed": 0, "aborted": 0, "active": 0},
    )
    if errors:
        d["errors"] = errors
    return d


class HistorySampler:
    """Per-process background sampler feeding a crash-persistent history
    ring (the flight ring's MmapRing framing with history-sized slots).

    ``hosts`` is a mapping (key -> NodeHost) or a zero-arg callable
    returning one — the callable form is for fleets whose membership
    changes under the sampler (tools.longhaul crash/restart rounds).
    One slot is written per live host per tick; a host that dies between
    ticks simply stops appearing, and its final pre-crash samples are
    exactly what the ring exists to preserve.

    Entirely off the engines' step path: the thread wakes every
    ``interval_s``, reads the zero-sync surfaces (sample_host) and does
    one json.dumps + MmapRing.write per host. A pre-existing ring at
    ``path`` rotates to ``<path>.prev`` first — same preservation
    contract as FlightRecorder.attach_mmap. ``stop()`` takes one final
    sample so a graceful shutdown's last state is on disk too."""

    def __init__(
        self,
        path: str,
        hosts,
        interval_s: float = HISTORY_INTERVAL_S,
        capacity: int = HISTORY_RING_CAPACITY,
        slot_size: int = HISTORY_RING_SLOT,
        max_lanes: int = HISTORY_MAX_LANES,
    ) -> None:
        self.path = path
        self.interval_s = max(0.01, float(interval_s))
        self.max_lanes = int(max_lanes)
        self._hosts = hosts
        self._mu = threading.Lock()
        try:
            with open(path, "rb") as f:
                had_ring = f.read(len(_RING_MAGIC)) == _RING_MAGIC
            if had_ring:
                os.replace(path, path + ".prev")
        except OSError:
            pass  # no previous ring (or unreadable): nothing to preserve
        self._ring: Optional[MmapRing] = MmapRing(
            path, capacity=capacity, slot_size=slot_size
        )
        # plain-int telemetry (torn reads cost one stale gauge sample)
        self.samples_total = 0
        self.errors_total = 0
        self.last_sample_s = 0.0
        self.cost_s_total = 0.0
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- hosts
    def _host_map(self) -> dict:
        hosts = self._hosts
        if callable(hosts):
            try:
                hosts = hosts()
            except Exception:
                hosts = {}
        return dict(hosts or {})

    # ----------------------------------------------------------- sampling
    def sample_once(self) -> int:
        """Take one sample of every live host NOW (also the final-flush
        path); returns the number of slots written."""
        t0 = time.monotonic()
        with self._mu:
            ring = self._ring
        if ring is None:
            return 0
        wrote = 0
        for _key, nh in sorted(
            self._host_map().items(), key=lambda kv: str(kv[0])
        ):
            if nh is None:
                continue
            try:
                d = sample_host(nh, max_lanes=self.max_lanes)
                ring.write(
                    json.dumps(d, default=str, sort_keys=True).encode()
                )
                wrote += 1
            except Exception:
                # a host mid-crash must never kill the sampler; the gap
                # in its series is itself a diagnostic signal
                self.errors_total += 1
        dt = time.monotonic() - t0
        self.samples_total += wrote
        self.last_sample_s = dt
        self.cost_s_total += dt
        return wrote

    def _run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            self.sample_once()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "HistorySampler":
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        t = threading.Thread(
            target=self._run, name="history-sampler", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def stop(self, final_sample: bool = True) -> None:
        self._stop_ev.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)
        if final_sample:
            try:
                self.sample_once()
            except Exception:
                pass
        with self._mu:
            ring, self._ring = self._ring, None
        if ring is not None:
            ring.close()

    def flush(self) -> None:
        with self._mu:
            ring = self._ring
        if ring is not None:
            ring.flush()

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        """The engine_history_* gauge schema (HISTORY_STATS_KEYS)."""
        return {
            "samples_total": int(self.samples_total),
            "errors_total": int(self.errors_total),
            "last_sample_seconds": round(self.last_sample_s, 6),
            "sample_cost_seconds_total": round(self.cost_s_total, 6),
            "interval_seconds": self.interval_s,
        }

    @staticmethod
    def empty_stats() -> dict:
        """Zero-filled stats schema for hosts with no sampler attached —
        gauges and bench JSON keys stay ALWAYS present."""
        return {
            "samples_total": 0,
            "errors_total": 0,
            "last_sample_seconds": 0.0,
            "sample_cost_seconds_total": 0.0,
            "interval_seconds": 0.0,
        }


def read_history(path: str):
    """Recover a (possibly SIGKILL'd) process's history ring: returns
    (meta, samples) with samples seal-ordered; non-sample events that
    share the ring (none today) are filtered out by event name."""
    meta, events = read_mmap_ring(path)
    return meta, [e for e in events if e.get("event") == HISTORY_EVENT]


# ---------------------------------------------------------------------------
# process-global singletons (like trace.flight_recorder: every engine and
# NodeHost in the process feeds one plane, and the exposition/bench folds
# read it without plumbing)
# ---------------------------------------------------------------------------

_phase_plane = PhasePlane()
_sync_audit = SyncAudit()
_compile_watch = CompileWatch()


def phase_plane() -> PhasePlane:
    return _phase_plane


def sync_audit() -> SyncAudit:
    return _sync_audit


def compile_watch() -> CompileWatch:
    return _compile_watch


def note_seam_sync() -> None:
    """The blessed ``_fetch_output``/``_fetch_super`` seams' self-report:
    one integer add per consolidated device->host transfer, always on."""
    _sync_audit.seam += 1


def note_engine_steps(n: int = 1) -> None:
    """Protocol-step accounting for the seam ratio: the decode path
    reports how many engine steps one fetch covered (1 on the classic
    path, K on a multi-step super-step) so ``engine_steps_per_sync``
    stays an honest per-step denominator at any K."""
    _sync_audit.engine_steps += n


def write_exposition(w, prefix: str = _PREFIX) -> None:
    """Append the attribution plane to a Prometheus text exposition:
    the ``engine_phase_seconds`` histograms plus per-jitted-function
    compile-cache gauges (scalar device-sync/compile counters ride the
    NodeHost MetricsRegistry as ``engine_device_syncs_*`` /
    ``engine_compile_events_total``)."""
    _phase_plane.write(w, prefix)
    per_fn = _compile_watch.per_function()
    if per_fn:
        full = f"{prefix}_engine_compile_cache_entries"
        w.write(f"# TYPE {full} gauge\n")
        for name, n in sorted(per_fn.items()):
            w.write(f"{full}{_labels((('function', name),))} {n}\n")


__all__ = [
    "CENSUS_KEYS",
    "CompileWatch",
    "DeviceCensus",
    "EXEC_PHASES",
    "HISTORY_EVENT",
    "HISTORY_INTERVAL_S",
    "HISTORY_MAX_LANES",
    "HISTORY_STATS_KEYS",
    "HOT_LANE_COUNTERS",
    "HistorySampler",
    "PhasePlane",
    "SyncAudit",
    "VECTOR_PHASES",
    "compile_watch",
    "diff_compiles",
    "diff_sync",
    "note_engine_steps",
    "note_seam_sync",
    "phase_plane",
    "read_history",
    "sample_host",
    "sync_audit",
    "write_exposition",
]
