"""dragonboat-tpu: a TPU-native multi-group Raft consensus framework.

A re-imagining of Dragonboat (github.com/lni/dragonboat v3.2 line) for TPU:
one NodeHost process hosts thousands of Raft groups, and the per-group
protocol step loop is replaced by a single vectorized JAX kernel that
advances all groups' protocol state — term, vote, matchIndex, commitIndex
tensors over a (groups, peers) layout — in one compiled step. Host-side
control plane (log storage, transport, snapshots, state machines) keeps
Dragonboat's pluggable seams.

Layers:
  - types/config/client: wire types, configuration, client sessions
  - core: scalar (per-group) Raft protocol oracle
  - ops: the vectorized multi-group protocol kernel (JAX)
  - engine: batched execution engine driving the kernel
  - storage: pluggable log storage (LogDB)
  - transport: pluggable message transport
  - rsm: replicated state machine management
  - nodehost: the public facade
"""

__version__ = "0.1.0"

from .config import Config, EngineConfig, NodeHostConfig
from .client import Session
from .types import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Membership,
    Message,
    MessageType,
    Snapshot,
    State,
    SystemCtx,
    Update,
)

__all__ = [
    "Config",
    "EngineConfig",
    "NodeHostConfig",
    "Session",
    "ConfigChange",
    "ConfigChangeType",
    "Entry",
    "EntryType",
    "Membership",
    "Message",
    "MessageType",
    "Snapshot",
    "State",
    "SystemCtx",
    "Update",
    "__version__",
]
