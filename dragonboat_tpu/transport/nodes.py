"""Node registry: (cluster_id, node_id) -> NodeHost address resolution.

cf. internal/transport/nodes.go — static records added via add_node plus
remotes learned from inbound traffic source addresses; reverse resolution
feeds Unreachable fanout when a target address fails.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class Nodes:
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._addr: Dict[Tuple[int, int], str] = {}
        self._learned: Dict[Tuple[int, int], str] = {}

    def add_node(self, cluster_id: int, node_id: int, address: str) -> None:
        with self._mu:
            self._addr[(cluster_id, node_id)] = address

    def add_remote_address(self, cluster_id: int, node_id: int, address: str) -> None:
        """Record an address learned from inbound traffic
        (cf. nodes.go AddRemoteAddress)."""
        with self._mu:
            if (cluster_id, node_id) not in self._addr:
                self._learned[(cluster_id, node_id)] = address

    def resolve(self, cluster_id: int, node_id: int) -> Optional[str]:
        with self._mu:
            addr = self._addr.get((cluster_id, node_id))
            if addr is None:
                addr = self._learned.get((cluster_id, node_id))
            return addr

    def reverse_resolve(self, address: str) -> List[Tuple[int, int]]:
        with self._mu:
            out = [k for k, v in self._addr.items() if v == address]
            out.extend(
                k for k, v in self._learned.items() if v == address and k not in out
            )
            return out

    def remove_cluster(self, cluster_id: int) -> None:
        with self._mu:
            for d in (self._addr, self._learned):
                for k in [k for k in d if k[0] == cluster_id]:
                    del d[k]

    def remove_node(self, cluster_id: int, node_id: int) -> None:
        with self._mu:
            self._addr.pop((cluster_id, node_id), None)
            self._learned.pop((cluster_id, node_id), None)


__all__ = ["Nodes"]
