"""Transport layer (cf. internal/transport/)."""

from .loopback import LoopbackRPC, loopback_factory
from .nodes import Nodes
from .tcp import TCPTransport
from .transport import Transport

__all__ = [
    "Transport",
    "Nodes",
    "TCPTransport",
    "LoopbackRPC",
    "loopback_factory",
]
