"""In-process loopback RPC module for tests and single-process deployments.

The analogue of the reference's NOOP transport
(cf. internal/transport/noop.go:30-177): message batches are delivered
directly to the destination's registered handler through a process-global
registry, with SetToFail/SetBlocked chaos knobs.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..raftio import IConnection, IRaftRPC, ISnapshotConnection
from ..types import MessageBatch, SnapshotChunk
from .. import codec


class _Registry:
    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._handlers: Dict[str, tuple] = {}

    def register(self, addr: str, req_handler, chunk_handler) -> None:
        with self._mu:
            self._handlers[addr] = (req_handler, chunk_handler)

    def unregister(self, addr: str) -> None:
        with self._mu:
            self._handlers.pop(addr, None)

    def lookup(self, addr: str):
        with self._mu:
            return self._handlers.get(addr)


_global_registry = _Registry()


class LoopbackConnection(IConnection):
    def __init__(self, rpc: "LoopbackRPC", target: str) -> None:
        self._rpc = rpc
        self._target = target

    def close(self) -> None:
        pass

    def send_message_batch(self, batch: MessageBatch) -> None:
        self._rpc.deliver(self._target, batch)


class LoopbackSnapshotConnection(ISnapshotConnection):
    def __init__(self, rpc: "LoopbackRPC", target: str) -> None:
        self._rpc = rpc
        self._target = target

    def close(self) -> None:
        pass

    def send_chunk(self, chunk: SnapshotChunk) -> None:
        self._rpc.deliver_chunk(self._target, chunk)


class LoopbackRPC(IRaftRPC):
    """In-process IRaftRPC; every instance registers its own address and
    dials others through the shared registry."""

    def __init__(
        self,
        request_handler: Callable[[MessageBatch], None],
        chunk_handler: Callable[[SnapshotChunk], bool],
        address: str = "",
        registry: Optional[_Registry] = None,
    ) -> None:
        self._address = address
        self._req_handler = request_handler
        self._chunk_handler = chunk_handler
        self._registry = registry or _global_registry
        # chaos knobs (cf. noop.go SetToFail / SetBlocked)
        self.fail_send = False
        self.blocked = False

    def set_address(self, address: str) -> None:
        self._address = address

    def name(self) -> str:
        return "loopback"

    def start(self) -> None:
        self._registry.register(
            self._address, self._req_handler, self._chunk_handler
        )

    def stop(self) -> None:
        self._registry.unregister(self._address)

    def get_connection(self, target: str) -> LoopbackConnection:
        if self.fail_send or self._registry.lookup(target) is None:
            raise ConnectionError(f"loopback: no listener at {target}")
        return LoopbackConnection(self, target)

    def get_snapshot_connection(self, target: str) -> LoopbackSnapshotConnection:
        if self.fail_send or self._registry.lookup(target) is None:
            raise ConnectionError(f"loopback: no listener at {target}")
        return LoopbackSnapshotConnection(self, target)

    def deliver(self, target: str, batch: MessageBatch) -> None:
        if self.blocked or self.fail_send:
            raise ConnectionError("loopback send blocked")
        entry = self._registry.lookup(target)
        if entry is None:
            raise ConnectionError(f"loopback: no listener at {target}")
        # serialize/deserialize to guarantee value semantics across "hosts"
        # and to exercise the codec exactly like the TCP path does
        data = codec.encode_message_batch(batch)
        decoded, _ = codec.decode_message_batch(data)
        entry[0](decoded)

    def deliver_chunk(self, target: str, chunk: SnapshotChunk) -> None:
        if self.blocked or self.fail_send:
            raise ConnectionError("loopback send blocked")
        entry = self._registry.lookup(target)
        if entry is None:
            raise ConnectionError(f"loopback: no listener at {target}")
        data = codec.encode_chunk(chunk)
        decoded, _ = codec.decode_chunk(data)
        if not entry[1](decoded):
            raise ConnectionError("chunk rejected")


def loopback_factory(address: str = "", registry=None):
    """Factory adapter for Transport(rpc_factory=...)."""

    def make(request_handler, chunk_handler):
        return LoopbackRPC(
            request_handler, chunk_handler, address=address, registry=registry
        )

    return make


__all__ = [
    "LoopbackRPC",
    "loopback_factory",
    "_global_registry",
]
